"""Engine dataflow graph: operator nodes.

This is the TPU-build equivalent of the reference's engine operation surface
(``trait Graph``, ``src/engine/graph.rs:664-1012``) and its differential
implementation (``src/engine/dataflow.rs``).  Design differences, on purpose:

- Epoch-synchronous scheduling (one consistent batch per logical timestamp)
  instead of asynchronous timely progress tracking — same externally
  observable consistency (outputs only at closed timestamps), far simpler
  host runtime, and a natural fit for feeding batched jitted TPU executors.
- Nodes are *stateless descriptions*; all mutable execution state lives in a
  per-run :class:`RunContext`, so a graph can be executed many times
  (mirrors the reference replaying the parse graph per worker).
- Retraction-aware: every operator processes ``diff=±1`` update batches.
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Callable, Iterable, Sequence

from pathway_tpu.internals import api
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.engine import cluster as cl
from pathway_tpu.engine.reducers import ReducerImpl
from pathway_tpu.engine.stream import Batch, Update, consolidate, per_key_changes


class ErrorEntry(str):
    """One error-log record.  A ``str`` subclass so every existing
    consumer (substring checks, len, logging) keeps working, with the
    structured fields the reference routes to its global error-log table
    (``src/engine/error.rs`` + ``parse_graph.add_error_log``)."""

    operator: str
    trace: str
    time: int

    def __new__(cls, message: str, operator: str = "", trace: str = "", time: int = 0):
        text = f"{message} [at {trace}]" if trace else message
        self = super().__new__(cls, text)
        self.message = message
        self.operator = operator
        self.trace = trace
        self.time = time
        return self


_ctx_local = __import__("threading").local()


def current_ctx() -> "RunContext | None":
    """The RunContext this worker thread is currently processing an epoch
    for — lets per-cell expression errors reach the run's error log."""
    return getattr(_ctx_local, "ctx", None)


def set_current_ctx(ctx: "RunContext | None") -> None:
    _ctx_local.ctx = ctx


def _user_trace() -> str:
    """file:line of the first stack frame OUTSIDE pathway_tpu — the user
    code that created the operator (reference ``internals/trace.py``
    captures the creation frame the same way)."""
    import sys

    f = sys._getframe(1)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(pkg_root) and "pathway_tpu" not in fn:
            return f"{fn}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
    return ""


class RunContext:
    """Per-run mutable state: node states, current time, worker topology."""

    def __init__(self, n_workers: int = 1, worker_id: int = 0):
        self.states: dict[int, Any] = {}
        self.time: int = 0
        self.n_workers = n_workers
        self.worker_id = worker_id
        self.error_log: list[str] = []
        self.stats: dict[str, Any] = {}
        #: entries not yet drained into the error-log table node; ONLY
        #: filled when the graph has an ErrorLogNode (the scheduler sets
        #: error_sink_enabled) — otherwise nothing ever drains it and a
        #: long streaming run would leak unboundedly
        self.error_pending: list[ErrorEntry] = []
        self.error_sink_enabled: bool = False
        #: input node ids whose connector gave up under on_failure=
        #: "degrade": downstream tables reflect only the rows delivered
        #: before the failure (stale).  Filled by the connector
        #: supervisor; surfaced through the monitoring snapshot.
        self.stale_sources: set[int] = set()

    def state(self, node: "Node") -> Any:
        if node.id not in self.states:
            self.states[node.id] = node.make_state()
        return self.states[node.id]

    def log_error(self, node: "Node | None", message: str) -> ErrorEntry:
        """Record an operator error with its creation trace; the entry
        feeds both ``ctx.error_log`` and the global error-log table."""
        entry = ErrorEntry(
            message,
            operator=repr(node) if node is not None else "",
            trace=getattr(node, "trace", "") or "",
            time=self.time,
        )
        self.error_log.append(entry)
        if self.error_sink_enabled:
            self.error_pending.append(entry)
        return entry


class Node:
    """An operator in the dataflow graph."""

    #: nodes that want a `process` call every epoch even with empty input
    always_tick = False

    #: True when :meth:`process` understands
    #: :class:`~pathway_tpu.engine.columnar.ColumnarBatch` inputs (frame
    #: segments consumed by native kernels); the scheduler materializes
    #: frames to row lists before calling any node that leaves this False
    #: — the Python-UDF row-at-a-time fallback
    supports_columnar = False

    def __init__(self, graph: "EngineGraph", inputs: Sequence["Node"], name: str = ""):
        self.graph = graph
        self.inputs = list(inputs)
        self.name = name or type(self).__name__
        self.id = graph.register(self)
        #: user file:line that created this operator (engine errors are
        #: re-annotated with it — reference OperatorProperties.trace,
        #: ``src/engine/graph.rs:441-463``)
        self.trace = _user_trace()
        #: build-time annotations consumed by the pre-flight static
        #: analyzer (pathway_tpu/analysis/): expression ASTs, declared
        #: column names/dtypes, join-key pairs.  Never read by the engine
        #: hot path and never shipped across processes.
        self.meta: dict[str, Any] = {}

    def exchange_routes(self) -> list | None:
        """Multi-worker co-location: one route function per input port
        (``Update -> stable shard int``; destination worker = shard % W),
        or None for operators that process rows wherever they are
        (reference key-hash exchange, ``src/engine/dataflow.rs:1068-1072``).
        Stateful operators MUST route so each worker owns a disjoint state
        shard; stateless ones keep data local."""
        return None

    def make_state(self) -> Any:
        return {}

    def process(self, ctx: RunContext, time: int, inbatches: list[Batch]) -> Batch:
        raise NotImplementedError

    def on_time_end(self, ctx: RunContext, time: int) -> None:
        pass

    def on_end(self, ctx: RunContext) -> None:
        pass

    def on_restore(self, ctx: RunContext) -> None:
        """Called once after this node's state was restored from an
        operator snapshot, before any epoch runs.  Sinks reposition their
        outputs to the checkpointed watermark here so replayed epochs
        cannot double-emit; most operators need nothing."""

    def snapshot_state(self, ctx: RunContext) -> Any:
        """Extra state to checkpoint IN PLACE of ``ctx.states[self.id]``,
        or None to snapshot the plain operator state.  Operators holding
        large out-of-band state (an external index) fold a serialized
        copy into the snapshot here, keyed to the same connector offsets
        as everything else; :meth:`on_restore` unfolds it.  Must return
        picklable data (numpy, not jax arrays)."""
        return None

    def __repr__(self) -> str:
        return f"<{self.name}#{self.id}>"


class EngineGraph:
    """Holds the node list; topological order == creation order (inputs are
    always created before consumers; `iterate` bodies live in subgraphs)."""

    def __init__(self) -> None:
        self.nodes: list[Node] = []
        #: per-epoch stats callbacks (reference attach_prober/probe_table,
        #: src/engine/graph.rs:988-995); invoked by the scheduler on
        #: worker 0 after every epoch
        self.probers: list[Callable[[dict], None]] = []

    def register(self, node: Node) -> int:
        self.nodes.append(node)
        return len(self.nodes) - 1


# ---------------------------------------------------------------------------
# Sources


class InputNode(Node):
    """A table fed from outside the graph: static rows and/or a live
    connector subject (reference ``connector_table``,
    ``src/engine/graph.rs:961``)."""

    def __init__(
        self,
        graph: EngineGraph,
        n_cols: int,
        static_rows: Iterable[tuple[Pointer, tuple]] = (),
        subject: Any = None,
        name: str = "input",
        upsert: bool = False,
    ):
        super().__init__(graph, [], name)
        self.n_cols = n_cols
        self.static_rows = list(static_rows)
        self.subject = subject
        self.upsert = upsert
        # upsert sessions walk per-row state; only the plain append
        # stream can pass frames through untouched
        self.supports_columnar = not upsert

    def exchange_routes(self):
        return [cl.route_by_key] if self.upsert else None

    def make_state(self) -> Any:
        return {"rows": {}}  # key -> values, for upsert semantics

    def process(self, ctx: RunContext, time: int, inbatches: list[Batch]) -> Batch:
        # inbatches[0] is the externally injected batch for this epoch
        raw = inbatches[0] if inbatches else []
        if not self.upsert:
            from pathway_tpu.engine.columnar import ColumnarBatch

            if isinstance(raw, ColumnarBatch):
                # frame passthrough: append-only frames flow downstream
                # columnar (the header's all_plus flag makes the check
                # O(segments)); anything with retractions materializes
                # for the consolidation pass below
                if raw.all_plus():
                    return raw
                raw = raw.to_list()
            if not isinstance(raw, list):
                raw = list(raw)  # the all() scan below must not consume it
            # append-only batch (no retractions): consolidation is a
            # semantic no-op on the multiset — skip the hash pass
            native = _native.load()
            if native is not None:
                if native.all_positive(raw):
                    return raw
            elif all(u.diff > 0 for u in raw):
                return raw
            return consolidate(raw)
        # Upsert session semantics (reference SessionType::Upsert,
        # src/connectors/adaptors.rs:23-40): +1 overwrites, -1 deletes by key.
        rows = ctx.state(self)["rows"]
        out: list[Update] = []
        for u in raw:
            old = rows.get(u.key)
            if u.diff > 0:
                if old == u.values:
                    continue  # no-op overwrite: an object re-read's
                    # unchanged prefix must not churn downstream
                if old is not None:
                    out.append(Update(u.key, old, -1))
                rows[u.key] = u.values
                out.append(Update(u.key, u.values, 1))
            else:
                if old is not None:
                    out.append(Update(u.key, old, -1))
                    del rows[u.key]
        return consolidate(out)


# ---------------------------------------------------------------------------
# Stateless row transforms


class RowwiseNode(Node):
    """expression_table (reference ``Graph::expression_table``): compute a new
    tuple of columns for each row via compiled expression closures."""

    #: positional projection tuple set by the plan compiler
    #: (analysis/rewrite._pass_columnar) when every output column is a
    #: plain column reference — arms the frame_project fast path (and
    #: supports_columnar with it)
    frame_project: "tuple | None" = None

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        row_fn: Callable[[Pointer, tuple], tuple],
        name: str = "select",
        typecheck_info: tuple[list[str], list] | None = None,
        programs: Any = None,
    ):
        super().__init__(graph, [input], name)
        self.row_fn = row_fn
        #: per-column VM bytecode capsules (internals/expr_vm.py) — the
        #: fully-native select path; row_fn remains the semantic ground
        #: truth and the PATHWAY_DISABLE_NATIVE fallback
        self.programs = programs
        #: (column names, declared dtypes) for PATHWAY_RUNTIME_TYPECHECKING
        self.typecheck_info = typecheck_info
        self._checker: Any = None

    def _typecheck(self) -> Callable[[tuple], None] | None:
        """The runtime validator iff typechecking is on for this run
        (reference runtime typechecking mode) — checked per batch so
        ``pw.run(runtime_typechecking=True)`` works after graph build."""
        if self.typecheck_info is None:
            return None
        from pathway_tpu.internals.config import pathway_config

        if not pathway_config.runtime_typechecking:
            return None
        if self._checker is None:
            from pathway_tpu.internals.type_interpreter import (
                make_runtime_checker,
            )

            names, dtypes = self.typecheck_info
            self._checker = make_runtime_checker(names, dtypes, self.name)
        return self._checker

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.columnar import ColumnarBatch

        batch = inbatches[0]
        if isinstance(batch, ColumnarBatch):
            check = self._typecheck()
            native = _native.load()
            if (
                self.frame_project is None
                or check is not None
                or native is None
            ):
                inbatches = [batch.to_list()]
            else:
                # pure projection: column copies per frame segment, row
                # segments ride the existing row kernels below
                out = ColumnarBatch()
                for kind, seg in batch.segments:
                    if kind == "f":
                        out.append_frame(
                            native.frame_project(seg, self.frame_project)
                        )
                    elif seg:
                        out.extend(self.process(ctx, time, [seg]))
                return out
        fn = self.row_fn
        check = self._typecheck()
        native = _native.load()
        if native is not None and check is None:
            if self.programs is not None:
                # expression VM: typed tree evaluated in C, no per-row
                # Python closure dispatch (reference expression.rs role)
                return native.vm_eval_batch(
                    inbatches[0],
                    self.programs,
                    Update,
                    api.ERROR,
                    lambda e: ctx.log_error(self, f"{self.name}: {e!r}"),
                )
            return native.rowwise_map(
                inbatches[0],
                fn,
                Update,
                api.ERROR,
                lambda e: ctx.log_error(self, f"{self.name}: {e!r}"),
            )
        out = []
        for u in inbatches[0]:
            try:
                vals = fn(u.key, u.values)
            except Exception as e:
                ctx.log_error(self, f"{self.name}: {e!r}")
                vals = tuple([api.ERROR])
            else:
                if check is not None:
                    check(vals)  # declared-type violations fail the run
            out.append(Update(u.key, vals, u.diff))
        return out


class FilterNode(Node):
    #: (pos, cmp_op, const) set by the plan compiler for a single
    #: col-cmp-const predicate — arms the frame_filter fast path
    frame_filter_spec: "tuple | None" = None

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        pred: Callable[[Pointer, tuple], Any],
        name: str = "filter",
        program: Any = None,
    ):
        super().__init__(graph, [input], name)
        self.pred = pred
        #: VM bytecode capsule for the predicate (internals/expr_vm.py)
        self.program = program

    @classmethod
    def detached(
        cls,
        input: Node,
        pred: Callable[[Pointer, tuple], Any],
        *,
        node_id: int,
        name: str = "filter",
        program: Any = None,
    ) -> "FilterNode":
        """Build a filter without registering it in any graph — the plan
        rewriter (analysis/rewrite.py) inserts these into its execution
        view with an id it allocates itself, leaving the captured graph's
        id space untouched."""
        n = object.__new__(cls)
        n.graph = input.graph
        n.inputs = [input]
        n.name = name
        n.id = node_id
        n.trace = input.trace
        n.meta = {}
        n.pred = pred
        n.program = program
        return n

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.columnar import ColumnarBatch

        batch = inbatches[0]
        if isinstance(batch, ColumnarBatch):
            native = _native.load()
            spec = self.frame_filter_spec
            if native is None or spec is None:
                inbatches = [batch.to_list()]
            else:
                out = ColumnarBatch()
                for kind, seg in batch.segments:
                    if kind == "f":
                        try:
                            out.append_frame(
                                native.frame_filter(seg, *spec)
                            )
                            continue
                        except native.Unsupported:
                            # e.g. int column vs float const: exact
                            # arithmetic parity needs the row semantics
                            seg = native.frame_to_updates(seg)
                    if seg:
                        out.extend(self.process(ctx, time, [seg]))
                return out
        pred = self.pred
        native = _native.load()
        if native is not None:
            if self.program is not None:
                return native.vm_filter_batch(
                    inbatches[0], self.program, api.ERROR
                )
            return native.filter_batch(inbatches[0], pred, api.ERROR)
        out = []
        for u in inbatches[0]:
            try:
                keep = pred(u.key, u.values)
            except Exception:
                keep = False
            # accept any truthy value (incl. numpy bools); Error/None drop
            if keep is not None and keep is not api.ERROR and bool(keep):
                out.append(u)
        return out


class FlattenNode(Node):
    """Explode one column; derived keys (reference ``Graph::flatten_table``)."""

    def __init__(self, graph: EngineGraph, input: Node, col_idx: int, name: str = "flatten"):
        super().__init__(graph, [input], name)
        self.col_idx = col_idx

    def process(self, ctx, time, inbatches):
        out = []
        ci = self.col_idx
        for u in inbatches[0]:
            seq = u.values[ci]
            if seq is None or seq is api.ERROR:
                continue
            if isinstance(seq, str):
                elems: Iterable[Any] = list(seq)
            else:
                try:
                    elems = list(seq)
                except TypeError:
                    continue
            for i, e in enumerate(elems):
                vals = u.values[:ci] + (e,) + u.values[ci + 1 :]
                out.append(Update(K.derive(u.key, "flatten", i), vals, u.diff))
        return out


class ReindexNode(Node):
    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        key_fn: Callable[[Pointer, tuple], Pointer],
        name: str = "reindex",
    ):
        super().__init__(graph, [input], name)
        self.key_fn = key_fn

    def process(self, ctx, time, inbatches):
        fn = self.key_fn
        return [Update(fn(u.key, u.values), u.values, u.diff) for u in inbatches[0]]


class ConcatNode(Node):
    """Union of disjoint-key tables (reference ``Graph::concat_tables``)."""

    def __init__(self, graph: EngineGraph, inputs: Sequence[Node], name: str = "concat"):
        super().__init__(graph, inputs, name)

    def process(self, ctx, time, inbatches):
        out: list[Update] = []
        for b in inbatches:
            out.extend(b)
        return consolidate(out)


# ---------------------------------------------------------------------------
# Keyed stateful combinators

def _apply_batch_to_rows(rows: dict, batch: Batch) -> dict[Pointer, tuple]:
    """Apply updates to a key->values dict; return {key: old_values_or_None}
    of touched keys (before state)."""
    touched: dict[Pointer, Any] = {}
    for key, (rem, add) in per_key_changes(batch).items():
        if key not in touched:
            touched[key] = rows.get(key)
        if add:
            rows[key] = add[-1]
        elif rem:
            rows.pop(key, None)
    return touched


class IntersectNode(Node):
    """Rows of main whose key exists in every other input
    (reference ``Graph::intersect_tables``)."""

    def __init__(self, graph: EngineGraph, main: Node, others: Sequence[Node], name: str = "intersect"):
        super().__init__(graph, [main, *others], name)

    def exchange_routes(self):
        return [cl.route_by_key] * len(self.inputs)

    def make_state(self):
        return {"main": {}, "others": [dict() for _ in self.inputs[1:]]}

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        # O(batch): _apply_batch_to_rows returns pre-update values of exactly
        # the touched keys; untouched keys read current state.
        tm = _apply_batch_to_rows(st["main"], inbatches[0])
        tos = [
            _apply_batch_to_rows(st["others"][i], b)
            for i, b in enumerate(inbatches[1:])
        ]
        touched: set[Pointer] = set(tm)
        for to in tos:
            touched.update(to)

        def old_value(key):
            return tm[key] if key in tm else st["main"].get(key)

        def old_in_other(i, key):
            if key in tos[i]:
                return tos[i][key] is not None
            return key in st["others"][i]

        out = []
        for key in touched:
            was_v = old_value(key)
            was = was_v is not None and all(old_in_other(i, key) for i in range(len(tos)))
            now_v = st["main"].get(key)
            now = now_v is not None and all(key in o for o in st["others"])
            if was:
                out.append(Update(key, was_v, -1))
            if now:
                out.append(Update(key, now_v, 1))
        return consolidate(out)


class SubtractNode(Node):
    """Rows of main whose key is absent from other
    (reference ``Graph::subtract_table``)."""

    def __init__(self, graph: EngineGraph, main: Node, other: Node, name: str = "difference"):
        super().__init__(graph, [main, other], name)

    def exchange_routes(self):
        return [cl.route_by_key, cl.route_by_key]

    def make_state(self):
        return {"main": {}, "other": {}}

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        tm = _apply_batch_to_rows(st["main"], inbatches[0])
        to = _apply_batch_to_rows(st["other"], inbatches[1])
        touched: set[Pointer] = set(tm) | set(to)
        out = []
        for key in touched:
            was_v = tm[key] if key in tm else st["main"].get(key)
            was_in_other = (to[key] is not None) if key in to else key in st["other"]
            was = was_v is not None and not was_in_other
            now_v = st["main"].get(key)
            now = now_v is not None and key not in st["other"]
            if was:
                out.append(Update(key, was_v, -1))
            if now:
                out.append(Update(key, now_v, 1))
        return consolidate(out)


class UpdateRowsNode(Node):
    """``a.update_rows(b)``: per key, b wins (reference
    ``Graph::update_rows_table``)."""

    def __init__(self, graph: EngineGraph, a: Node, b: Node, name: str = "update_rows"):
        super().__init__(graph, [a, b], name)

    def exchange_routes(self):
        return [cl.route_by_key, cl.route_by_key]

    def make_state(self):
        return {"a": {}, "b": {}}

    def _value(self, st, key):
        if key in st["b"]:
            return st["b"][key]
        return st["a"].get(key)

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        ta = _apply_batch_to_rows(st["a"], inbatches[0])
        tb = _apply_batch_to_rows(st["b"], inbatches[1])
        touched: set[Pointer] = set(ta) | set(tb)
        out = []
        for key in touched:
            old_a = ta[key] if key in ta else st["a"].get(key)
            old_b = tb[key] if key in tb else st["b"].get(key)
            was = old_b if old_b is not None else old_a
            now = self._value(st, key)
            if was is not None:
                out.append(Update(key, was, -1))
            if now is not None:
                out.append(Update(key, now, 1))
        return consolidate(out)


class UpdateCellsNode(Node):
    """``a.update_cells(b)``: override selected columns for keys present in b
    (reference ``Graph::update_cells_table``).  ``col_map[i]`` gives, for
    output column i, ``(source, idx)`` with source 0=a, 1=b."""

    def __init__(self, graph: EngineGraph, a: Node, b: Node, col_map: list[tuple[int, int]], name: str = "update_cells"):
        super().__init__(graph, [a, b], name)
        self.col_map = col_map

    def exchange_routes(self):
        return [cl.route_by_key, cl.route_by_key]

    def make_state(self):
        return {"a": {}, "b": {}}

    def _value(self, st, key):
        a = st["a"].get(key)
        if a is None:
            return None
        b = st["b"].get(key)
        if b is None:
            return a
        return tuple(a[i] if src == 0 else b[i] for src, i in self.col_map)

    def _value_from(self, a, b):
        if a is None:
            return None
        if b is None:
            return a
        return tuple(a[i] if src == 0 else b[i] for src, i in self.col_map)

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        ta = _apply_batch_to_rows(st["a"], inbatches[0])
        tb = _apply_batch_to_rows(st["b"], inbatches[1])
        touched: set[Pointer] = set(ta) | set(tb)
        out = []
        for key in touched:
            old_a = ta[key] if key in ta else st["a"].get(key)
            old_b = tb[key] if key in tb else st["b"].get(key)
            was = self._value_from(old_a, old_b)
            now = self._value(st, key)
            if was is not None:
                out.append(Update(key, was, -1))
            if now is not None:
                out.append(Update(key, now, 1))
        return consolidate(out)


# ---------------------------------------------------------------------------
# GroupBy / reduce


class GroupByNode(Node):
    """Incremental grouped reduction (reference ``Graph::group_by_table`` +
    ``src/engine/reduce.rs``).  Only dirty groups re-extract per epoch."""

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        group_fn: Callable[[Pointer, tuple], tuple],
        reducer_args: list[tuple[ReducerImpl, Callable[[Pointer, tuple], tuple]]],
        output_key_fn: Callable[[tuple], Pointer] | None = None,
        include_group_values: bool = True,
        name: str = "groupby",
        fast_spec: tuple | None = None,
    ):
        super().__init__(graph, [input], name)
        self.group_fn = group_fn
        self.reducer_args = reducer_args
        self.output_key_fn = output_key_fn or (lambda gvals: K.ref_scalar(*gvals))
        self.include_group_values = include_group_values
        #: (group_positions, reducer_specs) for the native partial
        #: aggregation path (groupbys.py builds it when every grouping and
        #: reducer argument is a plain positional column)
        self.fast_spec = fast_spec
        # frame segments reduce via frame_groupby_partials, which needs
        # the same positional spec as the row-batch partials kernel
        self.supports_columnar = fast_spec is not None

    def exchange_routes(self):
        route = cl.route_by(self.group_fn)
        if self.fast_spec is not None:
            # native route_split hashes the same positional group cells
            # stable_shard would (one C pass instead of per-row closures)
            route.positional = self.fast_spec[0]
        return [route]

    def specialize_append_only(self) -> list[str]:
        """Swap every reducer that has a non-retracting variant
        (reducers.append_only_variant); returns the swapped reducers'
        names.  Sound only when the input stream is proven append-only —
        the caller (analysis/rewrite.py) owns that proof.  Builds a
        fresh reducer_args list so a cloned node never mutates the
        original's.  fast_spec stays valid: variants keep native_code 2,
        the partial format the swapped-in merge_partial folds."""
        from pathway_tpu.engine.reducers import append_only_variant

        swapped: list[str] = []
        new_args = []
        for impl, arg_fn in self.reducer_args:
            variant = append_only_variant(impl)
            if variant is None:
                new_args.append((impl, arg_fn))
            else:
                swapped.append(impl.name)
                new_args.append((variant, arg_fn))
        if swapped:
            self.reducer_args = new_args
        return swapped

    def make_state(self):
        # group_hash -> {gvals, accs: [...], count, last_out: tuple|None}
        return {"groups": {}}

    def _group(self, st, gvals):
        from pathway_tpu.engine.stream import hashable_row

        # plain tuple hash first (scalar group keys — the common case);
        # unhashable cells fall back to the type-tagged form
        groups = st["groups"]
        try:
            g = groups.get(gvals)
            gh = gvals
        except TypeError:
            gh = hashable_row(gvals)
            g = groups.get(gh)
        if g is None:
            g = {
                "gvals": gvals,
                "accs": [r.make_acc() for r, _ in self.reducer_args],
                "count": 0,
                "last_out": None,
            }
            groups[gh] = g
        return gh, g

    def _accumulate_native(self, st, batch) -> dict | None:
        """One C pass producing per-group partials, merged per dirty group
        (native ``groupby_partials``); None -> caller runs the Python loop."""
        from pathway_tpu.internals import native as _native
        from pathway_tpu.engine.stream import hashable_row

        native = _native.load()
        if native is None:
            return None
        try:
            partials = native.groupby_partials(
                batch,
                self.fast_spec[0],
                self.fast_spec[1],
                api.ERROR,
                hashable_row,
            )
        except native.Unsupported:
            return None
        dirty: dict[Any, Any] = {}
        self._merge_partials(st, partials, dirty)
        return dirty

    def _merge_partials(self, st, partials: dict, dirty: dict) -> None:
        """Fold a per-group partials dict (the shared output format of
        ``groupby_partials`` and ``frame_groupby_partials``) into the
        live group accumulators, marking touched groups dirty."""
        reducer_args = self.reducer_args
        for gvals, (cdelta, parts) in partials.items():
            gh, g = self._group(st, gvals)
            g["count"] += cdelta
            for (reducer, _), acc, part in zip(reducer_args, g["accs"], parts):
                reducer.merge_partial(acc, part)
            dirty[gh] = g

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.columnar import ColumnarBatch

        st = ctx.state(self)
        batch = inbatches[0]
        frame_dirty: dict[Any, Any] = {}
        if isinstance(batch, ColumnarBatch):
            # frame segments: one native pass per frame producing the
            # SAME partials dict as the row kernel — no Update objects,
            # no per-row key hashing (groupby never looks at row keys
            # when grouping by columns, so lazy frame keys stay lazy).
            # Frames cannot hold the ERROR sentinel by construction, so
            # the error-poisoning scan below applies only to row
            # segments.  Unsupported frames (overflow, odd types) fall
            # back to rows individually.
            from pathway_tpu.internals import native as _native

            native = _native.load()
            rows: list = []
            for seg_kind, seg in batch.segments:
                if seg_kind != "f":
                    rows.extend(seg)
                    continue
                partials = None
                if self.fast_spec is not None and native is not None:
                    try:
                        partials = native.frame_groupby_partials(
                            seg,
                            self.fast_spec[0],
                            self.fast_spec[1],
                            api.ERROR,
                        )
                    except native.Unsupported:
                        partials = None
                if partials is None:
                    rows.extend(native.frame_to_updates(seg))
                else:
                    self._merge_partials(st, partials, frame_dirty)
            batch = rows
        if not isinstance(batch, list):
            batch = list(batch)  # Unsupported fallback must re-iterate
        # ERROR poisoning (reference reduce.rs: any Error input makes the
        # group's aggregate Value::Error until it is retracted).  Error
        # presence is tracked per (group, reducer) in g["errs"], balanced
        # by diffs; extract() is bypassed while the count is nonzero.
        dirty: dict[Any, Any] | None = None
        if self.fast_spec is not None:
            dirty = self._accumulate_native(st, batch)
        if dirty is None:
            dirty = {}
            reducer_args = self.reducer_args
            group_fn = self.group_fn
            for u in batch:
                gvals = group_fn(u.key, u.values)
                gh, g = self._group(st, gvals)
                g["count"] += u.diff
                for ri, ((reducer, arg_fn), acc) in enumerate(
                    zip(reducer_args, g["accs"])
                ):
                    # args computed ONCE; an ERROR arg (raw cell or a
                    # computed expression that errored) or a raising
                    # arg expression poisons instead of reaching
                    # update() — multiset reducers would otherwise store
                    # the sentinel and crash at extract
                    try:
                        rargs = arg_fn(u.key, u.values)
                        poisoned = bool(reducer.n_args) and any(
                            a is api.ERROR for a in rargs
                        )
                    except Exception:
                        rargs, poisoned = None, True
                    if poisoned:
                        errs = g.setdefault("errs", {})
                        errs[ri] = errs.get(ri, 0) + u.diff
                        continue
                    reducer.update(acc, rargs, u.diff)
                dirty[gh] = g
        else:
            # native fast path: reducer args are plain column positions
            # (fast_spec), so scanning the raw cells is exact; the C
            # partials skip sum-like error args and the multiset stores
            # them symmetrically — extract is masked while poisoned.
            # The sentinel scan itself runs in C too: a per-update Python
            # any() over the cells costs more than the aggregation.
            from pathway_tpu.internals import native as _native

            native = _native.load()
            err_rows = batch
            if native is not None:
                try:
                    err_rows = native.rows_with_error(batch, api.ERROR)
                except (native.Unsupported, AttributeError):
                    err_rows = batch
            for u in err_rows:
                if not any(v is api.ERROR for v in u.values):
                    continue
                gvals = self.group_fn(u.key, u.values)
                gh, g = self._group(st, gvals)
                for ri, (reducer, arg_fn) in enumerate(self.reducer_args):
                    if not reducer.n_args:
                        continue  # count() never looks at values
                    try:
                        poisoned = any(
                            a is api.ERROR for a in arg_fn(u.key, u.values)
                        )
                    except Exception:
                        poisoned = True
                    if poisoned:
                        errs = g.setdefault("errs", {})
                        errs[ri] = errs.get(ri, 0) + u.diff
                dirty[gh] = g
        if frame_dirty:
            dirty.update(frame_dirty)
        out = []
        for gh, g in dirty.items():
            # output key is a pure function of the group values — hash it
            # once per group's lifetime, not once per dirty epoch
            okey = g.get("okey")
            if okey is None:
                okey = g["okey"] = self.output_key_fn(g["gvals"])
            if g["last_out"] is not None:
                out.append(Update(okey, g["last_out"], -1))
                g["last_out"] = None
            if g["count"] > 0:
                errs = g.get("errs") or {}
                reduced = tuple(
                    api.ERROR if errs.get(ri, 0) != 0 else r.extract(acc)
                    for ri, ((r, _), acc) in enumerate(
                        zip(self.reducer_args, g["accs"])
                    )
                )
                row = (tuple(g["gvals"]) + reduced) if self.include_group_values else reduced
                out.append(Update(okey, row, 1))
                g["last_out"] = row
            elif g["count"] == 0:
                del st["groups"][gh]
        return consolidate(out)


class DeduplicateNode(Node):
    """Stateful deduplicate (reference ``Graph::deduplicate``,
    ``src/engine/graph.rs:895``): per instance, keep one accepted row;
    ``acceptor(new, old) -> bool`` decides replacement."""

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        instance_fn: Callable[[Pointer, tuple], Any],
        acceptor: Callable[[tuple, tuple | None], bool],
        name: str = "deduplicate",
    ):
        super().__init__(graph, [input], name)
        self.instance_fn = instance_fn
        self.acceptor = acceptor

    def exchange_routes(self):
        return [cl.route_by(self.instance_fn)]

    def make_state(self):
        return {"kept": {}}  # instance -> (key, values)

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.stream import hashable

        st = ctx.state(self)
        out = []
        for u in inbatches[0]:
            if u.diff <= 0:
                continue  # deduplicate consumes additions only (append-only source)
            inst = hashable(self.instance_fn(u.key, u.values))
            old = st["kept"].get(inst)
            try:
                accept = self.acceptor(u.values, old[1] if old else None)
            except Exception as e:
                ctx.log_error(self, f"deduplicate acceptor failed: {e!r}")
                continue
            if accept:
                if old is not None:
                    out.append(Update(old[0], old[1], -1))
                st["kept"][inst] = (u.key, u.values)
                out.append(Update(u.key, u.values, 1))
        return consolidate(out)


# ---------------------------------------------------------------------------
# Joins


class JoinNode(Node):
    """Incremental equi-join (reference ``Graph::join_tables``).

    Output rows: ``left_values + right_values`` (either side replaced by
    Nones when unmatched in outer modes).  Per-epoch algorithm: apply both
    deltas to the per-join-key arrangements, then recompute the output block
    for every dirty join key and emit the difference — correct for
    inner/left/right/outer under arbitrary mixed deltas.
    """

    def __init__(
        self,
        graph: EngineGraph,
        left: Node,
        right: Node,
        left_jk_fn: Callable[[Pointer, tuple], tuple],
        right_jk_fn: Callable[[Pointer, tuple], tuple],
        left_ncols: int,
        right_ncols: int,
        kind: str = "inner",  # inner|left|right|outer
        *,
        left_id_only: bool = False,
        name: str = "join",
        jk_programs: Any = None,
    ):
        super().__init__(graph, [left, right], name)
        self.left_jk_fn = left_jk_fn
        self.right_jk_fn = right_jk_fn
        self.left_ncols = left_ncols
        self.right_ncols = right_ncols
        self.kind = kind
        self.left_id_only = left_id_only
        #: (left_prog, right_prog) VM capsules computing the join-key
        #: tuple per row — enables the full native epoch pass
        self.jk_programs = jk_programs

    def exchange_routes(self):
        return [cl.route_by(self.left_jk_fn), cl.route_by(self.right_jk_fn)]

    def make_state(self):
        return {"left": {}, "right": {}}  # jk -> {row_key: values}

    def _block(self, lrows: dict, rrows: dict) -> dict[Pointer, tuple]:
        """Full output block for one join key."""
        out: dict[Pointer, tuple] = {}
        lnone = (None,) * self.left_ncols
        rnone = (None,) * self.right_ncols
        if lrows and rrows:
            if self.left_id_only and len(rrows) > 1:
                # id=pw.left.id requires at most one match per left row
                # (reference raises on duplicated ids)
                raise api.EngineError(
                    f"join with id=left.id: left row has {len(rrows)} right matches"
                )
            for lk, lv in lrows.items():
                for rk, rv in rrows.items():
                    okey = lk if self.left_id_only else K.join_key(lk, rk)
                    out[okey] = lv + rv + (lk, rk)
        elif lrows and self.kind in ("left", "outer"):
            for lk, lv in lrows.items():
                okey = lk if self.left_id_only else K.join_key(lk, None)
                out[okey] = lv + rnone + (lk, None)
        elif rrows and self.kind in ("right", "outer"):
            for rk, rv in rrows.items():
                out[K.ref_scalar("__join_r__", int(rk))] = lnone + rv + (None, rk)
        return out

    @staticmethod
    def _side_jks(batch: Batch, jk_fn) -> list:
        """Hashable join key per update (None = null key, never matches);
        computed ONCE per row and reused by the dirty scan + state apply."""
        from pathway_tpu.engine.stream import hashable_row

        out = []
        for u in batch:
            jk = jk_fn(u.key, u.values)
            try:
                hash(jk)  # plain-scalar tuples: use as-is (common case)
            except TypeError:
                jk = hashable_row(jk)
            if jk is None or any(v is None for v in jk):
                jk = None
            out.append(jk)
        return out

    @staticmethod
    def _apply_side(side: dict, batch: Batch, jks: list) -> None:
        for u, jk in zip(batch, jks):
            if jk is None:
                continue  # null join keys never match
            rows = side.setdefault(jk, {})
            if u.diff > 0:
                rows[u.key] = u.values
            else:
                rows.pop(u.key, None)

    _KIND_CODES = {"inner": 0, "left": 1, "right": 2, "outer": 3}

    def _split_null_keys(self, batch, jk_fn, side: str, null_out: list):
        """Partition null-jk rows off a batch, appending their
        passthrough updates (built by :meth:`_block`, the single owner of
        the output row shape) to ``null_out``.  Returns (kept_rows,
        kept_jks)."""
        batch = list(batch)
        jks = self._side_jks(batch, jk_fn)
        if all(jk is not None for jk in jks):
            return batch, jks
        kept, kept_jks = [], []
        for u, jk in zip(batch, jks):
            if jk is not None:
                kept.append(u)
                kept_jks.append(jk)
                continue
            single = {u.key: u.values}
            block = (
                self._block(single, {})
                if side == "left"
                else self._block({}, single)
            )
            null_out.extend(
                Update(okey, vals, u.diff) for okey, vals in block.items()
            )
        return kept, kept_jks

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        native = _native.load()
        if native is not None and self.jk_programs is not None:
            # whole-epoch native pass (build/probe/diff in C, mirroring
            # groupby_partials); Unsupported is only raised BEFORE the
            # arrangements mutate, so the fallback below re-runs safely
            try:
                out = native.join_process(
                    inbatches[0],
                    inbatches[1],
                    self.jk_programs[0],
                    self.jk_programs[1],
                    st["left"],
                    st["right"],
                    self._KIND_CODES[self.kind],
                    1 if self.left_id_only else 0,
                    self.left_ncols,
                    self.right_ncols,
                    Update,
                    api.ERROR,
                    api.EngineError,
                )
            except native.Unsupported:
                pass
            else:
                return consolidate(out)
        # SQL outer semantics: a null join key never MATCHES, but the row
        # is RETAINED unmatched on its preserved side (LEFT/RIGHT/FULL
        # OUTER keep null-key rows; only INNER drops them).  The native
        # pass emits these passthroughs itself
        # (join_emit_null_passthroughs); this split only runs on the
        # Python fallback, and its jks feed the arrangement pass below so
        # nothing is evaluated twice.
        null_out: list[Update] = []
        ljks = rjks = None
        if self.kind in ("left", "outer"):
            left_b, ljks = self._split_null_keys(
                inbatches[0], self.left_jk_fn, "left", null_out
            )
            inbatches = [left_b, inbatches[1]]
        if self.kind in ("right", "outer"):
            right_b, rjks = self._split_null_keys(
                inbatches[1], self.right_jk_fn, "right", null_out
            )
            inbatches = [inbatches[0], right_b]
        if ljks is None:
            ljks = self._side_jks(inbatches[0], self.left_jk_fn)
        if rjks is None:
            rjks = self._side_jks(inbatches[1], self.right_jk_fn)
        dirty_keys: set = set()
        dirty_keys.update(jk for jk in ljks if jk is not None)
        dirty_keys.update(jk for jk in rjks if jk is not None)
        old_blocks = {
            jk: self._block(st["left"].get(jk, {}), st["right"].get(jk, {}))
            for jk in dirty_keys
        }
        self._apply_side(st["left"], inbatches[0], ljks)
        self._apply_side(st["right"], inbatches[1], rjks)
        out: list[Update] = []
        for jk in dirty_keys:
            new_block = self._block(st["left"].get(jk, {}), st["right"].get(jk, {}))
            old_block = old_blocks[jk]
            for okey, vals in old_block.items():
                if new_block.get(okey) != vals:
                    out.append(Update(okey, vals, -1))
            for okey, vals in new_block.items():
                if old_block.get(okey) != vals:
                    out.append(Update(okey, vals, 1))
            if not st["left"].get(jk) and not st["right"].get(jk):
                st["left"].pop(jk, None)
                st["right"].pop(jk, None)
        return consolidate(out + null_out)


class IxNode(Node):
    """Row lookup by pointer (reference ``Graph::ix_table``): for each request
    row holding a key into `target`, output the target row under the request's
    key.  Maintains a reverse index so target changes re-resolve requests."""

    def __init__(
        self,
        graph: EngineGraph,
        target: Node,
        requests: Node,
        key_fn: Callable[[Pointer, tuple], Any],
        target_ncols: int,
        optional: bool = False,
        strict: bool = True,
        name: str = "ix",
    ):
        super().__init__(graph, [target, requests], name)
        self.key_fn = key_fn
        self.optional = optional
        self.strict = strict
        self.target_ncols = target_ncols

    def exchange_routes(self):
        from pathway_tpu.engine import cluster as cl

        def route_request(u):
            try:
                tkey = self.key_fn(u.key, u.values)
            except Exception:
                return 0
            if tkey is None or tkey is api.ERROR:
                return 0
            return int(tkey)

        return [cl.route_by_key, route_request]

    def make_state(self):
        # out: req_key -> last emitted values (the cache that keeps
        # retractions consistent when target and requests change together)
        return {"target": {}, "requests": {}, "reverse": {}, "out": {}}

    def _resolve(self, st, req_key, req_vals):
        """Return (output_values_or_None, target_key_or_None) against the
        CURRENT target state."""
        tkey = self.key_fn(req_key, req_vals)
        if tkey is None or tkey is api.ERROR:
            if self.optional:
                return (None,) * self.target_ncols, None
            return tuple([api.ERROR] * self.target_ncols), None
        tv = st["target"].get(tkey)
        if tv is None:
            if self.strict:
                return tuple([api.ERROR] * self.target_ncols), tkey
            return None, tkey
        return tv, tkey

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        out: list[Update] = []
        touched_targets = _apply_batch_to_rows(st["target"], inbatches[0])
        handled: set[Pointer] = set()
        for u in inbatches[1]:
            handled.add(u.key)
            if u.diff > 0:
                vals, tkey = self._resolve(st, u.key, u.values)
                st["requests"][u.key] = u.values
                if tkey is not None:
                    st["reverse"].setdefault(tkey, set()).add(u.key)
                if vals is not None:
                    out.append(Update(u.key, vals, 1))
                    st["out"][u.key] = vals
            else:
                _, tkey = self._resolve(st, u.key, u.values)
                st["requests"].pop(u.key, None)
                if tkey is not None:
                    st["reverse"].get(tkey, set()).discard(u.key)
                prev = st["out"].pop(u.key, None)
                if prev is not None:
                    out.append(Update(u.key, prev, -1))
        for tkey in touched_targets:
            for rkey in list(st["reverse"].get(tkey, set())):
                if rkey in handled or rkey not in st["requests"]:
                    continue
                new_out, _ = self._resolve(st, rkey, st["requests"][rkey])
                old_out = st["out"].get(rkey)
                if old_out == new_out:
                    continue
                if old_out is not None:
                    out.append(Update(rkey, old_out, -1))
                if new_out is not None:
                    out.append(Update(rkey, new_out, 1))
                    st["out"][rkey] = new_out
                else:
                    st["out"].pop(rkey, None)
        return consolidate(out)


class ZipNode(Node):
    """Zip same-universe tables by key: output tuple = concatenation of every
    input's values (inner semantics — a key emits only when present in all
    inputs).  Supports select() referencing columns of several same-universe
    tables, the capability the reference gets from its column/universe model
    (``internals/column.py``)."""

    def __init__(self, graph: EngineGraph, inputs: Sequence[Node], widths: Sequence[int], name: str = "zip"):
        super().__init__(graph, inputs, name)
        self.widths = list(widths)

    def exchange_routes(self):
        return [cl.route_by_key] * len(self.inputs)

    def make_state(self):
        return {"rows": [dict() for _ in self.inputs], "out": {}}

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        touched: set[Pointer] = set()
        for i, b in enumerate(inbatches):
            touched.update(_apply_batch_to_rows(st["rows"][i], b).keys())
        out: list[Update] = []
        for key in touched:
            parts = [st["rows"][i].get(key) for i in range(len(self.inputs))]
            new = None
            if all(p is not None for p in parts):
                new = tuple(v for p in parts for v in p)
            old = st["out"].get(key)
            if old == new:
                continue
            if old is not None:
                out.append(Update(key, old, -1))
            if new is not None:
                out.append(Update(key, new, 1))
                st["out"][key] = new
            else:
                st["out"].pop(key, None)
        return consolidate(out)


class ErrorLogNode(Node):
    """The global error-log TABLE's source (reference
    ``parse_graph.add_error_log`` + ``src/engine/error.rs``): drains the
    run context's pending error entries every epoch into rows
    ``(message, operator, trace)``.  Errors raised by operators processed
    after this node in an epoch surface one epoch later (and the final
    flush epoch drains the tail)."""

    always_tick = True

    def __init__(self, graph: EngineGraph, name: str = "error_log"):
        super().__init__(graph, [], name)

    def make_state(self):
        return {"seq": 0}

    def process(self, ctx, time, inbatches):
        if not ctx.error_pending:
            return []
        st = ctx.state(self)
        out = []
        for entry in ctx.error_pending:
            st["seq"] += 1
            key = K.ref_scalar("__error__", ctx.worker_id, st["seq"])
            out.append(
                Update(key, (entry.message, entry.operator, entry.trace), 1)
            )
        ctx.error_pending = []
        return out


class GradualBroadcastNode(Node):
    """Apportioned broadcast of a changing scalar (reference
    ``gradual_broadcast`` operator,
    ``src/engine/dataflow/operators/gradual_broadcast.rs``, 490 LoC).

    Port 0: the keyed table; port 1: a (usually 1-row) threshold table
    whose rows yield an approximation triplet ``(lower, value, upper)``
    via ``triplet_fn``.  Every output row carries an extra ``apx_value``
    column holding SOME value within the most recent ``[lower, upper]``
    window; a row's apx only changes when its held value falls OUTSIDE
    the new window.  This is the churn-damping contract the reference
    provides: a slightly-changed global aggregate (e.g. Louvain's total
    edge weight) does not retract/re-emit every row downstream."""

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        threshold: Node,
        triplet_fn: Callable[[Pointer, tuple], tuple],
        name: str = "gradual_broadcast",
    ):
        super().__init__(graph, [input, threshold], name)
        self.triplet_fn = triplet_fn

    # the threshold triplet is global state: centralize like the
    # reference's temporal buffers (TimeKey::shard() -> one worker)
    exchange_routes = cl.route_all_to_zero

    def make_state(self):
        return {"rows": {}, "apx": {}, "cur": None}

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        out: list[Update] = []
        # newest triplet first, so rows arriving this epoch use it
        trip = None
        for u in inbatches[1]:
            if u.diff > 0:
                trip = self.triplet_fn(u.key, u.values)
        if trip is not None:
            lower, value, upper = (float(x) for x in trip)
            st["cur"] = (lower, value, upper)
            for key, apx in list(st["apx"].items()):
                if apx is not None and lower <= apx <= upper:
                    continue  # still inside the window: no churn
                vals = st["rows"].get(key)
                if vals is None:
                    continue
                out.append(Update(key, vals + (apx,), -1))
                out.append(Update(key, vals + (value,), 1))
                st["apx"][key] = value
        removals = [u for u in inbatches[0] if u.diff < 0]
        additions = [u for u in inbatches[0] if u.diff > 0]
        for u in removals:
            vals = st["rows"].pop(u.key, None)
            apx = st["apx"].pop(u.key, None)
            if vals is not None:
                out.append(Update(u.key, vals + (apx,), -1))
        cur = st["cur"]
        for u in additions:
            apx = cur[1] if cur is not None else None
            st["rows"][u.key] = u.values
            st["apx"][u.key] = apx
            out.append(Update(u.key, u.values + (apx,), 1))
        return consolidate(out)


class SortNode(Node):
    """Sorting index: emits (prev, next) pointer columns per row, ordered by a
    sort key within an instance (reference ``prev_next`` operator,
    ``src/engine/dataflow/operators/prev_next.rs``).  Dirty instances are
    re-sorted per epoch; only rows whose neighbours changed re-emit."""

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        key_fn: Callable[[Pointer, tuple], Any],
        instance_fn: Callable[[Pointer, tuple], Any],
        name: str = "sort",
    ):
        super().__init__(graph, [input], name)
        self.key_fn = key_fn
        self.instance_fn = instance_fn

    def exchange_routes(self):
        return [cl.route_by(self.instance_fn)]

    def make_state(self):
        # instances: inst -> {row_key: sort_val}; out: row_key -> (prev, next)
        return {"instances": {}, "out": {}, "inst_of": {}}

    def process(self, ctx, time, inbatches):
        from pathway_tpu.engine.stream import hashable

        st = ctx.state(self)
        dirty: set = set()
        removed: list[Pointer] = []
        for u in inbatches[0]:
            inst = hashable(self.instance_fn(u.key, u.values))
            rows = st["instances"].setdefault(inst, {})
            if u.diff > 0:
                rows[u.key] = self.key_fn(u.key, u.values)
                st["inst_of"][u.key] = inst
            else:
                rows.pop(u.key, None)
                st["inst_of"].pop(u.key, None)
                removed.append(u.key)
            dirty.add(inst)
        out: list[Update] = []
        for rk in removed:
            pair = st["out"].pop(rk, None)
            if pair is not None:
                out.append(Update(rk, pair, -1))
        for inst in dirty:
            rows = st["instances"].get(inst, {})
            ordering = sorted(rows.items(), key=lambda kv: (kv[1], kv[0]))
            for i, (rk, _sv) in enumerate(ordering):
                prev = ordering[i - 1][0] if i > 0 else None
                nxt = ordering[i + 1][0] if i + 1 < len(ordering) else None
                pair = (prev, nxt)
                old = st["out"].get(rk)
                if old != pair:
                    if old is not None:
                        out.append(Update(rk, old, -1))
                    out.append(Update(rk, pair, 1))
                    st["out"][rk] = pair
            if not rows:
                st["instances"].pop(inst, None)
        return consolidate(out)


# ---------------------------------------------------------------------------
# Async / batched UDF execution


class AsyncMapNode(Node):
    """Per-epoch micro-batched async map (reference ``map_named_async``,
    ``src/engine/dataflow/operators.rs:218-305``): collect all additions in
    the epoch, run one batched async/jitted call, emit results at the same
    epoch.  Retractions replay the cached result."""

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        batch_fn: Callable[[list[tuple]], list[Any]],
        name: str = "async_map",
        distributed: bool = False,
    ):
        super().__init__(graph, [input], name)
        self.batch_fn = batch_fn
        #: False (default): all rows route to worker 0 — REQUIRED for
        #: device-batched UDFs (one TPU host executes one big batch;
        #: sharding would split it into per-worker fragments on workers
        #: without the device).  True: shard rows by key — right for
        #: IO-bound async UDFs (API calls), whose concurrency scales with
        #: workers instead of funneling through one.
        self.distributed = distributed

    def exchange_routes(self):
        return [cl.route_by_key if self.distributed else cl.route_to_zero]

    def make_state(self):
        return {"cache": {}}  # key -> result

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        additions = [u for u in inbatches[0] if u.diff > 0]
        removals = [u for u in inbatches[0] if u.diff < 0]
        out: list[Update] = []
        if additions:
            try:
                results = self.batch_fn([u.values for u in additions])
            except Exception as e:
                ctx.log_error(self, f"{self.name}: batched UDF failed: {e!r}")
                results = [api.ERROR] * len(additions)
            for u, res in zip(additions, results):
                st["cache"][u.key] = res
                out.append(Update(u.key, u.values + (res,), 1))
        for u in removals:
            res = st["cache"].get(u.key, api.ERROR)
            out.append(Update(u.key, u.values + (res,), -1))
        return out


# ---------------------------------------------------------------------------
# Outputs


def _record_sink_latency(ctx) -> None:
    """Per-stage latency probe at a sink (sink = epoch cut -> delivery
    here, e2e = earliest connector enqueue -> delivery); anchors are set
    by the scheduler only for live streaming epochs."""
    lat = getattr(ctx, "latency", None)
    if lat is None:
        return
    done_ns = lat.now_ns()
    cut_ns = getattr(ctx, "epoch_cut_ns", None)
    if cut_ns is not None:
        lat.record("sink", done_ns - cut_ns)
    origin_ns = getattr(ctx, "epoch_origin_ns", None)
    if origin_ns is not None:
        lat.record("e2e", done_ns - origin_ns)


class OutputNode(Node):
    """subscribe_table (reference ``src/engine/graph.rs:754``,
    ``SubscribeCallbacks`` ``:569``)."""

    def __init__(
        self,
        graph: EngineGraph,
        input: Node,
        on_change: Callable[[Pointer, tuple, int, int], None] | None = None,
        on_time_end: Callable[[int], None] | None = None,
        on_end: Callable[[], None] | None = None,
        name: str = "subscribe",
        writer: Any = None,
    ):
        super().__init__(graph, [input], name)
        self._on_change = on_change
        self._on_time_end = on_time_end
        self._on_end = on_end
        #: the file writer behind this sink, when there is one — enables
        #: checkpointed sink-dedup watermarks (see on_restore)
        self._writer = writer

    def exchange_routes(self):
        return [cl.route_to_zero]

    def make_state(self):
        return {"saw_data": False}

    def process(self, ctx, time, inbatches):
        if self._on_change is not None:
            for u in inbatches[0]:
                self._on_change(u.key, u.values, time, u.diff)
        if inbatches[0]:
            ctx.state(self)["saw_data"] = True
            _record_sink_latency(ctx)
        return []

    def on_time_end(self, ctx, time):
        # multi-worker: all updates are routed to worker 0, which alone
        # drives the output lifecycle (single-writer semantics)
        if ctx.worker_id == 0 and self._on_time_end is not None:
            self._on_time_end(time)
            if self._writer is not None:
                # sink dedup watermark: the byte offset of everything
                # emitted through this epoch, checkpointed with the
                # operator state — on_restore truncates the file back to
                # it, so replayed epochs never double-emit
                wm = getattr(self._writer, "watermark", None)
                if wm is not None:
                    ctx.state(self)["sink_watermark"] = wm()

    def on_end(self, ctx):
        if ctx.worker_id == 0 and self._on_end is not None:
            self._on_end()

    def on_restore(self, ctx):
        if ctx.worker_id != 0 or self._writer is None:
            return
        resume = getattr(self._writer, "resume_at", None)
        watermark = ctx.state(self).get("sink_watermark")
        if resume is not None and watermark is not None:
            resume(watermark)


class ExportNode(Node):
    """Cross-graph table export (reference ``ExportedTable``:
    ``src/engine/dataflow/export.rs``, ``src/engine/graph.rs:630``): a
    thread-safe update log with a closed-epoch frontier, offset reads, and
    replay-then-live subscriptions.  Another graph imports it through
    ``internals.interactive.import_table`` and continues from the stream."""

    def __init__(self, graph: EngineGraph, input: Node, name: str = "export"):
        import threading

        super().__init__(graph, [input], name)
        self._lock = threading.Lock()
        self._log: list[tuple[int, Pointer, tuple, int]] = []
        self._frontier = -1
        self._closed = False
        self._subs: list[Callable] = []

    def exchange_routes(self):
        return [cl.route_to_zero]

    def process(self, ctx, time, inbatches):
        batch = [(time, u.key, u.values, u.diff) for u in inbatches[0]]
        # callbacks run UNDER the lock so delivery order matches log order
        # and subscribe()'s replay-then-live handoff has no gap; callbacks
        # must not call back into this export (they'd deadlock)
        with self._lock:
            self._log.extend(batch)
            self._frontier = time
            for cb in self._subs:
                cb(batch, time)
        return []

    def on_end(self, ctx):
        with self._lock:
            self._closed = True

    # --- reader side (any thread) ------------------------------------
    def frontier(self) -> int:
        """Last closed epoch exported so far (reference
        ``ExportedTable::frontier``)."""
        with self._lock:
            return self._frontier

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def data_from_offset(
        self, offset: int
    ) -> tuple[list[tuple[int, Pointer, tuple, int]], int, int, bool]:
        """Updates from ``offset`` on: (batch, next_offset, frontier,
        closed) — reference ``ExportedTable::data_from_offset``."""
        with self._lock:
            batch = self._log[offset:]
            return batch, len(self._log), self._frontier, self._closed

    def subscribe(self, cb: Callable, replay: bool = True) -> None:
        """``cb(batch, frontier)``; with ``replay`` the full history is
        delivered first, atomically with registration (the history call
        and all live deliveries happen under one lock, so no epoch can
        slip between or around them)."""
        with self._lock:
            if replay and self._log:
                cb(list(self._log), self._frontier)
            self._subs.append(cb)


class CaptureNode(Node):
    """Collects the final table state + full update stream (test/debug
    support — reference captured-stream test utilities)."""

    def __init__(self, graph: EngineGraph, input: Node, name: str = "capture"):
        super().__init__(graph, [input], name)

    def exchange_routes(self):
        return [cl.route_to_zero]

    def make_state(self):
        return {"rows": {}, "stream": []}

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        if inbatches[0]:
            _record_sink_latency(ctx)
        native = _native.load()
        if native is not None:
            native.capture_batch(st["stream"], st["rows"], inbatches[0], time)
            return []
        for u in inbatches[0]:
            st["stream"].append((u.key, u.values, time, u.diff))
            if u.diff > 0:
                st["rows"][u.key] = u.values
            else:
                st["rows"].pop(u.key, None)
        return []
