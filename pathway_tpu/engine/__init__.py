"""Engine: epoch-synchronous incremental dataflow (host plane).

TPU-build equivalent of the reference Rust engine (``src/engine/``): update
streams, operator nodes, scheduler, reducers.  The numeric plane (embedders,
KNN, rerankers) lives in ``pathway_tpu.ops`` / ``pathway_tpu.models`` and is
fed micro-batches by this engine.
"""

from pathway_tpu.engine.graph import EngineGraph, Node, RunContext
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.engine.stream import Batch, Update

__all__ = ["EngineGraph", "Node", "RunContext", "Scheduler", "Batch", "Update"]
