"""Retraction-aware reducer implementations.

Capability parity with reference ``src/engine/reduce.rs`` (count, sums,
min/max, argmin/argmax, unique, any, sorted_tuple, tuple, earliest/latest,
stateful Python reducers).  Each reducer maintains an accumulator that
supports ``add``/``remove`` with multiplicities; non-invertible reducers
(min/max/unique/...) keep a multiset counter and recompute on extract — the
group sizes seen in streaming ETL make O(distinct) extraction acceptable, and
only dirty groups are re-extracted per epoch.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.engine.stream import hashable


class ReducerImpl:
    """One reducer instance bound to its argument extractors."""

    name = "reducer"
    # how many expression arguments the reducer consumes
    n_args = 1
    #: native partial-aggregation code (native/pathway_native.cpp
    #: groupby_partials): 0 = count, 1 = sum-like, 2 = multiset,
    #: None = no native fast path for this reducer
    native_code: int | None = None

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.ANY

    def make_acc(self) -> Any:
        raise NotImplementedError

    def update(self, acc: Any, args: tuple, diff: int) -> None:
        raise NotImplementedError

    def merge_partial(self, acc: Any, partial: Any) -> None:
        """Fold one native partial (see ``native_code``) into ``acc``."""
        raise NotImplementedError

    def extract(self, acc: Any) -> Any:
        raise NotImplementedError


class CountReducer(ReducerImpl):
    name = "count"
    n_args = 0
    native_code = 0

    def return_dtype(self, arg_dtypes):
        return dt.INT

    def make_acc(self):
        return [0]

    def update(self, acc, args, diff):
        acc[0] += diff

    def merge_partial(self, acc, partial):
        acc[0] += partial

    def extract(self, acc):
        return acc[0]


class SumReducer(ReducerImpl):
    name = "sum"
    native_code = 1

    def return_dtype(self, arg_dtypes):
        return arg_dtypes[0] if arg_dtypes else dt.ANY

    def make_acc(self):
        return [None, 0]  # total, count

    def update(self, acc, args, diff):
        v = args[0]
        if v is None or v is api.ERROR:
            return
        if acc[0] is None:
            acc[0] = v * diff if not isinstance(v, np.ndarray) else v * diff
        else:
            acc[0] = acc[0] + v * diff
        acc[1] += diff

    def merge_partial(self, acc, partial):
        total, cnt = partial
        if total is None:
            return
        acc[0] = total if acc[0] is None else acc[0] + total
        acc[1] += cnt

    def extract(self, acc):
        if acc[1] == 0 and not isinstance(acc[0], np.ndarray):
            return 0 if acc[0] is None else type(acc[0])(0) if isinstance(acc[0], (int, float)) else acc[0]
        return acc[0]


class AvgReducer(ReducerImpl):
    name = "avg"
    native_code = 1

    def return_dtype(self, arg_dtypes):
        return dt.FLOAT

    def make_acc(self):
        return [0.0, 0]

    def update(self, acc, args, diff):
        v = args[0]
        if v is None or v is api.ERROR:
            return
        acc[0] += v * diff
        acc[1] += diff

    def merge_partial(self, acc, partial):
        total, cnt = partial
        if total is None:
            return
        acc[0] += total
        acc[1] += cnt

    def extract(self, acc):
        return acc[0] / acc[1] if acc[1] else None


class _MultisetReducer(ReducerImpl):
    """Base for non-invertible reducers: keeps Counter of hashable args with
    original values remembered for extraction."""

    def make_acc(self):
        return {"counter": Counter(), "orig": {}}

    native_code = 2

    def update(self, acc, args, diff):
        # Signed accumulation: counts may go transiently negative (a
        # retraction arriving before its matching addition inside one
        # batch) and are clamped only at extract time via _items.  This
        # matches the native groupby_partials netting semantics — the
        # native path nets per-batch deltas before applying them, so
        # clamping per-event here would diverge on inconsistent streams.
        h = hashable(args)
        c = acc["counter"][h] + diff
        if c == 0:
            del acc["counter"][h]
            acc["orig"].pop(h, None)
        else:
            acc["counter"][h] = c
            acc["orig"].setdefault(h, args)

    def merge_partial(self, acc, partial):
        counter = acc["counter"]
        orig = acc["orig"]
        for h, (delta, args) in partial.items():
            c = counter[h] + delta
            if c == 0:
                del counter[h]
                orig.pop(h, None)
            else:
                counter[h] = c
                orig.setdefault(h, args)

    def _items(self, acc):
        # only positive multiplicities are visible; negatives are pending
        # retractions awaiting their additions
        return [(acc["orig"][h], c) for h, c in acc["counter"].items() if c > 0]


class MinReducer(_MultisetReducer):
    name = "min"

    def return_dtype(self, arg_dtypes):
        return arg_dtypes[0]

    def extract(self, acc):
        vals = [v[0] for v, _ in self._items(acc) if v[0] is not None]
        return min(vals) if vals else None


class MaxReducer(MinReducer):
    name = "max"

    def extract(self, acc):
        vals = [v[0] for v, _ in self._items(acc) if v[0] is not None]
        return max(vals) if vals else None


class ArgMinReducer(_MultisetReducer):
    """args = (value, key_pointer)."""

    name = "argmin"
    n_args = 2

    def return_dtype(self, arg_dtypes):
        return dt.POINTER

    def _pick(self, acc, fn):
        items = [v for v, _ in self._items(acc) if v[0] is not None]
        if not items:
            return None
        best = fn(items, key=lambda p: (p[0], p[1]))
        return best[1]

    def extract(self, acc):
        return self._pick(acc, min)


class ArgMaxReducer(ArgMinReducer):
    name = "argmax"

    def extract(self, acc):
        return self._pick(acc, max)


class UniqueReducer(_MultisetReducer):
    name = "unique"

    def return_dtype(self, arg_dtypes):
        return arg_dtypes[0]

    def extract(self, acc):
        items = self._items(acc)
        distinct = {hashable(v[0]) for v, _ in items}
        if len(distinct) > 1:
            return api.ERROR
        return items[0][0][0] if items else None


class AnyReducer(_MultisetReducer):
    name = "any"

    def return_dtype(self, arg_dtypes):
        return arg_dtypes[0]

    def extract(self, acc):
        items = self._items(acc)
        if not items:
            return None
        return min(items, key=lambda it: repr(hashable(it[0])))[0][0]


class SortedTupleReducer(_MultisetReducer):
    name = "sorted_tuple"

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def return_dtype(self, arg_dtypes):
        return dt.List(arg_dtypes[0] if arg_dtypes else dt.ANY)

    def extract(self, acc):
        out = []
        for v, c in self._items(acc):
            if self.skip_nones and v[0] is None:
                continue
            out.extend([v[0]] * c)
        return tuple(sorted(out, key=lambda x: (x is None, x)))


class TupleReducer(ReducerImpl):
    """Collects values; ordered by insertion sequence (stable across
    retraction of any copy)."""

    name = "tuple"

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def return_dtype(self, arg_dtypes):
        return dt.List(arg_dtypes[0] if arg_dtypes else dt.ANY)

    def make_acc(self):
        return {"seq": 0, "items": {}}  # seq_id -> value ; plus index by hash

    def update(self, acc, args, diff):
        v = args[0]
        if diff > 0:
            for _ in range(diff):
                acc["items"][acc["seq"]] = v
                acc["seq"] += 1
        else:
            h = hashable(v)
            to_remove = -diff
            for sid in sorted(acc["items"], reverse=True):
                if to_remove == 0:
                    break
                if hashable(acc["items"][sid]) == h:
                    del acc["items"][sid]
                    to_remove -= 1

    def extract(self, acc):
        vals = [acc["items"][sid] for sid in sorted(acc["items"])]
        if self.skip_nones:
            vals = [v for v in vals if v is not None]
        return tuple(vals)


class EarliestReducer(ReducerImpl):
    name = "earliest"

    def return_dtype(self, arg_dtypes):
        return arg_dtypes[0]

    def make_acc(self):
        return TupleReducer().make_acc()

    def update(self, acc, args, diff):
        TupleReducer().update(acc, args, diff)

    def extract(self, acc):
        if not acc["items"]:
            return None
        return acc["items"][min(acc["items"])]


class LatestReducer(EarliestReducer):
    name = "latest"

    def extract(self, acc):
        if not acc["items"]:
            return None
        return acc["items"][max(acc["items"])]


class NdarrayReducer(ReducerImpl):
    name = "ndarray"

    def return_dtype(self, arg_dtypes):
        return dt.ANY_ARRAY

    def make_acc(self):
        return TupleReducer().make_acc()

    def update(self, acc, args, diff):
        TupleReducer().update(acc, args, diff)

    def extract(self, acc):
        vals = [acc["items"][sid] for sid in sorted(acc["items"])]
        return np.array(vals)


class NpSumReducer(ReducerImpl):
    name = "npsum"

    def return_dtype(self, arg_dtypes):
        return dt.ANY_ARRAY

    def make_acc(self):
        return [None, 0]

    def update(self, acc, args, diff):
        v = args[0]
        if v is None or v is api.ERROR:
            # defense in depth: GroupByNode poisons error args before
            # update(), but a direct caller must not crash on the sentinel
            return
        v = np.asarray(v)
        acc[0] = v * diff if acc[0] is None else acc[0] + v * diff
        acc[1] += diff

    def extract(self, acc):
        return acc[0]


class StatefulReducer(ReducerImpl):
    """Python custom reducer (reference ``stateful_many``/
    ``BaseCustomAccumulator``, ``internals/custom_reducers.py``).  Keeps the
    multiset of rows; folds the user accumulator on extraction, using
    ``retract`` only when available — otherwise replays from scratch."""

    name = "stateful"
    native_code = 2

    def __init__(self, fold: Callable[[list[tuple]], Any], n_args: int = 1):
        self.fold = fold
        self.n_args = n_args
        self._ms = _MultisetReducer()

    def return_dtype(self, arg_dtypes):
        return dt.ANY

    def make_acc(self):
        return self._ms.make_acc()

    def update(self, acc, args, diff):
        self._ms.update(acc, args, diff)

    def merge_partial(self, acc, partial):
        self._ms.merge_partial(acc, partial)

    def extract(self, acc):
        rows: list[tuple] = []
        for v, c in self._ms._items(acc):
            rows.extend([v] * c)
        return self.fold(rows)


class _AppendOnlyExtreme(ReducerImpl):
    """O(1) running-extreme accumulator for inputs the analyzer proved
    append-only (``graph_facts.append_only``): no retraction can ever
    arrive, so the multiset bookkeeping of :class:`_MultisetReducer`
    is dead weight.  Negative diffs are ignored — the optimizer only
    installs these when the proof holds, and the proof is the contract.

    ``native_code`` stays 2: the native partial format (``{h: (delta,
    args)}``) is folded directly, so a swapped reducer keeps the
    groupby's ``fast_spec`` valid.
    """

    native_code = 2

    def _better(self, a: Any, b: Any) -> bool:
        raise NotImplementedError

    def return_dtype(self, arg_dtypes):
        return arg_dtypes[0] if arg_dtypes else dt.ANY

    def make_acc(self):
        return [None]

    def update(self, acc, args, diff):
        if diff <= 0:
            return
        v = args[0]
        if v is None or v is api.ERROR:
            return
        if acc[0] is None or self._better(v, acc[0]):
            acc[0] = v

    def merge_partial(self, acc, partial):
        for _, (delta, args) in partial.items():
            if delta <= 0:
                continue
            v = args[0]
            if v is None or v is api.ERROR:
                continue
            if acc[0] is None or self._better(v, acc[0]):
                acc[0] = v

    def extract(self, acc):
        return acc[0]


class AppendOnlyMinReducer(_AppendOnlyExtreme):
    name = "min"

    def _better(self, a, b):
        return a < b


class AppendOnlyMaxReducer(_AppendOnlyExtreme):
    name = "max"

    def _better(self, a, b):
        return a > b


class _AppendOnlyArgExtreme(_AppendOnlyExtreme):
    """Append-only argmin/argmax: acc holds the best ``(value, key)``
    pair; comparison is lexicographic, matching ``ArgMinReducer._pick``'s
    ``key=lambda p: (p[0], p[1])`` tie-breaking exactly."""

    n_args = 2

    def return_dtype(self, arg_dtypes):
        return dt.POINTER

    def update(self, acc, args, diff):
        if diff <= 0 or args[0] is None or args[0] is api.ERROR:
            return
        pair = (args[0], args[1])
        if acc[0] is None or self._better(pair, acc[0]):
            acc[0] = pair

    def merge_partial(self, acc, partial):
        for _, (delta, args) in partial.items():
            if delta <= 0 or args[0] is None or args[0] is api.ERROR:
                continue
            pair = (args[0], args[1])
            if acc[0] is None or self._better(pair, acc[0]):
                acc[0] = pair

    def extract(self, acc):
        return None if acc[0] is None else acc[0][1]


class AppendOnlyArgMinReducer(_AppendOnlyArgExtreme):
    name = "argmin"

    def _better(self, a, b):
        return a < b


class AppendOnlyArgMaxReducer(_AppendOnlyArgExtreme):
    name = "argmax"

    def _better(self, a, b):
        return a > b


#: exact-type table: MaxReducer subclasses MinReducer, so lookup must be
#: by ``type(impl)``, never isinstance.  Deliberately absent: Unique
#: (needs the distinct count), Any (its pick is defined over the *current*
#: multiset ordering), the tuple family (extraction needs all elements).
_APPEND_ONLY_VARIANTS: dict[type, Callable[[], ReducerImpl]] = {
    MinReducer: AppendOnlyMinReducer,
    MaxReducer: AppendOnlyMaxReducer,
    ArgMinReducer: AppendOnlyArgMinReducer,
    ArgMaxReducer: AppendOnlyArgMaxReducer,
}


def append_only_variant(impl: ReducerImpl) -> "ReducerImpl | None":
    """Non-retracting drop-in for ``impl``, or None when the reducer has
    no append-only specialization (or is already one)."""
    cls = _APPEND_ONLY_VARIANTS.get(type(impl))
    return cls() if cls is not None else None


def make_reducer(name: str, **kwargs: Any) -> ReducerImpl:
    table: dict[str, Callable[[], ReducerImpl]] = {
        "count": CountReducer,
        "sum": SumReducer,
        "avg": AvgReducer,
        "min": MinReducer,
        "max": MaxReducer,
        "argmin": ArgMinReducer,
        "argmax": ArgMaxReducer,
        "unique": UniqueReducer,
        "any": AnyReducer,
        "earliest": EarliestReducer,
        "latest": LatestReducer,
        "ndarray": NdarrayReducer,
        "npsum": NpSumReducer,
    }
    if name == "sorted_tuple":
        return SortedTupleReducer(skip_nones=kwargs.get("skip_nones", False))
    if name == "tuple":
        return TupleReducer(skip_nones=kwargs.get("skip_nones", False))
    return table[name]()
