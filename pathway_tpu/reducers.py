"""``pw.reducers`` — the user-facing reducer registry.

Capability parity with reference ``python/pathway/reducers.py:28-46`` +
``internals/custom_reducers.py``: any, argmax, argmin, avg, count, earliest,
int_sum, latest, max, min, ndarray, npsum, sorted_tuple, sum, tuple, unique,
plus ``udf_reducer`` / ``stateful_single`` / ``stateful_many`` custom
reducers.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import reducers as engine_reducers
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, ReducerExpression, _wrap


class Reducer:
    def __init__(self, name: str, n_args: int = 1):
        self.name = name
        self.n_args = n_args

    def __call__(self, *args: Any, **kwargs: Any) -> ReducerExpression:
        return ReducerExpression(self, *[_wrap(a) for a in args], **kwargs)

    def make_impl(self, **kwargs: Any) -> engine_reducers.ReducerImpl:
        return engine_reducers.make_reducer(self.name, **kwargs)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return self.make_impl().return_dtype(arg_dtypes)

    def __repr__(self) -> str:
        return f"pw.reducers.{self.name}"


count = Reducer("count", n_args=0)
sum = Reducer("sum")
int_sum = Reducer("sum")
npsum = Reducer("npsum")
ndarray = Reducer("ndarray")
avg = Reducer("avg")
min = Reducer("min")
max = Reducer("max")
argmin = Reducer("argmin")
argmax = Reducer("argmax")
unique = Reducer("unique")
any = Reducer("any")
earliest = Reducer("earliest")
latest = Reducer("latest")
sorted_tuple = Reducer("sorted_tuple")
tuple = Reducer("tuple")


class _StatefulReducer(Reducer):
    def __init__(self, fold: Callable[[list], Any], name: str = "stateful"):
        super().__init__(name)
        self._fold = fold

    def make_impl(self, **kwargs: Any) -> engine_reducers.ReducerImpl:
        return engine_reducers.StatefulReducer(self._fold)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.ANY


def stateful_many(combine_many: Callable) -> Reducer:
    """Custom reducer from a combine function over the full multiset of rows
    (reference ``pw.reducers.stateful_many``).  ``combine_many(state, rows)``
    is replayed from ``state=None`` on each extraction — correct under
    retraction without requiring invertibility."""

    def fold(rows: list[Any]) -> Any:
        return combine_many(None, [(r, 1) for r in rows])

    return _StatefulReducer(fold, name="stateful_many")


def stateful_single(combine_single: Callable) -> Reducer:
    def fold(rows: list[Any]) -> Any:
        state = None
        for r in rows:
            state = combine_single(state, *r)
        return state

    return _StatefulReducer(fold, name="stateful_single")


class BaseCustomAccumulator:
    """Reference ``internals/custom_reducers.py`` ``BaseCustomAccumulator``:
    subclass with ``from_row``, ``update``, optional ``retract``, and
    ``compute_result``."""

    @classmethod
    def from_row(cls, row: list[Any]) -> "BaseCustomAccumulator":
        raise NotImplementedError

    def update(self, other: "BaseCustomAccumulator") -> None:
        raise NotImplementedError

    def compute_result(self) -> Any:
        raise NotImplementedError


def udf_reducer(accumulator: type[BaseCustomAccumulator]) -> Reducer:
    def fold(rows: list[Any]) -> Any:
        acc = None
        for r in rows:
            nxt = accumulator.from_row(list(r))
            if acc is None:
                acc = nxt
            else:
                acc.update(nxt)
        return acc.compute_result() if acc is not None else None

    return _StatefulReducer(fold, name=f"udf_reducer_{accumulator.__name__}")
