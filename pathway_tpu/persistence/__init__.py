"""``pw.persistence`` — checkpoint / resume / record / replay.

Capability parity with the reference persistence layer
(``src/persistence/``: input snapshots ``input_snapshot.rs:32-218``,
tracker ``tracker.rs:26-63``, backends ``backends/``; Python API
``python/pathway/persistence/__init__.py:13-165``).  Mechanism is
re-designed for the epoch-synchronous engine:

- **input snapshots**: every connector event (add/remove/commit) is
  appended to the backend per input node; on restart the log is replayed
  as the first epochs (same consistency: rewind to the last committed
  frontier, reference ``Connector::rewind_from_disk_snapshot``),
  and cooperative readers skip the already-delivered prefix via
  ``events.resume_offset``.
- **UDF caching**: ``DiskCache`` keys results under the same backend
  (reference ``PersistenceMode::UdfCaching``).
- **record/replay modes**: ``RealtimeReplay``/``SpeedrunReplay`` replay
  the log INSTEAD of reading live sources (reference
  ``src/connectors/mod.rs:108-116``).
"""

from __future__ import annotations

import enum
import json
import logging
import os
import pickle
import threading
import time as _time
from typing import Any

from pathway_tpu.internals import native as _native_mod

__all__ = [
    "Backend",
    "CachedObjectStorage",
    "Config",
    "PersistenceMode",
    "attach_persistence",
]

_logger = logging.getLogger("pathway_tpu.persistence")


class PersistenceMode(enum.Enum):
    BATCH = "batch"
    PERSISTING = "persisting"
    SELECTIVE_PERSISTING = "selective_persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    UDF_CACHING = "udf_caching"
    REALTIME_REPLAY = "realtime_replay"
    SPEEDRUN_REPLAY = "speedrun_replay"


class _BackendImpl:
    def append(self, stream: str, record: bytes, durable: bool = True) -> None:
        """Append one record.  ``durable=False`` lets the backend defer
        physical sync: commits are the durability points of the log —
        replay trusts only the committed prefix (snapshot consumed-counts
        are always within it), so data records between commits may ride
        the OS page cache.  Backends without a sync concept ignore it."""
        raise NotImplementedError

    def read_all(self, stream: str) -> list[bytes]:
        raise NotImplementedError

    def truncate(self, stream: str, n_records: int) -> None:
        """Drop every record after the first ``n_records`` (rewind the log
        to the committed frontier, reference
        ``Connector::rewind_from_disk_snapshot``)."""
        raise NotImplementedError

    def put_blob(self, name: str, data: bytes) -> None:
        """Atomically store a named blob (operator snapshots)."""
        raise NotImplementedError

    def get_blob(self, name: str) -> bytes | None:
        raise NotImplementedError

    def put_meta(self, data: dict) -> None:
        raise NotImplementedError

    def get_meta(self) -> dict:
        raise NotImplementedError


class _MemoryBackend(_BackendImpl):
    _stores: dict[str, dict] = {}

    def __init__(self, namespace: str = "default"):
        store = self._stores.setdefault(
            namespace, {"streams": {}, "meta": {}, "blobs": {}}
        )
        self._streams = store["streams"]
        self._meta = store["meta"]
        self._blobs = store.setdefault("blobs", {})
        self._lock = threading.Lock()

    def append(self, stream, record, durable=True):
        with self._lock:
            self._streams.setdefault(stream, []).append(record)

    def read_all(self, stream):
        return list(self._streams.get(stream, []))

    def truncate(self, stream, n_records):
        with self._lock:
            records = self._streams.get(stream)
            if records is not None and len(records) > n_records:
                del records[n_records:]

    def put_blob(self, name, data):
        with self._lock:
            self._blobs[name] = data

    def get_blob(self, name):
        return self._blobs.get(name)

    def put_meta(self, data):
        self._meta.clear()
        self._meta.update(data)

    def get_meta(self):
        return dict(self._meta)


class _FsBackend(_BackendImpl):
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        #: per-stream end offset of each complete record, filled by the
        #: read_all scan so truncate() need not rescan multi-GB logs
        self._offsets: dict[str, list[int]] = {}
        #: cached append handles — an open()+fsync per record would bound
        #: ingest throughput (measured ~30% of the wordcount benchmark)
        self._handles: dict[str, Any] = {}

    def _stream_path(self, stream: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in stream)
        return os.path.join(self.path, f"{safe}.log")

    def _handle(self, stream: str):
        f = self._handles.get(stream)
        if f is None or f.closed:
            f = open(self._stream_path(stream), "ab")
            self._handles[stream] = f
        return f

    def _drop_handle(self, stream: str) -> None:
        f = self._handles.pop(stream, None)
        if f is not None and not f.closed:
            f.close()

    def append(self, stream, record, durable=True):
        with self._lock:
            self._offsets.pop(stream, None)  # offset cache is now stale
            f = self._handle(stream)
            f.write(len(record).to_bytes(8, "little"))
            f.write(record)
            f.flush()  # always reaches the OS page cache
            if durable:  # commits/snapshots survive power loss
                os.fsync(f.fileno())

    def read_all(self, stream):
        path = self._stream_path(stream)
        with self._lock:
            f_open = self._handles.get(stream)
            if f_open is not None and not f_open.closed:
                f_open.flush()
        if not os.path.exists(path):
            return []
        out = []
        offsets = []
        with self._lock:  # keeps the offset cache consistent vs append
            with open(path, "rb") as f:
                while True:
                    header = f.read(8)
                    if len(header) < 8:
                        break
                    n = int.from_bytes(header, "little")
                    payload = f.read(n)
                    if len(payload) < n:
                        break  # torn tail write: rewind to last complete record
                    out.append(payload)
                    offsets.append(f.tell())
            self._offsets[stream] = offsets
        return out

    def truncate(self, stream, n_records):
        path = self._stream_path(stream)
        if not os.path.exists(path):
            return
        with self._lock:
            self._drop_handle(stream)  # the append handle's position is stale
            offsets = self._offsets.get(stream)
            if offsets is None:  # no prior scan: find record boundaries now
                keep = 0
                count = 0
                with open(path, "rb") as f:
                    while count < n_records:
                        header = f.read(8)
                        if len(header) < 8:
                            break
                        n = int.from_bytes(header, "little")
                        payload = f.read(n)
                        if len(payload) < n:
                            break
                        keep = f.tell()
                        count += 1
            else:
                # clamp to end-of-log: a caller asking to keep more
                # records than the scanned log holds (e.g. a commit count
                # from a newer snapshot against an older log) keeps
                # everything instead of IndexError-ing
                if n_records <= 0:
                    keep = 0
                elif n_records >= len(offsets):
                    keep = offsets[-1] if offsets else 0
                else:
                    keep = offsets[n_records - 1]
                del offsets[n_records:]
            with open(path, "r+b") as f:
                f.truncate(keep)
                f.flush()
                os.fsync(f.fileno())

    def put_blob(self, name, data):
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
        tmp = os.path.join(self.path, f"{safe}.blob.tmp")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, f"{safe}.blob"))

    def get_blob(self, name):
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
        path = os.path.join(self.path, f"{safe}.blob")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def put_meta(self, data):
        # unique tmp per writer: check_topology runs on EVERY worker, so
        # first-run meta writes race across threads AND processes — a
        # shared tmp path would let one writer os.replace a peer's
        # half-written file (or find its own renamed away)
        tmp = os.path.join(
            self.path,
            f"metadata.json.tmp.{os.getpid()}.{threading.get_ident()}",
        )
        with self._lock:
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, os.path.join(self.path, "metadata.json"))

    def get_meta(self):
        path = os.path.join(self.path, "metadata.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)


class _S3Backend(_BackendImpl):
    """Persistence over an S3-compatible object store (reference
    ``src/persistence/backends/s3.rs``).  S3 has no append: every
    ``append`` writes one immutable object under
    ``{root}/streams/{stream}/{counter:012d}`` — the chunked "addmany"
    log records keep that to ~one PUT per ingest chunk.  The client is
    injectable (boto3-compatible: put/get/list/delete_object), the same
    pattern as ``pw.io.s3``."""

    def __init__(self, root: str, settings: Any):
        self.root = root.strip("/")
        self.settings = settings
        self._client = settings.create_client()
        self._bucket = settings.bucket_name
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}

    # -- low-level ------------------------------------------------------
    def _key(self, *parts: str) -> str:
        return "/".join([self.root, *parts])

    def _put(self, key: str, data: bytes) -> None:
        self._client.put_object(Bucket=self._bucket, Key=key, Body=data)

    def _get(self, key: str) -> bytes | None:
        try:
            body = self._client.get_object(Bucket=self._bucket, Key=key)["Body"]
        except Exception:
            return None
        return body.read() if hasattr(body, "read") else bytes(body)

    def _list(self, prefix: str) -> list[str]:
        keys: list[str] = []
        token = None
        while True:
            kwargs = {"Bucket": self._bucket, "Prefix": prefix}
            if token:
                kwargs["ContinuationToken"] = token
            resp = self._client.list_objects_v2(**kwargs)
            keys.extend(o["Key"] for o in resp.get("Contents", []))
            if not resp.get("IsTruncated"):
                return sorted(keys)
            token = resp.get("NextContinuationToken")

    # -- streams --------------------------------------------------------
    def _stream_keys(self, stream: str) -> list[str]:
        return self._list(self._key("streams", stream) + "/")

    def append(self, stream, record, durable=True):
        # S3 puts are atomic and durable on success; the flag is moot
        with self._lock:
            n = self._counters.get(stream)
            if n is None:
                n = len(self._stream_keys(stream))
            self._put(self._key("streams", stream, f"{n:012d}"), record)
            self._counters[stream] = n + 1

    def read_all(self, stream):
        keys = self._stream_keys(stream)
        with self._lock:
            self._counters[stream] = len(keys)
        out = []
        for k in keys:
            data = self._get(k)
            if data is not None:
                out.append(data)
        return out

    def truncate(self, stream, n_records):
        keys = self._stream_keys(stream)
        with self._lock:
            for k in keys[n_records:]:
                self._client.delete_object(Bucket=self._bucket, Key=k)
            self._counters[stream] = min(n_records, len(keys))

    # -- blobs / meta ---------------------------------------------------
    def put_blob(self, name, data):
        self._put(self._key("blobs", name), data)

    def get_blob(self, name):
        return self._get(self._key("blobs", name))

    def put_meta(self, data):
        self._put(self._key("metadata.json"), json.dumps(data).encode())

    def get_meta(self):
        raw = self._get(self._key("metadata.json"))
        return json.loads(raw) if raw else {}


class Backend:
    """reference ``pw.persistence.Backend`` factory methods."""

    def __init__(self, impl: _BackendImpl, kind: str):
        self._impl = impl
        self.kind = kind

    @classmethod
    def filesystem(cls, path: str | os.PathLike) -> "Backend":
        return cls(_FsBackend(os.fspath(path)), "filesystem")

    @classmethod
    def memory(cls, namespace: str = "default") -> "Backend":
        return cls(_MemoryBackend(namespace), "memory")

    mock = memory

    @classmethod
    def s3(cls, root_path: str, bucket_settings: Any = None) -> "Backend":
        """Snapshots in an S3 bucket (reference ``Backend::s3``,
        ``python/pathway/persistence/__init__.py`` over
        ``src/persistence/backends/s3.rs``).  ``bucket_settings`` is a
        ``pw.io.s3.AwsS3Settings``; pass ``client=`` there to inject a
        boto3-compatible client (tests use a fake)."""
        if bucket_settings is None:
            raise ValueError(
                "Backend.s3 requires bucket_settings (pw.io.s3.AwsS3Settings)"
            )
        return cls(_S3Backend(root_path, bucket_settings), "s3")

    azure = s3


class CachedObjectStorage:
    """Versioned blob cache for connector-downloaded objects (reference
    ``src/persistence/cached_object_storage.rs:1-377``): a connector that
    downloads remote objects (S3 blobs, parsed documents) stores them
    here keyed by (uri, version); after a restart — or when the remote
    charges per GET — an unchanged version is served from the cache.
    Backed by any persistence backend (fs/memory/S3)."""

    _INDEX = "__object_cache_index__"

    def __init__(self, backend: Backend):
        self.impl = backend._impl
        raw = self.impl.get_blob(self._INDEX)
        self._index: dict[str, dict] = json.loads(raw) if raw else {}
        # callers include the S3 source's 8-thread downloader pool — the
        # index mutation + serialization must be atomic
        self._lock = threading.Lock()

    def _blob_name(self, uri: str) -> str:
        import hashlib

        return "objcache_" + hashlib.blake2b(uri.encode(), digest_size=16).hexdigest()

    def contains(self, uri: str, version: str) -> bool:
        with self._lock:
            entry = self._index.get(uri)
            return entry is not None and entry.get("version") == str(version)

    def get(self, uri: str, version: str) -> bytes | None:
        if not self.contains(uri, version):
            return None
        return self.impl.get_blob(self._blob_name(uri))

    def put(self, uri: str, version: str, data: bytes) -> None:
        self.impl.put_blob(self._blob_name(uri), data)
        with self._lock:
            self._index[uri] = {"version": str(version), "size": len(data)}
            self._flush_index()

    def invalidate(self, uri: str) -> None:
        with self._lock:
            if self._index.pop(uri, None) is not None:
                self._flush_index()

    def uris(self) -> list[str]:
        with self._lock:
            return sorted(self._index)

    def _flush_index(self) -> None:
        """Caller holds ``self._lock``."""
        self.impl.put_blob(self._INDEX, json.dumps(self._index).encode())


class Config:
    """reference ``pw.persistence.Config``."""

    def __init__(
        self,
        backend: Backend,
        *,
        snapshot_interval_ms: int = 0,
        checkpoint_interval: float | None = None,
        persistence_mode: PersistenceMode = PersistenceMode.PERSISTING,
        continue_after_replay: bool = True,
        replay_speedup: float = 1.0,
    ):
        self.backend = backend
        self.snapshot_interval_ms = snapshot_interval_ms
        #: coordinated-checkpoint period in SECONDS (the cluster-facing
        #: knob; ``snapshot_interval_ms`` is the legacy ms spelling).  Env
        #: ``PATHWAY_CHECKPOINT_INTERVAL`` overrides either.
        self.checkpoint_interval = checkpoint_interval
        self.persistence_mode = persistence_mode
        self.continue_after_replay = continue_after_replay
        #: REALTIME_REPLAY speed factor: recorded inter-commit gaps are
        #: divided by this before sleeping (2.0 = replay twice as fast;
        #: <= 0 = no gap sleeps).  Env PATHWAY_REPLAY_SPEEDUP overrides.
        self.replay_speedup = replay_speedup

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs: Any) -> "Config":
        return cls(backend, **kwargs)


# ---------------------------------------------------------------------------
# engine attachment


class _RecordingEvents:
    """Wraps ConnectorEvents: drops the first ``resume_offset`` data
    events (the reader re-produces what the snapshot already replayed —
    deterministic readers re-emit in the same order) and records every
    NEW event to the snapshot log."""

    def __init__(self, inner: Any, impl: _BackendImpl, stream: str, resume_offset: int):
        self._inner = inner
        self._impl = impl
        self._stream = stream
        self.resume_offset = resume_offset
        self._dirty = False  # data recorded since the last logged commit

    @property
    def stopped(self) -> bool:
        return self._inner.stopped

    def _record_and_forward(self, kind: str, key, values, forward) -> None:
        if self.resume_offset > 0:
            self.resume_offset -= 1
            return
        # keys log as plain ints: pickling the Pointer int-subclass goes
        # through per-object copyreg and is ~2.4x slower; replay rewraps
        # durable=False: commits are the log's durability points (replay
        # trusts only the committed prefix), so data records may defer sync
        self._impl.append(
            self._stream, pickle.dumps((kind, int(key), values)), durable=False
        )
        self._dirty = True
        forward(key, values)

    def add(self, key, values):
        self._record_and_forward("add", key, values, self._inner.add)

    def add_many(self, rows):
        """Chunked ingest: skip the replayed prefix, log the surviving
        chunk as ONE "addmany" record (one pickle per chunk, not per row —
        the log write must not bound ingest throughput), then forward."""
        skip = min(self.resume_offset, len(rows))
        if skip:
            self.resume_offset -= skip
            rows = rows[skip:]
        if not rows:
            return
        blob = None
        native = _native_mod.load()
        if native is not None:
            try:
                # one C pass over the chunk (tagged binary frame) instead
                # of a per-row int()/tuple listcomp + pickle — the log
                # write must not bound ingest throughput
                blob = pickle.dumps(("addmany_b", native.pack_kv(rows), None))
            except Exception:
                blob = None
        if blob is None:
            blob = pickle.dumps(
                ("addmany", [(int(k), v) for k, v in rows], None)
            )
        self._impl.append(self._stream, blob, durable=False)
        self._dirty = True
        self._inner.add_many(rows)

    def add_frame(self, cap):
        """Columnar ingest: skip the replayed prefix by frame_slice (keys
        stay lazy, pool shared), log the survivor as ONE "addframe"
        record holding the frame's wire encoding — replay expands it back
        to per-row events, so resume offsets stay row-accurate."""
        native = _native_mod.load()
        n = native.frame_len(cap)
        skip = min(self.resume_offset, n)
        if skip:
            self.resume_offset -= skip
            if skip == n:
                return
            cap = native.frame_slice(cap, skip, n)
        self._impl.append(
            self._stream,
            pickle.dumps(("addframe", native.frame_pack(cap, None), None)),
            durable=False,
        )
        self._dirty = True
        self._inner.add_frame(cap)

    def remove(self, key, values):
        self._record_and_forward("remove", key, values, self._inner.remove)

    def force_log_commit(self):
        """Commit the log WITHOUT cutting an engine epoch — called when an
        operator snapshot is taken, so every recorded event is committed
        and the snapshot's consumed counts always lie within the committed
        prefix (never past it)."""
        if self._dirty:
            from pathway_tpu.io import _connector as _conn

            self._impl.append(
                self._stream,
                pickle.dumps(
                    ("commit", _conn._autogen_counter.peek(), _time.time())
                ),
            )
            self._dirty = False

    def commit(self):
        if self.resume_offset > 0:
            return  # still skipping the replayed prefix: don't re-log commits
        if self._dirty:  # data-free commits would only grow the log
            # the commit record carries the autogen-counter high-water mark:
            # every key recorded before it embeds a smaller sequence number,
            # so resume can fast-forward the counter past all replayed keys
            from pathway_tpu.io import _connector as _conn

            self._impl.append(
                self._stream,
                pickle.dumps(
                    ("commit", _conn._autogen_counter.peek(), _time.time())
                ),
            )
            self._dirty = False
        self._inner.commit()

    def close(self):
        self._inner.close()


class PersistenceHooks:
    """Installed on the Scheduler by :func:`attach_persistence`."""

    def __init__(self, config: Config):
        self.config = config
        self.impl = config.backend._impl
        self.replay_only = config.persistence_mode in (
            PersistenceMode.REALTIME_REPLAY,
            PersistenceMode.SPEEDRUN_REPLAY,
        )
        #: replay honours recorded inter-commit wall-clock gaps
        #: (reference PersistenceMode::RealtimeReplay); SPEEDRUN replays
        #: as fast as possible
        self.realtime_replay = (
            config.persistence_mode == PersistenceMode.REALTIME_REPLAY
        )
        #: only sources with an explicit persistent_id are recorded
        #: (reference PersistenceMode::SelectivePersisting)
        self.selective = (
            config.persistence_mode == PersistenceMode.SELECTIVE_PERSISTING
        )
        #: persist compacted operator state so restart skips recomputation
        #: (reference src/persistence/operator_snapshot.rs:21-337)
        self.operator_mode = (
            config.persistence_mode == PersistenceMode.OPERATOR_PERSISTING
        )
        # -- async checkpoint writer (coordinated cluster checkpoints) --
        # Periodic snapshots pickle on the WORKER thread (the state must
        # be captured at the epoch boundary) but hit disk on this writer,
        # so the hot path never blocks on fsync.  The queue coalesces to
        # the latest snapshot per worker: under backpressure intermediate
        # checkpoints are superseded, never queued up.
        self._ckpt_cv = threading.Condition()
        self._ckpt_queue: dict[int, tuple[int, bytes, tuple]] = {}
        self._ckpt_inflight = 0
        self._ckpt_thread: threading.Thread | None = None
        self._ckpt_stats_lock = threading.Lock()
        #: last successfully persisted checkpoint (any worker of this
        #: process), for /status and /metrics
        self.checkpoint_stats: dict[str, Any] = {
            "epoch": None,
            "bytes": 0,
            "count": 0,
            "wall_at": None,
            "mono_at": None,
            # serialization cost on the WORKER thread (epoch boundary):
            # with external-index state riding the snapshot this is the
            # part of the checkpoint the hot path actually pays for
            "pickle_seconds": 0.0,
        }

    def persisted(self, node: Any) -> bool:
        """Whether this source participates in persistence at all."""
        if self.selective:
            return getattr(node, "persistent_id", None) is not None
        return True

    # -- operator snapshots -------------------------------------------
    def save_operator_snapshot(
        self,
        worker: int,
        epoch: int,
        consumed: dict[int, int],
        states: dict[int, Any],
    ) -> bool:
        """Persist ``{epoch, per-input consumed data-event counts, node
        states}`` for one worker.  Returns False (and disables nothing)
        when a state is unpicklable — recovery then falls back to full
        input replay for correctness."""
        t0 = _time.monotonic()
        try:
            blob = pickle.dumps(
                {"epoch": epoch, "consumed": dict(consumed), "states": states},
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        except Exception as e:  # unpicklable state (e.g. device buffers)
            _logger.warning(
                "operator snapshot skipped (unpicklable state): %r", e
            )
            return False
        pickle_s = _time.monotonic() - t0
        self.impl.put_blob(f"opsnap_w{worker}", blob)
        self._note_checkpoint(epoch, len(blob), pickle_s)
        return True

    def save_operator_snapshot_async(
        self,
        worker: int,
        epoch: int,
        consumed: dict[int, int],
        states: dict[int, Any],
        commit_fns: tuple = (),
    ) -> bool:
        """Asynchronous variant for periodic coordinated checkpoints:
        pickling happens here on the caller (state consistency at the
        epoch boundary), the durable writes happen on the writer thread.
        ``commit_fns`` are the inputs' ``force_log_commit`` closures; the
        writer runs them BEFORE the blob lands, so a visible snapshot's
        consumed counts always lie within the committed log prefix (any
        events the worker records after this enqueue are past the
        snapshot's counts — a later commit covering them is harmless).
        Returns False only when the state is unpicklable."""
        t0 = _time.monotonic()
        try:
            blob = pickle.dumps(
                {"epoch": epoch, "consumed": dict(consumed), "states": states},
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        except Exception as e:
            _logger.warning(
                "operator snapshot skipped (unpicklable state): %r", e
            )
            return False
        self._last_pickle_s = _time.monotonic() - t0
        with self._ckpt_cv:
            if self._ckpt_thread is None:
                self._ckpt_thread = threading.Thread(
                    target=self._ckpt_loop,
                    daemon=True,
                    name="pw-checkpoint-writer",
                )
                self._ckpt_thread.start()
            self._ckpt_queue[worker] = (epoch, blob, tuple(commit_fns))
            self._ckpt_cv.notify()
        return True

    def _ckpt_loop(self) -> None:
        while True:
            with self._ckpt_cv:
                while not self._ckpt_queue:
                    self._ckpt_cv.wait(1.0)
                worker = next(iter(self._ckpt_queue))
                epoch, blob, commit_fns = self._ckpt_queue.pop(worker)
                self._ckpt_inflight += 1
            try:
                for fn in commit_fns:  # log commits land before the blob
                    fn()
                self.impl.put_blob(f"opsnap_w{worker}", blob)
                self._note_checkpoint(
                    epoch, len(blob), getattr(self, "_last_pickle_s", 0.0)
                )
            except Exception as e:  # a failed checkpoint only delays recovery
                _logger.warning("async checkpoint failed: %r", e)
            finally:
                with self._ckpt_cv:
                    self._ckpt_inflight -= 1
                    self._ckpt_cv.notify_all()

    def flush_checkpoints(self, timeout: float = 10.0) -> bool:
        """Drain the async checkpoint queue (called before a final
        synchronous snapshot and at run teardown).  True iff everything
        queued has been persisted within ``timeout``."""
        deadline = _time.monotonic() + timeout
        with self._ckpt_cv:
            while self._ckpt_queue or self._ckpt_inflight:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._ckpt_cv.wait(min(remaining, 0.5))
        return True

    def _note_checkpoint(
        self, epoch: int, nbytes: int, pickle_s: float = 0.0
    ) -> None:
        with self._ckpt_stats_lock:
            st = self.checkpoint_stats
            st["epoch"] = epoch
            st["bytes"] = nbytes
            st["count"] += 1
            st["wall_at"] = _time.time()
            st["mono_at"] = _time.monotonic()
            st["pickle_seconds"] = round(pickle_s, 6)

    def checkpoint_snapshot(self) -> dict[str, Any]:
        """Monitoring view of the last checkpoint: epoch, size, count and
        age in seconds (None until the first checkpoint lands)."""
        with self._ckpt_stats_lock:
            st = dict(self.checkpoint_stats)
        mono_at = st.pop("mono_at")
        st["age_seconds"] = (
            round(_time.monotonic() - mono_at, 3) if mono_at is not None else None
        )
        return st

    def load_operator_snapshot(self, worker: int) -> dict | None:
        blob = self.impl.get_blob(f"opsnap_w{worker}")
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception as e:
            _logger.warning("operator snapshot unreadable, replaying: %r", e)
            return None

    def check_topology(self, n_workers: int) -> None:
        """Snapshot streams are per-worker; resuming under a different
        worker count would double-count (a partitioned reader's skip
        counter no longer lines up) or silently drop the ``_wN`` streams.
        The reference ties snapshots to worker topology the same way."""
        meta = self.impl.get_meta()
        stored = meta.get("n_workers")
        if stored is not None and stored != n_workers:
            raise RuntimeError(
                f"persistence snapshot was recorded with {stored} worker(s); "
                f"resuming with {n_workers} is not supported — restart with "
                f"the original topology or clear the snapshot directory"
            )
        if stored is None and not self.replay_only:
            meta["n_workers"] = n_workers
            self.impl.put_meta(meta)

    def stream_name(self, node: Any, worker: int = 0) -> str:
        # one snapshot stream per (input, worker): partitioned readers
        # record and resume independently (reference per-worker snapshot
        # writers, src/persistence/tracker.rs).  An explicit persistent_id
        # names the stream stably across graph edits (reference
        # persistent-id management, src/persistence/tracker.rs:26-63)
        pid = getattr(node, "persistent_id", None)
        base = f"input_pid_{pid}" if pid else f"input_{node.name}_{node.id}"
        return f"{base}_w{worker}" if worker else base

    @staticmethod
    def _replayable(node: Any) -> bool:
        """Count-based resume is only sound for readers that re-emit their
        history deterministically in the same order (file-style sources).
        Live-only subjects (e.g. custom python connectors, Kafka from the
        live position) opt in by setting ``deterministic_replay = True``."""
        return bool(getattr(node.subject, "deterministic_replay", False))

    def replay_events(self, node: Any, worker: int = 0) -> list[tuple[str, Any, Any]]:
        """Committed events for this input, for ALL source kinds (the
        reference persists and rewinds every input snapshot regardless of
        reader type).  The uncommitted tail is dropped AND truncated from
        the on-disk log — otherwise the resumed reader re-records the tail
        events and the next commit makes both copies committed
        (double-counting on the second restart).

        Auxiliary loopback inputs (e.g. AsyncTransformer results) are
        excluded: their rows are recomputed from the replayed upstream, so
        replaying a recorded copy as well would double-count them."""
        if getattr(node, "auxiliary", False):
            return []
        if not self.persisted(node):
            return []  # SELECTIVE_PERSISTING: no persistent_id, no snapshot
        stream = self.stream_name(node, worker)
        records = [pickle.loads(r) for r in self.impl.read_all(stream)]
        last_commit = -1
        counter_mark = 0
        for i, (kind, k, _v) in enumerate(records):
            if kind == "commit":
                last_commit = i
                if isinstance(k, int):  # autogen high-water mark (see commit())
                    counter_mark = max(counter_mark, k)
        if not self.replay_only:
            # unconditionally: also chops torn trailing bytes that read_all
            # skipped (a crash mid-append), which would otherwise corrupt
            # records appended after them
            self.impl.truncate(stream, last_commit + 1)
        # fast-forward the autogen key counter past every sequence number a
        # replayed key can embed, so new rows can never collide
        from pathway_tpu.io import _connector as _conn

        _conn._autogen_counter.advance_to(counter_mark)
        from pathway_tpu.internals.keys import Pointer

        out: list[tuple[str, Any, Any]] = []
        for kind, k, v in records[: last_commit + 1]:
            if kind == "addmany_b":  # binary chunked record (native frame)
                native = _native_mod.load()
                if native is None:
                    raise RuntimeError(
                        "snapshot log holds binary addmany records but the "
                        "native module is unavailable"
                    )
                out.extend(("add", kk, vv) for kk, vv in native.unpack_kv(k))
            elif kind == "addframe":  # columnar frame record
                native = _native_mod.load()
                if native is None:
                    raise RuntimeError(
                        "snapshot log holds columnar frame records but the "
                        "native module is unavailable"
                    )
                out.extend(
                    ("add" if u.diff > 0 else "remove", u.key, u.values)
                    for u in native.frame_to_updates(
                        native.frame_unpack(k, None)
                    )
                )
            elif kind == "addmany":  # chunked record: expand to per-row events
                out.extend(("add", Pointer(kk), vv) for kk, vv in k)
            elif kind in ("add", "remove"):
                # rewrap logged int keys (see _record_and_forward): derived-
                # key hashing tags Pointer and int differently
                out.append((kind, Pointer(k), v))
            else:
                out.append((kind, k, v))
        return out

    def wrap_events(self, node: Any, events: Any, replayed: int, worker: int = 0) -> Any:
        if self.replay_only:
            return events
        if getattr(node, "auxiliary", False):
            return events  # loopbacks are never recorded (see replay_events)
        if not self.persisted(node):
            return events  # SELECTIVE_PERSISTING: source opted out
        if replayed and not self._replayable(node):
            # Non-deterministic reader: it will NOT re-emit its history, so
            # nothing is skipped.  Readers that track their own positions
            # (e.g. Kafka offsets) are told how many committed events were
            # restored so they can seek past them; others get a loud
            # warning that re-delivered rows would double-count.
            hook = getattr(node.subject, "on_persistence_resume", None)
            if hook is not None:
                hook(replayed)
            else:
                _logger.warning(
                    "input %r resumed from %d persisted events but its reader "
                    "is not deterministically replayable and defines no "
                    "on_persistence_resume(n) hook; if it re-delivers old rows "
                    "they will be double-counted",
                    getattr(node, "name", node),
                    replayed,
                )
            replayed = 0
        return _RecordingEvents(
            events, self.impl, self.stream_name(node, worker), replayed
        )


def attach_persistence(sched: Any, config: Config) -> None:
    """Install persistence hooks on a Scheduler (called by ``pw.run``)."""
    if config.persistence_mode == PersistenceMode.UDF_CACHING:
        # UDF DiskCache reads PATHWAY_PERSISTENT_STORAGE
        if isinstance(config.backend._impl, _FsBackend):
            os.environ.setdefault(
                "PATHWAY_PERSISTENT_STORAGE", config.backend._impl.path
            )
        return
    sched.persistence = PersistenceHooks(config)
