"""TPU model zoo: the encoders behind the RAG numeric plane.

The reference runs SentenceTransformer / cross-encoder models per-row on
torch (``python/pathway/xpacks/llm/embedders.py:270-327``,
``rerankers.py:186-235``).  Here the same model families are brand-new
flax modules, jit-compiled in bf16, batched per epoch, and shardable
(tensor-parallel param rules + data-parallel batches) over a
``jax.sharding.Mesh``.
"""

from pathway_tpu.models.encoder import (
    BGE_BASE,
    BGE_LARGE,
    BGE_RERANKER_BASE,
    BGE_SMALL,
    E5_BASE,
    MINILM_L6,
    CrossEncoderModel,
    EncoderConfig,
    TextEncoderModel,
    encoder_param_specs,
)
from pathway_tpu.models.tokenizer import HashTokenizer, Tokenizer, get_tokenizer
from pathway_tpu.models.vision import SIGLIP_BASE, DualEncoderModel, VisionConfig

__all__ = [
    "EncoderConfig",
    "TextEncoderModel",
    "CrossEncoderModel",
    "VisionConfig",
    "DualEncoderModel",
    "encoder_param_specs",
    "MINILM_L6",
    "BGE_SMALL",
    "BGE_BASE",
    "BGE_LARGE",
    "E5_BASE",
    "BGE_RERANKER_BASE",
    "SIGLIP_BASE",
    "Tokenizer",
    "HashTokenizer",
    "get_tokenizer",
]
