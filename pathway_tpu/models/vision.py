"""SigLIP-class dual (image+text) encoder, TPU-first flax.

Covers the reference's multimodal path (vision-LLM image parsing /
SigLIP-style multimodal retrieval configs in BASELINE.md): a ViT image
tower + text tower projected into a shared embedding space.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from pathway_tpu.models.encoder import EncoderBlock, EncoderConfig, TextEncoderModel

__all__ = ["VisionConfig", "VisionEncoderModel", "DualEncoderModel", "SIGLIP_BASE"]


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch: int = 16
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    embed_dim: int = 768  # shared space
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    def as_encoder_cfg(self) -> EncoderConfig:
        return EncoderConfig(
            hidden=self.hidden,
            layers=self.layers,
            heads=self.heads,
            mlp_dim=self.mlp_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )


SIGLIP_BASE = VisionConfig()


class VisionEncoderModel(nn.Module):
    """ViT tower: images [B, H, W, 3] -> [B, embed_dim] (mean-pooled)."""

    cfg: VisionConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg = self.cfg
        x = nn.Conv(
            features=cfg.hidden,
            kernel_size=(cfg.patch, cfg.patch),
            strides=(cfg.patch, cfg.patch),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.hidden)  # [B, P, H]
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, cfg.n_patches, cfg.hidden),
            cfg.param_dtype,
        )
        x = x + pos.astype(cfg.dtype)
        mask = jnp.ones(x.shape[:2], dtype=jnp.int32)
        ecfg = cfg.as_encoder_cfg()
        for i in range(cfg.layers):
            x = EncoderBlock(ecfg, name=f"layer_{i}")(x, mask)
        pooled = jnp.mean(x.astype(jnp.float32), axis=1)
        out = nn.Dense(
            cfg.embed_dim, dtype=jnp.float32, param_dtype=cfg.param_dtype,
            name="projection",
        )(pooled)
        norm = jnp.sqrt(jnp.sum(out**2, axis=-1, keepdims=True))
        return out / jnp.maximum(norm, 1e-12)


class DualEncoderModel(nn.Module):
    """SigLIP-style contrastive pair: embed_image / embed_text entry points
    plus a combined call returning the pairwise logit matrix."""

    vision_cfg: VisionConfig
    text_cfg: EncoderConfig

    def setup(self) -> None:
        self.vision = VisionEncoderModel(self.vision_cfg)
        self.text = TextEncoderModel(
            dataclasses.replace(self.text_cfg, normalize=True)
        )
        self.logit_scale = self.param(
            "logit_scale", nn.initializers.constant(1.0), (), jnp.float32
        )
        self.logit_bias = self.param(
            "logit_bias", nn.initializers.constant(0.0), (), jnp.float32
        )

    def embed_image(self, images: jax.Array) -> jax.Array:
        return self.vision(images)

    def embed_text(self, ids: jax.Array, mask: jax.Array) -> jax.Array:
        return self.text(ids, mask)

    def __call__(
        self, images: jax.Array, ids: jax.Array, mask: jax.Array
    ) -> jax.Array:
        img = self.embed_image(images)
        txt = self.embed_text(ids, mask)
        return img @ txt.T * jnp.exp(self.logit_scale) + self.logit_bias
