"""Pure-Python WordPiece tokenizer (BERT-family), loaded from a vocab file.

The reference runs real SentenceTransformer/CrossEncoder checkpoints whose
tokenization is HuggingFace WordPiece (``xpacks/llm/embedders.py:270-327``).
This is a dependency-free reimplementation of the BERT tokenization
pipeline — basic tokenization (clean, CJK spacing, optional lowercasing +
accent stripping, punctuation splitting) followed by greedy
longest-match-first WordPiece — byte-compatible with
``transformers.BertTokenizer`` on the same ``vocab.txt`` (see
``tests/test_models_parity.py`` for the equivalence test).

No network: the vocab file must exist locally (shipped next to a model
checkpoint as ``vocab.txt``).
"""

from __future__ import annotations

import unicodedata
from typing import Any, Sequence

import numpy as np

from pathway_tpu.models.tokenizer import Tokenizer
from pathway_tpu.ops.bucketing import bucket_size

__all__ = ["WordPieceTokenizer", "load_vocab"]


def load_vocab(vocab_file: str) -> dict[str, int]:
    vocab: dict[str, int] = {}
    with open(vocab_file, encoding="utf-8") as f:
        for i, line in enumerate(f):
            token = line.rstrip("\n")
            if token:
                vocab[token] = i
    return vocab


def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges treated as punctuation by BERT even when unicode says no
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        (0x4E00 <= cp <= 0x9FFF)
        or (0x3400 <= cp <= 0x4DBF)
        or (0x20000 <= cp <= 0x2A6DF)
        or (0x2A700 <= cp <= 0x2B73F)
        or (0x2B740 <= cp <= 0x2B81F)
        or (0x2B820 <= cp <= 0x2CEAF)
        or (0xF900 <= cp <= 0xFAFF)
        or (0x2F800 <= cp <= 0x2FA1F)
    )


class WordPieceTokenizer(Tokenizer):
    """BERT tokenization: basic tokenizer + WordPiece over a vocab file."""

    def __init__(
        self,
        vocab_file: str,
        *,
        do_lower_case: bool = True,
        unk_token: str = "[UNK]",
        cls_token: str = "[CLS]",
        sep_token: str = "[SEP]",
        pad_token: str = "[PAD]",
        max_input_chars_per_word: int = 100,
    ):
        self.vocab = load_vocab(vocab_file)
        self.do_lower_case = do_lower_case
        self.unk_id = self.vocab[unk_token]
        self.cls_id = self.vocab[cls_token]
        self.sep_id = self.vocab[sep_token]
        self.pad_id = self.vocab[pad_token]
        self.max_input_chars_per_word = max_input_chars_per_word
        self.vocab_size = len(self.vocab)
        self._native_vocab: Any = None  # built lazily (wp_build capsule)

    def _native_handle(self):
        """C++ WordPiece handle, or None.  ASCII texts tokenize in one C
        pass (native/pathway_native.cpp wp_encode); others fall back."""
        if self._native_vocab is None:
            from pathway_tpu.internals import native as _native

            mod = _native.load()
            if mod is None or not hasattr(mod, "wp_build"):
                self._native_vocab = (None, None)
            else:
                self._native_vocab = (
                    mod,
                    mod.wp_build(
                        self.vocab, self.unk_id, self.max_input_chars_per_word
                    ),
                )
        return self._native_vocab

    def tokenize_ids_batch(self, texts: Sequence[str]) -> list[list[int]]:
        mod, cap = self._native_handle()
        if cap is None:
            return [self.tokenize_ids(t) for t in texts]
        rows = mod.wp_encode(cap, list(texts), self.do_lower_case)
        for i, r in enumerate(rows):
            if r is None:  # non-ASCII text: exact unicode pipeline
                rows[i] = self.tokenize_ids(texts[i])
        return rows

    # -- basic tokenization -------------------------------------------
    def _clean(self, text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    def _space_cjk(self, text: str) -> str:
        out = []
        for ch in text:
            if _is_cjk(ord(ch)):
                out.append(f" {ch} ")
            else:
                out.append(ch)
        return "".join(out)

    @staticmethod
    def _strip_accents(text: str) -> str:
        return "".join(
            ch
            for ch in unicodedata.normalize("NFD", text)
            if unicodedata.category(ch) != "Mn"
        )

    @staticmethod
    def _split_punct(token: str) -> list[str]:
        out: list[list[str]] = []
        start_new = True
        for ch in token:
            if _is_punctuation(ch):
                out.append([ch])
                start_new = True
            else:
                if start_new:
                    out.append([])
                    start_new = False
                out[-1].append(ch)
        return ["".join(x) for x in out]

    def basic_tokenize(self, text: str) -> list[str]:
        text = self._space_cjk(self._clean(text))
        tokens: list[str] = []
        for tok in text.split():
            if self.do_lower_case:
                tok = self._strip_accents(tok.lower())
            tokens.extend(self._split_punct(tok))
        return tokens

    # -- wordpiece ----------------------------------------------------
    def wordpiece(self, token: str) -> list[int]:
        if len(token) > self.max_input_chars_per_word:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        n = len(token)
        while start < n:
            end = n
            cur = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def tokenize_ids(self, text: str) -> list[int]:
        ids: list[int] = []
        for tok in self.basic_tokenize(text):
            ids.extend(self.wordpiece(tok))
        return ids

    # -- Tokenizer interface ------------------------------------------
    def count_tokens(self, text: str) -> int:
        return len(self.tokenize_ids(text))

    def encode_batch(
        self,
        texts: Sequence[str],
        *,
        max_len: int = 512,
        pair: Sequence[str] | None = None,
        bucket_len: bool = True,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        firsts = self.tokenize_ids_batch(texts)
        seconds = self.tokenize_ids_batch(pair) if pair is not None else None
        rows: list[list[int]] = []
        types: list[list[int]] = []
        for i, text in enumerate(texts):
            first = firsts[i]
            if pair is not None:
                second = seconds[i]
                # HF "longest_first" pair truncation: trim the longer side
                budget = max(0, max_len - 3)
                while len(first) + len(second) > budget and (first or second):
                    if len(first) >= len(second):
                        first = first[:-1]
                    else:
                        second = second[:-1]
                ids = [self.cls_id] + first + [self.sep_id] + second + [self.sep_id]
                tps = [0] * (len(first) + 2) + [1] * (len(second) + 1)
            else:
                ids = [self.cls_id] + first[: max(0, max_len - 2)] + [self.sep_id]
                tps = [0] * len(ids)
            rows.append(ids)
            types.append(tps)
        longest = max((len(r) for r in rows), default=1)
        width = (
            bucket_size(longest, min_bucket=16, max_bucket=max_len)
            if bucket_len
            else max_len
        )
        width = max(width, longest)
        b = len(rows)
        ids_arr = np.full((b, width), self.pad_id, dtype=np.int32)
        mask = np.zeros((b, width), dtype=np.int32)
        type_arr = np.zeros((b, width), dtype=np.int32)
        for i, (r, t) in enumerate(zip(rows, types)):
            ids_arr[i, : len(r)] = r
            mask[i, : len(r)] = 1
            type_arr[i, : len(t)] = t
        return ids_arr, mask, type_arr
