"""Tokenizers feeding the TPU encoders.

Two implementations behind one interface:

- :class:`HFTokenizer` — wraps a locally cached HuggingFace tokenizer
  when one is available (the environment has no network egress, so this
  is gated on the local cache).
- :class:`HashTokenizer` — deterministic hashing WordPiece stand-in:
  lowercase, split on non-alphanumerics, id = stable 64-bit hash of the
  token folded into the vocab.  Preserves the shapes/FLOPs of the real
  pipeline (exactly what benchmarking and tests need offline).

Both produce bucketed, padded ``(ids, mask)`` int32 batches — static
shapes for XLA (see :mod:`pathway_tpu.ops.bucketing`).
"""

from __future__ import annotations

import hashlib
import re
from typing import Sequence

import numpy as np

from pathway_tpu.ops.bucketing import bucket_size

__all__ = ["Tokenizer", "HashTokenizer", "HFTokenizer", "get_tokenizer"]

_WORD_RE = re.compile(r"[a-z0-9]+", re.UNICODE)

PAD_ID = 0
CLS_ID = 101
SEP_ID = 102
_RESERVED = 1000  # ids below this are reserved for specials


class Tokenizer:
    def encode_batch(
        self,
        texts: Sequence[str],
        *,
        max_len: int = 512,
        pair: Sequence[str] | None = None,
        bucket_len: bool = True,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (ids, mask, type_ids), each int32 [B, L]."""
        raise NotImplementedError

    def count_tokens(self, text: str) -> int:
        raise NotImplementedError


class HashTokenizer(Tokenizer):
    def __init__(self, vocab_size: int = 30522):
        self.vocab_size = vocab_size

    def _token_id(self, tok: str) -> int:
        h = int.from_bytes(hashlib.blake2b(tok.encode(), digest_size=8).digest(), "little")
        return _RESERVED + h % (self.vocab_size - _RESERVED)

    def _tokens(self, text: str) -> list[int]:
        return [self._token_id(t) for t in _WORD_RE.findall(text.lower())]

    def count_tokens(self, text: str) -> int:
        return len(_WORD_RE.findall(text.lower()))

    def encode_batch(
        self,
        texts: Sequence[str],
        *,
        max_len: int = 512,
        pair: Sequence[str] | None = None,
        bucket_len: bool = True,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        rows: list[list[int]] = []
        types: list[list[int]] = []
        for i, text in enumerate(texts):
            ids = [CLS_ID] + self._tokens(text)[: max_len - 2] + [SEP_ID]
            tps = [0] * len(ids)
            if pair is not None:
                second = self._tokens(pair[i])[: max_len - len(ids) - 1] + [SEP_ID]
                ids += second
                tps += [1] * len(second)
            rows.append(ids[:max_len])
            types.append(tps[:max_len])
        longest = max((len(r) for r in rows), default=1)
        width = bucket_size(longest, min_bucket=16, max_bucket=max_len) if bucket_len else max_len
        width = max(width, longest)
        b = len(rows)
        ids_arr = np.full((b, width), PAD_ID, dtype=np.int32)
        mask = np.zeros((b, width), dtype=np.int32)
        type_arr = np.zeros((b, width), dtype=np.int32)
        for i, (r, t) in enumerate(zip(rows, types)):
            ids_arr[i, : len(r)] = r
            mask[i, : len(r)] = 1
            type_arr[i, : len(t)] = t
        return ids_arr, mask, type_arr


class HFTokenizer(Tokenizer):
    """Locally cached HuggingFace tokenizer (no downloads attempted)."""

    def __init__(self, name: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name, local_files_only=True)

    def count_tokens(self, text: str) -> int:
        return len(self._tok.encode(text, add_special_tokens=False))

    def encode_batch(
        self,
        texts: Sequence[str],
        *,
        max_len: int = 512,
        pair: Sequence[str] | None = None,
        bucket_len: bool = True,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        enc = self._tok(
            list(texts),
            text_pair=list(pair) if pair is not None else None,
            truncation=True,
            max_length=max_len,
            padding=True,
            return_tensors="np",
        )
        ids = enc["input_ids"].astype(np.int32)
        mask = enc["attention_mask"].astype(np.int32)
        if bucket_len:
            width = min(max(bucket_size(ids.shape[1], min_bucket=16), ids.shape[1]), max_len)
            if width > ids.shape[1]:
                pad = width - ids.shape[1]
                ids = np.pad(ids, ((0, 0), (0, pad)))
                mask = np.pad(mask, ((0, 0), (0, pad)))
        tps = enc.get("token_type_ids")
        tps = (
            tps.astype(np.int32)
            if tps is not None and tps.shape == ids.shape
            else np.zeros_like(ids)
        )
        return ids, mask, tps


def get_tokenizer(model_name: str | None = None, vocab_size: int = 30522) -> Tokenizer:
    """HF tokenizer if cached locally, else the deterministic hash stand-in."""
    if model_name:
        try:
            return HFTokenizer(model_name)
        except Exception:
            pass
    return HashTokenizer(vocab_size)
