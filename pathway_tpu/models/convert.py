"""HuggingFace BERT-family checkpoint → flax parameter converter.

The reference loads real SentenceTransformer / CrossEncoder torch
checkpoints (``xpacks/llm/embedders.py:270-327``, ``rerankers.py:186-235``).
This converter maps a locally stored HF checkpoint (``model.safetensors``
or ``pytorch_model.bin`` + ``config.json`` + ``vocab.txt``) onto the
TPU-native flax modules in :mod:`pathway_tpu.models.encoder`, so
MiniLM/BGE/E5 and the BGE reranker run with their published weights on the
MXU.  No network access is attempted — everything reads local files.

Weight layout translation (torch ``nn.Linear`` stores ``[out, in]``; flax
``Dense`` kernels are ``[in, out]``; our attention uses ``DenseGeneral``
with fused ``[in, heads, head_dim]`` kernels):

==================================================  =========================
HF name                                             flax path
==================================================  =========================
embeddings.word_embeddings.weight                   embeddings/word/embedding
embeddings.position_embeddings.weight               embeddings/position/embedding
embeddings.token_type_embeddings.weight             embeddings/type/embedding
embeddings.LayerNorm.{weight,bias}                  embeddings/ln/{scale,bias}
encoder.layer.N.attention.self.query.{weight,bias}  layer_N/attention/query
  (weight.T reshaped [hidden, heads, head_dim])
encoder.layer.N.attention.output.dense              layer_N/attention/out
  (weight.T reshaped [heads, head_dim, hidden])
encoder.layer.N.attention.output.LayerNorm          layer_N/attention_ln
encoder.layer.N.intermediate.dense                  layer_N/mlp_up
encoder.layer.N.output.dense                        layer_N/mlp_down
encoder.layer.N.output.LayerNorm                    layer_N/mlp_ln
pooler.dense                                        pooler   (cross-encoder)
classifier                                          classifier
==================================================  =========================
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import numpy as np

from pathway_tpu.models.encoder import EncoderConfig

__all__ = [
    "load_state_dict",
    "config_from_hf",
    "convert_bert_checkpoint",
    "load_encoder",
]


def load_state_dict(model_dir: str) -> dict[str, np.ndarray]:
    """Read a checkpoint directory's weights as numpy arrays
    (safetensors preferred, torch pickle fallback)."""
    st_path = os.path.join(model_dir, "model.safetensors")
    if os.path.exists(st_path):
        from safetensors.numpy import load_file

        return dict(load_file(st_path))
    bin_path = os.path.join(model_dir, "pytorch_model.bin")
    if os.path.exists(bin_path):
        import torch

        sd = torch.load(bin_path, map_location="cpu", weights_only=True)
        return {k: v.numpy() for k, v in sd.items()}
    raise FileNotFoundError(
        f"no model.safetensors or pytorch_model.bin under {model_dir}"
    )


def config_from_hf(
    model_dir: str, *, pool: str | None = None, num_labels: int = 0, **overrides: Any
) -> EncoderConfig:
    """EncoderConfig from a checkpoint's ``config.json``."""
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    if pool is None:
        # BGE-style retrievers pool CLS; sentence-transformers default mean
        pool = "cls" if "bge" in str(hf.get("_name_or_path", "")).lower() else "mean"
    # HF does not serialize num_labels itself — classification heads are
    # detected via the architectures list, width via id2label
    archs = hf.get("architectures") or []
    is_classifier = any(str(a).endswith("SequenceClassification") for a in archs)
    detected_labels = 0
    if is_classifier:
        detected_labels = int(
            hf.get("num_labels") or len(hf.get("id2label") or {}) or 1
        )
    cfg = EncoderConfig(
        vocab_size=hf["vocab_size"],
        hidden=hf["hidden_size"],
        layers=hf["num_hidden_layers"],
        heads=hf["num_attention_heads"],
        mlp_dim=hf["intermediate_size"],
        max_len=hf.get("max_position_embeddings", 512),
        type_vocab=hf.get("type_vocab_size", 2),
        ln_eps=hf.get("layer_norm_eps", 1e-12),
        gelu_approx=hf.get("hidden_act", "gelu") in ("gelu_new", "gelu_pytorch_tanh"),
        pool=pool,
        num_labels=num_labels or detected_labels,
    )
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _strip_prefix(sd: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Drop wrapper prefixes (``bert.``, ``model.``, ``roberta.``)."""
    for prefix in ("bert.", "model.", "roberta.", "distilbert."):
        if any(k.startswith(prefix + "embeddings") for k in sd):
            out = {}
            for k, v in sd.items():
                out[k[len(prefix):] if k.startswith(prefix) else k] = v
            return out
    return sd


def convert_bert_checkpoint(
    sd: dict[str, np.ndarray], cfg: EncoderConfig
) -> dict[str, Any]:
    """Torch/HF state dict → flax params tree for TextEncoderModel /
    CrossEncoderModel (cite: parity test tests/test_models_parity.py)."""
    sd = _strip_prefix(sd)
    H, heads, hd = cfg.hidden, cfg.heads, cfg.head_dim

    def t(name: str) -> np.ndarray:
        return np.asarray(sd[name], dtype=np.float32)

    def linear(name: str) -> dict[str, np.ndarray]:
        return {"kernel": t(f"{name}.weight").T, "bias": t(f"{name}.bias")}

    def ln(name: str) -> dict[str, np.ndarray]:
        return {"scale": t(f"{name}.weight"), "bias": t(f"{name}.bias")}

    def qkv(name: str) -> dict[str, np.ndarray]:
        return {
            "kernel": t(f"{name}.weight").T.reshape(H, heads, hd),
            "bias": t(f"{name}.bias").reshape(heads, hd),
        }

    params: dict[str, Any] = {
        "embeddings": {
            "word": {"embedding": t("embeddings.word_embeddings.weight")},
            "position": {"embedding": t("embeddings.position_embeddings.weight")},
            "ln": ln("embeddings.LayerNorm"),
        }
    }
    if cfg.type_vocab and "embeddings.token_type_embeddings.weight" in sd:
        params["embeddings"]["type"] = {
            "embedding": t("embeddings.token_type_embeddings.weight")
        }
    for i in range(cfg.layers):
        p = f"encoder.layer.{i}"
        params[f"layer_{i}"] = {
            "attention": {
                "query": qkv(f"{p}.attention.self.query"),
                "key": qkv(f"{p}.attention.self.key"),
                "value": qkv(f"{p}.attention.self.value"),
                "out": {
                    "kernel": t(f"{p}.attention.output.dense.weight").T.reshape(
                        heads, hd, H
                    ),
                    "bias": t(f"{p}.attention.output.dense.bias"),
                },
            },
            "attention_ln": ln(f"{p}.attention.output.LayerNorm"),
            "mlp_up": linear(f"{p}.intermediate.dense"),
            "mlp_down": linear(f"{p}.output.dense"),
            "mlp_ln": ln(f"{p}.output.LayerNorm"),
        }
    if cfg.num_labels > 0:
        params["pooler"] = linear("pooler.dense")
        params["classifier"] = linear("classifier")
    return {"params": params}


def load_encoder(
    model_dir: str,
    *,
    pool: str | None = None,
    num_labels: int = 0,
    dtype: Any = None,
    **overrides: Any,
) -> tuple[Any, dict[str, Any], Any]:
    """One-call loader: ``(model, params, tokenizer)`` from a local HF
    checkpoint directory (``config.json`` + weights + ``vocab.txt``)."""
    from pathway_tpu.models.encoder import CrossEncoderModel, TextEncoderModel
    from pathway_tpu.models.wordpiece import WordPieceTokenizer

    cfg = config_from_hf(model_dir, pool=pool, num_labels=num_labels, **overrides)
    if dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    params = convert_bert_checkpoint(load_state_dict(model_dir), cfg)
    model = CrossEncoderModel(cfg) if cfg.num_labels > 0 else TextEncoderModel(cfg)
    vocab = os.path.join(model_dir, "vocab.txt")
    tok = WordPieceTokenizer(vocab) if os.path.exists(vocab) else None
    return model, params, tok
