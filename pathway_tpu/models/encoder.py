"""BERT-family text encoders and cross-encoders, TPU-first.

Brand-new flax implementation of the model families the reference drives
through torch SentenceTransformers (MiniLM, BGE, E5 —
``xpacks/llm/embedders.py:270``) and torch CrossEncoder
(``xpacks/llm/rerankers.py:186``).  Design for the MXU:

- bf16 activations / f32 params (configurable), static shapes via
  bucketed padding (see :mod:`pathway_tpu.ops.bucketing`);
- post-LN BERT blocks expressed as einsum-shaped flax modules so XLA
  fuses bias+gelu+residual into the matmuls;
- tensor-parallel sharding RULES (:func:`encoder_param_specs`) mapping
  each param to a ``PartitionSpec`` over a mesh "model" axis: attention
  heads and MLP hidden dim are split, embeddings/LN replicated.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from pathway_tpu.ops.pooling import cls_pool, masked_mean_pool

__all__ = [
    "EncoderConfig",
    "TextEncoderModel",
    "CrossEncoderModel",
    "encoder_param_specs",
    "MINILM_L6",
    "BGE_SMALL",
    "BGE_BASE",
    "BGE_LARGE",
    "E5_BASE",
    "BGE_RERANKER_BASE",
]


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    """Architecture hyperparameters (BERT-style post-LN encoder)."""

    vocab_size: int = 30522
    hidden: int = 384
    layers: int = 6
    heads: int = 12
    mlp_dim: int = 1536
    max_len: int = 512
    type_vocab: int = 2
    pool: str = "mean"  # mean | cls
    normalize: bool = True  # L2-normalize sentence embedding
    num_labels: int = 0  # >0 => cross-encoder classification head
    dtype: Any = jnp.bfloat16  # activation dtype
    param_dtype: Any = jnp.float32
    ln_eps: float = 1e-12
    #: tanh-approximated gelu (faster on MXU); HF "gelu" is the exact erf
    #: form — the checkpoint converter sets this from config.json
    gelu_approx: bool = True
    #: sequence-parallel long-document attention: when a Mesh is set,
    #: every SelfAttention runs ops.ring_attention with the sequence
    #: dimension sharded over ``seq_axis`` (K/V blocks rotate over ICI
    #: via ppermute; exact flash-style running softmax).  Sequences may
    #: then exceed one device's attention memory; max_len still bounds
    #: the position table.  Meshes hash by identity, so the config stays
    #: a valid static jit argument.
    seq_mesh: Any = None
    seq_axis: str = "data"

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


# Presets mirroring the model families in the reference's xpack docs/tests.
MINILM_L6 = EncoderConfig(hidden=384, layers=6, heads=12, mlp_dim=1536)
BGE_SMALL = EncoderConfig(hidden=384, layers=12, heads=12, mlp_dim=1536, pool="cls")
BGE_BASE = EncoderConfig(hidden=768, layers=12, heads=12, mlp_dim=3072, pool="cls")
BGE_LARGE = EncoderConfig(hidden=1024, layers=24, heads=16, mlp_dim=4096, pool="cls")
E5_BASE = EncoderConfig(hidden=768, layers=12, heads=12, mlp_dim=3072, pool="mean")
BGE_RERANKER_BASE = dataclasses.replace(
    BGE_BASE, num_labels=1, pool="cls", normalize=False
)


class SelfAttention(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.cfg
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(cfg.heads, cfg.head_dim),
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name=name,
        )
        q = dense("query")(x)  # [B, L, h, d]
        k = dense("key")(x)
        v = dense("value")(x)
        if cfg.seq_mesh is not None:
            # long-document path: sequence-parallel ring attention
            # (ops/ring_attention.py) — same math, K/V ring over ICI
            from pathway_tpu.ops.ring_attention import ring_attention

            ctx = ring_attention(
                q, k, v, mask, mesh=cfg.seq_mesh, axis=cfg.seq_axis
            )
        else:
            scale = 1.0 / jnp.sqrt(jnp.float32(cfg.head_dim))
            logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
            bias = jnp.where(mask.astype(bool)[:, None, None, :], 0.0, -1e30)
            probs = jax.nn.softmax(logits + bias, axis=-1).astype(cfg.dtype)
            ctx = jnp.einsum("bhlm,bmhd->blhd", probs, v)
        out = nn.DenseGeneral(
            features=cfg.hidden,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="out",
        )(ctx)
        return out


class EncoderBlock(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.cfg
        a = SelfAttention(cfg, name="attention")(x, mask)
        x = nn.LayerNorm(
            epsilon=cfg.ln_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="attention_ln",
        )(x + a)
        h = nn.Dense(
            cfg.mlp_dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="mlp_up"
        )(x)
        h = nn.gelu(h, approximate=cfg.gelu_approx)
        h = nn.Dense(
            cfg.hidden, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="mlp_down"
        )(h)
        return nn.LayerNorm(
            epsilon=cfg.ln_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="mlp_ln",
        )(x + h)


class Embeddings(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids: jax.Array, type_ids: jax.Array | None) -> jax.Array:
        cfg = self.cfg
        emb = nn.Embed(
            cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="word",
        )(ids)
        pos = nn.Embed(
            cfg.max_len, cfg.hidden, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="position",
        )(jnp.arange(ids.shape[1])[None, :])
        emb = emb + pos
        if cfg.type_vocab:
            t = type_ids if type_ids is not None else jnp.zeros_like(ids)
            emb = emb + nn.Embed(
                cfg.type_vocab, cfg.hidden, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="type",
            )(t)
        return nn.LayerNorm(
            epsilon=cfg.ln_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="ln",
        )(emb)


class TextEncoderModel(nn.Module):
    """Sentence encoder: token ids -> pooled (optionally normalized)
    embedding [B, hidden]."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(
        self,
        ids: jax.Array,
        mask: jax.Array,
        type_ids: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.cfg
        x = Embeddings(cfg, name="embeddings")(ids, type_ids)
        for i in range(cfg.layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x, mask)
        pooled = cls_pool(x) if cfg.pool == "cls" else masked_mean_pool(x, mask)
        if cfg.normalize:
            norm = jnp.sqrt(
                jnp.sum(pooled.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
            )
            pooled = (pooled.astype(jnp.float32) / jnp.maximum(norm, 1e-12))
        return pooled.astype(jnp.float32)


class CrossEncoderModel(nn.Module):
    """(query, doc) pair scorer: encoder + classification head -> [B] or
    [B, num_labels] logits (reference CrossEncoderReranker's model)."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(
        self,
        ids: jax.Array,
        mask: jax.Array,
        type_ids: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.cfg
        x = Embeddings(cfg, name="embeddings")(ids, type_ids)
        for i in range(cfg.layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x, mask)
        cls = cls_pool(x)
        h = nn.Dense(
            cfg.hidden, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="pooler"
        )(cls)
        h = jnp.tanh(h)
        logits = nn.Dense(
            max(cfg.num_labels, 1), dtype=jnp.float32,
            param_dtype=cfg.param_dtype, name="classifier",
        )(h)
        return logits[:, 0] if max(cfg.num_labels, 1) == 1 else logits


# ---------------------------------------------------------------------------
# Tensor-parallel sharding rules


def encoder_param_specs(params: Any, model_axis: str = "model") -> Any:
    """PartitionSpec tree for encoder params: heads + MLP hidden split over
    ``model_axis``, everything else replicated.

    Works for both :class:`TextEncoderModel` and :class:`CrossEncoderModel`
    (and the towers of :class:`DualEncoderModel`), because the rules key on
    leaf path names, not tree structure.
    """
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf) -> Any:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        joined = "/".join(str(n) for n in names)
        nd = leaf.ndim
        if "kernel" in joined:
            if any(s in joined for s in ("query", "key", "value")):
                # [hidden, heads, head_dim] -> split heads
                return P(*([None] * (nd - 2)), model_axis, None)
            if "attention/out" in joined or joined.endswith("out/kernel"):
                # [heads, head_dim, hidden] -> split heads
                return P(model_axis, *([None] * (nd - 1)))
            if "mlp_up" in joined:
                return P(*([None] * (nd - 1)), model_axis)
            if "mlp_down" in joined:
                return P(model_axis, *([None] * (nd - 1)))
        if "bias" in joined:
            if any(s in joined for s in ("query", "key", "value")):
                return P(model_axis, *([None] * (nd - 1)))
            if "mlp_up" in joined:
                return P(*([None] * (nd - 1)), model_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
