"""The full live-RAG serving graph: ingest → embed → index → answer.

:class:`RagServingApp` composes the repo's pieces into one servable
system (ROADMAP item 2 / the paper's headline capability):

- **live ingest** runs as engine dataflow: a queue-driven document feed
  (``upsert``/``delete``) flows through a splitter (``pw.apply``) and a
  ``subscribe`` sink that embeds chunks on the SLO scheduler's embed
  lane and upserts them into a churn-safe :class:`SegmentedIndex`
  (delta segments + background merges, PR 9);
- **queries** are admitted per tenant (:class:`AdmissionController`),
  then travel embed → lookahead retrieve → generate through the
  :class:`StageCoScheduler` — retrieval overlaps generation instead of
  barriering behind it;
- optional **REST ingress** (:meth:`serve_rest`) exposes ``/v1/answer``
  with the admission controller wired into the connector, so overload
  answers 429 + ``Retry-After`` before a row ever enters the engine.

Everything here is dependency-light by design: the default embedder is
a deterministic feature-hashing bag-of-tokens (no model download), the
default generator is extractive — the point is the *serving fabric*
(admission, SLO scheduling, co-scheduling, live index), not model
quality.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.engine.cluster import WakeupHub
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals import tracing as _tracing
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._subscribe import subscribe
from pathway_tpu.io.python import ConnectorSubject
from pathway_tpu.stdlib.indexing.hnsw import HnswIndex
from pathway_tpu.stdlib.indexing.segments import SegmentedIndex

from .admission import AdmissionController, TenantPolicy
from .coscheduler import StageCoScheduler
from .scheduler import SloScheduler

__all__ = ["HashingEmbedder", "RagServingApp", "simple_splitter"]


class HashingEmbedder:
    """Deterministic feature-hashed bag-of-tokens embedding (crc32 mod
    dim, L2-normalized).  Same text → same vector, on any machine, with
    zero model weight — exactly what serving tests and benches need."""

    def __init__(self, dim: int = 64):
        self.dim = max(8, int(dim))

    def __call__(self, text: str) -> np.ndarray:
        vec = np.zeros(self.dim, np.float32)
        for token in str(text).lower().split():
            h = zlib.crc32(token.encode("utf-8"))
            vec[h % self.dim] += 1.0 if (h >> 16) & 1 else 0.5
        norm = float(np.linalg.norm(vec))
        if norm > 0:
            vec /= norm
        return vec


def simple_splitter(doc_id: str, text: str, chunk_words: int = 48) -> list[tuple[str, str]]:
    """Word-window splitter: ``[(chunk_id, chunk_text), ...]`` with
    stable ids ``{doc_id}#{i}`` so re-upserts replace their chunks."""
    words = str(text).split()
    if not words:
        return []
    chunks = []
    for i in range(0, len(words), chunk_words):
        chunks.append((f"{doc_id}#{i // chunk_words}", " ".join(words[i : i + chunk_words])))
    return chunks


class _DocFeed(ConnectorSubject):
    """Queue-driven live document source: ``push`` from any thread, the
    reader drains on WakeupHub generation-waits (no polling sleeps)."""

    def __init__(self, hub: WakeupHub):
        super().__init__("serving_docs")
        self._hub = hub
        self._q: list[tuple[str, dict]] = []
        self._qlock = threading.Lock()

    def push(self, op: str, row: dict) -> None:
        with self._qlock:
            self._q.append((op, row))
        self._hub.notify()

    def run(self) -> None:
        while not self.stopped:
            seen = self._hub.seq()
            with self._qlock:
                batch, self._q = self._q, []
            if not batch:
                self._hub.wait(seen, 0.05)
                continue
            for op, row in batch:
                if op == "delete":
                    self._remove(row)
                else:
                    self.next(**row)
            self.commit()


class RagServingApp:
    """One multi-tenant live-RAG serving instance.

    ``policies`` maps tenant name → :class:`TenantPolicy`; unknown
    tenants get ``default_policy``.  ``start()`` builds the dataflow
    into the current global graph and runs the engine scheduler on a
    daemon thread; ``close()`` tears everything down."""

    def __init__(
        self,
        policies: dict[str, TenantPolicy] | None = None,
        *,
        default_policy: TenantPolicy | None = None,
        embed_dim: int = 64,
        k: int = 4,
        chunk_words: int = 48,
        delta_cap: int = 256,
        auto_merge: bool = True,
        index: Any = None,
        embedder: Any = None,
        answerer: Any = None,
        lanes: dict[str, float] | None = None,
        target_ms: dict[str, float] | None = None,
        max_batch: int = 32,
        lookahead: bool = True,
        probe: Any = None,
        autocommit_ms: int = 10,
        shards: int | None = None,
        standby: bool = True,
    ):
        from pathway_tpu import serving as _serving

        self.hub = WakeupHub()
        self.probe = probe if probe is not None else _serving.serving_probe()
        self.admission = AdmissionController(
            policies, default_policy=default_policy, hub=self.hub
        )
        self.embedder = embedder if embedder is not None else HashingEmbedder(embed_dim)
        self.shards = int(shards) if shards else 0
        self.standby = bool(standby)
        if index is not None:
            self.index = index
        elif self.shards >= 2:
            # partial-failure survival: split the corpus across shard
            # owners so one owner's death degrades answers (partial:true
            # over the survivors + snapshot-backed standby) instead of
            # taking the query surface down — serving/failover.py
            from .failover import PartitionedIndex

            dim, cap, merge = self.embedder.dim, delta_cap, auto_merge
            self.index = PartitionedIndex(
                lambda: SegmentedIndex(
                    HnswIndex(dim, metric="cos"),
                    delta_cap=cap,
                    auto_merge=merge,
                ),
                n_shards=self.shards,
                standby=self.standby,
            )
        else:
            self.index = SegmentedIndex(
                HnswIndex(self.embedder.dim, metric="cos"),
                delta_cap=delta_cap,
                auto_merge=auto_merge,
            )
        self.scheduler = SloScheduler(
            lanes=lanes,
            target_ms=target_ms,
            max_batch=max_batch,
            hub=self.hub,
            probe=self.probe,
        )
        self._chunk_texts: dict[str, str] = {}
        self._chunk_lock = threading.Lock()
        self.coscheduler = StageCoScheduler(
            embedder=self.embedder,
            index=self.index,
            doc_text=self._text_of,
            answerer=answerer,
            scheduler=self.scheduler,
            probe=self.probe,
            k=k,
            lookahead=lookahead,
        )
        self.chunk_words = chunk_words
        self.autocommit_ms = autocommit_ms
        self._docs: dict[str, dict] = {}
        self._feed = _DocFeed(self.hub)
        self.sched: Scheduler | None = None
        self._run_thread: threading.Thread | None = None
        self._rest_port: int | None = None
        self.ingested_chunks = 0
        self.removed_chunks = 0

    # ------------------------------------------------------------- dataflow

    def _text_of(self, chunk_id: Any) -> str:
        with self._chunk_lock:
            return self._chunk_texts.get(chunk_id, "")

    def build(self) -> None:
        """Wire the ingest dataflow into the current global graph."""

        class DocSchema(pw.Schema):
            doc_id: str = pw.column_definition(primary_key=True)
            text: str
            tenant: str = pw.column_definition(default_value="default")

        docs = pw.io.python.read(self._feed, schema=DocSchema, name="serving_docs")
        chunk_words = self.chunk_words
        chunked = docs.select(
            chunks=pw.apply(
                lambda d, t: simple_splitter(d, t, chunk_words),
                pw.this.doc_id,
                pw.this.text,
            ),
            tenant=pw.this.tenant,
        )
        sink = subscribe(chunked, on_change=self._on_chunks, name="serving_ingest")
        # analyzer-facing stage annotations: without these the serving
        # pipeline is three opaque nodes and pw.analyze() cannot tell the
        # ingest path from a user graph (the old PW-S001 near-miss), nor
        # see that the sink is a keyed upsert into the live index
        docs._node.meta["serving"] = {
            "stage": "ingest",
            "admission": type(self.admission).__name__,
            "scheduler": type(self.scheduler).__name__,
        }
        chunked._node.meta["serving"] = {
            "stage": "chunk",
            "coscheduler": type(self.coscheduler).__name__,
        }
        sink.meta["serving"] = {"stage": "index-upsert"}
        # chunk ids are stable (doc_id + position) and the feed is a
        # single-reader python connector, so the upsert is order-safe —
        # the annotation lets PW-X001 verify that instead of assuming it
        sink.meta["index_upsert"] = True
        # availability annotation for PW-R002: a sharded index with
        # snapshot-backed standbys keeps answering (degraded) through a
        # shard owner's death; a single-owner index does not, and the
        # analyzer should say so
        sink.meta["failover"] = {
            "standby": self.shards >= 2 and self.standby,
            "shards": self.shards or 1,
        }

    def _on_chunks(self, key: Any, row: dict, time: int, is_addition: bool) -> None:
        chunks = list(row.get("chunks") or ())
        if not chunks:
            return
        tenant = str(row.get("tenant") or "default")
        cls = self.admission.policy(tenant).tenant_class
        if is_addition:
            with self._chunk_lock:
                for cid, text in chunks:
                    self._chunk_texts[cid] = text
            # embed + upsert ride the embed lane under the writer's
            # class: ingest competes with query embedding for device
            # time instead of bypassing the partition
            self.scheduler.submit(
                "embed", cls, self._ingest_batch, item=chunks, coalesce=None
            )
        else:
            # a re-upsert arrives as retraction(old) + addition(new) in
            # unspecified order; the addition path above stores the new
            # chunk text synchronously, so a retracted chunk whose
            # stored text no longer matches has already been superseded
            # — removing it would delete the replacement (the lane add
            # upserts by stable chunk id, so no removal is needed)
            with self._chunk_lock:
                ids = [
                    cid
                    for cid, text in chunks
                    if self._chunk_texts.get(cid) == text
                ]
                for cid in ids:
                    self._chunk_texts.pop(cid, None)
            if ids:
                self.index.remove(ids)
                self.removed_chunks += len(ids)

    def _ingest_batch(self, chunks: list[tuple[str, str]]) -> int:
        pairs = [(cid, self.embedder(text)) for cid, text in chunks]
        self.index.add(pairs)
        self.ingested_chunks += len(pairs)
        return len(pairs)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "RagServingApp":
        self.build()
        self.sched = Scheduler(G.engine_graph, autocommit_ms=self.autocommit_ms)
        self._run_thread = threading.Thread(
            target=self.sched.run, daemon=True, name="serving_engine"
        )
        self._run_thread.start()
        return self

    def close(self) -> None:
        if self.sched is not None:
            self.sched.stop()
        if self._run_thread is not None:
            self._run_thread.join(5.0)
        self.coscheduler.close()
        self.scheduler.close()
        close = getattr(self.index, "close", None)
        if close is not None:
            close()

    # -------------------------------------------------------------- writes

    def upsert(self, doc_id: str, text: str, tenant: str = "default") -> None:
        row = {"doc_id": str(doc_id), "text": str(text), "tenant": str(tenant)}
        self._docs[row["doc_id"]] = row
        self._feed.push("upsert", row)

    def delete(self, doc_id: str) -> None:
        row = self._docs.pop(str(doc_id), None)
        if row is not None:
            self._feed.push("delete", row)

    def wait_indexed(self, n_chunks: int, timeout: float = 10.0) -> bool:
        """Generation-wait until at least ``n_chunks`` live in the index."""
        import time as _t

        deadline = _t.monotonic() + timeout
        while True:
            seen = self.hub.seq()
            if len(self.index) >= n_chunks:
                return True
            remaining = deadline - _t.monotonic()
            if remaining <= 0:
                return len(self.index) >= n_chunks
            self.hub.wait(seen, min(remaining, 0.05))

    # ------------------------------------------------------------- queries

    def submit_query(self, query: str, tenant: str = "default", k: int | None = None):
        """Admit + co-schedule one query; returns a Future.  Raises
        ``RetryLater`` when the tenant is over its rate or queue bound.

        Tracing starts HERE: the request's trace context is born before
        admission and rides the request object through every stage — the
        response dict carries its ``trace_id`` back out."""
        trace = _tracing.new_trace()
        t0_ns = _tracing.now_ns()
        ticket = self.admission.admit(tenant, route="/v1/answer")
        _tracing.record_span(
            "admit", t0_ns, _tracing.now_ns(), ctx=trace,
            args={"tenant": tenant},
        )
        try:
            fut = self.coscheduler.submit(
                query, tenant_class=ticket.tenant_class, k=k, trace=trace
            )
        except BaseException:
            ticket.release()
            raise
        fut.add_done_callback(lambda _f: ticket.release())
        return fut

    def answer(
        self, query: str, tenant: str = "default", k: int | None = None, timeout: float = 30.0
    ) -> dict:
        return self.submit_query(query, tenant, k).result(timeout=timeout)

    # ---------------------------------------------------------------- REST

    def serve_rest(self, host: str = "127.0.0.1", port: int = 8080) -> None:
        """Register ``/v1/answer`` on a webserver with admission wired
        into the ingress (must be called before :meth:`start`)."""
        import asyncio

        from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer

        class AnswerSchema(pw.Schema):
            query: str
            tenant: str = pw.column_definition(default_value="default")
            k: int = pw.column_definition(default_value=0)

        queries, writer = pw.io.http.rest_connector(
            host=host,
            port=port,
            route="/v1/answer",
            schema=AnswerSchema,
            delete_completed_queries=False,
            admission=self.admission,
            tenant_field="tenant",
        )
        app = self

        class AnswerTransformer(AsyncTransformer):
            output_schema = pw.schema_from_types(result=dict)

            async def invoke(self, query: str, tenant: str, k: int) -> dict:
                cls = app.admission.policy(str(tenant)).tenant_class
                fut = app.coscheduler.submit(
                    str(query), tenant_class=cls, k=int(k) or None
                )
                result = await asyncio.wait_for(
                    asyncio.wrap_future(fut), timeout=30
                )
                return {"result": result}

        writer(AnswerTransformer(input_table=queries).successful)
        self._rest_port = port

    # -------------------------------------------------------------- status

    def stats(self) -> dict[str, Any]:
        return {
            "admission": self.admission.stats(),
            "scheduler": self.scheduler.stats(),
            "coscheduler": self.coscheduler.stats(),
            "index": self.index.stats() if hasattr(self.index, "stats") else {},
            "ingested_chunks": self.ingested_chunks,
            "removed_chunks": self.removed_chunks,
        }
