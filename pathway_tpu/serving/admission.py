"""Per-tenant admission control for the serving ingress.

The REST ingress used to buffer every request into an unbounded queue —
under overload that turns into unbounded memory growth and unbounded
tail latency, and a misbehaving tenant degrades everyone.  The
:class:`AdmissionController` makes the ingress *bounded*:

- a **token bucket** per tenant (``rate_per_s`` + ``burst``) caps the
  sustained request rate;
- a **bounded in-flight queue** per tenant (``queue_cap``) caps how many
  admitted requests a tenant may have inside the system at once;
- on either limit the request is **shed** with
  :class:`pathway_tpu.io.http.RetryLater` — the ingress maps it to HTTP
  429 + ``Retry-After`` (the bucket's refill ETA), never a silent drop.

Tickets are released when the response resolves (or the request dies),
and every release notifies the shared :class:`WakeupHub` so a parked
:meth:`wait_admit` re-checks immediately — all waits on the admission
path are finite generation-waits, never unbounded blocks (lint LK006,
``scripts/check_locks.py``).

**Brownout mode**: the engine pushes its pressure level (ingest-buffer
occupancy, exchange credit backlog) via :meth:`set_pressure`.  Under
pressure the controller tightens each class's effective token rate by a
weight-graded power law — best-effort classes collapse first, the
interactive class degrades last — and computes ``Retry-After`` from the
*measured* drain rate (EWMA of ticket-release gaps) instead of the
configured rate, so clients back off proportionally to how slow the
system actually is.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from pathway_tpu.engine.cluster import WakeupHub

__all__ = ["AdmissionController", "AdmissionTicket", "TenantPolicy"]

#: default weighted-fair share per SLO class (interactive requests get a
#: 4x device-time share over batch when both queues are backlogged)
DEFAULT_CLASS_WEIGHTS = {"interactive": 4.0, "batch": 1.0}


def _retry_later(retry_after: float, reason: str) -> Exception:
    # imported lazily: admission is loaded by /metrics scrapes and must
    # not pull the whole io stack in at import time
    from pathway_tpu.io.http import RetryLater

    return RetryLater(retry_after=retry_after, reason=reason)


class TenantPolicy:
    """Admission + scheduling policy for one tenant.

    ``tenant_class`` names the SLO class ("interactive" / "batch");
    ``rate_per_s``/``burst`` parameterize the token bucket; ``queue_cap``
    bounds in-flight admitted requests; ``weight`` overrides the class's
    weighted-fair share in the SLO scheduler."""

    __slots__ = ("tenant_class", "rate_per_s", "burst", "queue_cap", "weight")

    def __init__(
        self,
        tenant_class: str = "interactive",
        rate_per_s: float = 50.0,
        burst: float | None = None,
        queue_cap: int = 8,
        weight: float | None = None,
    ):
        self.tenant_class = str(tenant_class)
        self.rate_per_s = max(0.001, float(rate_per_s))
        self.burst = float(burst) if burst is not None else max(1.0, self.rate_per_s / 4)
        self.queue_cap = max(1, int(queue_cap))
        self.weight = (
            float(weight)
            if weight is not None
            else DEFAULT_CLASS_WEIGHTS.get(self.tenant_class, 1.0)
        )


class _TokenBucket:
    """On-demand-refill token bucket (no timer thread)."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t_last = now

    def _refill(self, now: float) -> None:
        dt = now - self.t_last
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
            self.t_last = now

    def take(self, now: float) -> bool:
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def eta(self, now: float) -> float:
        """Seconds until one token is available (0 if available now)."""
        self._refill(now)
        if self.tokens >= 1.0:
            return 0.0
        return (1.0 - self.tokens) / self.rate


class AdmissionTicket:
    """One admitted request's slot in its tenant's bounded queue.

    ``release()`` is idempotent — the ingress calls it from a ``finally``
    and callbacks may race it."""

    __slots__ = ("_controller", "tenant", "tenant_class", "_released")

    def __init__(self, controller: "AdmissionController", tenant: str, tenant_class: str):
        self._controller = controller
        self.tenant = tenant
        self.tenant_class = tenant_class
        self._released = False

    def release(self) -> None:
        c, self._controller = self._controller, None
        if c is not None and not self._released:
            self._released = True
            c._release(self.tenant)


class AdmissionController:
    """Token-bucket + bounded-queue admission over named tenants."""

    def __init__(
        self,
        policies: dict[str, TenantPolicy] | None = None,
        *,
        default_policy: TenantPolicy | None = None,
        hub: WakeupHub | None = None,
        clock: Any = None,
    ):
        self._lock = threading.Lock()
        self.hub = hub if hub is not None else WakeupHub()
        self._clock = clock if clock is not None else time.monotonic
        self._policies: dict[str, TenantPolicy] = dict(policies or {})
        self._default = default_policy or TenantPolicy()
        self._buckets: dict[str, _TokenBucket] = {}
        self._inflight: dict[str, int] = {}
        self.admitted_total: dict[str, int] = {}
        self.shed_total: dict[str, int] = {}
        #: brownout inputs: pressure level in [0, 1] per source (e.g.
        #: "engine"); the effective level is the max across sources
        self._pressure: dict[str, float] = {}
        #: sheds attributable to brownout (also counted in shed_total)
        self.brownout_shed_total: dict[str, int] = {}
        #: EWMA of ticket-release gaps (seconds) — the measured service
        #: time brownout Retry-After is derived from
        self._drain_ewma_s: float | None = None
        self._last_release_t: float | None = None
        from pathway_tpu import serving as _serving

        _serving._register_admission(self)

    # ------------------------------------------------------------- brownout

    def set_pressure(self, source: str, level: float) -> None:
        """Record a pressure signal in [0, 1]; ``level <= 0`` clears the
        source.  Notifies the hub so parked ``wait_admit`` calls re-check
        (pressure easing may admit them; pressure rising re-derives their
        shed verdict)."""
        level = min(1.0, float(level))
        with self._lock:
            if level <= 0.0:
                if self._pressure.pop(source, None) is None:
                    return
            else:
                self._pressure[source] = level
            # re-arm every bucket: effective rates change with pressure
            self._buckets.clear()
        self.hub.notify()

    def pressure_level(self) -> float:
        with self._lock:
            return max(self._pressure.values(), default=0.0)

    def _brownout_mult_locked(self, pol: TenantPolicy) -> float:
        """Rate multiplier in [0, 1] for this policy under the current
        pressure.  Weight-graded power law: with headroom ``h = 1 -
        level``, a class keeps ``h ** (w_max / w)`` of its rate — the
        heaviest class degrades linearly while lighter (best-effort)
        classes collapse polynomially faster, freeing the drain for
        interactive traffic."""
        if not self._pressure:
            return 1.0
        level = max(self._pressure.values())
        if level >= 1.0:
            return 0.0
        w_max = max(
            [p.weight for p in self._policies.values()]
            + [self._default.weight]
            + list(DEFAULT_CLASS_WEIGHTS.values())
        )
        return (1.0 - level) ** (w_max / max(pol.weight, 0.001))

    def _brownout_retry_after_locked(self) -> float:
        """Retry-After from the measured drain rate: roughly the time to
        drain everything currently in flight, clamped to [0.05, 30]."""
        ewma = self._drain_ewma_s
        if ewma is None:
            ewma = 0.1  # no releases observed yet: conservative default
        backlog = sum(self._inflight.values()) + 1
        return min(max(backlog * ewma, 0.05), 30.0)

    # ------------------------------------------------------------- policies

    def set_policy(self, tenant: str, policy: TenantPolicy) -> None:
        with self._lock:
            self._policies[tenant] = policy
            self._buckets.pop(tenant, None)  # re-arm with the new rate

    def policy(self, tenant: str) -> TenantPolicy:
        with self._lock:
            return self._policies.get(tenant, self._default)

    # ------------------------------------------------------------ admission

    def _admit_locked(
        self, tenant: str, now: float
    ) -> tuple[AdmissionTicket | None, float, str]:
        """(ticket, retry_after_s, reason); ticket None means shed."""
        pol = self._policies.get(tenant, self._default)
        mult = self._brownout_mult_locked(pol)
        if mult < 0.05:
            # this class's share has collapsed: shed outright, with a
            # Retry-After derived from the measured drain rate
            return None, self._brownout_retry_after_locked(), "brownout"
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = _TokenBucket(
                pol.rate_per_s * mult, pol.burst, now
            )
        inflight = self._inflight.get(tenant, 0)
        if inflight >= pol.queue_cap:
            # ETA heuristic: one service turn at the tenant's rate
            return None, max(1.0 / pol.rate_per_s, 0.05), "tenant queue full"
        if not bucket.take(now):
            if mult < 1.0:
                # browned-out rate limit: back off at the DRAIN rate, not
                # the configured token rate the class no longer gets
                return (
                    None,
                    max(bucket.eta(now), self._brownout_retry_after_locked()),
                    "brownout rate limited",
                )
            return None, max(bucket.eta(now), 0.01), "rate limited"
        self._inflight[tenant] = inflight + 1
        cls = pol.tenant_class
        self.admitted_total[cls] = self.admitted_total.get(cls, 0) + 1
        return AdmissionTicket(self, tenant, cls), 0.0, "admitted"

    def admit(self, tenant: str, route: str | None = None) -> AdmissionTicket:
        """Admit one request or raise ``RetryLater`` (counted as shed)."""
        now = self._clock()
        with self._lock:
            ticket, retry_after, reason = self._admit_locked(tenant, now)
            if ticket is None:
                cls = self._policies.get(tenant, self._default).tenant_class
                self.shed_total[cls] = self.shed_total.get(cls, 0) + 1
                if reason.startswith("brownout"):
                    self.brownout_shed_total[cls] = (
                        self.brownout_shed_total.get(cls, 0) + 1
                    )
        if ticket is None:
            suffix = f" ({route})" if route else ""
            raise _retry_later(retry_after, f"{reason}: tenant {tenant!r}{suffix}")
        return ticket

    def try_admit(self, tenant: str, route: str | None = None) -> AdmissionTicket | None:
        """Non-raising probe; a refusal is NOT counted as shed (callers
        like :meth:`wait_admit` retry instead of failing the request)."""
        now = self._clock()
        with self._lock:
            ticket, _, _ = self._admit_locked(tenant, now)
        return ticket

    def wait_admit(
        self, tenant: str, route: str | None = None, timeout: float = 5.0
    ) -> AdmissionTicket:
        """Generation-wait until admitted or ``timeout`` (then sheds).

        Every park is a finite ``hub.wait`` slice: a ticket release (or
        token refill elsewhere) notifies the hub and the admit re-checks
        immediately — no polling sleep, no unbounded block."""
        deadline = self._clock() + max(0.0, timeout)
        while True:
            seen = self.hub.seq()
            ticket = self.try_admit(tenant, route)
            if ticket is not None:
                return ticket
            remaining = deadline - self._clock()
            if remaining <= 0:
                return self.admit(tenant, route)  # counts the shed, raises
            self.hub.wait(seen, min(remaining, 0.05))

    def _release(self, tenant: str) -> None:
        now = self._clock()
        with self._lock:
            n = self._inflight.get(tenant, 0)
            if n > 1:
                self._inflight[tenant] = n - 1
            else:
                self._inflight.pop(tenant, None)
            # drain-rate EWMA over release gaps (capped: an idle stretch
            # is not a slow drain) — feeds brownout Retry-After
            last = self._last_release_t
            self._last_release_t = now
            if last is not None:
                gap = min(max(now - last, 0.0), 5.0)
                ewma = self._drain_ewma_s
                self._drain_ewma_s = (
                    gap if ewma is None else 0.8 * ewma + 0.2 * gap
                )
        self.hub.notify()

    # -------------------------------------------------------------- metrics

    def stats(self) -> dict[str, Any]:
        with self._lock:
            inflight_by_class: dict[str, int] = {}
            for tenant, n in self._inflight.items():
                cls = self._policies.get(tenant, self._default).tenant_class
                inflight_by_class[cls] = inflight_by_class.get(cls, 0) + n
            return {
                "admitted_total": dict(self.admitted_total),
                "shed_total": dict(self.shed_total),
                "inflight": inflight_by_class,
                "tenants": len(self._policies),
                "pressure": {
                    "level": max(self._pressure.values(), default=0.0),
                    "sources": dict(self._pressure),
                    "brownout_shed_total": dict(self.brownout_shed_total),
                    "drain_s": self._drain_ewma_s,
                },
            }
