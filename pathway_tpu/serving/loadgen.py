"""Seedable multi-tenant traffic generator for the serving layer.

Open-loop load (arrivals follow the offered rate, not the service rate,
so queueing delay is *measured* instead of hidden), one pacing thread
per tenant, exponential inter-arrivals from a per-tenant
``numpy.random.default_rng(seed + index)`` — bit-identical schedules
run-to-run.  Mixed read/write: each arrival is a query or (with
``write_fraction``) an upsert into the live index, so the bench load is
the paper's concurrent query+churn regime, not a read-only cache test.

Reports per tenant and per SLO class: offered vs achieved qps, shed
count, and p50/p99 latency.  Shed requests (429 / ``RetryLater``) are
counted, not retried — the point is to see the admission controller
hold the bound.

Pacing waits are ``Event.wait(dt)`` on the generator's stop event
(finite, interruptible — LK006-clean), never bare sleeps.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np

__all__ = ["LoadGen", "TenantLoad", "percentile"]


def percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]); 0.0 on empty input."""
    if not samples:
        return 0.0
    data = sorted(samples)
    rank = max(0, min(len(data) - 1, int(round(q / 100.0 * (len(data) - 1)))))
    return data[rank]


class TenantLoad:
    """One tenant's offered load: ``qps`` arrivals/s for ``duration_s``,
    each a query or (with probability ``write_fraction``) an upsert."""

    __slots__ = ("tenant", "qps", "write_fraction", "queries", "doc_words")

    def __init__(
        self,
        tenant: str,
        qps: float,
        write_fraction: float = 0.0,
        queries: list[str] | None = None,
        doc_words: int = 40,
    ):
        self.tenant = str(tenant)
        self.qps = max(0.01, float(qps))
        self.write_fraction = min(1.0, max(0.0, float(write_fraction)))
        self.queries = list(queries or ["latency tail", "index merge", "device slab"])
        self.doc_words = int(doc_words)


class LoadGen:
    """Drive a :class:`RagServingApp`-shaped target with concurrent
    tenants; ``run()`` blocks until the duration elapses and returns the
    per-tenant / per-class report."""

    def __init__(
        self,
        app: Any,
        tenants: list[TenantLoad],
        *,
        duration_s: float = 2.0,
        seed: int = 0,
        request_timeout_s: float = 30.0,
        submit: Callable[[str, str], Any] | None = None,
    ):
        self.app = app
        self.tenants = list(tenants)
        self.duration_s = float(duration_s)
        self.seed = int(seed)
        self.request_timeout_s = float(request_timeout_s)
        # submit(tenant, query) -> Future; defaults to the in-proc path
        self._submit = submit if submit is not None else app.submit_query
        self._stop = threading.Event()
        self._report_lock = threading.Lock()
        self._lat_ms: dict[str, list[float]] = {}
        self._shed: dict[str, int] = {}
        self._errors: dict[str, int] = {}
        self._sent: dict[str, int] = {}
        self._writes: dict[str, int] = {}

    # ---------------------------------------------------------------- drive

    def _record_latency(self, tenant: str, ms: float) -> None:
        with self._report_lock:
            self._lat_ms.setdefault(tenant, []).append(ms)

    def _bump(self, counter: dict[str, int], tenant: str) -> None:
        with self._report_lock:
            counter[tenant] = counter.get(tenant, 0) + 1

    def _request_done(self, tenant: str, t0: float, fut: Any) -> None:
        exc = fut.exception(timeout=0)
        if exc is None:
            self._record_latency(tenant, (time.monotonic() - t0) * 1e3)
            return
        if getattr(exc, "retry_after", None) is not None:
            self._bump(self._shed, tenant)
        else:
            self._bump(self._errors, tenant)

    def _fire(self, load: TenantLoad, rng: np.random.Generator, n: int) -> None:
        tenant = load.tenant
        if load.write_fraction > 0 and rng.random() < load.write_fraction:
            words = " ".join(
                rng.choice(["alpha", "beta", "gamma", "delta", "tpu", "index"])
                for _ in range(load.doc_words)
            )
            self._bump(self._writes, tenant)
            try:
                self.app.upsert(f"{tenant}-doc-{n}", words, tenant=tenant)
            except Exception:
                self._bump(self._errors, tenant)
            return
        query = load.queries[int(rng.integers(len(load.queries)))]
        self._bump(self._sent, tenant)
        t0 = time.monotonic()
        try:
            fut = self._submit(query, tenant)
        except Exception as e:  # RetryLater sheds at admission
            if getattr(e, "retry_after", None) is not None:
                self._bump(self._shed, tenant)
            else:
                self._bump(self._errors, tenant)
            return
        fut.add_done_callback(lambda f: self._request_done(tenant, t0, f))

    def _tenant_loop(self, idx: int, load: TenantLoad) -> None:
        rng = np.random.default_rng(self.seed + idx)
        deadline = time.monotonic() + self.duration_s
        n = 0
        while not self._stop.is_set():
            dt = float(rng.exponential(1.0 / load.qps))
            if self._stop.wait(timeout=dt):
                break
            if time.monotonic() >= deadline:
                break
            self._fire(load, rng, n)
            n += 1

    def run(self) -> dict[str, Any]:
        threads = [
            threading.Thread(
                target=self._tenant_loop,
                args=(i, load),
                daemon=True,
                name=f"loadgen_{load.tenant}",
            )
            for i, load in enumerate(self.tenants)
        ]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.duration_s + 10.0)
        # wait for in-flight responses to land before reporting
        settle_deadline = time.monotonic() + self.request_timeout_s
        while time.monotonic() < settle_deadline:
            with self._report_lock:
                landed = sum(len(v) for v in self._lat_ms.values())
                outstanding = (
                    sum(self._sent.values())
                    - landed
                    - sum(self._shed.values())
                    - sum(self._errors.values())
                )
            if outstanding <= 0:
                break
            self._stop.wait(timeout=0.05)
        wall_s = max(1e-6, time.monotonic() - t_start)
        return self.report(wall_s)

    def stop(self) -> None:
        self._stop.set()

    # --------------------------------------------------------------- report

    def report(self, wall_s: float) -> dict[str, Any]:
        classes: dict[str, dict[str, Any]] = {}
        per_tenant: dict[str, dict[str, Any]] = {}
        with self._report_lock:
            for load in self.tenants:
                tenant = load.tenant
                cls = self.app.admission.policy(tenant).tenant_class
                lat = self._lat_ms.get(tenant, [])
                row = {
                    "tenant_class": cls,
                    "offered_qps": load.qps,
                    "achieved_qps": len(lat) / wall_s,
                    "sent": self._sent.get(tenant, 0),
                    "completed": len(lat),
                    "shed": self._shed.get(tenant, 0),
                    "errors": self._errors.get(tenant, 0),
                    "writes": self._writes.get(tenant, 0),
                    "p50_ms": percentile(lat, 50),
                    "p99_ms": percentile(lat, 99),
                }
                per_tenant[tenant] = row
                agg = classes.setdefault(
                    cls,
                    {
                        "offered_qps": 0.0,
                        "achieved_qps": 0.0,
                        "sent": 0,
                        "completed": 0,
                        "shed": 0,
                        "errors": 0,
                        "writes": 0,
                        "_lat": [],
                    },
                )
                agg["offered_qps"] += row["offered_qps"]
                agg["achieved_qps"] += row["achieved_qps"]
                agg["sent"] += row["sent"]
                agg["completed"] += row["completed"]
                agg["shed"] += row["shed"]
                agg["errors"] += row["errors"]
                agg["writes"] += row["writes"]
                agg["_lat"].extend(lat)
        for cls, agg in classes.items():
            lat = agg.pop("_lat")
            agg["p50_ms"] = percentile(lat, 50)
            agg["p99_ms"] = percentile(lat, 99)
        return {
            "wall_s": wall_s,
            "seed": self.seed,
            "tenants": per_tenant,
            "classes": classes,
        }
