"""``pathway_tpu.serving`` — multi-tenant RAG serving layer.

Admission control (:mod:`~pathway_tpu.serving.admission`), SLO-class
scheduling (:mod:`~pathway_tpu.serving.scheduler`), stage co-scheduling
with lookahead retrieval (:mod:`~pathway_tpu.serving.coscheduler`), the
composed live-RAG graph (:mod:`~pathway_tpu.serving.graph`), and a
seedable traffic generator (:mod:`~pathway_tpu.serving.loadgen`).

This module is import-light on purpose: the monitoring endpoint calls
:func:`serving_snapshot` on every ``/metrics`` scrape, and the heavy
graph/loadgen modules (which pull in the engine) load lazily.

The module-level registry tracks live serving components (weakly — a
closed app's entries vanish with it) so process-wide monitoring can
aggregate admission counters, scheduler lane stats, and per-tenant-class
latency without holding references that keep dead apps alive.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

from .admission import AdmissionController, AdmissionTicket, TenantPolicy

__all__ = [
    "AdmissionController",
    "AdmissionTicket",
    "TenantPolicy",
    "SloScheduler",
    "StageCoScheduler",
    "RagServingApp",
    "HashingEmbedder",
    "LoadGen",
    "TenantLoad",
    "PartitionedIndex",
    "ShardOwner",
    "ShardHealthTracker",
    "ShardFailoverSupervisor",
    "serving_probe",
    "serving_snapshot",
]

_registry_lock = threading.Lock()
_admissions: "weakref.WeakSet[Any]" = weakref.WeakSet()
_schedulers: "weakref.WeakSet[Any]" = weakref.WeakSet()
_coschedulers: "weakref.WeakSet[Any]" = weakref.WeakSet()
_shard_sets: "weakref.WeakSet[Any]" = weakref.WeakSet()
_probe: Any = None


def _register_admission(obj: Any) -> None:
    with _registry_lock:
        _admissions.add(obj)


def _register_scheduler(obj: Any) -> None:
    with _registry_lock:
        _schedulers.add(obj)


def _register_coscheduler(obj: Any) -> None:
    with _registry_lock:
        _coschedulers.add(obj)


def _register_shard_set(obj: Any) -> None:
    with _registry_lock:
        _shard_sets.add(obj)


def serving_probe() -> Any:
    """The process-wide per-tenant-class latency probe (lazy singleton)."""
    global _probe
    with _registry_lock:
        if _probe is None:
            from pathway_tpu.internals.monitoring import LabeledLatencyProbe

            _probe = LabeledLatencyProbe()
        return _probe


def push_pressure(source: str, level: float) -> None:
    """Propagate an engine pressure signal (0..1) to every live
    :class:`~pathway_tpu.serving.admission.AdmissionController` — the
    brownout actuator.  Called by the scheduler's epoch loop; safe with
    no controllers live (no-op)."""
    with _registry_lock:
        admissions = list(_admissions)
        schedulers = list(_schedulers)
    for a in admissions:
        try:
            a.set_pressure(source, level)
        except Exception:
            pass  # one controller's failure must not starve the rest
    for s in schedulers:
        try:
            s.set_pressure(level)
        except Exception:
            pass


def serving_snapshot() -> dict[str, Any]:
    """Aggregate snapshot across every live serving component: admission
    counters per tenant class, scheduler lane/class stats, co-scheduler
    overlap counters, and the per-(stage, tenant_class) latency
    histograms.  Empty sections mean no component of that kind is live."""
    with _registry_lock:
        admissions = list(_admissions)
        schedulers = list(_schedulers)
        coschedulers = list(_coschedulers)
        shard_sets = list(_shard_sets)
        probe = _probe
    admitted: dict[str, int] = {}
    shed: dict[str, int] = {}
    inflight: dict[str, int] = {}
    brownout_shed: dict[str, int] = {}
    pressure_level = 0.0
    for a in admissions:
        s = a.stats()
        for cls, n in s.get("admitted_total", {}).items():
            admitted[cls] = admitted.get(cls, 0) + n
        for cls, n in s.get("shed_total", {}).items():
            shed[cls] = shed.get(cls, 0) + n
        for cls, n in s.get("inflight", {}).items():
            inflight[cls] = inflight.get(cls, 0) + n
        pr = s.get("pressure", {})
        pressure_level = max(pressure_level, pr.get("level", 0.0))
        for cls, n in pr.get("brownout_shed_total", {}).items():
            brownout_shed[cls] = brownout_shed.get(cls, 0) + n
    out: dict[str, Any] = {}
    if admissions:
        out["admission"] = {
            "admitted_total": admitted,
            "shed_total": shed,
            "inflight": inflight,
            "pressure_level": pressure_level,
            "brownout_shed_total": brownout_shed,
        }
    if schedulers:
        out["schedulers"] = [s.stats() for s in schedulers]
    if coschedulers:
        out["coschedulers"] = [c.stats() for c in coschedulers]
    if shard_sets:
        # degraded-mode aggregate across every live partitioned index:
        # total/healthy shard counts, degraded responses, and the
        # failover-seconds histogram (summed counts, worst-case maxima)
        shards_total = shards_healthy = degraded = failovers = 0
        hists = []
        for p in shard_sets:
            s = p.stats()
            shards_total += s.get("shards_total", 0)
            shards_healthy += s.get("shards_healthy", 0)
            degraded += s.get("degraded_responses", 0)
            failovers += s.get("failovers_total", 0)
            h = s.get("failover_seconds")
            if h:
                hists.append(h)
        failover_s: dict[str, Any] = {}
        if hists:
            failover_s = {
                "count": sum(h.get("count", 0) for h in hists),
                "sum_ns": sum(h.get("sum_ns", 0) for h in hists),
                "max_ns": max(h.get("max_ns", 0) for h in hists),
                "p50_ns": max(h.get("p50_ns", 0) for h in hists),
                "p95_ns": max(h.get("p95_ns", 0) for h in hists),
                "p99_ns": max(h.get("p99_ns", 0) for h in hists),
            }
        out["failover"] = {
            "shards_total": shards_total,
            "shards_healthy": shards_healthy,
            "degraded_responses_total": degraded,
            "failovers_total": failovers,
            "failover_seconds": failover_s,
        }
    if probe is not None:
        lat = probe.snapshot()
        if lat:
            out["latency"] = lat
    return out


def __getattr__(name: str) -> Any:
    if name == "SloScheduler":
        from .scheduler import SloScheduler

        return SloScheduler
    if name in ("StageCoScheduler", "extractive_answerer"):
        from . import coscheduler as _m

        return getattr(_m, name)
    if name in ("RagServingApp", "HashingEmbedder", "simple_splitter"):
        from . import graph as _m

        return getattr(_m, name)
    if name in ("LoadGen", "TenantLoad", "percentile"):
        from . import loadgen as _m

        return getattr(_m, name)
    if name in (
        "PartitionedIndex",
        "ShardOwner",
        "ShardHealthTracker",
        "ShardFailoverSupervisor",
    ):
        from . import failover as _m

        return getattr(_m, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
