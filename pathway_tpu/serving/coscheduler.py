"""Stage co-scheduler: overlap retrieval with generation (lookahead).

A lockstep RAG pipeline runs embed → retrieve → generate as barriers:
the index sits idle while the generator works and vice versa.  TeleRAG
(PAPERS.md) shows the win from *lookahead retrieval* — fire the index
probe speculatively as soon as the query embedding exists, while the
generation stage of the previous request is still busy; HedraRAG makes
the general case: co-schedule heterogeneous RAG stages instead of
serializing them.  :class:`StageCoScheduler` implements that shape:

- **embed** runs on the SLO scheduler's ``embed`` lane (coalescable, so
  concurrent queries share one batched embedding call);
- **retrieve** runs on the ``search`` lane and only *dispatches* the
  probe (:meth:`SegmentedIndex.dispatch` — an async device launch), then
  parks the request in the generation queue.  The probe is in flight on
  the device while the request waits behind the previous generation —
  that wait is the overlap the lookahead buys;
- **generate** runs on a dedicated worker thread (modeling the
  generation stream): it *collects* the already-running probe, reranks,
  and answers.

Every queue handoff is WakeupHub-notified with finite waits (LK006);
per-request latencies land in the serving
:class:`~pathway_tpu.internals.monitoring.LabeledLatencyProbe` under the
request's tenant class.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable

from pathway_tpu.internals import tracing as _tracing

from .scheduler import SloScheduler

__all__ = ["StageCoScheduler", "extractive_answerer"]


def extractive_answerer(query: str, docs: list[dict]) -> str:
    """Dependency-free default generator: extractive answer from the top
    retrieved chunk (keeps the serving pipeline runnable without an LLM)."""
    if not docs:
        return f"no context found for: {query}"
    top = docs[0]
    return f"[{top.get('id')}] {str(top.get('text', ''))[:240]}"


class _Req:
    __slots__ = (
        "query",
        "k",
        "tenant_class",
        "future",
        "t0_ns",
        "t_embed_ns",
        "t_dispatch_ns",
        "t_dispatch_done_ns",
        "t_collect_ns",
        "t_collect_done_ns",
        "t_genq_ns",
        "payload",
        "coverage",
        "trace",
    )

    def __init__(
        self,
        query: str,
        k: int,
        tenant_class: str,
        future: Future,
        t0_ns: int,
        trace: Any = None,
    ):
        self.query = query
        self.k = k
        self.tenant_class = tenant_class
        self.future = future
        self.t0_ns = t0_ns
        self.t_embed_ns = 0
        self.t_dispatch_ns = 0
        self.t_dispatch_done_ns = 0
        self.t_collect_ns = 0
        self.t_collect_done_ns = 0
        self.t_genq_ns = 0
        self.payload: Any = None
        # (partial, shards_answered, shards_total) — the partial-result
        # contract, read off the index probe handle after collect
        self.coverage: tuple[bool, int, int] = (False, 1, 1)
        #: the request's TraceContext, born at admission and carried
        #: through every stage hop (threads change; the context doesn't)
        self.trace = trace


class StageCoScheduler:
    """embed → (speculative retrieve) → generate, stages overlapped."""

    def __init__(
        self,
        *,
        embedder: Callable[[str], Any],
        index: Any,
        doc_text: Callable[[Any], str] | None = None,
        answerer: Callable[[str, list[dict]], str] | None = None,
        scheduler: SloScheduler | None = None,
        probe: Any = None,
        k: int = 4,
        lookahead: bool = True,
        gen_queue_cap: int = 1024,
        idle_wait_s: float = 0.05,
    ):
        self.embedder = embedder
        self.index = index
        self.doc_text = doc_text or (lambda key: str(key))
        self.answerer = answerer or extractive_answerer
        self.probe = probe
        self.default_k = max(1, int(k))
        self.lookahead = bool(lookahead)
        self.gen_queue_cap = max(1, int(gen_queue_cap))
        self._idle_wait_s = idle_wait_s
        self.scheduler = scheduler if scheduler is not None else SloScheduler(probe=probe)
        self.hub = self.scheduler.hub
        self._gen_q: deque[_Req] = deque()  # lk009: capped at gen_queue_cap
        self._gen_lock = threading.Lock()
        self._stop = threading.Event()
        # lookahead accounting: how often the probe was already in
        # flight when generation picked the request up, and for how long
        self.lookahead_probes = 0
        self.overlap_ns_total = 0
        self.completed = 0
        self.failed = 0
        #: responses served with partial shard coverage (degraded, not
        #: failed — the partial-result contract)
        self.degraded_responses = 0
        self._gen_thread = threading.Thread(
            target=self._gen_loop, daemon=True, name="serving_generate"
        )
        self._gen_thread.start()
        from pathway_tpu import serving as _serving

        _serving._register_coscheduler(self)

    # -------------------------------------------------------------- submit

    def submit(
        self,
        query: str,
        tenant_class: str = "interactive",
        k: int | None = None,
        trace: Any = None,
    ) -> Future:
        """Returns a Future resolving to ``{"answer", "docs", ...}``.
        ``trace`` continues the caller's trace (the admission layer's);
        without one a fresh trace is opened so every response carries a
        ``trace_id``."""
        fut: Future = Future()
        if trace is None:
            trace = _tracing.new_trace()
        req = _Req(
            str(query),
            k if k is not None else self.default_k,
            tenant_class,
            fut,
            time.monotonic_ns(),
            trace,
        )
        efut = self.scheduler.submit(
            "embed", tenant_class, self._embed_batch, item=req.query,
            coalesce="query_embed", trace=trace,
        )
        efut.add_done_callback(lambda f: self._after_embed(f, req))
        return fut

    def _embed_batch(self, queries: list[str]) -> list[Any]:
        return [self.embedder(q) for q in queries]

    def _after_embed(self, efut: Future, req: _Req) -> None:
        exc = efut.exception(timeout=0)
        if exc is not None:
            self._fail(req, exc)
            return
        req.t_embed_ns = time.monotonic_ns()
        if self.probe is not None:
            self.probe.record(
                "serve_embed", req.tenant_class, req.t_embed_ns - req.t0_ns
            )
        vec = efut.result(timeout=0)
        rfut = self.scheduler.submit(
            "search", req.tenant_class, self._retrieve, item=(req, vec),
            trace=req.trace,
        )
        rfut.add_done_callback(lambda f: self._after_retrieve(f, req))

    def _retrieve(self, req_vec: tuple[_Req, Any]) -> Any:
        """Search-lane stage: fire the probe, do NOT wait for results."""
        req, vec = req_vec
        dispatch = getattr(self.index, "dispatch", None)
        if self.lookahead and dispatch is not None:
            req.t_dispatch_ns = time.monotonic_ns()
            handle = dispatch(vec, req.k)
            req.t_dispatch_done_ns = time.monotonic_ns()
            return ("handle", handle)
        req.t_dispatch_ns = time.monotonic_ns()
        hits = self.index.search(vec, req.k)
        req.t_dispatch_done_ns = time.monotonic_ns()
        return ("hits", hits)

    def _after_retrieve(self, rfut: Future, req: _Req) -> None:
        exc = rfut.exception(timeout=0)
        if exc is not None:
            self._fail(req, exc)
            return
        req.payload = rfut.result(timeout=0)
        req.t_genq_ns = time.monotonic_ns()
        overflow = False
        with self._gen_lock:
            if len(self._gen_q) >= self.gen_queue_cap:
                overflow = True
            else:
                self._gen_q.append(req)
        if overflow:
            # bounded handoff even past admission (belt and suspenders):
            # fail loudly instead of buffering without limit
            self._fail(req, RuntimeError("generation queue full"))
            return
        self.hub.notify()

    # ------------------------------------------------------------ generate

    def _gen_loop(self) -> None:
        while not self._stop.is_set():
            seen = self.hub.seq()
            with self._gen_lock:
                req = self._gen_q.popleft() if self._gen_q else None
            if req is None:
                self.hub.wait(seen, self._idle_wait_s)
                continue
            self._generate(req)

    def _resolve_hits(self, req: _Req) -> list[tuple[Any, float]]:
        kind, value = req.payload
        if kind == "hits":
            return value[0] if value else []
        t_collect = req.t_collect_ns = time.monotonic_ns()
        # ambient for the index's own spans (collect_segments /
        # collect_shard parent onto the request trace, not trace 0)
        prev_ctx = _tracing.set_ambient(req.trace)
        try:
            hits = self.index.collect(value)
        finally:
            _tracing.set_ambient(prev_ctx)
        req.t_collect_done_ns = time.monotonic_ns()
        # the probe handle carries shard coverage after collect (identity
        # 1/1 for a single index; real health for a PartitionedIndex)
        req.coverage = (
            bool(getattr(value, "partial", False)),
            int(getattr(value, "shards_answered", 1)),
            int(getattr(value, "shards_total", 1)),
        )
        if req.t_dispatch_ns:
            self.lookahead_probes += 1
            self.overlap_ns_total += t_collect - req.t_dispatch_ns
        return hits[0] if hits else []

    def _generate(self, req: _Req) -> None:
        try:
            t_hits_start = req.t_embed_ns or req.t0_ns
            t_pick = time.monotonic_ns()
            hits = self._resolve_hits(req)
            t_hits = time.monotonic_ns()
            docs = [
                {"id": key, "score": float(score), "text": self.doc_text(key)}
                for key, score in hits
            ]
            t_gen = time.monotonic_ns()
            answer = self.answerer(req.query, docs)
            t_done = time.monotonic_ns()
            if self.probe is not None:
                cls = req.tenant_class
                self.probe.record("serve_retrieve", cls, t_hits - t_hits_start)
                self.probe.record("serve_generate", cls, t_done - t_hits)
                self.probe.record("serve_e2e", cls, t_done - req.t0_ns)
            self.completed += 1
            partial, answered, total = req.coverage
            if partial:
                self.degraded_responses += 1
            if _tracing.enabled():
                # materialize the whole request's spans in ONE call from
                # the timestamps stamped along the way — per-stage record
                # calls are measurable at this request rate
                spans = []
                if req.t_embed_ns:
                    spans.append(("serve_embed", req.t0_ns, req.t_embed_ns, None))
                if req.t_dispatch_done_ns:
                    stage = "dispatch" if req.payload[0] == "handle" else "search"
                    spans.append(
                        (stage, req.t_dispatch_ns, req.t_dispatch_done_ns, None)
                    )
                if req.t_collect_done_ns:
                    spans.append(
                        ("collect", req.t_collect_ns, req.t_collect_done_ns, None)
                    )
                if req.t_genq_ns:
                    # time parked in the generation queue behind the
                    # previous request — queue-wait, not service time
                    spans.append(("gen_queue_wait", req.t_genq_ns, t_pick, None))
                spans.append(("generate", t_gen, t_done, None))
                # the whole request as one root-level span, then
                # tail-keep: a request over the tail threshold survives
                # head sampling
                spans.append(
                    ("serve_e2e", req.t0_ns, t_done,
                     {"class": req.tenant_class})
                )
                _tracing.record_spans(req.trace, spans)
                _tracing.finish_request(req.trace, t_done)
            if not req.future.done():
                req.future.set_result(
                    {
                        "answer": answer,
                        "docs": docs,
                        "tenant_class": req.tenant_class,
                        "latency_ms": (t_done - req.t0_ns) / 1e6,
                        # partial-result contract: a response over a
                        # degraded corpus says so instead of erroring
                        "partial": partial,
                        "shards_answered": answered,
                        "shards_total": total,
                        # the causal timeline's key: look this id up in a
                        # flight-recorder dump / /debug/trace export
                        "trace_id": (
                            req.trace.trace_id if req.trace is not None else 0
                        ),
                    }
                )
        except BaseException as e:  # noqa: BLE001 — fault goes to the caller
            self._fail(req, e)

    def _fail(self, req: _Req, exc: BaseException) -> None:
        self.failed += 1
        if not req.future.done():
            req.future.set_exception(exc)

    # --------------------------------------------------------------- admin

    def stats(self) -> dict[str, Any]:
        with self._gen_lock:
            queued = len(self._gen_q)
        n = max(1, self.lookahead_probes)
        return {
            "completed": self.completed,
            "failed": self.failed,
            "degraded_responses": self.degraded_responses,
            "gen_queued": queued,
            "lookahead_probes": self.lookahead_probes,
            "overlap_ms_total": self.overlap_ns_total / 1e6,
            "overlap_ms_mean": self.overlap_ns_total / n / 1e6,
        }

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self.hub.notify()
        self._gen_thread.join(timeout)
        with self._gen_lock:
            leftovers = list(self._gen_q)
            self._gen_q.clear()
        for req in leftovers:
            self._fail(req, RuntimeError("coscheduler closed"))
