"""Shard health, degraded serving, and snapshot-backed shard failover.

A single :class:`~pathway_tpu.stdlib.indexing.segments.SegmentedIndex`
is one fail domain: when its owner dies, every query dies with it.  This
module partitions the corpus across N shard owners and makes the loss of
one owner a *degradation* instead of an outage (ISSUE 13; HedraRAG's
stage-isolation argument, EdgeRAG's recompute-on-miss-as-degraded-path):

- :class:`ShardHealthTracker` — per-shard ``alive``/``suspect``/``dead``
  states, mirroring the cluster membership states in
  :mod:`pathway_tpu.engine.cluster`.  Failures promote (a configurable
  streak marks dead), successes demote, so one slow collect doesn't
  blacklist a shard forever.
- :class:`ShardOwner` — one shard's index plus its recovery machinery:
  a monotonic per-shard oplog and a periodic segment snapshot
  (``{"seq", "state"}``).  :meth:`ShardOwner.restore` rebuilds the shard
  from the snapshot and replays the oplog tail ``seq > snapshot_seq``
  **exactly once** (ops are uniquely sequenced; the snapshot records the
  high-water mark), then bumps the owner's ``incarnation`` — the
  generation handshake that lets in-flight probes detect they raced a
  restore.
- :class:`PartitionedIndex` — routes upserts by
  ``stable_shard(key) % n_shards`` and fans every query out to all
  shards.  Probes to dead shards are skipped or served from the
  snapshot-backed **standby** (stale up to one snapshot window, and
  therefore *not* authoritative); probes to suspect shards are hedged:
  collected on a side thread with a timeout, falling back to the standby
  if the owner doesn't answer in time.  The merged response carries the
  partial-result contract — ``partial: true`` with
  ``shards_answered``/``shards_total`` — instead of erroring, so the
  serving pipeline keeps answering at full speed on the healthy fraction
  of the corpus.
- :class:`ShardFailoverSupervisor` — background monitor that notices a
  dead shard and restores it (optionally paced through an SLO-scheduler
  ``recover`` lane so restore work cannot starve live queries),
  recording detection→restored wall time in the failover histogram.

The partial-result contract (documented in README "Degraded operation &
failover"): ``shards_answered`` counts **authoritative** owners only —
a standby-served shard keeps ``partial: true`` until its owner is
restored, because the standby may be stale by up to one snapshot window.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.engine.cluster import (
    PEER_ALIVE,
    PEER_DEAD,
    PEER_SUSPECT,
    stable_shard,
)
from pathway_tpu.internals import tracing as _tracing
from pathway_tpu.internals.monitoring import _PyHist

__all__ = [
    "ShardHealthTracker",
    "ShardOwner",
    "PartitionedIndex",
    "ShardFailoverSupervisor",
]


class ShardHealthTracker:
    """Per-shard health states with streak-based promotion.

    ``record_failure`` moves ``alive -> suspect`` immediately and
    ``suspect -> dead`` after ``dead_after`` consecutive failures; any
    ``record_success`` resets the streak and demotes ``suspect`` back to
    ``alive``.  ``dead`` is sticky: only :meth:`revive` (called by the
    failover path after a successful restore) clears it, so a dead shard
    cannot flap back into the query path half-recovered."""

    def __init__(self, n_shards: int, *, dead_after: int = 2):
        self.n_shards = int(n_shards)
        self.dead_after = max(1, int(dead_after))
        self._lock = threading.Lock()
        self._state = {i: PEER_ALIVE for i in range(self.n_shards)}
        self._streak = {i: 0 for i in range(self.n_shards)}
        self._reason: dict[int, str | None] = {}

    def state(self, shard_id: int) -> str:
        with self._lock:
            return self._state[shard_id]

    def states(self) -> dict[int, str]:
        with self._lock:
            return dict(self._state)

    def record_failure(self, shard_id: int, reason: str | None = None) -> str:
        with self._lock:
            if self._state[shard_id] == PEER_DEAD:
                return PEER_DEAD
            self._streak[shard_id] += 1
            if self._streak[shard_id] >= self.dead_after:
                self._state[shard_id] = PEER_DEAD
                self._reason[shard_id] = reason
            else:
                self._state[shard_id] = PEER_SUSPECT
            return self._state[shard_id]

    def record_success(self, shard_id: int) -> None:
        with self._lock:
            self._streak[shard_id] = 0
            if self._state[shard_id] == PEER_SUSPECT:
                self._state[shard_id] = PEER_ALIVE

    def mark_dead(self, shard_id: int, reason: str | None = None) -> None:
        with self._lock:
            self._state[shard_id] = PEER_DEAD
            self._streak[shard_id] = self.dead_after
            self._reason[shard_id] = reason

    def mark_suspect(self, shard_id: int) -> None:
        with self._lock:
            if self._state[shard_id] == PEER_ALIVE:
                self._state[shard_id] = PEER_SUSPECT

    def revive(self, shard_id: int) -> None:
        with self._lock:
            self._state[shard_id] = PEER_ALIVE
            self._streak[shard_id] = 0
            self._reason.pop(shard_id, None)

    def dead_shards(self) -> list[int]:
        with self._lock:
            return sorted(
                i for i, s in self._state.items() if s == PEER_DEAD
            )

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._state.values() if s != PEER_DEAD)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "states": dict(self._state),
                "reasons": {
                    i: r for i, r in self._reason.items() if r is not None
                },
            }


class ShardOwner:
    """One shard's index plus its snapshot/oplog recovery machinery.

    Every mutation is sequenced into the oplog *before* it is applied,
    and a snapshot (``{"seq", "state"}``) is cut every
    ``snapshot_every`` ops — the pair is exactly PR 9's
    snapshot-plus-offset-tail recovery contract, applied per shard.
    :meth:`kill` simulates the owner dying (the live index is dropped —
    there is nothing to limp along on); :meth:`restore` builds a fresh
    index from the factory, loads the snapshot, and replays the tail
    ``seq > snapshot_seq`` exactly once, then bumps ``incarnation``."""

    def __init__(
        self,
        shard_id: int,
        index_factory: Callable[[], Any],
        *,
        snapshot_every: int = 256,
    ):
        self.shard_id = int(shard_id)
        self.index_factory = index_factory
        self.index: Any = index_factory()
        self.snapshot_every = max(1, int(snapshot_every))
        self.incarnation = 0
        self.alive = True
        self.tail_replayed = 0
        self.restores_total = 0
        self._lock = threading.RLock()
        self._seq = 0
        self._snapshot: dict[str, Any] | None = None
        self._snapshot_seq = 0
        # [(seq, op, key, vec-or-None)] — ops since the last snapshot
        self._oplog: list[tuple[int, str, Any, Any]] = []
        self._standby: Any = None  # lazy snapshot-backed read replica

    # ---------------------------------------------------------- mutation

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        if not items:
            return
        with self._lock:
            prepared = []
            for key, vec in items:
                vec = np.asarray(vec, np.float32)
                self._seq += 1
                self._oplog.append((self._seq, "add", key, vec))
                prepared.append((key, vec))
            if self.alive:
                self.index.add(prepared)
            self._maybe_snapshot_locked()

    def remove(self, keys: Sequence[Any]) -> None:
        if not keys:
            return
        with self._lock:
            for key in keys:
                self._seq += 1
                self._oplog.append((self._seq, "remove", key, None))
            if self.alive:
                self.index.remove(list(keys))
            self._maybe_snapshot_locked()

    def _maybe_snapshot_locked(self) -> None:
        if not self.alive:
            return
        if self._seq - self._snapshot_seq >= self.snapshot_every:
            self.snapshot_now()

    def snapshot_now(self) -> None:
        """Cut a snapshot at the current high-water mark and trim the
        oplog below it — the tail that remains is exactly what a restore
        must replay."""
        with self._lock:
            if not self.alive:
                return
            self._snapshot = {
                "seq": self._seq,
                "state": self.index.state_dict(),
            }
            self._snapshot_seq = self._seq
            self._oplog = [
                op for op in self._oplog if op[0] > self._snapshot_seq
            ]
            self._standby = None  # stale: rebuilt lazily from new snapshot

    # ----------------------------------------------------------- failure

    def kill(self) -> None:
        """Simulate the shard owner dying: the live index is gone.  The
        snapshot and oplog survive (they model durable state — PR 9's
        segment snapshot plus the connector offset tail)."""
        with self._lock:
            self.alive = False
            self.index = None

    def restore(self) -> float:
        """Rebuild from the snapshot + exactly-once oplog tail replay.

        Returns wall seconds spent restoring.  Idempotent: restoring an
        already-alive owner is a no-op returning 0.  Each oplog entry is
        applied at most once because entries are uniquely sequenced and
        the replay window is strictly ``seq > snapshot_seq``."""
        with self._lock:
            if self.alive:
                return 0.0
            t0 = time.monotonic()
            index = self.index_factory()
            if self._snapshot is not None:
                index.load_state_dict(self._snapshot["state"])
            tail = [op for op in self._oplog if op[0] > self._snapshot_seq]
            adds: list[tuple[Any, Any]] = []
            for _seq, op, key, vec in tail:
                if op == "add":
                    adds.append((key, vec))
                else:
                    if adds:
                        index.add(adds)
                        adds = []
                    index.remove([key])
            if adds:
                index.add(adds)
            self.tail_replayed += len(tail)
            self.index = index
            self.alive = True
            self.restores_total += 1
            # the generation handshake: in-flight probes dispatched
            # against the dead incarnation detect the mismatch at
            # collect time and re-search the restored index
            self.incarnation += 1
            return time.monotonic() - t0

    # ------------------------------------------------------------ search

    def dispatch(self, queries: np.ndarray, k: int) -> Any:
        with self._lock:
            if not self.alive:
                raise RuntimeError(f"shard {self.shard_id} owner dead")
            return self.index.dispatch(queries, k)

    def collect(self, handle: Any) -> list[list[tuple[Any, float]]]:
        with self._lock:
            if not self.alive:
                raise RuntimeError(f"shard {self.shard_id} owner dead")
            index = self.index
        return index.collect(handle)

    def search(self, queries: np.ndarray, k: int) -> list:
        with self._lock:
            if not self.alive:
                raise RuntimeError(f"shard {self.shard_id} owner dead")
            index = self.index
        return index.search(queries, k)

    def standby_search(self, queries: np.ndarray, k: int) -> list | None:
        """Serve from the snapshot-backed standby (stale by up to one
        snapshot window — the caller must keep the response marked
        partial).  Returns None when no snapshot exists yet."""
        with self._lock:
            if self._snapshot is None:
                return None
            if self._standby is None:
                standby = self.index_factory()
                standby.load_state_dict(self._snapshot["state"])
                self._standby = standby
            standby = self._standby
        return standby.search(queries, k)

    def __len__(self) -> int:
        with self._lock:
            return len(self.index) if self.alive else 0

    def keys(self) -> list:
        with self._lock:
            if not self.alive:
                return []
            keys = self.index.keys
            return list(keys() if callable(keys) else keys)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "shard_id": self.shard_id,
                "alive": self.alive,
                "size": len(self.index) if self.alive else 0,
                "incarnation": self.incarnation,
                "seq": self._seq,
                "snapshot_seq": self._snapshot_seq,
                "oplog_tail": len(
                    [op for op in self._oplog if op[0] > self._snapshot_seq]
                ),
                "tail_replayed": self.tail_replayed,
                "restores_total": self.restores_total,
            }


class _PartProbe:
    """In-flight partitioned search: one entry per shard, plus the
    coverage fields stamped by :meth:`PartitionedIndex.collect` —
    :class:`~pathway_tpu.serving.coscheduler.StageCoScheduler` reads them
    off the handle to build the partial-result contract."""

    __slots__ = (
        "queries",
        "k",
        "entries",
        "partial",
        "shards_answered",
        "shards_total",
        "shards_standby",
    )

    def __init__(self, queries: np.ndarray, k: int, entries: list):
        self.queries = queries
        self.k = k
        self.entries = entries
        self.partial = False
        self.shards_answered = 0
        self.shards_total = len(entries)
        self.shards_standby = 0


class PartitionedIndex:
    """N shard owners behind one ``(key, vector)`` index facade.

    Routing is ``stable_shard(key) % n_shards`` (process-stable, so the
    same key always lands on the same shard across restarts).  Queries
    fan out to every shard; per-shard failures degrade the response
    instead of failing it — see the module docstring for the contract.
    """

    def __init__(
        self,
        index_factory: Callable[[], Any],
        n_shards: int = 2,
        *,
        snapshot_every: int = 256,
        hedge_timeout_s: float = 0.25,
        standby: bool = True,
        dead_after: int = 2,
        health: ShardHealthTracker | None = None,
    ):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.hedge_timeout_s = float(hedge_timeout_s)
        self.standby = bool(standby)
        self.owners = [
            ShardOwner(i, index_factory, snapshot_every=snapshot_every)
            for i in range(self.n_shards)
        ]
        self.health = (
            health
            if health is not None
            else ShardHealthTracker(self.n_shards, dead_after=dead_after)
        )
        self._lock = threading.Lock()
        self.degraded_responses = 0
        self.failovers_total = 0
        self.probes_recovered = 0
        self.standby_serves = 0
        #: detection→restored wall time per failover (ns buckets)
        self.failover_hist = _PyHist()
        from pathway_tpu import serving as _serving

        _serving._register_shard_set(self)

    # ----------------------------------------------------------- routing

    def _route(self, key: Any) -> int:
        return stable_shard(key) % self.n_shards

    def add(self, items: Sequence[tuple[Any, Any]]) -> None:
        by_shard: dict[int, list] = {}
        for key, vec in items:
            by_shard.setdefault(self._route(key), []).append((key, vec))
        for sid, part in by_shard.items():
            self.owners[sid].add(part)

    def remove(self, keys: Sequence[Any]) -> None:
        by_shard: dict[int, list] = {}
        for key in keys:
            by_shard.setdefault(self._route(key), []).append(key)
        for sid, part in by_shard.items():
            self.owners[sid].remove(part)

    def __len__(self) -> int:
        return sum(len(o) for o in self.owners)

    def keys(self) -> list:
        out: list = []
        for o in self.owners:
            out.extend(o.keys())
        return out

    @property
    def has_standby(self) -> bool:
        return self.standby

    # ------------------------------------------------------------ search

    def dispatch(self, queries: np.ndarray, k: int) -> _PartProbe:
        """Fan the probe out to every shard whose owner might answer.

        Dead shards get a ``standby``/``skip`` entry up front (no wasted
        dispatch); a dispatch failure on a live shard records against its
        health and degrades to the standby path for this probe."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        entries: list[tuple] = []
        for sid, owner in enumerate(self.owners):
            if self.health.state(sid) == PEER_DEAD:
                entries.append(("standby" if self.standby else "skip", sid))
                continue
            try:
                with _tracing.span("dispatch_shard", {"shard": sid}):
                    handle = owner.dispatch(queries, k)
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                self.health.record_failure(sid, repr(e))
                entries.append(("standby" if self.standby else "skip", sid))
                continue
            entries.append(("handle", sid, owner.incarnation, handle))
        return _PartProbe(queries, k, entries)

    def _collect_one(
        self, sid: int, incarnation: int, handle: Any, probe: _PartProbe
    ) -> list | None:
        """Collect one shard's probe; None means this shard contributed
        nothing authoritative (caller decides on standby)."""
        owner = self.owners[sid]
        if owner.incarnation != incarnation:
            # the owner was restored while the probe was in flight: the
            # handle belongs to the dead incarnation — re-search the
            # restored index (authoritative) instead of trusting it
            try:
                hits = owner.search(probe.queries, probe.k)
            except Exception as e:  # noqa: BLE001
                self.health.record_failure(sid, repr(e))
                return None
            with self._lock:
                self.probes_recovered += 1
            self.health.record_success(sid)
            return hits
        if self.health.state(sid) == PEER_SUSPECT:
            # hedged collect: a suspect owner gets one bounded chance
            result: dict[str, Any] = {}

            def _run() -> None:
                try:
                    result["hits"] = owner.collect(handle)
                except Exception as e:  # noqa: BLE001
                    result["exc"] = e

            t = threading.Thread(
                target=_run, daemon=True, name=f"pw-hedge-collect-{sid}"
            )
            t.start()
            t.join(self.hedge_timeout_s)
            if t.is_alive() or "exc" in result:
                reason = repr(result.get("exc", "hedge timeout"))
                self.health.record_failure(sid, reason)
                return None
            self.health.record_success(sid)
            return result["hits"]
        try:
            t0 = time.monotonic()
            hits = owner.collect(handle)
            if time.monotonic() - t0 > self.hedge_timeout_s:
                # answered, but slow: flag for hedging next time
                self.health.mark_suspect(sid)
            else:
                self.health.record_success(sid)
            return hits
        except Exception as e:  # noqa: BLE001
            self.health.record_failure(sid, repr(e))
            return None

    def collect(self, probe: _PartProbe) -> list[list[tuple[Any, float]]]:
        """Resolve the fan-out: merge per-shard top-k into global top-k
        and stamp the coverage fields on the handle.  A shard that fails
        at collect degrades to its standby (when enabled) — the response
        is marked partial, never an exception."""
        n_q = probe.queries.shape[0]
        per_query: list[list[tuple[Any, float]]] = [[] for _ in range(n_q)]
        answered = 0
        standby_served = 0
        for entry in probe.entries:
            if entry[0] == "handle":
                _tag, sid, incarnation, handle = entry
                with _tracing.span("collect_shard", {"shard": sid}):
                    hits = self._collect_one(sid, incarnation, handle, probe)
                if hits is not None:
                    answered += 1
                    for qi in range(n_q):
                        per_query[qi].extend(hits[qi])
                    continue
                # fall through to standby for this shard
            sid = entry[1]
            if self.standby:
                hits = self.owners[sid].standby_search(
                    probe.queries, probe.k
                )
                if hits is not None:
                    standby_served += 1
                    for qi in range(n_q):
                        per_query[qi].extend(hits[qi])
        out = []
        for qi in range(n_q):
            merged = per_query[qi]
            merged.sort(key=lambda kv: (-kv[1], str(kv[0])))
            out.append(merged[: probe.k])
        probe.shards_answered = answered
        probe.shards_standby = standby_served
        probe.partial = answered < probe.shards_total
        if probe.partial:
            with self._lock:
                self.degraded_responses += 1
                self.standby_serves += standby_served
        return out

    def search(self, queries: np.ndarray, k: int) -> list:
        return self.collect(self.dispatch(queries, k))

    # ----------------------------------------------------------- failover

    def fail_shard(self, shard_id: int, reason: str = "killed") -> None:
        """Kill one shard owner (chaos/test API): the live index drops,
        health goes dead, queries degrade immediately."""
        self.owners[shard_id].kill()
        self.health.mark_dead(shard_id, reason)

    def recover_shard(self, shard_id: int, detected_at: float | None = None) -> float:
        """Restore a dead shard from snapshot + exactly-once tail replay
        and put it back in the query path.  Returns failover seconds
        (detection→restored when ``detected_at`` is given, else restore
        time alone) and records it in the failover histogram."""
        t_detect = detected_at if detected_at is not None else time.monotonic()
        self.owners[shard_id].restore()
        self.health.revive(shard_id)
        elapsed = time.monotonic() - t_detect
        with self._lock:
            self.failovers_total += 1
        self.failover_hist.record(int(elapsed * 1e9))
        return elapsed

    # ------------------------------------------------------- persistence

    def state_dict(self) -> dict:
        return {
            "kind": "partitioned",
            "shards": [o.index.state_dict() for o in self.owners],
        }

    def load_state_dict(self, state: dict) -> None:
        shards = state["shards"]
        if len(shards) != self.n_shards:
            raise ValueError(
                f"shard count mismatch: state has {len(shards)}, "
                f"index has {self.n_shards}"
            )
        for owner, sub in zip(self.owners, shards):
            owner.index.load_state_dict(sub)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            degraded = self.degraded_responses
            failovers = self.failovers_total
            recovered = self.probes_recovered
            standby_serves = self.standby_serves
        return {
            "shards_total": self.n_shards,
            "shards_healthy": self.health.healthy_count(),
            "health": self.health.states(),
            "degraded_responses": degraded,
            "failovers_total": failovers,
            "probes_recovered": recovered,
            "standby_serves": standby_serves,
            "failover_seconds": self.failover_hist.snapshot(),
            "shards": [o.stats() for o in self.owners],
        }

    def close(self) -> None:
        for o in self.owners:
            index = o.index
            close = getattr(index, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass


class ShardFailoverSupervisor:
    """Background monitor: notices dead shards and restores them.

    The restore can be paced through an SLO-scheduler lane (default
    ``recover``) so recovery work shares device time under the same
    fairness discipline as live queries instead of stealing it; without
    a scheduler it runs inline on the monitor thread.  Detection→restored
    wall time lands in the partitioned index's failover histogram."""

    def __init__(
        self,
        part: PartitionedIndex,
        *,
        poll_interval_s: float = 0.05,
        scheduler: Any = None,
        lane: str = "recover",
    ):
        self.part = part
        self.poll_interval_s = float(poll_interval_s)
        self.scheduler = scheduler
        self.lane = lane
        if scheduler is not None:
            ensure = getattr(scheduler, "ensure_lane", None)
            if ensure is not None:
                ensure(lane, share=0.25)
        self._stopped = threading.Event()
        self._inflight: set[int] = set()
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pw-shard-failover"
        )
        self._thread.start()

    def _restore(self, args: tuple[int, float]) -> float:
        sid, detected_at = args
        try:
            return self.part.recover_shard(sid, detected_at=detected_at)
        finally:
            with self._lock:
                self._inflight.discard(sid)

    def _loop(self) -> None:
        while not self._stopped.is_set():
            for sid in self.part.health.dead_shards():
                with self._lock:
                    if sid in self._inflight:
                        continue
                    self._inflight.add(sid)
                detected_at = time.monotonic()
                if self.scheduler is not None:
                    self.scheduler.submit(
                        self.lane, "batch", self._restore, (sid, detected_at)
                    )
                else:
                    try:
                        self._restore((sid, detected_at))
                    except Exception:  # noqa: BLE001 — retried next poll
                        pass
            self._stopped.wait(self.poll_interval_s)

    def close(self, timeout: float = 5.0) -> None:
        self._stopped.set()
        self._thread.join(timeout)
