"""SLO-class scheduler: weighted-fair queues over device-time lanes.

VectorLiteRAG's observation (PAPERS.md): under mixed RAG load the fight
is for *device time* between index-search traffic and embed/generation
traffic — and tail latency is held by partitioning it, not by FIFO.
:class:`SloScheduler` models that partition explicitly:

- **lanes** — one per device-resource kind (``"search"`` for index
  probes, ``"embed"`` for embedding/generation batches), each with a
  configured share of device time.  The dispatcher picks the eligible
  lane with the smallest ``busy_time / share`` (deficit arbitration), so
  a burst of batch embeds cannot starve index probes.
- **weighted-fair queues per (lane, tenant class)** — classic virtual
  finish times: a task's ``vfinish = max(lane vtime, class's last
  vfinish) + cost / weight``; the queue with the smallest head vfinish
  dispatches next.  With interactive weight 4 and batch weight 1, a
  saturated batch tenant gets 1/5 of a contended lane, no matter how
  deep its backlog.
- **latency-aware batch sizing** — coalescable tasks (same ``coalesce``
  key) merge into one call sized ``target_ms / ewma_item_ms`` (clamped
  to ``max_batch``): batches grow only while the per-item service time
  keeps the batch under the lane's latency target.

All handoffs ride the shared :class:`WakeupHub` (generation waits with
finite timeouts — lint LK003/LK006); results come back as
``concurrent.futures.Future``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable

from pathway_tpu.engine.cluster import WakeupHub
from pathway_tpu.internals import tracing as _tracing

from .admission import DEFAULT_CLASS_WEIGHTS

__all__ = ["SloScheduler"]

_EWMA_ALPHA = 0.2


class _Task:
    __slots__ = (
        "fn",
        "item",
        "future",
        "lane",
        "tenant_class",
        "coalesce",
        "cost",
        "vfinish",
        "enq_ns",
        "trace",
    )

    def __init__(
        self,
        fn: Callable,
        item: Any,
        future: Future,
        lane: str,
        tenant_class: str,
        coalesce: Any,
        cost: float,
        vfinish: float,
        enq_ns: int,
        trace: Any = None,
    ):
        self.fn = fn
        self.item = item
        self.future = future
        self.lane = lane
        self.tenant_class = tenant_class
        self.coalesce = coalesce
        self.cost = cost
        self.vfinish = vfinish
        self.enq_ns = enq_ns
        self.trace = trace


class SloScheduler:
    """Weighted-fair, lane-partitioned dispatcher for serving stages."""

    def __init__(
        self,
        *,
        lanes: dict[str, float] | None = None,
        class_weights: dict[str, float] | None = None,
        target_ms: dict[str, float] | None = None,
        max_batch: int = 32,
        hub: WakeupHub | None = None,
        probe: Any = None,
        idle_wait_s: float = 0.05,
        name: str = "slo_scheduler",
    ):
        self._lanes = dict(lanes or {"search": 1.0, "embed": 1.0})
        self._class_weights = dict(class_weights or DEFAULT_CLASS_WEIGHTS)
        self._target_ns = {
            lane: int(
                (target_ms or {}).get(lane, 10.0) * 1e6
            )
            for lane in self._lanes
        }
        self.max_batch = max(1, int(max_batch))
        self.hub = hub if hub is not None else WakeupHub()
        self.probe = probe
        self._idle_wait_s = idle_wait_s
        self._lock = threading.Lock()
        self._queues: dict[tuple[str, str], deque[_Task]] = {}
        self._vtime: dict[str, float] = {lane: 0.0 for lane in self._lanes}
        self._last_vf: dict[tuple[str, str], float] = {}
        self._busy_ns: dict[str, int] = {lane: 0 for lane in self._lanes}
        self._ewma_item_ns: dict[str, float] = {}
        self._dispatched: dict[tuple[str, str], int] = {}
        self._last_batch: dict[str, int] = {}
        # per-lane span args, built once: the queue-wait record is per
        # request, so a fresh dict per record is measurable overhead
        self._lane_args: dict[str, dict] = {}
        self._submitted = 0
        self._completed = 0
        #: brownout pressure in [0, 1]: under pressure, lighter classes
        #: accrue virtual time faster (see submit), deferring batch work
        #: behind interactive work harder than steady-state WFQ does
        self._pressure = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=name
        )
        self._thread.start()
        from pathway_tpu import serving as _serving

        _serving._register_scheduler(self)

    def ensure_lane(
        self, lane: str, share: float = 1.0, target_ms: float = 10.0
    ) -> None:
        """Add a device-time lane at runtime if it doesn't exist yet.

        Used by the shard-failover path to carve out a low-share
        ``recover`` lane: restore work then competes for device time
        under the same deficit arbitration as live queries instead of
        stealing it (or bypassing the partition entirely)."""
        with self._lock:
            if lane in self._lanes:
                return
            self._lanes[lane] = float(share)
            self._target_ns[lane] = int(target_ms * 1e6)
            self._vtime[lane] = 0.0
            self._busy_ns[lane] = 0

    # -------------------------------------------------------------- submit

    def submit(
        self,
        lane: str,
        tenant_class: str,
        fn: Callable,
        item: Any = None,
        *,
        coalesce: Any = None,
        cost: float = 1.0,
        trace: Any = None,
    ) -> Future:
        """Enqueue one unit of lane work; returns its Future.

        ``coalesce`` non-None marks the task mergeable: the dispatcher
        may batch same-key neighbors into one ``fn(list_of_items)`` call
        returning one result per item, in order.  ``coalesce=None`` runs
        ``fn(item)`` alone.  ``trace`` (a
        :class:`~pathway_tpu.internals.tracing.TraceContext`) rides the
        task across the queue: the dispatcher records the lane queue-wait
        as a span under it and executes single-task work with it ambient."""
        if lane not in self._lanes:
            raise KeyError(f"unknown lane {lane!r} (have {sorted(self._lanes)})")
        fut: Future = Future()
        now_ns = time.monotonic_ns()
        weight = self._class_weights.get(tenant_class, 1.0)
        pressure = self._pressure
        if pressure > 0.0:
            # brownout: stretch the weight spread — the heaviest class
            # keeps its share, lighter ones fall behind proportionally
            # harder, so interactive queue-wait holds while batch defers
            w_max = max(self._class_weights.values(), default=1.0)
            weight = weight / (
                1.0 + pressure * (w_max / max(weight, 1e-9) - 1.0)
            )
        with self._lock:
            if self._stop.is_set():
                raise RuntimeError("scheduler closed")
            qkey = (lane, tenant_class)
            start = max(self._vtime[lane], self._last_vf.get(qkey, 0.0))
            vfinish = start + float(cost) / max(weight, 1e-9)
            self._last_vf[qkey] = vfinish
            task = _Task(
                fn, item, fut, lane, tenant_class, coalesce, cost, vfinish,
                now_ns, trace,
            )
            self._queues.setdefault(qkey, deque()).append(task)
            self._submitted += 1
        self.hub.notify()
        return fut

    # ------------------------------------------------------------ dispatch

    def _batch_target_locked(self, lane: str) -> int:
        ewma = self._ewma_item_ns.get(lane, 0.0)
        if ewma <= 0.0:
            return self.max_batch  # no signal yet: let the batch form
        return max(1, min(self.max_batch, int(self._target_ns[lane] / ewma)))

    def _select(self) -> tuple[str, str, list[_Task]] | None:
        with self._lock:
            lanes_with_work = [
                lane
                for lane in self._lanes
                if any(
                    q and key[0] == lane for key, q in self._queues.items()
                )
            ]
            if not lanes_with_work:
                return None
            # deficit arbitration: least-served lane (busy/share) first
            lane = min(
                lanes_with_work,
                key=lambda ln: self._busy_ns[ln] / self._lanes[ln],
            )
            # WFQ pick: smallest head virtual-finish among this lane's
            # class queues
            heads = [
                (q[0].vfinish, key[1])
                for key, q in self._queues.items()
                if q and key[0] == lane
            ]
            _, cls = min(heads)
            q = self._queues[(lane, cls)]
            head = q.popleft()
            self._vtime[lane] = max(self._vtime[lane], head.vfinish)
            tasks = [head]
            if head.coalesce is not None:
                n = self._batch_target_locked(lane)
                while len(tasks) < n and q and q[0].coalesce == head.coalesce:
                    t = q.popleft()
                    self._vtime[lane] = max(self._vtime[lane], t.vfinish)
                    tasks.append(t)
            qkey = (lane, cls)
            self._dispatched[qkey] = self._dispatched.get(qkey, 0) + len(tasks)
            self._last_batch[lane] = len(tasks)
            return lane, cls, tasks

    def _execute(self, lane: str, cls: str, tasks: list[_Task]) -> None:
        t0 = time.monotonic_ns()
        if self.probe is not None:
            for t in tasks:
                self.probe.record("serve_sched", cls, t0 - t.enq_ns)
        # lane queue-wait, per request: the time between submit and this
        # dispatch is a span on each task's own trace — tail attribution
        # can then tell queue-wait from service time
        if _tracing.enabled():
            wait_args = self._lane_args.get(lane)
            if wait_args is None:
                wait_args = self._lane_args[lane] = {"lane": lane}
            for t in tasks:
                if t.trace is not None:
                    _tracing.record_span(
                        "serve_sched_wait", t.enq_ns, t0, ctx=t.trace,
                        args=wait_args,
                    )
        # single-task (or single-trace batch) execution adopts the trace
        # as ambient so spans inside fn — index dispatch/collect — nest;
        # a mixed-trace coalesced batch has no single owner, so none
        exec_ctx = tasks[0].trace
        for t in tasks[1:]:
            if t.trace is not exec_ctx:
                exec_ctx = None
                break
        prev_ctx = _tracing.set_ambient(exec_ctx)
        try:
            if tasks[0].coalesce is not None:
                results = tasks[0].fn([t.item for t in tasks])
                for t, r in zip(tasks, results):
                    if not t.future.done():
                        t.future.set_result(r)
            else:
                r = tasks[0].fn(tasks[0].item)
                if not tasks[0].future.done():
                    tasks[0].future.set_result(r)
        except BaseException as e:  # noqa: BLE001 — fault goes to callers
            for t in tasks:
                if not t.future.done():
                    t.future.set_exception(e)
        finally:
            _tracing.set_ambient(prev_ctx)
        dt = time.monotonic_ns() - t0
        per_item = dt / len(tasks)
        with self._lock:
            self._busy_ns[lane] += dt
            prev = self._ewma_item_ns.get(lane)
            self._ewma_item_ns[lane] = (
                per_item
                if prev is None
                else (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * per_item
            )
            self._completed += len(tasks)

    def _loop(self) -> None:
        while not self._stop.is_set():
            seen = self.hub.seq()
            picked = self._select()
            if picked is None:
                self.hub.wait(seen, self._idle_wait_s)
                continue
            self._execute(*picked)

    # --------------------------------------------------------------- admin

    def drain(self, timeout: float = 10.0) -> bool:
        """Generation-wait until every submitted task completed (True) or
        the deadline passes (False)."""
        deadline = time.monotonic() + timeout
        while True:
            seen = self.hub.seq()
            with self._lock:
                done = self._completed >= self._submitted
            if done:
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self.hub.wait(seen, min(remaining, 0.05))

    def stats(self) -> dict[str, Any]:
        with self._lock:
            lanes = {
                lane: {
                    "share": self._lanes[lane],
                    "busy_ms": self._busy_ns[lane] / 1e6,
                    "ewma_item_us": self._ewma_item_ns.get(lane, 0.0) / 1e3,
                    "last_batch": self._last_batch.get(lane, 0),
                    "queued": sum(
                        len(q)
                        for key, q in self._queues.items()
                        if key[0] == lane
                    ),
                }
                for lane in self._lanes
            }
            classes: dict[str, dict[str, int]] = {}
            for (lane, cls), n in self._dispatched.items():
                c = classes.setdefault(cls, {"dispatched": 0, "queued": 0})
                c["dispatched"] += n
            for (lane, cls), q in self._queues.items():
                c = classes.setdefault(cls, {"dispatched": 0, "queued": 0})
                c["queued"] += len(q)
            return {
                "lanes": lanes,
                "classes": classes,
                "submitted": self._submitted,
                "completed": self._completed,
                "pressure": self._pressure,
            }

    def set_pressure(self, level: float) -> None:
        """Brownout input (see :meth:`submit`); clamped to [0, 1]."""
        self._pressure = min(1.0, max(0.0, float(level)))
        self.hub.notify()

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self.hub.notify()
        self._thread.join(timeout)
        # fail any tasks still queued so callers never block on a dead
        # dispatcher
        with self._lock:
            leftovers = [t for q in self._queues.values() for t in q]
            for q in self._queues.values():
                q.clear()
        for t in leftovers:
            if not t.future.done():
                t.future.set_exception(RuntimeError("scheduler closed"))
