"""``pw.universes`` — key-set relation promises (reference
``python/pathway/universes.py``).

In the reference these register facts with the universe solver; here
universes are structural (layout tokens), so promises adjust the
tables' tokens and are validated lazily at zip time.
"""

from __future__ import annotations

from pathway_tpu.internals.table import Table

__all__ = [
    "promise_is_subset_of",
    "promise_are_equal",
    "promise_are_pairwise_disjoint",
]


def promise_is_subset_of(subset: Table, superset: Table) -> Table:
    """Declare subset's keys ⊆ superset's keys; returns ``subset`` bound to
    superset's universe (enables cross-table column use in select).  The
    relation is also registered with the universe solver (reference
    ``universe_solver.py``) so later operations can query it."""
    from pathway_tpu.internals.universe_solver import solver

    solver.register_as_subset(subset._layout_token, superset._layout_token)
    out = subset.copy()
    out._layout_token = superset._layout_token
    return out


def promise_are_equal(*tables: Table) -> None:
    """Declare all tables share the same key set."""
    from pathway_tpu.internals.universe_solver import solver

    if not tables:
        return
    token = tables[0]._layout_token
    for t in tables[1:]:
        solver.register_as_equal(token, t._layout_token)
        t._layout_token = token


def promise_are_pairwise_disjoint(*tables: Table) -> None:
    """Declare the tables' key sets are pairwise disjoint (concat is then
    safe; our concat already checks at runtime)."""
    return None
