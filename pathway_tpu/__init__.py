"""pathway_tpu — a TPU-native live-data framework.

A brand-new implementation of the Pathway capability surface (incremental
streaming ETL with a Python Table API, connectors, persistence, and a live
LLM/RAG stack) designed for JAX/XLA: batched jitted numeric plane, sharded
device-resident KNN indexes, epoch-synchronous incremental host engine.

Import convention::

    import pathway_tpu as pw
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import api as _api
from pathway_tpu.internals import dtype as _dt
from pathway_tpu.internals import udfs
from pathway_tpu.internals.api import PENDING, PyObjectWrapper, wrap_py_object
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply,
    apply_async,
    apply_with_type,
    cast,
    coalesce,
    fill_error,
    if_else,
    make_tuple,
    require,
    unwrap,
)
from pathway_tpu.internals.config import set_license_key, set_monitoring_config
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.internals.parse_graph import G, global_error_log
from pathway_tpu.internals.row_transformer import (
    ClassArg,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)
from pathway_tpu.internals.run import MonitoringLevel, run, run_all
from pathway_tpu.internals.schema import (
    Schema,
    column_definition,
    schema_builder,
    schema_from_dict,
    schema_from_pandas,
    schema_from_types,
)
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import left, right, this
from pathway_tpu.internals.udfs import UDF, udf
from pathway_tpu.internals.joins import JoinKind, JoinMode, JoinResult

from pathway_tpu import debug
from pathway_tpu import reducers

#: engine Error value — poisoned cells propagate instead of aborting
Error = _api.ERROR

DATE_TIME_NAIVE = _dt.DATE_TIME_NAIVE
DATE_TIME_UTC = _dt.DATE_TIME_UTC
DURATION = _dt.DURATION

__version__ = "0.1.0"


def __getattr__(name: str) -> Any:
    # heavier subpackages load lazily to keep import fast
    if name == "io":
        import pathway_tpu.io as io

        return io
    if name == "stdlib":
        import pathway_tpu.stdlib as stdlib

        return stdlib
    if name == "temporal":
        import pathway_tpu.stdlib.temporal as temporal

        return temporal
    if name == "indexing":
        import pathway_tpu.stdlib.indexing as indexing

        return indexing
    if name == "ml":
        import pathway_tpu.stdlib.ml as ml

        return ml
    if name == "graphs":
        import pathway_tpu.stdlib.graphs as graphs

        return graphs
    if name == "stateful":
        import pathway_tpu.stdlib.stateful as stateful

        return stateful
    if name == "statistical":
        import pathway_tpu.stdlib.statistical as statistical

        return statistical
    if name == "ordered":
        import pathway_tpu.stdlib.ordered as ordered

        return ordered
    if name == "utils":
        import pathway_tpu.stdlib.utils as utils

        return utils
    if name == "xpacks":
        import pathway_tpu.xpacks as xpacks

        return xpacks
    if name == "demo":
        import pathway_tpu.demo as demo

        return demo
    if name == "persistence":
        import pathway_tpu.persistence as persistence

        return persistence
    if name == "testing":
        import pathway_tpu.testing as testing

        return testing
    if name == "ConnectorRecoveryPolicy":
        from pathway_tpu.internals.resilience import ConnectorRecoveryPolicy

        return ConnectorRecoveryPolicy
    if name == "universes":
        import pathway_tpu.universes as universes

        return universes
    if name == "AsyncTransformer":
        from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer

        return AsyncTransformer
    if name == "asynchronous":
        # deprecated alias kept for parity (reference pathway.asynchronous
        # -> pw.udfs, python/pathway/asynchronous.py:1-6)
        from pathway_tpu.internals import udfs as asynchronous

        return asynchronous
    if name == "PersistenceMode":
        from pathway_tpu.persistence import PersistenceMode

        return PersistenceMode
    if name in ("DateTimeNaive", "DateTimeUtc", "Duration"):
        from pathway_tpu.internals import dtype as _dt

        return {
            "DateTimeNaive": _dt.DateTimeNaive,
            "DateTimeUtc": _dt.DateTimeUtc,
            "Duration": _dt.Duration,
        }[name]
    if name == "declare_type":
        from pathway_tpu.internals.expression import declare_type

        return declare_type
    if name == "attach_prober":
        from pathway_tpu.internals.run import attach_prober

        return attach_prober
    if name == "iterate":
        from pathway_tpu.internals.iterate import iterate

        return iterate
    if name == "iterate_universe":
        from pathway_tpu.internals.iterate import iterate_universe

        return iterate_universe
    if name == "enable_interactive_mode":
        from pathway_tpu.internals.interactive import enable_interactive_mode

        return enable_interactive_mode
    if name in ("LiveTable", "live", "export_table", "import_table", "ExportedTable"):
        from pathway_tpu.internals import interactive

        return getattr(interactive, name)
    if name == "viz":
        import pathway_tpu.stdlib.viz as viz

        return viz
    if name == "sql":
        from pathway_tpu.internals.sql import sql

        return sql
    if name == "load_yaml":
        from pathway_tpu.internals.yaml_loader import load_yaml

        return load_yaml
    if name == "analysis":
        import pathway_tpu.analysis as analysis

        return analysis
    if name in (
        "analyze",
        "explain",
        "estimate_memory",
        "MemoryReport",
        "EstimateParams",
        "Diagnostic",
        "AnalysisError",
        "ExecutionPlan",
    ):
        from pathway_tpu import analysis

        return getattr(analysis, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Table",
    "Schema",
    "Json",
    "Pointer",
    "Error",
    "PENDING",
    "PyObjectWrapper",
    "wrap_py_object",
    "ColumnExpression",
    "ColumnReference",
    "this",
    "left",
    "right",
    "JoinKind",
    "JoinMode",
    "JoinResult",
    "apply",
    "apply_async",
    "apply_with_type",
    "cast",
    "coalesce",
    "if_else",
    "require",
    "unwrap",
    "fill_error",
    "make_tuple",
    "udf",
    "udfs",
    "UDF",
    "run",
    "run_all",
    "global_error_log",
    "ClassArg",
    "input_attribute",
    "input_method",
    "method",
    "output_attribute",
    "transformer",
    "MonitoringLevel",
    "debug",
    "reducers",
    "column_definition",
    "schema_from_types",
    "schema_from_dict",
    "schema_builder",
    "schema_from_pandas",
    "set_license_key",
    "set_monitoring_config",
    "G",
    "analyze",
    "explain",
    "estimate_memory",
    "MemoryReport",
    "EstimateParams",
    "Diagnostic",
    "AnalysisError",
    "ExecutionPlan",
]
