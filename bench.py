"""Headline benchmarks for the TPU-native build.

Four sections, one JSON line (driver contract: the LAST stdout line):

1. **KNN retrieval** (BASELINE.md north star #2: <50 ms p50 over 1M docs).
   Corpus in TPU HBM as a bf16 slab (reference counterpart: host
   ``Array2<f64>`` scalar loops,
   ``src/external_integration/brute_force_knn_integration.rs``); one query
   batch = one MXU matmul + top-k.  Reported three ways: batched serving
   (epoch batch of 50 — what ``ExternalIndexNode`` actually dispatches),
   pipelined batch=1 (4 dispatches in flight hide the host link RTT), and
   strict sync batch=1 (pays full RTT per call, reported for honesty).
2. **Ingest**: bulk ``add_batch`` docs/sec into the live index (donated
   scatters, normalization/cast as whole-array numpy ops).
3. **Embedding throughput + MFU** (BASELINE.md north star #1: >=10k docs/s
   BGE-large-class on v5e-8, i.e. 1250 docs/s/chip): tokenize -> jitted
   bf16 encode -> index, end-to-end.  MFU counts the FLOPs the hardware
   actually executed (padded seq len) vs device peak.  Reference
   counterpart: per-row torch ``model.encode``
   (``python/pathway/xpacks/llm/embedders.py:270-327``).
4. **Streaming engine wordcount** (reference harness
   ``integration_tests/wordcount/base.py``): JSONL file -> groupby(word)
   -> count, input-snapshot persistence ON, single worker host plane.

``vs_baseline`` = baseline_ms / measured_ms for the headline (>1 means
faster than the 50 ms target).  Extra context goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from collections import deque

import numpy as np

N_DOCS = 1_000_000
DIM = 384  # MiniLM/BGE-small embedding width
K = 10
N_QUERIES = 50
BASELINE_MS = 50.0

EMBED_SEQ = 128
EMBED_BATCH = 512  # chunk size; encode() pipelines chunk i+1 over i's readback
EMBED_DEPTH = 4  # in-flight chunks (hides the host link RTT)
EMBED_DOCS = 8192
EMBED_TRIALS = 5  # report MEDIAN (headline) + BEST (tunnel variance)
EMBED_TARGET_PER_CHIP = 10_000 / 8  # BASELINE target is for v5e-8

WC_LINES = 2_000_000
WC_WORDS = 1000
SELECT_N = 1_000_000
STRDT_N = 300_000

#: --smoke: seconds-long sanity run — tiny corpus, host-plane sections
#: only (no 1M index build, no model benches); same JSON contract
SMOKE = False

#: bf16 peak FLOPs/s per chip by device_kind substring
_PEAKS = [
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v6", 918e12),
    ("v4", 275e12),
]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def artifact_path(name: str) -> str:
    """Where a ``BENCH_*.json`` evidence artifact gets written.

    Smoke runs measure a corpus orders of magnitude smaller than the
    published numbers, so they must never overwrite the committed
    artifacts README/ROADMAP cite — they land in a gitignored
    ``BENCH_*.smoke.json`` sidecar instead."""
    if SMOKE:
        base, ext = os.path.splitext(name)
        name = f"{base}.smoke{ext}"
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)


def smoke_analyze(graph_name: str) -> None:
    """--smoke gate: run the pre-flight static analyzer on the bench
    graph just built and abort on error-severity findings — the bench
    graphs double as analyzer regression fixtures."""
    if not SMOKE:
        return
    from pathway_tpu.analysis import SEV_ERROR, analyze, format_diagnostics
    from pathway_tpu.analysis.rewrite import resolve_level

    # plan-aware, like pw.run(strict=...): gate on the view that will
    # execute, so a rewrite that cures a finding (append-only reducer
    # specialization, dead columns) also clears the gate
    diags = analyze(optimize=resolve_level(None))
    errors = [d for d in diags if d.severity == SEV_ERROR]
    if errors:
        log(format_diagnostics(diags))
        raise SystemExit(
            f"{graph_name}: static analysis found {len(errors)} "
            "error-severity finding(s)"
        )
    log(f"{graph_name}: analyzer clean ({len(diags)} warning(s))")


def device_peak_flops(dev) -> float | None:
    kind = getattr(dev, "device_kind", "").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return None


# ---------------------------------------------------------------------------


def bench_knn(extra: dict) -> float:
    import jax
    import jax.numpy as jnp

    from pathway_tpu.parallel import ShardedKnnIndex, make_mesh

    devs = jax.devices()
    log(f"devices: {devs}")
    mesh = make_mesh() if len(devs) > 1 else None

    idx = ShardedKnnIndex(
        DIM, metric="cos", capacity=N_DOCS, mesh=mesh, dtype=jnp.bfloat16
    )

    # Bulk-load the corpus through the live-upsert path (donated scatters);
    # host prep is whole-array numpy since the columnar add_batch rework.
    rng = np.random.default_rng(0)
    log(f"building {N_DOCS}x{DIM} corpus...")
    t0 = time.perf_counter()
    chunk = 100_000
    for start in range(0, N_DOCS, chunk):
        n = min(chunk, N_DOCS - start)
        block = rng.normal(size=(n, DIM)).astype(np.float32)
        idx.add_batch(range(start, start + n), block)
    jax.block_until_ready(idx._vectors)
    build_s = time.perf_counter() - t0
    ingest = N_DOCS / build_s
    log(f"corpus loaded in {build_s:.1f}s ({ingest:.0f} docs/sec incl. host prep)")
    extra["knn_ingest_docs_per_sec"] = round(ingest)

    # Live-upsert rate in isolation: the block is generated OUTSIDE the
    # timer, so this measures add_batch itself (normalize/cast + donated
    # scatter) — the number the README ingest row cites, separated from
    # the RNG host prep the bulk-load figure above includes.
    up_n = 100_000
    up_block = rng.normal(size=(up_n, DIM)).astype(np.float32)
    idx.add_batch(range(up_n), up_block)  # warm the scatter shape
    jax.block_until_ready(idx._vectors)
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        idx.add_batch(range(up_n), up_block)
    jax.block_until_ready(idx._vectors)
    upsert = reps * up_n / (time.perf_counter() - t0)
    log(f"live upsert (host prep excluded): {upsert:.0f} docs/sec")
    extra["knn_upsert_docs_per_sec"] = round(upsert)

    queries = rng.normal(size=(N_QUERIES, DIM)).astype(np.float32)

    # warmup / compile (batch=1 and batch=N_QUERIES shapes)
    idx.search(queries[:1], K)
    idx.search(queries[:1], K)
    idx.search(queries, K)

    # Link RTT floor: one trivial jit + readback round trip.  On tunneled
    # dev setups this is ~90 ms and bounds ALL single-query latencies
    # below; on co-located TPU hardware it is sub-millisecond.
    tiny = jnp.zeros((1, 8))
    bump = jax.jit(lambda a: a + 1)
    jax.device_get(bump(tiny))
    rtts = []
    for _ in range(10):
        t0 = time.perf_counter()
        jax.device_get(bump(tiny))
        rtts.append((time.perf_counter() - t0) * 1000.0)
    rtts.sort()
    rtt = rtts[len(rtts) // 2]
    log(f"link RTT floor (trivial jit+readback): {rtt:.2f}ms")
    extra["link_rtt_floor_ms"] = round(rtt, 3)

    # Strict sync-per-call latency: pays the full link RTT per call.
    sync_lat = []
    for i in range(20):
        t0 = time.perf_counter()
        res = idx.search(queries[i : i + 1], K)
        sync_lat.append((time.perf_counter() - t0) * 1000.0)
        assert len(res[0]) == K
    sync_lat.sort()
    sync_p50 = sync_lat[len(sync_lat) // 2]
    log(f"sync-per-call p50={sync_p50:.2f}ms (incl. link RTT)")
    extra["knn_p50_sync_single_query_ms"] = round(sync_p50, 3)

    # Pipelined batch=1: keep DEPTH dispatches in flight; dispatch also
    # starts the result's device->host copy (copy_to_host_async), so
    # compute and readback overlap later dispatches.  Latency per query =
    # its own dispatch -> collected result (includes pipeline queue wait).
    # depth sized to RTT/service ratio: deeper queues only add latency
    # once the device is saturated (service time ~15-20 ms at batch=1)
    DEPTH = 4
    NPIPE = 96
    inflight: deque = deque()
    pipe_lat = []
    t_all = time.perf_counter()
    for i in range(NPIPE):
        q = queries[i % N_QUERIES : i % N_QUERIES + 1]
        inflight.append((time.perf_counter(), idx.dispatch(q, K)))
        if len(inflight) >= DEPTH:
            t0, h = inflight.popleft()
            idx.collect(h)
            pipe_lat.append((time.perf_counter() - t0) * 1000.0)
    while inflight:
        t0, h = inflight.popleft()
        idx.collect(h)
        pipe_lat.append((time.perf_counter() - t0) * 1000.0)
    pipe_wall = time.perf_counter() - t_all
    pipe_lat.sort()
    pipe_p50 = pipe_lat[len(pipe_lat) // 2]
    log(
        f"pipelined batch=1 (depth {DEPTH}): p50={pipe_p50:.2f}ms/query, "
        f"{NPIPE / pipe_wall:.0f} queries/s sustained"
    )
    extra["knn_p50_single_query_pipelined_ms"] = round(pipe_p50, 3)
    extra["knn_pipelined_queries_per_sec"] = round(NPIPE / pipe_wall, 1)

    # Device-side single-query latency: the <50ms target without the
    # tunnel RTT caveat.  Estimator: dispatches queue on the device and
    # execute back-to-back, so wall(n2 dispatches+block) - wall(n1+block)
    # cancels the one host round trip and divides out to the on-device
    # service time per query.  Five repeats; report the median slope.
    N1, N2 = 4, 20
    slopes = []
    for _ in range(5):
        # timing collects only the LAST handle (device executes FIFO, so
        # it blocks until the whole queue drained); the rest are drained
        # after each timing so _inflight bookkeeping stays balanced
        hs = []
        t0 = time.perf_counter()
        for i in range(N1):
            hs.append(
                idx.dispatch(queries[i % N_QUERIES : i % N_QUERIES + 1], K)
            )
        idx.collect(hs[-1])
        t_a = time.perf_counter() - t0
        for h in hs[:-1]:
            idx.collect(h)
        hs = []
        t0 = time.perf_counter()
        for i in range(N2):
            hs.append(
                idx.dispatch(queries[i % N_QUERIES : i % N_QUERIES + 1], K)
            )
        idx.collect(hs[-1])
        t_b = time.perf_counter() - t0
        for h in hs[:-1]:
            idx.collect(h)
        slopes.append((t_b - t_a) * 1000.0 / (N2 - N1))
    slopes.sort()
    dev_q = slopes[len(slopes) // 2]
    log(
        f"device-side single-query service time: p50={dev_q:.2f}ms "
        f"(RTT-cancelled slope over {N1}->{N2} queued dispatches x5)"
    )
    extra["knn_p50_device_single_query_ms"] = round(dev_q, 3)

    # Headline: per-query latency in the engine's serving mode — all of an
    # epoch's queries answered in ONE batched dispatch + ONE readback
    # (exactly what ExternalIndexNode does).
    groups = []
    for _ in range(9):
        t0 = time.perf_counter()
        res = idx.search(queries, K)
        groups.append((time.perf_counter() - t0) * 1000.0 / N_QUERIES)
        assert all(len(r) == K for r in res)
    groups.sort()
    p50 = groups[len(groups) // 2]
    log(
        f"per-query p50={p50:.3f}ms in batch-{N_QUERIES} serving mode "
        f"(batch latencies: {['%.1f' % (g * N_QUERIES) for g in groups]} ms)"
    )
    return p50


# ---------------------------------------------------------------------------


def bench_embed(extra: dict) -> None:
    import jax

    from pathway_tpu.models.encoder import BGE_LARGE
    from pathway_tpu.parallel import ShardedKnnIndex, make_mesh
    from pathway_tpu.parallel.executor import JittedEncoder

    devs = jax.devices()
    mesh = make_mesh() if len(devs) > 1 else None
    n_dev = len(devs)

    cfg = BGE_LARGE
    enc = JittedEncoder(
        cfg,
        mesh=mesh,
        max_batch=EMBED_BATCH,
        max_len=EMBED_SEQ,
        pipeline_depth=EMBED_DEPTH,
    )
    idx = ShardedKnnIndex(cfg.hidden, metric="cos", capacity=EMBED_DOCS, mesh=mesh)

    rng = np.random.default_rng(1)
    vocab = [f"tok{i}" for i in range(5000)]
    docs = [
        " ".join(rng.choice(vocab, size=100)) for _ in range(EMBED_DOCS)
    ]  # ~100 words -> padded to the 128-token bucket

    log(
        f"embed bench: BGE-large-class ({cfg.layers}L/{cfg.hidden}h bf16), "
        f"seq {EMBED_SEQ}, batch {EMBED_BATCH} x depth {EMBED_DEPTH}, "
        f"{EMBED_DOCS} docs x {EMBED_TRIALS} trials (median)"
    )
    # warmup: compile the bucket shape, one full pipelined pass (warm
    # upload/readback streams), and the index scatter at the full-batch
    # shape — the first cold pass otherwise pays every compile and reads
    # ~50% low
    enc.encode(docs[:EMBED_BATCH])
    enc.encode_into(idx, range(EMBED_BATCH * EMBED_DEPTH),
                    docs[: EMBED_BATCH * EMBED_DEPTH])
    idx.add_batch(
        range(EMBED_DOCS), np.zeros((EMBED_DOCS, cfg.hidden), np.float32)
    )
    jax.block_until_ready(idx._vectors)

    # repeated full passes: the tunnel RTT and shared-TPU load swing
    # single passes by +-40%, so the headline is the MEDIAN trial.  The
    # pipeline is tokenize -> encode -> index with the embeddings staying
    # in HBM (encode_into/add_batch_device): only token ids cross the
    # host link, so a congested tunnel no longer caps the number — and
    # on any deployment, skipping the host round trip is simply the
    # right TPU-native design for embed+index.
    trial_dps = []
    done = EMBED_DOCS
    for trial in range(EMBED_TRIALS):
        t0 = time.perf_counter()
        n_done = enc.encode_into(idx, range(EMBED_DOCS), docs)
        jax.block_until_ready(idx._vectors)
        trial_dt = time.perf_counter() - t0
        assert n_done == EMBED_DOCS
        trial_dps.append(done / trial_dt)
        log(f"  e2e trial {trial}: {done / trial_dt:.0f} docs/s")
    trial_dps.sort()
    dps = trial_dps[len(trial_dps) // 2]
    best_dps = trial_dps[-1]
    dt = done / dps

    # device steady state (re-dispatch one resident chunk): isolates the
    # compiled encoder's MFU from host tokenize/upload/readback overheads.
    # start_host_copy=False keeps the output in HBM — the encode_into
    # serving path; with the copy on (the old loop), every dispatch also
    # raced a device->host transfer and the number measured readback.
    ids, mask, tps = enc.tokenizer.encode_batch(
        docs[:EMBED_BATCH], max_len=EMBED_SEQ
    )
    enc._run(ids, mask, tps)
    t0 = time.perf_counter()
    for _ in range(8):
        out, _n = enc._dispatch(ids, mask, tps, start_host_copy=False)
    jax.block_until_ready(out)
    dev_dt = time.perf_counter() - t0
    dev_dps = 8 * EMBED_BATCH / dev_dt

    # same loop with the async copy started and every output materialized
    # on the host: the encode() consumer path, paying the link
    t0 = time.perf_counter()
    outs = [enc._dispatch(ids, mask, tps)[0] for _ in range(8)]
    for o in outs:
        np.asarray(o)
    rb_dt = time.perf_counter() - t0
    rb_dps = 8 * EMBED_BATCH / rb_dt

    # FLOPs the hardware executed (padded seq): per token per layer,
    # matmul MACs = 4h^2 (QKVO) + 2hL (scores+context) + 2*h*mlp (up+down);
    # FLOPs = 2*MACs.  Pool/head negligible.
    h, L = cfg.hidden, EMBED_SEQ
    per_tok_layer = 2 * (4 * h * h + 2 * h * L + 2 * h * cfg.mlp_dim)
    flops = done * L * cfg.layers * per_tok_layer
    peak = device_peak_flops(devs[0])
    mfu = (flops / dt) / (peak * n_dev) if peak else None

    target = EMBED_TARGET_PER_CHIP * n_dev
    dev_mfu = (
        (flops / done * EMBED_BATCH * 8) / dev_dt / (peak * n_dev)
        if peak
        else None
    )
    log(
        f"embed+index: {dps:.0f} docs/s on {n_dev} chip(s) "
        f"({flops / dt / 1e12:.1f} TFLOPs/s"
        + (f", MFU {mfu * 100:.1f}%" if mfu is not None else ", MFU n/a")
        + f"); device steady state {dev_dps:.0f} docs/s"
        + (f" (MFU {dev_mfu * 100:.1f}%)" if dev_mfu is not None else "")
        + f"; with readback {rb_dps:.0f} docs/s"
        + f"; target share {target:.0f} docs/s"
    )
    extra["embed_docs_per_sec"] = round(dps, 1)
    extra["embed_docs_per_sec_best"] = round(best_dps, 1)
    extra["embed_docs_per_sec_trials"] = [round(x, 1) for x in trial_dps]
    extra["embed_mfu_pct"] = round(mfu * 100, 1) if mfu is not None else None
    extra["embed_device_docs_per_sec"] = round(dev_dps, 1)
    extra["embed_readback_docs_per_sec"] = round(rb_dps, 1)
    extra["embed_device_mfu_pct"] = (
        round(dev_mfu * 100, 1) if dev_mfu is not None else None
    )
    extra["embed_model"] = f"bge-large-class {cfg.layers}L/{cfg.hidden}h bf16"
    extra["embed_seq_len"] = EMBED_SEQ
    extra["embed_n_chips"] = n_dev
    extra["embed_vs_target"] = round(dps / target, 2)


# ---------------------------------------------------------------------------


def _write_wc_input(d: str) -> str:
    fp = os.path.join(d, "lines.jsonl")
    rng = np.random.default_rng(2)
    words = rng.integers(0, WC_WORDS, size=WC_LINES)
    with open(fp, "w") as f:
        f.write("\n".join('{"word": "w%d"}' % w for w in words))
        f.write("\n")
    return fp


def _wc_graph(pw, fp: str):
    """Wordcount with a select chain and an unread column: real work for
    the optimizer (dead-column elimination + two select fusions)."""

    class S(pw.Schema):
        word: str

    lines = pw.io.jsonlines.read(fp, schema=S, mode="static")
    counts = lines.groupby(lines.word).reduce(lines.word, c=pw.reducers.count())
    viewd = counts.select(counts.word, c=counts.c, dead=counts.c * 100 + 1)
    final = viewd.select(viewd.word, c=viewd.c)
    return final._capture_node()


def bench_wordcount(extra: dict) -> None:
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    d = tempfile.mkdtemp(prefix="pw_bench_wc_")
    fp = _write_wc_input(d)
    log(f"wordcount: {WC_LINES} JSONL lines, persistence PERSISTING -> {d}")
    rps_by_level: dict[int, float] = {}
    for level in (0, 2):
        G.clear()
        pdir = os.path.join(d, f"pstorage_opt{level}")
        t0 = time.perf_counter()
        cap = _wc_graph(pw, fp)
        if level == 2:
            smoke_analyze("wordcount")
        ctx = pw.run(
            optimize=level,
            persistence_config=pw.persistence.Config(
                backend=pw.persistence.Backend.filesystem(pdir)
            ),
        )
        dt = time.perf_counter() - t0
        rps = WC_LINES / dt
        rows = ctx.state(cap)["rows"]
        total = sum(v[1] for v in rows.values())
        assert total == WC_LINES, f"lost rows: {total} != {WC_LINES}"
        log(
            f"wordcount[opt{level}]: {WC_LINES} rows in {dt:.1f}s -> "
            f"{rps:.0f} rows/s, {len(rows)} groups"
        )
        rps_by_level[level] = rps
        extra[f"wordcount_rows_per_sec_opt{level}"] = round(rps)
    plan = getattr(G, "last_plan", None)
    extra["wordcount_plan_rewrites"] = dict(plan.counters()) if plan else {}
    # headline number is the default (optimized) path
    extra["wordcount_rows_per_sec"] = round(rps_by_level[2])
    extra["wordcount_lines"] = WC_LINES
    extra["wordcount_persistence"] = "PERSISTING"
    if SMOKE:
        # the optimizer must never cost throughput; 0.7 absorbs noise on
        # a seconds-long smoke corpus
        assert rps_by_level[2] >= rps_by_level[0] * 0.7, (
            f"optimize=2 ({rps_by_level[2]:.0f} rows/s) regressed vs "
            f"optimize=0 ({rps_by_level[0]:.0f} rows/s)"
        )


def _run_wc_cluster(n_procs: int, fp: str, d: str) -> tuple[float, float, dict]:
    """Run the wordcount over an n-process TCP cluster; returns
    (slowest worker RUN_SECONDS, summed worker CPU seconds measured
    around pw.run only, summed exchange stats across workers)."""
    import subprocess
    import textwrap

    repo = os.path.dirname(os.path.abspath(__file__))
    out_fp = os.path.join(d, f"out_{n_procs}.jsonl")
    prog = os.path.join(d, f"prog_{n_procs}.py")
    with open(prog, "w") as f:
        f.write(
            textwrap.dedent(
                f"""
                import sys
                sys.path.insert(0, {repo!r})
                import pathway_tpu as pw

                class S(pw.Schema):
                    word: str

                t = pw.io.jsonlines.read({fp!r}, schema=S, mode="static")
                counts = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
                pw.io.jsonlines.write(counts, {out_fp!r})
                import json as _json, os as _os, time as _time
                _t0 = _time.perf_counter()
                _c0 = _os.times()
                ctx = pw.run(autocommit_duration_ms=200)
                _c1 = _os.times()
                print("RUN_SECONDS=%.3f" % (_time.perf_counter() - _t0))
                print("CPU_SECONDS=%.3f"
                      % (_c1.user + _c1.system - _c0.user - _c0.system))
                print("EXCHANGE_STATS="
                      + _json.dumps(ctx.stats.get("exchange", {{}})))
                """
            )
        )
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(
        os.environ,
        PATHWAY_THREADS="1",
        PATHWAY_PROCESSES=str(n_procs),
        PATHWAY_FIRST_PORT=str(port),
        JAX_PLATFORMS="cpu",
    )
    procs = []
    for pid in range(n_procs):
        e = dict(env, PATHWAY_PROCESS_ID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, prog],
                env=e,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    run_secs, cpu_secs = [], []
    xstats: dict = {}
    for p in procs:
        out, err = p.communicate(timeout=600)
        if p.returncode != 0:
            raise RuntimeError(f"cluster proc failed: {err.decode()[-500:]}")
        for line in out.decode().splitlines():
            if line.startswith("RUN_SECONDS="):
                run_secs.append(float(line.split("=", 1)[1]))
            elif line.startswith("CPU_SECONDS="):
                cpu_secs.append(float(line.split("=", 1)[1]))
            elif line.startswith("EXCHANGE_STATS="):
                for k, v in json.loads(line.split("=", 1)[1]).items():
                    if isinstance(v, (int, float)):
                        xstats[k] = xstats.get(k, 0) + v
    return max(run_secs), sum(cpu_secs), xstats


def bench_wordcount_multiprocess(extra: dict) -> None:
    """The same wordcount across 1-, 2- and 4-process TCP clusters (spawn
    env contract) — the scale story the thread mode (GIL-bound) can't
    tell.  All sizes run through the SAME subprocess harness so the CPU
    numbers are comparable.

    Wall-clock speedup needs free cores: on a 1-core host (this driver
    box) the theoretical ceiling for N processes is 1.0x a single
    process, so the honest scaling evidence is (a) the host core count,
    (b) CPU-normalized efficiency — single-process CPU seconds over the
    N-process total, 1.0 = scaling costs nothing — and (c) the exchange
    overhead probe: pack/send/unpack milliseconds the pipelined transport
    spent, as a share of total worker CPU."""
    d = tempfile.mkdtemp(prefix="pw_bench_wc_mp_")
    fp = _write_wc_input(d)
    n_cores = os.cpu_count() or 1
    extra["host_cpu_cores"] = n_cores
    log(f"wordcount multiprocess: {WC_LINES} lines, host has {n_cores} core(s)")
    keys = {
        1: "wordcount_1proc",
        2: "wordcount_multiprocess",
        4: "wordcount_4proc",
        8: "wordcount_8proc",
    }
    cpu_by_n: dict[int, float] = {}
    for n_procs in (1, 2) if SMOKE else (1, 2, 4, 8):
        dt, cpu, xstats = _run_wc_cluster(n_procs, fp, d)
        rps = WC_LINES / dt
        cpu_by_n[n_procs] = cpu
        key = keys[n_procs]
        extra[f"{key}_rows_per_sec"] = round(rps)
        extra[f"{key}_cpu_seconds"] = round(cpu, 2)
        busy_ms = sum(xstats.get(k, 0.0) for k in ("pack_ms", "send_ms", "unpack_ms"))
        overhead = busy_ms / (cpu * 1000.0) * 100.0 if cpu > 0 else 0.0
        log(
            f"wordcount {n_procs}-process: {rps:.0f} rows/s "
            f"(run {dt:.1f}s, {cpu:.1f} CPU-s in pw.run, "
            f"exchange busy {busy_ms:.0f}ms = {overhead:.1f}% of CPU)"
        )
        if n_procs == 2:
            # the headline overhead probe: CPU the transport itself burnt
            # (serialize/syscall/deserialize) over total worker CPU — the
            # wait times are idle, reported separately in the stats blob
            extra["wordcount_exchange_overhead_pct"] = round(overhead, 2)
            extra["wordcount_exchange_stats"] = {
                k: round(v, 1) if isinstance(v, float) else v
                for k, v in xstats.items()
            }
    for n in (2, 4, 8):
        if n in cpu_by_n and cpu_by_n[n] > 0:
            extra[f"wordcount_cpu_normalized_efficiency_{n}proc"] = round(
                cpu_by_n[1] / cpu_by_n[n], 3
            )
    extra["wordcount_multiprocess_n_procs"] = 2


def bench_columnar(extra: dict) -> None:
    """Columnar-vs-row differential on the SAME wordcount corpus, plus
    the zero-copy exchange before/after — the evidence artifact for the
    batch-execution work (``BENCH_columnar.json``).

    Four measurements:

    - single-core wordcount at optimize=2 with frames (default) and with
      ``PATHWAY_DISABLE_COLUMNAR=1`` (row path) — the kernel speedup;
    - ``columnar_rows`` path attribution from the run context (how many
      rows actually took the fast path);
    - 2-process cluster exchange stats row vs columnar — per-stage
      pack/send/unpack milliseconds and the string-pool hit rate of the
      ``_K_FRAME`` wire format;
    - the cluster scaling numbers (1/2/4/8-proc rows/s and
      CPU-normalized efficiency) copied from the multiprocess section.

    ``--smoke`` gates that the columnar kernels are no slower than the
    row path they replace, and that the columnar wire engages, ships
    fewer bytes, and burns less pack+unpack CPU than the row wire (its
    wall-clock rows/s is not gated: at smoke scale the 2-proc exchange
    is dominated by fixed status waits, so that ordering is noise).
    Smoke output goes to ``BENCH_columnar.smoke.json`` — it never
    replaces the committed full-run artifact."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    d = tempfile.mkdtemp(prefix="pw_bench_col_")
    fp = _write_wc_input(d)

    def _run_single(disable: bool) -> tuple[float, dict]:
        saved = os.environ.pop("PATHWAY_DISABLE_COLUMNAR", None)
        if disable:
            os.environ["PATHWAY_DISABLE_COLUMNAR"] = "1"
        try:
            G.clear()
            t0 = time.perf_counter()
            cap = _wc_graph(pw, fp)
            ctx = pw.run(optimize=2)
            dt = time.perf_counter() - t0
            rows = ctx.state(cap)["rows"]
            total = sum(v[1] for v in rows.values())
            assert total == WC_LINES, f"lost rows: {total} != {WC_LINES}"
            return WC_LINES / dt, dict(ctx.stats.get("columnar_rows", {}))
        finally:
            if saved is None:
                os.environ.pop("PATHWAY_DISABLE_COLUMNAR", None)
            else:
                os.environ["PATHWAY_DISABLE_COLUMNAR"] = saved

    rps_row, colrows_row = _run_single(disable=True)
    rps_col, colrows_col = _run_single(disable=False)
    speedup = rps_col / rps_row if rps_row > 0 else 0.0
    log(
        f"columnar wordcount: {rps_col:.0f} rows/s columnar vs "
        f"{rps_row:.0f} rows/s row path ({speedup:.2f}x), "
        f"path attribution {colrows_col}"
    )

    # exchange before/after: the same 2-proc cluster, row wire format
    # (PATHWAY_DISABLE_COLUMNAR=1 → _K_UPDATES) vs columnar (_K_FRAME)
    os.environ["PATHWAY_DISABLE_COLUMNAR"] = "1"
    try:
        dt2_row, cpu2_row, xstats_row = _run_wc_cluster(2, fp, d)
    finally:
        os.environ.pop("PATHWAY_DISABLE_COLUMNAR", None)
    dt2_col, cpu2_col, xstats_col = _run_wc_cluster(2, fp, d)

    def _overhead(xstats: dict, cpu: float) -> float:
        busy = sum(xstats.get(k, 0.0) for k in ("pack_ms", "send_ms", "unpack_ms"))
        return busy / (cpu * 1000.0) * 100.0 if cpu > 0 else 0.0

    ov_row, ov_col = _overhead(xstats_row, cpu2_row), _overhead(xstats_col, cpu2_col)
    pool_hits = xstats_col.get("strpool_hits", 0)
    pool_misses = xstats_col.get("strpool_misses", 0)
    pool_rate = (
        pool_hits / (pool_hits + pool_misses) if pool_hits + pool_misses else 0.0
    )
    log(
        f"columnar exchange 2-proc: {WC_LINES / dt2_col:.0f} rows/s "
        f"(overhead {ov_col:.1f}% vs row-wire {ov_row:.1f}%), "
        f"string pool hit rate {pool_rate:.0%}"
    )

    extra["columnar_rows_per_sec"] = round(rps_col)
    extra["columnar_row_path_rows_per_sec"] = round(rps_row)
    extra["columnar_speedup_single_core"] = round(speedup, 2)
    extra["columnar_exchange_overhead_pct"] = round(ov_col, 2)
    extra["columnar_strpool_hit_rate"] = round(pool_rate, 3)

    def _round(xs: dict) -> dict:
        return {
            k: round(v, 1) if isinstance(v, float) else v for k, v in xs.items()
        }

    cluster_keys = (
        "wordcount_1proc_rows_per_sec",
        "wordcount_multiprocess_rows_per_sec",
        "wordcount_4proc_rows_per_sec",
        "wordcount_8proc_rows_per_sec",
        "wordcount_cpu_normalized_efficiency_2proc",
        "wordcount_cpu_normalized_efficiency_4proc",
        "wordcount_cpu_normalized_efficiency_8proc",
        "wordcount_exchange_overhead_pct",
        "host_cpu_cores",
    )
    out = artifact_path("BENCH_columnar.json")
    with open(out, "w") as f:
        json.dump(
            {
                "cmd": "JAX_PLATFORMS=cpu python bench.py (bench_columnar)",
                "config": {
                    "wc_lines": WC_LINES,
                    "wc_words": WC_WORDS,
                    "optimize": 2,
                    "smoke": SMOKE,
                },
                "single_core": {
                    "wordcount_rows_per_sec": round(rps_col),
                    "wordcount_rows_per_sec_row_path": round(rps_row),
                    "columnar_speedup": round(speedup, 2),
                    "columnar_rows": colrows_col,
                    "columnar_rows_row_path": colrows_row,
                },
                "exchange_2proc": {
                    "row_wire": {
                        "rows_per_sec": round(WC_LINES / dt2_row),
                        "worker_cpu_seconds": round(cpu2_row, 2),
                        "overhead_pct": round(ov_row, 2),
                        "stats": _round(xstats_row),
                    },
                    "columnar_wire": {
                        "rows_per_sec": round(WC_LINES / dt2_col),
                        "worker_cpu_seconds": round(cpu2_col, 2),
                        "overhead_pct": round(ov_col, 2),
                        "strpool_hit_rate": round(pool_rate, 3),
                        "stats": _round(xstats_col),
                    },
                },
                "cluster": {k: extra[k] for k in cluster_keys if k in extra},
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")
    log(f"wrote {out}")

    if SMOKE:
        assert rps_col >= rps_row, (
            f"columnar path ({rps_col:.0f} rows/s) is slower than the row "
            f"path it replaces ({rps_row:.0f} rows/s)"
        )
        assert colrows_col.get("columnar", 0) > 0, (
            f"no rows took the columnar path at optimize=2: {colrows_col}"
        )
        # Wire-path gate.  Wall-clock rows/s of the 2-proc exchange is
        # NOT comparable at smoke scale — a 20k-line corpus is dominated
        # by fixed status-round waits, so the ordering is noise — but
        # the codec wins are deterministic at any scale: _K_FRAME must
        # actually engage (a silent fallback to the row wire would pass
        # every other assert), ship fewer bytes, and burn less pack +
        # unpack CPU than the row wire on the same corpus.
        assert (
            xstats_col.get("strpool_hits", 0)
            + xstats_col.get("strpool_misses", 0)
            > 0
        ), f"columnar wire never engaged (no string-pool traffic): {xstats_col}"
        assert xstats_col.get("bytes_sent", 0) < xstats_row.get("bytes_sent", 0), (
            f"columnar wire sent {xstats_col.get('bytes_sent')} bytes, not "
            f"fewer than the row wire's {xstats_row.get('bytes_sent')}"
        )
        codec_col = xstats_col.get("pack_ms", 0.0) + xstats_col.get("unpack_ms", 0.0)
        codec_row = xstats_row.get("pack_ms", 0.0) + xstats_row.get("unpack_ms", 0.0)
        assert codec_col <= codec_row, (
            f"columnar codec CPU {codec_col:.1f} ms exceeds the row wire's "
            f"{codec_row:.1f} ms"
        )


def bench_select(extra: dict) -> None:
    """Expression-VM select/filter pipeline throughput (native bytecode,
    reference expression.rs role)."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    N = SELECT_N
    rows = [(i, float(i % 97)) for i in range(N)]
    t = pw.debug.table_from_rows(pw.schema_from_types(a=int, b=float), rows)
    out = t.select(
        t.a,
        q=t.a * 3 + 1,
        r=t.b / 2.0,
        f=pw.if_else(t.a % 7 > 3, t.a, -t.a),
    )
    flt = out.filter(out.q % 5 != 0)
    cap = flt._capture_node()
    t0 = time.perf_counter()
    ctx = pw.run()
    dt = time.perf_counter() - t0
    n_out = len(ctx.state(cap)["rows"])
    assert n_out > 0
    log(f"select+filter pipeline: {N / dt:.0f} rows/s ({n_out} survivors)")
    extra["select_rows_per_sec"] = round(N / dt)


def bench_strdt(extra: dict) -> None:
    """String/datetime expression throughput: the OP_METHOD native
    namespace ops (reference evaluates these enums in Rust,
    src/engine/expression.rs:26-340)."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    N = STRDT_N
    rows = [
        (
            f"2020-03-{(i % 27) + 1:02d} 10:{i % 60:02d}:{(i * 7) % 60:02d}",
            f"  User {i} Name  ",
        )
        for i in range(N)
    ]
    t = pw.debug.table_from_rows(pw.schema_from_types(ts=str, name=str), rows)
    parsed = t.select(
        d=t.ts.str.parse_datetime("%Y-%m-%d %H:%M:%S"),
        clean=t.name.str.strip().str.lower(),
    )
    out = parsed.select(
        hour=parsed.d.dt.hour(),
        dow=parsed.d.dt.day_of_week(),
        stamp=parsed.d.dt.timestamp(),
        rounded=parsed.d.dt.round(pw.Duration(minutes=15)),
        tag=parsed.clean.str.replace(" ", "_"),
    )
    cap = out._capture_node()
    t0 = time.perf_counter()
    ctx = pw.run()
    dt = time.perf_counter() - t0
    assert len(ctx.state(cap)["rows"]) == N
    log(f"string/datetime pipeline: {N / dt:.0f} rows/s")
    extra["strdt_rows_per_sec"] = round(N / dt)


def bench_streaming_latency(extra: dict) -> None:
    """End-to-end streaming latency percentiles vs offered rate: timed
    source -> groupby count -> subscribe, latency = sink wall time minus
    the row's produce time.  Mirrors the reference's p50-p99
    latency-vs-rate suite
    (examples/projects/kafka-alternatives/benchmarks/README.md:19-33)."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    results = {}
    rates = (5_000,) if SMOKE else (10_000, 20_000, 30_000)
    for rate in rates:
        G.clear()
        # ~2s of traffic per rate step (~1s in smoke)
        n_msgs = min(rate, 6_000) if SMOKE else min(rate * 2, 40_000)

        class Source(pw.io.python.ConnectorSubject):
            def run(self) -> None:
                t_start = time.perf_counter()
                sent = 0
                while sent < n_msgs:
                    # pace to the offered rate in 1ms micro-slices
                    target = int((time.perf_counter() - t_start) * rate)
                    burst = min(target - sent, 2000)
                    if burst <= 0:
                        time.sleep(0.0005)
                        continue
                    now = time.perf_counter()
                    for i in range(sent, sent + burst):
                        self.next(
                            key=f"k{i % 100}", produced_at=now
                        )
                    sent += burst

        class S(pw.Schema):
            key: str
            produced_at: float

        t = pw.io.python.read(Source(), schema=S)
        counts = t.groupby(t.key).reduce(
            t.key,
            n=pw.reducers.count(),
            last_produced=pw.reducers.max(t.produced_at),
        )
        lats: list = []

        def on_change(key, row, time_, is_addition, lats=lats):
            if is_addition:
                lats.append(time.perf_counter() - row["last_produced"])

        pw.io.subscribe(counts, on_change)
        smoke_analyze(f"streaming_latency@{rate}")
        t0 = time.perf_counter()
        pw.run(autocommit_duration_ms=50, monitoring_level=pw.MonitoringLevel.NONE)
        wall = time.perf_counter() - t0
        lats.sort()

        def pct(p: float) -> float:
            return round(lats[min(len(lats) - 1, int(p * len(lats)))] * 1000.0, 1)

        achieved = n_msgs / wall
        # per-stage breakdown straight from the scheduler's latency probe
        # (ingest -> cut -> process -> sink -> e2e, streaming-safe
        # log-bucketed histograms; same numbers /metrics exports)
        sched = G.active_scheduler
        stages = sched.latency.snapshot() if sched is not None else {}
        results[str(rate)] = {
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "p99_ms": pct(0.99),
            "achieved_msgs_per_sec": round(achieved),
            "stages": stages,
        }
        log(
            f"streaming latency @ {rate} msg/s offered: "
            f"p50={pct(0.50)}ms p95={pct(0.95)}ms p99={pct(0.99)}ms "
            f"({achieved:.0f} msg/s achieved)"
        )
        for name, st in sorted(stages.items()):
            log(
                f"  stage {name:>8}: p50={st['p50_ms']}ms "
                f"p95={st['p95_ms']}ms p99={st['p99_ms']}ms "
                f"(n={st['count']})"
            )
    extra["streaming_latency_vs_rate"] = results
    if SMOKE:
        # smoke gate: with wakeup-driven cuts the tail tracks the median
        # — a p99/p50 dispersion blowout means a wait loop regressed to
        # timer polling somewhere
        probe = results[str(rates[0])]
        dispersion = probe["p99_ms"] / max(probe["p50_ms"], 0.1)
        extra["streaming_latency_smoke"] = {
            "p50_ms": probe["p50_ms"],
            "p99_ms": probe["p99_ms"],
            "dispersion_p99_over_p50": round(dispersion, 2),
        }
        if dispersion > 25.0:
            raise RuntimeError(
                f"streaming latency dispersion p99/p50 = {dispersion:.1f} "
                "exceeds the 25x smoke bound"
            )


def bench_checkpoint_overhead(extra: dict) -> None:
    """What epoch-aligned coordinated checkpointing charges the hot
    path: the same OPERATOR_PERSISTING wordcount run with periodic async
    checkpoints firing every ~50ms vs an interval too long to ever fire
    (both still take the final sync snapshot, so the delta is exactly
    the periodic pickle+enqueue cost the writer thread is meant to
    hide).  Best-of-3 per config to shave scheduler noise."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.testing.chaos import ClusterDrill

    # fixed corpus even in smoke: a 5% bound needs a run long enough
    # that scheduler jitter (a few ms) can't masquerade as overhead
    n_lines = 100_000 if SMOKE else min(WC_LINES, 200_000)
    d = tempfile.mkdtemp(prefix="pw_bench_ckpt_")
    fp = os.path.join(d, "lines.jsonl")
    rng = np.random.default_rng(2)
    with open(fp, "w") as f:
        for w in rng.integers(0, WC_WORDS, size=n_lines):
            f.write('{"word": "w%d"}\n' % w)
    # cap epoch size so the run cuts many epochs — checkpoints ride
    # epoch boundaries, one giant epoch would measure nothing
    saved_rows = os.environ.get("PATHWAY_EPOCH_MAX_ROWS")
    saved_interval = os.environ.pop("PATHWAY_CHECKPOINT_INTERVAL", None)
    os.environ["PATHWAY_EPOCH_MAX_ROWS"] = str(max(n_lines // 32, 64))

    def run_once(interval_s: float, tag: str, rep: int) -> float:
        G.clear()
        pdir = os.path.join(d, f"pstorage_{tag}_{rep}")
        out_fp = os.path.join(d, f"out_{tag}_{rep}.jsonl")

        # a real file sink, NOT _capture_node(): the debug capture keeps
        # the full update stream in operator state, so checkpointing it
        # would pickle O(corpus) bytes per snapshot and measure the
        # bench harness, not the engine
        class S(pw.Schema):
            word: str

        lines = pw.io.jsonlines.read(fp, schema=S, mode="static")
        counts = lines.groupby(lines.word).reduce(
            lines.word, n=pw.reducers.count()
        )
        pw.io.jsonlines.write(counts, out_fp)
        pconf = pw.persistence.Config(
            backend=pw.persistence.Backend.filesystem(pdir),
            persistence_mode=pw.persistence.PersistenceMode.OPERATOR_PERSISTING,
            checkpoint_interval=interval_s,
        )
        t0 = time.perf_counter()
        pw.run(autocommit_duration_ms=20, persistence_config=pconf)
        dt = time.perf_counter() - t0
        final = json.loads(ClusterDrill.canonical_output(out_fp))
        total = sum(final.values())
        assert total == n_lines, f"lost rows: {total} != {n_lines}"
        return dt

    try:
        log(f"checkpoint overhead: {n_lines} lines, OPERATOR_PERSISTING")
        run_once(3600.0, "warm", 0)  # discarded: imports + page cache
        # interleave configs: on a busy 1-core host, phase drift between
        # two back-to-back batches dwarfs the effect being measured
        base_times, ckpt_times = [], []
        for rep in range(3):
            base_times.append(run_once(3600.0, "off", rep))
            ckpt_times.append(run_once(0.05, "on", rep))
        base, ckpt = min(base_times), min(ckpt_times)
    finally:
        if saved_rows is None:
            os.environ.pop("PATHWAY_EPOCH_MAX_ROWS", None)
        else:
            os.environ["PATHWAY_EPOCH_MAX_ROWS"] = saved_rows
        if saved_interval is not None:
            os.environ["PATHWAY_CHECKPOINT_INTERVAL"] = saved_interval
    overhead = (ckpt - base) / base * 100.0
    extra["wordcount_checkpoint_overhead_pct"] = round(overhead, 2)
    extra["wordcount_checkpoint_base_seconds"] = round(base, 3)
    extra["wordcount_checkpoint_on_seconds"] = round(ckpt, 3)
    log(
        f"checkpoint overhead: off {base:.2f}s -> on {ckpt:.2f}s "
        f"= {overhead:+.1f}%"
    )
    if SMOKE and overhead > 5.0:
        raise RuntimeError(
            f"checkpoint overhead {overhead:.1f}% exceeds the 5% smoke "
            "bound — async checkpointing is blocking the hot path"
        )


def bench_cluster_recovery(extra: dict) -> None:
    """Kill-a-worker drill on a 2-process cluster: the seeded chaos
    harness kills one rank mid-run, the ClusterSupervisor restarts the
    generation, workers roll back to the last consistent checkpoint,
    and the recovered sink output must byte-match the fault-free run.
    Records detection+respawn wall time as ``cluster_recovery_seconds``."""
    from pathway_tpu.testing.chaos import ClusterDrill

    d = tempfile.mkdtemp(prefix="pw_bench_recover_")
    drill = ClusterDrill(d, seed=7, processes=2, rows=400, kill_epoch=4)
    log(
        f"cluster recovery drill: 2 processes, kill rank "
        f"{drill.kill_rank} at epoch {drill.kill_epoch}"
    )
    report = drill.run()
    rec = report["recovery_seconds"]
    extra["cluster_recovery_seconds"] = round(rec[0], 3) if rec else None
    extra["cluster_recovery_restarts"] = report["restarts"]
    extra["cluster_recovery_identical_output"] = report["identical"]
    log(
        f"cluster recovery: {report['restarts']} restart(s), "
        f"recovery {rec[0]:.3f}s, output identical={report['identical']}"
        if rec
        else f"cluster recovery: no restart observed ({report})"
    )
    if not report["identical"]:
        raise RuntimeError(
            "recovered sink output diverged from the fault-free run"
        )
    if not report["restarts"]:
        raise RuntimeError(f"chaos kill never fired: {report}")


def bench_index_churn(extra: dict) -> None:
    """Online index maintenance (``stdlib/indexing/segments.py``):
    sustained upsert throughput through the delta segment with
    background merges and a constant interleaved query load, then
    checkpoint-restore vs full-rebuild wall time — the number that
    justifies snapshotting the index into coordinated checkpoints so a
    restarted worker skips the corpus replay."""
    import jax

    from pathway_tpu.parallel import ShardedKnnIndex
    from pathway_tpu.stdlib.indexing.segments import SegmentedIndex

    n = 4_000 if SMOKE else 20_000
    churn = n // 2
    d = 64
    batch = 128
    k = 10
    rng = np.random.default_rng(11)
    x = rng.standard_normal((n, d)).astype(np.float32)

    # -- sustained upserts: device-slab main (in-place scatter merges —
    # the TPU-native serving index), one 8-query search every 4th batch
    # as the constant read load
    seg = SegmentedIndex(
        ShardedKnnIndex(d, metric="cos", capacity=n),
        delta_cap=512,
        auto_merge=True,
    )
    try:
        seg.add(list(zip(range(n), x)))  # bulk load: straight into main
        fresh = rng.standard_normal((churn, d)).astype(np.float32)
        victims = rng.integers(0, n, size=churn)
        q = rng.standard_normal((8, d)).astype(np.float32)
        log(f"index churn: {n} base docs, {churn} live upserts (batch {batch})")
        t0 = time.perf_counter()
        done = bi = 0
        while done < churn:
            m = min(batch, churn - done)
            keys = [
                int(victims[i]) if i % 2 == 0 else n + i
                for i in range(done, done + m)
            ]
            seg.add(list(zip(keys, fresh[done : done + m])))
            if bi % 4 == 0:
                seg.search(q, k)
            done += m
            bi += 1
        if seg._maintenance is not None:
            seg._maintenance.drain()  # sustained rate includes merge debt
        upsert_dt = time.perf_counter() - t0
        churn_stats = seg.stats()
    finally:
        seg.close()

    # -- checkpoint restore vs rebuild-from-raw on the device slab
    items = list(zip(range(n), x))

    def slab() -> SegmentedIndex:
        return SegmentedIndex(
            ShardedKnnIndex(d, metric="cos", capacity=n),
            delta_cap=512,
            auto_merge=False,
        )

    seg_r = slab()
    t0 = time.perf_counter()
    for lo in range(0, n, 1024):
        seg_r.add(items[lo : lo + 1024])
    jax.block_until_ready(seg_r.main._vectors)
    rebuild_s = time.perf_counter() - t0

    state = seg_r.state_dict()
    seg2 = slab()
    t0 = time.perf_counter()
    seg2.load_state_dict(state)
    jax.block_until_ready(seg2.main._vectors)
    restore_s = time.perf_counter() - t0
    if len(seg2) != n:
        raise RuntimeError(f"restore lost rows: {len(seg2)} != {n}")

    extra["knn_sustained_upsert_docs_per_sec"] = int(churn / upsert_dt)
    extra["index_churn_merges_total"] = churn_stats["merges_total"]
    extra["index_restore_seconds"] = round(restore_s, 4)
    extra["index_rebuild_seconds"] = round(rebuild_s, 4)
    extra["index_restore_speedup"] = round(rebuild_s / restore_s, 2)
    log(
        f"index churn: {extra['knn_sustained_upsert_docs_per_sec']} upserts/s "
        f"({churn_stats['merges_total']} merges); restore {restore_s:.3f}s "
        f"vs rebuild {rebuild_s:.3f}s ({extra['index_restore_speedup']}x)"
    )
    if SMOKE and restore_s >= rebuild_s:
        raise RuntimeError(
            f"checkpoint restore ({restore_s:.3f}s) not faster than a full "
            f"rebuild ({rebuild_s:.3f}s) — restoring the index snapshot "
            "buys nothing over replaying the corpus"
        )


def bench_capacity(extra: dict) -> None:
    """Capacity cross-validation (ISSUE 15): the static estimator's
    predicted steady-state operator bytes (``pw.estimate_memory`` with
    the ACTUAL run scenario in ``PATHWAY_MEMORY_*``) against the
    scheduler's sampled operator state (``approx_state_bytes`` over
    ``ctx.states``, the same numbers /metrics exports as
    ``pathway_tpu_state_bytes``) on two graphs: the batch wordcount
    (groupby state keyed by word) and a keyed index-churn pipeline
    (upsert source + external KNN index under re-upserts).  The ratio
    predicted/measured per graph lands in ``BENCH_capacity.json``;
    ``--smoke`` gates it to within 3x both ways — the estimator is a
    provisioning tool, an order-of-magnitude miss means its constants
    or growth classes no longer describe the engine."""
    import pathway_tpu as pw
    from pathway_tpu.internals.monitoring import memory_stats
    from pathway_tpu.internals.parse_graph import G

    bound = 3.0
    graphs: dict[str, dict] = {}
    saved_env: dict[str, str | None] = {}

    def set_scenario(**kv) -> dict:
        scenario = {}
        for k, v in kv.items():
            key = f"PATHWAY_MEMORY_{k.upper()}"
            saved_env.setdefault(key, os.environ.get(key))
            os.environ[key] = str(v)
            scenario[k] = v
        return scenario

    def compare(tag: str, scenario: dict) -> dict:
        sched = G.active_scheduler
        stats = memory_stats(sched) if sched is not None else {}
        ops = {}
        pred = meas = 0
        # only operators with BOTH a static estimate and sampled state
        # enter the ratio: stateless probes and un-modeled nodes would
        # turn the gate into a row-count comparison
        for label, v in sorted(stats.items()):
            if v["estimated"] > 0 and v["measured"] > 0:
                pred += v["estimated"]
                meas += v["measured"]
                ops[label] = {
                    "predicted_bytes": v["estimated"],
                    "measured_bytes": v["measured"],
                    "growth": v["growth"],
                    "ratio": round(v["estimated"] / v["measured"], 3),
                }
        if not ops:
            raise RuntimeError(
                f"capacity {tag}: no operator had both a static estimate "
                f"and sampled state ({len(stats)} probe(s))"
            )
        ratio = pred / meas
        log(
            f"capacity {tag}: predicted {pred} B vs measured {meas} B "
            f"-> {ratio:.2f}x over {len(ops)} stateful op(s)"
        )
        return {
            "scenario": scenario,
            "predicted_bytes": pred,
            "measured_bytes": meas,
            "ratio": round(ratio, 3),
            "operators": ops,
        }

    d = tempfile.mkdtemp(prefix="pw_bench_cap_")
    try:
        # -- graph 1: batch wordcount, state = one group per word --------
        n_lines = 20_000 if SMOKE else 100_000
        fp = os.path.join(d, "lines.jsonl")
        rng = np.random.default_rng(5)
        with open(fp, "w") as f:
            for w in rng.integers(0, WC_WORDS, size=n_lines):
                f.write('{"word": "w%d"}\n' % w)
        G.clear()
        scenario = set_scenario(rows=n_lines, keys=WC_WORDS, str_bytes=8)

        class S(pw.Schema):
            word: str

        lines = pw.io.jsonlines.read(fp, schema=S, mode="static")
        counts = lines.groupby(lines.word).reduce(
            lines.word, n=pw.reducers.count()
        )
        cap = counts._capture_node()
        ctx = pw.run()
        rows = ctx.state(cap)["rows"]
        total = sum(v[1] for v in rows.values())
        assert total == n_lines, f"lost rows: {total} != {n_lines}"
        graphs["wordcount"] = compare("wordcount", scenario)

        # -- graph 2: keyed upserts through an external KNN index --------
        # (examples/index_churn.py at bench scale: every key re-upserted
        # once, so the index holds n_docs live vectors after 1.5x adds)
        n_docs = 1_000 if SMOKE else 4_000
        churn = n_docs // 2
        # the scenario's ``keys`` knob is global (one cardinality for
        # every upsert source), so the query feed runs at half the doc
        # count rather than a token handful — otherwise the per-op
        # breakdown for the query source would be a pure scenario miss
        n_q = n_docs // 2
        G.clear()
        scenario = set_scenario(
            rows=n_docs + churn + n_q,
            keys=n_docs,
            str_bytes=8,
            array_bytes=160,
        )
        from pathway_tpu.io.python import ConnectorSubject
        from pathway_tpu.stdlib.indexing import BruteForceKnnFactory

        class Doc(pw.Schema):
            doc_id: str = pw.column_definition(primary_key=True)
            vx: float
            vy: float
            vz: float
            vw: float

        class Query(pw.Schema):
            qid: str = pw.column_definition(primary_key=True)
            qx: float
            qy: float
            qz: float
            qw: float

        vec_rng = np.random.default_rng(6)
        vecs = vec_rng.standard_normal((n_docs + churn, 4)).astype(float)

        class DocFeed(ConnectorSubject):
            def run(self) -> None:
                for i in range(n_docs + churn):
                    # the tail re-upserts existing keys: delta churn
                    key = i if i < n_docs else (i - n_docs) * 2
                    self.next(
                        doc_id=f"doc{key}",
                        vx=vecs[i, 0],
                        vy=vecs[i, 1],
                        vz=vecs[i, 2],
                        vw=vecs[i, 3],
                    )
                    if i % 512 == 511:
                        self.commit()
                self.commit()

        class QueryFeed(ConnectorSubject):
            def run(self) -> None:
                for i in range(n_q):
                    self.next(
                        qid=f"q{i}", qx=1.0, qy=float(i), qz=0.0, qw=0.0
                    )
                self.commit()

        docs = pw.io.python.read(DocFeed("docs"), schema=Doc, name="docs")
        docs = docs.select(
            doc_id=pw.this.doc_id,
            vec=pw.apply(
                lambda a, b, c, e: (float(a), float(b), float(c), float(e)),
                pw.this.vx,
                pw.this.vy,
                pw.this.vz,
                pw.this.vw,
            ),
        )
        queries = pw.io.python.read(
            QueryFeed("queries"), schema=Query, name="queries"
        )
        queries = queries.select(
            qid=pw.this.qid,
            qvec=pw.apply(
                lambda a, b, c, e: (float(a), float(b), float(c), float(e)),
                pw.this.qx,
                pw.this.qy,
                pw.this.qz,
                pw.this.qw,
            ),
        )
        index = BruteForceKnnFactory(
            dimensions=4, reserved_space=n_docs + n_q
        ).build_data_index(docs.vec, docs)
        hits = index.query_as_of_now(queries.qvec, number_of_matches=2)
        answered: list = []
        pw.io.subscribe(
            hits,
            on_change=lambda key, row, time, is_addition: answered.append(key),
        )
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
        assert answered, "index-churn queries produced no results"
        graphs["index_churn"] = compare("index_churn", scenario)
    finally:
        for key, old in saved_env.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old

    for tag, rep in graphs.items():
        extra[f"capacity_{tag}_ratio"] = rep["ratio"]
        extra[f"capacity_{tag}_predicted_bytes"] = rep["predicted_bytes"]
        extra[f"capacity_{tag}_measured_bytes"] = rep["measured_bytes"]
    out = artifact_path("BENCH_capacity.json")
    with open(out, "w") as f:
        json.dump(
            {
                "cmd": "JAX_PLATFORMS=cpu python bench.py (bench_capacity)",
                "estimator": (
                    "pw.estimate_memory with PATHWAY_MEMORY_* pinned to "
                    "the run scenario vs approx_state_bytes sampled over "
                    "ctx.states at run end; ratio over operators with "
                    "both an estimate and live state"
                ),
                "bound_x": bound,
                "graphs": graphs,
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")
    log(f"wrote {out}")
    if SMOKE:
        for tag, rep in graphs.items():
            r = rep["ratio"]
            if not (1.0 / bound <= r <= bound):
                raise RuntimeError(
                    f"capacity prediction on {tag} is {r:.2f}x measured — "
                    f"outside the {bound:g}x cross-validation bound"
                )


def bench_device(extra: dict) -> None:
    """Device-safety cross-validation (ISSUE 20): the PW-J static
    analyzer's recompile-site prediction joined with the runtime
    jit-compile counter (``jax.monitoring`` backend_compile events).

    Three measurements over the live IVF index:

    1. **warmup**: a sweep of 39 distinct query-batch sizes — bucketed
       padding means compiles grow with the LOG of the size range, not
       linearly (the pre-fix tree compiled once per distinct size);
    2. **steady state**: the identical sweep again — the zero-recompile
       invariant: a warmed serving loop must hit the executable cache on
       every dispatch, so the compile-counter delta is exactly 0;
    3. **shape-unstable control**: a fresh jit called over linearly
       growing shapes — one compile per call, proving the counter sees
       real compiles (the storm the analyzer's PW-J001 predicts).

    The smoke gate fails the run when steady-state compiles != 0, when
    the control records nothing, or when the static sweep predicts
    recompile sites on the committed tree."""
    import jax
    import jax.numpy as jnp

    from pathway_tpu.analysis.device import device_profile
    from pathway_tpu.internals import device_counters as devctr
    from pathway_tpu.parallel.ivf_knn import IvfKnnIndex

    devctr.install()
    profile = device_profile(refresh=True)
    predicted = profile["predicted_recompile_sites"]
    log(
        f"device: static sweep over {profile['files_scanned']} device "
        f"modules: {profile['findings']} finding(s), "
        f"{predicted} predicted recompile site(s)"
    )

    dim = 32
    n_docs = 1536
    rng = np.random.default_rng(17)
    idx = IvfKnnIndex(dim, capacity=1024, query_block=8)
    idx.add_batch(
        [f"d{i}" for i in range(n_docs)],
        rng.standard_normal((n_docs, dim)).astype(np.float32),
    )
    if not idx.trained:
        idx.train()

    sizes = list(range(1, 40))  # 39 distinct serving batch sizes
    h2d0 = devctr.snapshot()["h2d_bytes"]

    base = devctr.compile_count()
    for nq in sizes:
        idx.search(rng.standard_normal((nq, dim)).astype(np.float32), k=5)
    warmup_compiles = devctr.compile_count() - base

    base = devctr.compile_count()
    t0 = time.perf_counter()
    for nq in sizes:
        idx.search(rng.standard_normal((nq, dim)).astype(np.float32), k=5)
    steady_s = time.perf_counter() - t0
    steady_compiles = devctr.compile_count() - base
    h2d_bytes = devctr.snapshot()["h2d_bytes"] - h2d0

    # shape-unstable control: what an unbucketed hot path looks like —
    # every distinct length is a fresh trace+compile
    @jax.jit
    def _unsteady(x):
        return (x * x).sum()

    base = devctr.compile_count()
    for n in range(1, 8):
        _unsteady(jnp.ones((n,), jnp.float32)).block_until_ready()
    unstable_compiles = devctr.compile_count() - base

    extra["device_predicted_recompile_sites"] = predicted
    extra["device_warmup_compiles"] = warmup_compiles
    extra["device_steady_state_compiles"] = steady_compiles
    extra["device_unbucketed_compiles"] = unstable_compiles
    log(
        f"device: warmup={warmup_compiles} compiles over {len(sizes)} "
        f"sizes, steady-state={steady_compiles}, unbucketed control="
        f"{unstable_compiles}, steady sweep {steady_s * 1e3:.1f} ms, "
        f"h2d {h2d_bytes} B"
    )

    out = artifact_path("BENCH_device.json")
    with open(out, "w") as f:
        json.dump(
            {
                "cmd": "JAX_PLATFORMS=cpu python bench.py (bench_device)",
                "counter": (
                    "jax.monitoring backend_compile_duration events "
                    "(one per real XLA compile; cache hits emit nothing) "
                    "via pathway_tpu.internals.device_counters"
                ),
                "sweep": {
                    "distinct_batch_sizes": len(sizes),
                    "warmup_compiles": warmup_compiles,
                    "steady_state_compiles": steady_compiles,
                    "unbucketed_control_compiles": unstable_compiles,
                },
                "ivf_fix": {
                    # measured on this sweep against the pre-fix tree
                    # (ivf_knn.py padding rows to a MULTIPLE of
                    # query_block instead of a power-of-two block count,
                    # and _assign_cells uploading unpadded batches):
                    # one program per distinct size
                    "before_compiles": 46,
                    "after_compiles": warmup_compiles,
                    "finding_codes": ["PW-J001"],
                },
                "cross_validation": {
                    "static_predicted_recompile_sites": predicted,
                    "observed_steady_state_compiles": steady_compiles,
                    "agree": predicted == 0 and steady_compiles == 0,
                },
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")
    log(f"wrote {out}")

    if SMOKE:
        if steady_compiles != 0:
            raise RuntimeError(
                f"zero-recompile invariant broken: {steady_compiles} "
                "compile(s) in the steady-state sweep — a hot path is "
                "tracing new shapes after warmup"
            )
        if unstable_compiles == 0:
            raise RuntimeError(
                "shape-unstable control recorded 0 compiles — the "
                "jit-compile counter is not seeing backend compiles"
            )
        if predicted != 0:
            raise RuntimeError(
                f"static sweep predicts {predicted} recompile site(s) "
                "on the committed device modules — fix or waive "
                "(# pw-j001:) before shipping"
            )
        if h2d_bytes <= 0:
            raise RuntimeError(
                "no H2D bytes recorded during the serving sweep — "
                "transfer accounting is dead"
            )


def bench_rag_serving(extra: dict) -> None:
    """Multi-tenant RAG serving (``pathway_tpu/serving/``, ISSUE 10):
    per-tenant-class p50/p99 vs offered load, measured open-loop under
    the paper's live regime — an interactive tenant querying while a
    rate-capped batch tenant mixes queries with index upserts, so every
    load point exercises admission shed, SLO-class scheduling, and
    lookahead retrieval against a churning index at once."""
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.serving import LoadGen, RagServingApp, TenantLoad, TenantPolicy

    points = (15.0, 60.0, 240.0) if SMOKE else (20.0, 80.0, 320.0)
    duration = 1.2 if SMOKE else 2.5
    n_docs = 48
    rng = np.random.default_rng(29)
    vocab = ["solar", "merge", "slab", "tail", "bucket", "chunk", "probe", "lane"]
    docs = [
        (f"doc{i}", " ".join(rng.choice(vocab) for _ in range(30)))
        for i in range(n_docs)
    ]
    rows = []
    for qi, qps in enumerate(points):
        G.clear()
        pols = {
            # interactive tenant provisioned above its offer: its tail
            # is the scheduler's to hold, not admission's to hide
            "live": TenantPolicy(
                "interactive",
                rate_per_s=max(qps * 4, 50.0),
                burst=max(qps, 16.0),
                queue_cap=256,
            ),
            # batch tenant capped at half its offer: shed must grow
            # with load instead of queueing into the interactive tail
            "bulk": TenantPolicy(
                "batch", rate_per_s=max(qps / 2, 2.0), burst=8, queue_cap=16
            ),
        }
        app = RagServingApp(pols, embed_dim=64, delta_cap=64, autocommit_ms=10)
        app.start()
        try:
            for doc_id, text in docs:
                app.upsert(doc_id, text, tenant="live")
            if not app.wait_indexed(n_docs, timeout=30.0):
                raise RuntimeError(f"ingest stalled: {app.stats()}")
            for _ in range(3):  # warm the embed/search/generate lanes
                app.answer("bucket probe lane", tenant="live", timeout=30)
            rep = LoadGen(
                app,
                [
                    TenantLoad("live", qps=qps),
                    TenantLoad("bulk", qps=qps, write_fraction=0.4),
                ],
                duration_s=duration,
                seed=13 + qi,
            ).run()
            cls = rep["classes"]
            cos = app.coscheduler.stats()
            rows.append(
                {
                    "offered_qps_per_tenant": qps,
                    "interactive": cls.get("interactive", {}),
                    "batch": cls.get("batch", {}),
                    "lookahead_overlap_ms_mean": round(cos["overlap_ms_mean"], 4),
                    "index_merges": app.index.stats()["merges_total"],
                }
            )
            inter = cls["interactive"]
            log(
                f"rag serving @ {qps:g} qps/tenant: interactive "
                f"p50 {inter['p50_ms']:.2f}ms p99 {inter['p99_ms']:.2f}ms "
                f"shed {inter['shed']}; batch shed {cls['batch']['shed']} "
                f"writes {cls['batch']['writes']}"
            )
        finally:
            app.close()
    extra["rag_serving_points"] = rows
    low, high = rows[0], rows[-1]
    extra["rag_serving_interactive_p50_ms_low_load"] = low["interactive"]["p50_ms"]
    extra["rag_serving_interactive_p99_ms_low_load"] = low["interactive"]["p99_ms"]
    extra["rag_serving_interactive_p99_ms_high_load"] = high["interactive"]["p99_ms"]
    extra["rag_serving_interactive_shed_total"] = sum(
        r["interactive"]["shed"] for r in rows
    )
    extra["rag_serving_batch_shed_high_load"] = high["batch"]["shed"]
    extra["rag_serving_lookahead_overlap_ms_mean"] = rows[-1][
        "lookahead_overlap_ms_mean"
    ]
    if SMOKE:
        p50 = max(low["interactive"]["p50_ms"], 0.05)
        p99 = low["interactive"]["p99_ms"]
        if p99 > 5.0 * p50:
            raise RuntimeError(
                f"interactive tail blew past the SLO at LOW load: "
                f"p99 {p99:.2f}ms > 5x p50 {p50:.2f}ms — the class "
                "partition is not holding even without contention"
            )


def bench_tracing(extra: dict) -> None:
    """Tracing overhead gate + critical-path attribution (ISSUE 14).
    The flight recorder is only allowed to stay always-on if it is
    effectively free, so the same wordcount and serving workloads run
    tracing-off vs tracing-on (sample=1.0); ``--smoke`` enforces <=2%
    on both.  Measurement discipline, tuned on a 1-core shared host
    where wall-clock drifts 10-20% in multi-second phases:

    - wordcount gates on PROCESS CPU seconds (the recorder's cost is
      pure CPU; wall time on a preempted core measures the neighbors),
      median per-pair delta over order-alternated on/off run pairs
    - serving gates on the tracing work itself, timed in situ: every
      tracing entry point is wrapped with a timer for a request batch
      and the summed per-request cost (wrapper-calibrated, still
      conservative) is divided by the tracing-off p50 — block-p50
      noise is +-20% here, so differencing a sub-1% effect is hopeless

    The tracing-on runs feed ``analysis/tracecrit.py`` and the
    per-stage p50/p99 attribution of the wordcount epochs and the
    rag-serving requests lands in ``BENCH_trace.json``."""
    import gc

    import pathway_tpu as pw
    from pathway_tpu.analysis import tracecrit
    from pathway_tpu.internals import tracing
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.serving import RagServingApp, TenantPolicy

    n_lines = 100_000 if SMOKE else min(WC_LINES, 200_000)
    d = tempfile.mkdtemp(prefix="pw_bench_trace_")
    fp = os.path.join(d, "lines.jsonl")
    rng = np.random.default_rng(3)
    with open(fp, "w") as f:
        for w in rng.integers(0, WC_WORDS, size=n_lines):
            f.write('{"word": "w%d"}\n' % w)
    # many short epochs: the traced span set scales with epoch count, so
    # one giant epoch would measure an idle recorder
    saved_rows = os.environ.get("PATHWAY_EPOCH_MAX_ROWS")
    os.environ["PATHWAY_EPOCH_MAX_ROWS"] = str(max(n_lines // 32, 64))

    def run_wc(tag: str, rep: int) -> tuple[float, float]:
        G.clear()

        class S(pw.Schema):
            word: str

        lines = pw.io.jsonlines.read(fp, schema=S, mode="static")
        counts = lines.groupby(lines.word).reduce(
            lines.word, n=pw.reducers.count()
        )
        out_fp = os.path.join(d, f"out_{tag}_{rep}.jsonl")
        pw.io.jsonlines.write(counts, out_fp)
        gc.collect()
        w0 = time.perf_counter()
        c0 = time.process_time()
        pw.run(autocommit_duration_ms=20)
        return time.process_time() - c0, time.perf_counter() - w0

    saved_trace = os.environ.get("PATHWAY_TRACE")
    saved_sample = os.environ.get("PATHWAY_TRACE_SAMPLE")
    app = None
    try:
        log(f"tracing overhead: wordcount {n_lines} lines, on vs off")
        tracing.configure(PATHWAY_TRACE="1", PATHWAY_TRACE_SAMPLE="1.0")
        # two discarded warm runs: imports + page cache, and the first
        # measured pair still drifts ~20% downward on a cold heap
        run_wc("warm", 0)
        run_wc("warm", 1)
        # --- wordcount attribution run (tracing on, full sampling) ---
        t_mark = time.monotonic_ns()
        run_wc("attr", 0)
        wc_events = tracing.chrome_events(since_ns=t_mark, all_spans=True)
        wc_report = tracecrit.report(wc_events)
        # --- wordcount overhead: paired CPU-seconds runs, order
        # alternated (off-on, on-off, ...), gated on the MEDIAN of the
        # per-pair deltas.  A slow host phase hits both members of a
        # pair about equally, order alternation cancels within-pair
        # drift, and the median discards the pairs a phase boundary
        # still splits — min-of-N flaps several % on this host ---
        off_times, on_times, deltas = [], [], []
        for rep in range(8):
            order = ("0", "1") if rep % 2 == 0 else ("1", "0")
            cpu = {}
            for mode in order:
                tracing.configure(PATHWAY_TRACE=mode)
                c, _w = run_wc("on" if mode == "1" else "off", rep)
                cpu[mode] = c
            off_times.append(cpu["0"])
            on_times.append(cpu["1"])
            deltas.append((cpu["1"] - cpu["0"]) / cpu["0"] * 100.0)
        deltas.sort()
        wc_overhead = deltas[len(deltas) // 2]
        wc_off, wc_on = min(off_times), min(on_times)
        log(
            f"tracing overhead wordcount: median paired delta "
            f"{wc_overhead:+.2f}% over {len(deltas)} pairs "
            f"(min cpu off {wc_off:.2f}s / on {wc_on:.2f}s)"
        )
        # --- serving: one long-lived app, alternating request blocks.
        # A representative request (256-dim embed, HNSW k=16 over 768
        # docs, extractive generate) runs ~2ms; the recorder's ~10-15us
        # of spans must stay inside 2% of THAT, not of an empty loop ---
        G.clear()
        tracing.configure(PATHWAY_TRACE="1", PATHWAY_TRACE_SAMPLE="1.0")
        app = RagServingApp(
            {"live": TenantPolicy("interactive", rate_per_s=1e9, burst=1e9)},
            embed_dim=256,
            delta_cap=1024,
            autocommit_ms=10,
        )
        app.start()
        vocab = [
            "solar", "merge", "slab", "tail", "bucket", "probe", "chunk",
            "lane", "shard", "epoch", "frame", "torus", "slice", "queue",
            "token", "graph",
        ]
        n_docs = 768
        for i in range(n_docs):
            app.upsert(
                f"doc{i}",
                " ".join(vocab[(i * 7 + j) % 16] for j in range(80)),
            )
        if not app.wait_indexed(n_docs, timeout=120.0):
            raise RuntimeError(f"ingest stalled: {app.stats()}")
        query = " ".join(vocab[j % 16] for j in range(12))

        def serve_block(n: int, lats: list) -> None:
            pc = time.perf_counter
            for i in range(n):
                t0 = pc()
                app.answer(
                    query + " " + vocab[i % 16], tenant="live", k=16,
                    timeout=30,
                )
                lats.append(pc() - t0)

        serve_block(300, [])  # warm the embed/search/generate lanes
        # attribution batch first (tracing is on, sample=1.0)
        t_mark = time.monotonic_ns()
        serve_block(200, [])
        srv_events = tracing.chrome_events(since_ns=t_mark, all_spans=True)
        srv_report = tracecrit.report(srv_events)
        # --- serving gate: time the tracing work itself, in situ.
        # The recorder adds ~15us to a ~2ms request; block-p50 noise on
        # this host is +-20%, so on/off differencing cannot resolve a
        # sub-1% effect in bounded time.  Instead every tracing entry
        # point is wrapped with a timer for a measured request batch —
        # that sums the ACTUAL per-request tracing cost (cold caches
        # and all), calibrated by subtracting the wrapper's own no-op
        # cost (under-subtraction leaves the estimate conservative) ---
        acc_ns: dict = {}
        acc_n: dict = {}
        saved_fns = {}

        def _timed(name, fn):
            pc = time.perf_counter_ns

            def w(*a, **k):
                t0 = pc()
                r = fn(*a, **k)
                dt = pc() - t0
                acc_ns[name] = acc_ns.get(name, 0) + dt
                acc_n[name] = acc_n.get(name, 0) + 1
                return r

            return w

        wrapped = (
            "record_span", "record_spans", "new_trace",
            "finish_request", "set_ambient",
        )
        # two timed batches, keep the cheaper one: a slow host phase
        # inflates the timers themselves, and min-of-2 sheds it
        n_timed = 250
        batches = []
        try:
            for name in wrapped:
                saved_fns[name] = getattr(tracing, name)
                setattr(tracing, name, _timed(name, saved_fns[name]))
            for _ in range(2):
                acc_ns.clear()
                acc_n.clear()
                serve_block(n_timed, [])
                batches.append((dict(acc_ns), dict(acc_n)))
        finally:
            for name, fn in saved_fns.items():
                setattr(tracing, name, fn)
        # calibrate: per-call cost of the timing wrapper around a no-op
        acc_ns.clear()
        acc_n.clear()
        nop = _timed("_nop", lambda: None)
        for _ in range(20_000):
            nop()
        wrap_ns = acc_ns.pop("_nop") / acc_n.pop("_nop")
        per_batch = [
            max(0.0, (sum(ns.values()) - sum(n.values()) * wrap_ns)
                / 1e3 / n_timed)
            for ns, n in batches
        ]
        traced_us = min(per_batch)
        n_calls = sum(batches[0][1].values())
        # baseline p50 with tracing off (pooled over two blocks)
        tracing.configure(PATHWAY_TRACE="0")
        off_lats: list = []
        serve_block(150, off_lats)
        serve_block(150, off_lats)
        off_lats.sort()
        srv_off = off_lats[len(off_lats) // 2]
        tracing.configure(PATHWAY_TRACE="1")
        on_lats: list = []
        serve_block(150, on_lats)
        on_lats.sort()
        srv_on = on_lats[len(on_lats) // 2]
        srv_overhead = traced_us / (srv_off * 1e6) * 100.0
        log(
            f"tracing overhead serving: {traced_us:.1f}us of traced work "
            f"per request ({n_calls / n_timed:.0f} calls), p50 off "
            f"{srv_off * 1e6:.0f}us -> {srv_overhead:+.2f}% "
            f"(p50 on {srv_on * 1e6:.0f}us, informational)"
        )
    finally:
        if app is not None:
            app.close()
        if saved_rows is None:
            os.environ.pop("PATHWAY_EPOCH_MAX_ROWS", None)
        else:
            os.environ["PATHWAY_EPOCH_MAX_ROWS"] = saved_rows
        tracing.configure(
            PATHWAY_TRACE=saved_trace, PATHWAY_TRACE_SAMPLE=saved_sample
        )

    extra["tracing_overhead_wordcount_pct"] = round(wc_overhead, 2)
    extra["tracing_overhead_serving_pct"] = round(srv_overhead, 2)
    extra["tracing_serving_p50_us_on"] = round(srv_on * 1e6, 1)
    extra["tracing_serving_p50_us_off"] = round(srv_off * 1e6, 1)
    extra["tracing_wordcount_attribution"] = wc_report.get(
        "mean_by_category_ms", {}
    )
    extra["tracing_serving_attribution"] = srv_report.get(
        "mean_by_category_ms", {}
    )
    out = artifact_path("BENCH_trace.json")
    with open(out, "w") as f:
        json.dump(
            {
                "cmd": "JAX_PLATFORMS=cpu python bench.py (bench_tracing)",
                "config": {
                    "wordcount_lines": n_lines,
                    "wordcount_estimator": (
                        "median per-pair process-CPU delta over 8 "
                        "order-alternated on/off run pairs (gc.collect "
                        "before each run)"
                    ),
                    "serving_workload": {
                        "embed_dim": 256,
                        "docs": n_docs,
                        "words_per_doc": 80,
                        "k": 16,
                    },
                    "serving_estimator": (
                        "in-situ timed tracing entry points over "
                        f"{n_timed} requests, wrapper-cost calibrated, "
                        "divided by tracing-off p50"
                    ),
                    "serving_traced_us_per_request": round(traced_us, 2),
                    "sampling": 1.0,
                },
                "overhead_pct": {
                    "wordcount": round(wc_overhead, 2),
                    "serving": round(srv_overhead, 2),
                    "serving_p50_us_off": round(srv_off * 1e6, 1),
                    "serving_p50_us_on": round(srv_on * 1e6, 1),
                    "bound_pct": 2.0,
                },
                "wordcount": wc_report,
                "rag_serving": srv_report,
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")
    log(f"wrote {out}")
    if SMOKE:
        for name, pct in (("wordcount", wc_overhead), ("serving", srv_overhead)):
            if pct > 2.0:
                raise RuntimeError(
                    f"tracing overhead on {name} is {pct:.2f}% — over the "
                    "2% always-on budget; the recorder is no longer free"
                )


def bench_failover(extra: dict) -> None:
    """Partial-failure survival (ISSUE 13): availability while one of two
    shard owners is dead, and the per-shard failover time (snapshot
    restore + exactly-once oplog tail replay) vs the whole-generation
    recovery path ``bench_cluster_recovery`` measures — the number that
    justifies per-rank restart over tearing the mesh down."""
    from pathway_tpu.serving import HashingEmbedder, StageCoScheduler
    from pathway_tpu.serving.failover import PartitionedIndex
    from pathway_tpu.serving.loadgen import percentile
    from pathway_tpu.stdlib.indexing.hnsw import HnswIndex
    from pathway_tpu.stdlib.indexing.segments import SegmentedIndex

    dim = 32
    n_docs = 120 if SMOKE else 240
    healthy_s, outage_s, recovered_s = (
        (0.4, 0.4, 0.3) if SMOKE else (0.8, 0.8, 0.5)
    )
    rng = np.random.default_rng(31)
    part = PartitionedIndex(
        lambda: SegmentedIndex(
            HnswIndex(dim, metric="cos"), delta_cap=64, auto_merge=False
        ),
        n_shards=2,
        snapshot_every=64,
    )
    co = StageCoScheduler(
        embedder=HashingEmbedder(dim=dim), index=part, k=4, lookahead=True
    )
    vocab = ["solar", "merge", "slab", "tail", "bucket", "chunk", "probe", "lane"]
    try:
        part.add(
            [
                (
                    f"doc{i}",
                    HashingEmbedder(dim=dim)(
                        " ".join(rng.choice(vocab) for _ in range(12))
                    ),
                )
                for i in range(n_docs)
            ]
        )
        co.submit("bucket probe lane").result(timeout=30)  # warm the lanes

        def load_phase(seconds: float) -> dict:
            ok: list[dict] = []
            errors = 0
            deadline = time.perf_counter() + seconds
            i = 0
            while time.perf_counter() < deadline:
                fut = co.submit(f"{vocab[i % len(vocab)]} probe {i}")
                try:
                    ok.append(fut.result(timeout=10))
                except Exception:  # noqa: BLE001 — counted, not masked
                    errors += 1
                i += 1
            lat = [r["latency_ms"] for r in ok]
            n = len(ok) + errors
            return {
                "responses": n,
                "availability": round(len(ok) / max(n, 1), 4),
                "partial_fraction": round(
                    sum(1 for r in ok if r["partial"]) / max(len(ok), 1), 4
                ),
                "p50_ms": round(percentile(lat, 50.0), 3) if lat else None,
                "p99_ms": round(percentile(lat, 99.0), 3) if lat else None,
            }

        healthy = load_phase(healthy_s)
        part.fail_shard(1)  # one owner dies; survivors keep answering
        # writes during the outage land in the dead owner's oplog and
        # must survive the restore via the exactly-once tail replay
        part.add(
            [
                (
                    f"late{j}",
                    HashingEmbedder(dim=dim)(
                        " ".join(rng.choice(vocab) for _ in range(12))
                    ),
                )
                for j in range(32)
            ]
        )
        outage = load_phase(outage_s)
        failover_s = part.recover_shard(1)
        recovered = load_phase(recovered_s)

        owner = part.owners[1]
        generation_s = extra.get("cluster_recovery_seconds")
        extra["failover_phases"] = {
            "healthy": healthy,
            "outage": outage,
            "recovered": recovered,
        }
        extra["failover_seconds"] = round(failover_s, 4)
        extra["failover_tail_replayed"] = owner.tail_replayed
        extra["failover_outage_availability"] = outage["availability"]
        extra["failover_degraded_fraction"] = outage["partial_fraction"]
        if generation_s:
            extra["failover_vs_generation_speedup"] = round(
                generation_s / max(failover_s, 1e-9), 2
            )
        log(
            f"failover: outage availability {outage['availability']:.3f} "
            f"(partial {outage['partial_fraction']:.0%}, p99 "
            f"{outage['p99_ms']}ms), shard restore {failover_s * 1e3:.1f}ms"
            + (
                f" vs whole-generation {generation_s:.3f}s "
                f"({extra['failover_vs_generation_speedup']}x)"
                if generation_s
                else ""
            )
        )
        if SMOKE:
            if outage["availability"] < 1.0:
                raise RuntimeError(
                    f"queries errored during the outage window "
                    f"(availability {outage['availability']:.3f}) — degraded "
                    "serving must answer partial, never 5xx"
                )
            if outage["partial_fraction"] <= 0.0:
                raise RuntimeError(
                    "no response reported partial coverage with a dead "
                    "shard — the partial-result contract is not surfacing"
                )
            if recovered["partial_fraction"] > 0.0:
                raise RuntimeError(
                    "responses still partial after the shard recovered"
                )
            if generation_s and failover_s >= generation_s:
                raise RuntimeError(
                    f"per-shard failover ({failover_s:.3f}s) not faster than "
                    f"whole-generation recovery ({generation_s:.3f}s)"
                )
    finally:
        co.close()
        part.close()


def _vm_rss_bytes() -> int:
    """Resident set size of this process, from /proc (no psutil)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


_SIGSTOP_PEER_PROGRAM = """\
import sys, time

port, n_frames = int(sys.argv[1]), int(sys.argv[2])
from pathway_tpu.engine.cluster import _ProcessLinks

links = _ProcessLinks(1, 2, port, heartbeat_s=0.2, liveness_timeout_s=30.0)
try:
    for i in range(n_frames):
        links.recv_from_all(("s", i))
        time.sleep(0.05)
finally:
    links.close()
print("drained", flush=True)
"""


def bench_overload(extra: dict) -> None:
    """End-to-end backpressure drill (ISSUE 16): offered load vs
    goodput/shed-rate/p99/max-RSS at 1x/2x/5x of measured serving
    capacity, then a SIGSTOP'd (alive, not dead) exchange peer to show
    the credit window capping sender-side backlog, with the stall time
    attributed by ``analysis/tracecrit.py`` as ``credit_wait`` spans.

    The ladder runs the full pressure chain for real: a small
    PATHWAY_INGEST_BUFFER_BYTES makes the bulk tenant's upserts fill the
    ingest credit ledger, the engine scheduler pushes that occupancy to
    serving, and brownout tightens the batch class while interactive
    keeps flowing — the ``--smoke`` gates are bounded RSS at 5x and
    interactive p99(5x) <= 5x the 1x-load p99."""
    import socket
    import subprocess
    import sys as _sys
    import threading

    from pathway_tpu.analysis import tracecrit
    from pathway_tpu.engine.cluster import _ProcessLinks
    from pathway_tpu.internals import tracing
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.serving import LoadGen, RagServingApp, TenantLoad, TenantPolicy
    from pathway_tpu.testing.chaos import chaos

    duration = 1.2 if SMOKE else 5.0
    ingest_cap = 32 * 1024  # small on purpose: overload must FILL it
    saved_env = {
        k: os.environ.get(k)
        for k in ("PATHWAY_INGEST_BUFFER_BYTES", "PATHWAY_EXCHANGE_CREDIT_BYTES")
    }
    saved_trace = os.environ.get("PATHWAY_TRACE")
    saved_sample = os.environ.get("PATHWAY_TRACE_SAMPLE")
    os.environ["PATHWAY_INGEST_BUFFER_BYTES"] = str(ingest_cap)

    rng = np.random.default_rng(31)
    vocab = ["solar", "merge", "slab", "tail", "bucket", "chunk", "probe", "lane"]
    n_docs = 48
    docs = [
        (f"doc{i}", " ".join(rng.choice(vocab) for _ in range(30)))
        for i in range(n_docs)
    ]

    def build_app(cap: float) -> "RagServingApp":
        # policies are provisioned for 1x CAPACITY and frozen across the
        # ladder — overload means the offer outgrows the provision, so
        # shed must rise with the multiplier instead of the caps
        # silently stretching to absorb it
        G.clear()
        pols = {
            "live": TenantPolicy(
                "interactive",
                rate_per_s=cap * 4,
                burst=max(cap, 16.0),
                queue_cap=256,
            ),
            "bulk": TenantPolicy(
                "batch", rate_per_s=max(cap / 2, 2.0), burst=8, queue_cap=16
            ),
        }
        app = RagServingApp(pols, embed_dim=64, delta_cap=64, autocommit_ms=10)
        app.start()
        for doc_id, text in docs:
            app.upsert(doc_id, text, tenant="live")
        if not app.wait_indexed(n_docs, timeout=30.0):
            raise RuntimeError(f"ingest stalled: {app.stats()}")
        for _ in range(3):
            app.answer("bucket probe lane", tenant="live", timeout=30)
        return app

    # --- calibrate 1x: closed-loop service rate of one interactive lane
    # (clamped to what a single open-loop pacing thread can honestly
    # offer at 5x — attempted qps is recorded per point regardless) ---
    app = build_app(50.0)
    try:
        n_cal = 24 if SMOKE else 60
        t0 = time.perf_counter()
        for i in range(n_cal):
            app.answer("bucket probe " + vocab[i % 8], tenant="live", timeout=30)
        cap_qps = min(max(n_cal / (time.perf_counter() - t0), 10.0), 150.0)
    finally:
        app.close()
    log(f"overload: calibrated serving capacity ~{cap_qps:.0f} qps/tenant")

    rows = []
    for mult in (1, 2, 5):
        qps = cap_qps * mult
        app = build_app(cap_qps)
        try:
            rss0 = _vm_rss_bytes()
            peak = {"rss": rss0, "pressure": 0.0}
            stop_sampler = threading.Event()

            def sample() -> None:
                while not stop_sampler.is_set():
                    peak["rss"] = max(peak["rss"], _vm_rss_bytes())
                    st = app.admission.stats()
                    peak["pressure"] = max(
                        peak["pressure"], st["pressure"]["level"]
                    )
                    stop_sampler.wait(0.05)

            sampler = threading.Thread(target=sample, daemon=True)
            sampler.start()
            try:
                rep = LoadGen(
                    app,
                    [
                        TenantLoad("live", qps=qps),
                        # heavy writes with fat docs: the upsert stream is
                        # what loads the engine's ingest credit ledger
                        TenantLoad(
                            "bulk", qps=qps, write_fraction=0.5, doc_words=160
                        ),
                    ],
                    duration_s=duration,
                    seed=41 + mult,
                ).run()
            finally:
                stop_sampler.set()
                sampler.join(2.0)
            adm = app.admission.stats()
            cls = rep["classes"]
            inter = cls.get("interactive", {})
            batch = cls.get("batch", {})
            sent = max(1, inter.get("sent", 0) + batch.get("sent", 0))
            shed = inter.get("shed", 0) + batch.get("shed", 0)
            wall = max(rep.get("wall_s", duration), 1e-6)
            rows.append(
                {
                    "mult": mult,
                    "offered_qps_per_tenant": round(qps, 1),
                    # what the pacing threads actually fired (the nominal
                    # offer saturates thread timer resolution at high mult)
                    "attempted_qps": round(
                        (
                            inter.get("sent", 0)
                            + batch.get("sent", 0)
                            + batch.get("writes", 0)
                        )
                        / wall,
                        1,
                    ),
                    "goodput_rps": round(
                        inter.get("achieved_qps", 0.0)
                        + batch.get("achieved_qps", 0.0),
                        2,
                    ),
                    "shed_rate": round(shed / sent, 4),
                    "interactive": inter,
                    "batch": batch,
                    "pressure_level_max": round(peak["pressure"], 3),
                    "brownout_shed_total": adm["pressure"]["brownout_shed_total"],
                    "max_rss_bytes": peak["rss"],
                    "rss_growth_frac": round(
                        (peak["rss"] - rss0) / max(rss0, 1), 4
                    ),
                }
            )
            log(
                f"overload @ {mult}x ({qps:.0f} qps/tenant): goodput "
                f"{rows[-1]['goodput_rps']:.0f} rps, shed rate "
                f"{rows[-1]['shed_rate']:.1%}, interactive p99 "
                f"{inter.get('p99_ms', 0.0):.2f}ms, pressure max "
                f"{peak['pressure']:.2f}, rss +{rows[-1]['rss_growth_frac']:.1%}"
            )
        finally:
            app.close()

    # --- SIGSTOP'd peer: credit window caps sender backlog; the stall is
    # visible to tracecrit as credit_wait spans on the producer's trace ---
    credit = 8192
    os.environ["PATHWAY_EXCHANGE_CREDIT_BYTES"] = str(credit)
    tracing.configure(PATHWAY_TRACE="1", PATHWAY_TRACE_SAMPLE="1.0")
    port = None
    for base in range(29200, 29900, 2):
        try:
            for off in range(2):
                s = socket.socket()
                s.bind(("127.0.0.1", base + off))
                s.close()
            port = base
            break
        except OSError:
            continue
    if port is None:
        raise RuntimeError("no free port pair for the exchange drill")
    d = tempfile.mkdtemp(prefix="pw_bench_overload_")
    peer_py = os.path.join(d, "peer.py")
    with open(peer_py, "w") as f:
        f.write(_SIGSTOP_PEER_PROGRAM)
    n_frames = 24 if SMOKE else 60
    repo_root = os.path.dirname(os.path.abspath(__file__))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = repo_root + (
        os.pathsep + child_env["PYTHONPATH"] if child_env.get("PYTHONPATH") else ""
    )
    child = subprocess.Popen(
        [_sys.executable, peer_py, str(port), str(n_frames)],
        cwd=repo_root,
        env=child_env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    links0 = None
    try:
        links0 = _ProcessLinks(
            0, 2, port, heartbeat_s=0.2, liveness_timeout_s=30.0
        )
        boxes = [[[(i, ("v" * 40,), 1) for i in range(60)]]]
        t_mark = time.monotonic_ns()
        sent: list = []

        def producer() -> None:
            with tracing.use(tracing.new_trace(sampled=True)):
                for i in range(n_frames):
                    links0.send_updates_async(1, ("s", i), boxes)
                    sent.append(i)

        prod = threading.Thread(target=producer, daemon=True)
        prod.start()
        deadline = time.monotonic() + 10.0
        while len(sent) < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        if len(sent) < 3:
            raise RuntimeError("exchange drill never started moving frames")
        max_backlog = 0
        states = set()
        with chaos(seed=7) as ch:
            ch.pause_resume(child.pid, pause_s=2.0)
            t_end = time.monotonic() + 2.0
            while time.monotonic() < t_end:
                pr = links0.exchange_pressure()
                max_backlog = max(max_backlog, pr["peers"][1]["backlog_bytes"])
                states.add(pr["peers"][1]["state"])
                time.sleep(0.05)
        prod.join(45.0)
        rcode = child.wait(timeout=45.0)
        events = tracing.chrome_events(since_ns=t_mark, all_spans=True)
        credit_wait_ms = round(
            sum(e["dur"] for e in events if e["name"] == "credit_wait") / 1e3, 3
        )
        crit = tracecrit.report(events)
        with links0.stats_lock:
            stalls = links0.stats["credit_stalls"]
            stall_ms = round(links0.stats["credit_stall_ms"], 3)
        sigstop = {
            "credit_bytes": credit,
            "n_frames": n_frames,
            "frames_sent": len(sent),
            "pause_s": 2.0,
            "max_backlog_bytes": max_backlog,
            "peer_states_seen": sorted(states),
            "peer_exit_code": rcode,
            "producer_done": not prod.is_alive(),
            "credit_stalls": stalls,
            "credit_stall_ms": stall_ms,
            "credit_wait_ms": credit_wait_ms,
        }
        log(
            f"overload sigstop drill: backlog max {max_backlog}B "
            f"(cap {credit}B), states {sorted(states)}, credit_wait "
            f"{credit_wait_ms:.0f}ms over {stalls} stalls"
        )
    finally:
        if links0 is not None:
            links0.close()
        if child.poll() is None:
            child.kill()
        tracing.configure(
            PATHWAY_TRACE=saved_trace, PATHWAY_TRACE_SAMPLE=saved_sample
        )
        for key, old in saved_env.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old

    extra["overload_capacity_qps"] = round(cap_qps, 1)
    extra["overload_interactive_p99_ms_1x"] = rows[0]["interactive"].get("p99_ms")
    extra["overload_interactive_p99_ms_5x"] = rows[-1]["interactive"].get("p99_ms")
    extra["overload_goodput_rps_5x"] = rows[-1]["goodput_rps"]
    extra["overload_shed_rate_5x"] = rows[-1]["shed_rate"]
    extra["overload_rss_growth_frac_5x"] = rows[-1]["rss_growth_frac"]
    extra["overload_sigstop_max_backlog_bytes"] = max_backlog
    extra["overload_credit_wait_ms"] = credit_wait_ms

    out = artifact_path("BENCH_overload.json")
    with open(out, "w") as f:
        json.dump(
            {
                "cmd": "JAX_PLATFORMS=cpu python bench.py (bench_overload)",
                "config": {
                    "capacity_qps_per_tenant": round(cap_qps, 1),
                    "duration_s": duration,
                    "ingest_buffer_bytes": ingest_cap,
                    "write_fraction_bulk": 0.5,
                    "smoke": SMOKE,
                },
                "ladder": rows,
                "sigstop_peer": sigstop,
                "tracecrit": crit,
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")
    log(f"wrote {out}")
    if SMOKE:
        p99_1x = max(rows[0]["interactive"].get("p99_ms", 0.0), 0.5)
        p99_5x = rows[-1]["interactive"].get("p99_ms", 0.0)
        if p99_5x > 5.0 * p99_1x:
            raise RuntimeError(
                f"interactive p99 under 5x overload is {p99_5x:.2f}ms > 5x "
                f"the 1x-load p99 ({p99_1x:.2f}ms) — brownout is not "
                "holding the interactive class"
            )
        growth = rows[-1]["rss_growth_frac"]
        if growth > 0.10:
            raise RuntimeError(
                f"RSS grew {growth:.1%} during the 5x point — a queue is "
                "unbounded somewhere in the pressure chain"
            )
        if "dead" in states:
            raise RuntimeError(
                "SIGSTOP'd peer was declared dead — a stalled-but-alive "
                "peer must be throttled, not isolated"
            )
        if max_backlog > 2 * credit:
            raise RuntimeError(
                f"sender backlog reached {max_backlog}B against a "
                f"{credit}B credit window — flow control is not capping "
                "the SIGSTOP'd peer"
            )
        if credit_wait_ms <= 0.0 or stalls <= 0:
            raise RuntimeError(
                "no credit_wait spans recorded during the SIGSTOP drill — "
                "the stall is invisible to tracecrit attribution"
            )


# ---------------------------------------------------------------------------


def main() -> None:
    global SMOKE, WC_LINES, SELECT_N, STRDT_N
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="seconds-long sanity run: tiny corpus, host-plane sections "
        "only (skips the 1M index build and the model benches); same "
        "last-line JSON contract",
    )
    args = ap.parse_args()
    if args.smoke:
        SMOKE = True
        WC_LINES = 20_000
        SELECT_N = 50_000
        STRDT_N = 20_000

    # batch-job collector discipline: long sweep interval (the managed-GC
    # caretaker still bounds cycles; see internals/run.py _ManagedGc)
    os.environ.setdefault("PATHWAY_GC_INTERVAL_S", "10")
    extra: dict = {}
    # host-plane benches run FIRST, on a heap not yet holding jax buffers
    # or the 1M-doc corpus bookkeeping (their numbers used to sag ~10%
    # when run after the TPU sections)
    sections = [
        (bench_wordcount, "wordcount"),
        (bench_wordcount_multiprocess, "wordcount_multiprocess"),
        (bench_columnar, "columnar"),
        (bench_select, "select"),
        (bench_strdt, "strdt"),
        (bench_streaming_latency, "streaming_latency"),
        (bench_checkpoint_overhead, "checkpoint_overhead"),
        (bench_cluster_recovery, "cluster_recovery"),
        (bench_index_churn, "index_churn"),
        (bench_capacity, "capacity"),
        (bench_device, "device"),
        (bench_rag_serving, "rag_serving"),
        (bench_failover, "failover"),
        (bench_tracing, "tracing"),
        (bench_overload, "overload"),
    ]
    if not SMOKE:
        sections += [
            (bench_embed, "embed"),
        ]
    for fn, slug in sections:
        try:
            fn(extra)
        except Exception as e:  # noqa: BLE001 — no bench masks the headline
            log(f"{slug} bench failed: {e!r}")
            extra[f"{slug}_error"] = repr(e)

    if SMOKE:
        print(
            json.dumps(
                {
                    "metric": "smoke_wordcount_rows_per_sec",
                    "value": extra.get("wordcount_rows_per_sec"),
                    "unit": "rows/s",
                    "smoke": True,
                    "extra": extra,
                }
            )
        )
        return

    p50 = bench_knn(extra)
    print(
        json.dumps(
            {
                "metric": "knn_p50_per_query_latency_1M_docs_batched_serving",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / p50, 2),
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
