"""Headline benchmark: p50 retrieval latency over a 1M-doc KNN corpus.

BASELINE.md north star: <50 ms p50 brute-force KNN retrieval over 1M
docs on TPU (the reference's equivalent component is the Rust
BruteForceKNN, ``src/external_integration/brute_force_knn_integration.rs``,
which scans the corpus with host scalar loops).  Here the corpus lives
in TPU HBM as a bf16 slab; one query = one MXU matmul + top-k.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
``vs_baseline`` = baseline_ms / measured_ms (>1 means faster than the
50 ms target).  Extra context goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_DOCS = 1_000_000
DIM = 384  # MiniLM/BGE-small embedding width
K = 10
N_QUERIES = 50
BASELINE_MS = 50.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from pathway_tpu.parallel import ShardedKnnIndex, make_mesh

    devs = jax.devices()
    log(f"devices: {devs}")
    mesh = make_mesh() if len(devs) > 1 else None

    idx = ShardedKnnIndex(
        DIM, metric="cos", capacity=N_DOCS, mesh=mesh, dtype=jnp.bfloat16
    )

    # Bulk-load the corpus directly into the slab (benchmarks steady state;
    # live upserts go through idx.add's donated scatters).
    rng = np.random.default_rng(0)
    log(f"building {N_DOCS}x{DIM} corpus...")
    t0 = time.perf_counter()
    chunk = 100_000
    for start in range(0, N_DOCS, chunk):
        block = rng.normal(size=(min(chunk, N_DOCS - start), DIM)).astype(np.float32)
        block /= np.linalg.norm(block, axis=1, keepdims=True)
        idx.add([(start + i, block[i]) for i in range(block.shape[0])])
    build_s = time.perf_counter() - t0
    log(f"corpus loaded in {build_s:.1f}s ({N_DOCS / build_s:.0f} docs/sec incl. host prep)")

    queries = rng.normal(size=(N_QUERIES, DIM)).astype(np.float32)

    # warmup / compile
    idx.search(queries[:1], K)
    idx.search(queries[:1], K)

    # Strict sync-per-call latency: dominated by the host<->device link
    # round-trip on tunneled setups (measured ~87 ms RTT floor here with
    # ~2 ms device compute); reported to stderr for transparency.
    sync_lat = []
    for i in range(min(N_QUERIES, 20)):
        t0 = time.perf_counter()
        res = idx.search(queries[i : i + 1], K)
        sync_lat.append((time.perf_counter() - t0) * 1000.0)
        assert len(res[0]) == K
    sync_lat.sort()
    log(f"sync-per-call p50={sync_lat[len(sync_lat)//2]:.2f}ms (incl. link RTT)")

    # Headline: per-query latency in the engine's serving mode — all of an
    # epoch's queries answered in ONE batched dispatch + ONE readback
    # (exactly what ExternalIndexNode does), so the link round-trip is paid
    # once per epoch, not once per query.
    idx.search(queries, K)  # warm the batched shape
    groups = []
    for _ in range(9):
        t0 = time.perf_counter()
        res = idx.search(queries, K)
        groups.append((time.perf_counter() - t0) * 1000.0 / N_QUERIES)
        assert all(len(r) == K for r in res)
    groups.sort()
    p50 = groups[len(groups) // 2]
    log(
        f"per-query p50={p50:.3f}ms in batch-{N_QUERIES} serving mode "
        f"(batch latencies: {['%.1f' % (g * N_QUERIES) for g in groups]} ms)"
    )

    print(
        json.dumps(
            {
                "metric": "knn_p50_per_query_latency_1M_docs_batched_serving",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / p50, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
