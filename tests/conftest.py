import os

# Sharding tests run on a virtual 8-device CPU mesh; the engine host plane
# doesn't need the TPU, and tests must not depend on one being attached.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pytest


@pytest.fixture(autouse=True)
def fresh_graph():
    """Reset the global graph between tests (reference
    ``python/pathway/conftest.py`` resets ParseGraph per test)."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()
