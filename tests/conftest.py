import os

# Sharding tests run on a virtual 8-device CPU mesh; the engine host plane
# doesn't need the TPU, and tests must not depend on one being attached.
# NOTE: env vars alone are not enough — this environment's JAX plugin
# overrides JAX_PLATFORMS, so also force the config flag before any
# backend initialization.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--regen-plans",
        action="store_true",
        default=False,
        help="rewrite tests/plans/*.txt golden execution plans from the "
        "current optimizer instead of comparing against them",
    )


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: deterministic chaos/fault-injection
    # tests stay in tier-1 (marker `chaos`), long randomized drills are
    # additionally marked `slow` and excluded
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection / crash-recovery test"
    )
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from the tier-1 run"
    )


@pytest.fixture(autouse=True)
def fresh_graph():
    """Reset the global graph between tests (reference
    ``python/pathway/conftest.py`` resets ParseGraph per test)."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()
