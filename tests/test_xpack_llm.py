"""LLM xpack: embedders, splitters, rerankers, DocumentStore, RAG QA."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.models import BGE_RERANKER_BASE, MINILM_L6
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseRAGQuestionAnswerer,
    answer_with_geometric_rag_strategy,
)
from pathway_tpu.xpacks.llm.rerankers import CrossEncoderReranker, rerank_topk_filter
from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter, null_splitter
from tests.utils import T, run_to_rows

TINY = dataclasses.replace(
    MINILM_L6, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
)
TINY_CROSS = dataclasses.replace(
    BGE_RERANKER_BASE, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
)


class FakeChat:
    """Deterministic chat stub for QA tests."""

    def __init__(self, answer_if=None):
        self.calls = []
        self.answer_if = answer_if  # substring of prompt that unlocks answer

    def __wrapped__(self, messages):
        prompt = messages[-1]["content"]
        self.calls.append(prompt)
        if self.answer_if is None or self.answer_if in prompt:
            return "The answer is 42."
        return "No information found."


@pytest.fixture(scope="module")
def tiny_embedder():
    return TPUEncoderEmbedder(config=TINY)


def test_embedder_batches_per_epoch(tiny_embedder):
    docs = T(
        """
    d | text
    1 | apple pie
    2 | banana bread
    3 | cherry cake
    """
    )
    out = docs.select(emb=tiny_embedder(pw.this.text))
    rows = run_to_rows(out)
    assert len(rows) == 3
    assert np.asarray(rows[0][0]).shape == (64,)
    assert tiny_embedder.get_embedding_dimension() == 64


def test_splitters():
    text = "One sentence here. " * 30
    chunks = TokenCountSplitter(min_tokens=10, max_tokens=30).__wrapped__(text)
    assert len(chunks) > 1
    assert all(isinstance(c, tuple) and isinstance(c[1], dict) for c in chunks)
    assert null_splitter("abc") == [("abc", {})]


def test_rerank_topk_filter():
    docs = [{"text": f"d{i}"} for i in range(5)]
    scores = [0.1, 0.9, 0.5, 0.3, 0.8]
    kept, ks = rerank_topk_filter.__wrapped_fun__(docs, scores, 2)
    assert [d["text"] for d in kept] == ["d1", "d4"]
    assert ks == [0.9, 0.8]


def test_cross_encoder_reranker_batch():
    rr = CrossEncoderReranker(config=TINY_CROSS)
    scores = rr.__batch__(
        [{"text": "doc one"}, {"text": "doc two"}], ["q", "q"]
    )
    assert len(scores) == 2 and all(isinstance(s, float) for s in scores)


def _doc_store(tiny_embedder):
    docs = T(
        """
    d | data
    1 | apples grow on trees in the orchard
    2 | bananas are yellow tropical fruit
    3 | the tpu runs matrix multiplications fast
    """
    ).select(
        data=pw.this.data,
        _metadata=pw.apply(lambda d: {"path": f"/docs/{d}.txt"}, pw.this.d),
    )
    factory = BruteForceKnnFactory(embedder=tiny_embedder, reserved_space=32)
    return DocumentStore(docs, retriever_factory=factory)


def test_document_store_retrieve(tiny_embedder):
    store = _doc_store(tiny_embedder)
    queries = T(
        """
    q
    bananas
    """
    ).select(
        query=pw.this.q,
        k=pw.apply(lambda _q: 2, pw.this.q),
        metadata_filter=pw.apply(lambda _q: None, pw.this.q),
        filepath_globpattern=pw.apply(lambda _q: None, pw.this.q),
    )
    res = store.retrieve_query(queries)
    rows = run_to_rows(res)
    docs = rows[0][-1]
    assert len(docs) == 2
    assert all("text" in d and "score" in d and "metadata" in d for d in docs)
    # embedding is deterministic: the same text embeds to the same vector,
    # and 'bananas...' contains the query token so it should rank well —
    # but with random weights we only require the structure, not ranking.


def test_document_store_statistics_and_inputs(tiny_embedder):
    store = _doc_store(tiny_embedder)
    stats_q = T(
        """
    dummy
    x
    """
    ).select()
    stats = store.statistics_query(stats_q)
    rows = run_to_rows(stats)
    assert rows[0][0]["file_count"] == 3

    inputs_q = T(
        """
    dummy
    x
    """
    ).select(
        metadata_filter=pw.apply(lambda _d: None, pw.this.dummy),
        filepath_globpattern=pw.apply(lambda _d: "*1.txt", pw.this.dummy),
    )
    inputs = store.inputs_query(inputs_q)
    rows = run_to_rows(inputs)
    assert [f["path"] for f in rows[0][-1]] == ["/docs/1.txt"]


def test_base_rag_answerer(tiny_embedder):
    store = _doc_store(tiny_embedder)
    chat = FakeChat()
    rag = BaseRAGQuestionAnswerer(chat, store, search_topk=2)
    queries = T(
        """
    p
    what color are bananas?
    """
    ).select(
        prompt=pw.this.p,
        filters=pw.apply(lambda _p: None, pw.this.p),
        model=pw.apply(lambda _p: None, pw.this.p),
        return_context_docs=pw.apply(lambda _p: True, pw.this.p),
    )
    res = rag.answer_query(queries)
    rows = run_to_rows(res)
    out = rows[0][-1]
    assert out["response"] == "The answer is 42."
    assert len(out["context_docs"]) == 2
    assert len(chat.calls) == 1 and "bananas" in chat.calls[0]


def test_geometric_rag_strategy_escalates():
    chat = FakeChat(answer_if="doc3")
    answers = answer_with_geometric_rag_strategy(
        ["q"], [["doc1", "doc2", "doc3", "doc4"]], chat,
        n_starting_documents=1, factor=2, max_iterations=4,
    )
    assert answers == ["The answer is 42."]
    # escalation: 1 doc -> 2 docs -> 4 docs (includes doc3)
    assert len(chat.calls) == 3


def test_hybrid_index_with_embedder(tiny_embedder):
    """Hybrid KNN+BM25 over raw text: each child must apply its own
    embedding (regression: child embedders were ignored)."""
    from pathway_tpu.stdlib.indexing import HybridIndexFactory, TantivyBM25Factory

    docs = T(
        """
    d | text
    1 | apples grow on trees
    2 | bananas are yellow
    """
    )
    queries = T(
        """
    q
    bananas
    """
    )
    factory = HybridIndexFactory(
        retriever_factories=[
            BruteForceKnnFactory(embedder=tiny_embedder, reserved_space=16),
            TantivyBM25Factory(),
        ]
    )
    index = factory.build_data_index(docs.text, docs)
    res = index.query_as_of_now(queries.q, number_of_matches=2)
    rows = run_to_rows(res)
    returned = [d["text"] for d in rows[0][-1]]
    assert len(returned) == 2
    # BM25 leg guarantees the exact-token match ranks first under RRF
    assert returned[0] == "bananas are yellow"


def test_batch_udf_screens_errors():
    """One None/ERROR row must not poison the epoch batch."""
    calls = []

    @pw.udfs.batch_udf(return_type=float, propagate_none=True)
    def length(texts):
        calls.append(list(texts))
        assert all(t is not None for t in texts)
        return [float(len(t)) for t in texts]

    t = T(
        """
    a | b
    1 | hello
    2 | __none__
    """
    ).select(b=pw.apply(lambda b: None if b == "__none__" else b, pw.this.b))
    out = t.select(n=length(pw.this.b))
    rows = run_to_rows(out)
    assert sorted(rows, key=str) == sorted([(5.0,), (None,)], key=str)
    assert calls == [["hello"]]


def test_adaptive_rag_answerer(tiny_embedder):
    store = _doc_store(tiny_embedder)
    chat = FakeChat()
    rag = AdaptiveRAGQuestionAnswerer(
        chat, store, n_starting_documents=1, factor=2, max_iterations=2
    )
    queries = T(
        """
    p
    what is a tpu?
    """
    ).select(
        prompt=pw.this.p,
        filters=pw.apply(lambda _p: None, pw.this.p),
        model=pw.apply(lambda _p: None, pw.this.p),
        return_context_docs=pw.apply(lambda _p: False, pw.this.p),
    )
    rows = run_to_rows(rag.answer_query(queries))
    assert rows[0][-1]["response"] == "The answer is 42."


def test_document_store_ingests_html_and_docx(tiny_embedder):
    """DocumentStore ingests binary .html/.docx via ParseUnstructured's
    built-in extractors; chunks carry element-category metadata
    (VERDICT r3 item 8)."""
    from tests.test_parsers import _HTML, _minimal_docx
    from pathway_tpu.xpacks.llm.parsers import ParseUnstructured

    files = [("page.html", _HTML), ("report.docx", _minimal_docx())]
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(data=bytes, _metadata=dict),
        [(data, {"path": f"/in/{name}"}) for name, data in files],
    )
    factory = BruteForceKnnFactory(embedder=tiny_embedder, reserved_space=32)
    store = DocumentStore(
        docs,
        retriever_factory=factory,
        parser=ParseUnstructured(mode="elements"),
    )
    inputs_q = T(
        """
    dummy
    x
    """
    ).select(
        metadata_filter=pw.apply(lambda _q: None, pw.this.dummy),
        filepath_globpattern=pw.apply(lambda _q: None, pw.this.dummy),
    )
    listing = run_to_rows(store.inputs_query(inputs_q))
    paths = {d["path"] for d in listing[0][0]}
    assert paths == {"/in/page.html", "/in/report.docx"}

    queries = T(
        """
    q
    revenue
    """
    ).select(
        query=pw.this.q,
        k=pw.apply(lambda _q: 4, pw.this.q),
        metadata_filter=pw.apply(lambda _q: None, pw.this.q),
        filepath_globpattern=pw.apply(lambda _q: None, pw.this.q),
    )
    res = run_to_rows(store.retrieve_query(queries))
    docs_out = res[0][-1]
    assert docs_out, "retrieval returned nothing"
    texts = " ".join(d["text"] for d in docs_out)
    all_meta = [d["metadata"] for d in docs_out]
    # chunks originate from parsed blocks with category metadata
    assert any(m.get("category") in
               ("Title", "NarrativeText", "ListItem", "Table")
               for m in all_meta), all_meta
    assert "Revenue" in texts or "Apples" in texts
