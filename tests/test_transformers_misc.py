"""Row transformers, universe solver, LSH banding, SharePoint connector
(reference: internals/row_transformer.py + decorators.py,
internals/universe_solver.py, stdlib/ml/classifiers/_knn_lsh.py,
xpacks/connectors/sharepoint)."""

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.keys import ref_scalar


def _chain_table(n=4):
    """a -> b -> c -> d linked list as a 1-column table of next-pointers."""
    import pathway_tpu.engine.graph as eg
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.table import Table

    keys = [ref_scalar("n", i) for i in range(n)]
    rows = [(keys[i], (keys[i + 1],)) for i in range(n - 1)] + [(keys[-1], (None,))]
    node = eg.InputNode(G.engine_graph, n_cols=1, static_rows=rows, name="nodes")
    return Table(node, ["next"], name="nodes"), keys


def test_row_transformer_linked_list():
    """The reference's canonical linked-list example: output attribute
    computed by a recursive pointer walk + a callable method column."""

    @pw.transformer
    class linked_list_transformer:
        class linked_list(pw.ClassArg):
            next = pw.input_attribute()

            @pw.output_attribute
            def len(self):
                if self.next is None:
                    return 1
                return 1 + self.transformer.linked_list[self.next].len

            @pw.method
            def forward(self, steps):
                if steps == 0:
                    return self.id
                if self.next is not None:
                    return self.transformer.linked_list[self.next].forward(steps - 1)
                return None

    t, keys = _chain_table(4)
    res = linked_list_transformer(linked_list=t).linked_list
    cap = res._capture_node()
    ctx = pw.run()
    rows = ctx.state(cap)["rows"]
    assert sorted(v[0] for v in rows.values()) == [1, 2, 3, 4]
    assert rows[keys[0]][1](2) == keys[2]
    assert rows[keys[0]][1](5) is None


def test_row_transformer_two_tables():
    """Cross-table pointer dereference between two ClassArgs."""
    import pathway_tpu.engine.graph as eg
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.table import Table

    pkeys = [ref_scalar("p", i) for i in range(2)]
    prices = Table(
        eg.InputNode(
            G.engine_graph,
            n_cols=1,
            static_rows=[(pkeys[0], (10.0,)), (pkeys[1], (20.0,))],
            name="prices",
        ),
        ["price"],
    )
    orders = Table(
        eg.InputNode(
            G.engine_graph,
            n_cols=2,
            static_rows=[
                (ref_scalar("o", 0), (pkeys[0], 3)),
                (ref_scalar("o", 1), (pkeys[1], 2)),
            ],
            name="orders",
        ),
        ["product", "qty"],
    )

    @pw.transformer
    class pricing:
        class products(pw.ClassArg):
            price = pw.input_attribute()

            @pw.output_attribute
            def doubled(self):
                return self.price * 2

        class orders(pw.ClassArg):
            product = pw.input_attribute()
            qty = pw.input_attribute()

            @pw.output_attribute
            def total(self):
                return self.transformer.products[self.product].price * self.qty

    res = pricing(products=prices, orders=orders)
    cap = res.orders._capture_node()
    ctx = pw.run()
    rows = ctx.state(cap)["rows"]
    assert sorted(v[0] for v in rows.values()) == [30.0, 40.0]


def test_universe_solver_relations():
    from pathway_tpu.internals.universe_solver import UniverseSolver, UniverseToken

    s = UniverseSolver()
    a, b, c, d = (UniverseToken() for _ in range(4))
    s.register_as_subset(a, b)
    s.register_as_subset(b, c)
    assert s.query_is_subset_of(a, a)  # reflexive
    assert s.query_is_subset_of(a, b)
    assert s.query_is_subset_of(a, c)  # transitive
    assert not s.query_is_subset_of(c, a)
    s.register_as_equal(c, d)
    assert s.query_are_equal(c, d)
    assert s.query_is_subset_of(a, d)  # through the equivalence


def test_promises_register_with_solver():
    from pathway_tpu.internals.universe_solver import solver
    from tests.utils import T

    big = T(
        """
        a
        1
        2
        3
        """
    )
    small = big.filter(big.a > 1)
    tok_small = small._layout_token
    bound = pw.universes.promise_is_subset_of(small, big)
    assert solver.query_is_subset_of(tok_small, big._layout_token)
    # the returned table is usable in big's universe
    joined = big.select(a=big.a)
    assert bound._layout_token is big._layout_token


def test_lsh_banding_recall_and_removal():
    from pathway_tpu.stdlib.ml import LshBandingIndex

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(8, 16)).astype(np.float64) * 5
    x = np.concatenate([c + 0.05 * rng.normal(size=(50, 16)) for c in centers])
    idx = LshBandingIndex(16, L=16, M=6, A=4.0, metric="euclidean")
    for i, v in enumerate(x):
        idx.add(i, v)
    assert len(idx) == 400

    # self-query: the point itself must be its own nearest neighbour
    hits = 0
    for i in range(0, 400, 25):
        res = idx.query(x[i], 3)
        if res and res[0][0] == i:
            hits += 1
    assert hits >= 14  # >= 87% self-recall on clustered data

    # candidates are a strict subset (banding actually prunes)
    cand = idx.candidates(x[0])
    assert 0 < len(cand) < 400

    idx.remove(0)
    assert all(key != 0 for key, _ in idx.query(x[0], 3))

    # cosine variant
    c = LshBandingIndex(16, L=12, M=8, metric="cosine")
    for i, v in enumerate(x[:100]):
        c.add(i, v)
    res = c.query(x[5], 1)
    assert res and res[0][0] == 5


def test_sharepoint_requires_entitlement():
    import pytest

    from pathway_tpu.internals.license import LicenseError
    from pathway_tpu.xpacks.connectors import sharepoint

    with pytest.raises(LicenseError, match="xpack-sharepoint"):
        sharepoint.read(connection=object(), root_path="/x", mode="static")


def test_sharepoint_fake_connection(monkeypatch):
    from pathway_tpu.internals import license as _lic
    from pathway_tpu.xpacks.connectors.sharepoint import FileEntry
    from pathway_tpu.xpacks.connectors import sharepoint

    # licensed xpack: the demo key unlocks it for offline evaluation
    monkeypatch.setattr(
        "pathway_tpu.internals.config.pathway_config.license_key", "demo"
    )
    _lic._cache.clear()

    class FakeConn:
        def __init__(self):
            self.files = {
                "/sites/x/a.txt": (b"alpha", 100),
                "/sites/x/b.pdf": (b"%PDF beta", 200),
                "/sites/x/huge.bin": (b"X" * 1000, 300),
            }

        def list_files(self, root_path):
            return [
                FileEntry(path=p, size=len(d), created_at=t, modified_at=t)
                for p, (d, t) in sorted(self.files.items())
            ]

        def download(self, path):
            return self.files[path][0]

    t = sharepoint.read(
        connection=FakeConn(),
        root_path="/sites/x",
        mode="static",
        object_size_limit=100,
        with_metadata=True,
    )
    keys, cols = pw.debug.table_to_dicts(t)
    datas = {cols["_metadata"][k]["path"]: cols["data"][k] for k in keys}
    assert datas["/sites/x/a.txt"] == b"alpha"
    # oversized file: explicit status, empty payload
    assert datas["/sites/x/huge.bin"] == b""
    statuses = {
        cols["_metadata"][k]["path"]: cols["_metadata"][k]["status"] for k in keys
    }
    assert statuses["/sites/x/huge.bin"] == "size_limit_exceeded"
    assert statuses["/sites/x/a.txt"] == "downloaded"


def test_telemetry_spans_and_otlp_export():
    """Spans/metrics record in-process and export OTLP/HTTP JSON to a
    configured endpoint (reference src/engine/telemetry.rs)."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from pathway_tpu.internals.telemetry import Telemetry

    received = []

    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, json.loads(body)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tel = Telemetry(endpoint=f"http://127.0.0.1:{srv.server_port}")
        with tel.span("graph_runner.run", operators=3):
            pass
        tel.gauge("run.epoch", 4)
        tel.record_process_metrics()
        tel.export_metrics()
        assert tel.spans[0]["name"] == "graph_runner.run"
        assert tel.gauges["run.epoch"] == 4.0
        assert "process.memory.rss_kb" in tel.gauges
        paths = [p for p, _ in received]
        assert "/v1/traces" in paths and "/v1/metrics" in paths
        trace_payload = next(b for p, b in received if p == "/v1/traces")
        span = trace_payload["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
        assert span["name"] == "graph_runner.run"
    finally:
        srv.shutdown()


def test_fuzzy_match_weighting_and_by_hand():
    from pathway_tpu.stdlib.ml.smart_table_ops import (
        FuzzyJoinNormalization,
        fuzzy_match_tables,
    )
    from tests.utils import T

    left = pw.debug.table_from_rows(
        pw.schema_from_types(name=str),
        [("alpha beta common",), ("gamma delta common",)],
    )
    right = pw.debug.table_from_rows(
        pw.schema_from_types(name=str),
        [("alpha beta common",), ("delta gamma common",)],
    )
    res = fuzzy_match_tables(left, right)
    keys, cols = pw.debug.table_to_dicts(res)
    assert len(keys) == 2  # both rows matched 1:1
    assert all(w > 0 for w in cols["weight"].values())

    # rare features outweigh the ubiquitous "common" token
    res2 = fuzzy_match_tables(
        left, right, normalization=FuzzyJoinNormalization.WEIGHT
    )
    _, cols2 = pw.debug.table_to_dicts(res2)
    assert all(w > 0 for w in cols2["weight"].values())
