"""Regression tests for code-review findings (round 1)."""

import numpy as np
import pytest

import pathway_tpu as pw
from tests.utils import T, _rows_of, assert_table_equality_wo_index


def test_str_methods_with_default_args():
    t = T(
        """
        s
        '  hi  '
        """
    )
    res = t.select(
        stripped=t.s.str.strip(),
        split=t.s.str.split(),
        found=t.s.str.find("h"),
    )
    assert list(_rows_of(res).values()) == [("hi", ("hi",), 2)]


def test_filter_numpy_bool():
    t = T(
        """
        a
        1
        5
        """
    )
    r = t.select(b=pw.apply(lambda x: np.int64(x), t.a))
    res = r.filter(r.b > 2)
    assert len(_rows_of(res)) == 1


def test_join_left_id_duplicate_matches_raises():
    t1 = T(
        """
        a | k
        1 | x
        """
    )
    t2 = T(
        """
        b | k
        100 | x
        200 | x
        """
    )
    # per-node containment (VERDICT r1): the id-collision error is routed
    # to the error log and the run survives instead of aborting
    rows = _rows_of(t1.join(t2, t1.k == t2.k, id=pw.left.id).select(c=t2.b))
    assert rows == {}
    ctx = pw.G.last_run_ctx
    assert any("join" in e and "right matches" in e for e in ctx.error_log)


def test_duplicate_column_reference_in_expr():
    target = T(
        """
        id | v
        1  | 5
        """
    )
    req = T(
        """
        x
        1
        """
    ).select(p=target.pointer_from(pw.this.x))
    res = target.ix_ref(req.p, req.p, context=req)
    # hash of (ptr, ptr) won't match target keys -> Error rows, but no crash
    assert len(_rows_of(res)) <= 1


def test_having_filters():
    t = T(
        """
        id | a
        1  | 1
        2  | 2
        3  | 3
        """
    )
    ptrs = T(
        """
        x
        1
        3
        """
    ).select(p=t.pointer_from(pw.this.x))
    assert sorted(_rows_of(t.having(ptrs.p)).values()) == [(1,), (3,)]


def test_ambiguous_join_column_raises():
    t1 = T(
        """
        v | k
        1 | x
        """
    )
    t2 = T(
        """
        v | k
        2 | x
        """
    )
    with pytest.raises(Exception):
        t1.join(t2, t1.k == t2.k).select(out=pw.this.v)


def test_sort_prev_next():
    t = T(
        """
        a
        30
        10
        20
        """
    )
    s = t.sort(key=pw.this.a)
    rows = _rows_of(s)
    pairs = list(rows.values())
    n_first = sum(1 for p in pairs if p[0] is None)
    n_last = sum(1 for p in pairs if p[1] is None)
    assert n_first == 1 and n_last == 1 and len(pairs) == 3


def test_diff():
    t = T(
        """
        ts | v
        1  | 10
        2  | 13
        3  | 17
        """
    )
    d = t.diff(pw.this.ts, pw.this.v)
    assert sorted(_rows_of(d).values()) == [(1, 10, None), (2, 13, 3), (3, 17, 4)]


def test_interpolate():
    from pathway_tpu.stdlib.statistical import interpolate

    t = T(
        """
        ts | v
        1  | 1.0
        2  | None
        3  | 3.0
        """
    )
    res = interpolate(t, pw.this.ts, pw.this.v)
    assert sorted(_rows_of(res).values()) == [(1, 1.0), (2, 2.0), (3, 3.0)]


def test_select_across_same_universe_tables_zip():
    t = T(
        """
        a
        1
        2
        """
    )
    doubled = t.select(b=t.a * 2)
    combined = t.select(t.a, doubled.b)
    assert_table_equality_wo_index(
        combined,
        T(
            """
            a | b
            1 | 2
            2 | 4
            """
        ),
    )
