"""Connector resilience: backoff policy, circuit breaker, supervised
restart (exactly-once resume), graceful degradation, crash-safe UDF cache."""

import asyncio
import pickle
import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.resilience import (
    BreakerState,
    CircuitBreaker,
    ConnectorRecoveryPolicy,
    DEFAULT_POLICY,
)
from pathway_tpu.internals.udfs import (
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
)
from pathway_tpu.io._connector import DictSource, input_table
from pathway_tpu.testing import flaky_once


class WordSchema(pw.Schema):
    word: str


# ---------------------------------------------------------------------------
# backoff schedule (satellite: max_delay cap + full jitter, shared with udfs)


def test_exponential_backoff_caps_at_max_delay():
    s = ExponentialBackoffRetryStrategy(
        initial_delay=100, backoff_factor=10.0, jitter_ms=0, max_delay_ms=500
    )
    assert s.next_delay(0) == pytest.approx(0.1)
    assert s.next_delay(1) == pytest.approx(0.5)  # 1.0s capped
    assert s.next_delay(7) == pytest.approx(0.5)  # stays capped forever


def test_exponential_backoff_jitter_respects_cap():
    # additive jitter must not push the delay past the cap
    s = ExponentialBackoffRetryStrategy(
        initial_delay=400, backoff_factor=2.0, jitter_ms=10_000, max_delay_ms=500
    )
    for attempt in range(6):
        assert s.next_delay(attempt) <= 0.5 + 1e-9


def test_full_jitter_is_seeded_and_bounded():
    mk = lambda seed: ExponentialBackoffRetryStrategy(
        initial_delay=100,
        backoff_factor=2.0,
        max_delay_ms=1000,
        full_jitter=True,
        seed=seed,
    )
    a = [mk(7).next_delay(i) for i in range(8)]
    b = [mk(7).next_delay(i) for i in range(8)]
    assert a == b  # same seed, same schedule
    assert a != [mk(8).next_delay(i) for i in range(8)]
    for i, d in enumerate(a):
        assert 0.0 <= d <= min(0.1 * 2**i, 1.0)


def test_fixed_delay_next_delay_is_public():
    assert FixedDelayRetryStrategy(delay_ms=250).next_delay(3) == pytest.approx(0.25)


def test_policy_backoff_strategy_and_validation():
    p = ConnectorRecoveryPolicy(
        max_restarts=4, initial_delay_ms=10, jitter_ms=0, max_delay_ms=40
    )
    s = p.backoff_strategy()
    assert isinstance(s, ExponentialBackoffRetryStrategy)
    assert [s.next_delay(i) for i in range(4)] == pytest.approx(
        [0.01, 0.02, 0.04, 0.04]
    )
    assert p.make_breaker() is None  # breaker disabled by default
    with pytest.raises(ValueError):
        ConnectorRecoveryPolicy(on_failure="explode")


# ---------------------------------------------------------------------------
# circuit breaker (injectable clock: no sleeps)


def test_circuit_breaker_transitions():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_after_s=10.0, clock=lambda: now[0])
    assert br.state == BreakerState.CLOSED and br.allow()

    br.record_failure()
    assert br.state == BreakerState.CLOSED  # below threshold
    br.record_failure()
    assert br.state == BreakerState.OPEN
    assert not br.allow()

    now[0] = 10.0  # cool-down elapsed: half-open, exactly one probe
    assert br.state == BreakerState.HALF_OPEN
    assert br.allow()
    assert not br.allow()  # probe slot consumed, re-armed

    br.record_failure()  # probe failed: back to open, fresh cool-down
    assert br.state == BreakerState.OPEN
    assert not br.allow()

    now[0] = 20.0
    assert br.allow()
    br.record_success()  # probe succeeded
    assert br.state == BreakerState.CLOSED
    assert br.allow()


def test_circuit_breaker_success_resets_failure_count():
    br = CircuitBreaker(failure_threshold=3, clock=lambda: 0.0)
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == BreakerState.CLOSED  # streak was broken


# ---------------------------------------------------------------------------
# supervised restart: exactly-once resume


def _stats_for(sched, name):
    return next(
        v for k, v in sched.connector_stats.items() if k.startswith(f"{name}#")
    )


def _collect_counts(table, results):
    counts = table.groupby(table.word).reduce(table.word, n=pw.reducers.count())

    def on_change(key, row, time, is_addition):
        if is_addition:
            results[row["word"]] = row["n"]
        elif results.get(row["word"]) == row["n"]:
            del results[row["word"]]

    pw.io.subscribe(counts, on_change=on_change)


def test_supervisor_restart_delivers_exactly_once():
    """A transient reader fault mid-stream: the supervisor restarts the
    source, the already-delivered prefix is skipped, and the final counts
    equal the fault-free run's (the PR's headline acceptance drill)."""
    rows = [{"word": w} for w in ["a", "b", "a", "c", "a", "b"]]
    src = DictSource(flaky_once(rows, 3), WordSchema, commit_every=2)
    policy = ConnectorRecoveryPolicy(
        max_restarts=2, initial_delay_ms=5, jitter_ms=0, seed=0, on_failure="stop"
    )
    t = input_table(src, WordSchema, name="flaky", recovery_policy=policy)
    results: dict = {}
    _collect_counts(t, results)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    sched.run()
    assert results == {"a": 3, "b": 2, "c": 1}
    stats = _stats_for(sched, "flaky")
    assert stats["restarts"] == 1 and stats["failures"] == 1


def test_default_policy_keeps_legacy_drop_behaviour():
    """Nodes without an explicit policy: one failure closes the stream,
    no restart, the run continues on what was delivered."""
    assert DEFAULT_POLICY.max_restarts == 0
    assert DEFAULT_POLICY.on_failure == "drop"

    rows = [{"word": w} for w in ["a", "a", "b"]]
    src = DictSource(flaky_once(rows, 2), WordSchema, commit_every=1)
    t = input_table(src, WordSchema, name="legacy")  # no recovery_policy
    results: dict = {}
    _collect_counts(t, results)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    sched.run()
    assert results == {"a": 2}  # the prefix survived, "b" never arrived
    stats = _stats_for(sched, "legacy")
    assert stats["restarts"] == 0 and stats["failures"] == 1


def test_degrade_mode_finishes_run_and_records_error():
    """Breaker trips before the restart budget is spent; on_failure=
    'degrade' keeps the run alive, routes the failure into the global
    error-log table and marks the source stale (acceptance criterion)."""

    def bad_gen():
        yield {"word": "a"}
        raise RuntimeError("boom")

    src = DictSource(bad_gen, WordSchema, commit_every=1)
    policy = ConnectorRecoveryPolicy(
        max_restarts=2,
        initial_delay_ms=2,
        jitter_ms=0,
        seed=0,
        breaker_failure_threshold=2,
        breaker_reset_after_s=60.0,
        on_failure="degrade",
    )
    t = input_table(src, WordSchema, name="dying", recovery_policy=policy)
    captured: list = []
    pw.io.subscribe(
        pw.global_error_log(),
        on_change=lambda k, row, time, add: captured.append(row["message"])
        if add
        else None,
    )
    results: dict = {}
    _collect_counts(t, results)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    ctx = sched.run()

    assert results == {"a": 1}  # the run completed on delivered data
    assert any("gave up" in m for m in captured), captured
    assert ctx.stale_sources
    stats = _stats_for(sched, "dying")
    # two failures tripped the threshold-2 breaker after one restart
    assert stats["failures"] == 2 and stats["restarts"] == 1
    assert stats["stale"] and stats["state"] == "degrade"


def test_watchdog_fences_stalled_source_and_restarts():
    """A reader that hangs without progress: the watchdog fences the
    zombie attempt's sink and a fresh attempt resumes exactly-once."""
    state = {"attempt": 0}
    hang = threading.Event()

    def gen():
        state["attempt"] += 1
        yield {"word": "a"}
        if state["attempt"] == 1:
            hang.wait()  # first attempt stalls forever
        yield {"word": "b"}
        yield {"word": "a"}

    src = DictSource(gen, WordSchema, commit_every=1)
    policy = ConnectorRecoveryPolicy(
        max_restarts=1,
        initial_delay_ms=5,
        jitter_ms=0,
        seed=0,
        watchdog_timeout_s=0.3,
        on_failure="stop",
    )
    t = input_table(src, WordSchema, name="stall", recovery_policy=policy)
    results: dict = {}
    _collect_counts(t, results)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    try:
        sched.run()
    finally:
        hang.set()  # release the abandoned zombie thread
    assert results == {"a": 2, "b": 1}
    stats = _stats_for(sched, "stall")
    assert stats["restarts"] == 1
    assert "WatchdogTimeout" in stats["last_error"]


def test_recovery_policy_exposed_at_top_level():
    assert pw.ConnectorRecoveryPolicy is ConnectorRecoveryPolicy


def test_telemetry_counters_roundtrip():
    from pathway_tpu.internals.telemetry import Telemetry

    t = Telemetry()
    assert t.counter("connector.restarts") == 1
    assert t.counter("connector.restarts", 2) == 3
    assert t.snapshot_counters()["connector.restarts"] == 3


# ---------------------------------------------------------------------------
# satellite: crash-safe DiskCache


def _cached_fn(tmp_path, calls):
    cache = DiskCache(str(tmp_path))

    async def fn(x):
        calls.append(x)
        return x * 2

    fn.__qualname__ = "resilience_test_fn"  # stable cache key
    return cache.make_wrapper(fn)


def test_disk_cache_corrupt_entry_is_a_miss(tmp_path):
    calls: list = []
    wrapped = _cached_fn(tmp_path, calls)
    assert asyncio.run(wrapped(3)) == 6
    assert calls == [3]
    (entry,) = [p for p in tmp_path.iterdir()]
    entry.write_bytes(b"\x80garbage-not-a-pickle")  # torn/corrupt write

    assert asyncio.run(wrapped(3)) == 6  # recomputed, not crashed
    assert calls == [3, 3]
    assert asyncio.run(wrapped(3)) == 6  # rewritten entry serves again
    assert calls == [3, 3]


def test_disk_cache_writes_atomically(tmp_path):
    calls: list = []
    wrapped = _cached_fn(tmp_path, calls)
    asyncio.run(wrapped(5))
    names = [p.name for p in tmp_path.iterdir()]
    assert len(names) == 1 and ".tmp." not in names[0]
    with open(tmp_path / names[0], "rb") as f:
        assert pickle.load(f) == 10


def test_disk_cache_unpicklable_result_leaves_no_file(tmp_path):
    cache = DiskCache(str(tmp_path))

    async def fn(x):
        return threading.Lock()  # unpicklable

    fn.__qualname__ = "resilience_unpicklable_fn"
    wrapped = cache.make_wrapper(fn)
    with pytest.raises(Exception):
        asyncio.run(wrapped(1))
    assert list(tmp_path.iterdir()) == []  # no torn entry under any name


# ---------------------------------------------------------------------------
# satellite: _FsBackend.truncate clamps beyond-end requests


def test_fs_truncate_clamps_past_end(tmp_path):
    from pathway_tpu.persistence import Backend

    impl = Backend.filesystem(tmp_path / "p")._impl
    for i in range(3):
        impl.append("s", b"rec%d" % i)
    assert len(impl.read_all("s")) == 3  # populates the offsets cache

    impl.truncate("s", 10)  # snapshot count > log length: keep everything
    assert impl.read_all("s") == [b"rec0", b"rec1", b"rec2"]

    impl.truncate("s", 2)
    assert impl.read_all("s") == [b"rec0", b"rec1"]

    impl.read_all("s")
    impl.truncate("s", 0)
    assert impl.read_all("s") == []
    impl.truncate("s", 5)  # empty log + beyond-end request: still fine
    assert impl.read_all("s") == []


# ---------------------------------------------------------------------------
# per-rank supervisor restart + streak-based backoff reset (ISSUE 13)

_RANK_WORKER = """
import os, sys, time
pid = int(os.environ["PATHWAY_PROCESS_ID"])
inc = int(os.environ.get("PATHWAY_CLUSTER_INCARNATION", "0"))
mode = os.environ.get("DRILL_MODE", "once")
if mode == "once":
    # rank 1 dies once at incarnation 0; everyone else finishes clean
    if pid == 1 and inc == 0:
        time.sleep(0.15)
        sys.exit(1)
    time.sleep(0.6)
    sys.exit(0)
else:  # "flaky": rank 0 dies at incarnations 0..3 after a healthy window
    if pid == 0 and inc < 4:
        time.sleep(0.4)
        sys.exit(1)
    time.sleep(0.5)
    sys.exit(0)
"""


def _rank_worker(tmp_path):
    import sys

    prog = tmp_path / "rank_worker.py"
    prog.write_text(_RANK_WORKER)
    return [sys.executable, str(prog)]


def _rank_policy(max_restarts: int):
    from pathway_tpu.internals.resilience import ConnectorRecoveryPolicy

    return ConnectorRecoveryPolicy(
        max_restarts=max_restarts,
        initial_delay_ms=10,
        max_delay_ms=50,
        jitter_ms=0,
    )


def test_restart_scope_rank_respawns_only_dead_rank(tmp_path):
    """restart_scope='rank': one rank's death respawns only that rank
    (with a bumped incarnation); survivors are never torn down, and the
    report carries per-rank restart counts."""
    from pathway_tpu.internals.resilience import ClusterSupervisor

    sup = ClusterSupervisor(
        _rank_worker(tmp_path),
        3,
        env={"DRILL_MODE": "once"},
        restart_scope="rank",
        policy=_rank_policy(3),
    )
    report = sup.run(timeout=60)
    assert report.returncode == 0, report.failures
    assert report.rank_restarts == {1: 1}, report.rank_restarts
    assert report.restarts == 1
    assert len(report.recovery_seconds) == 1


def test_restart_scope_validation():
    import sys

    from pathway_tpu.internals.resilience import ClusterSupervisor

    with pytest.raises(ValueError, match="restart_scope"):
        ClusterSupervisor([sys.executable, "-c", "pass"], 1, restart_scope="bogus")


def test_backoff_streak_resets_after_stable_window(tmp_path):
    """Regression: the restart budget counts the current failure STREAK,
    not lifetime restarts.  A rank that fails 4 times with stable-healthy
    windows in between must survive a max_restarts=2 budget — each reset
    window clears the streak — while the same schedule with the reset
    disabled exhausts the budget and gives up."""
    from pathway_tpu.internals.resilience import ClusterSupervisor

    argv = _rank_worker(tmp_path)
    with_reset = ClusterSupervisor(
        argv,
        2,
        env={"DRILL_MODE": "flaky"},
        restart_scope="rank",
        poll_interval_s=0.02,
        healthy_reset_polls=5,
        policy=_rank_policy(2),
    ).run(timeout=120)
    assert with_reset.returncode == 0, with_reset.failures
    assert with_reset.rank_restarts == {0: 4}, with_reset.rank_restarts

    without_reset = ClusterSupervisor(
        argv,
        2,
        env={"DRILL_MODE": "flaky"},
        restart_scope="rank",
        poll_interval_s=0.02,
        healthy_reset_polls=None,
        policy=_rank_policy(2),
    ).run(timeout=120)
    assert without_reset.returncode == 1
    assert without_reset.rank_restarts == {0: 2}, without_reset.rank_restarts
