"""De-stubbed service connectors, tested against injectable fakes
(the kafka MockBroker / s3 fake-client pattern; reference counterparts in
``python/pathway/io/*`` and ``src/connectors/data_storage.rs``)."""

import json
import threading
import time

import pathway_tpu as pw
from tests.utils import T


def _word_table():
    return T(
        """
        word | n
        a    | 1
        b    | 2
        """
    )


def test_mongodb_write_fake_client():
    inserted = []

    class FakeColl:
        def insert_many(self, docs):
            inserted.extend(docs)

    class FakeClient:
        def __getitem__(self, db):
            assert db == "testdb"
            return {"c": FakeColl()}

    t = _word_table()
    pw.io.mongodb.write(
        t,
        connection_string="mongodb://x",
        database="testdb",
        collection="c",
        client=FakeClient(),
    )
    pw.run()
    assert sorted((d["word"], d["n"], d["diff"]) for d in inserted) == [
        ("a", 1, 1),
        ("b", 2, 1),
    ]
    assert all("time" in d for d in inserted)


def test_bigquery_write_fake_client():
    batches = []

    class FakeBQ:
        def insert_rows_json(self, table_ref, rows):
            batches.append((table_ref, list(rows)))
            return []

    t = _word_table()
    pw.io.bigquery.write(t, "ds", "tbl", client=FakeBQ())
    pw.run()
    (ref, rows), = batches
    assert ref == "ds.tbl"
    assert sorted(r["word"] for r in rows) == ["a", "b"]
    assert all(r["diff"] == 1 and "time" in r for r in rows)


def test_pubsub_write_fake_publisher():
    published = []

    class FakePublisher:
        def topic_path(self, project, topic):
            return f"projects/{project}/topics/{topic}"

        def publish(self, topic, data, **attrs):
            published.append((topic, data, attrs))

    t = _word_table().select(payload=pw.apply(lambda w: w.encode(), pw.this.word))
    pw.io.pubsub.write(t, FakePublisher(), "proj", "top")
    pw.run()
    assert sorted(d for _t, d, _a in published) == [b"a", b"b"]
    assert all(t == "projects/proj/topics/top" for t, _d, _a in published)
    assert all(a["pathway_diff"] == "1" for _t, _d, a in published)


def test_pubsub_write_requires_single_column():
    t = _word_table()
    try:
        pw.io.pubsub.write(t, object(), "p", "t")
        assert False, "expected ValueError"
    except ValueError as e:
        assert "single payload column" in str(e)


def test_slack_send_alerts_fake_poster():
    posts = []

    def poster(url, headers, payload):
        posts.append((url, headers, payload))

    t = _word_table()
    pw.io.slack.send_alerts(t.word, "C123", "xoxb-tok", poster=poster)
    pw.run()
    assert sorted(p["text"] for _u, _h, p in posts) == ["a", "b"]
    assert all(p["channel"] == "C123" for _u, _h, p in posts)
    assert all(h["Authorization"] == "Bearer xoxb-tok" for _u, h, _p in posts)


def test_logstash_write_fake_sender_with_retries():
    sent = []
    fail_first = [True]

    def sender(endpoint, payload):
        if fail_first[0]:
            fail_first[0] = False
            raise ConnectionError("transient")
        sent.append((endpoint, json.loads(payload)))

    t = _word_table()
    pw.io.logstash.write(t, "http://ls:5044", n_retries=2, sender=sender)
    pw.run()
    assert len(sent) == 2
    assert all(e == "http://ls:5044" for e, _d in sent)
    assert sorted(d["word"] for _e, d in sent) == ["a", "b"]


def test_nats_mock_roundtrip():
    """Writer publishes to a mock subject; a reader on the same subject
    receives the rows (pub/sub wiring + headers)."""
    from pathway_tpu.io.nats import MockNats

    broker = MockNats.get("mock://rt1")
    received = []
    broker.subscribe("updates", lambda p, h: received.append((p, h)))

    t = _word_table()
    pw.io.nats.write(t, "mock://rt1", "updates", format="json")
    pw.run()
    assert len(received) == 2
    docs = sorted(json.loads(p)["word"] for p, _h in received)
    assert docs == ["a", "b"]
    assert all(h["pathway_diff"] == "1" for _p, h in received)


def test_nats_reader_receives_messages():
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.io.nats import MockNats

    broker = MockNats.get("mock://rt2")

    class S(pw.Schema):
        word: str

    t = pw.io.nats.read("mock://rt2", "words", schema=S, format="json")
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    results = {}

    def on_change(key, row, time_, is_addition):
        if is_addition:
            results[row["word"]] = row["n"]

    pw.io.subscribe(counts, on_change=on_change)

    def feed():
        time.sleep(0.3)
        broker.publish("words", b'{"word": "x"}')
        broker.publish("words", b'{"word": "x"}')
        broker.publish("words", b'{"word": "y"}')
        time.sleep(0.5)
        G.active_scheduler.stop()

    th = threading.Thread(target=feed, daemon=True)
    th.start()
    pw.run(autocommit_duration_ms=20)
    th.join()
    assert results == {"x": 2, "y": 1}


def test_pyfilesystem_read_fake_fs():
    class FakeInfo:
        def __init__(self, size):
            self.size = size
            self.modified = "2026-01-01"

    class FakeFS:
        def __init__(self):
            self.files = {"/docs/a.txt": b"alpha", "/docs/b.bin": b"\x00\x01"}

        class walk:
            pass

        def listdir(self, path):
            return sorted(p.rsplit("/", 1)[1] for p in self.files)

        def readbytes(self, path):
            return self.files[path]

        def getinfo(self, path, namespaces=None):
            return FakeInfo(len(self.files[path]))

    fs = FakeFS()
    fs.walk = type(
        "W", (), {"files": staticmethod(lambda path="/": sorted(fs.files))}
    )()
    t = pw.io.pyfilesystem.read(fs, path="/docs", mode="static", with_metadata=True)
    keys, cols = pw.debug.table_to_dicts(t)
    datas = sorted(cols["data"].values())
    assert datas == [b"\x00\x01", b"alpha"]
    metas = list(cols["_metadata"].values())
    assert all("path" in m for m in metas)


def test_deltalake_roundtrip_change_stream(tmp_path):
    """write -> read replays the change stream including retractions."""
    t = T(
        """
        word | n | __time__ | __diff__
        a    | 1 | 2        | 1
        a    | 1 | 4        | -1
        a    | 2 | 4        | 1
        b    | 5 | 4        | 1
        """
    )
    path = tmp_path / "tbl"
    pw.io.deltalake.write(t, str(path))
    pw.run()
    assert (path / "_delta_log" / "00000000000000000000.json").exists()

    from pathway_tpu.internals.parse_graph import G

    G.clear()

    class S(pw.Schema):
        word: str
        n: int

    r = pw.io.deltalake.read(str(path), schema=S, mode="static")
    keys, cols = pw.debug.table_to_dicts(r)
    final = sorted((cols["word"][k], cols["n"][k]) for k in keys)
    assert final == [("a", 2), ("b", 5)]  # (a,1) retracted


def test_deltalake_appends_stream_new_versions(tmp_path):
    """Streaming reader picks up commits appended after the first read."""
    from pathway_tpu.internals.parse_graph import G

    path = tmp_path / "tbl"
    t1 = _word_table()
    pw.io.deltalake.write(t1, str(path))
    pw.run()
    G.clear()

    class S(pw.Schema):
        word: str
        n: int

    r = pw.io.deltalake.read(str(path), schema=S, mode="streaming")
    seen = {}

    def on_change(key, row, time_, is_addition):
        if is_addition:
            seen[row["word"]] = row["n"]

    pw.io.subscribe(r, on_change=on_change)

    def feed():
        time.sleep(0.5)
        # append a new commit out-of-band (another writer)
        from pathway_tpu.io.deltalake import _DeltaWriter

        w = _DeltaWriter(str(path))
        w.write({"word": "c", "n": 9}, 8, 1)
        w.flush()
        time.sleep(1.0)
        G.active_scheduler.stop()

    th = threading.Thread(target=feed, daemon=True)
    th.start()
    pw.run(autocommit_duration_ms=20)
    th.join()
    assert seen == {"a": 1, "b": 2, "c": 9}
