"""Json value semantics, 128-bit key/pointer API, and env config
refresh — reference ``internals/json.py``, ``src/engine/value.rs`` Key,
and the PATHWAY_* env contract in ``internals/config.py``.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import keys as K
from pathway_tpu.internals.json import Json
from tests.utils import T, run_to_rows


# ---------------------------------------------------------------------------
# Json


def test_json_wrapping_and_access():
    j = Json({"a": {"b": [1, 2, 3]}, "s": "x", "f": 2.5, "t": True})
    assert j["a"]["b"][1].as_int() == 2
    assert j["s"].as_str() == "x"
    assert j["f"].as_float() == 2.5
    assert j["t"].as_bool() is True
    assert j.get("missing", default="d") == "d"


def test_json_equality_and_hash():
    a = Json({"x": [1, {"y": 2}]})
    b = Json({"x": [1, {"y": 2}]})
    c = Json({"x": [1, {"y": 3}]})
    assert a == b and hash(a) == hash(b)
    assert a != c


def test_json_through_pipeline_and_vm():
    """Json cells flow through select/get; the VM's OP_GET handles them
    natively (internals/expr_vm.py)."""
    pw.G.clear()
    rows = [(Json({"user": {"name": "ada", "score": 7}}),)]
    t = pw.debug.table_from_rows(pw.schema_from_types(j=object), rows)
    out = t.select(
        name=t.j.get("user").get("name"),
        score=t.j.get("user").get("score"),
        missing=t.j.get("nope", default="fallback"),
    )
    (r,) = run_to_rows(out)
    name, score, missing = r
    assert str(name).strip('"') == "ada" or name == "ada"
    assert (score.as_int() if isinstance(score, Json) else score) == 7
    assert (
        missing == "fallback"
        or (isinstance(missing, Json) and missing.value == "fallback")
    )


def test_json_falsiness():
    assert not Json(None) and not Json({}) and not Json([]) and not Json(0)
    assert Json({"a": 1}) and Json([0]) and Json("x")


# ---------------------------------------------------------------------------
# keys / pointers


def test_ref_scalar_stable_and_type_tagged():
    assert K.ref_scalar(1, "a") == K.ref_scalar(1, "a")
    # type tagging: the INT 1 and the STRING "1" hash differently
    assert K.ref_scalar(1) != K.ref_scalar("1")
    assert K.ref_scalar(True) != K.ref_scalar(1)
    # 128-bit range
    assert 0 <= int(K.ref_scalar("x")) < 2**128


def test_pointer_repr_and_value():
    p = K.ref_scalar("row")
    assert isinstance(p, K.Pointer)
    assert p.value == int(p)
    assert str(p).startswith("^")


def test_keys_for_values_batch_matches_scalar():
    args = [(1, "a"), (2, "b"), (3, "c")]
    batch = K.keys_for_values(args)
    assert list(batch) == [K.ref_scalar(*a) for a in args]


def test_pointer_from_in_pipeline_matches_row_ids():
    pw.G.clear()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    t = pw.debug.table_from_rows(S, [(1, "x"), (2, "y")])
    withptr = t.select(t.v, p=t.pointer_from(t.k))
    from tests.utils import _run_capture

    ((rows, _),) = _run_capture(withptr)
    for key, (v, p) in rows.items():
        assert key == p  # pointer_from(pk) reproduces the row id


def test_sequential_keys_distinct():
    ks = {K.sequential_key(i) for i in range(100)}
    assert len(ks) == 100


# ---------------------------------------------------------------------------
# config


def test_config_env_refresh(monkeypatch):
    from pathway_tpu.internals.config import pathway_config

    monkeypatch.setenv("PATHWAY_THREADS", "3")
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    monkeypatch.setenv("PATHWAY_FIRST_PORT", "12345")
    pathway_config.refresh()
    try:
        assert pathway_config.threads == 3
        assert pathway_config.processes == 2
        assert pathway_config.process_id == 1
        assert pathway_config.first_port == 12345
        assert pathway_config.total_workers == 6
    finally:
        monkeypatch.undo()
        pathway_config.refresh()


def test_config_bad_env_values_fall_back(monkeypatch):
    from pathway_tpu.internals.config import pathway_config

    monkeypatch.setenv("PATHWAY_THREADS", "not-a-number")
    pathway_config.refresh()
    try:
        assert pathway_config.threads >= 1  # default, not a crash
    finally:
        monkeypatch.undo()
        pathway_config.refresh()
