"""Connector round-trips: write a table through each file format, read
it back, recover the original — the end-to-end contract the reference
pins with its csv/jsonlines integration tests
(``python/pathway/tests/test_io.py`` role).  Also covers type fidelity
through jsonlines (ints vs floats vs bools vs strings), CSV quoting,
and streaming-update output records (time/diff columns).
"""

from __future__ import annotations

import csv as _csv
import json
import os
import threading
import time

import pytest

import pathway_tpu as pw
from tests.utils import run_to_rows


def _write_and_read(tmp_path, rows, schema, write_fmt, read_back):
    pw.G.clear()
    t = pw.debug.table_from_rows(schema, rows)
    out = tmp_path / f"out.{write_fmt}"
    if write_fmt == "jsonl":
        pw.io.jsonlines.write(t, str(out))
    else:
        pw.io.csv.write(t, str(out))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return read_back(out)


def test_jsonlines_roundtrip_type_fidelity(tmp_path):
    rows = [
        (1, 2.5, True, "plain"),
        (2, -0.0, False, 'quotes "inside" and, commas'),
        (3, 1e300, True, "unicode: ünïcødé ✓"),
        (4, 2.0, False, ""),  # float that LOOKS like an int
    ]
    schema = pw.schema_from_types(i=int, f=float, b=bool, s=str)
    pw.G.clear()
    t = pw.debug.table_from_rows(schema, rows)
    out = tmp_path / "data.jsonl"
    pw.io.jsonlines.write(t, str(out))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    # read back through the connector; types must survive
    pw.G.clear()

    class S(pw.Schema):
        i: int
        f: float
        b: bool
        s: str

    back = pw.io.jsonlines.read(str(out), schema=S, mode="static")
    got = sorted(run_to_rows(back.select(back.i, back.f, back.b, back.s)))
    assert got == sorted(rows)
    for r in got:
        assert isinstance(r[0], int) and isinstance(r[1], float)
        assert isinstance(r[2], bool) and isinstance(r[3], str)


def test_csv_roundtrip_with_quoting(tmp_path):
    rows = [
        (1, "plain"),
        (2, "has,comma"),
        (3, 'has "quotes"'),
        (4, "multi word value"),
    ]
    schema = pw.schema_from_types(k=int, s=str)
    pw.G.clear()
    t = pw.debug.table_from_rows(schema, rows)
    out = tmp_path / "data.csv"
    pw.io.csv.write(t, str(out))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    pw.G.clear()

    class S(pw.Schema):
        k: int
        s: str

    back = pw.io.csv.read(str(out), schema=S, mode="static")
    got = sorted(run_to_rows(back.select(back.k, back.s)))
    assert got == sorted(rows)


def test_jsonlines_output_carries_time_and_diff(tmp_path):
    """Streaming output rows record the epoch and the sign — the CDC
    contract downstream consumers rely on."""
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    v | __time__ | __diff__
    1 | 2        | 1
    2 | 2        | 1
    1 | 4        | -1
    """
    )
    out = tmp_path / "stream.jsonl"
    pw.io.jsonlines.write(t, str(out))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    recs = [json.loads(line) for line in open(out)]
    assert all("time" in r and "diff" in r for r in recs)
    adds = [r for r in recs if r["diff"] == 1]
    dels = [r for r in recs if r["diff"] == -1]
    assert {r["v"] for r in adds} == {1, 2}
    assert [r["v"] for r in dels] == [1]
    # the retraction happens at a later epoch than its addition
    add_t = next(r["time"] for r in adds if r["v"] == 1)
    del_t = dels[0]["time"]
    assert del_t > add_t


def test_csv_reader_streaming_appends(tmp_path):
    """CSV dir-watching picks up appended rows with a consistent header."""
    p = tmp_path / "data.csv"
    p.write_text("k,s\n1,one\n")

    class S(pw.Schema):
        k: int
        s: str

    pw.G.clear()
    t = pw.io.csv.read(str(tmp_path), schema=S, mode="streaming")
    got = []
    pw.io.subscribe(t, on_change=lambda key, row, tm, add: got.append(row["k"]))

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()
    deadline = time.monotonic() + 8
    while 1 not in got and time.monotonic() < deadline:
        time.sleep(0.02)
    with open(p, "a") as f:
        f.write("2,two\n")
    while 2 not in got and time.monotonic() < deadline:
        time.sleep(0.02)
    sched.stop()
    run_t.join(timeout=3)
    assert got[:2] == [1, 2]


def test_jsonlines_skips_malformed_lines(tmp_path):
    p = tmp_path / "mixed.jsonl"
    p.write_text(
        '{"a": 1}\n'
        "this is not json\n"
        '{"a": 2}\n'
        '{"a": }\n'
        '{"a": 3}\n'
    )

    class S(pw.Schema):
        a: int

    pw.G.clear()
    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    got = sorted(run_to_rows(t.select(t.a)))
    assert got == [(1,), (2,), (3,)]


def test_null_and_missing_fields_coerce_to_defaults(tmp_path):
    p = tmp_path / "nulls.jsonl"
    p.write_text('{"a": 1, "b": "x"}\n{"a": 2}\n{"a": 3, "b": null}\n')

    class S(pw.Schema):
        a: int
        b: str | None

    pw.G.clear()
    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    got = sorted(run_to_rows(t.select(t.a, t.b)), key=lambda r: r[0])
    assert got == [(1, "x"), (2, None), (3, None)]


def test_psql_snapshot_output_applies_updates(tmp_path):
    """The psql-family writer over a real sqlite connection maintains a
    live snapshot table end-to-end: upserts overwrite by key, a
    retraction without replacement deletes."""
    import sqlite3

    from pathway_tpu.io.postgres import _PsqlWriter
    from pathway_tpu.io._connector import attach_writer

    db = tmp_path / "snap.db"
    conn = sqlite3.connect(db, check_same_thread=False)
    conn.execute("CREATE TABLE counts (word TEXT PRIMARY KEY, n INTEGER)")
    conn.commit()

    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    word | n | __time__ | __diff__
    a    | 1 | 2        | 1
    b    | 1 | 2        | 1
    a    | 1 | 4        | -1
    a    | 2 | 4        | 1
    """
    )
    writer = _PsqlWriter(None, conn, "counts", snapshot_keys=["word"])
    attach_writer(t, writer, name="snapshot_out")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # the run closes the writer's connection after its final flush;
    # inspect through a fresh one
    check = sqlite3.connect(db)
    rows = sorted(check.execute("SELECT word, n FROM counts").fetchall())
    check.close()
    assert rows == [("a", 2), ("b", 1)]
