"""RAG quality eval harness over the TPU embedder + reranker stack
(offline analogue of the reference ``integration_tests/rag_evals/``:
in-tree dataset, recall@k + answer-overlap metrics)."""

import dataclasses

import pathway_tpu as pw
from pathway_tpu.models.encoder import MINILM_L6
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder
from pathway_tpu.xpacks.llm.rag_eval import (
    RagEvalItem,
    answer_token_f1,
    evaluate_retrieval,
    recall_at_k,
)
from pathway_tpu.xpacks.llm.rerankers import CrossEncoderReranker
from tests.utils import T, run_to_rows

# ---------------------------------------------------------------------------
# in-tree mini corpus + QA dataset (the reference keeps its dataset under
# integration_tests/rag_evals/dataset/)

CORPUS = {
    1: "apples grow on trees in the orchard and are harvested in autumn",
    2: "bananas are yellow tropical fruit rich in potassium",
    3: "the tpu accelerator runs matrix multiplications on a systolic array",
    4: "paris is the capital city of france on the seine river",
    5: "whales are marine mammals that breathe air through blowholes",
    6: "the kafka broker stores partitioned replicated message logs",
    7: "sourdough bread rises using wild yeast in a fermented starter",
    8: "saturn is the sixth planet and has prominent icy rings",
}

DATASET = [
    RagEvalItem("where do apples grow?", {1}, "apples grow on trees in the orchard"),
    RagEvalItem("what color are bananas?", {2}, "bananas are yellow"),
    RagEvalItem("what runs matrix multiplications?", {3}, "the tpu accelerator"),
    RagEvalItem("what is the capital of france?", {4}, "paris"),
    RagEvalItem("how do whales breathe?", {5}, "whales breathe air through blowholes"),
    RagEvalItem("what does the kafka broker store?", {6}, "partitioned replicated message logs"),
    RagEvalItem(
        "what starter makes sourdough bread?", {7}, "wild yeast in a fermented starter"
    ),
    RagEvalItem("which planet has icy rings?", {8}, "saturn"),
]

# 0 transformer layers: mean-pooled random token projections = a random
# projection of the bag of words.  UNTRAINED attention layers would
# scramble the lexical signal the offline eval relies on; with real BGE
# weights (checkpoint_dir=...) the same harness measures semantic
# retrieval — this pins the harness itself, not model quality.
TINY = dataclasses.replace(
    MINILM_L6, hidden=64, layers=0, heads=4, mlp_dim=128, max_len=64
)
TINY_CROSS = dataclasses.replace(
    TINY, layers=2, num_labels=1, normalize=False
)


def _build_store(embedder):
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(d=int, data=str),
        [(d, text) for d, text in CORPUS.items()],
    ).select(
        data=pw.this.data,
        _metadata=pw.apply(lambda d: {"doc_id": d, "path": f"/c/{d}.txt"}, pw.this.d),
    )
    factory = BruteForceKnnFactory(embedder=embedder, reserved_space=64)
    return DocumentStore(docs, retriever_factory=factory)


def _retriever(store, k_max=8):
    """One batched retrieve over the whole dataset -> per-question lists."""
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(q=str), [(item.question,) for item in DATASET]
    ).select(
        query=pw.this.q,
        k=pw.apply(lambda _q: k_max, pw.this.q),
        metadata_filter=pw.apply(lambda _q: None, pw.this.q),
        filepath_globpattern=pw.apply(lambda _q: None, pw.this.q),
    )
    res = store.retrieve_query(queries)
    rows = run_to_rows(res.select(q=pw.this.query, result=pw.this.result))
    by_q = {q: result for q, result in rows}
    return {
        item.question: [d["metadata"]["doc_id"] for d in by_q[item.question]]
        for item in DATASET
    }


def test_rag_retrieval_recall_at_k():
    """TPU embedder end-to-end through DocumentStore: recall@3 over the
    in-tree dataset must clear 0.85 (random-projection embeddings carry
    token overlap; relevant docs share distinctive words)."""
    embedder = TPUEncoderEmbedder(config=TINY)
    store = _build_store(embedder)
    retrieved = _retriever(store)
    report = evaluate_retrieval(
        DATASET, lambda q, k: retrieved[q][:k], k=3
    )
    assert report.recall_at_k >= 0.85, str(report)
    assert len(report.per_question) == len(DATASET)


def test_rag_reranker_stage_scores_all_pairs():
    """Cross-encoder reranker over retrieved candidates: scores exist for
    every (query, doc) pair and reordering never LOSES docs."""
    embedder = TPUEncoderEmbedder(config=TINY)
    store = _build_store(embedder)
    retrieved = _retriever(store)
    rr = CrossEncoderReranker(config=TINY_CROSS)
    q = DATASET[0].question
    docs = [{"text": CORPUS[d]} for d in retrieved[q][:4]]
    scores = rr.__batch__(docs, [q] * len(docs))
    assert len(scores) == 4 and all(isinstance(s, float) for s in scores)
    order = sorted(range(4), key=lambda i: -scores[i])
    assert sorted(order) == [0, 1, 2, 3]


def test_rag_answer_overlap_with_extractive_chat():
    """Full RAG loop with a deterministic extractive 'chat' (returns the
    first context doc): mean answer token-F1 over the dataset."""
    embedder = TPUEncoderEmbedder(config=TINY)
    store = _build_store(embedder)
    retrieved = _retriever(store)

    def answer(question):
        # extractive "reader": among the top-3 retrieved docs, answer with
        # the one sharing the most question tokens
        from pathway_tpu.xpacks.llm.rag_eval import _tokens

        qtok = set(_tokens(question))
        cands = retrieved[question][:3]
        best = max(cands, key=lambda d: len(qtok & set(_tokens(CORPUS[d]))))
        return CORPUS[best]

    report = evaluate_retrieval(
        DATASET, lambda q, k: retrieved[q][:k], k=3, answer=answer
    )
    assert report.answer_f1 is not None
    # extractive answers from the top doc must overlap expected answers
    assert report.answer_f1 >= 0.4, str(report)


def test_metric_functions():
    assert answer_token_f1("paris", "paris") == 1.0
    assert answer_token_f1("london", "paris") == 0.0
    assert 0.0 < answer_token_f1("the capital is paris", "paris") < 1.0
    assert recall_at_k([[1, 2], [3]], [frozenset({2}), frozenset({9})], 2) == 0.5
