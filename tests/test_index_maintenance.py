"""Online index maintenance (ISSUE 9): the delta-segment + tombstone +
background-merge layer (``stdlib/indexing/segments.py``) under churn.

The core property drill interleaves upserts, deletions and queries over
every backing index type (host HNSW graph, device sharded slab, device
IVF) and holds recall >= 0.95 against brute force over the reference
corpus at every step — including immediately after explicit merges and
after a ``state_dict``/``load_state_dict`` round-trip into a fresh
index.  The remaining tests pin the sharp edges individually: snapshot
consistency of a checkpoint racing a merge, full rollback of a failed
merge, HNSW tombstone compaction, absent-key deletes, and sharded-slab
dispatch handles surviving a capacity grow."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from pathway_tpu.parallel import IvfKnnIndex, ShardedKnnIndex
from pathway_tpu.stdlib.indexing.hnsw import HnswIndex
from pathway_tpu.stdlib.indexing.segments import SegmentedIndex

D = 16  # vector dimensionality for every test in this file
K = 5


def _unit(rng, n=1):
    x = rng.standard_normal((n, D)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


def _factory(kind):
    if kind == "hnsw":
        return HnswIndex(D, metric="cos")
    if kind == "sharded":
        return ShardedKnnIndex(D, metric="cos", capacity=256)
    # nprobe == nlist: the scan is exhaustive, so any recall loss is the
    # maintenance layer's fault, not the ANN approximation's
    return IvfKnnIndex(D, metric="cos", capacity=1024, nlist=8, nprobe=8)


def _recall(seg, ref, queries, k=K):
    """Recall of ``seg.search`` vs brute force over the reference dict."""
    got = seg.search(queries, k)
    keys = list(ref)
    mat = np.stack([ref[key] for key in keys])
    mat = mat / np.linalg.norm(mat, axis=1, keepdims=True)
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    scores = qn @ mat.T
    hits = total = 0
    for qi, reply in enumerate(got):
        kk = min(k, len(keys))
        truth = {keys[i] for i in np.argsort(-scores[qi])[:kk]}
        hits += len({key for key, _ in reply[:kk]} & truth)
        total += kk
    return hits / max(total, 1)


# ---------------------------------------------------------------------------
# the seeded churn property


@pytest.mark.parametrize("kind", ["hnsw", "sharded", "ivf"])
def test_segmented_churn_recall_property(kind):
    """Seeded interleaving of upserts (new + re-keyed), deletions
    (live + absent), explicit merges and queries: recall vs brute force
    must hold at EVERY step, the live key set must track the reference
    exactly, and a checkpoint round-trip must preserve both."""
    rng = np.random.default_rng(42)
    ref: dict[str, np.ndarray] = {}
    seg = SegmentedIndex(_factory(kind), delta_cap=32, auto_merge=False)
    next_id = 0
    try:
        for step in range(12):
            # upserts: ~30% overwrite a live key, the rest are new
            items = []
            for _ in range(int(rng.integers(8, 24))):
                if ref and rng.random() < 0.3:
                    key = str(rng.choice(sorted(ref)))
                else:
                    key = f"k{next_id}"
                    next_id += 1
                vec = _unit(rng)[0]
                items.append((key, vec))
                ref[key] = vec
            seg.add(items)
            # deletions on odd steps: live victims plus an absent key
            # (replay can send deletes for rows that never landed)
            if ref and step % 2:
                victims = [
                    str(v)
                    for v in rng.choice(
                        sorted(ref), size=min(5, len(ref)), replace=False
                    )
                ]
                seg.remove(victims + [f"absent-{step}"])
                for v in victims:
                    del ref[v]
            if step in (4, 8, 10):
                seg.merge(wait=True)
            assert set(seg.keys()) == set(ref), f"step {step} key drift"
            assert len(seg) == len(ref)
            # queries: perturbed live vectors + fresh randoms
            probes = [str(v) for v in rng.choice(sorted(ref), size=4)]
            q = np.concatenate(
                [
                    np.stack([ref[p] for p in probes])
                    + 0.1 * rng.standard_normal((4, D)).astype(np.float32),
                    _unit(rng, 4),
                ]
            )
            r = _recall(seg, ref, q)
            assert r >= 0.95, f"step {step}: recall {r:.3f} < 0.95"
        assert seg.merges_total == 3

        # checkpoint round-trip into a completely fresh index
        state = seg.state_dict()
        seg2 = SegmentedIndex(_factory(kind), delta_cap=32, auto_merge=False)
        seg2.load_state_dict(state)
        assert set(seg2.keys()) == set(ref)
        q = _unit(rng, 8)
        r = _recall(seg2, ref, q)
        assert r >= 0.95, f"post-restore recall {r:.3f} < 0.95"
        # and the restored index keeps absorbing churn
        seg2.add([("fresh", _unit(rng)[0])])
        ref["fresh"] = seg2._delta["fresh"]
        assert "fresh" in seg2
        seg2.merge(wait=True)
        assert set(seg2.keys()) == set(ref)
    finally:
        seg.close()


# ---------------------------------------------------------------------------
# delta visibility and the bulk-load fast path


def test_segmented_upsert_visible_before_merge():
    rng = np.random.default_rng(0)
    seg = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=64, auto_merge=False)
    x = _unit(rng, 8)
    seg.add([(f"k{i}", x[i]) for i in range(8)])
    assert len(seg.main) == 0, "small batch must buffer in the delta"
    (res,) = seg.search(x[:1], 1)
    assert res[0][0] == "k0", "fresh upsert invisible to the next query"
    seg.remove(["k3"])
    (res,) = seg.search(x[3:4], 8)
    assert "k3" not in {k for k, _ in res}


def test_segmented_bulk_load_goes_straight_to_main():
    rng = np.random.default_rng(1)
    seg = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=16, auto_merge=False)
    x = _unit(rng, 32)
    seg.add([(f"k{i}", x[i]) for i in range(32)])  # batch >= delta_cap
    assert len(seg.main) == 32
    assert not seg._delta, "bulk load must not crawl through the delta"
    assert len(seg) == 32


def test_segmented_auto_merge_triggers():
    """Both merge triggers fire through the background maintenance
    thread: delta at capacity, then tombstones past the fraction."""
    rng = np.random.default_rng(2)
    seg = SegmentedIndex(
        HnswIndex(D, metric="cos"),
        delta_cap=8,
        tombstone_fraction=0.25,
        auto_merge=True,
    )
    try:
        x = _unit(rng, 64)
        for i in range(8):  # one-by-one: crosses delta_cap on the last add
            seg.add([(f"k{i}", x[i])])
        seg._maintenance.drain()
        assert seg.merges_total == 1
        assert not seg._delta and len(seg.main) == 8
        # grow main past the 16-tombstone floor (bulk path), delete a third
        seg.add([(f"k{i}", x[i]) for i in range(8, 64)])
        seg.remove([f"k{i}" for i in range(20)])
        seg._maintenance.drain()
        assert seg.merges_total == 2, seg.stats()
        assert len(seg.main) == 44 and not seg._tombs
        assert len(seg) == 44
    finally:
        seg.close()


# ---------------------------------------------------------------------------
# snapshot consistency and crash/rollback behavior


@pytest.mark.parametrize("kind", ["hnsw", "sharded"])
def test_segmented_state_dict_racing_merge_is_pre_merge_view(kind):
    """A checkpoint taken in the instant before a merge commits (the
    same window the chaos drill kills in) must serialize the pre-merge
    segmentation — frozen delta folded back — and restore cleanly."""
    rng = np.random.default_rng(3)
    seg = SegmentedIndex(_factory(kind), delta_cap=8, auto_merge=False)
    x = _unit(rng, 48)
    seg.add([(f"m{i}", x[i]) for i in range(32)])  # bulk -> main
    seg.add([(f"d{i}", x[32 + i]) for i in range(6)])  # delta
    seg.remove(["m0", "m1"])  # tombstones
    pre = seg.state_dict()
    pre_keys = set(seg.keys())

    captured = {}
    seg._pre_commit = lambda: captured.update(mid=seg.state_dict())
    seg.merge(wait=True)

    mid = captured["mid"]
    assert set(mid["delta_keys"]) == set(pre["delta_keys"])
    assert set(mid["tombstones"]) == set(pre["tombstones"])
    restored = SegmentedIndex(_factory(kind), delta_cap=8, auto_merge=False)
    restored.load_state_dict(mid)
    assert set(restored.keys()) == pre_keys
    # after the commit the same snapshot API returns the merged view
    post = seg.state_dict()
    assert not post["delta_keys"] and not post["tombstones"]
    assert len(seg.main) == len(pre_keys)
    assert set(seg.keys()) == pre_keys


def test_segmented_failed_merge_rolls_back_fully():
    """A merge that dies mid-flight must leave the index exactly as if
    it never started: delta + tombstones restored, not merging, and the
    next merge succeeds."""
    rng = np.random.default_rng(4)
    seg = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=8, auto_merge=False)
    x = _unit(rng, 40)
    seg.add([(f"m{i}", x[i]) for i in range(32)])
    seg.add([(f"d{i}", x[32 + i]) for i in range(5)])
    seg.remove(["m2"])
    before_keys = set(seg.keys())
    before_hits = seg.search(x[:4], 3)

    def boom():
        raise RuntimeError("rebuild died")

    seg.main.fresh = boom
    with pytest.raises(RuntimeError, match="rebuild died"):
        seg.merge(wait=True)
    assert seg.merge_failures == 1 and not seg._merging
    assert set(seg.keys()) == before_keys
    assert len(seg._delta) == 5 and seg._tombs == {"m2"}
    assert seg.search(x[:4], 3) == before_hits

    del seg.main.fresh  # restore the real rebuild path
    seg.merge(wait=True)
    assert seg.merges_total == 1 and not seg._delta and not seg._tombs
    assert set(seg.keys()) == before_keys


def test_segmented_upsert_during_merge_wins_over_frozen():
    """An upsert landing between a merge's freeze and its commit goes to
    the LIVE delta and must shadow the frozen (about-to-be-merged) value
    for every query — before the commit, after it, and after the next
    merge folds it into main."""
    rng = np.random.default_rng(5)
    seg = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=8, auto_merge=False)
    old = _unit(rng)[0]
    new = -old  # opposite direction: shadowing failures are unmissable
    seg.add([("k", old)])
    # the hook runs in the pre-commit window; the lock is re-entrant
    seg._pre_commit = lambda: seg.add([("k", new)])
    seg.merge(wait=True)
    del seg._pre_commit
    assert len(seg) == 1
    (res,) = seg.search(new[None, :], 1)
    assert res[0][0] == "k" and res[0][1] > 0.99, res
    seg.merge(wait=True)  # folds the winning value into main
    assert not seg._delta
    (res,) = seg.search(new[None, :], 1)
    assert res[0][0] == "k" and res[0][1] > 0.99, res


# ---------------------------------------------------------------------------
# deletes racing a merge: the frozen delta must never resurrect them


@pytest.mark.parametrize("kind", ["hnsw", "sharded"])
def test_segmented_remove_frozen_key_mid_merge(kind):
    """A key whose latest value lives in the FROZEN delta, deleted while
    the merge is in flight, must be invisible for the whole merge window
    (search), must serialize as deleted (a checkpoint taken in the
    window restores without it), and must stay gone after the commit and
    after every later merge — the exactly-once guarantee."""
    rng = np.random.default_rng(11)
    seg = SegmentedIndex(_factory(kind), delta_cap=8, auto_merge=False)
    x = _unit(rng, 48)
    seg.add([(f"m{i}", x[i]) for i in range(32)])  # bulk -> main
    seg.add([("victim", x[40]), ("d0", x[41]), ("d1", x[42])])  # delta

    captured = {}

    def in_window():
        # the merge has frozen the delta but not committed: delete the
        # frozen-delta key NOW (the re-entrant lock admits us)
        seg.remove(["victim"])
        (hits,) = seg.search(x[40][None, :], 8)
        captured["mid_hits"] = {key for key, _ in hits}
        captured["mid_state"] = seg.state_dict()

    seg._pre_commit = in_window
    seg.merge(wait=True)
    del seg._pre_commit

    # invisible inside the merge window, in search AND in the snapshot
    assert "victim" not in captured["mid_hits"]
    mid = captured["mid_state"]
    assert "victim" not in set(mid["delta_keys"]), (
        "mid-merge checkpoint serialized the deleted key's frozen copy"
    )
    # gone after the commit
    assert "victim" not in seg
    (hits,) = seg.search(x[40][None, :], 8)
    assert "victim" not in {key for key, _ in hits}
    # the NEXT merge (which retires the tombstone) must not fold the
    # frozen vector back into main — the review's resurrection path
    seg.merge(wait=True)
    assert "victim" not in seg and "victim" not in set(seg.keys())
    (hits,) = seg.search(x[40][None, :], 8)
    assert "victim" not in {key for key, _ in hits}
    assert {"d0", "d1"} <= set(seg.keys())

    # a checkpoint taken in the window restores WITHOUT the key, and
    # merging the restored index does not resurrect it either
    restored = SegmentedIndex(_factory(kind), delta_cap=8, auto_merge=False)
    restored.load_state_dict(mid)
    assert "victim" not in restored
    (hits,) = restored.search(x[40][None, :], 8)
    assert "victim" not in {key for key, _ in hits}
    restored.merge(wait=True)
    assert "victim" not in restored and "victim" not in set(restored.keys())
    (hits,) = restored.search(x[40][None, :], 8)
    assert "victim" not in {key for key, _ in hits}
    assert set(restored.keys()) == set(seg.keys())


def test_segmented_remove_between_freeze_and_rebuild_fold():
    """A delete landing in the instant between the freeze and the
    rebuild reading the frozen delta: the rebuild must not fold the
    deleted key into the new main."""
    holder: dict = {}

    class Sneaky(HnswIndex):
        @property
        def merge_strategy(self):  # read by _run_merge right after freeze
            seg = holder.get("seg")
            if (
                seg is not None
                and "victim" in seg._frozen
                and "victim" in seg._keys
            ):
                seg.remove(["victim"])
            return "rebuild"

    rng = np.random.default_rng(12)
    seg = SegmentedIndex(Sneaky(D, metric="cos"), delta_cap=4, auto_merge=False)
    holder["seg"] = seg
    x = _unit(rng, 8)
    # bulk load keeps the Sneaky instance as main (a rebuild would swap
    # in a plain HnswIndex via fresh() and disarm the trigger)
    seg.add([(f"m{i}", x[i]) for i in range(4)])
    assert len(seg.main) == 4 and isinstance(seg.main, Sneaky)
    seg.add([("victim", x[6]), ("d9", x[7])])
    seg.merge(wait=True)  # property deletes victim post-freeze
    assert "victim" not in seg
    assert "victim" not in {k for k in seg.main.keys()}, (
        "rebuild folded a post-freeze-deleted frozen key into main"
    )
    (hits,) = seg.search(x[6][None, :], 8)
    assert "victim" not in {key for key, _ in hits}
    seg.merge(wait=True)
    assert "victim" not in seg


def test_segmented_failed_merge_rollback_preserves_deletes():
    """A delete issued while a merge is in flight must survive that
    merge FAILING: the rollback folds the frozen delta back into the
    live segment but must not revive the deleted keys."""
    rng = np.random.default_rng(13)
    seg = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=8, auto_merge=False)
    x = _unit(rng, 40)
    seg.add([(f"m{i}", x[i]) for i in range(16)])  # bulk -> main
    seg.add([("victim", x[20]), ("d0", x[21]), ("d1", x[22])])

    def boom():
        # between freeze and commit: delete a frozen-delta key and a
        # main key, then die
        seg.remove(["victim", "m1"])
        raise RuntimeError("rebuild died")

    seg.main.fresh = boom
    with pytest.raises(RuntimeError, match="rebuild died"):
        seg.merge(wait=True)
    assert seg.merge_failures == 1 and not seg._merging
    assert "victim" not in seg and "m1" not in seg
    assert "victim" not in seg._delta, "rollback revived a deleted key"
    assert {"d0", "d1"} <= set(seg._delta)
    (hits,) = seg.search(x[20][None, :], 16)
    found = {key for key, _ in hits}
    assert "victim" not in found and "m1" not in found

    del seg.main.fresh  # the next merge succeeds and retires tombstones
    seg.merge(wait=True)
    assert not seg._tombs and not seg._delta
    assert "victim" not in seg and "m1" not in seg
    (hits,) = seg.search(x[20][None, :], 16)
    found = {key for key, _ in hits}
    assert "victim" not in found and "m1" not in found
    assert {"d0", "d1"} <= found


def test_segmented_load_state_dict_delete_wins_on_conflict():
    """Checkpoints written before the delta-view fix can carry a key in
    both delta_keys and tombstones; loading one must treat the key as
    deleted."""
    rng = np.random.default_rng(14)
    seg = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=8, auto_merge=False)
    x = _unit(rng, 4)
    seg.add([("a", x[0]), ("b", x[1])])
    state = seg.state_dict()
    state["tombstones"] = list(state["tombstones"]) + ["b"]  # conflict
    fresh = SegmentedIndex(HnswIndex(D, metric="cos"), delta_cap=8, auto_merge=False)
    fresh.load_state_dict(state)
    assert "a" in fresh and "b" not in fresh
    (hits,) = fresh.search(x[1][None, :], 4)
    assert "b" not in {key for key, _ in hits}
    fresh.merge(wait=True)
    assert "b" not in fresh


# ---------------------------------------------------------------------------
# concurrency: queries off the segment lock vs live updates and merges


@pytest.mark.parametrize("kind", ["hnsw", "sharded"])
def test_segmented_concurrent_queries_and_updates(kind):
    """Searcher threads hammer the index while the main thread upserts,
    deletes and auto-merges (background maintenance thread): no
    exception may escape, and the final membership must track the
    reference exactly.  Exercises the off-lock main search, _main_mutex
    exclusion around in-place merges, and the defensive slot decode."""
    seg = SegmentedIndex(_factory(kind), delta_cap=16, auto_merge=True)
    rng = np.random.default_rng(15)
    ref: dict[str, np.ndarray] = {}
    errors: list[BaseException] = []
    stop = threading.Event()

    def searcher(seed):
        srng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                seg.search(_unit(srng, 2), 3)
        except BaseException as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=searcher, args=(100 + i,)) for i in range(2)]
    for t in threads:
        t.start()
    try:
        next_id = 0
        for step in range(30):
            items = []
            for _ in range(6):
                key = f"k{next_id}"
                next_id += 1
                vec = _unit(rng)[0]
                items.append((key, vec))
                ref[key] = vec
            seg.add(items)
            if ref and step % 3 == 2:
                victims = [
                    str(v)
                    for v in rng.choice(
                        sorted(ref), size=min(4, len(ref)), replace=False
                    )
                ]
                seg.remove(victims + ["absent"])
                for v in victims:
                    del ref[v]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        seg.close()
    assert not errors, f"concurrent search raised: {errors[:3]}"
    assert set(seg.keys()) == set(ref)
    q = _unit(rng, 8)
    r = _recall(seg, ref, q)
    assert r >= 0.95, f"post-churn recall {r:.3f} < 0.95"


def test_sharded_handle_across_load_state_dict_raises():
    """load_state_dict replaces the slot->key map wholesale, so a
    dispatch handle from before the restore must be rejected (its
    generation gates the decode) instead of resolving to wrong keys."""
    rng = np.random.default_rng(16)
    idx = ShardedKnnIndex(D, metric="cos", capacity=128)
    x = _unit(rng, 8)
    idx.add_batch([f"a{i}" for i in range(8)], x)
    state = idx.state_dict()
    handle = idx.dispatch(x[:1], 1)
    idx.load_state_dict(state)
    assert idx._inflight == 0 and not idx._quarantine
    with pytest.raises(RuntimeError, match="stale dispatch handle"):
        idx.collect(handle)
    # post-restore dispatches decode against the fresh map
    rows = idx.collect(idx.dispatch(x[:1], 1))
    assert rows[0][0][0] == "a0"
    assert idx._inflight == 0


# ---------------------------------------------------------------------------
# HNSW satellites: absent-key delete, tombstone compaction


def test_hnsw_remove_absent_key_is_noop():
    idx = HnswIndex(D, metric="cos")
    idx.remove(["ghost"])  # empty index
    assert len(idx) == 0
    rng = np.random.default_rng(6)
    x = _unit(rng, 4)
    idx.add([(f"k{i}", x[i]) for i in range(4)])
    idx.remove(["ghost", "k1", "ghost2"])  # mixed live/absent
    assert len(idx) == 3 and "k1" not in idx
    idx.remove(["k1"])  # double delete
    assert len(idx) == 3


def test_hnsw_compaction_reclaims_tombstoned_slots():
    """Deleting past ``tombstone_fraction`` of the slot high-water mark
    must rebuild the graph: dead slots reclaimed, survivors searchable."""
    from pathway_tpu.internals import native as _native

    if _native.load() is None:
        pytest.skip("native module unavailable: no slots to compact")
    rng = np.random.default_rng(7)
    idx = HnswIndex(D, metric="cos", tombstone_fraction=0.33)
    x = _unit(rng, 128)
    idx.add([(i, x[i]) for i in range(128)])
    assert idx._hw == 128 and idx.compactions == 0
    idx.remove(list(range(0, 128, 3)))  # ~33% dead: below the strict bound
    dead_now = idx._hw - len(idx._slot_of)
    if dead_now > 0:  # not yet compacted: push past the fraction
        idx.remove(list(range(1, 128, 3)))
    assert idx.compactions >= 1, (idx._hw, len(idx))
    assert idx._hw == len(idx._slot_of), "compaction left dead slots"
    survivors = sorted(idx.keys())
    res = idx.search(x[survivors[0]][None, :], 1)
    assert res[0][0][0] == survivors[0]
    # the counter the stats/metrics surface report
    assert idx.stats()["compactions"] == idx.compactions


# ---------------------------------------------------------------------------
# sharded slab satellite: dispatch handles across _grow


def test_sharded_pre_grow_handle_stays_valid():
    """A dispatch handle taken before a capacity grow must collect to
    the keys live at dispatch time: the handle's computation captured
    the pre-grow buffers and the generation tag in the handle keeps it
    from being confused with the new slab."""
    rng = np.random.default_rng(8)
    idx = ShardedKnnIndex(D, metric="cos", capacity=128)
    assert idx.capacity == 128
    x = _unit(rng, 100)
    idx.add_batch([f"a{i}" for i in range(100)], x)
    v0 = idx._version

    handle = idx.dispatch(x[:3], 1)
    # outstanding handle; now force a realloc with a second corpus
    y = _unit(rng, 64)
    idx.add_batch([f"b{i}" for i in range(64)], y)
    assert idx.capacity > 128 and idx._version > v0
    assert handle[3] == v0, "handle lost its pre-grow generation tag"

    rows = idx.collect(handle)
    assert [r[0][0] for r in rows] == ["a0", "a1", "a2"]
    # a post-grow dispatch sees the union
    rows2 = idx.collect(idx.dispatch(y[:1], 1))
    assert rows2[0][0][0] == "b0"


def test_sharded_remove_during_flight_quarantines_slot():
    """A slot freed while a handle is in flight must not be reused (and
    decoded to the wrong key) until every outstanding handle resolves."""
    rng = np.random.default_rng(9)
    idx = ShardedKnnIndex(D, metric="cos", capacity=128)
    x = _unit(rng, 8)
    idx.add_batch([f"a{i}" for i in range(8)], x)
    handle = idx.dispatch(x[:1], 2)
    idx.remove(["a5"])
    assert idx._quarantine and not idx._free
    idx.add_batch(["fresh"], _unit(rng))  # must NOT take a5's slot
    assert idx._slot_of["fresh"] not in idx._quarantine
    rows = idx.collect(handle)
    assert rows[0][0][0] == "a0"
    assert not idx._quarantine, "quarantine not drained after last collect"
    assert idx._free, "freed slot lost instead of returned to the pool"
