"""Golden-plan harness: the optimizer's textual plan is a stable,
reviewable artifact.

Each builder constructs a deterministic graph (fresh-graph fixture
guarantees stable node ids), runs the full level-2 pipeline, and
compares ``plan.format()`` byte-for-byte against the committed file in
``tests/plans/``.  An intentional optimizer change regenerates them:

    python -m pytest tests/test_plan_golden.py --regen-plans

then commit the updated ``tests/plans/*.txt`` alongside the change.
"""

from __future__ import annotations

import pathlib

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.rewrite import optimize_graph
from pathway_tpu.engine.graph import CaptureNode
from pathway_tpu.internals.parse_graph import G

PLANS_DIR = pathlib.Path(__file__).parent / "plans"


class _W(pw.Schema):
    word: str


class _E(pw.Schema):
    k: str
    a: int
    b: int


def _build_wordcount():
    # the acceptance graph: dead column + two-select chain over a groupby
    words = pw.debug.table_from_rows(_W, [("a",), ("b",), ("a",)])
    counts = words.groupby(words.word).reduce(words.word, n=pw.reducers.count())
    mid = counts.select(counts.word, n=counts.n, dead=counts.n * 100 + 1)
    return mid.select(mid.word, out=mid.n + 6)


def _build_join_pushdown():
    # selects feeding a join (projection pushdown), a post-join filter on
    # a left column (filter pushdown), and a fusable second filter
    t = pw.debug.table_from_rows(_E, [("a", 1, 2), ("b", 5, 1)])
    s = pw.debug.table_from_rows(_E, [("a", 3, 4), ("b", 7, 0)])
    lt = t.select(t.k, a=t.a + 0, b=t.b + 0)
    rt = s.select(s.k, a=s.a + 0, b=s.b + 0)
    j = lt.join(rt, lt.k == rt.k).select(
        k=pw.left.k, la=pw.left.a, ra=pw.right.a
    )
    f1 = j.filter(j.la > 2)
    return f1.filter(f1.ra > 0)


def _build_append_only_groupby():
    # inner join of append-only inputs keeps append-only-ness, so the
    # min/max reducers specialize to non-retracting variants
    t = pw.debug.table_from_rows(_E, [("a", 1, 2), ("a", 5, 1)])
    s = pw.debug.table_from_rows(_E, [("a", 3, 4)])
    j = t.join(s, t.k == s.k).select(k=pw.left.k, a=pw.left.a)
    return j.groupby(j.k).reduce(
        j.k,
        lo=pw.reducers.min(j.a),
        hi=pw.reducers.max(j.a),
        n=pw.reducers.count(),
    )


GRAPHS = {
    "wordcount": _build_wordcount,
    "join_pushdown": _build_join_pushdown,
    "append_only_groupby": _build_append_only_groupby,
}


def _plan_text(build) -> str:
    G.clear()
    table = build()
    CaptureNode(G.engine_graph, table._node)
    _exec_graph, plan = optimize_graph(G.engine_graph, 2)
    return plan.format() + "\n"


@pytest.mark.parametrize("name", sorted(GRAPHS))
def test_plan_golden(request, name):
    text = _plan_text(GRAPHS[name])
    golden = PLANS_DIR / f"{name}.txt"
    if request.config.getoption("--regen-plans"):
        PLANS_DIR.mkdir(exist_ok=True)
        golden.write_text(text)
        pytest.skip(f"regenerated {golden.name}")
    assert golden.exists(), (
        f"missing golden plan {golden}; run "
        "`python -m pytest tests/test_plan_golden.py --regen-plans`"
    )
    assert text == golden.read_text(), (
        f"execution plan for {name!r} changed; if intentional, regenerate "
        "with --regen-plans and commit the diff:\n" + text
    )


def test_plan_format_has_rewrites():
    """The committed plans must actually exercise the optimizer — an
    all-'(no rewrites)' set of goldens would test nothing."""
    text = _plan_text(GRAPHS["wordcount"])
    assert "dead_column_elim" in text and "select_fusion" in text
