"""C++ host-runtime extension: key-hash parity with the Python path."""

import datetime

import pytest

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native


@pytest.fixture(scope="module")
def mod():
    m = native.load()
    if m is None:
        pytest.skip("native extension unavailable (no g++?)")
    m.set_pointer_type(K.Pointer)
    return m


CASES = [
    (),
    (None,),
    (True,),
    (False,),
    (0,),
    (1,),
    (-1,),
    (255,),
    (-256,),
    (2**40,),
    (-(2**40),),
    (2**63 - 1,),
    (-(2**63),),
    (3.14,),
    (-0.0,),
    ("hello",),
    ("üñïçødé",),
    (b"bytes",),
    (("a", 1, (2.5, None)),),
    ("mix", 42, 3.3, None, True, ("t", (1,))),
]


def test_hash_parity(mod):
    for case in CASES:
        assert K.Pointer(mod.ref_scalar(*case)) == K._py_ref_scalar(*case), case
    assert K.Pointer(mod.ref_scalar(K.Pointer(12345))) == K._py_ref_scalar(
        K.Pointer(12345)
    )


def test_unsupported_falls_back(mod):
    with pytest.raises(mod.Unsupported):
        mod.ref_scalar(2**200)
    # the public entry point transparently falls back
    assert K.ref_scalar(2**200) == K._py_ref_scalar(2**200)
    dt = datetime.datetime(2021, 5, 1)
    assert K.ref_scalar(dt) == K._py_ref_scalar(dt)


def test_hash_rows_batch(mod):
    rows = [("a", i, float(i)) for i in range(500)]
    assert [K.Pointer(k) for k in mod.hash_rows(rows)] == [
        K._py_ref_scalar(*r) for r in rows
    ]


def test_scan_lines(mod):
    assert mod.scan_lines(b"abc\ndef\r\n\nxy") == [(0, 3), (4, 7), (10, 12)]
    assert mod.scan_lines(b"") == []
    assert mod.scan_lines(b"\n\n") == []
    assert mod.scan_lines(b"no-newline") == [(0, 10)]
