"""C++ host-runtime extension: key-hash parity with the Python path."""

import datetime

import pytest

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native


@pytest.fixture(scope="module")
def mod():
    m = native.load()
    if m is None:
        pytest.skip("native extension unavailable (no g++?)")
    m.set_pointer_type(K.Pointer)
    return m


CASES = [
    (),
    (None,),
    (True,),
    (False,),
    (0,),
    (1,),
    (-1,),
    (255,),
    (-256,),
    (2**40,),
    (-(2**40),),
    (2**63 - 1,),
    (-(2**63),),
    (3.14,),
    (-0.0,),
    ("hello",),
    ("üñïçødé",),
    (b"bytes",),
    (("a", 1, (2.5, None)),),
    ("mix", 42, 3.3, None, True, ("t", (1,))),
]


def test_hash_parity(mod):
    for case in CASES:
        assert K.Pointer(mod.ref_scalar(*case)) == K._py_ref_scalar(*case), case
    assert K.Pointer(mod.ref_scalar(K.Pointer(12345))) == K._py_ref_scalar(
        K.Pointer(12345)
    )


def test_big_ints_hash_natively(mod):
    # 128-bit join/derive key material hashes byte-identically in C
    for v in (2**64, 2**127 - 1, -(2**127), 2**200, -(2**200)):
        assert K.Pointer(mod.ref_scalar(v)) == K._py_ref_scalar(v), v


def test_unsupported_falls_back(mod):
    with pytest.raises(mod.Unsupported):
        mod.ref_scalar(2**600)  # beyond the native big-int window
    # the public entry point transparently falls back
    assert K.ref_scalar(2**600) == K._py_ref_scalar(2**600)
    dt = datetime.datetime(2021, 5, 1)
    assert K.ref_scalar(dt) == K._py_ref_scalar(dt)


def test_hash_rows_batch(mod):
    rows = [("a", i, float(i)) for i in range(500)]
    assert [K.Pointer(k) for k in mod.hash_rows(rows)] == [
        K._py_ref_scalar(*r) for r in rows
    ]


def test_scan_lines(mod):
    assert mod.scan_lines(b"abc\ndef\r\n\nxy") == [(0, 3), (4, 7), (10, 12)]
    assert mod.scan_lines(b"") == []
    assert mod.scan_lines(b"\n\n") == []
    assert mod.scan_lines(b"no-newline") == [(0, 10)]


# ---------------------------------------------------------------------------
# batch-op parity: every native batch function against its Python fallback


def _k(i):
    return K.ref_scalar(i)


def _mixed_batch():
    import numpy as np

    from pathway_tpu.engine.stream import Update

    return [
        Update(_k(1), ("a", 1), 1),
        Update(_k(1), ("a", 1), 1),
        Update(_k(2), ("b", 2.5), 1),
        Update(_k(1), ("a", 1), -2),
        Update(_k(3), ("c", None), -1),
        Update(_k(2), ("b", 2.5), 3),
        Update(_k(4), (np.ones(3), "nd"), 1),  # unhashable cell
        Update(_k(4), (np.ones(3), "nd"), 1),
    ]


def test_consolidate_parity(mod):
    from pathway_tpu.engine import stream

    batch = _mixed_batch()
    got = stream.consolidate(list(batch))
    exp = stream._py_consolidate(list(batch))

    def canon(b):
        return sorted(
            (u.key, stream.hashable_row(u.values), u.diff) for u in b
        )

    assert canon(got) == canon(exp)
    # single-occurrence updates are re-emitted by reference (no realloc)
    single = [u for u in got if u.key == _k(2)]
    assert single and type(single[0]) is stream.Update


def test_per_key_changes_parity(mod):
    from pathway_tpu.engine.stream import Update, per_key_changes

    batch = [
        Update(_k(1), ("a",), 2),
        Update(_k(1), ("b",), -1),
        Update(_k(2), ("c",), 1),
    ]
    out = per_key_changes(batch)
    assert out[_k(1)] == ([("b",)], [("a",), ("a",)])
    assert out[_k(2)] == ([], [("c",)])


def test_coerce_rows_parity(mod):
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io._connector import coerce_row, coerce_rows

    S = sch.schema_from_types(a=int, b=float, c=str, d=bool)
    rows = [
        {"a": "5", "b": 2, "c": 7, "d": "Yes"},
        {"a": 3.0, "b": "1.5", "c": "x", "d": "nope"},
        {"a": None, "b": None},
        {"a": True, "b": "zz", "c": None, "d": 1},
        {"a": 2.5, "b": float("inf"), "c": "", "d": "T"},
    ]
    bulk = coerce_rows(list(rows), S)
    single = [coerce_row(r, S) for r in rows]
    assert bulk == single
    for x, y in zip(bulk, single):
        for xi, yi in zip(x, y):
            assert type(xi) is type(yi), (xi, yi)


def test_filter_batch_parity_and_bool_error(mod):
    import numpy as np

    import pathway_tpu as pw
    from pathway_tpu.internals import api
    from pathway_tpu.engine.stream import Update

    batch = [
        Update(_k(1), (1,), 1),
        Update(_k(2), (0,), 1),
        Update(_k(3), (None,), 1),
        Update(_k(4), (2,), 1),
    ]
    out = mod.filter_batch(batch, lambda k, v: v[0], api.ERROR)
    assert [u.key for u in out] == [_k(1), _k(4)]
    assert out[0] is batch[0]  # passing rows are re-emitted, not rebuilt
    # raising predicate CALL drops the row (python parity)...
    out = mod.filter_batch(batch, lambda k, v: 1 // v[0], api.ERROR)
    assert [u.key for u in out] == [_k(1)]  # 1//1 truthy; 1//0 raises; None//..
    # ...but a raising truthiness test propagates, like bool(ndarray) does
    with pytest.raises(ValueError):
        mod.filter_batch(batch, lambda k, v: np.array([1, 2]), api.ERROR)


def test_rowwise_map_contains_errors(mod):
    from pathway_tpu.internals import api
    from pathway_tpu.engine.stream import Update

    batch = [Update(_k(1), (4,), 1), Update(_k(2), (0,), -1)]
    logged = []
    out = mod.rowwise_map(
        batch, lambda k, v: (8 // v[0],), Update, api.ERROR, logged.append
    )
    assert [(u.values, u.diff) for u in out] == [((2,), 1), ((api.ERROR,), -1)]
    assert len(logged) == 1 and isinstance(logged[0], ZeroDivisionError)


def test_groupby_partials_sum_does_not_alias_ndarray(mod):
    """A one-contribution ndarray sum must copy (python `v * diff` parity),
    not alias the ingested row's buffer."""
    import numpy as np

    import pathway_tpu as pw
    from tests.utils import T

    arr_rows = [("g", np.array([1.0, 2.0]))]
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=object), arr_rows
    )
    red = t.groupby(t.g).reduce(t.g, s=pw.reducers.sum(t.v))
    cap = red._capture_node()
    ctx = pw.run()
    (row,) = ctx.state(cap)["rows"].values()
    assert row[1] is not arr_rows[0][1]
    assert (row[1] == np.array([1.0, 2.0])).all()


def test_multiset_reducer_nets_retraction_before_addition():
    """A retraction preceding an addition of equal args inside one batch
    must net to zero on the per-update Python path exactly as the native
    merge_partial netting does (advisor r3: per-event clamping diverged)."""
    from pathway_tpu.engine.reducers import MaxReducer

    r = MaxReducer()
    # Python per-update path: -1 then +1 of the same args nets to nothing
    acc = r.make_acc()
    r.update(acc, (5,), -1)
    r.update(acc, (5,), 1)
    assert r.extract(acc) is None
    # native-partials path: same batch netted before merge
    acc2 = r.make_acc()
    from pathway_tpu.engine.stream import hashable

    h = hashable((5,))
    r.merge_partial(acc2, {h: (0, (5,))})
    assert r.extract(acc2) is None
    # and a genuinely present value still extracts on both paths
    r.update(acc, (7,), 1)
    assert r.extract(acc) == 7


def test_engine_parity_native_vs_python_subprocess(mod):
    """The same pipeline, native enabled vs PATHWAY_DISABLE_NATIVE=1,
    must print byte-identical results."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_markdown('''\n"
        "grp | v | w\n"
        "a   | 1 | x\n"
        "b   | 2 | y\n"
        "a   | 3 | x\n"
        "b   | 6 | y\n"
        "a   | 5 | q\n"
        "''')\n"
        "red = t.groupby(t.grp).reduce(t.grp, s=pw.reducers.sum(t.v),\n"
        "    mx=pw.reducers.max(t.v), c=pw.reducers.count(),\n"
        "    av=pw.reducers.avg(t.v), am=pw.reducers.argmax(t.v),\n"
        "    u=pw.reducers.unique(t.w))\n"
        "out = red.filter(red.s > 4).select(red.grp, d=red.s * 2)\n"
        "pw.debug.compute_and_print(out, include_id=False)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    a = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    env["PATHWAY_DISABLE_NATIVE"] = "1"
    b = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    assert a.returncode == 0, a.stderr[-2000:]
    assert b.returncode == 0, b.stderr[-2000:]
    assert a.stdout == b.stdout and a.stdout.strip()


# ---------------------------------------------------------------------------
# columnar frames (ISSUE 19): every kernel must be behaviour-identical to
# its row counterpart, and the wire codec must never read past a buffer.
# These run under scripts/sanitize_native.sh (ASan+UBSan) unmodified.


def _frame_rows(n=400, seed=5):
    import random

    from pathway_tpu.engine.stream import Update

    rng = random.Random(seed)
    pool = ["alpha", "beta", "überstr", ""]
    rows = []
    for i in range(n):
        s = (
            rng.choice(pool)
            if rng.random() < 0.6
            else "s%d" % rng.randrange(10**6)
        )
        vals = (
            rng.randrange(-(2**40), 2**40),  # i64
            None if rng.random() < 0.2 else rng.random() * 100 - 50,  # f64?
            s,  # interned-ish str
            None if rng.random() < 0.3 else s + "!",  # fresh str / None
            rng.random() < 0.5,  # bool
        )
        diff = -1 if rng.random() < 0.25 else 1
        rows.append(Update(K.Pointer(K.ref_scalar("r", i)), vals, diff))
    return rows


def test_frame_roundtrip_and_slice(mod):
    rows = _frame_rows()
    cap = mod.frame_from_updates(rows)
    assert mod.frame_len(cap) == len(rows)
    assert mod.frame_ncols(cap) == 5
    assert mod.frame_to_updates(cap) == rows
    head = mod.frame_slice(cap, 0, 123)
    tail = mod.frame_slice(cap, 123, len(rows))
    assert mod.frame_to_updates(head) + mod.frame_to_updates(tail) == rows


def test_frame_route_split_parity(mod):
    rows = _frame_rows()
    cap = mod.frame_from_updates(rows)
    for spec in ((2,), (0, 4), ()):  # str col, (int,bool), key-routed
        frames = mod.frame_route_split(cap, spec, 4)
        lists = mod.route_split(rows, spec, 4)
        assert [mod.frame_to_updates(f) for f in frames] == lists


def test_frame_groupby_partials_parity(mod):
    from pathway_tpu.engine.stream import hashable_row
    from pathway_tpu.internals import api

    rows = _frame_rows()
    cap = mod.frame_from_updates(rows)
    specs = ((0, ()), (1, (0,)))  # count + sum(int col)
    assert mod.frame_groupby_partials(
        cap, (2,), specs, api.ERROR
    ) == mod.groupby_partials(rows, (2,), specs, api.ERROR, hashable_row)


def test_frame_project_filter_parity(mod):
    from pathway_tpu.engine.stream import Update

    rows = _frame_rows()
    cap = mod.frame_from_updates(rows)
    pr = mod.frame_project(cap, (2, 0, 4))
    assert mod.frame_to_updates(pr) == [
        Update(u.key, (u.values[2], u.values[0], u.values[4]), u.diff)
        for u in rows
    ]
    # col0 > 0 — numeric with full validity
    fl = mod.frame_filter(cap, 0, 4, 0)
    assert mod.frame_to_updates(fl) == [
        u for u in rows if u.values[0] > 0
    ]
    # col3 != const — Optional[str]: None != const keeps the row (Python
    # semantics), None == / ordered comparisons drop it
    fl2 = mod.frame_filter(cap, 3, 1, "alpha!")
    assert mod.frame_to_updates(fl2) == [
        u for u in rows if u.values[3] != "alpha!"
    ]
    # cross-type pairing (int col, float const) must refuse, not guess
    with pytest.raises(mod.Unsupported):
        mod.frame_filter(cap, 0, 4, 0.5)


def test_frame_pack_pool_roundtrip(mod):
    rows = _frame_rows()
    cap = mod.frame_from_updates(rows)
    # one tx/rx pool pair per transmission, frames encoded and decoded
    # in the same order: pool refs resolve purely by insert index
    tx = mod.frame_txpool_new()
    a = mod.frame_pack(mod.frame_slice(cap, 0, 200), tx)
    b = mod.frame_pack(mod.frame_slice(cap, 200, 400), tx)
    hits, misses = mod.frame_txpool_stats(tx)
    assert hits > 0 and misses > 0  # shared strings dedup across frames
    rx = mod.frame_rxpool_new()
    out = mod.frame_to_updates(mod.frame_unpack(a, rx)) + mod.frame_to_updates(
        mod.frame_unpack(b, rx)
    )
    assert out == rows
    # poolless blob stays self-contained
    blob = mod.frame_pack(cap, None)
    assert mod.frame_to_updates(mod.frame_unpack(blob, None)) == rows


def test_frame_unpack_truncation_fuzz(mod):
    """Intentionally-truncated frames: every cut must raise ValueError —
    never crash, never read past the buffer (the sanitize_native.sh
    ASan job is the real referee here)."""
    rows = _frame_rows(n=150)
    blob = mod.frame_pack(mod.frame_from_updates(rows), None)
    for cut in range(len(blob)):
        with pytest.raises(ValueError):
            mod.frame_unpack(blob[:cut], None)
    # corrupt magic/version bytes must be rejected up front
    for i, b in ((0, 0x00), (1, 0xFF)):
        bad = bytearray(blob)
        bad[i] = b
        with pytest.raises(ValueError):
            mod.frame_unpack(bytes(bad), None)


def test_frame_from_updates_unsupported(mod):
    from pathway_tpu.engine.stream import Update

    # nested tuples are outside the typed column set: the whole batch
    # stays on the row path
    rows = [Update(K.Pointer(K.ref_scalar("r", 0)), (("a", 1),), 1)]
    with pytest.raises(mod.Unsupported):
        mod.frame_from_updates(rows)
