"""C++ host-runtime extension: key-hash parity with the Python path."""

import datetime

import pytest

from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native


@pytest.fixture(scope="module")
def mod():
    m = native.load()
    if m is None:
        pytest.skip("native extension unavailable (no g++?)")
    m.set_pointer_type(K.Pointer)
    return m


CASES = [
    (),
    (None,),
    (True,),
    (False,),
    (0,),
    (1,),
    (-1,),
    (255,),
    (-256,),
    (2**40,),
    (-(2**40),),
    (2**63 - 1,),
    (-(2**63),),
    (3.14,),
    (-0.0,),
    ("hello",),
    ("üñïçødé",),
    (b"bytes",),
    (("a", 1, (2.5, None)),),
    ("mix", 42, 3.3, None, True, ("t", (1,))),
]


def test_hash_parity(mod):
    for case in CASES:
        assert K.Pointer(mod.ref_scalar(*case)) == K._py_ref_scalar(*case), case
    assert K.Pointer(mod.ref_scalar(K.Pointer(12345))) == K._py_ref_scalar(
        K.Pointer(12345)
    )


def test_big_ints_hash_natively(mod):
    # 128-bit join/derive key material hashes byte-identically in C
    for v in (2**64, 2**127 - 1, -(2**127), 2**200, -(2**200)):
        assert K.Pointer(mod.ref_scalar(v)) == K._py_ref_scalar(v), v


def test_unsupported_falls_back(mod):
    with pytest.raises(mod.Unsupported):
        mod.ref_scalar(2**600)  # beyond the native big-int window
    # the public entry point transparently falls back
    assert K.ref_scalar(2**600) == K._py_ref_scalar(2**600)
    dt = datetime.datetime(2021, 5, 1)
    assert K.ref_scalar(dt) == K._py_ref_scalar(dt)


def test_hash_rows_batch(mod):
    rows = [("a", i, float(i)) for i in range(500)]
    assert [K.Pointer(k) for k in mod.hash_rows(rows)] == [
        K._py_ref_scalar(*r) for r in rows
    ]


def test_scan_lines(mod):
    assert mod.scan_lines(b"abc\ndef\r\n\nxy") == [(0, 3), (4, 7), (10, 12)]
    assert mod.scan_lines(b"") == []
    assert mod.scan_lines(b"\n\n") == []
    assert mod.scan_lines(b"no-newline") == [(0, 10)]


# ---------------------------------------------------------------------------
# batch-op parity: every native batch function against its Python fallback


def _k(i):
    return K.ref_scalar(i)


def _mixed_batch():
    import numpy as np

    from pathway_tpu.engine.stream import Update

    return [
        Update(_k(1), ("a", 1), 1),
        Update(_k(1), ("a", 1), 1),
        Update(_k(2), ("b", 2.5), 1),
        Update(_k(1), ("a", 1), -2),
        Update(_k(3), ("c", None), -1),
        Update(_k(2), ("b", 2.5), 3),
        Update(_k(4), (np.ones(3), "nd"), 1),  # unhashable cell
        Update(_k(4), (np.ones(3), "nd"), 1),
    ]


def test_consolidate_parity(mod):
    from pathway_tpu.engine import stream

    batch = _mixed_batch()
    got = stream.consolidate(list(batch))
    exp = stream._py_consolidate(list(batch))

    def canon(b):
        return sorted(
            (u.key, stream.hashable_row(u.values), u.diff) for u in b
        )

    assert canon(got) == canon(exp)
    # single-occurrence updates are re-emitted by reference (no realloc)
    single = [u for u in got if u.key == _k(2)]
    assert single and type(single[0]) is stream.Update


def test_per_key_changes_parity(mod):
    from pathway_tpu.engine.stream import Update, per_key_changes

    batch = [
        Update(_k(1), ("a",), 2),
        Update(_k(1), ("b",), -1),
        Update(_k(2), ("c",), 1),
    ]
    out = per_key_changes(batch)
    assert out[_k(1)] == ([("b",)], [("a",), ("a",)])
    assert out[_k(2)] == ([], [("c",)])


def test_coerce_rows_parity(mod):
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io._connector import coerce_row, coerce_rows

    S = sch.schema_from_types(a=int, b=float, c=str, d=bool)
    rows = [
        {"a": "5", "b": 2, "c": 7, "d": "Yes"},
        {"a": 3.0, "b": "1.5", "c": "x", "d": "nope"},
        {"a": None, "b": None},
        {"a": True, "b": "zz", "c": None, "d": 1},
        {"a": 2.5, "b": float("inf"), "c": "", "d": "T"},
    ]
    bulk = coerce_rows(list(rows), S)
    single = [coerce_row(r, S) for r in rows]
    assert bulk == single
    for x, y in zip(bulk, single):
        for xi, yi in zip(x, y):
            assert type(xi) is type(yi), (xi, yi)


def test_filter_batch_parity_and_bool_error(mod):
    import numpy as np

    import pathway_tpu as pw
    from pathway_tpu.internals import api
    from pathway_tpu.engine.stream import Update

    batch = [
        Update(_k(1), (1,), 1),
        Update(_k(2), (0,), 1),
        Update(_k(3), (None,), 1),
        Update(_k(4), (2,), 1),
    ]
    out = mod.filter_batch(batch, lambda k, v: v[0], api.ERROR)
    assert [u.key for u in out] == [_k(1), _k(4)]
    assert out[0] is batch[0]  # passing rows are re-emitted, not rebuilt
    # raising predicate CALL drops the row (python parity)...
    out = mod.filter_batch(batch, lambda k, v: 1 // v[0], api.ERROR)
    assert [u.key for u in out] == [_k(1)]  # 1//1 truthy; 1//0 raises; None//..
    # ...but a raising truthiness test propagates, like bool(ndarray) does
    with pytest.raises(ValueError):
        mod.filter_batch(batch, lambda k, v: np.array([1, 2]), api.ERROR)


def test_rowwise_map_contains_errors(mod):
    from pathway_tpu.internals import api
    from pathway_tpu.engine.stream import Update

    batch = [Update(_k(1), (4,), 1), Update(_k(2), (0,), -1)]
    logged = []
    out = mod.rowwise_map(
        batch, lambda k, v: (8 // v[0],), Update, api.ERROR, logged.append
    )
    assert [(u.values, u.diff) for u in out] == [((2,), 1), ((api.ERROR,), -1)]
    assert len(logged) == 1 and isinstance(logged[0], ZeroDivisionError)


def test_groupby_partials_sum_does_not_alias_ndarray(mod):
    """A one-contribution ndarray sum must copy (python `v * diff` parity),
    not alias the ingested row's buffer."""
    import numpy as np

    import pathway_tpu as pw
    from tests.utils import T

    arr_rows = [("g", np.array([1.0, 2.0]))]
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=object), arr_rows
    )
    red = t.groupby(t.g).reduce(t.g, s=pw.reducers.sum(t.v))
    cap = red._capture_node()
    ctx = pw.run()
    (row,) = ctx.state(cap)["rows"].values()
    assert row[1] is not arr_rows[0][1]
    assert (row[1] == np.array([1.0, 2.0])).all()


def test_multiset_reducer_nets_retraction_before_addition():
    """A retraction preceding an addition of equal args inside one batch
    must net to zero on the per-update Python path exactly as the native
    merge_partial netting does (advisor r3: per-event clamping diverged)."""
    from pathway_tpu.engine.reducers import MaxReducer

    r = MaxReducer()
    # Python per-update path: -1 then +1 of the same args nets to nothing
    acc = r.make_acc()
    r.update(acc, (5,), -1)
    r.update(acc, (5,), 1)
    assert r.extract(acc) is None
    # native-partials path: same batch netted before merge
    acc2 = r.make_acc()
    from pathway_tpu.engine.stream import hashable

    h = hashable((5,))
    r.merge_partial(acc2, {h: (0, (5,))})
    assert r.extract(acc2) is None
    # and a genuinely present value still extracts on both paths
    r.update(acc, (7,), 1)
    assert r.extract(acc) == 7


def test_engine_parity_native_vs_python_subprocess(mod):
    """The same pipeline, native enabled vs PATHWAY_DISABLE_NATIVE=1,
    must print byte-identical results."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_markdown('''\n"
        "grp | v | w\n"
        "a   | 1 | x\n"
        "b   | 2 | y\n"
        "a   | 3 | x\n"
        "b   | 6 | y\n"
        "a   | 5 | q\n"
        "''')\n"
        "red = t.groupby(t.grp).reduce(t.grp, s=pw.reducers.sum(t.v),\n"
        "    mx=pw.reducers.max(t.v), c=pw.reducers.count(),\n"
        "    av=pw.reducers.avg(t.v), am=pw.reducers.argmax(t.v),\n"
        "    u=pw.reducers.unique(t.w))\n"
        "out = red.filter(red.s > 4).select(red.grp, d=red.s * 2)\n"
        "pw.debug.compute_and_print(out, include_id=False)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    a = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    env["PATHWAY_DISABLE_NATIVE"] = "1"
    b = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    assert a.returncode == 0, a.stderr[-2000:]
    assert b.returncode == 0, b.stderr[-2000:]
    assert a.stdout == b.stdout and a.stdout.strip()
