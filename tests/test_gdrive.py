"""pw.io.gdrive against an injectable fake Drive v3 service.

Reference behavior under test: ``python/pathway/io/gdrive/__init__.py``
— paginated listing (``_query``, :85), recursive folder walk (``_ls``,
:108), glob/size filters (:131/:148), Google-native doc export
(``_prepare_download_request``, :196), and the streaming tree diff
(adds/updates by ``modifiedTime``, deletes; ``_GDriveTree``, :237-259).
"""

from __future__ import annotations

import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.io.gdrive import (
    DEFAULT_MIME_TYPE_MAPPING,
    MIME_TYPE_FOLDER,
    _GDriveClient,
    _GDriveTree,
)

DOC_MIME = "application/vnd.google-apps.document"


class _FakeRequest:
    def __init__(self, payload: bytes):
        self._payload = payload

    def execute(self) -> bytes:
        return self._payload


class _FakeListCall:
    def __init__(self, pages: list[dict]):
        self._pages = pages
        self._i = 0

    def execute(self) -> dict:
        page = self._pages[self._i]
        self._i += 1
        return page


class _FakeFiles:
    """files() surface: list/get/get_media/export_media."""

    def __init__(self, drive: "_FakeDrive"):
        self._drive = drive

    def list(self, *, q="", pageSize=10, pageToken=None, **_kw):
        # parse "'<id>' in parents and trashed=false" the way the
        # connector builds it
        parent = q.split("'")[1] if "'" in q else None
        children = [
            dict(f)
            for f in self._drive.files.values()
            if parent in f.get("parents", []) and not f.get("trashed")
        ]
        self._drive.list_calls += 1
        # honor pagination: serve pageSize items per page with tokens
        start = int(pageToken) if pageToken else 0
        page = children[start : start + pageSize]
        resp: dict = {"files": page}
        if start + pageSize < len(children):
            resp["nextPageToken"] = str(start + pageSize)
        self._drive.pages_served += 1
        return _FakeListCall([resp])

    def get(self, *, fileId, **_kw):
        f = self._drive.files.get(fileId)
        if f is None:
            raise ConnectionError(f"404: {fileId}")
        return _FakeListCall([dict(f)])

    def get_media(self, *, fileId):
        self._drive.media_calls.append(("get", fileId))
        return _FakeRequest(self._drive.payloads[fileId])

    def export_media(self, *, fileId, mimeType):
        self._drive.media_calls.append(("export", fileId, mimeType))
        return _FakeRequest(self._drive.payloads[fileId])


class _FakeDrive:
    """In-memory Drive: mutate ``files``/``payloads`` between polls."""

    def __init__(self):
        self.files: dict[str, dict] = {}
        self.payloads: dict[str, bytes] = {}
        self.list_calls = 0
        self.pages_served = 0
        self.media_calls: list = []
        self._lock = threading.Lock()

    def files_api(self):
        return _FakeFiles(self)

    # the connector calls service.files()
    def __getattr__(self, name):
        raise AttributeError(name)

    def put(self, id, name, payload=b"", mime="text/plain", parents=("root",),
            modified="2024-01-01T00:00:00Z", size=None):
        f = {
            "id": id,
            "name": name,
            "mimeType": mime,
            "parents": list(parents),
            "modifiedTime": modified,
            "trashed": False,
        }
        if size is None and mime not in DEFAULT_MIME_TYPE_MAPPING and mime != MIME_TYPE_FOLDER:
            size = len(payload)
        if size is not None:
            f["size"] = str(size)
        self.files[id] = f
        self.payloads[id] = payload
        return f


class _Service:
    def __init__(self, drive: _FakeDrive):
        self._drive = drive

    def files(self):
        return self._drive.files_api()


def _drive_with_tree() -> _FakeDrive:
    d = _FakeDrive()
    d.put("root", "root", mime=MIME_TYPE_FOLDER, parents=())
    d.put("f1", "a.txt", b"alpha", parents=("root",))
    d.put("f2", "b.pdf", b"%PDF beta", parents=("root",))
    d.put("sub", "subdir", mime=MIME_TYPE_FOLDER, parents=("root",))
    d.put("f3", "c.txt", b"gamma", parents=("sub",))
    d.put("doc1", "report", b"DOCX-EXPORT", mime=DOC_MIME, parents=("sub",))
    return d


def test_client_recursive_listing_and_export():
    d = _drive_with_tree()
    client = _GDriveClient(_Service(d), injected=True)
    tree = client.tree("root")
    assert set(tree.files) == {"f1", "f2", "f3", "doc1"}
    meta = tree.files["f1"]
    assert meta["url"].endswith("/f1/")
    assert meta["path"] == "a.txt"
    assert meta["status"] == "downloaded"
    # regular file downloads via get_media; Google-native doc exports
    assert client.download(tree.files["f2"]) == b"%PDF beta"
    assert client.download(tree.files["doc1"]) == b"DOCX-EXPORT"
    kinds = {c[0] for c in d.media_calls}
    assert kinds == {"get", "export"}
    export_call = next(c for c in d.media_calls if c[0] == "export")
    assert export_call[2] == DEFAULT_MIME_TYPE_MAPPING[DOC_MIME]


def test_client_pagination():
    d = _FakeDrive()
    d.put("root", "root", mime=MIME_TYPE_FOLDER, parents=())
    for i in range(25):  # pageSize=10 -> 3 pages
        d.put(f"f{i}", f"file{i:02d}.txt", b"x", parents=("root",))
    client = _GDriveClient(_Service(d), injected=True)
    tree = client.tree("root")
    assert len(tree.files) == 25
    assert d.pages_served >= 3


def test_client_filters():
    d = _drive_with_tree()
    only_txt = _GDriveClient(_Service(d), file_name_pattern="*.txt", injected=True)
    assert set(only_txt.tree("root").files) == {"f1", "f3"}
    multi = _GDriveClient(_Service(d), file_name_pattern=["*.pdf", "a.*"], injected=True)
    assert set(multi.tree("root").files) == {"f1", "f2"}
    # size limit: oversized files drop from the listing (reference
    # _filter_by_size); Google-native docs (no size) always pass
    d.put("big", "big.bin", b"z" * 100, parents=("root",))
    small = _GDriveClient(_Service(d), object_size_limit=10, injected=True)
    ids = set(small.tree("root").files)
    assert "big" not in ids and "doc1" in ids


def test_client_missing_root_and_single_file():
    d = _drive_with_tree()
    client = _GDriveClient(_Service(d), injected=True)
    assert client.tree("nope").files == {}
    # a file id as root lists exactly that file
    assert set(client.tree("f1").files) == {"f1"}


def test_tree_diff_semantics():
    a = _GDriveTree({
        "x": {"id": "x", "modifiedTime": "2024-01-01T00:00:00Z"},
        "y": {"id": "y", "modifiedTime": "2024-01-01T00:00:00Z"},
    })
    b = _GDriveTree({
        "y": {"id": "y", "modifiedTime": "2024-02-01T00:00:00Z"},  # changed
        "z": {"id": "z", "modifiedTime": "2024-01-01T00:00:00Z"},  # new
    })
    assert {f["id"] for f in b.new_and_changed_files(a)} == {"y", "z"}
    assert {f["id"] for f in b.removed_files(a)} == {"x"}


def test_static_read_end_to_end():
    d = _drive_with_tree()
    pw.G.clear()
    t = pw.io.gdrive.read(
        "root", mode="static", service=_Service(d), with_metadata=True
    )
    rows = []
    pw.io.subscribe(
        t, on_change=lambda k, row, time, add: rows.append(row)
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    payloads = sorted(r["data"] for r in rows)
    assert payloads == sorted([b"alpha", b"%PDF beta", b"gamma", b"DOCX-EXPORT"])
    names = {r["_metadata"]["name"] for r in rows}
    assert names == {"a.txt", "b.pdf", "c.txt", "report"}


def test_streaming_add_update_delete():
    d = _drive_with_tree()
    pw.G.clear()
    t = pw.io.gdrive.read(
        "root",
        mode="streaming",
        service=_Service(d),
        refresh_interval=0.05,
        with_metadata=True,
    )
    events: list[tuple[bool, str, bytes]] = []

    def on_change(key, row, time_, is_add):
        events.append((is_add, row["_metadata"]["name"], row["data"]))

    pw.io.subscribe(t, on_change=on_change)

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()

    def wait_for(pred, timeout=8.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return False

    assert wait_for(lambda: len([e for e in events if e[0]]) >= 4)
    # ADD a new file
    d.put("f9", "new.txt", b"fresh", parents=("root",),
          modified="2024-03-01T00:00:00Z")
    assert wait_for(lambda: any(e == (True, "new.txt", b"fresh") for e in events))
    # UPDATE an existing file: bump modifiedTime -> re-download + upsert
    d.put("f1", "a.txt", b"alpha-v2", parents=("root",),
          modified="2024-04-01T00:00:00Z")
    assert wait_for(lambda: any(e == (True, "a.txt", b"alpha-v2") for e in events))
    # upsert retracts the old version rather than duplicating
    assert wait_for(lambda: any(not e[0] and e[1] == "a.txt" for e in events))
    # DELETE a file -> retraction
    del d.files["f2"]
    del d.payloads["f2"]
    assert wait_for(lambda: any(not e[0] and e[1] == "b.pdf" for e in events))
    sched.stop()
    run_t.join(timeout=3)


def test_read_requires_credentials_or_service():
    pw.G.clear()
    with pytest.raises(ValueError, match="service"):
        pw.io.gdrive.read("root", mode="static")
    with pytest.raises(ValueError, match="mode"):
        pw.io.gdrive.read("root", mode="bogus", service=object())


def test_streaming_retries_failed_downloads():
    """A transient download failure must not mark the file as synced
    (it would otherwise never retry until the next Drive-side edit)."""
    d = _FakeDrive()
    d.put("root", "root", mime=MIME_TYPE_FOLDER, parents=())
    d.put("f1", "a.txt", b"alpha", parents=("root",))
    svc = _Service(d)

    flaky = {"fails_left": 2}
    real_files_api = d.files_api

    class _FlakyFiles(_FakeFiles):
        def get_media(self, *, fileId):
            if flaky["fails_left"] > 0:
                flaky["fails_left"] -= 1
                raise ConnectionError("transient")
            return super().get_media(fileId=fileId)

    d.files_api = lambda: _FlakyFiles(d)

    pw.G.clear()
    t = pw.io.gdrive.read(
        "root", mode="streaming", service=svc, refresh_interval=0.05
    )
    got = []
    pw.io.subscribe(t, on_change=lambda k, row, tm, add: got.append(row["data"]))

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()
    deadline = time.monotonic() + 8
    while b"alpha" not in got and time.monotonic() < deadline:
        time.sleep(0.02)
    sched.stop()
    run_t.join(timeout=3)
    assert b"alpha" in got  # delivered after the transient failures
    assert flaky["fails_left"] == 0
