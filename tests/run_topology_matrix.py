#!/usr/bin/env python
"""Topology test matrix (reference pattern ``tests/utils.py:37-50``:
the suite runs under multiple worker topologies, not just the default).

Runs the full test suite under PATHWAY_THREADS={1,2,4}.  The 2-process
TCP-cluster topology is exercised by tests/test_multiworker.py's
subprocess tests inside every pass (they spawn their own clusters via
the PATHWAY_PROCESSES env contract).

Usage:  python tests/run_topology_matrix.py [extra pytest args]
Exit code 0 iff every topology passes.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

TOPOLOGIES = [
    {"PATHWAY_THREADS": "1"},
    {"PATHWAY_THREADS": "2"},
    {"PATHWAY_THREADS": "4"},
]


def main() -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    extra = sys.argv[1:]
    results: list[tuple[str, int, float]] = []
    for topo in TOPOLOGIES:
        env = dict(os.environ, **topo)
        label = ",".join(f"{k.split('_')[-1].lower()}={v}" for k, v in topo.items())
        print(f"\n=== topology [{label}] ===", flush=True)
        t0 = time.monotonic()
        rc = subprocess.call(
            [sys.executable, "-m", "pytest", "tests/", "-q", *extra],
            cwd=repo,
            env=env,
        )
        results.append((label, rc, time.monotonic() - t0))
    print("\n=== topology matrix summary ===")
    for label, rc, dt in results:
        print(f"  [{label}] {'PASS' if rc == 0 else f'FAIL rc={rc}'} ({dt:.0f}s)")
    return 0 if all(rc == 0 for _, rc, _ in results) else 1


if __name__ == "__main__":
    sys.exit(main())
