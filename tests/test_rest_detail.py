"""REST ingress details: concurrent requests, schema defaults through
HTTP, OpenAPI docs endpoint, 404s, and serve_callable under concurrent
load (reference ``io/http`` webserver + ``servers.py``).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest

import pathway_tpu as pw


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url, payload, timeout=10.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _start_scheduler():
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()
    return sched, run_t


def _wait_server(base, route, payload, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            return _post(base + route, payload, timeout=5.0)
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(f"server at {base}{route} did not come up")


def test_rest_connector_concurrent_queries_and_docs():
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    pw.G.clear()
    port = _free_port()
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class S(pw.Schema):
        x: int
        y: int = pw.column_definition(default_value=10)

    queries, writer = rest_connector(webserver=ws, route="/add", schema=S)
    writer(queries.select(result=queries.x + queries.y))
    sched, run_t = _start_scheduler()
    try:
        base = f"http://127.0.0.1:{port}"
        first = _wait_server(base, "/add", {"x": 1})
        # schema default applies when y is omitted; the response body IS
        # the result column's value
        assert first == 11
        # concurrent posts all answer correctly
        with ThreadPoolExecutor(max_workers=8) as pool:
            futs = [
                pool.submit(_post, base + "/add", {"x": i, "y": i * 2})
                for i in range(16)
            ]
            results = [f.result() for f in futs]
        assert sorted(results) == sorted(3 * i for i in range(16))
        # OpenAPI description served
        docs = json.loads(
            urllib.request.urlopen(f"{base}/_schema", timeout=5).read()
        )
        assert isinstance(docs, dict)
        # unknown route -> 404
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base + "/no-such-route", {})
        assert e.value.code == 404
    finally:
        sched.stop()
        run_t.join(timeout=3)


def test_serve_callable_concurrent_and_error_path():
    from pathway_tpu.xpacks.llm.servers import BaseRestServer

    pw.G.clear()
    port = _free_port()
    server = BaseRestServer(host="127.0.0.1", port=port)

    class S(pw.Schema):
        text: str

    def transform(text: str) -> str:
        if text == "boom":
            raise ValueError("handler failure")
        return text[::-1]

    server.serve_callable("/v1/reverse", S, transform)
    sched, run_t = _start_scheduler()
    try:
        base = f"http://127.0.0.1:{port}"
        first = _wait_server(base, "/v1/reverse", {"text": "abc"})
        assert first == "cba"
        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [
                pool.submit(
                    _post, base + "/v1/reverse", {"text": f"word{i}"}
                )
                for i in range(8)
            ]
            out = [f.result() for f in futs]
        assert sorted(out) == sorted(f"word{i}"[::-1] for i in range(8))
        # a raising handler must not kill the server; subsequent
        # requests still answer
        try:
            _post(base + "/v1/reverse", {"text": "boom"})
        except (urllib.error.URLError, OSError):
            pass  # error response or timeout both acceptable
        again = _post(base + "/v1/reverse", {"text": "xyz"})
        assert again == "zyx"
    finally:
        sched.stop()
        run_t.join(timeout=3)
