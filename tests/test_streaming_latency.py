"""Event-driven streaming runtime: wakeup-driven epoch cuts and the
per-stage latency probe.

The scheduler no longer polls on a fixed interval — input threads wake
it on enqueue, so a lone message in an otherwise idle graph must reach
the sink in a small multiple of the settle window, NOT after the
autocommit interval.  The per-stage latency histograms
(ingest/cut/process/exchange/sink/e2e) are exposed through the
monitoring server; REALTIME_REPLAY gap sleeps honour a speed factor.
"""

from __future__ import annotations

import json
import socket
import time as _t
import urllib.request

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G


class WordSchema(pw.Schema):
    word: str


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_single_message_reaches_sink_well_before_autocommit():
    """A single message injected into an idle streaming graph must land
    at the sink orders of magnitude sooner than the autocommit bound —
    the enqueue wakes the scheduler, which cuts as soon as the queue
    settles (a timer-polled runtime would hold it for ~autocommit)."""
    pw.G.clear()
    marks: dict[str, float] = {}

    class OneShot(pw.io.python.ConnectorSubject):
        def run(self):
            # let the scheduler reach its idle wait first
            _t.sleep(0.1)
            marks["sent"] = _t.monotonic()
            self.next(word="ping")
            self.commit()
            # keep the source open: the quick delivery below cannot be
            # explained by the source-done flush
            _t.sleep(1.0)

    t = pw.io.python.read(OneShot(), schema=WordSchema)
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_add: marks.setdefault(
            "arrived", _t.monotonic()
        ),
    )
    pw.run(autocommit_duration_ms=2000, monitoring_level="none")
    assert "sent" in marks and "arrived" in marks
    delivery_s = marks["arrived"] - marks["sent"]
    # autocommit is 2 s; wakeup-driven cuts deliver in well under half a
    # second even on a loaded CI core
    assert delivery_s < 0.5, f"idle-graph delivery took {delivery_s:.3f}s"


def test_stage_latency_histograms_queryable_from_monitoring_server():
    """The per-stage p50/p95/p99 histograms surface in both /metrics
    (prometheus text) and /status (json) of the monitoring server."""
    from pathway_tpu.internals.monitoring_server import start_http_server

    pw.G.clear()

    class Burst(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(20):
                self.next(word=f"w{i % 3}")
                if i % 5 == 4:
                    self.commit()
                    _t.sleep(0.01)

    t = pw.io.python.read(Burst(), schema=WordSchema)
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    counts._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    port = _free_port()
    try:
        start_http_server(sched, port=port)
        sched.run()
        body = (
            urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5)
            .read()
            .decode()
        )
        assert 'pathway_tpu_stage_latency_ms{stage="ingest",quantile="p99"}' in body
        assert 'pathway_tpu_stage_latency_ms{stage="e2e",quantile="p50"}' in body
        assert 'pathway_tpu_stage_latency_count{stage="sink"}' in body
        status = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=5
            ).read()
        )
        lat = status["latency"]
        for stage in ("ingest", "cut", "process", "sink", "e2e"):
            assert lat[stage]["count"] > 0
            assert lat[stage]["p50_ms"] <= lat[stage]["p99_ms"] <= lat[stage]["max_ms"]
    finally:
        server = getattr(sched, "_monitoring_server", None)
        if server is not None:
            server.shutdown()
            server.server_close()


def test_latency_probe_quantiles_order():
    """Unit-level: recorded samples produce ordered, ~12%-accurate
    quantiles in both the native and pure-python histogram paths."""
    from pathway_tpu.internals.monitoring import LatencyProbe

    probe = LatencyProbe()
    for ns in (1_000_000, 2_000_000, 4_000_000, 100_000_000):
        for _ in range(25):
            probe.record("e2e", ns)
    snap = probe.snapshot()["e2e"]
    assert snap["count"] == 100
    assert snap["p50_ms"] <= snap["p95_ms"] <= snap["p99_ms"] <= snap["max_ms"]
    # p50 lands in the 2 ms bucket (within the ~12% bucket resolution)
    assert 1.5 <= snap["p50_ms"] <= 2.5
    assert 85.0 <= snap["max_ms"] <= 115.0


def test_realtime_replay_speed_factor(tmp_path):
    """``replay_speedup`` divides recorded inter-commit gaps before the
    REALTIME_REPLAY sleep: a 0.4 s recorded gap collapses to ~10 ms at
    40x, while the replayed rows stay identical."""
    from pathway_tpu.persistence import (
        Backend,
        Config,
        PersistenceMode,
        attach_persistence,
    )

    class SlowSource(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(word="x")
            self.commit()
            _t.sleep(0.4)
            self.next(word="y")
            self.commit()

    def build():
        G.clear()
        t = pw.io.python.read(SlowSource(), schema=WordSchema)
        counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
        return counts._capture_node()

    build()
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched, Config.simple_config(Backend.filesystem(tmp_path / "snap"))
    )
    sched.run()

    def replay(**cfg_kwargs):
        cap = build()
        sched = Scheduler(G.engine_graph, autocommit_ms=10)
        attach_persistence(
            sched,
            Config.simple_config(
                Backend.filesystem(tmp_path / "snap"),
                persistence_mode=PersistenceMode.REALTIME_REPLAY,
                **cfg_kwargs,
            ),
        )
        t0 = _t.monotonic()
        ctx = sched.run()
        return _t.monotonic() - t0, ctx.state(cap)["rows"]

    slow_dt, slow_rows = replay()
    fast_dt, fast_rows = replay(replay_speedup=40.0)
    assert sorted(slow_rows.values()) == sorted(fast_rows.values())
    assert sorted(fast_rows.values()) == [("x", 1), ("y", 1)]
    assert slow_dt >= 0.3  # the recorded gap is honoured at 1x...
    assert fast_dt < slow_dt - 0.25  # ...and collapses at 40x


def test_replay_speedup_env_override(tmp_path, monkeypatch):
    """PATHWAY_REPLAY_SPEEDUP overrides the Config knob without a code
    change — the operator's escape hatch for a slow recorded log."""
    from pathway_tpu.persistence import (
        Backend,
        Config,
        PersistenceMode,
        attach_persistence,
    )

    class SlowSource(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(word="x")
            self.commit()
            _t.sleep(0.4)
            self.next(word="y")
            self.commit()

    def build():
        G.clear()
        t = pw.io.python.read(SlowSource(), schema=WordSchema)
        counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
        return counts._capture_node()

    build()
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched, Config.simple_config(Backend.filesystem(tmp_path / "snap"))
    )
    sched.run()

    monkeypatch.setenv("PATHWAY_REPLAY_SPEEDUP", "100")
    cap = build()
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched,
        Config.simple_config(
            Backend.filesystem(tmp_path / "snap"),
            persistence_mode=PersistenceMode.REALTIME_REPLAY,
        ),
    )
    t0 = _t.monotonic()
    ctx = sched.run()
    dt = _t.monotonic() - t0
    assert sorted(ctx.state(cap)["rows"].values()) == [("x", 1), ("y", 1)]
    assert dt < 0.3, f"env speedup ignored: replay took {dt:.3f}s"
