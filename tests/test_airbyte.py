"""Airbyte connector: protocol driver, incremental state machinery, and
full-refresh diffing — tested against a local fake connector speaking the
Airbyte protocol (no Docker needed; reference ``io/airbyte`` +
``third_party/airbyte_serverless``)."""

from __future__ import annotations

import json
import sys
import textwrap

import pytest

import pathway_tpu as pw
from pathway_tpu.io.airbyte import (
    AirbyteStateTracker,
    ExecutableAirbyteSource,
)
from tests.utils import run_to_rows

#: a minimal Airbyte-protocol source: `discover` emits a catalog for an
#: incremental "events" stream; `read` emits RECORDs for database rows
#: past the state cursor, then a STREAM-type STATE with the new cursor
_FAKE_CONNECTOR = textwrap.dedent(
    """
    import json, sys

    def emit(obj):
        print(json.dumps(obj), flush=True)

    args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
    cmd = sys.argv[1]
    args = {}
    rest = sys.argv[2:]
    for i in range(0, len(rest) - 1, 2):
        args[rest[i]] = rest[i + 1]

    config = json.load(open(args["--config"])) if "--config" in args else {}
    db_path = config["db"]

    if cmd == "discover":
        emit({
            "type": "CATALOG",
            "catalog": {
                "streams": [
                    {
                        "name": "events",
                        "json_schema": {},
                        "supported_sync_modes": ["full_refresh", "incremental"],
                    },
                    {
                        "name": "snapshots",
                        "json_schema": {},
                        "supported_sync_modes": ["full_refresh"],
                    },
                ]
            },
        })
        sys.exit(0)

    assert cmd == "read", cmd
    catalog = json.load(open(args["--catalog"]))
    stream = catalog["streams"][0]["stream"]["name"]
    sync_mode = catalog["streams"][0]["sync_mode"]
    cursor = 0
    if "--state" in args:
        state = json.load(open(args["--state"]))
        if state and state.get("type") == "GLOBAL":
            for s in state["global"]["stream_states"]:
                if s["stream_descriptor"]["name"] == stream:
                    cursor = s["stream_state"].get("cursor", 0)

    rows = json.load(open(db_path))
    emit({"type": "LOG", "log": {"level": "INFO", "message": "reading"}})
    out = [r for r in rows if sync_mode != "incremental" or r["id"] > cursor]
    for r in out:
        emit({
            "type": "RECORD",
            "record": {"stream": stream, "data": r, "emitted_at": 0},
        })
    if sync_mode == "incremental":
        new_cursor = max([r["id"] for r in rows], default=cursor)
        emit({
            "type": "STATE",
            "state": {
                "type": "STREAM",
                "stream": {
                    "stream_descriptor": {"name": stream},
                    "stream_state": {"cursor": new_cursor},
                },
            },
        })
    """
)


@pytest.fixture
def fake_connector(tmp_path):
    script = tmp_path / "fake_source.py"
    script.write_text(_FAKE_CONNECTOR)
    db = tmp_path / "db.json"
    db.write_text(json.dumps([{"id": 1, "v": "a"}, {"id": 2, "v": "b"}]))
    return [sys.executable, str(script)], db


def test_state_tracker_flavors():
    tr = AirbyteStateTracker()
    assert tr.envelope() is None
    tr.observe({"type": "LEGACY", "data": {"pos": 5}})
    assert tr.envelope() == {"type": "LEGACY", "data": {"pos": 5}}
    # STREAM states supersede the legacy blob in the envelope
    tr.observe(
        {
            "type": "STREAM",
            "stream": {
                "stream_descriptor": {"name": "events"},
                "stream_state": {"cursor": 7},
            },
        }
    )
    env = tr.envelope()
    assert env["type"] == "GLOBAL"
    assert env["global"]["stream_states"] == [
        {"stream_descriptor": {"name": "events"}, "stream_state": {"cursor": 7}}
    ]
    # GLOBAL folds stream states + shared state
    tr.observe(
        {
            "type": "GLOBAL",
            "global": {
                "stream_states": [
                    {
                        "stream_descriptor": {"name": "other"},
                        "stream_state": {"cursor": 1},
                    }
                ],
                "shared_state": {"cdc": "lsn9"},
            },
        }
    )
    env = tr.envelope()
    names = {s["stream_descriptor"]["name"] for s in env["global"]["stream_states"]}
    assert names == {"events", "other"}
    assert env["global"]["shared_state"] == {"cdc": "lsn9"}
    # round trip
    tr2 = AirbyteStateTracker()
    tr2.load(env)
    assert tr2.envelope() == env


def test_source_discover_and_sync_mode(fake_connector, tmp_path):
    cmd, db = fake_connector
    src = ExecutableAirbyteSource(
        cmd, config={"db": str(db)}, streams=["events"]
    )
    cat = src.discover()
    assert {s["name"] for s in cat["streams"]} == {"events", "snapshots"}
    assert src.sync_mode == "incremental"
    full = ExecutableAirbyteSource(
        cmd, config={"db": str(db)}, streams=["snapshots"]
    )
    assert full.sync_mode == "full_refresh"
    with pytest.raises(ValueError, match="not found"):
        ExecutableAirbyteSource(
            cmd, config={"db": str(db)}, streams=["nope"]
        ).configured_catalog


def test_airbyte_incremental_read_and_resume(fake_connector, tmp_path):
    cmd, db = fake_connector
    state_path = tmp_path / "state.json"
    t = pw.io.airbyte.read(
        {"source": {"config": {"db": str(db)}}},
        ["events"],
        command=cmd,
        mode="static",
        state_path=str(state_path),
    )
    rows = run_to_rows(t)
    assert sorted(r[0]["id"] for r in rows) == [1, 2]
    saved = json.loads(state_path.read_text())
    assert saved["type"] == "GLOBAL"
    assert saved["global"]["stream_states"][0]["stream_state"] == {"cursor": 2}

    # new rows arrive; a fresh pipeline resumes FROM THE SAVED STATE and
    # extracts only the increment (the machinery VERDICT r3 asked for)
    db.write_text(
        json.dumps(
            [
                {"id": 1, "v": "a"},
                {"id": 2, "v": "b"},
                {"id": 3, "v": "c"},
            ]
        )
    )
    pw.G.clear()
    t2 = pw.io.airbyte.read(
        {"source": {"config": {"db": str(db)}}},
        ["events"],
        command=cmd,
        mode="static",
        state_path=str(state_path),
    )
    rows2 = run_to_rows(t2)
    assert [r[0]["id"] for r in rows2] == [3]
    assert json.loads(state_path.read_text())["global"]["stream_states"][0][
        "stream_state"
    ] == {"cursor": 3}


def test_airbyte_full_refresh_diffing(fake_connector, tmp_path):
    """full_refresh polls snapshot-diff: unchanged rows don't churn and
    disappeared rows retract."""
    from pathway_tpu.io.airbyte import _AirbyteSubject

    cmd, db = fake_connector
    src = ExecutableAirbyteSource(
        cmd, config={"db": str(db)}, streams=["snapshots"]
    )
    subject = _AirbyteSubject(src, mode="static", refresh_interval_ms=10)

    class Events:
        stopped = False

        def __init__(self):
            self.ops = []

        def add(self, key, row):
            self.ops.append(("add", row))

        def remove(self, key, row):
            self.ops.append(("remove", row))

        def commit(self):
            self.ops.append(("commit", None))

    import pathway_tpu.internals.schema as sch

    subject._schema = sch.schema_from_types(data=dict)
    subject._events = Events()
    subject.run()
    first = list(subject._events.ops)
    assert [op for op, _ in first] == ["add", "add", "commit"]

    # second poll, one row gone, one unchanged, one new
    db.write_text(json.dumps([{"id": 2, "v": "b"}, {"id": 9, "v": "z"}]))
    subject._events.ops.clear()
    subject.run()
    second = subject._events.ops
    kinds = [op for op, _ in second]
    assert kinds.count("add") == 1  # only the new row
    assert kinds.count("remove") == 1  # the disappeared row
    removed = [r for op, r in second if op == "remove"][0]
    assert removed[0]["id"] == 1


def test_airbyte_docker_config_stays_gated(tmp_path):
    from pathway_tpu.io._gated import MissingDependency

    with pytest.raises((MissingDependency, ImportError)):
        pw.io.airbyte.read(
            {"source": {"docker_image": "airbyte/source-faker:latest"}},
            ["users"],
            mode="static",
        )
