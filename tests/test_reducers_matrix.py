"""Full reducer matrix: every reducer against computed ground truth,
under both static input and streaming retraction (reference
``src/engine/reduce.rs`` reducer family + ``pw.reducers`` facade).
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import api
from tests.utils import T, run_to_rows


def _t():
    return pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int, w=float),
        [
            ("x", 3, 1.0),
            ("x", 1, 2.0),
            ("x", 2, 4.0),
            ("y", 10, 0.5),
        ],
    )


def test_numeric_reducers_ground_truth():
    pw.G.clear()
    t = _t()
    out = t.groupby(t.g).reduce(
        t.g,
        n=pw.reducers.count(),
        s=pw.reducers.sum(t.v),
        a=pw.reducers.avg(t.w),
        lo=pw.reducers.min(t.v),
        hi=pw.reducers.max(t.v),
    )
    rows = {r[0]: r[1:] for r in run_to_rows(out)}
    assert rows["x"] == (3, 6, pytest.approx(7.0 / 3), 1, 3)
    assert rows["y"] == (1, 10, 0.5, 10, 10)


def test_arg_reducers_pick_the_right_witness():
    pw.G.clear()
    t = _t()
    out = t.groupby(t.g).reduce(
        t.g,
        am=pw.reducers.argmax(t.v, t.w),  # w of the max-v row
        an=pw.reducers.argmin(t.v, t.w),
    )
    rows = {r[0]: r[1:] for r in run_to_rows(out)}
    assert rows["x"] == (1.0, 2.0)  # v=3 -> w=1.0; v=1 -> w=2.0
    assert rows["y"] == (0.5, 0.5)


def test_tuple_and_sorted_tuple():
    pw.G.clear()
    t = _t()
    out = t.groupby(t.g).reduce(
        t.g,
        st=pw.reducers.sorted_tuple(t.v),
        tp=pw.reducers.tuple(t.v),
    )
    rows = {r[0]: r[1:] for r in run_to_rows(out)}
    assert rows["x"][0] == (1, 2, 3)
    assert sorted(rows["x"][1]) == [1, 2, 3]  # tuple: arbitrary stable order
    assert rows["y"] == ((10,), (10,))


def test_unique_raises_on_multiple_values_and_any_picks_one():
    pw.G.clear()
    t = _t()
    uniq = t.groupby(t.g).reduce(t.g, u=pw.reducers.unique(t.g))
    rows = {r[0]: r[1] for r in run_to_rows(uniq)}
    assert rows == {"x": "x", "y": "y"}
    pw.G.clear()
    t = _t()
    # unique over a non-unique column yields ERROR for that group
    bad = t.groupby(t.g).reduce(t.g, u=pw.reducers.unique(t.v))
    vals = {r[0]: r[1] for r in run_to_rows(bad)}
    assert vals["y"] == 10
    assert vals["x"] is api.ERROR or isinstance(vals["x"], type(api.ERROR))
    pw.G.clear()
    t = _t()
    anyv = t.groupby(t.g).reduce(t.g, a=pw.reducers.any(t.v))
    vals = {r[0]: r[1] for r in run_to_rows(anyv)}
    assert vals["x"] in (1, 2, 3) and vals["y"] == 10


def test_earliest_latest_track_processing_order():
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    g | v | __time__ | __diff__
    x | 1 | 2        | 1
    x | 2 | 4        | 1
    x | 3 | 6        | 1
    """
    )
    out = t.groupby(t.g).reduce(
        t.g,
        first=pw.reducers.earliest(t.v),
        last=pw.reducers.latest(t.v),
    )
    rows = {r[0]: r[1:] for r in run_to_rows(out)}
    assert rows["x"] == (1, 3)


def test_ndarray_and_npsum():
    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, vec=object),
        [
            ("x", np.array([1.0, 2.0])),
            ("x", np.array([3.0, 4.0])),
        ],
    )
    out = t.groupby(t.g).reduce(
        t.g,
        total=pw.reducers.npsum(t.vec),
        stacked=pw.reducers.ndarray(t.vec),
    )
    ((g, total, stacked),) = run_to_rows(out)
    np.testing.assert_allclose(total, [4.0, 6.0])
    assert np.asarray(stacked).shape == (2, 2)


def test_stateful_single_reducer():
    pw.G.clear()
    t = _t()
    concat = pw.reducers.stateful_single(
        lambda state, val: (state or "") + str(val)
    )
    out = t.groupby(t.g).reduce(t.g, c=concat(t.v))
    rows = {r[0]: r[1] for r in run_to_rows(out)}
    assert rows["y"] == "10"
    assert sorted(rows["x"]) == sorted("312")  # all values folded once


def test_reducers_under_retraction_converge():
    """Every reducer recomputes correctly after the max element retracts
    (the multiset machinery must not cache the old extreme)."""
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    g | v | __time__ | __diff__
    x | 1 | 2        | 1
    x | 9 | 2        | 1
    x | 9 | 4        | -1
    x | 5 | 4        | 1
    """
    )
    out = t.groupby(t.g).reduce(
        t.g,
        hi=pw.reducers.max(t.v),
        lo=pw.reducers.min(t.v),
        s=pw.reducers.sum(t.v),
        st=pw.reducers.sorted_tuple(t.v),
    )
    ((g, hi, lo, s, st),) = run_to_rows(out)
    assert (hi, lo, s, st) == (5, 1, 6, (1, 5))


def test_avg_precision_floats():
    pw.G.clear()
    vals = [0.1] * 10
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=float), [("x", v) for v in vals]
    )
    out = t.groupby(t.g).reduce(t.g, a=pw.reducers.avg(t.v))
    ((_, a),) = run_to_rows(out)
    assert a == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# CDC: debezium envelopes and kafka upsert streams


def test_debezium_cdc_create_update_delete():
    """Debezium envelopes (c/u/d ops) fold into a live snapshot keyed by
    the record key — the CDC contract (reference debezium format,
    src/connectors/data_format.rs DebeziumMessageParser)."""
    import json as _json

    broker = pw.io.kafka.MockBroker.get("mock://dbz-matrix")

    def envelope(op, before, after):
        return _json.dumps({"payload": {"op": op, "before": before, "after": after}}).encode()

    broker.produce("cdc", envelope("c", None, {"id": 1, "name": "ada"}))
    broker.produce("cdc", envelope("c", None, {"id": 2, "name": "bob"}))
    broker.produce(
        "cdc", envelope("u", {"id": 1, "name": "ada"}, {"id": 1, "name": "ada2"})
    )
    broker.produce("cdc", envelope("d", {"id": 2, "name": "bob"}, None))
    broker.close_topic("cdc")

    class S(pw.Schema):
        id: int = pw.column_definition(primary_key=True)
        name: str

    pw.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "mock://dbz-matrix"},
        topic_name="cdc",
        schema=S,
    )
    rows = sorted(run_to_rows(t))
    assert rows == [(1, "ada2")]  # update applied, delete removed


def test_kafka_upsert_by_key_format():
    """raw-keyed kafka messages with the same key overwrite (upsert
    session semantics)."""
    import json as _json

    broker = pw.io.kafka.MockBroker.get("mock://upsert-matrix")
    broker.produce("t", _json.dumps({"k": "a", "v": 1}).encode())
    broker.produce("t", _json.dumps({"k": "b", "v": 2}).encode())
    broker.produce("t", _json.dumps({"k": "a", "v": 9}).encode())
    broker.close_topic("t")

    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    pw.G.clear()
    t = pw.io.kafka.read(
        {"bootstrap.servers": "mock://upsert-matrix"},
        topic="t",
        schema=S,
        format="json",
    )
    assert sorted(run_to_rows(t)) == [("a", 9), ("b", 2)]


def test_kafka_write_round_trip():
    """pw.io.kafka.write publishes the update stream back to a broker."""
    import json as _json

    in_broker = pw.io.kafka.MockBroker.get("mock://wr-in")
    in_broker.produce("src", _json.dumps({"v": 1}).encode())
    in_broker.produce("src", _json.dumps({"v": 2}).encode())
    in_broker.close_topic("src")

    class S(pw.Schema):
        v: int

    pw.G.clear()
    t = pw.io.kafka.read(
        {"bootstrap.servers": "mock://wr-in"}, topic="src", schema=S, format="json"
    )
    pw.io.kafka.write(
        t, {"bootstrap.servers": "mock://wr-in"}, topic_name="sink", format="json"
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    msgs = in_broker.consume_from("sink", 0)
    payloads = sorted(_json.loads(v)["v"] for _k, v in msgs)
    assert payloads == [1, 2]
