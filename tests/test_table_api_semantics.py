"""Table API semantics: this/left/right resolution, column renaming and
slices, with_id_from reindexing, with_universe_of, cast_to_types, ix
contexts, and TableSlice operations — reference ``Table`` surface
(``python/pathway/internals/table.py`` role).
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from tests.utils import T, run_to_rows


def _t():
    return pw.debug.table_from_rows(
        pw.schema_from_types(a=int, b=str, c=float),
        [(1, "x", 0.5), (2, "y", 1.5)],
    )


def test_pw_this_resolves_to_context_table():
    pw.G.clear()
    t = _t()
    out = t.select(doubled=pw.this.a * 2, label=pw.this.b)
    assert sorted(run_to_rows(out)) == [(2, "x"), (4, "y")]


def test_rename_kwargs_and_dict():
    pw.G.clear()
    t = _t()
    r1 = t.rename(alpha="a")
    assert "alpha" in r1.column_names() and "a" not in r1.column_names()
    assert sorted(run_to_rows(r1.select(r1.alpha))) == [(1,), (2,)]
    pw.G.clear()
    t = _t()
    r2 = t.rename_by_dict({"a": "first", "b": "second"})
    assert r2.column_names()[:2] == ["first", "second"]


def test_without_drops_columns():
    pw.G.clear()
    t = _t()
    w = t.without("b", "c")
    assert w.column_names() == ["a"]
    assert sorted(run_to_rows(w)) == [(1,), (2,)]


def test_slice_without_rename_compose():
    pw.G.clear()
    t = _t()
    sl = t.slice.without("c").rename({"a": "k"})
    # passing the SLICE ITSELF keeps its renames (splatting loses them:
    # bare refs only carry their original name)
    out = t.select(sl)
    assert out.column_names() == ["k", "b"]
    assert sorted(run_to_rows(out)) == [(1, "x"), (2, "y")]


def test_cast_to_types_changes_dtype_and_value():
    pw.G.clear()
    t = _t()
    c = t.cast_to_types(a=float)
    assert c._dtypes["a"] == dt.FLOAT
    rows = sorted(run_to_rows(c.select(c.a)))
    assert rows == [(1.0,), (2.0,)]
    assert all(isinstance(r[0], float) for r in rows)


def test_with_id_from_reindexes_deterministically():
    pw.G.clear()
    t = _t()
    keyed = t.with_id_from(t.b)
    from tests.utils import _run_capture

    ((rows, _),) = _run_capture(keyed)
    from pathway_tpu.internals import keys as K

    assert set(rows) == {K.ref_scalar("x"), K.ref_scalar("y")}


def test_with_universe_of_aligns_keys():
    pw.G.clear()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    a = pw.debug.table_from_rows(S, [(1, "x"), (2, "y")])

    class S2(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        w: int

    b = pw.debug.table_from_rows(S2, [(1, 10), (2, 20)])
    joined_cols = a.with_universe_of(b)
    # same universe: columns combine positionally by key
    both = joined_cols.select(joined_cols.v, w=b.w)
    assert sorted(run_to_rows(both)) == [("x", 10), ("y", 20)]


def test_ix_looks_up_rows_by_pointer():
    pw.G.clear()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    target = pw.debug.table_from_rows(S, [(1, "one"), (2, "two")])
    reqs = pw.debug.table_from_rows(
        pw.schema_from_types(want=int), [(2,), (1,)]
    )
    ptrs = reqs.select(p=target.pointer_from(reqs.want))
    looked = target.ix(ptrs.p, context=ptrs)
    out = ptrs.select(v=looked.v)
    assert sorted(run_to_rows(out)) == [("one",), ("two",)]


def test_ix_null_pointer_and_dangling_pointer():
    from pathway_tpu.internals import api

    pw.G.clear()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    target = pw.debug.table_from_rows(S, [(1, "one")])
    # a NULL pointer with optional=True resolves to None values; a
    # DANGLING pointer (valid hash, no such row) is an ERROR in strict
    # mode — the lookup contract
    reqs = pw.debug.table_from_rows(pw.schema_from_types(want=int), [(1,), (None,)])
    ptrs = reqs.select(
        p=target.pointer_from(reqs.want, optional=True)
    )
    looked = target.ix(ptrs.p, optional=True, context=ptrs)
    out = ptrs.select(v=looked.v)
    assert sorted(run_to_rows(out), key=repr) == sorted(
        [("one",), (None,)], key=repr
    )
    pw.G.clear()
    target = pw.debug.table_from_rows(S, [(1, "one")])
    reqs = pw.debug.table_from_rows(pw.schema_from_types(want=int), [(99,)])
    ptrs = reqs.select(p=target.pointer_from(reqs.want))
    looked = target.ix(ptrs.p, context=ptrs)
    ((dangling,),) = run_to_rows(ptrs.select(v=looked.v))
    assert dangling is api.ERROR


def test_concat_requires_same_columns():
    pw.G.clear()
    a = _t()
    b = pw.debug.table_from_rows(pw.schema_from_types(z=int), [(1,)])
    with pytest.raises(Exception):
        a.concat_reindex(b)


def test_select_star_and_override():
    pw.G.clear()
    t = _t()
    out = t.select(*t, a=t.a * 100)  # star then override one column
    # the override WINS and takes the later position (last-wins order)
    assert out.column_names() == ["b", "c", "a"]
    rows = sorted(run_to_rows(out))
    assert rows == [("x", 0.5, 100), ("y", 1.5, 200)]


def test_groupby_set_id_groups_under_group_key():
    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(g=str, v=int), [("x", 1), ("x", 2), ("y", 5)]
    )
    # id=: the group value BECOMES the row key (set_id contract — the
    # reference requires a pointer-typed value; this engine keys on the
    # value directly)
    red = t.groupby(t.g, id=t.g).reduce(t.g, s=pw.reducers.sum(t.v))
    from tests.utils import _run_capture

    ((rows, _),) = _run_capture(red)
    assert set(rows) == {"x", "y"}
    assert {v[1] for v in rows.values()} == {3, 5}
