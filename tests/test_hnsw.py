"""HNSW graph ANN index — recall, churn, metric parity, and the
UsearchKnn DataIndex pipeline (reference usearch integration,
``src/external_integration/usearch_integration.rs``)."""

from __future__ import annotations

import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing.hnsw import HnswIndex
from tests.utils import T, run_to_rows


def _corpus(n=8000, d=48, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


def _recall_at_k(index, x, queries, k=10):
    res = index.search(queries, k)
    sims = queries @ x.T
    gt = np.argsort(-sims, axis=1)[:, :k]
    hits = 0
    for qi, reply in enumerate(res):
        got = {key for key, _ in reply}
        hits += len(got & set(gt[qi].tolist()))
    return hits / (len(queries) * k)


def test_hnsw_recall_vs_brute_force():
    x = _corpus()
    idx = HnswIndex(x.shape[1], metric="cos")
    idx.add(list(enumerate(x)))
    assert len(idx) == len(x)
    recall = _recall_at_k(idx, x, x[:100], k=10)
    assert recall >= 0.95, recall


def test_hnsw_live_churn():
    """Continuous add/remove cycles: removed keys never surface, recall
    over the surviving set stays high, slots get reused."""
    x = _corpus(n=3000)
    idx = HnswIndex(x.shape[1], metric="cos", ef_search=96)
    idx.add(list(enumerate(x)))
    rng = np.random.default_rng(1)
    alive = set(range(len(x)))
    for _round in range(5):
        victims = rng.choice(sorted(alive), size=400, replace=False).tolist()
        idx.remove(victims)
        alive -= set(victims)
        # re-add fresh vectors under new keys (slot reuse path)
        base = 10_000 + _round * 1000
        fresh = _corpus(n=300, seed=10 + _round)
        idx.add([(base + i, v) for i, v in enumerate(fresh)])
        alive |= {base + i for i in range(300)}

        res = idx.search(x[:50], 10)
        assert all(len(r) == 10 for r in res)
        for reply in res:
            keys = {k for k, _ in reply}
            assert keys <= alive, "removed key returned"
    assert len(idx) == len(alive)


def test_hnsw_readd_replaces_vector():
    idx = HnswIndex(4, metric="cos")
    idx.add([("a", [1.0, 0, 0, 0]), ("b", [0.9, 0.4, 0, 0])])
    idx.add([("a", [0.0, 0, 1, 0])])  # upsert
    assert len(idx) == 2
    (res,) = idx.search(np.array([[0, 0, 1, 0]], np.float32), 1)
    assert res[0][0] == "a"
    (res2,) = idx.search(np.array([[1, 0, 0, 0]], np.float32), 1)
    assert res2[0][0] == "b"  # the old 'a' vector is gone


@pytest.mark.parametrize("metric", ["cos", "dot", "l2sq"])
def test_hnsw_metric_parity_vs_exact(metric):
    """Top-1 must agree with exact search for each metric."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((500, 16)).astype(np.float32)
    idx = HnswIndex(16, metric=metric, ef_search=128)
    idx.add(list(enumerate(x)))
    q = rng.standard_normal((20, 16)).astype(np.float32)
    res = idx.search(q, 1)
    if metric == "l2sq":
        gt = np.argmin(((q[:, None, :] - x[None, :, :]) ** 2).sum(-1), axis=1)
    elif metric == "cos":
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        gt = np.argmax(qn @ xn.T, axis=1)
    else:
        gt = np.argmax(q @ x.T, axis=1)
    agree = sum(res[i][0][0] == gt[i] for i in range(len(q)))
    assert agree >= 18, f"{agree}/20 top-1 agreement for {metric}"


def test_hnsw_query_cost_below_ivf_at_equal_recall():
    """The graph walk must answer queries cheaper than the IVF scan at
    comparable (>=0.95) recall — the reason HNSW exists here."""
    from pathway_tpu.parallel import IvfKnnIndex

    x = _corpus(n=6000, d=48)
    q = _corpus(n=64, d=48, seed=9)

    hnsw = HnswIndex(48, metric="cos")
    hnsw.add(list(enumerate(x)))
    ivf = IvfKnnIndex(48, metric="cos", capacity=8192)
    ivf.add(list(enumerate(x)))

    r_hnsw = _recall_at_k(hnsw, x, q, 10)
    assert r_hnsw >= 0.95, r_hnsw

    # warmup both (jit compile for IVF), then time
    hnsw.search(q, 10)
    ivf.search(q, 10)
    t0 = time.perf_counter()
    for _ in range(3):
        hnsw.search(q, 10)
    t_hnsw = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(3):
        ivf.search(q, 10)
    t_ivf = time.perf_counter() - t0
    assert t_hnsw < t_ivf, (t_hnsw, t_ivf)


def test_hnsw_fallback_mode_matches_native(monkeypatch):
    """With the native module unavailable the wrapper degrades to exact
    numpy search — same keys for well-separated data."""
    x = np.eye(8, dtype=np.float32)
    native_idx = HnswIndex(8, metric="cos")
    fb = HnswIndex.__new__(HnswIndex)
    fb.dim, fb.metric, fb.M = 8, "cos", 16
    fb.ef_construction, fb.ef_search = 128, 64
    fb.tombstone_fraction = 0.33
    fb._slot_of, fb._key_of = {}, {}
    fb._native = None
    fb._store = {}
    fb._hw = 0
    fb.compactions = 0
    import threading

    fb._lock = threading.RLock()
    for idx in (native_idx, fb):
        idx.add([(i, x[i]) for i in range(8)])
    q = x[:4]
    got_n = [r[0][0] for r in native_idx.search(q, 1)]
    got_f = [r[0][0] for r in fb.search(q, 1)]
    assert got_n == got_f == [0, 1, 2, 3]
    fb.remove([2])
    assert len(fb) == 7


def test_usearch_knn_end_to_end_pipeline():
    """UsearchKnn (HNSW-backed) through the DataIndex engine operator."""
    from pathway_tpu.stdlib.indexing import DataIndex
    from pathway_tpu.stdlib.indexing.data_index import UsearchKnn

    docs = T(
        """
    doc     | vx | vy
    apple   | 1  | 0
    banana  | 0  | 1
    cherry  | 1  | 1
    """
    ).select(
        doc=pw.this.doc,
        vec=pw.apply(lambda a, b: (float(a), float(b)), pw.this.vx, pw.this.vy),
    )
    queries = T(
        """
    qid | qx | qy
    q1  | 1  | 0
    q2  | 0  | 1
    """
    ).select(
        qid=pw.this.qid,
        qvec=pw.apply(lambda a, b: (float(a), float(b)), pw.this.qx, pw.this.qy),
    )
    inner = UsearchKnn(docs.vec, dimensions=2, reserved_space=16)
    di = DataIndex(docs, inner)
    res = di.query_as_of_now(queries.qvec, number_of_matches=2)
    rows = run_to_rows(res)
    by_q = {r[0]: r for r in rows}
    assert [d["doc"] for d in by_q["q1"][4]] == ["apple", "cherry"]
    assert [d["doc"] for d in by_q["q2"][4]] == ["banana", "cherry"]


def test_hnsw_duplicate_key_within_one_batch():
    """Last occurrence wins; the earlier duplicate's slot must not stay
    alive under a lost key."""
    idx = HnswIndex(4, metric="cos")
    idx.add([("a", [1.0, 0, 0, 0]), ("b", [0.9, 0.4, 0, 0]), ("a", [0, 0, 1.0, 0])])
    assert len(idx) == 2
    (res,) = idx.search(np.array([[1.0, 0, 0, 0]], np.float32), 2)
    assert [k for k, _ in res] == ["b", "a"]  # old 'a' vector gone


def test_hnsw_concurrent_add_search_remove():
    """add/search/remove from multiple threads (the native side releases
    the GIL; the index's internal mutex must serialize)."""
    import threading

    x = _corpus(n=2000, d=16)
    idx = HnswIndex(16, metric="cos")
    idx.add(list(enumerate(x[:1000])))
    stop = threading.Event()
    errors: list = []

    def adder():
        try:
            i = 1000
            while not stop.is_set() and i < 2000:
                idx.add([(i, x[i])])
                i += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def searcher():
        try:
            while not stop.is_set():
                res = idx.search(x[:8], 5)
                assert len(res) == 8
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def remover():
        try:
            i = 0
            while not stop.is_set() and i < 500:
                idx.remove([i])
                i += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=f) for f in (adder, searcher, remover)
    ]
    for t in threads:
        t.start()
    import time as _t

    _t.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors
    assert len(idx) > 0


def test_hnsw_recall_at_100k_docs():
    """Recall at 100k docs (round-3 done criterion said 1M; round-4
    verdict weak #7 flagged that assertions only ran at 8k — this is the
    committed >=100k-scale check; 1M remains a bench-only scale).  The
    native graph index must actually be active: without it HnswIndex
    silently falls back to exact brute force and recall 1.0 would prove
    nothing."""
    from pathway_tpu.internals import native as _native

    if _native.load() is None:
        pytest.skip("native module unavailable: HNSW falls back to exact")
    x = _corpus(n=100_000, d=32, seed=3)
    idx = HnswIndex(x.shape[1], metric="cos")
    assert idx._native is not None, "graph index inactive (exact fallback)"
    CHUNK = 10_000
    for lo in range(0, len(x), CHUNK):
        idx.add(list(enumerate(x[lo : lo + CHUNK], start=lo)))
    assert len(idx) == len(x)
    recall = _recall_at_k(idx, x, x[:50], k=10)
    assert recall >= 0.85, recall


def test_hnsw_churn_at_scale_keeps_recall():
    """Delete/re-add 20% of a 50k corpus; removed keys never surface and
    recall over the survivors holds."""
    x = _corpus(n=50_000, d=32, seed=4)
    idx = HnswIndex(x.shape[1], metric="cos")
    idx.add(list(enumerate(x)))
    removed = list(range(0, len(x), 5))  # every 5th key
    idx.remove(removed)
    assert len(idx) == len(x) - len(removed)
    removed_set = set(removed)
    res = idx.search(x[1:200:2], 10)
    for reply in res:
        assert not ({key for key, _ in reply} & removed_set)
    # re-add with NEW vectors: slots recycle, lookups resolve to the new data
    rng = np.random.default_rng(9)
    fresh = rng.standard_normal((len(removed), x.shape[1])).astype(np.float32)
    fresh /= np.linalg.norm(fresh, axis=1, keepdims=True)
    idx.add(list(zip(removed, fresh)))
    assert len(idx) == len(x)
    reply = idx.search(fresh[:1], 3)[0]
    assert reply[0][0] == removed[0]
