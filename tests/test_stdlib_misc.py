"""iterate, graphs, ml, sql, yaml, universes, utils, monitoring."""

import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from tests.utils import T, run_to_rows


# ---------------------------------------------------------------------------
# iterate


def test_iterate_fixed_point():
    t = T(
        """
    x
    5
    16
    """
    )

    def body(x):
        return x.select(
            x=pw.apply(
                lambda v: 1 if v == 1 else (v // 2 if v % 2 == 0 else 3 * v + 1),
                pw.this.x,
            )
        )

    res = pw.iterate(body, x=t)
    assert run_to_rows(res) == [(1,), (1,)]


def test_iterate_with_limit():
    t = T(
        """
    x
    0
    """
    )

    def body(x):
        return x.select(x=pw.this.x + 1)  # never converges

    res = pw.iterate(body, iteration_limit=5, x=t)
    assert run_to_rows(res) == [(5,)]


# ---------------------------------------------------------------------------
# graphs


def _edges():
    # a -1- b -1- c;  a -5- c
    v = T(
        """
    name | dist0
    a    | 0
    b    | __none__
    c    | __none__
    """
    ).select(
        name=pw.this.name,
        dist=pw.apply(lambda d: 0.0 if str(d) == "0" else None, pw.this.dist0),
    )
    vertices = v.with_id_from(pw.this.name)
    e = T(
        """
    u | v | dist
    a | b | 1
    b | c | 1
    a | c | 5
    """
    )
    edges = e.select(
        u=vertices.pointer_from(e.u),
        v=vertices.pointer_from(e.v),
        dist=pw.this.dist,
    )
    return vertices, edges


def test_bellman_ford():
    from pathway_tpu.stdlib.graphs import bellman_ford

    vertices, edges = _edges()
    res = bellman_ford(vertices, edges)
    dists = sorted(r[0] for r in run_to_rows(res))
    assert dists == [0.0, 1.0, 2.0]


def test_pagerank():
    from pathway_tpu.stdlib.graphs import pagerank

    e = T(
        """
    un | vn
    a  | b
    b  | c
    c  | a
    """
    )
    edges = e.select(u=pw.this.un, v=pw.this.vn)
    ranks = run_to_rows(pagerank(edges, steps=10))
    vals = [r[1] for r in ranks]
    assert len(vals) == 3
    assert all(abs(v - 1.0) < 0.1 for v in vals)  # symmetric cycle -> equal


def test_louvain_two_cliques():
    from pathway_tpu.stdlib.graphs import WeightedGraph, louvain_level

    e = T(
        """
    u | v | weight
    a | b | 1
    b | c | 1
    a | c | 1
    x | y | 1
    y | z | 1
    x | z | 1
    a | x | 0.1
    """
    )
    comms = run_to_rows(louvain_level(WeightedGraph(e)))
    by_node = {r[0]: r[1] for r in comms}
    assert by_node["a"] == by_node["b"] == by_node["c"]
    assert by_node["x"] == by_node["y"] == by_node["z"]
    assert by_node["a"] != by_node["x"]


# ---------------------------------------------------------------------------
# ml


def test_knn_index_legacy():
    from pathway_tpu.stdlib.ml import KNNIndex

    data = T(
        """
    label | x  | y
    l1    | 1  | 0
    l2    | 0  | 1
    """
    ).select(
        label=pw.this.label,
        vec=pw.apply(lambda a, b: (float(a), float(b)), pw.this.x, pw.this.y),
    )
    index = KNNIndex(data.vec, data, n_dimensions=2)
    queries = T(
        """
    qx | qy
    1  | 0
    """
    ).select(vec=pw.apply(lambda a, b: (float(a), float(b)), pw.this.qx, pw.this.qy))
    res = index.get_nearest_items(queries.vec, k=1)
    rows = run_to_rows(res)
    labels = [r for r in rows[0] if isinstance(r, tuple)][0]
    assert labels == ("l1",)


def test_knn_classifier():
    from pathway_tpu.stdlib.ml.classifiers import knn_lsh_classify, knn_lsh_train

    data = T(
        """
    label | x | y
    A     | 1 | 0
    A     | 1 | 1
    B     | 0 | 1
    """
    ).select(
        label=pw.this.label,
        data=pw.apply(lambda a, b: (float(a), float(b)), pw.this.x, pw.this.y),
    )
    index = knn_lsh_train(data, d=2)
    queries = T(
        """
    x | y
    1 | 0
    """
    ).select(data=pw.apply(lambda a, b: (float(a), float(b)), pw.this.x, pw.this.y))
    res = knn_lsh_classify(index, queries.data, k=3)
    assert run_to_rows(res) == [("A",)]


def test_hmm_reducer():
    from pathway_tpu.stdlib.ml.hmm import create_hmm_reducer

    hmm = create_hmm_reducer(
        graph={"sunny": {"sunny": 0.9, "rainy": 0.1}, "rainy": {"rainy": 0.9, "sunny": 0.1}},
    )
    t = T(
        """
    k | t | obs
    a | 1 | sunny
    a | 2 | sunny
    a | 3 | rainy
    a | 4 | rainy
    """
    )
    res = t.groupby(t.k).reduce(state=hmm(pw.make_tuple(t.t, t.obs)))
    assert run_to_rows(res) == [("rainy",)]


def test_fuzzy_match():
    from pathway_tpu.stdlib.ml.smart_table_ops import fuzzy_match_tables

    left = T(
        """
    ln | name
    1  | john smith
    2  | acme corp ltd
    """
    )
    right = T(
        """
    rn | title
    a  | smith john
    b  | acme corporation
    """
    )
    res = fuzzy_match_tables(left, right, left_column=left.name, right_column=right.title)
    rows = run_to_rows(res)
    assert len(rows) == 2
    weights = sorted(r[2] for r in rows)
    assert weights[0] > 0.2


# ---------------------------------------------------------------------------
# sql


def test_sql_select_where():
    t = T(
        """
    a | b
    1 | 10
    2 | 20
    3 | 30
    """
    )
    res = pw.sql("SELECT a, b FROM tab WHERE b > 15", tab=t)
    assert sorted(run_to_rows(res)) == [(2, 20), (3, 30)]


def test_sql_group_by():
    t = T(
        """
    owner | pets
    alice | 1
    bob   | 2
    alice | 3
    """
    )
    res = pw.sql(
        "SELECT owner, SUM(pets) AS total, COUNT(*) AS n FROM t GROUP BY owner",
        t=t,
    )
    assert sorted(run_to_rows(res)) == [("alice", 4, 2), ("bob", 2, 1)]


def test_sql_having_restated_aggregate():
    t = T(
        """
    owner | pets
    alice | 1
    bob   | 2
    alice | 3
    """
    )
    res = pw.sql(
        "SELECT owner, SUM(pets) AS total FROM t GROUP BY owner HAVING SUM(pets) > 2",
        t=t,
    )
    assert run_to_rows(res) == [("alice", 4)]


def test_sql_distinct_union_subquery():
    t = T(
        """
    a | b
    1 | x
    1 | x
    2 | y
    """
    )
    res = pw.sql("SELECT DISTINCT a, b FROM t", t=t)
    assert sorted(run_to_rows(res)) == [(1, "x"), (2, "y")]

    u = pw.sql(
        "SELECT a FROM t WHERE b = 'x' UNION SELECT a FROM t WHERE a = 2",
        t=t,
    )
    assert sorted(run_to_rows(u)) == [(1,), (2,)]

    ua = pw.sql(
        "SELECT a FROM t WHERE a = 2 UNION ALL SELECT a FROM t WHERE a = 2",
        t=t,
    )
    assert sorted(run_to_rows(ua)) == [(2,), (2,)]

    sub = pw.sql(
        "SELECT big.a AS a FROM (SELECT a FROM t WHERE a > 1) AS big",
        t=t,
    )
    assert sorted(run_to_rows(sub)) == [(2,)]


def test_sql_cte_case_in_between_like_null():
    t = T(
        """
    name  | score
    ann   | 10
    bob   | 25
    carol | 40
    """
    )
    res = pw.sql(
        """
        WITH ranked AS (
            SELECT name,
                   CASE WHEN score >= 30 THEN 'high'
                        WHEN score BETWEEN 15 AND 30 THEN 'mid'
                        ELSE 'low' END AS tier
            FROM t
        )
        SELECT name, tier FROM ranked WHERE tier IN ('high', 'mid')
        """,
        t=t,
    )
    assert sorted(run_to_rows(res)) == [("bob", "mid"), ("carol", "high")]

    like = pw.sql("SELECT name FROM t WHERE name LIKE 'c%l'", t=t)
    assert run_to_rows(like) == [("carol",)]

    notlike = pw.sql("SELECT name FROM t WHERE name NOT LIKE '%o%'", t=t)
    assert run_to_rows(notlike) == [("ann",)]

    # IS NULL over an optional column
    t2 = T(
        """
    v | w
    1 |
    2 | x
    """
    )
    isnull = pw.sql("SELECT v FROM t2 WHERE w IS NULL", t2=t2)
    assert run_to_rows(isnull) == [(1,)]
    notnull = pw.sql("SELECT v FROM t2 WHERE w IS NOT NULL", t2=t2)
    assert run_to_rows(notnull) == [(2,)]
    # three-valued logic: NULL NOT LIKE / NOT IN excludes the NULL row
    nl = pw.sql("SELECT v FROM t2 WHERE w NOT LIKE 'z%'", t2=t2)
    assert run_to_rows(nl) == [(2,)]
    ni = pw.sql("SELECT v FROM t2 WHERE w NOT IN ('zzz')", t2=t2)
    assert run_to_rows(ni) == [(2,)]


def test_yaml_forward_reference():
    cfg = pw.load_yaml(
        """
pipeline:
  size: $dim
dim: 7
"""
    )
    assert cfg["pipeline"]["size"] == 7


def test_groupby_majority():
    from pathway_tpu.stdlib.utils.col import groupby_reduce_majority

    t = T(
        """
    g | v
    a | x
    a | x
    a | y
    b | z
    """
    )
    res = run_to_rows(groupby_reduce_majority(t.g, t.v))
    assert sorted(res) == [("a", "x"), ("b", "z")]


def test_sql_join():
    a = T(
        """
    k | va
    1 | x
    2 | y
    """
    )
    b = T(
        """
    k2 | vb
    1  | p
    2  | q
    """
    )
    res = pw.sql("SELECT va, vb FROM a JOIN b ON a.k = b.k2", a=a, b=b)
    assert sorted(run_to_rows(res)) == [("x", "p"), ("y", "q")]


# ---------------------------------------------------------------------------
# yaml loader


def test_load_yaml_vars_and_tags():
    cfg = pw.load_yaml(
        """
dim: 4
splitter: !pw.xpacks.llm.splitters.TokenCountSplitter
  min_tokens: 2
  max_tokens: $dim
"""
    )
    from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter

    assert cfg["dim"] == 4
    assert isinstance(cfg["splitter"], TokenCountSplitter)
    assert cfg["splitter"].max_tokens == 4


# ---------------------------------------------------------------------------
# universes


def test_universe_promises():
    import pathway_tpu.universes as U

    t1 = T(
        """
    a
    1
    2
    """
    )
    t2 = t1.filter(pw.this.a > 1)
    t3 = U.promise_is_subset_of(t2, t1)
    # cross-table select now allowed
    combined = t1.select(a=pw.this.a, b=t3.a)
    rows = run_to_rows(combined)
    assert (2, 2) in rows


# ---------------------------------------------------------------------------
# AsyncTransformer


def test_async_transformer():
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    class OutSchema(pw.Schema):
        ret: int

    class Doubler(pw.AsyncTransformer):
        output_schema = OutSchema

        async def invoke(self, value: int) -> dict:
            if value == 13:
                raise ValueError("unlucky")
            return {"ret": value * 2}

    class InSubject(pw.io.python.ConnectorSubject):
        def run(self):
            for v in (1, 13, 4):
                self.next(value=v)
                self.commit()
                time.sleep(0.05)

    class InSchema(pw.Schema):
        value: int

    inputs = pw.io.python.read(InSubject(), schema=InSchema)
    transformer = Doubler(inputs)
    got: list = []
    pw.io.subscribe(
        transformer.successful,
        on_change=lambda key, row, time, is_addition: got.append(row["ret"])
        if is_addition
        else None,
    )
    failed: list = []
    pw.io.subscribe(
        transformer.failed,
        on_change=lambda key, row, time, is_addition: failed.append(1),
    )
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    th = threading.Thread(target=sched.run)
    th.start()
    th.join(timeout=15)
    alive = th.is_alive()
    sched.stop()
    assert not alive
    assert sorted(got) == [2, 8]
    assert len(failed) == 1


# ---------------------------------------------------------------------------
# utils.col


def test_unpack_col():
    from pathway_tpu.stdlib.utils import unpack_col

    t = T(
        """
    n
    1
    """
    ).select(packed=pw.apply(lambda n: (n, n * 10), pw.this.n))
    res = unpack_col(t.packed, "a", "b")
    assert run_to_rows(res) == [(1, 10)]


def test_pandas_transformer():
    from pathway_tpu.stdlib.utils import pandas_transformer

    class Out(pw.Schema):
        s: int

    @pandas_transformer(output_schema=Out)
    def double_sum(df):
        import pandas as pd

        return pd.DataFrame({"s": [int(df["x"].sum()) * 2]})

    t = T(
        """
    x
    1
    2
    """
    )
    assert run_to_rows(double_sum(t)) == [(6,)]


# ---------------------------------------------------------------------------
# monitoring HTTP server


def test_monitoring_http_server():
    import json
    import socket
    import urllib.request

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.internals.parse_graph import G

    t = T(
        """
    a
    1
    """
    )
    t.select(b=pw.this.a)
    sched = Scheduler(G.engine_graph)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    start_http_server(sched, port=port)
    time.sleep(0.3)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/status", timeout=5) as r:
        status = json.loads(r.read())
    assert status["operators"] >= 2
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        metrics = r.read().decode()
    assert "pathway_tpu_operator_count" in metrics
    sched._monitoring_server.shutdown()


def test_operator_probes_and_connector_counters():
    """Per-operator latency/row probes + per-connector counters feed
    ProberStats and the /metrics endpoint (reference attach_prober
    graph.rs:988-995, connectors/monitoring.rs)."""
    import json
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.monitoring import collect_stats
    from pathway_tpu.internals.monitoring_server import _metrics_text
    from pathway_tpu.internals.parse_graph import G

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(10):
                self.next(a=i)
            self.commit()

    class S(pw.Schema):
        a: int

    t = pw.io.python.read(Src(), schema=S)
    c = t.groupby(t.a).reduce(t.a, n=pw.reducers.count())
    cap = c._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    G.active_scheduler = sched
    sched.run()

    stats = collect_stats(sched)
    assert stats.input_rows == 10
    (cstats,) = stats.connectors.values()
    assert cstats["rows"] == 10 and cstats["commits"] >= 1 and cstats["closed"]
    probes = stats.operator_probes
    gb = next(p for p in probes.values() if p["name"].startswith("groupby"))
    assert gb["rows_in"] == 10 and gb["total_ms"] >= 0.0 and gb["epochs"] >= 1

    text = _metrics_text(sched)
    assert "pathway_tpu_connector_rows_total" in text
    assert 'pathway_tpu_operator_latency_ms_total{operator="groupby' in text


def test_viz_live_plot_svg():
    t = T(
        """
    x | y  | z
    1 | 10 | a
    2 | 40 | b
    3 | 25 | c
    """
    )
    view = pw.viz.plot(t, sorting_col="x")
    pw.run(monitoring_level=pw.internals.run.MonitoringLevel.NONE)
    svg = view.to_svg()
    assert svg.startswith("<svg") and "polyline" in svg
    assert ">y<" in svg  # numeric series labelled
    html = view._repr_html_()
    assert html == svg


def test_debug_parquet_roundtrip(tmp_path):
    import pandas as pd

    src = tmp_path / "t.parquet"
    pd.DataFrame({"a": [1, 2], "b": ["x", "y"]}).to_parquet(src)
    t = pw.debug.table_from_parquet(str(src))
    out = tmp_path / "o.parquet"
    pw.debug.table_to_parquet(t.select(t.a, t.b), str(out))
    back = pd.read_parquet(out)
    assert back.to_dict("records") == [
        {"a": 1, "b": "x"},
        {"a": 2, "b": "y"},
    ]


def test_sql_intersect_except():
    """INTERSECT/EXCEPT vs Table-op ground truth (VERDICT r3 item 10)."""
    a = T(
        """
    x | y
    1 | p
    2 | q
    2 | q
    3 | r
    """
    )
    b = T(
        """
    x | y
    2 | q
    3 | r
    4 | s
    """
    )
    inter = pw.sql("SELECT x, y FROM a INTERSECT SELECT x, y FROM b", a=a, b=b)
    assert sorted(run_to_rows(inter)) == [(2, "q"), (3, "r")]

    exc = pw.sql("SELECT x, y FROM a EXCEPT SELECT x, y FROM b", a=a, b=b)
    assert sorted(run_to_rows(exc)) == [(1, "p")]

    # EXCEPT dedups its result (set semantics): the duplicate (2,q) row
    # vanishes entirely, (1,p) appears once
    exc2 = pw.sql(
        "SELECT x, y FROM a EXCEPT SELECT x, y FROM b WHERE x = 3", a=a, b=b
    )
    assert sorted(run_to_rows(exc2)) == [(1, "p"), (2, "q")]

    # INTERSECT binds tighter than UNION (SQL precedence):
    # a UNION (b INTERSECT b-where-x=4) == a-distinct + (4,s)
    mix = pw.sql(
        "SELECT x FROM a UNION SELECT x FROM b INTERSECT "
        "SELECT x FROM b WHERE x = 4",
        a=a,
        b=b,
    )
    assert sorted(run_to_rows(mix)) == [(1,), (2,), (3,), (4,)]


def test_sql_in_subquery():
    orders = T(
        """
    cust | amount
    ann  | 10
    bob  | 25
    carol| 40
    dave | 5
    """
    )
    vips = T(
        """
    name
    bob
    carol
    """
    )
    semi = pw.sql(
        "SELECT cust, amount FROM o WHERE cust IN (SELECT name FROM v)",
        o=orders,
        v=vips,
    )
    assert sorted(run_to_rows(semi)) == [("bob", 25), ("carol", 40)]

    anti = pw.sql(
        "SELECT cust, amount FROM o WHERE cust NOT IN (SELECT name FROM v)",
        o=orders,
        v=vips,
    )
    assert sorted(run_to_rows(anti)) == [("ann", 10), ("dave", 5)]

    # combined with an ordinary conjunct
    both = pw.sql(
        "SELECT cust FROM o WHERE amount > 7 AND cust IN (SELECT name FROM v)",
        o=orders,
        v=vips,
    )
    assert sorted(run_to_rows(both)) == [("bob",), ("carol",)]

    # subquery with its own WHERE
    sub_where = pw.sql(
        "SELECT cust FROM o WHERE cust IN "
        "(SELECT name FROM v WHERE name = 'bob')",
        o=orders,
        v=vips,
    )
    assert run_to_rows(sub_where) == [("bob",)]

    # ground truth via table ops: semi-join equivalence
    vd = vips.groupby(vips.name).reduce(vips.name)
    gt = orders.join(vd, orders.cust == vd.name).select(
        pw.left.cust, pw.left.amount
    )
    assert sorted(run_to_rows(semi)) == sorted(run_to_rows(gt))


def test_load_yaml_private_keys_and_escape():
    """Reference app-template key conventions: a leading $ marks a
    private variable (referenced as $name, dropped from the result);
    $$name escapes to the literal key $name, which a $$name value
    reference resolves to."""
    cfg = pw.load_yaml(
        """
$hidden: 41
visible: $hidden
$$literal: 7
also: $$literal
"""
    )
    assert cfg == {"visible": 41, "$literal": 7, "also": 7}
    # private/public collision raises instead of silently shadowing
    import pytest as _pytest

    with _pytest.raises(KeyError, match="same variable name"):
        pw.load_yaml("$x: 1\nx: 2")
    # non-string keys pass through untouched
    assert pw.load_yaml("1: a\nb: 2") == {1: "a", "b": 2}
