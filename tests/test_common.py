"""Core Table API tests (modeled on the reference's test_common.py areas:
select/filter/expressions/groupby/join/concat/update/ix)."""

import pytest

import pathway_tpu as pw
from tests.utils import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


def test_select_arithmetic():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    res = t.select(s=t.a + t.b, d=pw.this.b - pw.this.a, m=t.a * t.b)
    expected = T(
        """
        s | d | m
        3 | 1 | 2
        7 | 1 | 12
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_select_this_splat():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    res = t.select(pw.this, c=pw.this.a + 10)
    expected = T(
        """
        a | b | c
        1 | 2 | 11
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_filter():
    t = T(
        """
        a
        1
        2
        3
        4
        """
    )
    res = t.filter(t.a > 2)
    expected = T(
        """
        a
        3
        4
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_filter_then_select_parent_column():
    t = T(
        """
        a | b
        1 | 10
        2 | 20
        3 | 30
        """
    )
    filtered = t.filter(t.a >= 2)
    res = filtered.select(t.b)
    expected = T(
        """
        b
        20
        30
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_if_else_coalesce():
    t = T(
        """
        a | b
        1 | 5
        7 | 2
        """
    )
    res = t.select(mx=pw.if_else(t.a > t.b, t.a, t.b))
    expected = T(
        """
        mx
        5
        7
        """
    )
    assert_table_equality_wo_index(res, expected)

    t2 = T(
        """
        x
        1
        None
        """
    )
    res2 = t2.select(y=pw.coalesce(pw.this.x, 0))
    expected2 = T(
        """
        y
        1
        0
        """
    )
    assert_table_equality_wo_index(res2, expected2)


def test_apply():
    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(b=pw.apply(lambda x: x * 100, t.a))
    expected = T(
        """
        b
        100
        200
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_udf():
    @pw.udf
    def inc(x: int) -> int:
        return x + 1

    t = T(
        """
        a
        1
        5
        """
    )
    res = t.select(b=inc(t.a))
    expected = T(
        """
        b
        2
        6
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_async_udf():
    import asyncio

    @pw.udf
    async def double(x: int) -> int:
        await asyncio.sleep(0.001)
        return 2 * x

    t = T(
        """
        a
        1
        2
        3
        """
    )
    res = t.select(b=double(t.a))
    expected = T(
        """
        b
        2
        4
        6
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_groupby_reduce():
    t = T(
        """
        g | v
        a | 1
        a | 2
        b | 10
        """
    )
    res = t.groupby(t.g).reduce(t.g, s=pw.reducers.sum(t.v), c=pw.reducers.count())
    expected = T(
        """
        g | s  | c
        a | 3  | 2
        b | 10 | 1
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_groupby_min_max_avg():
    t = T(
        """
        g | v
        a | 1
        a | 5
        b | 2
        """
    )
    res = t.groupby(t.g).reduce(
        t.g,
        mn=pw.reducers.min(t.v),
        mx=pw.reducers.max(t.v),
        av=pw.reducers.avg(t.v),
    )
    expected = T(
        """
        g | mn | mx | av
        a | 1  | 5  | 3.0
        b | 2  | 2  | 2.0
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_global_reduce():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    res = t.reduce(s=pw.reducers.sum(t.v))
    expected = T(
        """
        s
        6
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_reduce_expression_over_reducers():
    t = T(
        """
        g | v
        a | 1
        a | 3
        b | 10
        """
    )
    res = t.groupby(t.g).reduce(
        t.g, mean=pw.cast(float, pw.reducers.sum(t.v)) / pw.reducers.count()
    )
    expected = T(
        """
        g | mean
        a | 2.0
        b | 10.0
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_join_inner():
    t1 = T(
        """
        a | k
        1 | x
        2 | y
        3 | z
        """
    )
    t2 = T(
        """
        b | k
        10 | x
        20 | y
        """
    )
    res = t1.join(t2, t1.k == t2.k).select(t1.a, t2.b, pw.left.k)
    expected = T(
        """
        a | b  | k
        1 | 10 | x
        2 | 20 | y
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_join_left():
    t1 = T(
        """
        a | k
        1 | x
        3 | z
        """
    )
    t2 = T(
        """
        b | k
        10 | x
        """
    )
    res = t1.join_left(t2, t1.k == t2.k).select(t1.a, b=t2.b)
    expected = T(
        """
        a | b
        1 | 10
        3 | None
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_join_outer():
    t1 = T(
        """
        a | k
        1 | x
        """
    )
    t2 = T(
        """
        b | k
        10 | x
        20 | y
        """
    )
    res = t1.join_outer(t2, t1.k == t2.k).select(a=t1.a, b=t2.b)
    expected = T(
        """
        a    | b
        1    | 10
        None | 20
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_concat_reindex():
    t1 = T(
        """
        a
        1
        """
    )
    t2 = T(
        """
        a
        2
        """
    )
    res = t1.concat_reindex(t2)
    expected = T(
        """
        a
        1
        2
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_update_cells():
    t1 = T(
        """
        id | a | b
        1  | 1 | x
        2  | 2 | y
        """
    )
    t2 = T(
        """
        id | b
        1  | z
        """
    )
    res = t1.update_cells(t2)
    expected = T(
        """
        id | a | b
        1  | 1 | z
        2  | 2 | y
        """
    )
    assert_table_equality(res, expected)


def test_update_rows():
    t1 = T(
        """
        id | a
        1  | 1
        2  | 2
        """
    )
    t2 = T(
        """
        id | a
        2  | 20
        3  | 30
        """
    )
    res = t1.update_rows(t2)
    expected = T(
        """
        id | a
        1  | 1
        2  | 20
        3  | 30
        """
    )
    assert_table_equality(res, expected)


def test_intersect_difference():
    t1 = T(
        """
        id | a
        1  | 1
        2  | 2
        3  | 3
        """
    )
    t2 = T(
        """
        id | b
        2  | x
        3  | y
        """
    )
    assert_table_equality_wo_index(
        t1.intersect(t2),
        T(
            """
            a
            2
            3
            """
        ),
    )
    assert_table_equality_wo_index(
        t1.difference(t2),
        T(
            """
            a
            1
            """
        ),
    )


def test_flatten():
    t = T(
        """
        g
        a
        """
    ).select(pw.this.g, parts=pw.apply(lambda g: (1, 2, 3), pw.this.g))
    res = t.flatten(pw.this.parts)
    expected = T(
        """
        g | parts
        a | 1
        a | 2
        a | 3
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_ix():
    target = T(
        """
        id | v
        1  | 100
        2  | 200
        """
    )
    req = T(
        """
        ptr
        1
        2
        1
        """
    ).select(p=pw.apply(lambda x: x, pw.this.ptr))
    req = req.select(p=target.pointer_from(pw.this.p))
    # pointer_from hashes the value; target ids are hashed from markdown `id`
    res = target.ix(req.p).select(v=pw.this.v)
    expected = T(
        """
        v
        100
        200
        100
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_with_id_from():
    t = T(
        """
        a | b
        1 | x
        2 | y
        """
    )
    res = t.with_id_from(t.a)
    res2 = res.select(res.a, res.b)
    assert_table_equality_wo_index(
        res2,
        T(
            """
            a | b
            1 | x
            2 | y
            """
        ),
    )


def test_pointer_from_consistency():
    t = T(
        """
        a
        1
        2
        """
    )
    keyed = t.with_id_from(t.a)
    looked = keyed.ix(keyed.pointer_from(t.a, instance=None), context=t)
    assert_table_equality_wo_index(
        looked,
        T(
            """
            a
            1
            2
            """
        ),
    )


def test_deduplicate():
    t = T(
        """
        v
        1
        2
        5
        3
        """
    )
    res = t.deduplicate(value=pw.this.v, acceptor=lambda new, old: old is None or new > old)
    expected = T(
        """
        v
        5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_argmax_argmin():
    t = T(
        """
        g | v
        a | 1
        a | 5
        b | 7
        """
    )
    res = t.groupby(t.g).reduce(t.g, am=pw.reducers.argmax(t.v))
    rows = __import__("tests.utils", fromlist=["_rows_of"])._rows_of(res)
    assert len(rows) == 2


def test_tuple_reducers():
    t = T(
        """
        g | v
        a | 3
        a | 1
        b | 2
        """
    )
    res = t.groupby(t.g).reduce(t.g, st=pw.reducers.sorted_tuple(t.v))
    expected_rows = {("a", (1, 3)), ("b", (2,))}
    from tests.utils import _rows_of

    rows = set(tuple(v) for v in _rows_of(res).values())
    assert rows == expected_rows


def test_error_value_propagates():
    t = T(
        """
        a | b
        1 | 0
        6 | 3
        """
    )
    res = t.select(d=t.a // t.b)
    from tests.utils import _rows_of

    rows = sorted(_rows_of(res).values(), key=repr)
    assert (2,) in rows
    assert any(v[0] is pw.Error for v in rows)


def test_string_namespace():
    t = T(
        """
        s
        hello
        """
    )
    res = t.select(
        up=t.s.str.upper(), ln=t.s.str.len(), sw=t.s.str.startswith("he")
    )
    from tests.utils import _rows_of

    assert list(_rows_of(res).values()) == [("HELLO", 5, True)]


def test_concat_same_universe_raises_or_works():
    t1 = T(
        """
        id | a
        1  | 1
        """
    )
    t2 = T(
        """
        id | a
        1  | 2
        """
    )
    res = t1.concat_reindex(t2)
    from tests.utils import _rows_of

    assert sorted(_rows_of(res).values()) == [(1,), (2,)]


def test_api_surface_parity_names():
    """Reference top-level exports resolve (pw.asynchronous alias,
    declare_type, datetime annotation types, attach_prober,
    PersistenceMode re-export)."""
    from pathway_tpu.internals import dtype as dt

    for name in (
        "asynchronous", "declare_type", "DateTimeNaive", "DateTimeUtc",
        "Duration", "attach_prober", "PersistenceMode",
    ):
        assert getattr(pw, name) is not None, name
    S = pw.schema_from_types(a=pw.DateTimeNaive, b=pw.DateTimeUtc, c=pw.Duration)
    assert S.__columns__["a"].dtype == dt.DATE_TIME_NAIVE
    assert S.__columns__["b"].dtype == dt.DATE_TIME_UTC
    assert S.__columns__["c"].dtype == dt.DURATION


def test_declare_type_and_prober():
    from pathway_tpu.internals import dtype as dt

    t = T(
        """
    v
    3
    """
    )
    out = t.select(f=pw.declare_type(float, t.v))
    assert out._dtypes["f"] == dt.FLOAT  # declared only, value untouched
    cap = out._capture_node()
    seen = []
    pw.attach_prober(seen.append)  # whole per-epoch snapshots
    ctx = pw.run(monitoring_level="none")
    (row,) = ctx.state(cap)["rows"].values()
    assert row == (3,)
    assert seen  # fired at least once per epoch
    # the SNAPSHOTS carry operator stats (not just the live ctx dicts)
    assert any(
        p["rows_in"] for s in seen for p in s["operators"].values()
    )


def test_table_slice_api():
    """TableSlice (reference internals/table_slice.py): without /
    rename / with_prefix / with_suffix / subsetting, usable in select."""
    t = T(
        """
    a | b | c
    1 | 2 | 3
    """
    )
    s = t.slice
    assert s.keys() == ["a", "b", "c"]
    out = t.select(s.without("b"))
    assert out._column_names == ["a", "c"]
    pre = t.select(t.slice.with_prefix("l_"))
    assert pre._column_names == ["l_a", "l_b", "l_c"]
    ren = t.select(t.slice.rename({"a": "x"})[["x", "c"]])
    assert ren._column_names == ["x", "c"]
    from tests.utils import run_to_rows as _rows

    (row,) = _rows(t.select(t.slice.with_suffix("_r").without("b_r")))
    assert row == (1, 3)
    import pytest as _pytest

    with _pytest.raises(KeyError, match="zz"):
        t.slice.without("zz")
    with _pytest.raises(ValueError, match="collides"):
        t.slice.rename({"a": "b"})  # would silently drop a column
    # swaps are legal
    assert t.slice.rename({"a": "b", "b": "a"}).keys() == ["b", "a", "c"]
    other = T(
        """
    a
    9
    """
    )
    with _pytest.raises(ValueError, match="different table"):
        t.slice.without(other.a)


def test_await_futures_unwraps_dtypes():
    """Table.await_futures (reference parity): async results are already
    concrete in this engine, so only Future dtypes unwrap."""
    from pathway_tpu.internals import dtype as dt

    t = T(
        """
    a
    1
    """
    )
    t2 = t.copy()
    t2._dtypes = {"a": dt.Future(dt.INT)}
    out = t2.await_futures()
    assert out._dtypes["a"] == dt.INT
    from tests.utils import run_to_rows as _rows

    assert _rows(out.select(out.a)) == [(1,)]


class _Blob:
    def __init__(self, tag):
        self.tag = tag

    def __eq__(self, other):
        return isinstance(other, _Blob) and other.tag == self.tag

    def __hash__(self):
        return hash(self.tag)


class _BlobSer:
    @staticmethod
    def dumps(o):
        return o.tag.encode()

    @staticmethod
    def loads(b):
        return _Blob(b.decode() + "!")


def test_py_object_wrapper_through_pipeline():
    """pw.PyObjectWrapper flows through select/groupby/UDFs (reference
    Value::PyObjectWrapper, engine.pyi:895)."""
    Blob = _Blob

    from tests.utils import run_to_rows

    rows = [
        (1, pw.wrap_py_object(Blob("x"))),
        (2, pw.PyObjectWrapper(Blob("x"))),
        (3, pw.wrap_py_object(Blob("y"))),
    ]
    t = pw.debug.table_from_rows(pw.schema_from_types(a=int, o=object), rows)
    # UDF receives the wrapper and can unwrap it
    tagged = t.select(
        t.a, tag=pw.apply(lambda o: o.value.tag, t.o), o=t.o
    )
    g = tagged.groupby(tagged.o).reduce(
        n=pw.reducers.count(), tag=pw.reducers.unique(tagged.tag)
    )
    rows_out = sorted(run_to_rows(g))
    assert rows_out == [(1, "y"), (2, "x")]
    # pickle round trip (persistence path) preserves payload equality
    import pickle

    w = pw.wrap_py_object(Blob("z"))
    assert pickle.loads(pickle.dumps(w)) == w
    # custom serializer is honored
    w2 = pw.wrap_py_object(Blob("q"), serializer=_BlobSer)
    assert pickle.loads(pickle.dumps(w2)).value.tag == "q!"


def test_markdown_stream_replay_is_deterministic_across_tables():
    """Two ``__time__`` markdown tables replay on separate reader
    threads; the shared replay clock must serialize their batches into
    one deterministic epoch schedule (ascending time, construction order
    within a time) — without it, which epoch a row lands in is a thread
    race and any cross-table time assertion flakes."""
    from tests.utils import run_tables

    def one_run() -> list[tuple]:
        pw.G.clear()
        left = T(
            """
            a | __time__ | __diff__
            1 | 2        | 1
            2 | 4        | 1
            """
        )
        right = T(
            """
            b | __time__ | __diff__
            9 | 2        | 1
            8 | 6        | 1
            """
        )
        (_, ls), (_, rs) = run_tables(left, right)
        return sorted(
            (tag, vals, time, diff)
            for tag, stream in (("l", ls), ("r", rs))
            for _k, vals, time, diff in stream
        )

    first = one_run()
    assert first, "replay emitted nothing"
    # the serialized schedule: left@2, right@2, left@4, right@6 — each
    # batch its own epoch, so the four epochs are 0, 2, 4, 6
    assert sorted((tag, time) for tag, _v, time, _d in first) == [
        ("l", 0),
        ("l", 4),
        ("r", 2),
        ("r", 6),
    ]
    for _ in range(4):
        assert one_run() == first
