"""Cluster fault tolerance (ISSUE 8): a seeded kill-a-worker drill must
recover automatically to sink output byte-identical to the fault-free
run; a dead peer must be *detected* within the liveness timeout instead
of hanging a ``recv`` forever; and link teardown must complete in
bounded time even with peers mid-conversation.

The drills go through ``testing.chaos.ClusterDrill`` — the same harness
``bench.py`` uses for the committed recovery numbers — so the test and
the benchmark can never drift apart on what "recovered" means.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from pathway_tpu.testing.chaos import ClusterDrill, IndexDrill, chaos

_port_counter = [13000 + (os.getpid() % 500) * 16]


def next_port(n: int = 4) -> int:
    """A base port with `n` consecutive bindable ports (probed, so stray
    listeners from an earlier killed run can't collide)."""
    import socket

    while True:
        base = _port_counter[0]
        _port_counter[0] += n
        if _port_counter[0] > 60000:
            _port_counter[0] = 13000
        try:
            socks = []
            for i in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
        return base


# ---------------------------------------------------------------------------
# recovery drills


def _run_drill(tmp_path, processes: int, seed: int) -> dict:
    drill = ClusterDrill(str(tmp_path), seed=seed, processes=processes)
    report = drill.run()
    assert report["restarts"] >= 1, (
        f"chaos kill (rank {report['kill_rank']} at epoch "
        f"{report['kill_epoch']}) never triggered a restart: {report}"
    )
    assert report["ok"], f"cluster did not recover: {report['failures']}"
    assert report["identical"], (
        f"recovered sink output diverged from the fault-free run after "
        f"killing rank {report['kill_rank']} at epoch {report['kill_epoch']}:"
        f"\n fault-free: {report['baseline_output']!r}"
        f"\n recovered:  {report['recovered_output']!r}"
    )
    assert report["recovery_seconds"], "no recovery time recorded"
    return report


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [3, 11])
def test_kill_random_worker_2proc_output_identical(tmp_path, seed):
    """Property drill: kill a seeded-random rank at a seeded-random epoch
    on a 2-process cluster; the supervisor restarts the generation, the
    workers roll back to the last consistent checkpoint, and the final
    sink output must byte-match a fault-free run."""
    _run_drill(tmp_path, processes=2, seed=seed)


@pytest.mark.chaos
@pytest.mark.slow
def test_kill_random_worker_4proc_output_identical(tmp_path):
    """The same property at 4 workers — more ranks to kill, more peers
    whose sockets die mid-conversation, same byte-identical bar."""
    _run_drill(tmp_path, processes=4, seed=5)


@pytest.mark.chaos
def test_kill_worker_mid_merge_exactly_once(tmp_path):
    """Live-index churn drill (ISSUE 9): hard-kill the index-owning
    worker in the window between a finished background merge and its
    atomic commit.  The restarted worker restores the checkpointed
    (pre-merge) index and replays the tail; the recovered index must
    hold each doc exactly once — the lost merge dropped nothing, the
    replay double-applied nothing — and final query answers must reach
    recall >= 0.95 vs brute force over the post-churn corpus."""
    drill = IndexDrill(str(tmp_path), seed=7, processes=2)
    report = drill.run()
    assert report["restarts"] >= 1, (
        f"mid-merge kill never triggered a restart: {report}"
    )
    assert report["returncode"] == 0, (
        f"cluster did not recover: {report['failures']}"
    )
    assert report["exactly_once"], (
        f"recovered index holds {report['recovered_size']} docs, expected "
        f"{report['expected_size']} (lost or double-applied upserts): "
        f"{report}"
    )
    assert report["recall"] >= 0.95, (
        f"recovered recall {report['recall']:.3f} < 0.95 "
        f"(baseline {report['baseline_recall']:.3f}): {report}"
    )
    assert report["merges_total"] >= 1, report


# ---------------------------------------------------------------------------
# failure detection latency


def _link_pair(first_port: int, heartbeat_s: float, liveness_timeout_s: float):
    """Both ends of a 2-process TCP mesh, built in one process.  End 0
    blocks in its constructor waiting for end 1 to dial, so it goes on a
    thread."""
    from pathway_tpu.engine.cluster import _ProcessLinks

    out: dict[int, _ProcessLinks] = {}

    def build0() -> None:
        out[0] = _ProcessLinks(
            0,
            2,
            first_port,
            heartbeat_s=heartbeat_s,
            liveness_timeout_s=liveness_timeout_s,
        )

    t = threading.Thread(target=build0, daemon=True)
    t.start()
    out[1] = _ProcessLinks(
        1,
        2,
        first_port,
        heartbeat_s=heartbeat_s,
        liveness_timeout_s=liveness_timeout_s,
    )
    t.join(10.0)
    assert 0 in out, "mesh never completed"
    return out[0], out[1]


@pytest.mark.chaos
def test_muted_peer_detected_within_liveness_timeout():
    """Drop every transmission (heartbeats included) out of process 1;
    process 0 must declare the peer dead within the liveness timeout plus
    one io tick — not hang in ``recv`` forever.  The detector then closes
    its own sockets, so the muted side observes the EOF and fails too
    (socket-death detection, the fast path)."""
    liveness = 1.0
    links0, links1 = _link_pair(
        next_port(2), heartbeat_s=0.2, liveness_timeout_s=liveness
    )
    try:
        with chaos(seed=1) as c:
            c.drop_exchange_frames(after=0, process_id=1)
            t0 = time.monotonic()
            deadline = t0 + liveness + 3.0
            while links0._failed is None and time.monotonic() < deadline:
                time.sleep(0.02)
            detect_s = time.monotonic() - t0
            assert links0._failed is not None, (
                f"muted peer not detected after {detect_s:.1f}s"
            )
            assert "silent" in links0._failed or "lost" in links0._failed
            # bounded detection: liveness timeout + io tick + slack
            assert detect_s < liveness + 2.0, f"detection took {detect_s:.1f}s"
            # the failure must surface to a worker parked on the mailbox
            with pytest.raises(RuntimeError, match="cluster failure"):
                links0.recv_from_all(("never", 0))
            # ... and propagate to the muted side via socket death
            eof_deadline = time.monotonic() + 5.0
            while links1._failed is None and time.monotonic() < eof_deadline:
                time.sleep(0.02)
            assert links1._failed is not None, "peer EOF never detected"
    finally:
        links0.close()
        links1.close()


@pytest.mark.chaos
def test_idle_links_stay_alive_on_heartbeats():
    """The inverse guard: two healthy but completely idle links exchange
    only heartbeats and must NOT false-alarm past the liveness window."""
    liveness = 0.8
    links0, links1 = _link_pair(
        next_port(2), heartbeat_s=0.1, liveness_timeout_s=liveness
    )
    try:
        time.sleep(liveness * 2.5)
        assert links0._failed is None, links0._failed
        assert links1._failed is None, links1._failed
        with links0.stats_lock:
            sent = links0.stats["heartbeats_sent"]
        assert sent >= 1, "idle link never heartbeat"
    finally:
        links0.close()
        links1.close()


# ---------------------------------------------------------------------------
# bounded teardown


@pytest.mark.chaos
def test_close_is_bounded_with_live_peer():
    """``close()`` must return in bounded time — bounded sender joins,
    socket close to break parked reads, bounded re-join — even while the
    peer is still up and mid-heartbeat."""
    links0, links1 = _link_pair(
        next_port(2), heartbeat_s=0.1, liveness_timeout_s=5.0
    )
    links0.send_async(1, ("slot", 0), {"x": 1})  # traffic in flight
    t0 = time.monotonic()
    links0.close()
    links1.close()
    dt = time.monotonic() - t0
    assert dt < 8.0, f"teardown took {dt:.1f}s"
    for links in (links0, links1):
        for sender in links._senders.values():
            assert not sender.is_alive(), "sender thread survived close()"
        for reader in links._readers:
            reader.join(2.0)
            assert not reader.is_alive(), "reader thread survived close()"
