"""Cluster fault tolerance (ISSUE 8): a seeded kill-a-worker drill must
recover automatically to sink output byte-identical to the fault-free
run; a dead peer must be *detected* within the liveness timeout instead
of hanging a ``recv`` forever; and link teardown must complete in
bounded time even with peers mid-conversation.

The drills go through ``testing.chaos.ClusterDrill`` — the same harness
``bench.py`` uses for the committed recovery numbers — so the test and
the benchmark can never drift apart on what "recovered" means.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from pathway_tpu.testing.chaos import ClusterDrill, IndexDrill, chaos

_port_counter = [13000 + (os.getpid() % 500) * 16]


def next_port(n: int = 4) -> int:
    """A base port with `n` consecutive bindable ports (probed, so stray
    listeners from an earlier killed run can't collide)."""
    import socket

    while True:
        base = _port_counter[0]
        _port_counter[0] += n
        if _port_counter[0] > 60000:
            _port_counter[0] = 13000
        try:
            socks = []
            for i in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
        return base


# ---------------------------------------------------------------------------
# recovery drills


def _run_drill(tmp_path, processes: int, seed: int) -> dict:
    drill = ClusterDrill(str(tmp_path), seed=seed, processes=processes)
    report = drill.run()
    assert report["restarts"] >= 1, (
        f"chaos kill (rank {report['kill_rank']} at epoch "
        f"{report['kill_epoch']}) never triggered a restart: {report}"
    )
    assert report["ok"], f"cluster did not recover: {report['failures']}"
    assert report["identical"], (
        f"recovered sink output diverged from the fault-free run after "
        f"killing rank {report['kill_rank']} at epoch {report['kill_epoch']}:"
        f"\n fault-free: {report['baseline_output']!r}"
        f"\n recovered:  {report['recovered_output']!r}"
    )
    assert report["recovery_seconds"], "no recovery time recorded"
    return report


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [3, 11])
def test_kill_random_worker_2proc_output_identical(tmp_path, seed):
    """Property drill: kill a seeded-random rank at a seeded-random epoch
    on a 2-process cluster; the supervisor restarts the generation, the
    workers roll back to the last consistent checkpoint, and the final
    sink output must byte-match a fault-free run."""
    _run_drill(tmp_path, processes=2, seed=seed)


@pytest.mark.chaos
@pytest.mark.slow
def test_kill_random_worker_4proc_output_identical(tmp_path):
    """The same property at 4 workers — more ranks to kill, more peers
    whose sockets die mid-conversation, same byte-identical bar."""
    _run_drill(tmp_path, processes=4, seed=5)


@pytest.mark.chaos
def test_kill_worker_mid_merge_exactly_once(tmp_path):
    """Live-index churn drill (ISSUE 9): hard-kill the index-owning
    worker in the window between a finished background merge and its
    atomic commit.  The restarted worker restores the checkpointed
    (pre-merge) index and replays the tail; the recovered index must
    hold each doc exactly once — the lost merge dropped nothing, the
    replay double-applied nothing — and final query answers must reach
    recall >= 0.95 vs brute force over the post-churn corpus."""
    drill = IndexDrill(str(tmp_path), seed=7, processes=2)
    report = drill.run()
    assert report["restarts"] >= 1, (
        f"mid-merge kill never triggered a restart: {report}"
    )
    assert report["returncode"] == 0, (
        f"cluster did not recover: {report['failures']}"
    )
    assert report["exactly_once"], (
        f"recovered index holds {report['recovered_size']} docs, expected "
        f"{report['expected_size']} (lost or double-applied upserts): "
        f"{report}"
    )
    assert report["recall"] >= 0.95, (
        f"recovered recall {report['recall']:.3f} < 0.95 "
        f"(baseline {report['baseline_recall']:.3f}): {report}"
    )
    assert report["merges_total"] >= 1, report


# ---------------------------------------------------------------------------
# failure detection latency


def _link_pair(first_port: int, heartbeat_s: float, liveness_timeout_s: float):
    """Both ends of a 2-process TCP mesh, built in one process.  End 0
    blocks in its constructor waiting for end 1 to dial, so it goes on a
    thread."""
    from pathway_tpu.engine.cluster import _ProcessLinks

    out: dict[int, _ProcessLinks] = {}

    def build0() -> None:
        out[0] = _ProcessLinks(
            0,
            2,
            first_port,
            heartbeat_s=heartbeat_s,
            liveness_timeout_s=liveness_timeout_s,
        )

    t = threading.Thread(target=build0, daemon=True)
    t.start()
    out[1] = _ProcessLinks(
        1,
        2,
        first_port,
        heartbeat_s=heartbeat_s,
        liveness_timeout_s=liveness_timeout_s,
    )
    t.join(10.0)
    assert 0 in out, "mesh never completed"
    return out[0], out[1]


@pytest.mark.chaos
def test_muted_peer_detected_within_liveness_timeout():
    """Drop every transmission (heartbeats included) out of process 1;
    process 0 must declare the peer dead within the liveness timeout plus
    one io tick — not hang in ``recv`` forever.  The detector then closes
    its own sockets, so the muted side observes the EOF and fails too
    (socket-death detection, the fast path)."""
    liveness = 1.0
    links0, links1 = _link_pair(
        next_port(2), heartbeat_s=0.2, liveness_timeout_s=liveness
    )
    try:
        with chaos(seed=1) as c:
            c.drop_exchange_frames(after=0, process_id=1)
            t0 = time.monotonic()
            deadline = t0 + liveness + 3.0
            while links0._failed is None and time.monotonic() < deadline:
                time.sleep(0.02)
            detect_s = time.monotonic() - t0
            assert links0._failed is not None, (
                f"muted peer not detected after {detect_s:.1f}s"
            )
            assert "silent" in links0._failed or "lost" in links0._failed
            # bounded detection: liveness timeout + io tick + slack
            assert detect_s < liveness + 2.0, f"detection took {detect_s:.1f}s"
            # the failure must surface to a worker parked on the mailbox
            with pytest.raises(RuntimeError, match="cluster failure"):
                links0.recv_from_all(("never", 0))
            # ... and propagate to the muted side via socket death
            eof_deadline = time.monotonic() + 5.0
            while links1._failed is None and time.monotonic() < eof_deadline:
                time.sleep(0.02)
            assert links1._failed is not None, "peer EOF never detected"
    finally:
        links0.close()
        links1.close()


@pytest.mark.chaos
def test_idle_links_stay_alive_on_heartbeats():
    """The inverse guard: two healthy but completely idle links exchange
    only heartbeats and must NOT false-alarm past the liveness window."""
    liveness = 0.8
    links0, links1 = _link_pair(
        next_port(2), heartbeat_s=0.1, liveness_timeout_s=liveness
    )
    try:
        time.sleep(liveness * 2.5)
        assert links0._failed is None, links0._failed
        assert links1._failed is None, links1._failed
        with links0.stats_lock:
            sent = links0.stats["heartbeats_sent"]
        assert sent >= 1, "idle link never heartbeat"
    finally:
        links0.close()
        links1.close()


# ---------------------------------------------------------------------------
# bounded teardown


@pytest.mark.chaos
def test_close_is_bounded_with_live_peer():
    """``close()`` must return in bounded time — bounded sender joins,
    socket close to break parked reads, bounded re-join — even while the
    peer is still up and mid-heartbeat."""
    links0, links1 = _link_pair(
        next_port(2), heartbeat_s=0.1, liveness_timeout_s=5.0
    )
    links0.send_async(1, ("slot", 0), {"x": 1})  # traffic in flight
    t0 = time.monotonic()
    links0.close()
    links1.close()
    dt = time.monotonic() - t0
    assert dt < 8.0, f"teardown took {dt:.1f}s"
    for links in (links0, links1):
        for sender in links._senders.values():
            assert not sender.is_alive(), "sender thread survived close()"
        for reader in links._readers:
            reader.join(2.0)
            assert not reader.is_alive(), "reader thread survived close()"


# ---------------------------------------------------------------------------
# per-peer membership under the isolate fail policy (ISSUE 13)


def _isolate_link_pair(
    first_port: int,
    heartbeat_s: float = 0.1,
    liveness_timeout_s: float = 1.0,
):
    """2-process mesh with ``fail_policy='isolate'``: a peer's death
    quiesces only that peer's links instead of failing the whole mesh."""
    from pathway_tpu.engine.cluster import _ProcessLinks

    out: dict[int, "_ProcessLinks"] = {}

    def build0() -> None:
        out[0] = _ProcessLinks(
            0,
            2,
            first_port,
            heartbeat_s=heartbeat_s,
            liveness_timeout_s=liveness_timeout_s,
            fail_policy="isolate",
        )

    t = threading.Thread(target=build0, daemon=True)
    t.start()
    out[1] = _ProcessLinks(
        1,
        2,
        first_port,
        heartbeat_s=heartbeat_s,
        liveness_timeout_s=liveness_timeout_s,
        fail_policy="isolate",
    )
    t.join(10.0)
    assert 0 in out, "mesh never completed"
    return out[0], out[1]


def _wait_for(pred, timeout_s: float, what: str) -> None:
    deadline = time.monotonic() + timeout_s
    while not pred():
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        time.sleep(0.02)


@pytest.mark.chaos
def test_isolate_peer_death_degrades_instead_of_failing():
    """One peer dies; the isolate-policy survivor marks ONLY that peer
    dead (``_failed`` stays None — the mesh is degraded, not down) and a
    collective over the survivors returns instead of raising."""
    from pathway_tpu.engine.cluster import PEER_DEAD

    links0, links1 = _isolate_link_pair(next_port(2))
    try:
        links1.close()  # rank 1 "dies": its sockets drop
        _wait_for(
            lambda: links0.peer_states().get(1) == PEER_DEAD,
            8.0,
            "survivor to declare peer 1 dead",
        )
        assert links0._failed is None, (
            f"isolate policy failed the whole mesh: {links0._failed}"
        )
        member = links0.membership()[1]
        assert member["state"] == PEER_DEAD and member["reason"]
        # a collective over zero live peers degrades to the empty answer
        assert links0.recv_from_all(("epoch", 0)) == {}
        assert links0.stats["peers_declared_dead"] == 1
    finally:
        links0.close()


@pytest.mark.chaos
def test_isolate_rejoin_with_bumped_incarnation():
    """A replacement rank dialing with a bumped incarnation is admitted
    by the survivor (generation handshake), after which both directions
    of the link carry traffic again and the membership view heals."""
    from pathway_tpu.engine.cluster import PEER_ALIVE, PEER_DEAD, _ProcessLinks

    first_port = next_port(2)
    links0, links1 = _isolate_link_pair(first_port)
    replacement = None
    try:
        links1.close()
        _wait_for(
            lambda: links0.peer_states().get(1) == PEER_DEAD,
            8.0,
            "survivor to declare peer 1 dead",
        )
        # in-process rebind gotcha: the dead listener's fd lingers until
        # its 1s accept timeout elapses (a real dead rank is a separate
        # process whose fds close on exit), so give the port time to free
        time.sleep(1.3)
        for attempt in range(10):
            try:
                replacement = _ProcessLinks(
                    1,
                    2,
                    first_port,
                    heartbeat_s=0.1,
                    liveness_timeout_s=1.0,
                    fail_policy="isolate",
                    incarnation=1,
                )
                break
            except OSError:
                time.sleep(0.5)
        assert replacement is not None, "replacement never bound its port"
        _wait_for(
            lambda: links0.peer_states().get(1) == PEER_ALIVE,
            8.0,
            "survivor to admit the rejoining rank",
        )
        assert links0.membership()[1]["incarnation"] == 1
        assert links0.stats["peers_rejoined"] == 1
        # traffic flows both ways across the healed link
        links0.send_async(1, ("x", 0), {"hello": 0})
        replacement.send_async(0, ("x", 0), {"hello": 1})
        got0 = links0.recv_from_all(("x", 0))
        got1 = replacement.recv_from_all(("x", 0))
        assert got0 == {1: {"hello": 1}} and got1 == {0: {"hello": 0}}
    finally:
        links0.close()
        if replacement is not None:
            replacement.close()


@pytest.mark.chaos
def test_asymmetric_partition_is_detected_not_hung():
    """Gray failure: ONE direction of one link goes dark (1 -> 0 frames
    dropped, 0 -> 1 perfect).  The starved side must still classify the
    silent peer dead within the liveness window — and under the isolate
    policy neither side fails its whole mesh."""
    from pathway_tpu.engine.cluster import PEER_DEAD

    liveness = 1.0
    links0, links1 = _isolate_link_pair(
        next_port(2), heartbeat_s=0.2, liveness_timeout_s=liveness
    )
    try:
        with chaos(seed=5) as c:
            c.asymmetric_partition(1, 0, mode="drop")
            t0 = time.monotonic()
            _wait_for(
                lambda: links0.peer_states().get(1) == PEER_DEAD,
                liveness + 4.0,
                "starved side to declare the silent peer dead",
            )
            detect_s = time.monotonic() - t0
            assert detect_s < liveness + 2.0, (
                f"one-way partition detection took {detect_s:.1f}s"
            )
            assert links0._failed is None and links1._failed is None
    finally:
        links0.close()
        links1.close()


@pytest.mark.chaos
def test_slow_peer_degrades_but_stays_alive():
    """A slowed (but alive) rank keeps making its liveness deadlines:
    seeded per-frame delay below the suspect threshold must not get the
    peer declared dead, and its frames still arrive."""
    from pathway_tpu.engine.cluster import PEER_DEAD

    links0, links1 = _isolate_link_pair(
        next_port(2), heartbeat_s=0.1, liveness_timeout_s=2.0
    )
    try:
        with chaos(seed=9) as c:
            c.slow_peer(1, delay_s=0.05, jitter_s=0.02)
            links1.send_async(0, ("y", 0), {"v": 42})
            got = links0.recv_from_all(("y", 0))
            assert got == {1: {"v": 42}}
            time.sleep(0.5)  # several heartbeat intervals under the delay
            assert links0.peer_states().get(1) != PEER_DEAD
            assert links0._failed is None
    finally:
        links0.close()
        links1.close()


# ---------------------------------------------------------------------------
# flight-recorder dumps under chaos (ISSUE 14)


def _load_merged_trace(report: dict) -> list[dict]:
    import json

    trace_file = report["trace_file"]
    assert trace_file and os.path.exists(trace_file), (
        f"no merged flight-recorder dump: {report}"
    )
    with open(trace_file) as f:
        return json.load(f)["traceEvents"]


@pytest.mark.chaos
def test_kill_worker_flight_recorder_stitches_all_ranks(tmp_path):
    """A traced 2-proc kill drill must leave ONE merged Chrome-trace
    file holding spans from every rank — including the killed one (the
    chaos kill flushes the ring before ``os._exit``) — with epoch traces
    stitched across processes on the shared monotonic timebase and
    exchange spans naming both sides (src + dst)."""
    from pathway_tpu.analysis import tracecrit

    drill = ClusterDrill(str(tmp_path), seed=3, processes=2, trace=True)
    report = drill.run()
    assert report["restarts"] >= 1, report
    assert report["ok"], f"cluster did not recover: {report['failures']}"
    events = _load_merged_trace(report)
    ranks = {int(e.get("pid", -1)) for e in events}
    assert ranks == {0, 1}, f"merged dump missing ranks: {sorted(ranks)}"
    assert report["kill_rank"] in ranks
    assert sorted(report["trace_ranks"]) == [0, 1]
    # cross-process stitch: at least one epoch trace carries spans
    # recorded by BOTH ranks under one trace id, and its parent chain
    # resolves (no orphaned fragments)
    traces = tracecrit.group_traces(events)
    multi = [
        tid for tid, spans in traces.items()
        if len({s.get("pid") for s in spans}) >= 2
    ]
    assert multi, "no trace stitched spans from more than one rank"
    conn = tracecrit.connected_traces(events)
    assert any(conn[tid] for tid in multi), (
        "every cross-rank trace has orphaned parents"
    )
    exch = [
        e for e in events
        if e["name"] in ("pack", "unpack", "exchange_recv", "status_wait_peer")
    ]
    assert exch, "no exchange spans survived into the dump"
    for e in exch:
        assert {"src", "dst"} <= set(e["args"]), e


@pytest.mark.chaos
def test_kill_worker_mid_merge_flight_recorder_dump(tmp_path):
    """The mid-merge kill drill (ISSUE 9 harness) with tracing on: the
    merged dump must exist and hold spans from every rank including the
    one hard-killed inside the merge-commit window."""
    drill = IndexDrill(str(tmp_path), seed=7, processes=2, trace=True)
    report = drill.run()
    assert report["restarts"] >= 1, report
    assert report["returncode"] == 0, report["failures"]
    events = _load_merged_trace(report)
    ranks = {int(e.get("pid", -1)) for e in events}
    assert ranks == {0, 1}, f"merged dump missing ranks: {sorted(ranks)}"
    assert drill.kill_rank in ranks
    # the dump is usable for attribution: spans have positive-duration
    # complete events with span identity in args
    assert all(e.get("ph") == "X" for e in events)
    assert all("span_id" in e.get("args", {}) for e in events)
