"""gradual_broadcast operator + multi-level Louvain communities
(reference ``src/engine/dataflow/operators/gradual_broadcast.rs`` and
``python/pathway/stdlib/graphs/louvain_communities/impl.py``)."""

import itertools

import pathway_tpu as pw
from pathway_tpu.stdlib.graphs import (
    WeightedGraph,
    exact_modularity,
    louvain_communities,
    louvain_level,
)
from tests.utils import T


def test_gradual_broadcast_appends_apx_value():
    t = T(
        """
        a
        1
        2
        3
        """
    )
    th = t.reduce(m=pw.reducers.sum(pw.this.a)).select(
        lower=pw.apply(lambda m: m - 1.0, pw.this.m),
        value=pw.apply(float, pw.this.m),
        upper=pw.apply(lambda m: m + 1.0, pw.this.m),
    )
    b = t._gradual_broadcast(th, th.lower, th.value, th.upper)
    _, cols = pw.debug.table_to_dicts(b)
    assert set(cols["apx_value"].values()) == {6.0}
    assert sorted(cols["a"].values()) == [1, 2, 3]


def test_gradual_broadcast_damps_churn():
    """Rows only re-emit when their held value leaves the new window —
    a triplet move WITHIN the window must not retract anything."""
    t = T(
        """
        a | __time__ | __diff__
        1 | 2        | 1
        2 | 2        | 1
        """
    )
    # threshold stream: (5, 6, 8) at t=2, then (5, 7, 8) at t=4 (inside
    # the old window), then (20, 21, 22) at t=6 (outside)
    th = T(
        """
        lower | value | upper | __time__ | __diff__
        5.0   | 6.0   | 8.0   | 2        | 1
        5.0   | 7.0   | 8.0   | 4        | 1
        20.0  | 21.0  | 22.0  | 6        | 1
        """
    )
    b = t._gradual_broadcast(th, th.lower, th.value, th.upper)
    from tests.utils import stream_rows

    stream = stream_rows(b)
    # apx per row: 6.0 at t=2 (held through the t=4 update — inside
    # [5, 8]), then 21.0 at t=6
    apx_changes = [
        (vals[-1], time, diff) for _k, vals, time, diff in stream
    ]
    assert (6.0, 2, 1) in apx_changes
    # no churn at t=4: nothing retracted/emitted then
    assert not any(time == 4 for _v, time, _d in apx_changes)
    assert (6.0, 6, -1) in apx_changes
    assert (21.0, 6, 1) in apx_changes


def _two_cliques():
    rows = []
    for members in (range(5), range(5, 10)):
        for u, v in itertools.combinations(members, 2):
            rows.append((u, v, 1.0))
    rows.append((0, 5, 0.1))  # weak bridge
    return pw.debug.table_from_rows(
        pw.schema_from_types(u=int, v=int, weight=float), rows
    )


def test_louvain_communities_two_cliques():
    G = WeightedGraph(_two_cliques())
    lc = louvain_communities(G, levels=2)
    keys, cols = pw.debug.table_to_dicts(lc.final_clustering)
    assign = {cols["v"][k]: cols["c"][k] for k in keys}
    assert len(assign) == 10
    c_a = {assign[i] for i in range(5)}
    c_b = {assign[i] for i in range(5, 10)}
    assert len(c_a) == 1 and len(c_b) == 1 and c_a != c_b

    # hierarchical clustering has every vertex at level 0 and parents above
    _, hcols = pw.debug.table_to_dicts(lc.hierarchical_clustering)
    assert set(hcols["level"].values()) == {0, 1, 2}

    # community quality: known-good modularity for two 5-cliques + bridge
    _, mcols = pw.debug.table_to_dicts(exact_modularity(G, lc.final_clustering))
    (q,) = mcols["modularity"].values()
    assert q > 0.45


def test_louvain_level_with_gradual_total_weight():
    from pathway_tpu.stdlib.graphs import _approximate_total_weight

    edges = _two_cliques()
    tw = _approximate_total_weight(edges)
    c = louvain_level(WeightedGraph(edges), total_weight=tw)
    keys, cols = pw.debug.table_to_dicts(c)
    assign = {cols["node"][k]: cols["community"][k] for k in keys}
    assert len({assign[i] for i in range(5)}) == 1
    assert len({assign[i] for i in range(5, 10)}) == 1
