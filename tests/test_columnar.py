"""Columnar execution differential properties (ISSUE 19).

The columnar path is an optimization of REPRESENTATION only: frames
through native kernels must be observably identical to the row path.
Three layers of evidence:

- randomized typed batches (None/Optional cells, interned and
  non-interned strings, retractions) through :class:`ColumnarBatch`
  seams (split, extend_batch, iteration order);
- the cluster wire codec: ``_K_FRAME`` encode/decode symmetry with the
  per-transmission string pool, including the row-materializing
  fallback;
- whole pipelines: the same graph at ``optimize=0`` and ``optimize=2``,
  at 1 and 2 workers, columnar on vs ``PATHWAY_DISABLE_COLUMNAR=1`` —
  captured rows (keys included) must match exactly.

Kernel-level parity (roundtrip, route_split, groupby partials,
project/filter, pack/unpack, truncation fuzz) lives in
``tests/test_native.py`` so the sanitizer jobs cover it.
"""

from __future__ import annotations

import json
import random
import struct

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.rewrite import optimize_graph
from pathway_tpu.engine.cluster import Cluster, _PeerSender, _ProcessLinks
from pathway_tpu.engine.columnar import ColumnarBatch, extend_batch
from pathway_tpu.engine.graph import CaptureNode
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.engine.stream import Update
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(scope="module")
def mod():
    m = _native.load()
    if m is None:
        pytest.skip("native extension unavailable (no g++?)")
    m.set_pointer_type(K.Pointer)
    return m


def _rand_rows(rng: random.Random, n: int) -> list:
    pool = ["alpha", "beta", "überstr", ""]
    rows = []
    for i in range(n):
        s = (
            rng.choice(pool)
            if rng.random() < 0.6
            else "s%d" % rng.randrange(10**6)
        )
        vals = (
            rng.randrange(-(2**40), 2**40),
            None if rng.random() < 0.2 else rng.random() * 100 - 50,
            s,
            None if rng.random() < 0.3 else s + "!",
            rng.random() < 0.5,
        )
        rows.append(
            Update(
                K.Pointer(K.ref_scalar("r", i)),
                vals,
                -1 if rng.random() < 0.25 else 1,
            )
        )
    return rows


# ---------------------------------------------------------------------------
# ColumnarBatch seams


def test_batch_protocol_and_split(mod):
    rng = random.Random(17)
    rows = _rand_rows(rng, 300)
    cap = mod.frame_from_updates(rows[:200])
    cb = ColumnarBatch()
    cb.append_frame(cap)
    cb.extend(rows[200:])
    assert len(cb) == 300 and bool(cb)
    assert list(cb) == rows and cb.to_list() == rows
    assert cb.frame_rows() == 200
    for cut in (0, 1, 57, 200, 250, 300):
        head, tail = cb.split(cut)
        assert head.to_list() + tail.to_list() == rows
        assert len(head) == cut


def test_extend_batch_promotes_and_preserves_order(mod):
    rng = random.Random(23)
    rows = _rand_rows(rng, 120)
    cap = mod.frame_from_updates(rows[40:80])
    buf: list = list(rows[:40])
    more = ColumnarBatch()
    more.append_frame(cap)
    buf = extend_batch(buf, more)
    assert isinstance(buf, ColumnarBatch)
    buf = extend_batch(buf, rows[80:])
    assert buf.to_list() == rows
    # list buffer + list more stays a plain list (no gratuitous wrapping)
    assert extend_batch([1], [2]) == [1, 2]


# ---------------------------------------------------------------------------
# wire codec: _K_FRAME transmission symmetry


def _codec_roundtrip(mod, items):
    """Encode one transmission exactly as _PeerSender does, decode it
    exactly as the reader thread does."""
    buf = bytearray(b"\x00" * 12)
    txpool = mod.frame_txpool_new()
    from pathway_tpu.engine.cluster import _K_FRAME

    for slot, kind, payload in items:
        _PeerSender._encode_msg(buf, slot, kind, payload, mod, txpool)
    struct.pack_into("<QI", buf, 0, len(buf) - 8, len(items))
    return _ProcessLinks._decode(memoryview(bytes(buf))[8:], mod)


def test_frame_wire_codec_symmetry(mod):
    from pathway_tpu.engine.cluster import _K_FRAME

    rng = random.Random(31)
    rows = _rand_rows(rng, 400)
    cb0 = ColumnarBatch()
    cb0.append_frame(mod.frame_from_updates(rows[:150]))
    cb0.extend(rows[150:180])  # mixed frame+row segments in one box
    cb1 = ColumnarBatch()
    cb1.append_frame(mod.frame_from_updates(rows[180:300]))
    boxes = [[cb0, cb1, rows[300:350], []]]  # CB, CB, plain rows, empty
    out = _codec_roundtrip(
        mod, [("slot", _K_FRAME, boxes), ("s2", _K_FRAME, [[rows[350:]]])]
    )
    assert len(out) == 2
    slot, decoded, nbytes = out[0]
    assert slot == "slot" and nbytes > 0
    (drow,) = decoded
    assert isinstance(drow[0], ColumnarBatch)
    assert drow[0].frame_rows() == 150  # zero-copy: frames stay frames
    assert drow[0].to_list() == rows[:180]
    assert drow[1].to_list() == rows[180:300]
    assert drow[2] == rows[300:350]  # pure row box decodes to plain list
    assert drow[3] == []
    assert out[1][1][0][0] == rows[350:]


# ---------------------------------------------------------------------------
# whole-pipeline differential: optimize levels x workers x columnar


class _Ev(pw.Schema):
    word: str
    n: int
    x: float


def _write_events(tmp_path, n=400) -> str:
    rng = random.Random(29)
    fp = tmp_path / "events.jsonl"
    fp.write_text(
        "\n".join(
            json.dumps(
                {
                    "word": "w%d" % rng.randint(0, 15),
                    "n": rng.randint(-20, 20),
                    "x": rng.random() * 10 - 5,
                }
            )
            for _ in range(n)
        )
    )
    return str(fp)


def _build_frame_chain(fp):
    # jsonlines (frame parse) -> filter (frame_filter) -> projection
    # (frame_project) -> groupby (frame partials): the full fast chain
    t = pw.io.jsonlines.read(fp, schema=_Ev, mode="static")
    flt = t.filter(t.n >= 0)
    proj = flt.select(flt.x, flt.word)
    return proj.groupby(proj.word).reduce(
        proj.word, s=pw.reducers.sum(proj.x), c=pw.reducers.count()
    )


def _build_udf_fallback(fp):
    # a python UDF keeps its operator on the row path while neighbors
    # stay columnar — the per-operator materialization seam
    t = pw.io.jsonlines.read(fp, schema=_Ev, mode="static")
    u = t.select(t.word, z=pw.apply(lambda n, x: n * 2 + int(x), t.n, t.x))
    return u.groupby(u.word).reduce(u.word, s=pw.reducers.sum(u.z))


PIPELINES = {"frame_chain": _build_frame_chain, "udf_fallback": _build_udf_fallback}


def _assert_same(a: dict, b: dict, msg: str) -> None:
    """Exact equality except float cells, which get ULP-scale tolerance:
    native frame partials accumulate f64 sums in segment order, which is
    not the row path's iteration order, and float addition is not
    associative."""
    assert a.keys() == b.keys(), msg
    for k, va in a.items():
        vb = b[k]
        assert len(va) == len(vb), f"{msg}: {k}"
        for ca, cb in zip(va, vb):
            if isinstance(ca, float):
                assert cb == pytest.approx(ca, rel=1e-9, abs=1e-9), f"{msg}: {k}"
            else:
                assert ca == cb, f"{msg}: {k}"


def _run(build, fp, level: int, n_threads: int) -> dict:
    G.clear()
    table = build(fp)
    cap = CaptureNode(G.engine_graph, table._node)
    exec_graph, _plan = optimize_graph(G.engine_graph, level)
    sched = Scheduler(exec_graph, autocommit_ms=10)
    cluster = Cluster(threads=n_threads)
    try:
        ctx = sched.run_cluster(cluster)
    finally:
        cluster.close()
    return dict(ctx.state(cap)["rows"])


@pytest.mark.parametrize("n_threads", [1, 2])
@pytest.mark.parametrize("name", sorted(PIPELINES))
def test_columnar_row_equivalence(tmp_path, monkeypatch, mod, name, n_threads):
    fp = _write_events(tmp_path)
    build = PIPELINES[name]
    results = {}
    for tag, disabled, level in (
        ("col0", False, 0),
        ("col2", False, 2),
        ("row0", True, 0),
        ("row2", True, 2),
    ):
        if disabled:
            monkeypatch.setenv("PATHWAY_DISABLE_COLUMNAR", "1")
        else:
            monkeypatch.delenv("PATHWAY_DISABLE_COLUMNAR", raising=False)
        results[tag] = _run(build, fp, level, n_threads)
    _assert_same(results["col0"], results["row0"], f"{name}: optimize=0 diverged")
    _assert_same(results["col2"], results["row2"], f"{name}: optimize=2 diverged")
    _assert_same(results["col0"], results["col2"], f"{name}: levels diverged")
    assert results["col0"], f"{name}: empty capture"


def test_columnar_rows_counter_and_plan(tmp_path, monkeypatch, mod):
    """The runtime counter attributes rows to the path they ran, and the
    plan records every operator's decision with a fallback reason."""
    fp = _write_events(tmp_path)
    monkeypatch.delenv("PATHWAY_DISABLE_COLUMNAR", raising=False)
    G.clear()
    table = _build_frame_chain(fp)
    CaptureNode(G.engine_graph, table._node)
    exec_graph, plan = optimize_graph(G.engine_graph, 2)
    text = plan.format()
    assert "columnar:" in text
    assert any(p == "columnar" for _n, p, _r in plan.columnar)
    sched = Scheduler(exec_graph, autocommit_ms=10)
    cluster = Cluster(threads=1)
    try:
        ctx = sched.run_cluster(cluster)
    finally:
        cluster.close()
    cr = ctx.stats.get("columnar_rows", {})
    assert cr.get("columnar", 0) > 0, cr
    # UDF graph: the fallback reason is visible per operator
    G.clear()
    table = _build_udf_fallback(fp)
    CaptureNode(G.engine_graph, table._node)
    _g, plan = optimize_graph(G.engine_graph, 2)
    assert any(
        p == "row" and r for _n, p, r in plan.columnar
    ), plan.format()
