"""End-to-end YAML RAG app: a whole DocumentStore + TPU embedder + QA
server instantiated from an ``app.yaml`` via ``pw.load_yaml``, served
over REST, queried, and scored with the rag_eval metrics — mirroring
``/root/reference/integration_tests/rag_evals/app.yaml`` +
``test_eval.py`` (the reference deploys and evaluates complete RAG apps
from a single YAML file; round-4 verdict item 6's done criterion).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import textwrap
import time
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.xpacks.llm.rag_eval import RagEvalItem, evaluate_retrieval

DOCS = {
    "orchard.txt": "Apples grow on trees in the orchard near the river.",
    "banana.txt": "Bananas are yellow tropical fruit rich in potassium.",
    "tpu.txt": "The TPU systolic array executes matrix multiplications.",
    "bread.txt": "Sourdough bread needs a mature starter and patience.",
    "ocean.txt": "The ocean tide follows the moon's gravitational pull.",
}

APP_YAML = """
$sources:
  - !pw.io.fs.read
    path: {docs_dir}
    format: binary
    mode: static
    with_metadata: true

$llm: !yamlapp_helpers.ContextEchoChat

$embedder: !pw.xpacks.llm.embedders.TPUEncoderEmbedder
  config: !pw.models.encoder.EncoderConfig
    layers: 2
    hidden: 64
    heads: 4
    mlp_dim: 128
    dtype: float32

$splitter: !pw.xpacks.llm.splitters.TokenCountSplitter
  min_tokens: 1
  max_tokens: 100

$parser: !pw.xpacks.llm.parsers.ParseUtf8

$retriever_factory: !pw.stdlib.indexing.BruteForceKnnFactory
  reserved_space: 64
  embedder: $embedder

$document_store: !pw.xpacks.llm.document_store.DocumentStore
  docs: $sources
  parser: $parser
  splitter: $splitter
  retriever_factory: $retriever_factory

question_answerer: !pw.xpacks.llm.question_answering.BaseRAGQuestionAnswerer
  llm: $llm
  indexer: $document_store
  search_topk: 2

host: "127.0.0.1"
port: {port}
"""

HELPER_MODULE = '''
"""Deterministic chat for the YAML app test: answers with the first
context passage, so answer quality reflects retrieval quality."""
from pathway_tpu.xpacks.llm.llms import BaseChat


class ContextEchoChat(BaseChat):
    def __wrapped__(self, messages, **kwargs):
        content = messages[0]["content"] if messages else ""
        # prompt_qa_geometric_rag embeds retrieval as
        # "Documents:\\n<doc>\\n\\n<doc>\\n\\nQuestion: ..." — echo the
        # top-ranked document, so answer quality == retrieval quality
        if "Documents:" in content:
            after = content.split("Documents:", 1)[1]
            after = after.split("Question:", 1)[0]
            first = next((p for p in after.split("\\n") if p.strip()), "")
            return first.strip()
        return content[:100]
'''


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, payload: dict, timeout: float = 5.0) -> dict | list:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_yaml_rag_app_end_to_end(tmp_path):
    docs_dir = tmp_path / "docs"
    docs_dir.mkdir()
    for name, text in DOCS.items():
        (docs_dir / name).write_text(text)
    helper = tmp_path / "yamlapp_helpers.py"
    helper.write_text(HELPER_MODULE)
    sys.path.insert(0, str(tmp_path))
    try:
        port = _free_port()
        pw.G.clear()
        app = pw.load_yaml(
            APP_YAML.format(docs_dir=str(docs_dir), port=port)
        )
        qa = app["question_answerer"]
        thread = qa.run_server(app["host"], app["port"], threaded=True)
        assert thread is not None

        base = f"http://127.0.0.1:{port}"
        # wait for the server + index build
        deadline = time.monotonic() + 60
        docs_listed = None
        while time.monotonic() < deadline:
            try:
                docs_listed = _post(f"{base}/v1/pw_list_documents", {})
                if docs_listed:
                    break
            except Exception:
                time.sleep(0.3)
        assert docs_listed, "server did not come up with documents"
        assert len(docs_listed) == len(DOCS)

        # retrieval + answering scored with the rag_eval metrics
        # the YAML app's embedder is an untrained tiny encoder, so
        # similarity tracks token overlap: questions share distinctive
        # tokens with exactly one document each
        items = [
            RagEvalItem(
                "do apples grow on trees in the orchard?",
                {"orchard.txt"},
                expected_answer=DOCS["orchard.txt"],
            ),
            RagEvalItem(
                "does the TPU systolic array execute matrix multiplications?",
                {"tpu.txt"},
                expected_answer=DOCS["tpu.txt"],
            ),
            RagEvalItem(
                "does the ocean tide follow the moon?",
                {"ocean.txt"},
                expected_answer=DOCS["ocean.txt"],
            ),
        ]

        def retrieve(question: str, k: int) -> list[str]:
            out = _post(
                f"{base}/v1/retrieve",
                {"query": question, "k": k},
            )
            return [
                os.path.basename(d["metadata"].get("path", "")) for d in out
            ]

        def answer(question: str) -> str:
            out = _post(
                f"{base}/v1/pw_ai_answer",
                {"prompt": question},
            )
            return str(out.get("response", out) if isinstance(out, dict) else out)

        report = evaluate_retrieval(items, retrieve, k=2, answer=answer)
        assert report.recall_at_k >= 0.66, report
        assert report.answer_f1 is not None and report.answer_f1 >= 0.4, report
    finally:
        sys.path.remove(str(tmp_path))
        from pathway_tpu.internals.parse_graph import G

        sched = getattr(G, "active_scheduler", None)
        if sched is not None:
            sched.stop()
