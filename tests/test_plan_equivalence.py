"""Equivalence property: the optimizer must never change observable output.

Each representative graph (wordcount, windowed groupby, join+filter+
groupby, UDF mix) is built twice over identical seeded inputs — once at
optimize level 0 (graph untouched) and once at level 2 (the full
pipeline: constant folding, dead-column elimination, select/filter
fusion, append-only specialization, join pushdowns) — and run at 1 and
2 thread workers.  The captured rows, keys included, must match exactly.
"""

from __future__ import annotations

import json
import random

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.rewrite import optimize_graph
from pathway_tpu.engine.cluster import Cluster
from pathway_tpu.engine.graph import CaptureNode
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.stdlib import temporal


class _Words(pw.Schema):
    word: str


class _Events(pw.Schema):
    k: str
    t: int
    a: int
    b: int


def _write_words(tmp_path) -> str:
    rng = random.Random(11)
    fp = tmp_path / "words.jsonl"
    fp.write_text(
        "\n".join(
            json.dumps({"word": "w%d" % rng.randint(0, 12)}) for _ in range(120)
        )
    )
    return str(fp)


def _write_events(tmp_path, name: str, seed: int, n: int = 80) -> str:
    rng = random.Random(seed)
    fp = tmp_path / name
    fp.write_text(
        "\n".join(
            json.dumps(
                {
                    "k": rng.choice("abcde"),
                    "t": rng.randint(0, 99),
                    "a": rng.randint(-30, 30),
                    "b": rng.randint(0, 9),
                }
            )
            for _ in range(n)
        )
    )
    return str(fp)


def _build_wordcount(files):
    # select chain with a dead column: exercises DCE + select fusion
    lines = pw.io.jsonlines.read(files["words"], schema=_Words, mode="static")
    counts = lines.groupby(lines.word).reduce(lines.word, n=pw.reducers.count())
    mid = counts.select(counts.word, n=counts.n, dead=counts.n * 100 + 1)
    return mid.select(mid.word, out=mid.n + 6)


def _build_windowed_groupby(files):
    t = pw.io.jsonlines.read(files["main"], schema=_Events, mode="static")
    return t.windowby(t.t, window=temporal.tumbling(duration=10)).reduce(
        start=pw.this._pw_window_start,
        n=pw.reducers.count(),
        hi=pw.reducers.max(pw.this.a),
    )


def _build_join_filter(files):
    # exercises filter pushdown + append-only groupby specialization
    t = pw.io.jsonlines.read(files["main"], schema=_Events, mode="static")
    s = pw.io.jsonlines.read(files["side"], schema=_Events, mode="static")
    j = t.join(s, t.k == s.k).select(k=pw.left.k, a=pw.left.a, b=pw.right.b)
    f = j.filter(j.a > 0)
    return f.groupby(f.k).reduce(
        f.k, lo=pw.reducers.min(f.a), n=pw.reducers.count()
    )


def _build_udf_mix(files):
    # CALL_PY stages must survive untouched next to fusable pure stages
    t = pw.io.jsonlines.read(files["main"], schema=_Events, mode="static")
    u = t.select(t.k, z=pw.apply(lambda a, b: a * 2 + b, t.a, t.b), b=t.b)
    f = u.filter(u.z != 0)
    chained = f.select(f.k, y=f.z + 1, dead=f.b * 3)
    return chained.select(pw.this.k, pw.this.y)


GRAPHS = {
    "wordcount": _build_wordcount,
    "windowed_groupby": _build_windowed_groupby,
    "join_filter": _build_join_filter,
    "udf_mix": _build_udf_mix,
}


def _files(tmp_path):
    return {
        "words": _write_words(tmp_path),
        "main": _write_events(tmp_path, "main.jsonl", 23),
        "side": _write_events(tmp_path, "side.jsonl", 41, n=12),
    }


def _run(build, files, level: int, n_threads: int) -> dict:
    G.clear()
    table = build(files)
    cap = CaptureNode(G.engine_graph, table._node)
    exec_graph, _plan = optimize_graph(G.engine_graph, level)
    sched = Scheduler(exec_graph, autocommit_ms=10)
    cluster = Cluster(threads=n_threads)
    try:
        ctx = sched.run_cluster(cluster)
    finally:
        cluster.close()
    return dict(ctx.state(cap)["rows"])


@pytest.mark.parametrize("n_threads", [1, 2])
@pytest.mark.parametrize("name", sorted(GRAPHS))
def test_optimizer_output_equivalence(tmp_path, name, n_threads):
    files = _files(tmp_path)
    build = GRAPHS[name]
    plain = _run(build, files, 0, n_threads)
    optimized = _run(build, files, 2, n_threads)
    assert optimized == plain, (
        f"{name}: optimize=2 diverged from optimize=0 at {n_threads} worker(s)"
    )
    assert plain, f"{name}: empty capture — graph produced no rows"
