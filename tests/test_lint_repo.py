"""Tier-1 smoke tests for the repo lint gate (``scripts/lint_repo.sh``).

The ruff check itself only runs where ruff is installed; everywhere else
the script's documented SKIP behavior is what gets verified.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_repo.sh"


def _ruff_available() -> bool:
    if shutil.which("ruff"):
        return True
    proc = subprocess.run(
        ["python", "-c", "import ruff"], capture_output=True
    )
    return proc.returncode == 0


def test_skip_exit_codes_without_ruff():
    if _ruff_available():
        pytest.skip("ruff installed; skip-path not reachable")
    proc = subprocess.run(
        ["bash", str(SCRIPT)], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    assert "SKIP" in proc.stderr
    strict = subprocess.run(
        ["bash", str(SCRIPT)],
        capture_output=True,
        text=True,
        env={"PATH": "/usr/bin:/bin", "LINT_REPO_REQUIRE": "1"},
    )
    assert strict.returncode == 97


def test_repo_is_clean_under_pinned_rules():
    if not _ruff_available():
        pytest.skip("ruff not installed in this environment")
    proc = subprocess.run(
        ["bash", str(SCRIPT)], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_self_lint_stage_clean():
    """The dependency-free self-lint stage (the repo's own analyzer over
    every committed example graph + check_locks incl. LK007) must run
    and come back clean even where ruff is absent."""
    proc = subprocess.run(
        ["bash", str(SCRIPT)],
        capture_output=True,
        text=True,
        cwd=str(REPO),
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "self-lint stage" in proc.stderr
    assert "self-lint clean" in proc.stderr


def test_baseline_lists_warnings_only():
    """Errors are never baselined — ``lint_baseline.json`` may only
    accept warning-severity codes, keyed by committed example."""
    import json

    from pathway_tpu.analysis.diagnostics import CODES

    baseline = json.loads(
        (REPO / "scripts" / "lint_baseline.json").read_text()
    )
    for program, accepted in baseline.items():
        if program.startswith("_"):
            continue  # comment key
        assert (REPO / "examples" / program).is_file(), program
        for code in accepted:
            assert CODES[code] == "warning", (program, code)
