"""Tier-1 smoke tests for the repo lint gate (``scripts/lint_repo.sh``).

The ruff check itself only runs where ruff is installed; everywhere else
the script's documented SKIP behavior is what gets verified.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "lint_repo.sh"


def _ruff_available() -> bool:
    if shutil.which("ruff"):
        return True
    proc = subprocess.run(
        ["python", "-c", "import ruff"], capture_output=True
    )
    return proc.returncode == 0


def test_skip_exit_codes_without_ruff():
    if _ruff_available():
        pytest.skip("ruff installed; skip-path not reachable")
    proc = subprocess.run(
        ["bash", str(SCRIPT)], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    assert "SKIP" in proc.stderr
    strict = subprocess.run(
        ["bash", str(SCRIPT)],
        capture_output=True,
        text=True,
        env={"PATH": "/usr/bin:/bin", "LINT_REPO_REQUIRE": "1"},
    )
    assert strict.returncode == 97


def test_repo_is_clean_under_pinned_rules():
    if not _ruff_available():
        pytest.skip("ruff not installed in this environment")
    proc = subprocess.run(
        ["bash", str(SCRIPT)], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
