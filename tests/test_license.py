"""License keys / entitlements / worker cap (reference
``src/engine/license.rs``)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import license as lic


def _keypair():
    # signing needs the optional cryptography package (absent in the CI
    # image); verification-side tests below run without it
    pytest.importorskip("cryptography", reason="signing tests need cryptography")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives import serialization

    sk = Ed25519PrivateKey.generate()
    sk_pem = sk.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    pk_pem = sk.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    )
    return sk_pem, pk_pem.decode()


def test_free_tier_defaults():
    l = lic.parse_license(None)
    assert l.tier == "free" and l.worker_cap() == lic.MAX_WORKERS_FREE
    with pytest.raises(lic.LicenseError, match="missing entitlement"):
        l.check_entitlements("scale")


def test_demo_key():
    l = lic.parse_license("demo-license-key-with-telemetry")
    assert l.tier == "demo" and l.telemetry


def test_signed_key_roundtrip(monkeypatch):
    sk_pem, pk_pem = _keypair()
    monkeypatch.setenv("PATHWAY_LICENSE_PUBLIC_KEY", pk_pem)
    key = lic.generate_license_key(
        {"tier": "scale", "entitlements": ["scale", "xpack-sharepoint"]},
        sk_pem,
    )
    l = lic.parse_license(key)
    assert l.tier == "scale" and l.scale_unlimited
    assert l.worker_cap() is None
    l.check_entitlements("xpack-sharepoint")  # no raise

    # tampered payload must fail
    corrupted = "x" + key[1:]
    with pytest.raises(lic.LicenseError):
        lic.parse_license(corrupted)
    # signature from the WRONG key must fail
    other_sk, _ = _keypair()
    forged = lic.generate_license_key({"tier": "scale"}, other_sk)
    with pytest.raises(lic.LicenseError, match="signature"):
        lic.parse_license(forged)


def test_malformed_key():
    with pytest.raises(lic.LicenseError, match="malformed"):
        lic.parse_license("no-dot-separator-and-not-demo")


def test_worker_cap_clamps(monkeypatch, caplog):
    monkeypatch.setattr(
        "pathway_tpu.internals.config.pathway_config.license_key", None
    )
    lic._cache.clear()
    import logging

    with caplog.at_level(logging.WARNING, logger="pathway_tpu.license"):
        assert lic.effective_workers(32) == lic.MAX_WORKERS_FREE
    assert any("free tier" in r.message for r in caplog.records)
    assert lic.effective_workers(4) == 4


def test_set_license_key_lifts_cap(monkeypatch):
    sk_pem, pk_pem = _keypair()
    monkeypatch.setenv("PATHWAY_LICENSE_PUBLIC_KEY", pk_pem)
    key = lic.generate_license_key(
        {"tier": "scale", "entitlements": ["scale"]}, sk_pem
    )
    old = pw.internals.config.pathway_config.license_key
    lic._cache.clear()
    try:
        pw.set_license_key(key)
        assert lic.effective_workers(32) == 32
    finally:
        pw.set_license_key(old)
        lic._cache.clear()
