"""Expression-VM program shapes: deep and adversarial compositions of
the lazy constructs (if_else/coalesce/fill_error/require), tuple/get
chains, pointer expressions and namespace methods — each compared
against the pure-Python closure over the same rows (the op-level
differential matrix lives in test_expr_vm.py; this file covers the
COMPOSITIONS the lowering's jump patching must get right).
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.stream import Update
from pathway_tpu.internals import api
from pathway_tpu.internals import expr_vm
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import native as _native


@pytest.fixture(scope="module")
def native():
    mod = _native.load()
    if mod is None or not hasattr(mod, "vm_compile"):
        pytest.skip("native VM unavailable")
    return mod


class _T:
    pass


class _Layout:
    _POS = {"x": 0, "y": 1, "z": 2}

    def resolver(self, ref):
        if ref._name == "id":
            return lambda kv: kv[0]
        pos = self._POS[ref._name]
        return lambda kv, pos=pos: kv[1][pos]

    def resolve_pos(self, ref):
        if ref._name == "id":
            return -1
        return self._POS[ref._name]


_TBL = _T()
X = ex.ColumnReference(_TBL, "x")
Y = ex.ColumnReference(_TBL, "y")
Z = ex.ColumnReference(_TBL, "z")
L = _Layout()
E = api.ERROR

ROWS = [
    (1, 2, 3),
    (0, 0, 0),
    (-5, 7, 2),
    (None, 4, 1),
    (E, 4, 1),
    (10, None, None),
    ("s", 1, 2),
]


def _assert_parity(native, exprs, rows=ROWS):
    batch = [Update(K.Pointer(i + 1), r, 1) for i, r in enumerate(rows)]
    progs = expr_vm.lower_programs(list(exprs), L)
    assert progs is not None
    out = native.vm_eval_batch(batch, progs, Update, api.ERROR, lambda e: None)
    closures = [e._compile(L.resolver) for e in exprs]
    for u_in, u_out in zip(batch, out):
        expected = []
        any_raised = False
        for c in closures:
            try:
                expected.append(c((u_in.key, u_in.values)))
            except Exception:
                expected.append(api.ERROR)
                any_raised = True
        got = [repr(g) for g in u_out.values]
        if got == [repr(v) for v in expected]:
            continue
        # a ROW-level VM failure collapses the whole row to (ERROR,)
        # (rowwise_map contract) — accept it iff a closure raised too
        assert any_raised and got == [repr(api.ERROR)], (
            u_in.values,
            got,
            expected,
        )


def test_nested_if_else_pyramid(native):
    e = pw.if_else(
        X > 0,
        pw.if_else(Y > 0, X + Y, pw.if_else(Z > 0, X + Z, X)),
        pw.if_else(Y > 0, Y - X, 0),
    )
    _assert_parity(native, [e])


def test_if_else_branches_are_lazy(native):
    """The untaken branch must not evaluate: the false arm divides by
    zero, which would poison rows where the condition is true."""
    e = pw.if_else(Z != 0, X // pw.if_else(Z != 0, Z, 1), X // Z)
    _assert_parity(native, [e])


def test_deep_coalesce_chain(native):
    e = pw.coalesce(
        pw.coalesce(X, Y),
        pw.coalesce(Y, Z),
        pw.if_else(Z.is_none(), 0, Z),
        -1,
    )
    _assert_parity(native, [e])


def test_fill_error_over_nested_failure(native):
    e = pw.fill_error(X // Z + pw.fill_error(Y // Z, 100), -7)
    _assert_parity(native, [e])


def test_require_guards_composition(native):
    # require embedded in arithmetic: the None short-circuit's jump must
    # land so the addition still sees one value on the stack
    e = pw.require(X * 10, X, Y) + pw.coalesce(Y, 0)
    _assert_parity(native, [e])


def test_make_tuple_get_roundtrip(native):
    t = pw.make_tuple(X, Y, Z)
    _assert_parity(native, [t.get(0, default=-1), t.get(7, default=-1)])


def test_mixed_methods_and_lazy_ops(native):
    rows = [
        ("  Alpha  ", "x", 1),
        ("", "y", 2),
        (None, "z", 3),
        (E, "w", 4),
    ]
    e = pw.if_else(
        X.is_none(),
        "missing",
        pw.coalesce(X, "").str.strip().str.lower(),
    )
    _assert_parity(native, [e], rows)


def test_pointer_expression_inside_branches(native):
    e = pw.if_else(Y > 2, _TBL_pointer(X, Y), _TBL_pointer(Y))
    _assert_parity(native, [e])


def _TBL_pointer(*args):
    return ex.PointerExpression(_TBL, *[ex._wrap(a) for a in args])


def test_many_columns_one_program_each(native):
    exprs = [
        X + Y,
        pw.if_else(X > Y, X, Y),
        pw.coalesce(X, Y, Z, 0),
        pw.fill_error(X * Y, -1),
        pw.make_tuple(X, pw.if_else(Y.is_none(), 0, Y)),
    ]
    _assert_parity(native, exprs)


def test_stack_depth_stress(native):
    """A deeply right-nested arithmetic chain exercises the stack-depth
    validator (every intermediate stays live)."""
    e = X
    for i in range(30):
        e = e + pw.if_else(Y > i, 1, 0)
    _assert_parity(native, [e])


def test_end_to_end_matches_python_disable(native, tmp_path):
    """Whole pipeline through pw.run twice: native VM on vs off."""
    import json
    import subprocess
    import sys
    import textwrap

    prog = tmp_path / "p.py"
    prog.write_text(
        textwrap.dedent(
            """
            import json, os, sys
            sys.path.insert(0, %r)
            import pathway_tpu as pw
            from tests.utils import run_to_rows

            t = pw.debug.table_from_rows(
                pw.schema_from_types(a=int, b=int),
                [(i, (i * 7) %% 13) for i in range(500)],
            )
            out = t.select(
                q=pw.if_else(t.b != 0, t.a // t.b, -1),
                r=pw.coalesce(t.a, 0) * 2,
                s=pw.fill_error(t.a // (t.b - 6), 999),
            )
            print(json.dumps(sorted(run_to_rows(out))))
            """
        )
        % "/root/repo"
    )
    import os

    env_on = dict(os.environ, JAX_PLATFORMS="cpu")
    env_off = dict(env_on, PATHWAY_DISABLE_NATIVE="1")
    a = subprocess.run(
        [sys.executable, str(prog)], env=env_on, capture_output=True, text=True,
        cwd="/root/repo",
    )
    b = subprocess.run(
        [sys.executable, str(prog)], env=env_off, capture_output=True, text=True,
        cwd="/root/repo",
    )
    assert a.returncode == 0 and b.returncode == 0, (a.stderr, b.stderr)
    assert json.loads(a.stdout.splitlines()[-1]) == json.loads(
        b.stdout.splitlines()[-1]
    )
