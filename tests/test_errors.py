"""Global error-log table + operator traces (reference
``pw.global_error_log``, ``internals/parse_graph.py:183-202`` and
``internals/trace.py`` / ``src/engine/error.rs``)."""

import pathway_tpu as pw
from tests.utils import T


def test_failing_udf_lands_in_error_table_with_user_trace():
    t = T(
        """
        a | b
        1 | 0
        2 | 1
        """
    )
    err = pw.global_error_log()
    r = t.select(x=pw.apply(lambda a, b: a // b, t.a, t.b))
    cap_r = r._capture_node()
    cap_e = err._capture_node()
    ctx = pw.run()

    rows_r = ctx.state(cap_r)["rows"]
    vals = sorted(str(v[0]) for v in rows_r.values())
    assert "Error" in vals[0] or vals[0] == "2"  # ERROR value + the good row

    rows_e = ctx.state(cap_e)["rows"]
    assert len(rows_e) == 1
    message, operator, trace = next(iter(rows_e.values()))
    assert "ZeroDivisionError" in message
    # the trace points at THIS test file (the user's pw.apply call site)
    assert "test_errors.py" in trace


def test_operator_failure_lands_in_error_table():
    t = T(
        """
        a
        1
        2
        """
    )
    err = pw.global_error_log()

    def bad_acceptor(new, old):
        raise RuntimeError("acceptor exploded")

    d = t.deduplicate(value=pw.this.a, acceptor=bad_acceptor)
    cap_e = err._capture_node()
    ctx = pw.run()
    rows_e = ctx.state(cap_e)["rows"]
    assert any("acceptor" in v[0] for v in rows_e.values())
    # engine error_log strings carry the [at file:line] suffix
    assert any("[at " in str(e) for e in ctx.error_log)
    assert any("test_errors.py" in str(e) for e in ctx.error_log)


def test_error_table_composes_like_any_table():
    t = T(
        """
        a
        0
        """
    )
    err = pw.global_error_log()
    only_div = err.filter(
        pw.apply(lambda m: "ZeroDivisionError" in m, err.message)
    )
    t.select(x=pw.apply(lambda a: 1 // a, t.a))
    cap = only_div._capture_node()
    ctx = pw.run()
    assert len(ctx.state(cap)["rows"]) == 1


def test_every_node_records_creation_trace():
    t = T(
        """
        a
        1
        """
    )
    r = t.filter(t.a > 0)
    assert "test_errors.py" in r._node.trace
