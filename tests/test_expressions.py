"""Expression namespaces (.str / .dt / .num) and conversion helpers
(reference ``internals/expressions/`` — date_time 1613 LoC, string 931,
numerical 212 — and the expressions test suites)."""

import datetime

import pathway_tpu as pw
from tests.utils import T, run_to_rows


def _one(table):
    rows = run_to_rows(table)
    assert len(rows) == 1
    return rows[0]


# ---------------------------------------------------------------------------
# .str


def test_str_basic_transforms():
    t = T(
        """
    s
    'Hello World'
    """
    )
    row = _one(
        t.select(
            lo=t.s.str.lower(),
            up=t.s.str.upper(),
            rev=t.s.str.reversed(),
            n=t.s.str.len(),
            sw=t.s.str.swapcase(),
            ti=t.s.str.title(),
        )
    )
    assert row == (
        "hello world",
        "HELLO WORLD",
        "dlroW olleH",
        11,
        "hELLO wORLD",
        "Hello World",
    )


def test_str_search_and_edit():
    t = T(
        """
    s
    '  banana  '
    """
    )
    row = _one(
        t.select(
            stripped=t.s.str.strip(),
            cnt=t.s.str.strip().str.count("an"),
            f=t.s.str.strip().str.find("na"),
            rf=t.s.str.strip().str.rfind("na"),
            starts=t.s.str.strip().str.startswith("ban"),
            ends=t.s.str.strip().str.endswith("ana"),
            rep=t.s.str.strip().str.replace("na", "NA"),
            sl=t.s.str.strip().str.slice(1, 4),
        )
    )
    assert row == ("banana", 2, 2, 4, True, True, "baNANA", "ana")


def test_str_split_and_parse():
    t = T(
        """
    csv   | i    | f     | b
    'a,b' | '42' | '2.5' | 'yes'
    """
    )
    row = _one(
        t.select(
            parts=t.csv.str.split(","),
            i=t.i.str.parse_int(),
            f=t.f.str.parse_float(),
            b=t.b.str.parse_bool(),
        )
    )
    assert row == (("a", "b"), 42, 2.5, True)


# ---------------------------------------------------------------------------
# .dt


def test_dt_components_and_formatting():
    t = T(
        """
    s
    '2023-03-25 14:30:45'
    """
    )
    parsed = t.select(d=t.s.str.parse_datetime("%Y-%m-%d %H:%M:%S"))
    row = _one(
        parsed.select(
            y=parsed.d.dt.year(),
            mo=parsed.d.dt.month(),
            da=parsed.d.dt.day(),
            h=parsed.d.dt.hour(),
            mi=parsed.d.dt.minute(),
            se=parsed.d.dt.second(),
            dow=parsed.d.dt.day_of_week(),
            doy=parsed.d.dt.day_of_year(),
            s=parsed.d.dt.strftime("%d/%m/%Y"),
        )
    )
    assert row == (2023, 3, 25, 14, 30, 45, 5, 84, "25/03/2023")


def test_dt_arithmetic_and_round():
    t = T(
        """
    a                     | b
    '2023-01-01 10:00:30' | '2023-01-01 08:00:00'
    """
    )
    p = t.select(
        a=t.a.str.parse_datetime("%Y-%m-%d %H:%M:%S"),
        b=t.b.str.parse_datetime("%Y-%m-%d %H:%M:%S"),
    )
    row = _one(
        p.select(
            gap=p.a - p.b,
            hours=(p.a - p.b).dt.hours(),
            shifted=p.b + (p.a - p.b),
            floor=p.a.dt.floor(datetime.timedelta(hours=1)),
        )
    )
    assert row == (
        datetime.timedelta(hours=2, seconds=30),
        2,
        datetime.datetime(2023, 1, 1, 10, 0, 30),
        datetime.datetime(2023, 1, 1, 10, 0, 0),
    )


def test_dt_timestamp_roundtrip():
    t = T(
        """
    ts
    1700000000
    """
    )
    p = t.select(d=t.ts.dt.utc_from_timestamp(unit="s"))
    row = _one(p.select(back=p.d.dt.timestamp(unit="s")))
    assert row == (1700000000.0,)


def test_duration_components():
    t = T(
        """
    a                     | b
    '2023-01-03 00:00:00' | '2023-01-01 12:30:00'
    """
    )
    p = t.select(
        d=t.a.str.parse_datetime("%Y-%m-%d %H:%M:%S")
        - t.b.str.parse_datetime("%Y-%m-%d %H:%M:%S")
    )
    row = _one(
        p.select(
            days=p.d.dt.days(),
            hrs=p.d.dt.hours(),
            mins=p.d.dt.minutes(),
        )
    )
    assert row == (1, 35, 2130)


# ---------------------------------------------------------------------------
# .num + conversion helpers


def test_num_namespace():
    t = T(
        """
    x
    -2.567
    """
    )
    row = _one(
        t.select(
            a=t.x.num.abs(),
            r=t.x.num.round(2),
            f=t.x.num.fill_na(0.0),
        )
    )
    assert row == (2.567, -2.57, -2.567)


def test_conversion_helpers():
    t = T(
        """
    v | w
    1 |
    """
    )
    row = _one(
        t.select(
            c=pw.cast(float, t.v),
            co=pw.coalesce(t.w, t.v, 99),
            ie=pw.if_else(t.v > 0, "pos", "neg"),
            mt=pw.make_tuple(t.v, "x"),
            uw=pw.unwrap(t.v),
            isn=t.w.is_none(),
            notn=t.v.is_not_none(),
        )
    )
    assert row == (1.0, 1, "pos", (1, "x"), 1, True, True)


def test_fill_error_and_require():
    t = T(
        """
    a | b
    1 | 0
    """
    )
    row = _one(
        t.select(
            safe=pw.fill_error(t.a // t.b, -1),  # div by zero -> replacement
            req=pw.require(t.a + 1, t.a),  # deps non-null -> value
        )
    )
    assert row == (-1, 2)
