"""CLI spawn env contract, demo stream generators, and the temporal
behavior matrix (delay/cutoff/keep_results combinations) — reference
``cli.py`` spawn, ``demo/__init__.py`` generators, and
``stdlib/temporal/temporal_behavior.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.temporal import (
    common_behavior,
    exactly_once_behavior,
    tumbling,
)
from tests.utils import run_to_rows


# ---------------------------------------------------------------------------
# CLI


def test_cli_spawn_sets_env_contract(tmp_path):
    """``pathway spawn --processes N --threads M`` launches N copies with
    the PATHWAY_* env contract (reference spawn/spawn-from-env)."""
    # each child reports into its own file: concurrent children sharing
    # one stdout can interleave lines, which made the capfd version flaky
    prog = tmp_path / "p.py"
    prog.write_text(
        textwrap.dedent(
            f"""
            import json, os
            pid = os.environ.get("PATHWAY_PROCESS_ID")
            with open({str(tmp_path)!r} + "/env_%s.json" % pid, "w") as f:
                json.dump({{
                    "pid": pid,
                    "procs": os.environ.get("PATHWAY_PROCESSES"),
                    "threads": os.environ.get("PATHWAY_THREADS"),
                    "port": os.environ.get("PATHWAY_FIRST_PORT"),
                }}, f)
            """
        )
    )
    from pathway_tpu.cli import main

    rc = main(
        [
            "spawn",
            "--processes",
            "2",
            "--threads",
            "3",
            sys.executable,
            str(prog),
        ]
    )
    assert rc == 0
    import json

    lines = [
        json.loads(p.read_text())
        for p in sorted(tmp_path.glob("env_*.json"))
    ]
    assert len(lines) == 2
    assert {rec["pid"] for rec in lines} == {"0", "1"}
    assert all(rec["procs"] == "2" and rec["threads"] == "3" for rec in lines)
    assert len({rec["port"] for rec in lines}) == 1  # shared first port


def test_cli_rejects_unknown_command():
    from pathway_tpu.cli import main

    with pytest.raises(BaseException):  # argparse: SystemExit/ArgumentError
        main(["no-such-command"])


# ---------------------------------------------------------------------------
# demo generators


def test_demo_range_stream_values():
    pw.G.clear()
    t = pw.demo.range_stream(nb_rows=5, input_rate=1000)
    vals = sorted(r[0] for r in run_to_rows(t))
    assert vals == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_demo_noisy_linear_stream_shape():
    pw.G.clear()
    t = pw.demo.noisy_linear_stream(nb_rows=6, input_rate=1000)
    rows = run_to_rows(t)
    assert len(rows) == 6
    xs = sorted(r[0] for r in rows)
    assert xs == [0, 1, 2, 3, 4, 5]


def test_demo_replay_csv(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,b\n1,x\n2,y\n")

    class S(pw.Schema):
        a: int
        b: str

    pw.G.clear()
    t = pw.demo.replay_csv(str(p), schema=S, input_rate=1000)
    assert sorted(run_to_rows(t)) == [(1, "x"), (2, "y")]


def test_demo_generate_custom_stream():
    pw.G.clear()
    t = pw.demo.generate_custom_stream(
        value_generators={"n": lambda i: i * 10},
        schema=pw.schema_from_types(n=int),
        nb_rows=4,
        input_rate=1000,
    )
    assert sorted(run_to_rows(t)) == [(0,), (10,), (20,), (30,)]


# ---------------------------------------------------------------------------
# temporal behaviors


def _timed(rows_md: str):
    return pw.debug.table_from_markdown(rows_md)


def _window_with_behavior(behavior):
    t = _timed(
        """
    t  | v | __time__ | __diff__
    1  | 1 | 2        | 1
    3  | 2 | 2        | 1
    11 | 4 | 4        | 1
    2  | 8 | 6        | 1
    """
    )
    w = t.windowby(
        t.t, window=tumbling(duration=10), behavior=behavior
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    return w.select(w.start, w.s)


def test_behavior_none_keeps_late_updates():
    pw.G.clear()
    out = _window_with_behavior(None)
    rows = dict(run_to_rows(out))
    # the late t=2 row (arriving after t=11 advanced time) still lands
    assert rows[0] == 11 and rows[10] == 4


def test_behavior_cutoff_drops_late_rows():
    """common_behavior(cutoff=...): a window whose close time has passed
    the event-time watermark by cutoff ignores further updates."""
    pw.G.clear()
    out = _window_with_behavior(common_behavior(cutoff=0))
    rows = dict(run_to_rows(out))
    # the late t=2 arrival (watermark already at 11 > window end 10)
    # is dropped: the first window keeps only its on-time rows
    assert rows[0] == 3 and rows[10] == 4


def test_behavior_keep_results_false_forgets_closed_windows():
    pw.G.clear()
    out = _window_with_behavior(
        common_behavior(cutoff=0, keep_results=False)
    )
    rows = dict(run_to_rows(out))
    # closed windows vanish from the output; only the live window stays
    assert 0 not in rows and rows[10] == 4


def test_exactly_once_behavior_emits_single_version():
    """exactly_once: each window flushes once at close — no incremental
    revisions reach the output stream."""
    pw.G.clear()
    t = _timed(
        """
    t  | v | __time__ | __diff__
    1  | 1 | 2        | 1
    2  | 2 | 4        | 1
    11 | 4 | 6        | 1
    21 | 8 | 8        | 1
    """
    )
    out = t.windowby(
        t.t, window=tumbling(duration=10), behavior=exactly_once_behavior()
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    history: list = []
    pw.io.subscribe(
        out,
        on_change=lambda k, row, tm, add: history.append(
            (row["start"], add, row["s"])
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # window [0,10) emitted exactly once, with the final sum, no retraction
    w0 = [h for h in history if h[0] == 0]
    assert w0 == [(0, True, 3)]
