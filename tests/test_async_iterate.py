"""AsyncTransformer option matrix (retries, caching, failure routing)
and pw.iterate fixed points with limits/universe changes (reference
``stdlib/utils/async_transformer.py`` ``:282+`` and ``pw.iterate``).
"""

from __future__ import annotations

import asyncio

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from tests.utils import T, run_to_rows


class _Doubler(pw.AsyncTransformer):
    output_schema = pw.schema_from_types(doubled=int)

    async def invoke(self, a: int) -> dict:
        await asyncio.sleep(0)
        return {"doubled": a * 2}


def test_async_transformer_successful_results():
    pw.G.clear()
    t = T(
        """
        a
        1
        2
        3
        """
    )
    out = _Doubler(t).successful
    assert sorted(run_to_rows(out)) == [(2,), (4,), (6,)]


def test_async_transformer_failures_route_to_failed_table():
    pw.G.clear()
    t = T(
        """
        a
        1
        0
        3
        """
    )

    class Picky(pw.AsyncTransformer):
        output_schema = pw.schema_from_types(inv=float)

        async def invoke(self, a: int) -> dict:
            return {"inv": 1.0 / a}  # a=0 raises

    tr = Picky(t)
    ok = sorted(run_to_rows(tr.successful))
    assert ok == [(1.0 / 3,), (1.0,)]
    pw.G.clear()
    t = T(
        """
        a
        1
        0
        """
    )
    tr = Picky(t)
    # one run, both outputs captured (the transformer's host-side queue
    # drains once per run; a second pw.run would see no input)
    cap_failed = tr.failed._capture_node()
    cap_ok = tr.successful._capture_node()
    ctx = pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(ctx.state(cap_failed)["rows"]) == 1  # the a=0 row
    ok_vals = [v[0] for v in ctx.state(cap_ok)["rows"].values()]
    assert ok_vals == [1.0]


def test_async_transformer_with_retries_recovers():
    pw.G.clear()
    attempts: dict[int, int] = {}
    t = T(
        """
        a
        5
        6
        """
    )

    class Flaky(pw.AsyncTransformer):
        output_schema = pw.schema_from_types(v=int)

        async def invoke(self, a: int) -> dict:
            attempts[a] = attempts.get(a, 0) + 1
            if attempts[a] == 1:
                raise ValueError("first try fails")
            return {"v": a * 10}

    tr = Flaky(t).with_options(
        retry_strategy=udfs.FixedDelayRetryStrategy(max_retries=3, delay_ms=1)
    )
    assert sorted(run_to_rows(tr.successful)) == [(50,), (60,)]
    assert all(n >= 2 for n in attempts.values())


def test_async_transformer_cache_dedupes_equal_rows():
    pw.G.clear()
    calls: list[int] = []
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=int), [(7,), (7,), (8,)]
    )

    class Tracked(pw.AsyncTransformer):
        output_schema = pw.schema_from_types(v=int)

        async def invoke(self, a: int) -> dict:
            calls.append(a)
            return {"v": a + 1}

    tr = Tracked(t).with_options(cache_strategy=udfs.InMemoryCache())
    assert sorted(run_to_rows(tr.successful)) == [(8,), (8,), (9,)]
    assert sorted(calls) == [7, 8]


# ---------------------------------------------------------------------------
# iterate


def test_iterate_collatz_reaches_one():
    """Classic fixed point: every row iterates its Collatz sequence to 1."""
    pw.G.clear()
    t = T(
        """
        n
        6
        7
        27
        """
    )

    def step(state: pw.Table) -> pw.Table:
        return state.select(
            n=pw.if_else(
                state.n == 1,
                1,
                pw.if_else(
                    state.n % 2 == 0, state.n // 2, 3 * state.n + 1
                ),
            )
        )

    out = pw.iterate(step, state=t.select(n=t.n))
    assert sorted(run_to_rows(out)) == [(1,), (1,), (1,)]


def test_iterate_limit_stops_early():
    pw.G.clear()
    t = T(
        """
        n
        0
        """
    )

    def inc(state: pw.Table) -> pw.Table:
        return state.select(n=state.n + 1)

    out = pw.iterate(inc, iteration_limit=5, state=t.select(n=t.n))
    assert run_to_rows(out) == [(5,)]


def test_iterate_multi_table_fixed_point():
    """Two coupled tables: propagate the max value to every row."""
    pw.G.clear()
    t = T(
        """
        g | v
        x | 1
        x | 9
        x | 4
        """
    )

    def spread(state: pw.Table) -> pw.Table:
        m = state.groupby(state.g).reduce(state.g, mx=pw.reducers.max(state.v))
        j = state.join(m, state.g == m.g)
        return j.select(state.g, v=pw.right.mx)

    out = pw.iterate(spread, state=t.select(t.g, t.v))
    assert sorted(run_to_rows(out)) == [("x", 9), ("x", 9), ("x", 9)]
