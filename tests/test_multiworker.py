"""Multi-worker execution: sharded thread workers, TCP cluster processes,
partitioned readers, kill/restart recovery.

Mirrors the reference's scale-out contract: N-worker runs produce the same
output as 1-worker runs (reference thread-count CI matrix,
``tests/utils.py:37-50``; wordcount cluster harness
``integration_tests/wordcount/base.py:231-236``).
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.cluster import Cluster
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_port_counter = [11000 + (os.getpid() % 500) * 16]


def next_port(n: int = 4) -> int:
    """A base port with `n` consecutive bindable ports (probed, so stray
    listeners from an earlier killed run can't collide)."""
    import socket

    while True:
        base = _port_counter[0]
        _port_counter[0] += n
        if _port_counter[0] > 60000:
            _port_counter[0] = 11000
        try:
            socks = []
            for i in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
        return base


def _run_threads(n_threads: int):
    """Run the current graph on an in-process thread cluster; returns the
    worker-0 RunContext."""
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    cluster = Cluster(threads=n_threads)
    try:
        return sched.run_cluster(cluster)
    finally:
        cluster.close()


def _wordcount_results(input_file, results):
    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(str(input_file), schema=S, mode="static")
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())

    def on_change(key, row, time, is_addition):
        if is_addition:
            results[row["word"]] = row["n"]
        elif results.get(row["word"]) == row["n"]:
            del results[row["word"]]

    pw.io.subscribe(counts, on_change=on_change)


@pytest.mark.parametrize("n_threads", [2, 4])
def test_thread_workers_wordcount_matches_single(tmp_path, n_threads):
    words = ["a", "b", "a", "c", "a", "b", "d", "a", "e", "b"] * 5
    input_file = tmp_path / "w.jsonl"
    input_file.write_text("\n".join(json.dumps({"word": w}) for w in words))

    expected = {}
    for w in words:
        expected[w] = expected.get(w, 0) + 1

    results: dict = {}
    _wordcount_results(input_file, results)
    _run_threads(n_threads)
    assert results == expected


@pytest.mark.parametrize("n_threads", [2, 3])
def test_thread_workers_join_matches_single(n_threads):
    from tests.utils import T

    left = T(
        """
        k | a
        x | 1
        y | 2
        z | 3
        """
    )
    right = T(
        """
        k | b
        x | 10
        y | 20
        w | 40
        """
    )
    joined = left.join(right, left.k == right.k).select(
        left.k, s=pw.left.a + pw.right.b
    )
    from pathway_tpu.engine.graph import CaptureNode

    cap = CaptureNode(G.engine_graph, joined._node)
    ctx = _run_threads(n_threads)
    rows = sorted(ctx.state(cap)["rows"].values())
    assert rows == [("x", 11), ("y", 22)]


def test_thread_workers_stateful_ops_match_single():
    """groupby+filter+concat+distinct pipeline over threads == single."""
    from tests.utils import T

    t = T(
        """
        grp | v
        a   | 1
        b   | 2
        a   | 3
        c   | 4
        b   | 6
        a   | 5
        """
    )
    red = t.groupby(t.grp).reduce(
        t.grp,
        total=pw.reducers.sum(t.v),
        mx=pw.reducers.max(t.v),
    )
    big = red.filter(red.total > 4)
    from pathway_tpu.engine.graph import CaptureNode

    cap = CaptureNode(G.engine_graph, big._node)
    ctx = _run_threads(4)
    rows = sorted(ctx.state(cap)["rows"].values())
    assert rows == [("a", 9, 5), ("b", 8, 6)]


def test_partitioned_reader_covers_all_rows(tmp_path):
    """Each worker's partitioned file reader emits a disjoint share whose
    union is the full input (parallel_readers semantics)."""
    from pathway_tpu.io.fs import _FilesSource
    from pathway_tpu.internals import schema as sch

    f = tmp_path / "data.txt"
    f.write_text("\n".join(f"line{i}" for i in range(100)))
    schema = sch.schema_from_types(data=str)

    class Sink:
        stopped = False

        def __init__(self):
            self.rows = []

        def add(self, key, values):
            self.rows.append((key, values))

        def commit(self):
            pass

        def close(self):
            pass

    src = _FilesSource(
        str(f), schema, parse_line=lambda l: {"data": l.rstrip("\n")} or None,
        mode="static", tag="t",
    )
    W = 3
    shares = []
    for w in range(W):
        sink = Sink()
        src.partition(w, W).run(sink)
        shares.append(sink.rows)
    all_keys = [k for share in shares for k, _ in share]
    assert len(all_keys) == 100
    assert len(set(all_keys)) == 100  # disjoint
    assert all(shares[w] for w in range(W))  # balanced enough to be nonempty


def test_steady_state_one_barrier_per_round(tmp_path):
    """Piggybacked epoch-cut consensus: the per-round status gather rides
    the data streams (``round_statuses``), so ``allgather`` stays an O(1)
    run-boundary primitive.  Counted directly — the steady-state path must
    not regress to a second rendezvous per round."""
    words = [f"w{i % 11}" for i in range(200)]
    input_file = tmp_path / "w.jsonl"
    input_file.write_text("\n".join(json.dumps({"word": w}) for w in words))

    results: dict = {}
    _wordcount_results(input_file, results)
    sched = Scheduler(G.engine_graph, autocommit_ms=5)
    cluster = Cluster(threads=2)
    allgather_slots: list = []
    orig_allgather = cluster.allgather

    def counting_allgather(slot, thread_id, obj):
        allgather_slots.append(slot)
        return orig_allgather(slot, thread_id, obj)

    cluster.allgather = counting_allgather  # type: ignore[method-assign]
    try:
        sched.run_cluster(cluster)
    finally:
        stats = cluster.exchange_stats()
        cluster.close()

    assert results  # the pipeline actually ran
    assert stats["status_rounds"] >= 2
    # every allgather is a known run-boundary slot — never a per-round one
    boundary = {("replay_len",), ("snap_presence",), ("errlog", "final")}
    assert set(allgather_slots) <= boundary, allgather_slots
    # O(1) per run: both threads call each boundary slot once
    assert len(allgather_slots) <= 2 * len(boundary)
    assert stats["allgather_calls"] <= len(boundary)


# ---------------------------------------------------------------------------
# multi-process TCP cluster

_WORDCOUNT_PROGRAM = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read({input!r}, schema=S, mode={mode!r})
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    pw.io.jsonlines.write(counts, {output!r})
    {persistence}
    pw.run(autocommit_duration_ms=20, persistence_config=pconf)
    """
)


def _spawn_program(tmp_path, input_file, output_file, *, processes, threads,
                   mode="static", persist_dir=None, first_port=None,
                   persist_mode="persisting"):
    persistence = (
        f"from pathway_tpu.persistence import Backend, Config, PersistenceMode\n"
        f"pconf = Config.simple_config(Backend.filesystem({str(persist_dir)!r}), "
        f"persistence_mode=PersistenceMode({persist_mode!r}))"
        if persist_dir
        else "pconf = None"
    )
    prog = tmp_path / "prog.py"
    prog.write_text(
        _WORDCOUNT_PROGRAM.format(
            repo=REPO,
            input=str(input_file),
            output=str(output_file),
            mode=mode,
            persistence=persistence,
        )
    )
    env = dict(os.environ)
    env["PATHWAY_THREADS"] = str(threads)
    env["PATHWAY_PROCESSES"] = str(processes)
    env["PATHWAY_FIRST_PORT"] = str(first_port or next_port(processes + 1))
    procs = []
    for pid in range(processes):
        e = dict(env)
        e["PATHWAY_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=e,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    return procs


def _final_counts(output_file) -> dict:
    counts: dict = {}
    if not os.path.exists(output_file):
        return counts
    state: dict = {}
    with open(output_file) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            key = row["word"]
            if row["diff"] > 0:
                state[key] = row["n"]
            elif state.get(key) == row["n"]:
                del state[key]
    return state


def _wait_for_progress(output_file, timeout: float = 60.0) -> None:
    """Block until the pipeline demonstrably flowed end-to-end (output
    rows exist).  The kill/restart tests used to SIGKILL after a fixed
    wall-clock sleep, which raced suite load — killing before any commit
    made recovery trivially pass or the cluster handshake fail."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if os.path.getsize(output_file) > 0:
                # one more commit interval so persistence logs a commit
                # past the rows we just observed
                time.sleep(0.3)
                return
        except OSError:
            pass
        time.sleep(0.05)
    raise AssertionError(f"no pipeline progress within {timeout}s")


def test_two_process_cluster_wordcount(tmp_path):
    """spawn -n 2 -t 2: partitioned work, output identical to 1 worker."""
    words = ["apple", "pear", "apple", "plum", "apple", "pear"] * 10
    input_file = tmp_path / "w.jsonl"
    input_file.write_text("\n".join(json.dumps({"word": w}) for w in words))
    output_file = tmp_path / "out.jsonl"

    procs = _spawn_program(
        tmp_path, input_file, output_file, processes=2, threads=2
    )
    for p in procs:
        out, err = p.communicate(timeout=90)
        assert p.returncode == 0, err.decode()[-2000:]
    assert _final_counts(output_file) == {"apple": 30, "pear": 20, "plum": 10}


def test_process_kill_restart_recovers(tmp_path):
    """Kill one process mid-stream; restart the cluster; persistence
    resumes to exact counts (reference wordcount test_recovery)."""
    words = [f"w{i % 7}" for i in range(400)]
    input_file = tmp_path / "w.jsonl"
    input_file.write_text("\n".join(json.dumps({"word": w}) for w in words))
    output_file = tmp_path / "out.jsonl"
    persist_dir = tmp_path / "snap"

    port = next_port(4)
    procs = _spawn_program(
        tmp_path, input_file, output_file, processes=2, threads=1,
        mode="streaming", persist_dir=persist_dir, first_port=port,
    )
    # kill one worker only after output proves end-to-end progress
    _wait_for_progress(output_file)
    procs[1].send_signal(signal.SIGKILL)
    for p in procs:
        try:
            p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()

    # restart: static mode completes the read; resume must not double-count
    output_file.unlink(missing_ok=True)
    procs = _spawn_program(
        tmp_path, input_file, output_file, processes=2, threads=1,
        mode="static", persist_dir=persist_dir, first_port=port + 8,
    )
    for p in procs:
        out, err = p.communicate(timeout=90)
        assert p.returncode == 0, err.decode()[-2000:]
    expected: dict = {}
    for w in words:
        expected[w] = expected.get(w, 0) + 1
    assert _final_counts(output_file) == expected


def test_cluster_operator_snapshot_kill_restart(tmp_path):
    """OPERATOR_PERSISTING in a 2-process cluster: kill one process
    mid-stream, restart, final counts exact with bounded replay."""
    words = [f"w{i % 5}" for i in range(300)]
    input_file = tmp_path / "w.jsonl"
    input_file.write_text("\n".join(json.dumps({"word": w}) for w in words))
    output_file = tmp_path / "out.jsonl"
    persist_dir = tmp_path / "snap"

    port = next_port(4)
    procs = _spawn_program(
        tmp_path, input_file, output_file, processes=2, threads=1,
        mode="streaming", persist_dir=persist_dir, first_port=port,
        persist_mode="operator_persisting",
    )
    _wait_for_progress(output_file)
    procs[0].send_signal(signal.SIGKILL)
    for p in procs:
        try:
            p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()

    # restart in static mode; cumulative final state must be exact.
    # Operator snapshots give CONTINUATION semantics: only groups touched
    # after the restore re-fire, so merge both runs' outputs.
    state = _final_counts(output_file)
    output_file.unlink(missing_ok=True)
    procs = _spawn_program(
        tmp_path, input_file, output_file, processes=2, threads=1,
        mode="static", persist_dir=persist_dir, first_port=next_port(4),
        persist_mode="operator_persisting",
    )
    for p in procs:
        out, err = p.communicate(timeout=90)
        assert p.returncode == 0, err.decode()[-2000:]
    state.update(_final_counts(output_file))
    expected: dict = {}
    for w in words:
        expected[w] = expected.get(w, 0) + 1
    assert state == expected


_STATS_PROGRAM = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import pathway_tpu as pw

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read({input!r}, schema=S, mode="static")
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    pw.io.jsonlines.write(counts, {output!r})
    ctx = pw.run(autocommit_duration_ms=20, monitoring_level="none")
    print("EXCHANGE_STATS=" + json.dumps(ctx.stats.get("exchange", {{}})))
    """
)


def test_two_process_exchange_stats(tmp_path):
    """The pipelined transport reports its overhead probe: framed
    transmissions flowed, the status consensus rode them every round, and
    allgather stayed a run-boundary constant."""
    words = ["apple", "pear", "apple", "plum", "apple", "pear"] * 20
    input_file = tmp_path / "w.jsonl"
    input_file.write_text("\n".join(json.dumps({"word": w}) for w in words))
    output_file = tmp_path / "out.jsonl"

    prog = tmp_path / "prog.py"
    prog.write_text(
        _STATS_PROGRAM.format(
            repo=REPO, input=str(input_file), output=str(output_file)
        )
    )
    env = dict(os.environ)
    env["PATHWAY_THREADS"] = "2"
    env["PATHWAY_PROCESSES"] = "2"
    env["PATHWAY_FIRST_PORT"] = str(next_port(3))
    procs = []
    for pid in range(2):
        e = dict(env)
        e["PATHWAY_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(prog)],
                env=e,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    all_stats = []
    for p in procs:
        out, err = p.communicate(timeout=90)
        assert p.returncode == 0, err.decode()[-2000:]
        line = next(
            l for l in out.decode().splitlines() if l.startswith("EXCHANGE_STATS=")
        )
        all_stats.append(json.loads(line[len("EXCHANGE_STATS="):]))
    assert _final_counts(output_file) == {"apple": 60, "pear": 40, "plum": 20}

    for stats in all_stats:
        # data moved over the framed transport and was accounted for
        assert stats["transmissions"] > 0
        assert stats["frames_sent"] >= stats["transmissions"]
        assert stats["bytes_sent"] > 0 and stats["bytes_recv"] > 0
        assert stats["exchange_calls"] > 0
        # consensus piggybacked on the stream: many status rounds, but
        # allgather held to the run-boundary slots only
        assert stats["status_rounds"] >= 2
        assert stats["allgather_calls"] <= 3
        for key in ("pack_ms", "send_ms", "unpack_ms", "recv_wait_ms",
                    "status_wait_ms"):
            assert stats[key] >= 0.0
