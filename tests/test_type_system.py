"""Type system: build-time operator typing, the dtype lattice, and
runtime typechecking (reference ``internals/type_interpreter.py``,
``internals/dtype.py``, PATHWAY_RUNTIME_TYPECHECKING)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.type_interpreter import (
    TypeInterpreterError,
    binary_result_dtype,
    unary_result_dtype,
)
from tests.utils import T


# ---------------------------------------------------------------------------
# build-time operator typing


def test_str_plus_int_raises_at_build_time():
    t = T(
        """
        name | age
        ann  | 3
        """
    )
    with pytest.raises(TypeInterpreterError, match="STR.*INT|INT.*STR"):
        t.select(bad=t.name + t.age)


def test_ordering_comparison_of_incompatible_types_raises():
    t = T(
        """
        name | age
        ann  | 3
        """
    )
    with pytest.raises(TypeInterpreterError):
        t.select(bad=t.name < t.age)


def test_equality_is_total_and_arithmetic_promotes():
    t = T(
        """
        name | age | w
        ann  | 3   | 1.5
        bob  | 4   | 2.5
        """
    )
    out = t.select(
        eq=t.name == t.age,   # equality allowed across types
        f=t.age + t.w,        # INT + FLOAT -> FLOAT
        d=t.age / t.age,      # / always FLOAT
        n=-t.age,
    )
    assert out._dtypes["eq"] == dt.BOOL
    assert out._dtypes["f"] == dt.FLOAT
    assert out._dtypes["d"] == dt.FLOAT
    assert out._dtypes["n"] == dt.INT
    cap = out._capture_node()
    ctx = pw.run()
    rows = sorted(ctx.state(cap)["rows"].values())
    assert rows == [(False, 4.5, 1.0, -3), (False, 6.5, 1.0, -4)]


def test_datetime_duration_algebra():
    assert (
        binary_result_dtype("-", dt.DATE_TIME_NAIVE, dt.DATE_TIME_NAIVE)
        == dt.DURATION
    )
    assert (
        binary_result_dtype("+", dt.DATE_TIME_UTC, dt.DURATION)
        == dt.DATE_TIME_UTC
    )
    assert binary_result_dtype("/", dt.DURATION, dt.DURATION) == dt.FLOAT
    assert binary_result_dtype("//", dt.DURATION, dt.DURATION) == dt.INT
    assert binary_result_dtype("*", dt.DURATION, dt.INT) == dt.DURATION
    with pytest.raises(TypeInterpreterError):
        binary_result_dtype("+", dt.DATE_TIME_NAIVE, dt.DATE_TIME_NAIVE)
    with pytest.raises(TypeInterpreterError):
        binary_result_dtype("-", dt.DURATION, dt.INT)


def test_optional_propagates_through_ops():
    res = binary_result_dtype("+", dt.Optional(dt.INT), dt.INT)
    assert res == dt.Optional(dt.INT)
    assert binary_result_dtype("==", dt.Optional(dt.STR), dt.STR) == dt.Optional(
        dt.BOOL
    )
    assert unary_result_dtype("-", dt.Optional(dt.FLOAT)) == dt.Optional(dt.FLOAT)


def test_any_is_an_escape_hatch():
    # untyped columns never raise, like the reference
    assert binary_result_dtype("+", dt.ANY, dt.STR) == dt.ANY
    assert binary_result_dtype("<", dt.ANY, dt.INT) == dt.BOOL
    assert binary_result_dtype("*", dt.STR, dt.INT) == dt.STR


def test_bitwise_rules():
    assert binary_result_dtype("&", dt.BOOL, dt.BOOL) == dt.BOOL
    assert binary_result_dtype("|", dt.INT, dt.INT) == dt.INT
    with pytest.raises(TypeInterpreterError):
        binary_result_dtype("&", dt.STR, dt.BOOL)


# ---------------------------------------------------------------------------
# lattice


def test_is_subtype_basics():
    assert dt.is_subtype(dt.INT, dt.FLOAT)
    assert dt.is_subtype(dt.BOOL, dt.INT)
    assert not dt.is_subtype(dt.FLOAT, dt.INT)
    assert dt.is_subtype(dt.INT, dt.Optional(dt.INT))
    assert dt.is_subtype(dt.NONE, dt.Optional(dt.STR))
    assert not dt.is_subtype(dt.Optional(dt.INT), dt.INT)
    assert dt.is_subtype(dt.STR, dt.ANY)
    assert dt.is_subtype(
        dt.Tuple(dt.INT, dt.BOOL), dt.Tuple(dt.FLOAT, dt.INT)
    )
    assert dt.is_subtype(dt.Tuple(dt.INT, dt.INT), dt.List(dt.FLOAT))
    assert dt.is_subtype(dt.Array(2, dt.INT), dt.Array(None, dt.FLOAT))
    assert not dt.is_subtype(dt.Array(2, dt.INT), dt.Array(3, dt.INT))


def test_types_lca_structure_aware():
    assert dt.types_lca(dt.INT, dt.FLOAT) == dt.FLOAT
    assert dt.types_lca(dt.NONE, dt.INT) == dt.Optional(dt.INT)
    assert dt.types_lca(dt.Optional(dt.INT), dt.FLOAT) == dt.Optional(dt.FLOAT)
    assert dt.types_lca(
        dt.Tuple(dt.INT, dt.STR), dt.Tuple(dt.FLOAT, dt.STR)
    ) == dt.Tuple(dt.FLOAT, dt.STR)
    assert dt.types_lca(dt.Tuple(dt.INT), dt.Tuple(dt.INT, dt.INT)) == dt.List(
        dt.INT
    )
    assert dt.types_lca(dt.STR, dt.INT) == dt.ANY


# ---------------------------------------------------------------------------
# runtime typechecking


def test_runtime_typechecking_catches_bad_udf(monkeypatch):
    @pw.udf
    def lies(x: int) -> int:
        return f"not an int {x}"  # type: ignore[return-value]

    t = T(
        """
        v
        1
        """
    )
    out = t.select(r=lies(t.v))
    out._capture_node()
    with pytest.raises(TypeError, match="declared INT|declared"):
        pw.run(runtime_typechecking=True)


def test_runtime_typechecking_off_contains_quietly():
    @pw.udf
    def lies(x: int) -> int:
        return f"not an int {x}"  # type: ignore[return-value]

    t = T(
        """
        v
        1
        """
    )
    out = t.select(r=lies(t.v))
    cap = out._capture_node()
    ctx = pw.run(runtime_typechecking=False)
    (row,) = ctx.state(cap)["rows"].values()
    assert row == ("not an int 1",)  # dynamic by default, like the reference
