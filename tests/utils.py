"""Test helpers (reference ``python/pathway/tests/utils.py:470-560``):
``assert_table_equality`` and friends execute both tables in one run and
diff the captured final states / update streams."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.debug import _run_capture, table_from_markdown
from pathway_tpu.engine.stream import hashable_row

T = table_from_markdown


def _rows_of(table: pw.Table) -> dict:
    (rows, _), = _run_capture(table)
    return rows


def run_tables(*tables: pw.Table) -> list[tuple[dict, list]]:
    return _run_capture(*tables)


def assert_table_equality(actual: pw.Table, expected: pw.Table) -> None:
    (arows, _), (erows, _) = _run_capture(actual, expected)
    assert set(arows.keys()) == set(erows.keys()), (
        f"key sets differ:\nactual: {sorted(arows.items(), key=repr)}\n"
        f"expected: {sorted(erows.items(), key=repr)}"
    )
    for k in arows:
        assert hashable_row(arows[k]) == hashable_row(erows[k]), (
            f"row {k!r} differs: actual {arows[k]!r} != expected {erows[k]!r}"
        )


def assert_table_equality_wo_index(actual: pw.Table, expected: pw.Table) -> None:
    from collections import Counter

    (arows, _), (erows, _) = _run_capture(actual, expected)
    ac = Counter(hashable_row(v) for v in arows.values())
    ec = Counter(hashable_row(v) for v in erows.values())
    assert ac == ec, f"multisets differ:\nactual:   {sorted(ac.items(), key=repr)}\nexpected: {sorted(ec.items(), key=repr)}"


assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def assert_stream_equality(actual: pw.Table, expected: pw.Table) -> None:
    """Compare full update streams grouped by time (reference
    ``assert_stream_equality``)."""
    from collections import Counter, defaultdict

    (_, astream), (_, estream) = _run_capture(actual, expected)

    def by_time(stream: list) -> dict[int, Counter]:
        out: dict[int, Counter] = defaultdict(Counter)
        for key, vals, time, diff in stream:
            out[time][(key, hashable_row(vals), diff)] += 1
        return dict(out)

    a, e = by_time(astream), by_time(estream)
    a_times, e_times = sorted(a), sorted(e)
    assert len(a_times) == len(e_times), f"epoch counts differ: {a_times} vs {e_times}"
    for at, et in zip(a_times, e_times):
        assert a[at] == e[et], f"updates at epoch {at}/{et} differ:\n{a[at]}\nvs\n{e[et]}"


def stream_rows(table: pw.Table) -> list[tuple[Any, tuple, int, int]]:
    (_, stream), = _run_capture(table)
    return stream


def run_to_rows(table: pw.Table) -> list[tuple]:
    """Final state as a deterministically ordered list of value tuples."""
    (rows, _), = _run_capture(table)
    return sorted(rows.values(), key=repr)
