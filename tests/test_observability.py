"""Observability surfaces: /metrics + /status HTTP endpoints, operator
probes, connector stats, attach_prober callbacks, and license
introspection (reference monitoring/telemetry subsystem roles:
``src/engine/telemetry.rs``, ``prober`` machinery in graph.rs:988).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw
from tests.utils import T, run_to_rows


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_monitoring_http_metrics_and_status():
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    t = T(
        """
    a
    1
    2
    """
    )
    out = t.select(b=t.a * 2)
    out._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    port = _free_port()
    import pathway_tpu.internals.config as cfg

    try:
        start_http_server(sched, port=port)
        sched.run()
        # /metrics: prometheus text with per-operator counters
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "pathway" in body and "rows" in body
        # /status: json health document
        status = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=5
            ).read()
        )
        assert isinstance(status, dict) and status
    finally:
        server = getattr(sched, "_monitoring_server", None)
        if server is not None:
            server.shutdown()
            server.server_close()


def test_operator_probes_record_rows_and_latency():
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    t = T(
        """
    a
    1
    2
    3
    """
    )
    out = t.select(b=t.a + 1).filter(pw.this.b > 2)
    out._capture_node()
    sched = Scheduler(G.engine_graph)
    ctx = sched.run()
    probes = sched.snapshot_operator_probes(ctx)
    assert probes, "operators must register probes"
    total_rows = sum(p.get("rows_out", 0) for p in probes.values())
    assert total_rows > 0
    assert all(p.get("ms_total", 0) >= 0 for p in probes.values())


def test_attach_prober_fires_per_epoch():
    events = []
    pw.G.clear()
    t = T(
        """
    a
    1
    """
    )
    t.select(b=t.a)._capture_node()
    pw.attach_prober(lambda stats: events.append(stats))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert events
    first = events[0]
    assert "time" in first and "worker" in first and "operators" in first


def test_connector_stats_track_rows(tmp_path):
    p = tmp_path / "in.jsonl"
    p.write_text('{"a": 1}\n{"a": 2}\n{"a": 3}\n')

    class S(pw.Schema):
        a: int

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    t._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    sched.run()
    stats = sched.snapshot_connector_stats()
    assert stats
    name, s = next(iter(stats.items()))
    assert s["rows"] == 3
    assert s["closed"] is True


def test_telemetry_gauges_after_run():
    from pathway_tpu.internals.telemetry import get_telemetry

    pw.G.clear()
    t = T(
        """
    a
    1
    """
    )
    t.select(b=t.a)._capture_node()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    tel = get_telemetry()
    assert "run.epoch" in tel.gauges
    assert tel.gauges["run.errors"] == 0
    assert any(s["name"] == "graph_runner.run" for s in tel.spans)


def test_license_free_tier_reports():
    from pathway_tpu.internals.license import get_license

    from pathway_tpu.internals.license import LicenseError

    lic = get_license()
    # free tier: a worker cap exists; entitlement checks answer cleanly
    assert lic.worker_cap() is None or lic.worker_cap() >= 1
    if "scale" not in lic.entitlements:
        with pytest.raises(LicenseError, match="entitlement"):
            lic.check_entitlements("scale")


def test_global_graph_clear_resets_state():
    pw.G.clear()
    T(
        """
    a
    1
    """
    )
    from pathway_tpu.internals.parse_graph import G

    assert len(G.engine_graph.nodes) > 0
    pw.G.clear()
    assert len(G.engine_graph.nodes) == 0


def test_metrics_stage_latency_count_sum_companions():
    """The quantile gauges gained _count/_sum companion counters so
    rate(sum)/rate(count) yields true windowed means (ISSUE 14)."""
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.monitoring_server import _metrics_text
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    t = T(
        """
    a
    1
    2
    """
    )
    out = t.select(b=t.a * 2)
    out._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    sched.run()
    # known samples: 2ms + 4ms into the process stage
    sched.latency.record("process", 2_000_000)
    sched.latency.record("process", 4_000_000)
    body = _metrics_text(sched)
    assert "# TYPE pathway_tpu_stage_latency_ms_count counter" in body
    assert "# TYPE pathway_tpu_stage_latency_ms_sum counter" in body
    import re

    counts = {
        m.group(1): int(m.group(2))
        for m in re.finditer(
            r'pathway_tpu_stage_latency_ms_count\{stage="([^"]+)"\} (\d+)',
            body,
        )
    }
    sums = {
        m.group(1): float(m.group(2))
        for m in re.finditer(
            r'pathway_tpu_stage_latency_ms_sum\{stage="([^"]+)"\} ([\d.]+)',
            body,
        )
    }
    assert set(counts) == set(sums)
    assert counts["process"] == 2
    assert sums["process"] == pytest.approx(6.0, rel=0.01)


def test_metrics_serving_latency_companions_carry_tenant_class():
    from pathway_tpu import serving
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.monitoring_server import _metrics_text
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    probe = serving.serving_probe()
    probe.record("serve_e2e", "interactive", 5_000_000)
    probe.record("serve_e2e", "interactive", 7_000_000)
    t = T(
        """
    a
    1
    """
    )
    t.select(b=t.a)._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    body = _metrics_text(sched)
    assert (
        'pathway_tpu_stage_latency_ms_count{stage="serve_e2e",'
        'tenant_class="interactive"}' in body
    )
    import re

    m = re.search(
        r'pathway_tpu_stage_latency_ms_sum\{stage="serve_e2e",'
        r'tenant_class="interactive"\} ([\d.]+)',
        body,
    )
    assert m is not None and float(m.group(1)) >= 12.0  # 5ms + 7ms


def test_debug_stacks_and_trace_endpoints():
    """/debug/stacks dumps every thread; /debug/trace?seconds=N returns
    Chrome-trace JSON windowed to the last N seconds (ISSUE 14)."""
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals import tracing
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.internals.parse_graph import G

    pw.G.clear()
    tracing.configure(PATHWAY_TRACE="1", PATHWAY_TRACE_SAMPLE="1.0")
    t = T(
        """
    a
    1
    """
    )
    t.select(b=t.a)._capture_node()
    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    port = _free_port()
    try:
        start_http_server(sched, port=port)
        ctx = tracing.new_trace()
        now = tracing.now_ns()
        tracing.record_span("debug_probe", now - 1_000_000, now, ctx=ctx)
        stacks = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/stacks", timeout=5
        ).read().decode()
        assert "--- Thread" in stacks
        assert "pw_monitoring" in stacks  # the server's own thread shows up
        doc = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/trace?seconds=30", timeout=5
            ).read()
        )
        names = [e["name"] for e in doc["traceEvents"]]
        assert "debug_probe" in names
        # a window that excludes the span returns without it
        doc0 = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/trace?seconds=0.0000001",
                timeout=5,
            ).read()
        )
        assert "debug_probe" not in [e["name"] for e in doc0["traceEvents"]]
    finally:
        server = getattr(sched, "_monitoring_server", None)
        if server is not None:
            server.shutdown()
            server.server_close()


def test_sigusr2_dumps_stacks_and_flushes_flight_recorder(tmp_path, capfd):
    import os
    import signal

    from pathway_tpu.internals import tracing

    if not tracing.install_sigusr2():
        pytest.skip("SIGUSR2 handler not installable here")
    tracing.configure(
        PATHWAY_TRACE="1",
        PATHWAY_TRACE_SAMPLE="1.0",
        PATHWAY_TRACE_DIR=str(tmp_path),
    )
    try:
        ctx = tracing.new_trace()
        now = tracing.now_ns()
        tracing.record_span("pre_kill", now - 1000, now, ctx=ctx)
        os.kill(os.getpid(), signal.SIGUSR2)
        time.sleep(0.1)  # handler runs on the main thread at a bytecode edge
        err = capfd.readouterr().err
        assert "--- Thread" in err
        dumps = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert any("sigusr2" in f for f in dumps)
    finally:
        tracing.configure(PATHWAY_TRACE_DIR=None)
