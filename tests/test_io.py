"""Connector tests (modeled on reference test_io.py): jsonlines/csv/plaintext
round-trips, python ConnectorSubject, subscribe, kafka mock broker, sqlite,
REST connector."""

import json
import os
import sqlite3
import threading
import time

import pytest

import pathway_tpu as pw
from tests.utils import T, _rows_of, assert_table_equality_wo_index


def test_jsonlines_read_static(tmp_path):
    p = tmp_path / "in.jsonl"
    p.write_text('{"a": 1, "b": "x"}\n{"a": 2, "b": "y"}\n')

    class S(pw.Schema):
        a: int
        b: str

    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    assert sorted(_rows_of(t).values()) == [(1, "x"), (2, "y")]


def test_jsonlines_write(tmp_path):
    src = tmp_path / "in.jsonl"
    out = tmp_path / "out.jsonl"
    src.write_text('{"a": 1}\n{"a": 5}\n')

    class S(pw.Schema):
        a: int

    t = pw.io.jsonlines.read(str(src), schema=S, mode="static")
    res = t.select(b=pw.this.a * 2)
    pw.io.jsonlines.write(res, str(out))
    pw.run()
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert sorted(l["b"] for l in lines) == [2, 10]
    assert all(l["diff"] == 1 for l in lines)


def test_csv_roundtrip(tmp_path):
    src = tmp_path / "in.csv"
    out = tmp_path / "out.csv"
    src.write_text("a,b\n1,x\n2,y\n")

    class S(pw.Schema):
        a: int
        b: str

    t = pw.io.csv.read(str(src), schema=S, mode="static")
    pw.io.csv.write(t, str(out))
    pw.run()
    body = out.read_text().splitlines()
    assert body[0].startswith("a,b")
    assert len(body) == 3


def test_plaintext(tmp_path):
    p = tmp_path / "doc.txt"
    p.write_text("hello\nworld\n")
    t = pw.io.plaintext.read(str(p), mode="static")
    assert sorted(_rows_of(t).values()) == [("hello",), ("world",)]


def test_python_connector_subject():
    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(a=1)
            self.next(a=2)
            self.commit()
            self.next(a=3)
            self.commit()

    class S(pw.Schema):
        a: int

    t = pw.io.python.read(Subject(), schema=S)
    res = t.reduce(s=pw.reducers.sum(t.a))
    assert list(_rows_of(res).values()) == [(6,)]


def test_python_connector_upsert():
    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a", v=1)
            self.commit()
            self.next(k="a", v=5)  # overwrite by primary key
            self.next(k="b", v=2)
            self.commit()

    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.python.read(Subject(), schema=S)
    assert sorted(_rows_of(t).values()) == [("a", 5), ("b", 2)]


def test_subscribe_callbacks():
    t = T(
        """
        id | v | __time__ | __diff__
        1  | 5 | 2        | 1
        1  | 5 | 4        | -1
        """
    )
    seen = []
    times = []
    done = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_add: seen.append((row["v"], is_add)),
        on_time_end=lambda time: times.append(time),
        on_end=lambda: done.append(True),
    )
    pw.run(autocommit_duration_ms=5)
    assert seen == [(5, True), (5, False)]
    assert done == [True]
    assert len(times) >= 2


def test_kafka_mock_broker():
    broker = pw.io.kafka.MockBroker.get("mock://test1")
    for i in range(5):
        broker.produce("topic", json.dumps({"v": i}).encode())
    broker.close_topic("topic")

    class S(pw.Schema):
        v: int

    t = pw.io.kafka.read(
        {"bootstrap.servers": "mock://test1"}, topic="topic", schema=S, format="json"
    )
    res = t.reduce(s=pw.reducers.sum(t.v), c=pw.reducers.count())
    assert list(_rows_of(res).values()) == [(10, 5)]


def test_sqlite_static(tmp_path):
    db = tmp_path / "test.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE items (k TEXT PRIMARY KEY, v INTEGER)")
    conn.execute("INSERT INTO items VALUES ('a', 1), ('b', 2)")
    conn.commit()
    conn.close()

    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.sqlite.read(str(db), "items", S, mode="static")
    assert sorted(_rows_of(t).values()) == [("a", 1), ("b", 2)]


def test_demo_range_stream():
    t = pw.demo.range_stream(nb_rows=5, input_rate=1000)
    res = t.reduce(s=pw.reducers.sum(pw.this.value))
    assert list(_rows_of(res).values()) == [(10.0,)]


def test_fs_streaming_appends(tmp_path):
    """Files appended mid-run are picked up (dir watching)."""
    p = tmp_path / "stream.jsonl"
    p.write_text('{"a": 1}\n')

    class S(pw.Schema):
        a: int

    t = pw.io.jsonlines.read(str(tmp_path), schema=S, mode="streaming")
    got = []
    pw.io.subscribe(t, on_change=lambda k, row, time, add: got.append(row["a"]))

    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()
    deadline = time.monotonic() + 5
    while 1 not in got and time.monotonic() < deadline:
        time.sleep(0.02)
    with open(p, "a") as f:
        f.write('{"a": 2}\n')
    while 2 not in got and time.monotonic() < deadline:
        time.sleep(0.02)
    sched.stop()
    run_t.join(timeout=2)
    assert got[:2] == [1, 2]


def test_fs_partial_trailing_line(tmp_path):
    """A file whose last line lacks a newline must not crash the reader; the
    partial line is held back until completed (streaming) or read (static)."""
    p = tmp_path / "partial.jsonl"
    p.write_text('{"a": 1}\n{"a": 2}')  # no trailing newline

    class S(pw.Schema):
        a: int

    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    assert sorted(_rows_of(t).values()) == [(1,), (2,)]


def test_csv_multiple_files_headers(tmp_path):
    (tmp_path / "f1.csv").write_text("a,b\n1,x\n")
    (tmp_path / "f2.csv").write_text("a,b\n2,y\n")

    class S(pw.Schema):
        a: int
        b: str

    t = pw.io.csv.read(str(tmp_path), schema=S, mode="static")
    assert sorted(_rows_of(t).values()) == [(1, "x"), (2, "y")]


def test_jsonlines_non_object_lines_skipped(tmp_path):
    p = tmp_path / "odd.jsonl"
    p.write_text('3\n[1,2]\n{"a": 7}\n')

    class S(pw.Schema):
        a: int

    t = pw.io.jsonlines.read(str(p), schema=S, mode="static")
    assert sorted(_rows_of(t).values()) == [(7,)]


def test_kafka_dsv_format():
    broker = pw.io.kafka.MockBroker.get("mock://dsv")
    broker.produce("t", b"x;1")
    broker.produce("t", b"y;2")
    broker.close_topic("t")

    class S(pw.Schema):
        k: str
        v: int

    t = pw.io.kafka.read(
        {"bootstrap.servers": "mock://dsv"}, topic="t", schema=S, format="dsv"
    )
    assert sorted(_rows_of(t).values()) == [("x", 1), ("y", 2)]


def test_fs_csv_delimiter_passthrough(tmp_path):
    (tmp_path / "f.csv").write_text("a;b\n1;x\n")

    class S(pw.Schema):
        a: int
        b: str

    t = pw.io.fs.read(
        str(tmp_path),
        format="csv",
        schema=S,
        mode="static",
        csv_settings=pw.io.csv.CsvParserSettings(delimiter=";"),
    )
    assert sorted(_rows_of(t).values()) == [(1, "x")]


def test_jsonlines_invalid_utf8_line_skipped(tmp_path):
    """A non-UTF-8 line must be skipped (per-line fallback), not kill the
    reader thread (block parser raises UnicodeDecodeError = ValueError)."""
    import pathway_tpu as pw

    fp = tmp_path / "x.jsonl"
    fp.write_bytes(b'{"a": 1}\n{"a": \xff2}\n{"a": 3}\n')

    class S(pw.Schema):
        a: int

    t = pw.io.jsonlines.read(str(fp), schema=S, mode="static")
    res = pw.debug.table_to_pandas(t)
    assert sorted(res["a"].tolist()) == [1, 3]


def test_fs_line_longer_than_read_block(tmp_path, monkeypatch):
    """A single line longer than the block size must still be consumed
    (the block reader extends to the next newline instead of stalling)."""
    import pathway_tpu as pw

    fp = tmp_path / "y.jsonl"
    big = "x" * (9 << 20)  # > the 8 MiB read block
    fp.write_text('{"a": 7}\n{"a": 8, "pad": "%s"}\n{"a": 9}\n' % big)

    class S(pw.Schema):
        a: int

    t = pw.io.jsonlines.read(str(fp), schema=S, mode="static")
    res = pw.debug.table_to_pandas(t)
    assert sorted(res["a"].tolist()) == [7, 8, 9]


def test_s3_modified_object_retracts_old_version():
    """A changed object (new ETag/size) replaces its predecessor through
    the upsert session: re-added keys overwrite in place, vanished keys
    are deleted — the unchanged prefix never double-counts."""
    import threading
    import time

    import pathway_tpu as pw
    from pathway_tpu.io.s3 import AwsS3Settings, _parser_for, _S3Source

    class FakeClient:
        def __init__(self):
            self.objects = {"a.jsonl": b'{"v": 1}\n{"v": 2}\n'}

        def list_objects_v2(self, **kw):
            return {
                "Contents": [
                    {"Key": k, "ETag": str(hash(v)), "Size": len(v)}
                    for k, v in self.objects.items()
                ],
                "IsTruncated": False,
            }

        def get_object(self, Bucket, Key):
            return {"Body": self.objects[Key]}

    class S(pw.Schema):
        v: int

    client = FakeClient()
    settings = AwsS3Settings(bucket_name="b", client=client)
    src = _S3Source(
        settings, "", S, _parser_for("jsonlines", S, None),
        mode="streaming", poll_interval=0.05,
    )

    adds, removes, commits = [], [], [0]
    stop = threading.Event()

    class Events:
        @property
        def stopped(self):
            return stop.is_set()

        def add(self, key, row):
            adds.append((key, row))

        def remove(self, key, row):
            removes.append((key, row))

        def commit(self):
            commits[0] += 1

        def close(self):
            pass

    th = threading.Thread(target=src.run, args=(Events(),), daemon=True)
    th.start()
    deadline = time.time() + 5
    while commits[0] < 1 and time.time() < deadline:
        time.sleep(0.02)
    assert len(adds) == 2 and not removes
    # append a row -> new ETag/size: upsert re-add of the (unchanged)
    # prefix under the same keys + the new row; nothing vanished
    client.objects["a.jsonl"] += b'{"v": 3}\n'
    while commits[0] < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert len(adds) == 5 and not removes  # 2 + (2 re-upserts + 1 new)
    assert len({k for k, _ in adds}) == 3  # deterministic (object, seq) keys
    # shrink the object -> the vanished tail row is deleted BY KEY
    client.objects["a.jsonl"] = b'{"v": 1}\n'
    while commits[0] < 3 and time.time() < deadline:
        time.sleep(0.02)
    stop.set()
    th.join(timeout=5)
    assert len(removes) == 2  # rows 2 and 3's keys deleted
    add_keys = {k for k, _ in adds}
    assert all(k in add_keys for k, _ in removes)


def test_fs_binary_whole_file_streaming(tmp_path):
    """format='binary' reads one row per FILE and watches the dir:
    adds upsert, content changes overwrite, deletions retract."""
    (tmp_path / "a.txt").write_bytes(b"alpha")

    t = pw.io.fs.read(
        str(tmp_path), format="binary", mode="streaming",
        with_metadata=True, poll_interval=0.05,
    )
    events = []
    pw.io.subscribe(
        t,
        on_change=lambda k, row, tm, add: events.append(
            (add, row["_metadata"]["path"].rsplit("/", 1)[-1], row["data"])
        ),
    )
    from pathway_tpu.engine.scheduler import Scheduler
    from pathway_tpu.internals.parse_graph import G

    sched = Scheduler(G.engine_graph, autocommit_ms=20)
    run_t = threading.Thread(target=sched.run, daemon=True)
    run_t.start()

    def wait_for(pred, timeout=8.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return False

    assert wait_for(lambda: (True, "a.txt", b"alpha") in events)
    (tmp_path / "b.txt").write_bytes(b"beta")
    assert wait_for(lambda: (True, "b.txt", b"beta") in events)
    # rewrite: upsert retracts the old payload and adds the new
    time.sleep(0.05)  # distinct mtime
    (tmp_path / "a.txt").write_bytes(b"alpha-v2")
    assert wait_for(lambda: (True, "a.txt", b"alpha-v2") in events)
    assert wait_for(lambda: (False, "a.txt", b"alpha") in events)
    # deletion retracts
    (tmp_path / "b.txt").unlink()
    assert wait_for(lambda: any(not a and n == "b.txt" for a, n, _d in events))
    sched.stop()
    run_t.join(timeout=3)
