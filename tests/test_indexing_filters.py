"""Metadata filter language + index adapter edge cases (reference
JMESPath-subset filters, ``src/external_integration/mod.rs:92-181``, and
the BM25/hybrid/usearch adapter family).
"""

from __future__ import annotations

import numpy as np
import pytest

from pathway_tpu.stdlib.indexing.adapters import (
    BM25Adapter,
    HybridAdapter,
    KnnAdapter,
)
from pathway_tpu.stdlib.indexing.filters import compile_filter


# ---------------------------------------------------------------------------
# filter language


M = {
    "path": "/docs/report-2024.pdf",
    "owner": {"name": "ada", "age": 37},
    "tags": "alpha beta",
    "modified_at": 1700000000,
    "score": 2.5,
}


@pytest.mark.parametrize(
    "expr,expected",
    [
        ("modified_at == `1700000000`", True),
        ("modified_at != `1700000000`", False),
        ("modified_at > `1699999999`", True),
        ("modified_at >= `1700000000`", True),
        ("modified_at < `1700000000`", False),
        ("score == `2.5`", True),
        ("owner.name == 'ada'", True),
        ("owner.name == 'bob'", False),
        ("owner.age <= `37`", True),
        ("contains(tags, 'beta')", True),
        ("contains(tags, 'gamma')", False),
        ("globmatch('*.pdf', path)", True),
        ("globmatch('*.docx', path)", False),
        ("globmatch('/docs/*', path)", True),
        ("owner.name == 'ada' && score > `2`", True),
        ("owner.name == 'ada' && score > `3`", False),
        ("owner.name == 'bob' || contains(tags, 'alpha')", True),
        ("!(owner.name == 'bob')", True),
        ("!(owner.name == 'ada') || modified_at > `0`", True),
        ("(score > `2` || score < `1`) && owner.age == `37`", True),
    ],
)
def test_filter_expressions(expr, expected):
    assert compile_filter(expr)(M) is expected, expr


def test_filter_missing_fields_and_garbage_are_false():
    f = compile_filter("nosuch.field == 'x'")
    assert f(M) is False
    assert f({}) is False
    assert f(None) is False
    # comparing incompatible types fails closed, not loudly
    assert compile_filter("owner > `3`")(M) is False


def test_filter_memoization_returns_same_callable():
    a = compile_filter("score > `1`")
    b = compile_filter("score > `1`")
    assert a is b


def test_filter_quoting_variants():
    assert compile_filter('owner.name == "ada"')(M) is True
    assert compile_filter("path == '/docs/report-2024.pdf'")(M) is True


# ---------------------------------------------------------------------------
# adapters (batch API: add([(key, payload)]), search(payloads, ks, filters))


def test_bm25_rare_terms_outrank_common():
    docs = {
        1: "the quick brown fox",
        2: "the the the lazy dog",
        3: "quantum chromodynamics lattice",
    }
    idx = BM25Adapter()
    idx.add(list(docs.items()))
    res = idx.search(["quantum lattice"], [3], [None])[0]
    assert res[0][0] == 3
    res = idx.search(["the"], [3], [None])[0]
    assert {key for key, _score in res} <= {1, 2}


def test_bm25_removal_and_requery():
    idx = BM25Adapter()
    idx.add([(1, "alpha beta"), (2, "alpha gamma")])
    assert idx.search(["gamma"], [2], [None])[0][0][0] == 2
    idx.remove([2])
    res = idx.search(["gamma"], [2], [None])[0]
    assert all(key != 2 for key, _ in res)
    # re-add under the same key with new text
    idx.add([(2, "delta epsilon")])
    assert idx.search(["epsilon"], [1], [None])[0][0][0] == 2


def test_bm25_metadata_filter_applies():
    idx = BM25Adapter()
    idx.add(
        [
            (1, ("alpha report", {"path": "/a.pdf"})),
            (2, ("alpha summary", {"path": "/b.txt"})),
        ]
    )
    f = compile_filter("globmatch('*.pdf', path)")
    res = idx.search(["alpha"], [5], [f])[0]
    assert [key for key, _ in res] == [1]
    # same query unfiltered sees both
    res = idx.search(["alpha"], [5], [None])[0]
    assert {key for key, _ in res} == {1, 2}


def test_hybrid_rrf_fuses_lexical_and_vector():
    """A doc strong in BOTH modalities must outrank one that is strong
    in a single modality only (reciprocal rank fusion)."""
    vecs = {
        1: np.array([1.0, 0.0, 0.0], np.float32),
        2: np.array([0.9, 0.1, 0.0], np.float32),
        3: np.array([0.0, 1.0, 0.0], np.float32),
    }
    texts = {1: "apple pie recipe", 2: "apple tart", 3: "rocket engine"}
    knn = KnnAdapter(3, metric="cos")
    bm = BM25Adapter()
    hybrid = HybridAdapter([knn, bm])
    # hybrid add fans the same payload out to the children; feed the
    # children directly so each modality gets its own payload shape
    knn.add(list(vecs.items()))
    bm.add(list(texts.items()))
    # hybrid payloads are tuples with one element per child
    res = hybrid.search(
        [(np.array([1.0, 0.0, 0.0], np.float32), "apple")],
        [3],
        [None],
    )[0]
    assert res[0][0] in (1, 2)  # strong in both modalities
    assert res[-1][0] == 3


def test_knn_adapter_filter_and_churn():
    knn = KnnAdapter(4, metric="cos")
    rng = np.random.default_rng(0)
    rows = [
        (i, (rng.normal(size=4).astype(np.float32), {"grp": i % 2}))
        for i in range(20)
    ]
    knn.add(rows)
    q = rows[3][1][0]
    f = compile_filter("grp == `1`")
    res = knn.search([q], [5], [f])[0]
    assert res and all(key % 2 == 1 for key, _ in res)
    knn.remove([k for k, _p in rows if k % 2 == 1])
    res = knn.search([q], [5], [f])[0]
    assert res == []
