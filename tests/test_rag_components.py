"""RAG component behaviors: rerankers (cross-encoder, bi-encoder, LLM
judge), prompt templates, rerank_topk_filter, and the question-answering
flow with deterministic fakes (reference ``xpacks/llm/rerankers.py``,
``prompts.py``, ``question_answering.py``).
"""

from __future__ import annotations

import dataclasses

import pytest

import pathway_tpu as pw
from pathway_tpu.models import BGE_RERANKER_BASE, MINILM_L6
from pathway_tpu.xpacks.llm import prompts
from pathway_tpu.xpacks.llm.rerankers import (
    CrossEncoderReranker,
    EncoderReranker,
    LLMReranker,
    rerank_topk_filter,
)
from tests.utils import run_to_rows

import jax.numpy as jnp

TINY_CROSS = dataclasses.replace(
    BGE_RERANKER_BASE, layers=2, hidden=64, heads=4, mlp_dim=128,
    dtype=jnp.float32,
)
TINY_BI = dataclasses.replace(
    MINILM_L6, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
)


def test_prompt_templates_embed_docs_and_query():
    from pathway_tpu.internals.udfs import UDF

    def call(f, *args):
        return f.__wrapped__(*args) if isinstance(f, UDF) else f(*args)

    docs = [{"text": "alpha passage"}, {"text": "beta passage"}]
    for template in (
        prompts.prompt_qa_geometric_rag,
        prompts.prompt_short_qa,
        prompts.prompt_citing_qa,
    ):
        out = call(template, "why alpha?", docs)
        assert "why alpha?" in out
        assert "alpha passage" in out and "beta passage" in out
    s = call(prompts.prompt_summarize, ["one", "two"])
    assert "one" in s and "two" in s
    r = call(prompts.prompt_query_rewrite, "original question")
    assert "original question" in r


def test_cross_encoder_reranker_scores_batch():
    rr = CrossEncoderReranker(config=TINY_CROSS)
    scores = rr.__batch__(
        ["doc about apples", "doc about rockets"],
        ["apples", "apples"],
    )
    assert len(scores) == 2
    assert all(isinstance(s, float) for s in scores)
    # single-call path agrees with the batch path
    single = rr.__wrapped__("doc about apples", "apples")
    assert single == pytest.approx(scores[0], rel=1e-3, abs=1e-3)


def test_encoder_reranker_prefers_similar_text():
    from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder

    rr = EncoderReranker(embedder=TPUEncoderEmbedder(config=TINY_BI))
    scores = rr.__batch__(
        ["apples apples apples", "totally unrelated rocket engine"],
        ["apples apples apples", "apples apples apples"],
    )
    assert scores[0] > scores[1]  # identical text outranks unrelated


def test_llm_reranker_parses_scores_and_contains_garbage():
    class FakeChat:
        def __init__(self, replies):
            self.replies = list(replies)

        def __wrapped__(self, messages, **kw):
            return self.replies.pop(0)

    rr = LLMReranker(llm=FakeChat(["4", "not-a-number", "1"]))
    s1 = rr.__wrapped__("good doc", "q")
    s2 = rr.__wrapped__("weird doc", "q")
    s3 = rr.__wrapped__("bad doc", "q")
    assert s1 == 4.0 and s3 == 1.0
    assert s2 is None or isinstance(s2, float)


def test_rerank_topk_filter_in_pipeline():
    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(docs=tuple, scores=tuple),
        [((("d1", "d2", "d3", "d4"), (0.1, 0.9, 0.5, 0.7)))],
    )
    out = t.select(
        top=rerank_topk_filter(t.docs, t.scores, 2)
        if callable(rerank_topk_filter)
        else None
    )
    ((top,),) = run_to_rows(out)
    docs, scores = top
    assert list(docs) == ["d2", "d4"]  # best two by score
    assert list(scores) == [0.9, 0.7]


def test_adaptive_rag_widens_on_no_answer():
    """AdaptiveRAGQuestionAnswerer retries with geometrically more docs
    until the LLM stops saying 'No information found' (reference
    answer_with_geometric_rag_strategy)."""
    from pathway_tpu.xpacks.llm.question_answering import (
        AdaptiveRAGQuestionAnswerer,
    )
    from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
    from pathway_tpu.xpacks.llm.document_store import DocumentStore
    from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder

    pw.G.clear()
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(data=bytes, _metadata=dict),
        [
            (f"filler document {i} with unrelated text".encode(), {"path": f"/f{i}.txt"})
            for i in range(4)
        ]
        + [(b"the answer is forty-two", {"path": "/answer.txt"})],
    )
    store = DocumentStore(
        docs,
        retriever_factory=BruteForceKnnFactory(
            embedder=TPUEncoderEmbedder(config=TINY_BI), reserved_space=32
        ),
    )

    calls = []

    class CountingChat:
        def __wrapped__(self, messages, **kw):
            calls.append(messages)
            text = messages[0]["content"]
            if "forty-two" in text:
                return "forty-two"
            return "No information found."

    qa = AdaptiveRAGQuestionAnswerer(
        llm=CountingChat(), indexer=store, n_starting_documents=1, factor=2
    )
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(
            prompt=str, filters=str, model=str, return_context_docs=bool
        ),
        [("what is the answer", None, None, False)],
    )
    out = qa.answer_query(queries)
    ((result,),) = run_to_rows(out.select(out.result))
    answer = result["response"] if isinstance(result, dict) else result
    assert "forty-two" in str(answer)
    assert len(calls) >= 1  # widened until the answer doc entered context
