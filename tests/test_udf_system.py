"""UDF system: retry strategies, caches, executors, capacity/timeout
combinators — the reference's udfs package behaviors
(``python/pathway/internals/udfs/``: retries.py, caches.py,
executors.py), previously covered only incidentally through pipelines.
"""

from __future__ import annotations

import asyncio
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from tests.utils import run_to_rows


# ---------------------------------------------------------------------------
# retry strategies


def test_fixed_delay_retry_retries_then_succeeds():
    calls = []

    async def flaky(x):
        calls.append(x)
        if len(calls) < 3:
            raise ValueError("transient")
        return x * 10

    strat = udfs.FixedDelayRetryStrategy(max_retries=5, delay_ms=1)
    out = asyncio.run(strat.invoke(flaky, 4))
    assert out == 40
    assert len(calls) == 3


def test_fixed_delay_retry_exhausts_and_raises():
    async def always_fails():
        raise RuntimeError("permanent")

    strat = udfs.FixedDelayRetryStrategy(max_retries=2, delay_ms=1)
    with pytest.raises(RuntimeError, match="permanent"):
        asyncio.run(strat.invoke(always_fails))


def test_exponential_backoff_delay_growth():
    strat = udfs.ExponentialBackoffRetryStrategy(
        max_retries=4, initial_delay=100, backoff_factor=2, jitter_ms=0
    )
    delays = [strat._next_delay(a) for a in range(4)]
    # jitter off: exact doubling from the initial delay
    assert delays == [0.1, 0.2, 0.4, 0.8], delays


def test_no_retry_strategy_single_attempt():
    calls = []

    async def fails():
        calls.append(1)
        raise ValueError("once")

    with pytest.raises(ValueError):
        asyncio.run(udfs.NoRetryStrategy().invoke(fails))
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# caches


def test_in_memory_cache_memoizes_by_args():
    calls = []

    async def f(x, y=1):
        calls.append((x, y))
        return x + y

    wrapped = udfs.InMemoryCache().make_wrapper(f)
    assert asyncio.run(wrapped(1, y=2)) == 3
    assert asyncio.run(wrapped(1, y=2)) == 3
    assert asyncio.run(wrapped(2, y=2)) == 4
    assert calls == [(1, 2), (2, 2)]


def test_disk_cache_persists_across_instances(tmp_path):
    calls = []

    async def f(x):
        calls.append(x)
        return x * 2

    c1 = udfs.DiskCache(directory=str(tmp_path))
    assert asyncio.run(c1.make_wrapper(f)(21)) == 42
    # a FRESH cache over the same dir serves from disk
    c2 = udfs.DiskCache(directory=str(tmp_path))
    assert asyncio.run(c2.make_wrapper(f)(21)) == 42
    assert calls == [21]


def test_default_cache_exists():
    """pw.udfs.DefaultCache is the YAML-template alias the reference apps
    use (app.yaml: cache_strategy: !pw.udfs.DefaultCache)."""
    assert hasattr(udfs, "DefaultCache")
    c = udfs.DefaultCache()
    assert isinstance(c, udfs.CacheStrategy)


# ---------------------------------------------------------------------------
# combinators


def test_with_capacity_bounds_concurrency():
    peak = {"now": 0, "max": 0}

    async def slow(x):
        peak["now"] += 1
        peak["max"] = max(peak["max"], peak["now"])
        await asyncio.sleep(0.02)
        peak["now"] -= 1
        return x

    bounded = udfs.with_capacity(slow, 3)

    async def fan_out():
        return await asyncio.gather(*[bounded(i) for i in range(10)])

    out = asyncio.run(fan_out())
    assert out == list(range(10))
    assert peak["max"] <= 3, peak


def test_with_timeout_raises_on_slow_call():
    async def slow():
        await asyncio.sleep(1.0)

    fast = udfs.with_timeout(slow, 0.05)
    with pytest.raises(Exception):
        asyncio.run(fast())


def test_coerce_async_wraps_sync_function():
    out = asyncio.run(udfs.coerce_async(lambda x: x + 1)(4))
    assert out == 5


# ---------------------------------------------------------------------------
# UDF decorator through pipelines


def _t():
    return pw.debug.table_from_rows(
        pw.schema_from_types(x=int), [(1,), (2,), (3,)]
    )


def test_udf_decorator_sync_pipeline():
    @pw.udf
    def double(x: int) -> int:
        return x * 2

    pw.G.clear()
    out = _t().select(y=double(pw.this.x))
    assert sorted(run_to_rows(out)) == [(2,), (4,), (6,)]


def test_udf_async_executor_with_retries_in_pipeline():
    attempts: dict[int, int] = {}

    @pw.udf(
        executor=udfs.async_executor(
            capacity=2,
            retry_strategy=udfs.FixedDelayRetryStrategy(
                max_retries=3, delay_ms=1
            ),
        )
    )
    async def flaky_double(x: int) -> int:
        attempts[x] = attempts.get(x, 0) + 1
        if attempts[x] == 1:
            raise ValueError("first attempt always fails")
        return x * 2

    pw.G.clear()
    out = _t().select(y=flaky_double(pw.this.x))
    assert sorted(run_to_rows(out)) == [(2,), (4,), (6,)]
    assert all(n >= 2 for n in attempts.values())


def test_udf_cache_strategy_in_pipeline():
    calls = []

    @pw.udf(cache_strategy=udfs.InMemoryCache())
    def tracked(x: int) -> int:
        calls.append(x)
        return x + 100

    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=int), [(7,), (7,), (8,)]
    )
    out = t.select(y=tracked(t.x))
    assert sorted(run_to_rows(out)) == [(107,), (107,), (108,)]
    assert sorted(calls) == [7, 8]  # duplicate argument served from cache


def test_udf_batched_via_batch_hook():
    """UDFs defining __batch__ evaluate whole epochs in one call."""

    class BatchSquare(udfs.UDF):
        def __init__(self):
            super().__init__()
            self.batches = []

        def __wrapped__(self, x):
            raise AssertionError("per-row path must not run")

        def __batch__(self, xs):
            self.batches.append(len(xs))
            return [x * x for x in xs]

    u = BatchSquare()
    pw.G.clear()
    out = _t().select(y=u(pw.this.x))
    assert sorted(run_to_rows(out)) == [(1,), (4,), (9,)]
    assert u.batches == [3]
