"""Tier-1 tests for the concurrency-discipline lint
(``scripts/check_locks.py``): each rule has a trigger and a near-miss,
and the real engine files must come back clean."""

from __future__ import annotations

import importlib.util
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "check_locks.py"


@pytest.fixture(scope="module")
def cl():
    spec = importlib.util.spec_from_file_location("check_locks", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["check_locks"] = mod  # dataclasses resolves via sys.modules
    spec.loader.exec_module(mod)
    return mod


def test_lk001_bare_cv_wait_flagged(cl):
    src = (
        "class W:\n"
        "    def wait_one(self):\n"
        "        with self._lock:\n"
        "            self._cv.wait()\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK001"]


def test_lk001_wait_in_while_clean(cl):
    src = (
        "class W:\n"
        "    def wait_one(self):\n"
        "        with self._lock:\n"
        "            while not self._ready:\n"
        "                self._cv.wait()\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk001_generation_wait_clean(cl):
    # the WakeupHub idiom: no while loop, but the predicate is
    # re-checked after the wait (statement follows the wait call)
    src = (
        "class Hub:\n"
        "    def wait(self, seen, timeout):\n"
        "        with self._cv:\n"
        "            if self._seq != seen:\n"
        "                return True\n"
        "            self._cv.wait(timeout)\n"
        "            return self._seq != seen\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk002_inverted_lock_order_flagged(cl):
    src = (
        "class S:\n"
        "    def a(self):\n"
        "        with self._cb_lock:\n"
        "            with self._prober_lock:\n"
        "                pass\n"
        "    def b(self):\n"
        "        with self._prober_lock:\n"
        "            with self._cb_lock:\n"
        "                pass\n"
    )
    findings = cl.check_lock_order([(src, "y.py")])
    assert [f.code for f in findings] == ["LK002"]


def test_lk002_consistent_lock_order_clean(cl):
    src = (
        "class S:\n"
        "    def a(self):\n"
        "        with self._cb_lock:\n"
        "            with self._prober_lock:\n"
        "                pass\n"
        "    def b(self):\n"
        "        with self._cb_lock:\n"
        "            with self._prober_lock:\n"
        "                pass\n"
    )
    assert cl.check_lock_order([(src, "y.py")]) == []


def test_lk003_sleep_in_scheduler_flagged(cl):
    src = "import time\ndef drain():\n    time.sleep(0.01)\n"
    findings = cl.check_source(src, "scheduler.py")
    assert [f.code for f in findings] == ["LK003"]


def test_lk003_sleep_in_cluster_allowed(cl):
    # dial-retry sleeps in cluster.py are deliberate
    src = "import time as _time\ndef _dial():\n    _time.sleep(0.05)\n"
    assert cl.check_source(src, "cluster.py") == []


def test_lk004_notify_without_lock_flagged(cl):
    src = (
        "class S:\n"
        "    def kick(self):\n"
        "        self._cv.notify_all()\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK004"]


def test_lk004_notify_under_cv_clean(cl):
    src = (
        "class S:\n"
        "    def kick(self):\n"
        "        with self._cv:\n"
        "            self._seq += 1\n"
        "            self._cv.notify_all()\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk004_notify_under_associated_lock_clean(cl):
    # condvar built over an explicit lock: holding the lock suffices
    src = (
        "class S:\n"
        "    def kick(self):\n"
        "        with self._state_lock:\n"
        "            self.cond.notify()\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk004_non_cv_notify_ignored(cl):
    # WakeupHub / Event style single-waiter primitives are not condvars
    src = "def kick(hub):\n    hub.notify()\n"
    assert cl.check_source(src, "x.py") == []


def test_lk005_settimeout_none_flagged(cl):
    src = (
        "class R:\n"
        "    def arm(self):\n"
        "        self.sock.settimeout(None)\n"
    )
    findings = cl.check_source(src, "cluster.py")
    assert [f.code for f in findings] == ["LK005"]


def test_lk005_recv_without_timeout_flagged(cl):
    src = (
        "class R:\n"
        "    def read(self):\n"
        "        return self.sock.recv(4096)\n"
    )
    findings = cl.check_source(src, "cluster.py")
    assert [f.code for f in findings] == ["LK005"]


def test_lk005_recv_under_finite_timeout_clean(cl):
    # the liveness idiom: a finite settimeout anywhere in the class
    # bounds every recv; timeouts feed the per-peer liveness deadline
    src = (
        "class R:\n"
        "    def start(self):\n"
        "        self.sock.settimeout(0.5)\n"
        "    def read(self):\n"
        "        return self.sock.recv_into(self.view)\n"
    )
    assert cl.check_source(src, "cluster.py") == []


def test_lk005_untimed_cv_wait_flagged(cl):
    # inside a while loop LK001 is satisfied, but in a cluster path the
    # wait still needs a timeout — the notifier may be a dead peer
    src = (
        "class R:\n"
        "    def pump(self):\n"
        "        with self._cv:\n"
        "            while not self._q:\n"
        "                self._cv.wait()\n"
    )
    findings = cl.check_source(src, "cluster.py")
    assert [f.code for f in findings] == ["LK005"]


def test_lk005_timed_cv_wait_clean(cl):
    src = (
        "class R:\n"
        "    def pump(self):\n"
        "        with self._cv:\n"
        "            while not self._q:\n"
        "                self._cv.wait(1.0)\n"
    )
    assert cl.check_source(src, "cluster.py") == []


def test_lk005_not_applied_outside_cluster_paths(cl):
    # single-worker scheduler code may block indefinitely on local
    # producers; LK005 is a cluster-path rule only
    src = (
        "class R:\n"
        "    def read(self):\n"
        "        return self.sock.recv(4096)\n"
    )
    assert cl.check_source(src, "scheduler.py") == []


def test_lk006_bare_event_wait_flagged(cl):
    src = (
        "def park(ev):\n"
        "    ev.wait()\n"
    )
    findings = cl.check_source(src, "pathway_tpu/serving/admission.py")
    assert [f.code for f in findings] == ["LK006"]


def test_lk006_none_timeout_flagged(cl):
    src = (
        "def park(ev):\n"
        "    ev.wait(timeout=None)\n"
    )
    findings = cl.check_source(src, "pathway_tpu/serving/admission.py")
    assert [f.code for f in findings] == ["LK006"]


def test_lk006_finite_wait_clean(cl):
    src = (
        "def park(ev):\n"
        "    ev.wait(0.05)\n"
    )
    assert cl.check_source(src, "pathway_tpu/serving/admission.py") == []


def test_lk006_unbounded_result_and_join_flagged(cl):
    src = (
        "def settle(fut, t):\n"
        "    fut.result()\n"
        "    t.join()\n"
    )
    findings = cl.check_source(src, "pathway_tpu/serving/graph.py")
    assert [f.code for f in findings] == ["LK006", "LK006"]


def test_lk006_bounded_result_and_join_clean(cl):
    src = (
        "def settle(fut, t):\n"
        "    fut.result(timeout=30)\n"
        "    t.join(5.0)\n"
    )
    assert cl.check_source(src, "pathway_tpu/serving/graph.py") == []


def test_lk006_time_sleep_flagged(cl):
    src = (
        "import time\n"
        "def poll():\n"
        "    time.sleep(0.1)\n"
    )
    findings = cl.check_source(src, "pathway_tpu/serving/loadgen.py")
    assert [f.code for f in findings] == ["LK006"]


def test_lk006_not_applied_outside_serving_paths(cl):
    # tooling and tests may block; LK006 is a serving-path rule only
    src = (
        "def settle(fut):\n"
        "    fut.result()\n"
    )
    assert cl.check_source(src, "x.py") == []
    # and the override forces it on for any path
    findings = cl.check_source(src, "x.py", serving_path=True)
    assert [f.code for f in findings] == ["LK006"]


def test_lk008_producer_only_queue_flagged(cl):
    src = (
        "from collections import deque\n"
        "class Tap:\n"
        "    def __init__(self):\n"
        "        self._backlog = deque()\n"
        "    def feed(self, item):\n"
        "        self._backlog.append(item)\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK008"]
    assert "_backlog" in findings[0].message


def test_lk008_unbounded_queue_queue_flagged(cl):
    src = (
        "import queue\n"
        "class Tap:\n"
        "    def __init__(self):\n"
        "        self._inbox = queue.Queue()\n"
        "    def feed(self, item):\n"
        "        self._inbox.put(item)\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK008"]


def test_lk008_bounded_deque_clean(cl):
    # maxlen caps the container: append-only is fine
    src = (
        "from collections import deque\n"
        "class Tap:\n"
        "    def __init__(self):\n"
        "        self._backlog = deque(maxlen=1024)\n"
        "    def feed(self, item):\n"
        "        self._backlog.append(item)\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk008_drained_queue_clean(cl):
    # a consumer anywhere in the class bounds steady-state occupancy
    src = (
        "from collections import deque\n"
        "class Tap:\n"
        "    def __init__(self):\n"
        "        self._backlog = deque()\n"
        "    def feed(self, item):\n"
        "        self._backlog.append(item)\n"
        "    def drain(self):\n"
        "        while self._backlog:\n"
        "            yield self._backlog.popleft()\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk008_swap_drain_idiom_clean(cl):
    # the batch, self._q = self._q, [] handoff counts as eviction
    src = (
        "class Tap:\n"
        "    def __init__(self):\n"
        "        self._q = []\n"
        "    def feed(self, item):\n"
        "        self._q.append(item)\n"
        "    def drain(self):\n"
        "        batch, self._q = self._q, []\n"
        "        return batch\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk008_cache_without_eviction_flagged(cl):
    src = (
        "class Resolver:\n"
        "    def __init__(self):\n"
        "        self._cache = {}\n"
        "    def lookup(self, k):\n"
        "        if k not in self._cache:\n"
        "            self._cache[k] = self._slow(k)\n"
        "        return self._cache[k]\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK008"]
    assert "_cache" in findings[0].message


def test_lk008_cache_with_eviction_clean(cl):
    src = (
        "class Resolver:\n"
        "    def __init__(self):\n"
        "        self._cache = {}\n"
        "    def lookup(self, k):\n"
        "        if k not in self._cache:\n"
        "            self._cache[k] = self._slow(k)\n"
        "        return self._cache[k]\n"
        "    def invalidate(self):\n"
        "        self._cache.clear()\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk008_non_cache_named_dict_ignored(cl):
    # bounded-by-construction members (keyed by peer/worker id) don't
    # get flagged just for lacking eviction — only confessed caches do
    src = (
        "class Tracker:\n"
        "    def __init__(self):\n"
        "        self._last_seen_at = {}\n"
        "    def mark(self, peer, now):\n"
        "        self._last_seen_at[peer] = now\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk009_drained_but_unbounded_deque_flagged(cl):
    # drained ⇒ LK008 stays quiet, but the queue is still a backpressure
    # hole in an engine path: the producer never feels a slow consumer
    src = (
        "from collections import deque\n"
        "class Pipe:\n"
        "    def __init__(self):\n"
        "        self._q = deque()\n"
        "    def feed(self, item):\n"
        "        self._q.append(item)\n"
        "    def drain(self):\n"
        "        while self._q:\n"
        "            yield self._q.popleft()\n"
    )
    findings = cl.check_source(src, "pathway_tpu/engine/x.py")
    assert [f.code for f in findings] == ["LK009"]
    assert "maxsize/maxlen" in findings[0].message


def test_lk009_local_handoff_queue_flagged(cl):
    # local (non-self) producer-consumer queues count too — LK008 is
    # class-member-scoped, LK009 is not
    src = (
        "import queue\n"
        "def pump(rows):\n"
        "    q = queue.Queue()\n"
        "    for r in rows:\n"
        "        q.put(r)\n"
        "    while not q.empty():\n"
        "        yield q.get()\n"
    )
    findings = cl.check_source(src, "pathway_tpu/io/x.py")
    assert [f.code for f in findings] == ["LK009"]


def test_lk009_bounded_queue_clean(cl):
    src = (
        "import queue\n"
        "from collections import deque\n"
        "class Pipe:\n"
        "    def __init__(self):\n"
        "        self._q = queue.Queue(maxsize=1024)\n"
        "        self._d = deque(maxlen=64)\n"
        "    def feed(self, item):\n"
        "        self._q.put(item)\n"
        "        self._d.append(item)\n"
        "    def drain(self):\n"
        "        self._d.clear()\n"
        "        return self._q.get(timeout=1.0)\n"
    )
    assert cl.check_source(src, "pathway_tpu/serving/x.py") == []


def test_lk009_allowlist_comment_clean(cl):
    # the external-bound confession on the construction line allowlists
    # it — the marker doubles as documentation of where the bound lives
    src = (
        "from collections import deque\n"
        "class Pipe:\n"
        "    def __init__(self):\n"
        "        self._q = deque()  # lk009: bytes-bounded by credit accounting\n"
        "    def feed(self, item):\n"
        "        self._q.append(item)\n"
        "    def drain(self):\n"
        "        return self._q.popleft()\n"
    )
    assert cl.check_source(src, "pathway_tpu/engine/x.py") == []


def test_lk009_outside_pressure_paths_clean(cl):
    # same source, non-producer-consumer path: LK009 does not apply
    # (the drained queue also satisfies LK008)
    src = (
        "from collections import deque\n"
        "class Pipe:\n"
        "    def __init__(self):\n"
        "        self._q = deque()\n"
        "    def feed(self, item):\n"
        "        self._q.append(item)\n"
        "    def drain(self):\n"
        "        return self._q.popleft()\n"
    )
    assert cl.check_source(src, "pathway_tpu/internals/x.py") == []
    assert cl.check_source(
        src, "pathway_tpu/engine/x.py", pressure_path=False
    ) == []


_LK007_CYCLE = (
    "import threading\n"
    "class Store:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.idx = Index()\n"
    "    def put(self):\n"
    "        with self._lock:\n"
    "            self.idx.refresh()\n"
    "class Index:\n"
    "    def __init__(self):\n"
    "        self._main_mutex = threading.Lock()\n"
    "        self.store = None\n"
    "    def attach(self, s):\n"
    "        self.store = Store()\n"
    "    def refresh(self):\n"
    "        with self._main_mutex:\n"
    "            pass\n"
    "    def merge(self):\n"
    "        with self._main_mutex:\n"
    "            self.store.put()\n"
)


def test_lk007_planted_cycle_flagged(cl):
    """``Store.put`` holds ``Store._lock`` while (transitively) taking
    ``Index._main_mutex``; ``Index.merge`` nests the other way round."""
    findings = cl.check_lock_graph([(_LK007_CYCLE, "plant.py")])
    assert [f.code for f in findings] == ["LK007"]
    msg = findings[0].message
    assert "Store._lock" in msg and "Index._main_mutex" in msg
    # the full lock-order path names each edge's acquisition site
    assert "plant.py" in msg and "via" in msg


def test_lk007_consistent_global_order_clean(cl):
    # same classes, but merge() calls put() OUTSIDE the mutex: both
    # paths then acquire Store._lock before Index._main_mutex
    src = _LK007_CYCLE.replace(
        "    def merge(self):\n"
        "        with self._main_mutex:\n"
        "            self.store.put()\n",
        "    def merge(self):\n"
        "        self.store.put()\n"
        "        with self._main_mutex:\n"
        "            pass\n",
    )
    assert cl.check_lock_graph([(src, "plant.py")]) == []


def test_lk007_same_lock_reentry_not_a_cycle(cl):
    # two instances of one class taking each other's (same-named) lock
    # is a same-id self-edge, which instance-blind analysis must skip
    src = (
        "import threading\n"
        "class Shard:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.peer = None\n"
        "    def attach(self):\n"
        "        self.peer = Shard()\n"
        "    def pull(self):\n"
        "        with self._lock:\n"
        "            self.peer.push()\n"
        "    def push(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    assert cl.check_lock_graph([(src, "plant.py")]) == []


def test_lk007_whole_repo_roots_exist(cl):
    for root in cl.LOCK_GRAPH_ROOTS:
        assert (REPO / root).is_dir(), root


# ---------------------------------------------------------------- LK010


def test_lk010_device_put_under_lock_flagged(cl):
    src = (
        "import jax\n"
        "class Index:\n"
        "    def add(self, v):\n"
        "        with self._lock:\n"
        "            self._buf = jax.device_put(v)\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK010"]
    assert "device_put" in findings[0].message


def test_lk010_jnp_dispatch_and_sync_under_lock_flagged(cl):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "class Index:\n"
        "    def merge(self, xs):\n"
        "        with self._mutex:\n"
        "            self._buf = jnp.stack(xs)\n"
        "            self._buf.block_until_ready()\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK010", "LK010"]


def test_lk010_jitted_call_under_lock_flagged(cl):
    src = (
        "import jax\n"
        "class Index:\n"
        "    def query(self, q):\n"
        "        with self._lock:\n"
        "            return self._search_jit(5)(q)\n"
    )
    findings = cl.check_source(src, "x.py")
    assert [f.code for f in findings] == ["LK010"]


def test_lk010_stage_outside_swap_inside_clean(cl):
    # the scatter-swap idiom: device work staged lock-free, the lock
    # held only for the reference swap
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "class Index:\n"
        "    def add(self, v):\n"
        "        dev = jax.device_put(v)\n"
        "        with self._lock:\n"
        "            self._buf = dev\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk010_copy_to_host_async_exempt(cl):
    src = (
        "import jax\n"
        "class Index:\n"
        "    def pipeline(self, out):\n"
        "        with self._lock:\n"
        "            out.copy_to_host_async()\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk010_allowlist_comment_clean(cl):
    src = (
        "import jax\n"
        "class Index:\n"
        "    def add(self, v):\n"
        "        with self._lock:\n"
        "            self._buf = jax.device_put(v)  # lk010: 4 KiB control block\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk010_no_jax_import_not_a_device_path(cl):
    # without a jax import the file is host-only: device_put here is
    # some other library's name, not a transfer
    src = (
        "class Index:\n"
        "    def add(self, v):\n"
        "        with self._lock:\n"
        "            self._buf = device_put(v)\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk010_nested_def_under_lock_runs_later(cl):
    # a closure defined under the lock executes at an unknown lock
    # state — its body is scanned lock-free
    src = (
        "import jax.numpy as jnp\n"
        "class Index:\n"
        "    def later(self):\n"
        "        with self._lock:\n"
        "            def cb():\n"
        "                return jnp.stack(self._bufs)\n"
        "            self._cb = cb\n"
    )
    assert cl.check_source(src, "x.py") == []


def test_lk010_device_path_override(cl):
    # device_path=True forces the check on a file with no jax import:
    # jitted-name dispatch still resolves
    src = (
        "class Index:\n"
        "    def query(self, q):\n"
        "        with self._lock:\n"
        "            return self._encode_jit(q)\n"
    )
    findings = cl.check_source(src, "x.py", device_path=True)
    assert [f.code for f in findings] == ["LK010"]


def test_engine_files_clean():
    """The shipped cluster/scheduler must satisfy the discipline; this
    is the gate that keeps future edits honest."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
