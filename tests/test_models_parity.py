"""Weight-loading parity: HF checkpoint → flax conversion and WordPiece
tokenization must reproduce the torch reference exactly.

No network: a tiny BERT checkpoint is fabricated locally with torch
``transformers`` (CPU) and compared leaf-for-leaf.  With real MiniLM/BGE
weights dropped into a directory, the same code paths load them
(``pathway_tpu.models.convert.load_encoder``).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from pathway_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    convert_bert_checkpoint,
    load_encoder,
    load_state_dict,
)
from pathway_tpu.models.wordpiece import WordPieceTokenizer  # noqa: E402

VOCAB = (
    "[PAD] [unused0] [UNK] [CLS] [SEP] [MASK] the quick brown fox jumps over "
    "lazy dog un ##aff ##able run ##ning , . ! ? ' \" - hello world stream "
    "##ing data ##flow 2 ##0 ##2 ##4 tpu"
).split()


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    """Tiny random-init BERT saved exactly like an HF checkpoint dir."""
    d = tmp_path_factory.mktemp("tiny_bert")
    cfg = transformers.BertConfig(
        vocab_size=len(VOCAB),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=64,
        type_vocab_size=2,
        hidden_act="gelu",
    )
    torch.manual_seed(0)
    model = transformers.BertModel(cfg)
    model.eval()
    model.save_pretrained(str(d))
    with open(d / "vocab.txt", "w") as f:
        f.write("\n".join(VOCAB))
    return str(d), model


SENTENCES = [
    "The quick brown fox jumps over the lazy dog!",
    "hello world, streaming dataflow",
    "unaffable running data 2024 tpu",
    "the the the",
]


def test_wordpiece_matches_hf_bert_tokenizer(checkpoint):
    d, _model = checkpoint
    hf_tok = transformers.BertTokenizer(os.path.join(d, "vocab.txt"))
    ours = WordPieceTokenizer(os.path.join(d, "vocab.txt"))
    tricky = SENTENCES + [
        "  double  spaces\tand\nnewlines ",
        "punct,punct.punct!end?",
        "ACCENTS: café résumé",
        "unknownword xyzzy",
        "",
        "##weird ## tokens",
    ]
    for s in tricky:
        expected = hf_tok.encode(s, add_special_tokens=True)
        ids, mask, _t = ours.encode_batch([s], max_len=64, bucket_len=False)
        got = [int(i) for i in ids[0][: int(mask[0].sum())]]
        assert got == expected, (s, got, expected)


def test_wordpiece_pair_encoding_matches_hf(checkpoint):
    d, _ = checkpoint
    hf_tok = transformers.BertTokenizer(os.path.join(d, "vocab.txt"))
    ours = WordPieceTokenizer(os.path.join(d, "vocab.txt"))
    q, doc = "quick fox?", "the lazy dog runs over the fox."
    enc = hf_tok(q, doc, truncation=True, max_length=16)
    ids, mask, tps = ours.encode_batch([q], pair=[doc], max_len=16, bucket_len=False)
    n = int(mask[0].sum())
    assert [int(i) for i in ids[0][:n]] == enc["input_ids"]
    assert [int(i) for i in tps[0][:n]] == enc["token_type_ids"]


def _embed_torch(model, tok_dir, sentences, pool):
    hf_tok = transformers.BertTokenizer(os.path.join(tok_dir, "vocab.txt"))
    enc = hf_tok(sentences, padding=True, return_tensors="pt")
    with torch.no_grad():
        out = model(**enc).last_hidden_state  # [B, L, H]
    if pool == "cls":
        pooled = out[:, 0]
    else:
        m = enc["attention_mask"].unsqueeze(-1).float()
        pooled = (out * m).sum(1) / m.sum(1)
    pooled = torch.nn.functional.normalize(pooled, dim=-1)
    return pooled.numpy()


@pytest.mark.parametrize("pool", ["mean", "cls"])
def test_converted_encoder_matches_torch(checkpoint, pool):
    """cosine >= 0.999 between flax (converted weights, f32) and torch."""
    import jax.numpy as jnp

    from pathway_tpu.models.encoder import TextEncoderModel

    d, model = checkpoint
    cfg = config_from_hf(d, pool=pool, dtype=jnp.float32)
    params = convert_bert_checkpoint(load_state_dict(d), cfg)
    ours_tok = WordPieceTokenizer(os.path.join(d, "vocab.txt"))
    ids, mask, tps = ours_tok.encode_batch(SENTENCES, max_len=64, bucket_len=False)
    # trim to the true longest row: torch pads to longest too
    n = int(mask.sum(axis=1).max())
    flax_emb = np.asarray(
        TextEncoderModel(cfg).apply(
            params, jnp.asarray(ids[:, :n]), jnp.asarray(mask[:, :n]),
            jnp.asarray(tps[:, :n]),
        )
    )
    torch_emb = _embed_torch(model, d, SENTENCES, pool)
    cos = (flax_emb * torch_emb).sum(axis=1)
    assert cos.min() >= 0.999, cos


def test_converted_cross_encoder_matches_torch(checkpoint, tmp_path):
    import jax.numpy as jnp

    from pathway_tpu.models.encoder import CrossEncoderModel

    d, _ = checkpoint
    cfg = transformers.BertConfig(
        vocab_size=len(VOCAB),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=64,
        type_vocab_size=2,
        hidden_act="gelu",
        num_labels=1,
    )
    torch.manual_seed(1)
    ce = transformers.BertForSequenceClassification(cfg)
    ce.eval()
    ce_dir = tmp_path / "ce"
    ce.save_pretrained(str(ce_dir))
    (ce_dir / "vocab.txt").write_text("\n".join(VOCAB))

    mcfg = config_from_hf(str(ce_dir), pool="cls", num_labels=1, dtype=jnp.float32)
    params = convert_bert_checkpoint(load_state_dict(str(ce_dir)), mcfg)
    tok = WordPieceTokenizer(str(ce_dir / "vocab.txt"))
    q = ["quick fox", "hello world"]
    docs = ["the lazy dog", "streaming dataflow 2024"]
    ids, mask, tps = tok.encode_batch(q, pair=docs, max_len=64, bucket_len=False)
    flax_scores = np.asarray(
        CrossEncoderModel(mcfg).apply(
            params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(tps)
        )
    )
    hf_tok = transformers.BertTokenizer(str(ce_dir / "vocab.txt"))
    enc = hf_tok(q, docs, padding=True, return_tensors="pt")
    with torch.no_grad():
        torch_scores = ce(**enc).logits[:, 0].numpy()
    np.testing.assert_allclose(flax_scores, torch_scores, rtol=1e-3, atol=1e-3)


def test_load_encoder_one_call(checkpoint):
    import jax.numpy as jnp

    d, _ = checkpoint
    model, params, tok = load_encoder(d, pool="mean", dtype=jnp.float32)
    assert tok is not None
    ids, mask, tps = tok.encode_batch(["hello world"], max_len=32)
    emb = model.apply(params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(tps))
    assert emb.shape == (1, 32)
    assert np.isfinite(np.asarray(emb)).all()


def test_convert_config_json_roundtrip(checkpoint):
    d, _ = checkpoint
    cfg = config_from_hf(d)
    with open(os.path.join(d, "config.json")) as f:
        hf = json.load(f)
    assert cfg.hidden == hf["hidden_size"]
    assert cfg.layers == hf["num_hidden_layers"]
    assert cfg.gelu_approx is False  # "gelu" == exact erf form


def test_embedder_udf_loads_checkpoint_dir(checkpoint):
    """TPUEncoderEmbedder('path/to/checkpoint') runs real weights through
    the epoch-batched UDF path (reference SentenceTransformerEmbedder
    parity, embedders.py:270-327)."""
    import jax.numpy as jnp

    from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder

    d, model = checkpoint
    emb = TPUEncoderEmbedder(d, config=None)
    got = np.stack(emb._embed_batch(SENTENCES))
    expected = _embed_torch(model, d, SENTENCES, "mean")
    cos = (got * expected).sum(axis=1)
    assert cos.min() >= 0.99, cos
