"""Doctest harness: every ``>>>`` example in a public docstring runs in
CI, exactly like the reference's doctest pass over
``python/pathway/**`` (their public docstrings double as tested
examples — e.g. ``xpacks/llm/embedders.py:118-138``).

Each example runs against a FRESH parse graph so examples cannot leak
tables into each other, and a failure reports the owning module/object.
"""

from __future__ import annotations

import doctest
import importlib
import pkgutil

import pytest

import pathway_tpu as pw

#: packages scanned for docstring examples.  Import side effects must be
#: safe on CPU (tests force JAX_PLATFORMS=cpu via conftest).
_SCAN_ROOTS = [
    "pathway_tpu.internals.table",
    "pathway_tpu.internals.expression",
    "pathway_tpu.internals.expressions",
    "pathway_tpu.internals.sql",
    "pathway_tpu.internals.joins",
    "pathway_tpu.internals.groupbys",
    "pathway_tpu.internals.udfs",
    "pathway_tpu.reducers",
    "pathway_tpu.io.gdrive",
    "pathway_tpu.stdlib.temporal",
    "pathway_tpu.stdlib.indexing",
    "pathway_tpu.stdlib.stateful",
    "pathway_tpu.stdlib.ml",
    "pathway_tpu.stdlib.graphs",
    "pathway_tpu.xpacks.llm.parsers",
    "pathway_tpu.xpacks.llm.splitters",
    "pathway_tpu.xpacks.llm.embedders",
    "pathway_tpu.xpacks.llm.document_store",
    "pathway_tpu.xpacks.llm.question_answering",
]


def _iter_doctests():
    finder = doctest.DocTestFinder(exclude_empty=True)
    seen = set()
    for root in _SCAN_ROOTS:
        mod = importlib.import_module(root)
        mods = [mod]
        if hasattr(mod, "__path__"):
            for info in pkgutil.iter_modules(mod.__path__):
                try:
                    mods.append(
                        importlib.import_module(f"{root}.{info.name}")
                    )
                except ImportError:
                    continue
        for m in mods:
            for test in finder.find(m, name=m.__name__):
                if test.examples and test.name not in seen:
                    seen.add(test.name)
                    yield test


_ALL = list(_iter_doctests())


def test_doctest_corpus_nonempty():
    """The harness must actually be covering examples — an import
    regression that silently empties the corpus should fail loudly."""
    assert len(_ALL) >= 12, [t.name for t in _ALL]


@pytest.mark.parametrize("dt_case", _ALL, ids=lambda t: t.name)
def test_docstring_example(dt_case):
    pw.G.clear()
    runner = doctest.DocTestRunner(
        optionflags=doctest.NORMALIZE_WHITESPACE | doctest.ELLIPSIS
    )
    result = runner.run(dt_case)
    assert result.failed == 0, f"{dt_case.name}: {result.failed} failed"
