"""Error-value propagation semantics across the operator set: ERROR is a
first-class value (reference ``Value::Error``, ``src/engine/error.rs``)
— it flows through selects, drops from filters, is absorbed by joins and
groupbys per the reference's rules, and never aborts the run.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import api
from tests.utils import T, run_to_rows


def _with_error():
    """A table whose middle row computes an ERROR in column e."""
    t = T(
        """
        k | d
        1 | 1
        2 | 0
        3 | 3
        """
    )
    return t.select(t.k, e=pw.fill_error(t.k // t.d, -99) if False else t.k // t.d)


def test_error_value_flows_through_select_chain():
    t = _with_error()
    # further arithmetic on an ERROR stays ERROR, other rows unaffected
    out = t.select(t.k, y=t.e * 10 + 1)
    rows = dict(run_to_rows(out))
    assert rows[1] == 11 and rows[3] == 11 or True  # values checked below
    vals = sorted(run_to_rows(out), key=lambda r: r[0])
    assert vals[0] == (1, 11)
    assert vals[1][0] == 2 and vals[1][1] is api.ERROR
    assert vals[2] == (3, 11)


def test_fill_error_replaces_and_stops_propagation():
    t = _with_error()
    out = t.select(t.k, y=pw.fill_error(t.e, -1) * 2)
    assert sorted(run_to_rows(out)) == [(1, 2), (2, -2), (3, 2)]


def test_filter_drops_error_predicates():
    t = _with_error()
    # predicate on the ERROR row evaluates to ERROR -> row drops, run continues
    out = t.filter(t.e > 0).select(t.k)
    assert sorted(run_to_rows(out)) == [(1,), (3,)]


def test_groupby_absorbs_error_keys_and_values():
    """Rows whose GROUP KEY is ERROR group under the error key; aggregate
    VALUES that are ERROR poison their group's aggregate, not the run."""
    t = _with_error()
    out = t.groupby(t.e).reduce(n=pw.reducers.count())
    counts = sorted(v[0] for v in run_to_rows(out))
    # groups: e=1 (k=1 and k=3 both 1//1? no: k//d = 1,ERROR,1) -> {1: 2, ERROR: 1}
    assert counts == [1, 2]
    keyed = t.select(t.e, parity=t.k % 2)
    s = keyed.groupby(keyed.parity).reduce(total=pw.reducers.sum(keyed.e))
    vals = [v[0] for v in run_to_rows(s)]
    # the odd group sums cleanly; the even group's sum is poisoned
    assert sorted(str(v) for v in vals) == sorted(["2", str(api.ERROR)])


def test_join_on_error_key_produces_no_match():
    t = _with_error()
    other = T(
        """
        j | w
        1 | x
        3 | y
        """
    )
    jn = t.join(other, t.e == other.j).select(t.k, other.w)
    assert sorted(run_to_rows(jn)) == [(1, "x"), (3, "x")]


def test_unwrap_turns_none_into_error_and_requires():
    t = T(
        """
        a | b
        1 | 5
        2 |
        """
    )
    out = t.select(t.a, u=pw.unwrap(t.b) + 1)
    vals = dict(run_to_rows(out))
    assert vals[1] == 6
    assert vals[2] is api.ERROR


def test_error_log_collects_multiple_operator_failures():
    t = T(
        """
        a
        0
        1
        """
    )
    err = pw.global_error_log()
    t.select(x=pw.apply(lambda a: 1 // a, t.a))
    t.select(y=pw.apply(lambda a: [1, 2][a + 5], t.a))
    cap = err._capture_node()
    ctx = pw.run()
    messages = [v[0] for v in ctx.state(cap)["rows"].values()]
    assert any("ZeroDivisionError" in m for m in messages)
    assert any("IndexError" in m for m in messages)


def test_error_rows_do_not_reach_outputs_via_subscribe_filtering():
    """A pipeline can quarantine ERROR rows explicitly with fill_error +
    a sentinel filter — the recommended output hygiene pattern."""
    t = _with_error()
    clean = t.select(t.k, v=pw.fill_error(t.e, None)).filter(
        ~pw.this.v.is_none()
    )
    assert sorted(run_to_rows(clean)) == [(1, 1), (3, 1)]


def test_runtime_typecheck_violation_is_fatal(monkeypatch):
    """Declared-type violations under PATHWAY_RUNTIME_TYPECHECKING are a
    FATAL engine error (reference fail-whole-run), unlike value errors."""
    t = T(
        """
        a
        1
        """
    )
    bad = t.select(x=pw.declare_type(str, pw.apply(lambda a: a + 1, t.a)))
    bad._capture_node()
    with pytest.raises(Exception):
        pw.run(runtime_typechecking=True)


def test_groupby_error_poison_heals_on_retraction():
    """Retracting the ERROR-bearing row un-poisons its group's aggregate
    (reference reduce.rs keeps an error COUNT, not a sticky flag)."""
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    g | a | d | __time__ | __diff__
    x | 4 | 2 | 2        | 1
    x | 6 | 0 | 2        | 1
    x | 6 | 0 | 4        | -1
    """
    )
    w = t.select(t.g, v=t.a // t.d)
    out = w.groupby(w.g).reduce(w.g, s=pw.reducers.sum(w.v))
    history = []
    pw.io.subscribe(
        out, on_change=lambda k, row, tm, add: history.append((tm, add, row["s"]))
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # epoch 2: the aggregate is poisoned; epoch 4: clean sum again
    final_adds = [v for tm, add, v in history if add]
    assert str(final_adds[0]) == str(api.ERROR)
    assert final_adds[-1] == 2


def test_computed_reducer_arg_error_poisons_multiset_reducers():
    """The reducer ARGUMENT expression itself errors (no raw cell is
    ERROR): min/max/sorted_tuple must poison, not crash at extract, and
    sum must poison rather than silently skipping (review finding)."""
    pw.G.clear()
    t = T(
        """
        g | a | b
        x | 4 | 2
        x | 6 | 0
        y | 9 | 3
        """
    )
    out = t.groupby(t.g).reduce(
        t.g,
        m=pw.reducers.min(t.a // t.b),
        s=pw.reducers.sum(t.a // t.b),
        st_=pw.reducers.sorted_tuple(t.a // t.b),
    )
    rows = {r[0]: r[1:] for r in run_to_rows(out)}
    assert all(v is api.ERROR for v in rows["x"])
    assert rows["y"] == (3, 3, (3,))


def test_npsum_direct_error_arg_does_not_crash():
    from pathway_tpu.engine.reducers import NpSumReducer

    r = NpSumReducer()
    acc = r.make_acc()
    r.update(acc, (api.ERROR,), 1)  # must be a no-op, not a TypeError
    r.update(acc, ([1.0, 2.0],), 1)
    import numpy as np

    np.testing.assert_allclose(r.extract(acc), [1.0, 2.0])
