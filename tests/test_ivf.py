"""IVF-flat ANN index: recall vs brute force, upserts, API wiring.

Mirrors the role of the reference's USearch HNSW integration tests
(``src/external_integration/usearch_integration.rs``)."""

import numpy as np
import pytest

from pathway_tpu.parallel import IvfKnnIndex, ShardedKnnIndex


def _mixture(n, d, n_clusters=64, seed=0):
    """Clustered synthetic data — the regime ANN indexes exist for."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, d)).astype(np.float32) * 3.0
    assign = rng.integers(0, n_clusters, size=n)
    x = centers[assign] + rng.normal(size=(n, d)).astype(np.float32)
    return x.astype(np.float32)


def test_ivf_recall_vs_brute_force():
    n, d, k = 100_000, 64, 10
    x = _mixture(n, d)
    queries = _mixture(200, d, seed=1)

    ivf = IvfKnnIndex(d, metric="cos", capacity=n)
    ivf.add_batch(range(n), x)
    ivf.train(x)  # explicit train on the full corpus sample

    bf = ShardedKnnIndex(d, metric="cos", capacity=n)
    bf.add_batch(range(n), x)

    got = ivf.search(queries, k)
    want = bf.search(queries, k)
    hits = 0
    for g, w in zip(got, want):
        truth = {key for key, _ in w}
        hits += sum(1 for key, _ in g if key in truth)
    recall = hits / (len(queries) * k)
    assert recall >= 0.95, f"recall@{k} = {recall:.3f} < 0.95"


def test_ivf_upsert_remove_and_auto_train():
    d = 16
    idx = IvfKnnIndex(d, metric="cos", capacity=4096, nlist=16, nprobe=16)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2000, d)).astype(np.float32)
    idx.add_batch(range(2000), x)  # buffers, then auto-trains at threshold
    assert idx.trained
    assert len(idx) == 2000

    # exact self-query: with nprobe == nlist the scan is exhaustive
    res = idx.search(x[:5], 1)
    assert [r[0][0] for r in res] == [0, 1, 2, 3, 4]

    # upsert moves a key to its new vector's cell
    idx.add_batch([0], x[1][None, :])
    res = idx.search(x[1][None, :], 2)
    assert {key for key, _ in res[0]} == {0, 1}
    assert len(idx) == 2000

    idx.remove([0, 1])
    assert len(idx) == 1998
    res = idx.search(x[1][None, :], 2)
    assert 0 not in {key for key, _ in res[0]}
    assert 1 not in {key for key, _ in res[0]}


def test_ivf_grow_cells():
    d = 8
    idx = IvfKnnIndex(d, metric="dot", capacity=64, nlist=16, nprobe=16)
    rng = np.random.default_rng(0)
    # everything lands near one centroid -> forces per-cell overflow growth
    base = rng.normal(size=(1, d)).astype(np.float32)
    x = base + 0.01 * rng.normal(size=(3000, d)).astype(np.float32)
    idx.train(x[:500])
    cap0 = idx.cell_cap
    idx.add_batch(range(3000), x)
    assert idx.cell_cap > cap0  # grew
    # rows survive growth: an outlier added pre-growth is still findable
    outlier = (100.0 * np.eye(1, d)).astype(np.float32)
    idx.add_batch(["outlier"], outlier)
    res = idx.search(outlier, 1)
    assert res[0][0][0] == "outlier"
    assert len(idx) == 3001


def test_usearch_factory_dispatch():
    """Default UsearchKnn is the native HNSW graph (the reference's
    usearch role); nlist/nprobe opt into the TPU-resident IVF."""
    from pathway_tpu.stdlib.indexing.adapters import HnswAdapter, IvfAdapter
    from pathway_tpu.stdlib.indexing.data_index import UsearchKnn

    import pathway_tpu as pw

    class S(pw.Schema):
        v: list

    t = pw.debug.table_from_rows(S, [(1, ([1.0, 0.0],))])
    knn = UsearchKnn(t.v, dimensions=2, reserved_space=64)
    assert isinstance(knn.make_adapter(), HnswAdapter)

    ivf = UsearchKnn(t.v, dimensions=2, reserved_space=64, nlist=4, nprobe=2)
    assert isinstance(ivf.make_adapter(), IvfAdapter)

    # l2sq is native to the HNSW graph
    knn2 = UsearchKnn(t.v, dimensions=2, reserved_space=64, metric="l2sq")
    assert isinstance(knn2.make_adapter(), HnswAdapter)


def test_ivf_state_roundtrip():
    d = 8
    idx = IvfKnnIndex(d, metric="cos", capacity=512, nlist=16, nprobe=16)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(300, d)).astype(np.float32)
    idx.train(x)
    idx.add_batch(range(300), x)
    state = idx.state_dict()

    idx2 = IvfKnnIndex(d, metric="cos", capacity=512, nlist=16, nprobe=16)
    idx2.load_state_dict(state)
    r1 = idx.search(x[:4], 3)
    r2 = idx2.search(x[:4], 3)
    assert [[k for k, _ in row] for row in r1] == [[k for k, _ in row] for row in r2]


def test_ivf_duplicate_key_in_one_batch():
    """Upsert semantics for a key repeated within one batch: exactly one
    live slot; remove() leaves no orphan."""
    idx = IvfKnnIndex(8, metric="cos", capacity=256, nlist=4, nprobe=4)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(100, 8)).astype(np.float32)
    idx.train(x)
    idx.add_batch(["k", "k"], x[:2])
    assert len(idx) == 1
    res = idx.search(x[1][None, :], 3)
    assert [key for key, _ in res[0]].count("k") == 1
    idx.remove(["k"])
    res = idx.search(x[1][None, :], 3)
    assert "k" not in [key for key, _ in res[0]]
