"""Stdlib algorithm properties: graphs (bellman-ford, pagerank,
louvain), ordered (sort/diff), statistical interpolation, LSH
classifiers — correctness pinned against independently computed ground
truth on structured instances (reference ``stdlib/graphs``, ``ml``,
``ordered``, ``statistical`` test roles).
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from tests.utils import run_to_rows


# ---------------------------------------------------------------------------
# graphs


def _graph(v_md, e_md):
    v = pw.debug.table_from_markdown(v_md).select(
        name=pw.this.name,
        dist=pw.apply(
            lambda d: 0.0 if str(d) == "0" else None, pw.this.dist0
        ),
    )
    vertices = v.with_id_from(pw.this.name)
    e = pw.debug.table_from_markdown(e_md)
    edges = e.select(
        u=vertices.pointer_from(e.u),
        v=vertices.pointer_from(e.v),
        dist=pw.cast(float, e.dist),
    )
    return vertices, edges


def test_bellman_ford_shortest_paths_chain_vs_shortcut():
    """A long cheap chain must beat a direct expensive edge."""
    from pathway_tpu.stdlib.graphs import bellman_ford

    pw.G.clear()
    vertices, edges = _graph(
        """
    name | dist0
    a    | 0
    b    | __none__
    c    | __none__
    d    | __none__
    """,
        """
    u | v | dist
    a | b | 1
    b | c | 1
    c | d | 1
    a | d | 10
    """,
    )
    res = bellman_ford(vertices, edges)
    dists = sorted(r[0] for r in run_to_rows(res))
    assert dists == [0.0, 1.0, 2.0, 3.0]  # chain beats the shortcut


def test_pagerank_star_center_dominates():
    """All nodes link to a center: the center's rank must dominate."""
    from pathway_tpu.stdlib.graphs import pagerank

    pw.G.clear()
    e = pw.debug.table_from_markdown(
        """
    un | vn
    a  | z
    b  | z
    c  | z
    z  | a
    z  | b
    z  | c
    """
    )
    edges = e.select(u=pw.this.un, v=pw.this.vn)
    ranks = run_to_rows(pagerank(edges, steps=14))
    by_node = {r[0]: r[1] for r in ranks}
    others = [v for k, v in by_node.items() if k != "z"]
    assert by_node["z"] > 2 * max(others)  # z clearly dominates


def test_pagerank_symmetric_cycle_uniform():
    from pathway_tpu.stdlib.graphs import pagerank

    pw.G.clear()
    e = pw.debug.table_from_markdown(
        """
    un | vn
    a  | b
    b  | c
    c  | a
    """
    )
    edges = e.select(u=pw.this.un, v=pw.this.vn)
    vals = [r[1] for r in run_to_rows(pagerank(edges, steps=12))]
    assert max(vals) - min(vals) < 1e-6  # symmetry -> uniform rank


# ---------------------------------------------------------------------------
# ordered


def test_sort_produces_prev_next_chain():
    from tests.utils import _run_capture

    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    v
    30
    10
    20
    """
    )
    from pathway_tpu.stdlib.ordered import sort as o_sort

    s = o_sort(t, key=t.v)
    joined = t.with_columns(prev=s.prev, next=s.next)
    (rows, _), = _run_capture(joined)
    # exactly one head (prev None) and one tail (next None)
    prevs = [vals[1] for vals in rows.values()]
    nexts = [vals[2] for vals in rows.values()]
    assert prevs.count(None) == 1 and nexts.count(None) == 1
    # walking next-pointers from the head visits ascending v
    by_key = dict(rows)
    head = next(k for k, vals in rows.items() if vals[1] is None)
    walk, k = [], head
    while k is not None:
        walk.append(by_key[k][0])
        k = by_key[k][2]
    assert walk == [10, 20, 30]


def test_diff_computes_ordered_deltas():
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    ts | v
    1  | 10
    2  | 15
    4  | 25
    """
    )
    d = t.diff(t.ts, t.v)
    rows = sorted(run_to_rows(d.select(pw.this.diff_v)), key=repr)
    # first row has no predecessor -> None; others are deltas
    assert sorted((r[0] for r in rows if r[0] is not None)) == [5, 10]
    assert sum(1 for r in rows if r[0] is None) == 1


# ---------------------------------------------------------------------------
# statistical


def test_interpolate_linear_fills_gaps():
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    ts | v
    0  | 0.0
    10 | 100.0
    5  |
    """
    )
    from pathway_tpu.stdlib.statistical import interpolate

    out = interpolate(t, t.ts, t.v)
    vals = {r[0]: r[1] for r in run_to_rows(out.select(pw.this.ts, pw.this.v))}
    assert vals[0] == 0.0 and vals[10] == 100.0
    assert vals[5] == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# LSH classifiers


def test_lsh_knn_index_finds_close_neighbors():
    from pathway_tpu.stdlib.ml.classifiers import LshBandingIndex

    rng = np.random.default_rng(0)
    dim = 16
    idx = LshBandingIndex(dim, metric="euclidean", A=4.0)
    base = rng.normal(size=(30, dim))
    for i, v in enumerate(base):
        idx.add(i, v)
    # a point very close to base[7] must rank it first
    q = base[7] + rng.normal(scale=1e-3, size=dim)
    res = idx.query(q, k=3)
    assert res and res[0][0] == 7


def test_lsh_bucketers_are_deterministic_and_locality_sensitive():
    from pathway_tpu.stdlib.ml.classifiers import (
        generate_cosine_lsh_bucketer,
        generate_euclidean_lsh_bucketer,
    )

    rng = np.random.default_rng(1)
    for gen in (
        lambda: generate_euclidean_lsh_bucketer(8, 3, 4, 2.0),
        lambda: generate_cosine_lsh_bucketer(8, 3, 4),
    ):
        b = gen()
        x = rng.normal(size=8)
        assert b(x) == b(x)  # deterministic
        near = x + rng.normal(scale=1e-4, size=8)
        far = rng.normal(size=8) * 10
        same_near = sum(1 for p, q in zip(b(x), b(near)) if p == q)
        same_far = sum(1 for p, q in zip(b(x), b(far)) if p == q)
        assert same_near >= same_far


def test_fuzzy_self_match_pairs_identical_texts():
    from pathway_tpu.stdlib.ml.smart_table_ops import fuzzy_match_tables

    pw.G.clear()
    a = pw.debug.table_from_markdown(
        """
    txt
    alpha_beta_gamma
    delta_epsilon
    """
    )
    b = pw.debug.table_from_markdown(
        """
    txt
    alpha_beta_gamma
    zeta_eta
    """
    )
    m = fuzzy_match_tables(a, b)
    rows = run_to_rows(m)
    assert rows, "identical strings must produce at least one match"
