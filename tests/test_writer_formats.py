"""Direct unit coverage of service-writer wire formats: the SQL the
postgres writer generates (update-stream inserts, snapshot upserts,
deletes — reference ``data_format.rs`` PsqlUpdates/PsqlSnapshot
formatters, :1625-1684) and the elasticsearch bulk bodies."""

from pathway_tpu.io.postgres import _PsqlWriter


class FakeCursor:
    def __init__(self, log):
        self.log = log

    def execute(self, sql, params=None):
        self.log.append((sql, list(params or [])))


class FakeConn:
    """module name starts with 'tests' -> %s placeholders (non-sqlite)."""

    def __init__(self):
        self.executed: list = []
        self.commits = 0

    def cursor(self):
        return FakeCursor(self.executed)

    def commit(self):
        self.commits += 1

    def close(self):
        pass


def _writer(**kwargs):
    conn = FakeConn()
    w = _PsqlWriter(None, conn, "tbl", **kwargs)
    return w, conn


def test_update_stream_insert_carries_time_and_diff():
    w, conn = _writer()
    w.write({"a": 1, "b": "x"}, time=4, diff=-1)
    sql, params = conn.executed[0]
    assert sql == "INSERT INTO tbl (a, b, time, diff) VALUES (%s, %s, %s, %s)"
    assert params == [1, "x", 4, -1]


def test_snapshot_upsert_on_conflict_updates_non_key_columns():
    w, conn = _writer(snapshot_keys=["k"])
    w.write({"k": 7, "v": "new", "n": 2}, time=2, diff=1)
    sql, params = conn.executed[0]
    assert sql == (
        "INSERT INTO tbl (k, v, n) VALUES (%s, %s, %s) "
        "ON CONFLICT (k) DO UPDATE SET v = excluded.v, n = excluded.n"
    )
    assert params == [7, "new", 2]


def test_snapshot_delete_by_keys_only():
    w, conn = _writer(snapshot_keys=["k1", "k2"])
    w.write({"k1": 1, "k2": 2, "v": "gone"}, time=2, diff=-1)
    sql, params = conn.executed[0]
    assert sql == "DELETE FROM tbl WHERE k1 = %s AND k2 = %s"
    assert params == [1, 2]


def test_sqlite_connections_use_question_placeholders():
    import sqlite3

    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE tbl (a, b, time, diff)")
    w = _PsqlWriter(None, conn, "tbl")
    w.write({"a": 1, "b": "x"}, time=0, diff=1)
    w.flush()
    assert list(conn.execute("SELECT * FROM tbl")) == [(1, "x", 0, 1)]


def test_batch_commit_cadence():
    w, conn = _writer(max_batch_size=2)
    w.write({"a": 1}, 0, 1)
    assert conn.commits == 0
    w.write({"a": 2}, 0, 1)
    assert conn.commits == 1  # committed at the batch boundary
    w.flush()
    assert conn.commits == 2


def test_elasticsearch_bulk_bodies():
    from pathway_tpu.io import elasticsearch as es
    from pathway_tpu.internals.keys import ref_scalar

    class FakeClient:
        def __init__(self):
            self.calls = []

        def bulk(self, operations):
            self.calls.append(list(operations))

    client = FakeClient()
    w = es._ElasticWriter("http://fake:9200", None, "idx", client)
    k = ref_scalar(1)
    w.write({"id": k, "text": "hello"}, time=0, diff=1)
    w.write({"id": k, "text": "hello"}, time=2, diff=-1)
    w.flush()
    from pathway_tpu.io._connector import fmt_key

    (ops,) = client.calls
    kid = fmt_key(k)  # canonical full-key form shared with every sink
    assert kid == f"^{int(k):032X}" and "…" not in kid
    assert ops[0] == {"index": {"_index": "idx", "_id": kid}}
    assert ops[1] == {"text": "hello", "time": 0}
    assert ops[2] == {"delete": {"_index": "idx", "_id": kid}}
