"""End-to-end VectorStoreServer slice: docs -> split -> TPU embed ->
sharded KNN -> REST retrieve (BASELINE config #2 parity)."""

import dataclasses
import socket
import time

import jax.numpy as jnp
import pytest

import pathway_tpu as pw
from pathway_tpu.models import MINILM_L6
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder
from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter
from pathway_tpu.xpacks.llm.vector_store import VectorStoreClient, VectorStoreServer
from tests.utils import T

TINY = dataclasses.replace(
    MINILM_L6, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_vector_store_server_roundtrip():
    port = _free_port()
    docs = T(
        """
    d | data
    1 | apples grow on trees in the orchard
    2 | bananas are yellow tropical fruit
    3 | the tpu runs matrix multiplications very fast indeed
    """
    ).select(
        data=pw.this.data,
        _metadata=pw.apply(lambda d: {"path": f"/docs/{d}.txt", "modified_at": int(d)}, pw.this.d),
    )
    server = VectorStoreServer(
        docs,
        index_factory=BruteForceKnnFactory(
            embedder=TPUEncoderEmbedder(config=TINY), reserved_space=32
        ),
        splitter=TokenCountSplitter(min_tokens=1, max_tokens=100),
    )
    thread = server.run_server("127.0.0.1", port, threaded=True)
    assert thread is not None

    client = VectorStoreClient(port=port)
    deadline = time.monotonic() + 60
    result = None
    while time.monotonic() < deadline:
        try:
            result = client.query("bananas", k=2)
            break
        except Exception:
            time.sleep(0.3)
    assert result is not None, "server did not come up"
    assert len(result) == 2
    assert all("text" in d and "score" in d for d in result)

    stats = client.get_vectorstore_statistics()
    assert stats["file_count"] == 3

    inputs = client.get_input_files(filepath_globpattern="*2.txt")
    assert [f["path"] for f in inputs] == ["/docs/2.txt"]

    # glob filter through retrieval
    filtered = client.query("fruit", k=5, filepath_globpattern="*1.txt")
    assert len(filtered) == 1

    from pathway_tpu.internals.parse_graph import G

    G.active_scheduler.stop()
    thread.join(timeout=5)


def test_from_llamaindex_components_duck_typed():
    """The llama_index adapter works against the protocol alone (no
    llama_index import): get_text_embedding + split_text."""
    import numpy as np

    class FakeEmbedding:
        def get_text_embedding(self, text):
            rng = np.random.default_rng(abs(hash(text)) % 2**32)
            v = rng.normal(size=16)
            return (v / np.linalg.norm(v)).tolist()

    class FakeSplitter:
        def split_text(self, text):
            mid = max(1, len(text) // 2)
            return [text[:mid], text[mid:]]

    docs = T(
        """
    data
    bananas are yellow
    apples are red
    """
    ).select(data=pw.this.data)
    server = VectorStoreServer.from_llamaindex_components(
        docs, transformations=[FakeSplitter(), FakeEmbedding()]
    )
    retrieved = server.document_store.retrieve_query(
        T(
            """
    query | k
    bananas are yellow | 2
    """
        ).select(query=pw.this.query, k=pw.this.k, metadata_filter=None, filepath_globpattern=None)
    )
    cap = retrieved._capture_node()
    ctx = pw.run(monitoring_level=pw.internals.run.MonitoringLevel.NONE)
    (row,) = ctx.state(cap)["rows"].values()
    docs_out = row[-1]  # the `result` column
    assert len(docs_out) == 2  # two split chunks retrieved
    # unsupported transformation types are rejected loudly
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unsupported"):
        VectorStoreServer.from_llamaindex_components(
            docs, transformations=[object()]
        )
