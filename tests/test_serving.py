"""Multi-tenant RAG serving layer (ISSUE 10): admission control,
SLO-class scheduling, stage co-scheduling, and the composed live graph.

Coverage map:

- admission units — token-bucket shed + recovery, bounded per-tenant
  queue, ``wait_admit`` unparking on ticket release (all against a fake
  clock where rates matter, so no test sleeps on a refill);
- scheduler units — weighted-fair dispatch under backlog (interactive
  4:1 over batch), lane deficit arbitration (a slow embed burst cannot
  starve the search lane), latency-aware coalesced batch sizing;
- co-scheduler — lookahead retrieval overlaps probe flight with the
  generation queue wait, and the non-lookahead path stays correct;
- ``SegmentedIndex.dispatch``/``collect`` — parity with ``search`` and
  stale-handle recovery after a checkpoint restore;
- the full serving graph end-to-end (the tier-1 smoke the issue asks
  for): live ingest through the engine dataflow, one answered query per
  tenant class, serving counters + labeled latency series on /metrics;
- REST ingress backpressure: 429 + ``Retry-After`` + JSON error body on
  an over-rate tenant, no cross-tenant impact;
- noisy-neighbor isolation under live load with a delayed merge in
  flight, and a chaos-marked drill that kills a merge mid-commit and
  restores the index under in-flight lookahead probes.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.parallel import ShardedKnnIndex
from pathway_tpu.serving import (
    AdmissionController,
    HashingEmbedder,
    LoadGen,
    RagServingApp,
    SloScheduler,
    StageCoScheduler,
    TenantLoad,
    TenantPolicy,
)
from pathway_tpu.serving.loadgen import percentile
from pathway_tpu.stdlib.indexing.hnsw import HnswIndex
from pathway_tpu.stdlib.indexing.segments import SegmentedIndex
from pathway_tpu.testing.chaos import ChaosError, chaos

D = 32
K = 4


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _unit(rng, n=1, d=D):
    x = rng.standard_normal((n, d)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


class _Clock:
    """Deterministic clock for admission tests (no sleeping on refills)."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


DOCS = [
    ("solar", "solar panels convert sunlight into electricity efficiently"),
    ("merge", "database index merge compacts delta segments in background"),
    ("slab", "device slab stores vectors across shards for fast probes"),
    ("tail", "tail latency is held by weighted fair queue scheduling"),
    ("bucket", "token bucket admission sheds requests over the rate"),
    ("chunk", "document chunks are embedded and upserted into the index"),
]


# ---------------------------------------------------------------------------
# admission control


def test_token_bucket_sheds_over_rate_and_recovers():
    from pathway_tpu.io.http import RetryLater

    clk = _Clock()
    adm = AdmissionController(
        {"t": TenantPolicy("batch", rate_per_s=2.0, burst=2, queue_cap=100)},
        clock=clk,
    )
    t1 = adm.admit("t")
    t2 = adm.admit("t")
    with pytest.raises(RetryLater) as ei:
        adm.admit("t")
    assert ei.value.retry_after > 0
    assert "rate limited" in str(ei.value)
    assert adm.stats()["shed_total"] == {"batch": 1}
    # half a second refills one token at 2/s
    clk.t += 0.5
    t3 = adm.admit("t")
    assert adm.stats()["admitted_total"] == {"batch": 3}
    assert adm.stats()["inflight"] == {"batch": 3}
    for t in (t1, t2, t3):
        t.release()
    assert adm.stats()["inflight"] == {}


def test_queue_cap_bounds_inflight_per_tenant():
    from pathway_tpu.io.http import RetryLater

    clk = _Clock()
    adm = AdmissionController(
        {"t": TenantPolicy("interactive", rate_per_s=1000.0, queue_cap=2)},
        clock=clk,
    )
    t1 = adm.admit("t")
    adm.admit("t")
    with pytest.raises(RetryLater, match="tenant queue full"):
        adm.admit("t")
    # releasing a slot re-opens the queue; release is idempotent
    t1.release()
    t1.release()
    adm.admit("t")
    assert adm.stats()["admitted_total"] == {"interactive": 3}
    assert adm.stats()["shed_total"] == {"interactive": 1}


def test_unknown_tenant_uses_default_policy():
    adm = AdmissionController(
        {}, default_policy=TenantPolicy("batch", rate_per_s=10.0)
    )
    assert adm.policy("nobody").tenant_class == "batch"
    ticket = adm.admit("nobody")
    assert ticket.tenant_class == "batch"
    ticket.release()


def test_wait_admit_unparks_on_ticket_release():
    adm = AdmissionController(
        {"t": TenantPolicy("interactive", rate_per_s=1000.0, queue_cap=1)}
    )
    held = adm.admit("t")
    released = threading.Timer(0.1, held.release)
    released.start()
    t0 = time.monotonic()
    ticket = adm.wait_admit("t", timeout=5.0)
    elapsed = time.monotonic() - t0
    assert ticket is not None
    assert elapsed < 4.0  # unparked by the release, not the deadline
    ticket.release()
    released.join()


# ---------------------------------------------------------------------------
# SLO scheduler


def _gated_scheduler(lanes):
    """Scheduler whose dispatcher is parked on a gate task, so tests can
    enqueue a deterministic backlog before any dispatch decisions."""
    s = SloScheduler(lanes=lanes, idle_wait_s=0.01)
    gate = threading.Event()
    s.submit(next(iter(lanes)), "interactive", lambda _x: gate.wait(10), None)
    return s, gate


def test_wfq_interactive_beats_batch_backlog():
    s, gate = _gated_scheduler({"embed": 1.0})
    order: list[str] = []
    try:
        for i in range(10):
            s.submit("embed", "batch", lambda _x, i=i: order.append("batch"))
        for i in range(10):
            s.submit(
                "embed", "interactive", lambda _x, i=i: order.append("interactive")
            )
        gate.set()
        assert s.drain(10.0)
        # weights 4:1 — virtual finish times put all 10 interactive
        # tasks within the first 12 dispatches despite arriving last
        assert order[:12].count("interactive") >= 9
        stats = s.stats()
        assert stats["classes"]["interactive"]["dispatched"] == 11  # + gate task
        assert stats["classes"]["batch"]["dispatched"] == 10
    finally:
        gate.set()
        s.close()


def test_lane_deficit_keeps_search_unstarved():
    s, gate = _gated_scheduler({"embed": 1.0, "search": 1.0})
    order: list[str] = []
    try:
        for _ in range(5):
            s.submit(
                "embed",
                "batch",
                lambda _x: (time.sleep(0.005), order.append("embed")),
            )
        for _ in range(5):
            s.submit("search", "interactive", lambda _x: order.append("search"))
        # the gate task charged ~50ms of busy time to the embed lane, so
        # deficit arbitration must drain the idle search lane first
        time.sleep(0.05)
        gate.set()
        assert s.drain(10.0)
        assert order[:5] == ["search"] * 5
    finally:
        gate.set()
        s.close()


def test_batch_target_sizing_policy():
    s = SloScheduler(lanes={"embed": 1.0}, target_ms={"embed": 4.0}, max_batch=16)
    try:
        with s._lock:
            assert s._batch_target_locked("embed") == 16  # no signal yet
            s._ewma_item_ns["embed"] = 2e6  # 2 ms/item vs a 4 ms target
            assert s._batch_target_locked("embed") == 2
            s._ewma_item_ns["embed"] = 8e6  # slower than the target
            assert s._batch_target_locked("embed") == 1  # never starves
            s._ewma_item_ns["embed"] = 1e3  # ~free items
            assert s._batch_target_locked("embed") == 16  # clamped to max
    finally:
        s.close()


def test_latency_aware_batching_caps_after_ewma():
    batches: list[int] = []

    def work(items):
        batches.append(len(items))
        time.sleep(0.002 * len(items))
        return [x * 2 for x in items]

    s = SloScheduler(
        lanes={"embed": 1.0}, target_ms={"embed": 4.0}, max_batch=16, idle_wait_s=0.01
    )
    gate = threading.Event()
    try:
        # establish the EWMA: a dozen single tasks at ~2 ms each
        for _ in range(12):
            s.submit("embed", "interactive", lambda _x: time.sleep(0.002))
        assert s.drain(10.0)
        # now a gated coalescable backlog: with ~2 ms/item against a
        # 4 ms lane target the dispatcher must split it into small
        # batches instead of one max_batch call
        s.submit("embed", "interactive", lambda _x: gate.wait(10), None)
        futs = [
            s.submit("embed", "interactive", work, item=i, coalesce="w")
            for i in range(16)
        ]
        gate.set()
        assert s.drain(10.0)
        assert [f.result(timeout=5) for f in futs] == [i * 2 for i in range(16)]
        assert len(batches) >= 3  # the backlog was split…
        assert max(batches) <= 8  # …into latency-bounded batches
    finally:
        gate.set()
        s.close()


def test_scheduler_unknown_lane_and_close():
    s = SloScheduler(lanes={"embed": 1.0}, idle_wait_s=0.01)
    with pytest.raises(KeyError, match="unknown lane"):
        s.submit("gpu", "interactive", lambda _x: None)
    assert s.submit("embed", "interactive", lambda _x: 7).result(timeout=5) == 7
    s.close()
    with pytest.raises(RuntimeError, match="scheduler closed"):
        s.submit("embed", "interactive", lambda _x: None)


# ---------------------------------------------------------------------------
# stage co-scheduler


def _mini_corpus(emb):
    seg = SegmentedIndex(HnswIndex(emb.dim, metric="cos"), delta_cap=64, auto_merge=False)
    texts = {}
    for doc_id, text in DOCS:
        texts[doc_id] = text
        seg.add([(doc_id, emb(text))])
    return seg, texts


@pytest.mark.parametrize("lookahead", [True, False])
def test_coscheduler_pipeline_answers(lookahead):
    emb = HashingEmbedder(D)
    seg, texts = _mini_corpus(emb)
    sched = SloScheduler(idle_wait_s=0.01)
    cos = StageCoScheduler(
        embedder=emb,
        index=seg,
        doc_text=lambda key: texts.get(key, ""),
        scheduler=sched,
        k=3,
        lookahead=lookahead,
    )
    try:
        futs = [
            cos.submit("token bucket admission rate", tenant_class="interactive"),
            cos.submit("index merge delta segments", tenant_class="batch"),
        ]
        out = [f.result(timeout=10) for f in futs]
        assert out[0]["tenant_class"] == "interactive"
        assert out[1]["tenant_class"] == "batch"
        # retrieval is relevant: the matching doc tops each answer
        assert out[0]["docs"][0]["id"] == "bucket"
        assert out[1]["docs"][0]["id"] == "merge"
        assert "token bucket" in out[0]["docs"][0]["text"]
        stats = cos.stats()
        assert stats["completed"] == 2 and stats["failed"] == 0
        if lookahead:
            # the probe was dispatched on the search lane and collected
            # by the generation worker — flight time is the overlap
            assert stats["lookahead_probes"] == 2
            assert stats["overlap_ms_total"] >= 0.0
        else:
            assert stats["lookahead_probes"] == 0
    finally:
        cos.close()
        sched.close()
        seg.close()


# ---------------------------------------------------------------------------
# SegmentedIndex dispatch/collect (lookahead substrate)


def test_segmented_dispatch_collect_matches_search():
    rng = np.random.default_rng(7)
    seg = SegmentedIndex(
        ShardedKnnIndex(D, metric="cos", capacity=256), delta_cap=16, auto_merge=False
    )
    try:
        x = _unit(rng, 48)
        seg.add([(f"m{i}", x[i]) for i in range(40)])  # bulk → main
        seg.add([(f"d{i}", x[40 + i]) for i in range(6)])  # delta
        seg.remove(["m3", "m7"])  # tombstones mask main hits
        q = _unit(rng, 5)
        handle = seg.dispatch(q, K)
        got = seg.collect(handle)
        assert got == seg.search(q, K)
        assert all(len(hits) == K for hits in got)
        dead = {"m3", "m7"}
        assert all(key not in dead for hits in got for key, _s in hits)
        assert seg.stats()["probes_dispatched"] >= 2  # handle + the search
        assert seg.stats()["probes_recovered"] == 0
    finally:
        seg.close()


def test_segmented_stale_probe_recovers_after_restore():
    rng = np.random.default_rng(8)
    seg = SegmentedIndex(
        ShardedKnnIndex(D, metric="cos", capacity=256), delta_cap=16, auto_merge=False
    )
    try:
        x = _unit(rng, 32)
        seg.add([(f"m{i}", x[i]) for i in range(32)])
        q = _unit(rng, 3)
        handle = seg.dispatch(q, K)
        # the index owner "restarts" while the probe is in flight: the
        # device slab is reloaded and the handle's version goes stale
        seg.load_state_dict(seg.state_dict())
        got = seg.collect(handle)
        assert seg.stats()["probes_recovered"] == 1
        # recovery re-ran the search against the restored index: results
        # match a fresh query, no exception, no wrong keys
        assert got == seg.search(q, K)
    finally:
        seg.close()


# ---------------------------------------------------------------------------
# full serving graph (the issue's tier-1 smoke)


def _serving_app(**kw):
    pols = {
        "alice": TenantPolicy("interactive", rate_per_s=500.0, burst=50, queue_cap=64),
        "bob": TenantPolicy("batch", rate_per_s=500.0, burst=50, queue_cap=64),
    }
    kw.setdefault("embed_dim", D)
    kw.setdefault("delta_cap", 64)
    kw.setdefault("autocommit_ms", 10)
    return RagServingApp(pols, **kw)


def _seed_docs(app, tenant="alice"):
    for doc_id, text in DOCS:
        app.upsert(doc_id, text, tenant=tenant)
    assert app.wait_indexed(len(DOCS), timeout=30.0), app.stats()


def test_serving_graph_one_query_per_class_and_metrics():
    """Build the full serving graph (live ingest → embed lane →
    SegmentedIndex → co-scheduled answer) and serve one query per tenant
    class; the serving counters and tenant_class-labeled latency series
    must show up on /metrics next to the untouched engine lines."""
    from pathway_tpu.internals.monitoring_server import _metrics_text

    app = _serving_app().start()
    try:
        _seed_docs(app)
        r_int = app.answer("solar panels electricity", tenant="alice", timeout=30)
        r_bat = app.answer("index merge background", tenant="bob", timeout=30)
        assert r_int["tenant_class"] == "interactive"
        assert r_bat["tenant_class"] == "batch"
        assert r_int["docs"][0]["id"].startswith("solar")
        assert r_bat["docs"][0]["id"].startswith("merge")
        assert r_int["answer"] and r_int["latency_ms"] > 0

        st = app.stats()
        assert st["admission"]["admitted_total"] == {"interactive": 1, "batch": 1}
        assert st["ingested_chunks"] == len(DOCS)
        assert st["coscheduler"]["completed"] == 2

        text = _metrics_text(app.sched)
        assert 'pathway_tpu_serving_admitted_total{tenant_class="interactive"} ' in text
        assert 'pathway_tpu_serving_admitted_total{tenant_class="batch"} ' in text
        for stage in ("serve_embed", "serve_retrieve", "serve_generate", "serve_e2e"):
            assert (
                f'pathway_tpu_stage_latency_ms{{stage="{stage}",'
                f'tenant_class="interactive",quantile="p99"}}'
            ) in text
        # the engine's own stage series stay label-free (dashboards
        # parse the exact historical form)
        assert 'pathway_tpu_stage_latency_ms{stage=' in text
    finally:
        app.close()


def test_serving_upsert_replaces_and_delete_removes():
    app = _serving_app().start()
    try:
        _seed_docs(app)
        # re-upsert with new content: stable chunk ids replace in place
        app.upsert("solar", "wind turbines also make electricity", tenant="alice")
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = app.answer("wind turbines electricity", tenant="alice", timeout=30)
            if r["docs"] and "wind turbines" in r["docs"][0]["text"]:
                break
            time.sleep(0.05)
        assert "wind turbines" in r["docs"][0]["text"]
        app.delete("merge")
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = app.answer("index merge background", tenant="alice", timeout=30)
            if all(not d["id"].startswith("merge#") for d in r["docs"]):
                break
            time.sleep(0.05)
        assert all(not d["id"].startswith("merge#") for d in r["docs"])
        assert app.removed_chunks >= 1
    finally:
        app.close()


# ---------------------------------------------------------------------------
# REST ingress backpressure


def _post(port, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/answer",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_rest_429_retry_after_and_tenant_isolation():
    """An over-rate tenant gets 429 + Retry-After + a JSON error body
    (never a silent drop), and other tenants keep getting 200s."""
    port = _free_port()
    pols = {
        "fast": TenantPolicy("interactive", rate_per_s=500.0, burst=50, queue_cap=64),
        "slow": TenantPolicy("batch", rate_per_s=1.0, burst=1, queue_cap=4),
    }
    app = RagServingApp(pols, embed_dim=D, autocommit_ms=10)
    app.serve_rest(host="127.0.0.1", port=port)
    app.start()
    try:
        _seed_docs(app, tenant="fast")
        # warm-up: the aiohttp server may still be binding
        deadline = time.monotonic() + 30
        status = body = None
        while time.monotonic() < deadline:
            try:
                status, body = _post(
                    port, {"query": "solar panels", "tenant": "fast"}
                )
                break
            except (ConnectionError, urllib.error.URLError):
                time.sleep(0.2)
        assert status == 200, body
        # the writer unwraps the single `result` column: the body IS the
        # co-scheduler's answer payload
        assert body["docs"][0]["id"].startswith("solar")
        assert body["tenant_class"] == "interactive"

        # drain tenant "slow"'s single-token bucket, then hit the limit
        status, _ = _post(port, {"query": "index merge", "tenant": "slow"})
        assert status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, {"query": "index merge", "tenant": "slow"})
        err = ei.value
        assert err.code == 429
        assert int(err.headers["Retry-After"]) >= 1
        payload = json.loads(err.read())
        assert "rate limited" in payload["error"]
        assert payload["retry_after"] > 0
        assert app.admission.stats()["shed_total"] == {"batch": 1}

        # the shed is per-tenant: "fast" is unaffected
        status, body = _post(port, {"query": "token bucket", "tenant": "fast"})
        assert status == 200
        assert app.admission.stats()["shed_total"].get("interactive", 0) == 0
    finally:
        app.close()


# ---------------------------------------------------------------------------
# noisy-neighbor isolation + chaos


def test_noisy_neighbor_isolation_under_merge_load():
    """A batch tenant saturating its bucket (with interleaved writes)
    plus an index merge held in flight must not touch the interactive
    tenant: zero interactive sheds, zero lost requests, bounded p99."""
    app = _serving_app(delta_cap=8).start()  # tiny delta: merges fire mid-run
    app.admission.set_policy(
        "noisy", TenantPolicy("batch", rate_per_s=5.0, burst=2, queue_cap=2)
    )
    try:
        _seed_docs(app)
        with chaos(seed=3) as c:
            c.inject_latency(app.index, "_run_merge", delay_s=0.05)
            app.index.merge(wait=False)  # a merge is in flight as load starts
            lg = LoadGen(
                app,
                [
                    TenantLoad("alice", qps=40.0),
                    TenantLoad("noisy", qps=80.0, write_fraction=0.3),
                ],
                duration_s=1.5,
                seed=11,
            )
            rep = lg.run()
        fast = rep["tenants"]["alice"]
        noisy = rep["tenants"]["noisy"]
        assert fast["sent"] > 20
        assert fast["shed"] == 0 and fast["errors"] == 0
        assert fast["completed"] == fast["sent"]  # no cross-tenant loss
        assert 0 < fast["p99_ms"] <= 500.0, rep["classes"]
        assert noisy["shed"] > 0  # admission held the noisy bound
        assert noisy["writes"] > 0  # concurrent upserts really ran
        assert app.admission.stats()["shed_total"].get("interactive", 0) == 0
    finally:
        app.close()


@pytest.mark.chaos
def test_chaos_merge_killed_and_index_restored_mid_serving():
    """Kill the index owner mid-merge (the pre-commit instant), then
    restore the index from a checkpoint while lookahead probes are in
    flight: the merge rolls back fully, every in-flight query still
    answers from the restored index, and stale device handles are
    recovered, not surfaced."""
    gate = threading.Event()
    first_in = threading.Event()

    def slow_answerer(query, docs):
        first_in.set()
        gate.wait(15)
        if not docs:
            return f"no context found for: {query}"
        return f"[{docs[0]['id']}] {docs[0]['text'][:240]}"

    app = _serving_app(
        index=SegmentedIndex(
            ShardedKnnIndex(D, metric="cos", capacity=512),
            delta_cap=64,
            auto_merge=False,
        ),
        answerer=slow_answerer,
        lookahead=True,
    ).start()
    try:
        _seed_docs(app)
        state = app.index.state_dict()

        # -- the index owner dies between a finished merge and its commit
        with chaos(seed=5) as c:
            c.raise_on_nth_call(app.index, "_pre_commit", n=1)
            with pytest.raises(ChaosError):
                app.index.merge(wait=True)
            assert c.call_count(app.index, "_pre_commit") == 1
        assert app.index.stats()["merge_failures"] == 1
        assert not app.index._merging  # full rollback, not a wedged merge

        # -- restore under in-flight lookahead probes: f1 occupies the
        # generation worker; f2/f3 park in the gen queue with their
        # device probes already dispatched
        f1 = app.submit_query("solar panels electricity", tenant="alice")
        assert first_in.wait(10.0)
        f2 = app.submit_query("index merge background", tenant="alice")
        f3 = app.submit_query("token bucket admission", tenant="alice")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if app.coscheduler.stats()["gen_queued"] >= 2:
                break
            time.sleep(0.005)
        assert app.coscheduler.stats()["gen_queued"] >= 2

        app.index.load_state_dict(state)  # owner restart: handles go stale
        gate.set()
        out = [f.result(timeout=15) for f in (f1, f2, f3)]
        assert [r["docs"][0]["id"].split("#")[0] for r in out] == [
            "solar",
            "merge",
            "bucket",
        ]
        # exactly the two parked probes went stale and were re-run
        assert app.index.stats()["probes_recovered"] == 2
        assert app.admission.stats()["shed_total"] == {}

        # the next merge (no fault) completes cleanly on the restored index
        app.index.merge(wait=True)
        assert app.index.stats()["merges_total"] >= 1
        assert app.index.stats()["merge_failures"] == 1
    finally:
        gate.set()
        app.close()


# ---------------------------------------------------------------------------
# load generator


def test_percentile_nearest_rank():
    assert percentile([], 50) == 0.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 0) == 1.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0
    assert percentile([4.0, 1.0, 3.0, 2.0], 50) == 3.0  # sorts first


class _FakeServingTarget:
    """Duck-typed LoadGen target: instant answers, shed via admission."""

    def __init__(self, policies):
        self.admission = AdmissionController(policies)
        self.upserts = 0

    def submit_query(self, query, tenant="default", k=None):
        ticket = self.admission.admit(tenant)
        fut: Future = Future()
        fut.set_result({"answer": query})
        ticket.release()
        return fut

    def upsert(self, doc_id, text, tenant="default"):
        self.upserts += 1


def test_loadgen_reports_per_class_shed_and_latency():
    target = _FakeServingTarget(
        {
            "i": TenantPolicy("interactive", rate_per_s=1000.0, burst=100),
            "b": TenantPolicy("batch", rate_per_s=2.0, burst=1, queue_cap=2),
        }
    )
    lg = LoadGen(
        target,
        [
            TenantLoad("i", qps=50.0),
            TenantLoad("b", qps=50.0, write_fraction=0.2),
        ],
        duration_s=1.0,
        seed=42,
    )
    rep = lg.run()
    i_row, b_row = rep["tenants"]["i"], rep["tenants"]["b"]
    assert i_row["tenant_class"] == "interactive"
    assert i_row["shed"] == 0 and i_row["errors"] == 0
    assert i_row["completed"] == i_row["sent"] > 0
    assert i_row["p99_ms"] >= i_row["p50_ms"] >= 0
    assert b_row["tenant_class"] == "batch"
    assert b_row["shed"] > 0  # 50 qps offered into a 2/s bucket
    assert b_row["writes"] > 0 and target.upserts == b_row["writes"]
    # class aggregation mirrors the single-tenant-per-class rows
    assert rep["classes"]["batch"]["shed"] == b_row["shed"]
    assert rep["classes"]["interactive"]["completed"] == i_row["completed"]


# ---------------------------------------------------------------------------
# partial-failure survival: degraded serving + shard failover (ISSUE 13)


def _partitioned(n_docs: int = 120, n_shards: int = 2, seed: int = 0):
    from pathway_tpu.serving.failover import PartitionedIndex

    rng = np.random.default_rng(seed)
    part = PartitionedIndex(
        lambda: SegmentedIndex(
            HnswIndex(D, metric="cos"), delta_cap=64, auto_merge=False
        ),
        n_shards=n_shards,
        snapshot_every=32,
    )
    corpus = {}
    for i in range(n_docs):
        v = rng.standard_normal(D)
        v /= np.linalg.norm(v)
        corpus[f"d{i}"] = v
    part.add(list(corpus.items()))
    return part, corpus, rng


def _brute_topk(corpus: dict, q: np.ndarray, k: int) -> set:
    ids = sorted(corpus)
    mat = np.asarray([corpus[i] for i in ids])
    scores = mat @ (q / np.linalg.norm(q))
    return {ids[i] for i in np.argsort(-scores)[:k]}


def test_shard_health_tracker_streaks():
    from pathway_tpu.serving.failover import ShardHealthTracker

    t = ShardHealthTracker(2, dead_after=2)
    assert t.healthy_count() == 2
    t.record_failure(0)
    assert t.state(0) == "suspect"
    t.record_success(0)  # one success demotes suspect back to alive
    assert t.state(0) == "alive"
    t.record_failure(0)
    t.record_failure(0)
    assert t.state(0) == "dead" and t.dead_shards() == [0]
    t.record_success(0)  # dead is sticky until an explicit revive
    assert t.state(0) == "dead"
    t.revive(0)
    assert t.state(0) == "alive" and t.healthy_count() == 2


def test_partitioned_kill_one_shard_mid_load_partial_then_full_recall():
    """The ISSUE 13 acceptance drill: kill one of two shard owners while
    queries are in flight.  Every response keeps resolving (no errors) —
    degraded ones say ``partial: true`` with shard coverage — writes keep
    landing in the dead owner's oplog, and after a snapshot restore +
    exactly-once tail replay recall returns to 1.0 vs brute force while
    the surviving owner was never restarted."""
    part, corpus, rng = _partitioned()
    co = StageCoScheduler(
        embedder=HashingEmbedder(dim=D), index=part, k=K, lookahead=True
    )
    try:
        stop = threading.Event()
        results: list[dict] = []
        errors: list[BaseException] = []

        def load() -> None:
            i = 0
            while not stop.is_set():
                fut = co.submit(f"query {i % 7} alpha", "interactive")
                try:
                    results.append(fut.result(timeout=10))
                except BaseException as e:  # noqa: BLE001 - drill bookkeeping
                    errors.append(e)
                i += 1

        t = threading.Thread(target=load, daemon=True)
        t.start()
        time.sleep(0.15)  # healthy traffic first
        part.fail_shard(1)  # one owner dies mid-load
        time.sleep(0.25)
        # writes during the outage sequence into the dead owner's oplog
        extra = {}
        for j in range(24):
            v = rng.standard_normal(D)
            v /= np.linalg.norm(v)
            extra[f"x{j}"] = v
        part.add(list(extra.items()))
        corpus.update(extra)
        time.sleep(0.15)
        stop.set()
        t.join(10.0)
        assert not errors, f"degraded serving raised: {errors[:3]}"
        assert results, "no responses resolved during the drill"
        degraded = [r for r in results if r["partial"]]
        assert degraded, "no response reported partial coverage"
        assert all(
            r["shards_answered"] == 1 and r["shards_total"] == 2
            for r in degraded
        )
        healthy_owner = part.owners[0]
        assert healthy_owner.restores_total == 0  # survivor untouched

        # snapshot restore + exactly-once tail replay
        dead = part.owners[1]
        assert not dead.alive
        part.recover_shard(1)
        assert dead.alive and dead.restores_total == 1
        assert dead.tail_replayed > 0, "tail replay never happened"
        assert len(part) == len(corpus)  # nothing lost, nothing doubled
        assert healthy_owner.restores_total == 0

        # recall back to 1.0 vs brute force over the full corpus
        hits = total = 0
        for _ in range(10):
            q = rng.standard_normal(D)
            got = part.search([q], K)[0]
            hits += len(_brute_topk(corpus, q, K) & {key for key, _ in got})
            total += K
        assert hits / total == 1.0, f"post-recovery recall {hits / total:.3f}"
        probe = part.dispatch([rng.standard_normal(D)], K)
        part.collect(probe)
        assert probe.partial is False and probe.shards_answered == 2
        assert part.stats()["failovers_total"] == 1
    finally:
        co.close()
        part.close()


def test_failover_supervisor_auto_restores_dead_shard():
    from pathway_tpu.serving.failover import ShardFailoverSupervisor

    part, corpus, rng = _partitioned(n_docs=60)
    sup = ShardFailoverSupervisor(part, poll_interval_s=0.02)
    try:
        part.fail_shard(0)
        deadline = time.monotonic() + 5.0
        while part.owners[0].restores_total == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert part.owners[0].alive, "supervisor never restored the shard"
        assert part.stats()["shards_healthy"] == 2
        hist = part.stats()["failover_seconds"]
        assert hist["count"] == 1 and hist["max_ns"] > 0
    finally:
        sup.close()
        part.close()


def test_rag_app_sharded_serves_partial_results():
    """RagServingApp(shards=2) end-to-end: the partial-result contract
    reaches the answer dict through ingest, lookahead retrieval, and
    generation."""
    app = RagServingApp(shards=2, auto_merge=False, delta_cap=64).start()
    try:
        for i in range(30):
            app.upsert(f"doc{i}", f"topic {i % 5} body alpha beta w{i}")
        assert app.wait_indexed(30, timeout=15)
        healthy = app.answer("topic 2 alpha")
        assert healthy["partial"] is False and healthy["shards_total"] == 2
        app.index.fail_shard(1)
        degraded = app.answer("topic 3 beta")
        assert degraded["partial"] is True
        assert degraded["shards_answered"] == 1
        assert degraded["docs"], "degraded answer returned no docs"
        app.index.recover_shard(1)
        recovered = app.answer("topic 1 alpha")
        assert recovered["partial"] is False
        assert app.coscheduler.stats()["degraded_responses"] >= 1
    finally:
        app.close()
