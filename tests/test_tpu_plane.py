"""Numeric plane: ops, models, sharded KNN, jitted executors.

Runs on the virtual 8-device CPU mesh (see conftest.py) — sharding
semantics are identical on TPU; only speed differs.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pathway_tpu.models import (
    BGE_RERANKER_BASE,
    MINILM_L6,
    EncoderConfig,
    HashTokenizer,
    TextEncoderModel,
    encoder_param_specs,
)
from pathway_tpu.ops import (
    bucket_size,
    cosine_scores,
    l2sq_distances,
    masked_top_k,
    normalize,
)
from pathway_tpu.parallel import JittedEncoder, ShardedKnnIndex, best_mesh, make_mesh

TINY = dataclasses.replace(
    MINILM_L6, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
)


# ---------------------------------------------------------------------------
# ops


def test_bucket_size():
    assert bucket_size(1) == 8
    assert bucket_size(8) == 8
    assert bucket_size(9) == 16
    assert bucket_size(1000) == 1024
    assert bucket_size(100, max_bucket=64) == 64


def test_distances_match_numpy():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(3, 16)).astype(np.float32)
    c = rng.normal(size=(10, 16)).astype(np.float32)
    cos = np.asarray(cosine_scores(jnp.asarray(q), jnp.asarray(c)))
    qn = q / np.linalg.norm(q, axis=1, keepdims=True)
    cn = c / np.linalg.norm(c, axis=1, keepdims=True)
    np.testing.assert_allclose(cos, qn @ cn.T, atol=1e-5)
    l2 = np.asarray(l2sq_distances(jnp.asarray(q), jnp.asarray(c)))
    expected = ((q[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(l2, expected, rtol=1e-4, atol=1e-4)


def test_masked_top_k():
    scores = jnp.asarray([[1.0, 5.0, 3.0, 4.0]])
    valid = jnp.asarray([1.0, 0.0, 1.0, 1.0])
    vals, idx = masked_top_k(scores, valid, 2)
    assert idx.tolist() == [[3, 2]]
    np.testing.assert_allclose(np.asarray(vals), [[4.0, 3.0]])


def test_normalize():
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 8)).astype(np.float32))
    n = np.linalg.norm(np.asarray(normalize(x)), axis=1)
    np.testing.assert_allclose(n, np.ones(4), atol=1e-5)


# ---------------------------------------------------------------------------
# tokenizer


def test_hash_tokenizer_deterministic_and_bucketed():
    tok = HashTokenizer()
    ids, mask, tps = tok.encode_batch(["hello world", "a much longer sentence here ok"])
    ids2, _, _ = tok.encode_batch(["hello world", "a much longer sentence here ok"])
    np.testing.assert_array_equal(ids, ids2)
    assert ids.shape == mask.shape == tps.shape
    assert ids.shape[1] in (16, 32)  # bucketed
    assert mask[0].sum() == 4  # CLS hello world SEP
    assert tok.count_tokens("hello world") == 2


def test_hash_tokenizer_pairs():
    tok = HashTokenizer()
    ids, mask, tps = tok.encode_batch(["query"], pair=["doc text"])
    assert tps[0].max() == 1  # second segment present
    assert mask[0].sum() == 6  # CLS q SEP d t SEP


# ---------------------------------------------------------------------------
# models


def test_encoder_forward_shapes():
    model = TextEncoderModel(TINY)
    ids = jnp.zeros((2, 16), jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, mask)
    out = model.apply(params, ids, mask)
    assert out.shape == (2, 64)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=1), np.ones(2), atol=1e-4
    )


def test_encoder_param_specs_split_heads_and_mlp():
    model = TextEncoderModel(TINY)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32), jnp.ones((1, 8), jnp.int32)
    )
    specs = encoder_param_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_name = {"/".join(str(getattr(p, "key", p)) for p in path): s for path, s in flat}
    q = [s for n, s in by_name.items() if "query/kernel" in n][0]
    up = [s for n, s in by_name.items() if "mlp_up/kernel" in n][0]
    ln = [s for n, s in by_name.items() if "ln/scale" in n][0]
    assert "model" in str(q) and "model" in str(up)
    assert str(ln) == "PartitionSpec()"


# ---------------------------------------------------------------------------
# sharded KNN


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh()


def test_knn_basic_single_device():
    idx = ShardedKnnIndex(8, metric="l2sq", capacity=16)
    idx.add([("a", np.ones(8)), ("b", np.zeros(8)), ("c", 2 * np.ones(8))])
    res = idx.search(np.zeros((1, 8)), 2)
    assert [k for k, _ in res[0]] == ["b", "a"]


def test_knn_sharded_matches_bruteforce(mesh8):
    rng = np.random.default_rng(42)
    corpus = rng.normal(size=(200, 32)).astype(np.float32)
    idx = ShardedKnnIndex(32, metric="cos", capacity=64, mesh=mesh8)
    idx.add([(i, corpus[i]) for i in range(200)])
    queries = rng.normal(size=(5, 32)).astype(np.float32)
    res = idx.search(queries, 10)
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    cn = corpus / np.linalg.norm(corpus, axis=1, keepdims=True)
    scores = qn @ cn.T
    for qi in range(5):
        expect = list(np.argsort(-scores[qi])[:10])
        got = [k for k, _ in res[qi]]
        assert got == expect


def test_knn_upsert_and_remove(mesh8):
    idx = ShardedKnnIndex(4, metric="cos", capacity=8, mesh=mesh8)
    idx.add([("x", np.array([1, 0, 0, 0.0])), ("y", np.array([0, 1, 0, 0.0]))])
    r = idx.search(np.array([[1, 0, 0, 0.0]]), 1)
    assert r[0][0][0] == "x"
    # upsert x to point away from the query
    idx.add([("x", np.array([-1, 0, 0, 0.0]))])
    r = idx.search(np.array([[1, 0, 0, 0.0]]), 2)
    assert r[0][0][0] == "y"
    idx.remove(["y"])
    r = idx.search(np.array([[0, 1, 0, 0.0]]), 2)
    assert all(k != "y" for k, _ in r[0])
    assert len(idx) == 1


def test_knn_growth_preserves_data(mesh8):
    rng = np.random.default_rng(7)
    idx = ShardedKnnIndex(16, metric="cos", capacity=10, mesh=mesh8)
    first = rng.normal(size=16).astype(np.float32)
    idx.add([("first", first)])
    cap0 = idx.capacity
    idx.add([(f"n{i}", rng.normal(size=16).astype(np.float32)) for i in range(5000)])
    assert idx.capacity > cap0
    assert idx.search(first[None, :], 1)[0][0][0] == "first"


def test_knn_empty_search():
    idx = ShardedKnnIndex(4)
    assert idx.search(np.zeros((2, 4)), 3) == [[], []]


def test_knn_state_roundtrip():
    idx = ShardedKnnIndex(4, capacity=8)
    idx.add([("a", np.array([1, 0, 0, 0.0])), ("b", np.array([0, 1, 0, 0.0]))])
    state = idx.state_dict()
    idx2 = ShardedKnnIndex(4, capacity=8)
    idx2.load_state_dict(state)
    assert idx2.search(np.array([[0, 1, 0, 0.0]]), 1)[0][0][0] == "b"


# ---------------------------------------------------------------------------
# ring attention (sequence parallelism over the mesh)


def test_ring_attention_matches_local(mesh8):
    from pathway_tpu.ops.ring_attention import local_attention, ring_attention

    rng = np.random.default_rng(3)
    b, l, h, d = 2, 32, 4, 16  # L sharded 8-ways -> 4 per device
    q = jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
    mask = np.ones((b, l), np.int32)
    mask[1, 20:] = 0  # padded tail on one sequence
    mask = jnp.asarray(mask)

    expected = local_attention(q, k, v, mask)
    got = jax.jit(
        lambda q, k, v, m: ring_attention(q, k, v, m, mesh=mesh8)
    )(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-4
    )


# ---------------------------------------------------------------------------
# executors


def test_jitted_encoder_batches(mesh8):
    enc = JittedEncoder(TINY, mesh=None)
    out = enc.encode(["one", "two", "three"])
    assert out.shape == (3, 64)
    # deterministic across calls
    out2 = enc.encode(["one", "two", "three"])
    np.testing.assert_allclose(out, out2, atol=1e-5)


def test_encode_into_device_matches_host_path(mesh8):
    """encode_into keeps embeddings on device (add_batch_device); search
    results must be identical to encode() + add_batch through the host."""
    enc = JittedEncoder(TINY, mesh=None, max_batch=8, pipeline_depth=2)
    docs = [f"doc number {i} about topic{i % 7}" for i in range(21)]
    host_idx = ShardedKnnIndex(64, metric="cos", capacity=64)
    embs = enc.encode(docs)
    host_idx.add_batch(list(range(21)), embs)
    dev_idx = ShardedKnnIndex(64, metric="cos", capacity=64)
    assert enc.encode_into(dev_idx, list(range(21)), docs) == 21
    assert len(dev_idx) == 21
    for qi in (0, 7, 20):
        ra = host_idx.search(embs[qi : qi + 1], 5)[0]
        rb = dev_idx.search(embs[qi : qi + 1], 5)[0]
        assert [k for k, _ in ra] == [k for k, _ in rb]
        for (_, da), (_, db) in zip(ra, rb):
            assert abs(da - db) < 1e-2
    # upsert through the device path replaces, not duplicates
    assert enc.encode_into(dev_idx, [3], [docs[3]]) == 1
    assert len(dev_idx) == 21


def test_jitted_encoder_tp_dp():
    mesh = best_mesh(model_parallel=2)
    enc = JittedEncoder(TINY, mesh=mesh)
    out = enc.encode(["alpha", "beta", "gamma", "delta", "eps"])
    assert out.shape == (5, 64)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), np.ones(5), atol=1e-4)


def test_cross_encoder_scores():
    cfg = dataclasses.replace(
        BGE_RERANKER_BASE, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
    )
    ce = JittedEncoder(cfg, cross=True)
    s = ce.score_pairs(["q", "q"], ["relevant doc", "other"])
    assert s.shape == (2,) and s.dtype == np.float32


def test_encoder_long_doc_ring_attention_parity(mesh8):
    """The long-document path: TextEncoderModel with seq_mesh runs ring
    attention INSIDE every layer and must match local attention at seq
    1024 with the same params (VERDICT r3 item 6)."""
    import dataclasses

    from pathway_tpu.models.encoder import TextEncoderModel

    cfg_local = dataclasses.replace(
        TINY, max_len=1024, dtype=jnp.float32
    )
    cfg_ring = dataclasses.replace(cfg_local, seq_mesh=mesh8, seq_axis="data")
    model_local = TextEncoderModel(cfg_local)
    model_ring = TextEncoderModel(cfg_ring)

    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(2, 1024)), jnp.int32)
    mask = np.ones((2, 1024), np.int32)
    mask[1, 700:] = 0  # ragged doc: padded tail crosses device blocks
    mask = jnp.asarray(mask)

    params = model_local.init(jax.random.PRNGKey(0), ids, mask)
    out_local = model_local.apply(params, ids, mask)
    out_ring = jax.jit(model_ring.apply)(params, ids, mask)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_local), rtol=2e-4, atol=2e-4
    )


def test_jitted_encoder_sequence_parallel_long_docs(mesh8):
    """JittedEncoder(sequence_axis=...) embeds documents longer than one
    device's block; short and long inputs agree with the local-attention
    encoder on the same params."""
    import dataclasses

    cfg = dataclasses.replace(TINY, max_len=512, dtype=jnp.float32)
    enc_sp = JittedEncoder(cfg, mesh=mesh8, sequence_axis="data")
    enc_local = JittedEncoder(cfg, params=enc_sp.params)

    docs = [
        "short text",
        "long document " * 120,  # ~240+ tokens, crosses device blocks
    ]
    out_sp = enc_sp.encode(docs)
    out_local = enc_local.encode(docs)
    assert out_sp.shape == out_local.shape == (2, cfg.hidden)
    np.testing.assert_allclose(out_sp, out_local, rtol=2e-3, atol=2e-3)


def test_ring_attention_edge_masks_and_lengths(mesh8):
    """Round-4 verdict weak #8: the padded-equal-block constraint at the
    edges — lengths just around block boundaries (8 devices x 128-block
    at seq 1024) and degenerate masks, incl. a document whose valid
    tokens all sit in ONE device's block and a fully-masked row."""
    import dataclasses

    from pathway_tpu.models.encoder import TextEncoderModel

    cfg_local = dataclasses.replace(TINY, max_len=1024, dtype=jnp.float32)
    cfg_ring = dataclasses.replace(cfg_local, seq_mesh=mesh8, seq_axis="data")
    model_local = TextEncoderModel(cfg_local)
    model_ring = TextEncoderModel(cfg_ring)

    rng = np.random.default_rng(11)
    B = 7
    ids = jnp.asarray(
        rng.integers(0, TINY.vocab_size, size=(B, 1024)), jnp.int32
    )
    mask = np.zeros((B, 1024), np.int32)
    mask[0, :127] = 1    # one token short of the first block boundary
    mask[1, :128] = 1    # exactly one block
    mask[2, :129] = 1    # one token into the second block
    mask[3, :1023] = 1   # one short of full length
    mask[4, 256:384] = 1  # valid tokens entirely inside device 2's block
    mask[5, :1] = 1      # a single valid token
    # mask[6] stays all-zero: fully masked row must be well-defined
    # (both paths pool to zeros, no NaN) and agree
    mask = jnp.asarray(mask)

    params = model_local.init(jax.random.PRNGKey(0), ids, mask)
    out_local = model_local.apply(params, ids, mask)
    out_ring = jax.jit(model_ring.apply)(params, ids, mask)
    assert not np.isnan(np.asarray(out_ring)).any()
    assert not np.isnan(np.asarray(out_local)).any()
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_local), rtol=3e-4, atol=3e-4
    )
    # the fully-masked row pools to the zero vector on both paths
    np.testing.assert_allclose(np.asarray(out_ring)[6], 0.0, atol=1e-6)


def test_jitted_encoder_bucket_boundary_lengths(mesh8):
    """Sequence-parallel encoder at token counts straddling the pad
    bucket: results must agree with the local encoder for every length,
    not only the bucket-aligned ones."""
    import dataclasses

    cfg = dataclasses.replace(TINY, max_len=256, dtype=jnp.float32)
    enc_sp = JittedEncoder(cfg, mesh=mesh8, sequence_axis="data")
    enc_local = JittedEncoder(cfg, params=enc_sp.params)

    docs = [
        "w " * 31,   # just under a 32-token bucket
        "w " * 32,
        "w " * 33,   # just over
        "w " * 255,  # max_len - 1
        "w",         # single token
    ]
    out_sp = enc_sp.encode(docs)
    out_local = enc_local.encode(docs)
    assert out_sp.shape == out_local.shape == (5, cfg.hidden)
    np.testing.assert_allclose(out_sp, out_local, rtol=2e-3, atol=2e-3)
