"""Schema API surface: declarations, defaults, primary keys, dtype
introspection, composition and derivation (reference
``internals/schema.py`` + ``python/pathway/tests/test_schema.py`` role).
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from tests.utils import run_to_rows


def test_class_declaration_and_introspection():
    class S(pw.Schema):
        a: int
        b: str
        c: float | None

    assert S.column_names() == ["a", "b", "c"]
    assert S.dtypes()["a"] == dt.INT and S.dtypes()["b"] == dt.STR
    assert S.dtypes()["c"] == dt.Optional(dt.FLOAT)


def test_primary_key_and_defaults():
    class S(pw.Schema):
        key: int = pw.column_definition(primary_key=True)
        name: str = pw.column_definition(default_value="anon")
        score: float

    assert S.primary_key_columns() == ["key"]
    assert S["name"].has_default
    assert not S["score"].has_default
    # defaults apply through connector coercion
    from pathway_tpu.io._connector import coerce_row

    row = coerce_row({"key": 1, "score": 2.0}, S)
    assert row == (1, "anon", 2.0)


def test_schema_or_composition_and_without():
    class A(pw.Schema):
        x: int

    class B(pw.Schema):
        y: str

    AB = A | B
    assert AB.column_names() == ["x", "y"]
    assert AB.without("x").column_names() == ["y"]


def test_with_types_overrides():
    class S(pw.Schema):
        a: int
        b: str

    S2 = S.with_types(a=float)
    assert S2.dtypes()["a"] == dt.FLOAT
    assert S2.dtypes()["b"] == dt.STR
    # the original is untouched
    assert S.dtypes()["a"] == dt.INT


def test_schema_from_types_and_dict():
    S = pw.schema_from_types(a=int, b=str)
    assert S.column_names() == ["a", "b"]
    D = sch.schema_from_dict({"x": int, "y": float | None})
    assert D.dtypes()["y"] == dt.Optional(dt.FLOAT)


def test_table_schema_property_round_trip():
    t = pw.debug.table_from_rows(pw.schema_from_types(a=int, b=str), [(1, "x")])
    S = t.schema
    assert S.column_names() == ["a", "b"]
    assert t.typehints()["a"] == dt.INT


def test_primary_key_rows_keyed_by_value():
    """Two tables with the same pk values share row keys — the join-free
    mechanism connectors use for upserts."""

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    pw.G.clear()
    a = pw.debug.table_from_rows(S, [(1, "x"), (2, "y")])
    b = pw.debug.table_from_rows(S, [(1, "z")])
    # update_rows matches on row key = hash of pk
    out = a.update_rows(b)
    assert sorted(run_to_rows(out.select(out.k, out.v))) == [(1, "z"), (2, "y")]


def test_append_only_property_propagates():
    class S(pw.Schema, append_only=True):
        a: int

    assert S.append_only


# ---------------------------------------------------------------------------
# join matrix (left/right/outer against nulls and duplicates)


def _tables():
    a = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, va=str),
        [(1, "a1"), (2, "a2"), (2, "a2x"), (3, "a3")],
    )
    b = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, vb=str),
        [(2, "b2"), (3, "b3"), (3, "b3x"), (4, "b4")],
    )
    return a, b


def test_inner_join_duplicates_multiply():
    pw.G.clear()
    a, b = _tables()
    j = a.join(b, a.k == b.k).select(a.k, a.va, b.vb)
    got = sorted(run_to_rows(j))
    assert got == [
        (2, "a2", "b2"),
        (2, "a2x", "b2"),
        (3, "a3", "b3"),
        (3, "a3", "b3x"),
    ]


def test_left_join_unmatched_nulls():
    pw.G.clear()
    a, b = _tables()
    j = a.join_left(b, a.k == b.k).select(a.k, a.va, b.vb)
    got = sorted(run_to_rows(j), key=repr)
    assert (1, "a1", None) in got
    assert len(got) == 5  # 4 inner matches + 1 unmatched left


def test_right_join_unmatched_nulls():
    pw.G.clear()
    a, b = _tables()
    j = a.join_right(b, a.k == b.k).select(b.k, a.va, b.vb)
    got = sorted(run_to_rows(j), key=repr)
    assert (4, None, "b4") in got
    assert len(got) == 5


def test_outer_join_both_sides():
    pw.G.clear()
    a, b = _tables()
    j = a.join_outer(b, a.k == b.k).select(va=a.va, vb=b.vb)
    got = sorted(run_to_rows(j), key=repr)
    assert (None, "b4") in got
    assert ("a1", None) in got
    assert len(got) == 6


def test_join_how_kwarg_matches_methods():
    from pathway_tpu.internals.joins import JoinKind

    pw.G.clear()
    a, b = _tables()
    via_kw = sorted(
        run_to_rows(
            a.join(b, a.k == b.k, how=JoinKind.LEFT).select(a.k, b.vb)
        ),
        key=repr,
    )
    pw.G.clear()
    a, b = _tables()
    via_method = sorted(
        run_to_rows(a.join_left(b, a.k == b.k).select(a.k, b.vb)), key=repr
    )
    assert via_kw == via_method


def test_join_null_keys_never_match():
    pw.G.clear()
    a = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, v=str), [(1, "x"), (None, "n1")]
    )
    b = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, w=str), [(1, "y"), (None, "n2")]
    )
    j = a.join(b, a.k == b.k).select(a.v, b.w)
    assert sorted(run_to_rows(j)) == [("x", "y")]  # SQL semantics: no NULL match
    # outer keeps the null rows unmatched on their own sides
    jo = a.join_outer(b, a.k == b.k).select(a.v, b.w)
    got = sorted(run_to_rows(jo), key=repr)
    assert ("n1", None) in got and (None, "n2") in got


def test_multi_condition_join():
    pw.G.clear()
    a = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, g=str, v=int), [(1, "x", 10), (1, "y", 20)]
    )
    b = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, g=str, w=int), [(1, "x", 100), (1, "z", 200)]
    )
    j = a.join(b, a.k == b.k, a.g == b.g).select(a.g, a.v, b.w)
    assert run_to_rows(j) == [("x", 10, 100)]


def test_self_join_with_copy():
    pw.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=int, v=int), [(1, 10), (2, 20)]
    )
    u = t.copy()
    j = t.join(u, t.k == u.k).select(t.k, left_v=t.v, right_v=u.v)
    assert sorted(run_to_rows(j)) == [(1, 10, 10), (2, 20, 20)]


def test_markdown_leading_empty_cell_parses_as_null():
    """'  | n1' in bare style means an empty first cell, not a shifted
    row (the old strip('|') swallowed the leading empty field)."""
    pw.G.clear()
    t = pw.debug.table_from_markdown(
        """
    k | v
    1 | x
      | n1
    """
    )
    assert sorted(run_to_rows(t.select(t.k, t.v)), key=repr) == sorted(
        [(1, "x"), (None, "n1")], key=repr
    )
    # outer-pipe style rows behave identically
    u = pw.debug.table_from_markdown(
        """
    | k | v  |
    | 1 | x  |
    |   | n1 |
    """
    )
    assert sorted(run_to_rows(u.select(u.k, u.v)), key=repr) == sorted(
        [(1, "x"), (None, "n1")], key=repr
    )
