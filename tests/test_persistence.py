"""Persistence: input snapshots, resume, record/replay, UDF cache."""

import pathlib

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.persistence import Backend, Config, PersistenceMode, attach_persistence


class WordSchema(pw.Schema):
    word: str


def _build_wordcount(input_file: pathlib.Path, results: dict):
    table = pw.io.jsonlines.read(str(input_file), schema=WordSchema, mode="static")
    counts = table.groupby(table.word).reduce(
        table.word, n=pw.reducers.count()
    )

    def on_change(key, row, time, is_addition):
        if is_addition:
            results[row["word"]] = row["n"]
        elif results.get(row["word"]) == row["n"]:
            del results[row["word"]]

    pw.io.subscribe(counts, on_change=on_change)
    return counts


def _run_with_persistence(tmp_path, input_file, results):
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched, Config.simple_config(Backend.filesystem(tmp_path / "snapshots"))
    )
    sched.run()
    return sched


def test_snapshot_resume_no_duplicates(tmp_path):
    """Crash/restart: the second run replays the snapshot and the reader
    skips the already-delivered prefix — counts stay exact (the reference
    wordcount recovery scenario, integration_tests/wordcount)."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text(
        "\n".join('{"word": "%s"}' % w for w in ["a", "b", "a", "c", "a", "b"])
    )

    results1: dict = {}
    _build_wordcount(input_file, results1)
    _run_with_persistence(tmp_path, input_file, results1)
    assert results1 == {"a": 3, "b": 2, "c": 1}

    # "restart": fresh graph, same persistence dir, MORE input appended
    G.clear()
    with input_file.open("a") as f:
        f.write('\n{"word": "a"}\n{"word": "d"}')
    results2: dict = {}
    _build_wordcount(input_file, results2)
    _run_with_persistence(tmp_path, input_file, results2)
    assert results2 == {"a": 4, "b": 2, "c": 1, "d": 1}


def test_replay_mode_reproduces_without_source(tmp_path):
    """SpeedrunReplay re-runs from the snapshot alone (reference
    --record / replay, PersistenceMode::SpeedrunReplay)."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text('{"word": "x"}\n{"word": "x"}\n{"word": "y"}')

    results1: dict = {}
    _build_wordcount(input_file, results1)
    _run_with_persistence(tmp_path, input_file, results1)

    # delete the source; replay must still produce identical results
    input_file.unlink()
    G.clear()
    results2: dict = {}
    table = pw.io.jsonlines.read(
        str(tmp_path / "words.jsonl"), schema=WordSchema, mode="static"
    )
    counts = table.groupby(table.word).reduce(table.word, n=pw.reducers.count())
    pw.io.subscribe(
        counts,
        on_change=lambda key, row, time, is_addition: results2.__setitem__(
            row["word"], row["n"]
        )
        if is_addition
        else None,
    )
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched,
        Config.simple_config(
            Backend.filesystem(tmp_path / "snapshots"),
            persistence_mode=PersistenceMode.SPEEDRUN_REPLAY,
        ),
    )
    sched.run()
    assert results2 == {"x": 2, "y": 1}


def test_selective_persisting_only_with_persistent_id(tmp_path):
    """SELECTIVE_PERSISTING: sources without an explicit persistent_id are
    neither recorded nor replayed (reference
    PersistenceMode::SelectivePersisting); named sources keep the full
    record/resume contract, under a stream named by the id."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text('{"word": "a"}\n{"word": "b"}')

    def build(results):
        named = pw.io.jsonlines.read(
            str(input_file), schema=WordSchema, mode="static",
            persistent_id="words_src",
        )
        anon = pw.io.jsonlines.read(
            str(input_file), schema=WordSchema, mode="static"
        )
        both = named.concat_reindex(anon)
        counts = both.groupby(both.word).reduce(
            both.word, n=pw.reducers.count()
        )

        def on_change(key, row, time, is_addition):
            if is_addition:
                results[row["word"]] = row["n"]

        pw.io.subscribe(counts, on_change=on_change)

    def run():
        sched = Scheduler(G.engine_graph, autocommit_ms=10)
        attach_persistence(
            sched,
            Config.simple_config(
                Backend.filesystem(tmp_path / "snapshots"),
                persistence_mode=PersistenceMode.SELECTIVE_PERSISTING,
            ),
        )
        sched.run()

    results1: dict = {}
    build(results1)
    run()
    assert results1 == {"a": 2, "b": 2}
    # only the named source got a snapshot stream, keyed by its id
    logs = [p.name for p in (tmp_path / "snapshots").iterdir()]
    assert any("input_pid_words_src" in n for n in logs)
    assert not any("jsonlines" in n for n in logs)

    # restart: the named source resumes (no double-count), the anonymous
    # one re-reads from scratch
    G.clear()
    results2: dict = {}
    build(results2)
    run()
    assert results2 == {"a": 2, "b": 2}


def test_realtime_replay_honours_recorded_gaps(tmp_path):
    """REALTIME_REPLAY sleeps the recorded inter-commit wall gaps;
    SPEEDRUN replays the same log flat out."""
    import time as _t

    from pathway_tpu.io.python import ConnectorSubject

    class SlowSource(ConnectorSubject):
        def run(self):
            self.next(word="x")
            self.commit()
            _t.sleep(0.4)
            self.next(word="y")
            self.commit()

    def record():
        t = pw.io.python.read(SlowSource(), schema=WordSchema)
        counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
        counts._capture_node()
        sched = Scheduler(G.engine_graph, autocommit_ms=10)
        attach_persistence(
            sched, Config.simple_config(Backend.filesystem(tmp_path / "snap"))
        )
        sched.run()

    record()

    def replay(mode):
        G.clear()
        t = pw.io.python.read(SlowSource(), schema=WordSchema)
        counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
        cap = counts._capture_node()
        sched = Scheduler(G.engine_graph, autocommit_ms=10)
        attach_persistence(
            sched,
            Config.simple_config(
                Backend.filesystem(tmp_path / "snap"), persistence_mode=mode
            ),
        )
        t0 = _t.monotonic()
        ctx = sched.run()
        return _t.monotonic() - t0, ctx.state(cap)["rows"]

    fast_dt, fast_rows = replay(PersistenceMode.SPEEDRUN_REPLAY)
    slow_dt, slow_rows = replay(PersistenceMode.REALTIME_REPLAY)
    assert sorted(fast_rows.values()) == sorted(slow_rows.values())
    assert sorted(v for v in fast_rows.values()) == [("x", 1), ("y", 1)]
    assert slow_dt >= fast_dt + 0.25  # the recorded ~0.4 s gap was honoured


def test_memory_backend_roundtrip():
    b = Backend.memory(namespace="test_roundtrip")
    b._impl.append("s1", b"one")
    b._impl.append("s1", b"two")
    assert b._impl.read_all("s1") == [b"one", b"two"]
    b._impl.put_meta({"t": 5})
    assert Backend.memory(namespace="test_roundtrip")._impl.get_meta() == {"t": 5}


def test_fs_backend_torn_write(tmp_path):
    b = Backend.filesystem(tmp_path / "p")
    b._impl.append("s", b"complete")
    # simulate a torn tail write
    import os

    path = b._impl._stream_path("s")
    with open(path, "ab") as f:
        f.write((100).to_bytes(8, "little"))
        f.write(b"short")
    assert b._impl.read_all("s") == [b"complete"]


def test_udf_disk_cache(tmp_path, monkeypatch):
    calls = []

    @pw.udf(cache_strategy=pw.udfs.DiskCache(str(tmp_path / "cache")))
    def slow(x: int) -> int:
        calls.append(x)
        return x * 2

    from tests.utils import T, run_to_rows

    t = T(
        """
    x
    1
    2
    """
    )
    out1 = run_to_rows(t.select(y=slow(pw.this.x)))
    G.clear()
    t2 = T(
        """
    x
    1
    2
    """
    )
    out2 = run_to_rows(t2.select(y=slow(pw.this.x)))
    assert out1 == out2 == [(2,), (4,)]
    assert sorted(calls) == [1, 2]  # second run fully served from cache


def test_uncommitted_tail_truncated_on_resume(tmp_path):
    """ADVICE r1 (high): a crash between commits leaves uncommitted tail
    records in the snapshot log; resume must truncate them, or the resumed
    reader re-records them and the second restart double-counts
    (a:2,b:1 became a:4,b:2)."""
    import pickle

    input_file = tmp_path / "words.jsonl"
    input_file.write_text('{"word": "a"}\n{"word": "a"}\n{"word": "b"}')

    results1: dict = {}
    _build_wordcount(input_file, results1)
    _run_with_persistence(tmp_path, input_file, results1)
    assert results1 == {"a": 2, "b": 1}

    # simulate a crash that happened mid-epoch: tail events recorded
    # without a trailing commit
    backend = Backend.filesystem(tmp_path / "snapshots")
    streams = [
        p.stem for p in (tmp_path / "snapshots").glob("*.log")
    ]
    assert len(streams) == 1
    stream = streams[0]
    committed = len(backend._impl.read_all(stream))
    fake_key = __import__("pathway_tpu.internals.keys", fromlist=["ref_scalar"]).ref_scalar("__crash_tail__")
    backend._impl.append(stream, pickle.dumps(("add", fake_key, ("a",))))

    # restart twice; counts must stay exact both times
    for _ in range(2):
        G.clear()
        results: dict = {}
        _build_wordcount(input_file, results)
        _run_with_persistence(tmp_path, input_file, results)
        assert results == {"a": 2, "b": 1}
    # and the stale tail is gone from the log
    assert len(backend._impl.read_all(stream)) == committed


def test_nondeterministic_source_replays_committed_history(tmp_path):
    """ADVICE r1 (medium): sources without deterministic_replay used to
    have their recorded history silently discarded on restart.  Now the
    committed log is replayed for them too; the live reader only delivers
    new events."""
    from pathway_tpu.io._connector import RowSource, input_table, key_for_row

    class OneShotSource(RowSource):
        # NOT deterministically replayable: emits the given rows once
        deterministic_replay = False

        def __init__(self, rows):
            self.rows = rows
            self.resumed_from = None

        def on_persistence_resume(self, n):
            self.resumed_from = n

        def run(self, events):
            for w in self.rows:
                events.add(key_for_row({"word": w}, None), (w,))
            events.commit()

    def build(rows, results):
        src = OneShotSource(rows)
        table = input_table(src, WordSchema, name="oneshot")
        counts = table.groupby(table.word).reduce(table.word, n=pw.reducers.count())

        def on_change(key, row, time, is_addition):
            if is_addition:
                results[row["word"]] = row["n"]

        pw.io.subscribe(counts, on_change=on_change)
        return src

    results1: dict = {}
    build(["a", "b", "a"], results1)
    _run_with_persistence(tmp_path, None, results1)
    assert results1 == {"a": 2, "b": 1}

    # restart: the source only has NEW rows (a live feed can't rewind);
    # history must come back from the snapshot log
    G.clear()
    results2: dict = {}
    src2 = build(["c", "a"], results2)
    _run_with_persistence(tmp_path, None, results2)
    assert src2.resumed_from == 3
    assert results2 == {"a": 3, "b": 1, "c": 1}


def test_async_transformer_results_not_doubled_on_resume(tmp_path):
    """Auxiliary loopback inputs (AsyncTransformer results) are recomputed
    from the replayed upstream — persistence must not ALSO replay a
    recorded copy of them (review r2 finding)."""

    class OutSchema(pw.Schema):
        ret: int

    class Doubler(pw.AsyncTransformer):
        output_schema = OutSchema

        async def invoke(self, value: int) -> dict:
            return {"ret": value * 2}

    class InSchema(pw.Schema):
        value: int

    def build(results):
        import pathway_tpu.io._connector as conn

        src = conn.DictSource(
            lambda: [{"value": v} for v in (1, 4)], InSchema, tag="axt"
        )
        inputs = conn.input_table(src, InSchema, name="axt_in")
        transformer = Doubler(inputs)
        totals = transformer.successful.reduce(s=pw.reducers.sum(pw.this.ret))
        pw.io.subscribe(
            totals,
            on_change=lambda key, row, time, add: results.__setitem__("s", row["s"])
            if add
            else None,
        )

    r1: dict = {}
    build(r1)
    _run_with_persistence(tmp_path, None, r1)
    assert r1 == {"s": 10}

    G.clear()
    r2: dict = {}
    build(r2)
    _run_with_persistence(tmp_path, None, r2)
    assert r2 == {"s": 10}  # not 20: loopback history must not double


# ---------------------------------------------------------------------------
# operator snapshots (OPERATOR_PERSISTING)


def _op_config(tmp_path):
    return Config.simple_config(
        Backend.filesystem(tmp_path / "snapshots"),
        persistence_mode=PersistenceMode.OPERATOR_PERSISTING,
        snapshot_interval_ms=0,
    )


def _run_op(tmp_path, autocommit_ms=5):
    sched = Scheduler(G.engine_graph, autocommit_ms=autocommit_ms)
    attach_persistence(sched, _op_config(tmp_path))
    sched.run()
    return sched


def test_operator_snapshot_bounded_replay(tmp_path):
    """Restart restores compacted groupby state and replays only the tail:
    unchanged groups never re-fire (recomputation skipped), changed ones
    update correctly (reference operator_snapshot.rs)."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text(
        "\n".join('{"word": "%s"}' % w for w in ["a", "b", "a", "c", "a", "b"])
    )

    changes1: list = []
    results1: dict = {}

    def build(changes, results):
        table = pw.io.jsonlines.read(str(input_file), schema=WordSchema, mode="static")
        counts = table.groupby(table.word).reduce(table.word, n=pw.reducers.count())

        def on_change(key, row, time, is_addition):
            changes.append((row["word"], row["n"], is_addition))
            if is_addition:
                results[row["word"]] = row["n"]

        pw.io.subscribe(counts, on_change=on_change)

    build(changes1, results1)
    _run_op(tmp_path)
    assert results1 == {"a": 3, "b": 2, "c": 1}

    # restart with two appended rows: only touched groups may re-fire
    G.clear()
    with input_file.open("a") as f:
        f.write('\n{"word": "a"}\n{"word": "d"}')
    changes2: list = []
    results2: dict = {}
    build(changes2, results2)
    _run_op(tmp_path)
    assert results2 == {"a": 4, "d": 1}  # continuation: only updated groups fire
    words_fired = {w for w, _n, _add in changes2}
    assert "b" not in words_fired and "c" not in words_fired, changes2

    # third run with no new input: nothing at all re-fires
    G.clear()
    changes3: list = []
    results3: dict = {}
    build(changes3, results3)
    _run_op(tmp_path)
    assert changes3 == []


def test_operator_snapshot_join_window_equivalence(tmp_path):
    """Kill/restart over a join+tumbling-window pipeline: the restarted
    run's final captured state equals a fresh full-input run."""
    from pathway_tpu.engine.graph import CaptureNode

    events_file = tmp_path / "events.jsonl"
    rows1 = [
        {"k": "x", "t": 1, "v": 10},
        {"k": "y", "t": 2, "v": 20},
        {"k": "x", "t": 6, "v": 30},
    ]
    rows2 = [
        {"k": "y", "t": 7, "v": 40},
        {"k": "x", "t": 11, "v": 50},
    ]
    import json as _json

    class ES(pw.Schema):
        k: str
        t: int
        v: int

    names_file = tmp_path / "names.jsonl"
    names_file.write_text(
        '{"k": "x", "name": "xray"}\n{"k": "y", "name": "yankee"}'
    )

    class NS(pw.Schema):
        k: str
        name: str

    def build():
        ev = pw.io.jsonlines.read(str(events_file), schema=ES, mode="static")
        nm = pw.io.jsonlines.read(str(names_file), schema=NS, mode="static")
        win = ev.windowby(
            ev.t, window=pw.temporal.tumbling(duration=5), instance=ev.k
        ).reduce(
            k=pw.this._pw_instance,
            start=pw.this._pw_window_start,
            s=pw.reducers.sum(pw.this.v),
        )
        joined = win.join(nm, win.k == nm.k).select(
            nm.name, win.start, win.s
        )
        return CaptureNode(G.engine_graph, joined._node)

    # run 1 on partial input, "crash", append, restart
    events_file.write_text("\n".join(_json.dumps(r) for r in rows1))
    build()
    _run_op(tmp_path)
    G.clear()
    with events_file.open("a") as f:
        f.write("\n" + "\n".join(_json.dumps(r) for r in rows2))
    cap_restarted = build()
    sched = _run_op(tmp_path)
    restarted = sorted(sched.ctx.state(cap_restarted)["rows"].values())

    # fresh single run over the full input (no persistence)
    G.clear()
    cap_fresh = build()
    fresh_sched = Scheduler(G.engine_graph, autocommit_ms=5)
    fresh_sched.run()
    fresh = sorted(fresh_sched.ctx.state(cap_fresh)["rows"].values())
    assert restarted == fresh and len(fresh) >= 3


def test_operator_snapshot_windows_not_reflushed(tmp_path):
    """Clean shutdown snapshots AFTER the finalizing flush: a restart with
    no new input must not re-emit the flushed windows (review r2 finding)."""
    events_file = tmp_path / "ev.jsonl"
    events_file.write_text(
        '{"t": 1, "v": 10}\n{"t": 2, "v": 20}\n{"t": 7, "v": 30}'
    )

    class ES(pw.Schema):
        t: int
        v: int

    def build(fired):
        ev = pw.io.jsonlines.read(str(events_file), schema=ES, mode="static")
        win = ev.windowby(ev.t, window=pw.temporal.tumbling(duration=5)).reduce(
            start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v)
        )
        pw.io.subscribe(
            win,
            on_change=lambda k, row, time, add: fired.append(
                (row["start"], row["s"], add)
            ),
        )

    fired1: list = []
    build(fired1)
    _run_op(tmp_path)
    assert {(s, v) for s, v, add in fired1 if add} == {(0, 30), (5, 30)}

    G.clear()
    fired2: list = []
    build(fired2)
    _run_op(tmp_path)
    assert fired2 == []  # nothing re-flushes on a no-new-data restart


class FakeS3Client:
    """boto3-compatible in-memory object store shared across instances."""

    def __init__(self, store=None):
        self.store = store if store is not None else {}
        self.get_calls = 0

    def put_object(self, Bucket, Key, Body):
        self.store[Key] = bytes(Body)

    def get_object(self, Bucket, Key):
        self.get_calls += 1
        if Key not in self.store:
            raise KeyError(Key)
        return {"Body": self.store[Key]}

    def list_objects_v2(self, Bucket, Prefix, **kw):
        return {
            "Contents": [
                {"Key": k, "Size": len(v)}
                for k, v in sorted(self.store.items())
                if k.startswith(Prefix)
            ],
            "IsTruncated": False,
        }

    def delete_object(self, Bucket, Key):
        self.store.pop(Key, None)


def _s3_backend(store):
    from pathway_tpu.io.s3 import AwsS3Settings

    return Backend.s3(
        "pstorage/test",
        AwsS3Settings(bucket_name="bkt", client=FakeS3Client(store)),
    )


def test_s3_backend_snapshot_resume(tmp_path):
    """Full snapshot/restore cycle through the S3 persistence backend
    (reference ``src/persistence/backends/s3.rs``) with a fake client."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text(
        "\n".join('{"word": "%s"}' % w for w in ["a", "b", "a"])
    )
    store: dict = {}

    results1: dict = {}
    _build_wordcount(input_file, results1)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(sched, Config.simple_config(_s3_backend(store)))
    sched.run()
    assert results1 == {"a": 2, "b": 1}
    assert any(k.startswith("pstorage/test/streams/") for k in store)

    G.clear()
    with input_file.open("a") as f:
        f.write('\n{"word": "a"}\n{"word": "c"}')
    results2: dict = {}
    _build_wordcount(input_file, results2)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(sched, Config.simple_config(_s3_backend(store)))
    sched.run()
    assert results2 == {"a": 3, "b": 1, "c": 1}


def test_s3_backend_stream_truncate_roundtrip():
    store: dict = {}
    impl = _s3_backend(store)._impl
    for i in range(5):
        impl.append("st", b"rec%d" % i)
    assert impl.read_all("st") == [b"rec0", b"rec1", b"rec2", b"rec3", b"rec4"]
    impl.truncate("st", 2)
    assert impl.read_all("st") == [b"rec0", b"rec1"]
    impl.append("st", b"new")
    assert impl.read_all("st") == [b"rec0", b"rec1", b"new"]
    impl.put_meta({"n_workers": 2})
    assert impl.get_meta() == {"n_workers": 2}
    impl.put_blob("blb", b"xyz")
    assert impl.get_blob("blb") == b"xyz"


def test_cached_object_storage():
    from pathway_tpu.persistence import CachedObjectStorage

    backend = Backend.memory("obj_cache_test")
    cache = CachedObjectStorage(backend)
    assert cache.get("s3://b/k", "v1") is None
    cache.put("s3://b/k", "v1", b"data1")
    assert cache.contains("s3://b/k", "v1")
    assert not cache.contains("s3://b/k", "v2")
    assert cache.get("s3://b/k", "v1") == b"data1"
    # new version replaces
    cache.put("s3://b/k", "v2", b"data2")
    assert cache.get("s3://b/k", "v1") is None
    assert cache.get("s3://b/k", "v2") == b"data2"
    # survives a "restart" (new instance, same backend)
    cache2 = CachedObjectStorage(Backend.memory("obj_cache_test"))
    assert cache2.get("s3://b/k", "v2") == b"data2"
    cache2.invalidate("s3://b/k")
    assert cache2.get("s3://b/k", "v2") is None


def test_s3_source_uses_object_cache():
    """Unchanged object versions are served from the cache: the second
    source run does ZERO get_object calls."""
    import threading
    import time

    from pathway_tpu.io.s3 import AwsS3Settings, _parser_for, _S3Source
    from pathway_tpu.persistence import CachedObjectStorage

    store = {"pre/a.jsonl": b'{"v": 1}\n'}

    class ListingClient(FakeS3Client):
        def list_objects_v2(self, Bucket, Prefix, **kw):
            return {
                "Contents": [
                    {"Key": k, "Size": len(v), "ETag": "tag1"}
                    for k, v in sorted(self.store.items())
                ],
                "IsTruncated": False,
            }

    cache = CachedObjectStorage(Backend.memory("s3_src_cache_test"))

    class S(pw.Schema):
        v: int

    def run_once():
        client = ListingClient(store)
        src = _S3Source(
            AwsS3Settings(bucket_name="b", client=client),
            "pre/",
            S,
            _parser_for("jsonlines", S, None),
            mode="static",
            object_cache=cache,
        )
        rows = []

        class Events:
            stopped = False

            def add(self, key, row):
                rows.append(row)

            def remove(self, key, row):
                pass

            def commit(self):
                pass

            def close(self):
                pass

        src.run(Events())
        return client.get_calls, rows

    calls1, rows1 = run_once()
    assert calls1 == 1 and rows1 == [(1,)]
    calls2, rows2 = run_once()
    assert calls2 == 0 and rows2 == [(1,)]  # cache hit, no download
