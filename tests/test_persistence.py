"""Persistence: input snapshots, resume, record/replay, UDF cache."""

import pathlib

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.persistence import Backend, Config, PersistenceMode, attach_persistence


class WordSchema(pw.Schema):
    word: str


def _build_wordcount(input_file: pathlib.Path, results: dict):
    table = pw.io.jsonlines.read(str(input_file), schema=WordSchema, mode="static")
    counts = table.groupby(table.word).reduce(
        table.word, n=pw.reducers.count()
    )

    def on_change(key, row, time, is_addition):
        if is_addition:
            results[row["word"]] = row["n"]
        elif results.get(row["word"]) == row["n"]:
            del results[row["word"]]

    pw.io.subscribe(counts, on_change=on_change)
    return counts


def _run_with_persistence(tmp_path, input_file, results):
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched, Config.simple_config(Backend.filesystem(tmp_path / "snapshots"))
    )
    sched.run()
    return sched


def test_snapshot_resume_no_duplicates(tmp_path):
    """Crash/restart: the second run replays the snapshot and the reader
    skips the already-delivered prefix — counts stay exact (the reference
    wordcount recovery scenario, integration_tests/wordcount)."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text(
        "\n".join('{"word": "%s"}' % w for w in ["a", "b", "a", "c", "a", "b"])
    )

    results1: dict = {}
    _build_wordcount(input_file, results1)
    _run_with_persistence(tmp_path, input_file, results1)
    assert results1 == {"a": 3, "b": 2, "c": 1}

    # "restart": fresh graph, same persistence dir, MORE input appended
    G.clear()
    with input_file.open("a") as f:
        f.write('\n{"word": "a"}\n{"word": "d"}')
    results2: dict = {}
    _build_wordcount(input_file, results2)
    _run_with_persistence(tmp_path, input_file, results2)
    assert results2 == {"a": 4, "b": 2, "c": 1, "d": 1}


def test_replay_mode_reproduces_without_source(tmp_path):
    """SpeedrunReplay re-runs from the snapshot alone (reference
    --record / replay, PersistenceMode::SpeedrunReplay)."""
    input_file = tmp_path / "words.jsonl"
    input_file.write_text('{"word": "x"}\n{"word": "x"}\n{"word": "y"}')

    results1: dict = {}
    _build_wordcount(input_file, results1)
    _run_with_persistence(tmp_path, input_file, results1)

    # delete the source; replay must still produce identical results
    input_file.unlink()
    G.clear()
    results2: dict = {}
    table = pw.io.jsonlines.read(
        str(tmp_path / "words.jsonl"), schema=WordSchema, mode="static"
    )
    counts = table.groupby(table.word).reduce(table.word, n=pw.reducers.count())
    pw.io.subscribe(
        counts,
        on_change=lambda key, row, time, is_addition: results2.__setitem__(
            row["word"], row["n"]
        )
        if is_addition
        else None,
    )
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(
        sched,
        Config.simple_config(
            Backend.filesystem(tmp_path / "snapshots"),
            persistence_mode=PersistenceMode.SPEEDRUN_REPLAY,
        ),
    )
    sched.run()
    assert results2 == {"x": 2, "y": 1}


def test_memory_backend_roundtrip():
    b = Backend.memory(namespace="test_roundtrip")
    b._impl.append("s1", b"one")
    b._impl.append("s1", b"two")
    assert b._impl.read_all("s1") == [b"one", b"two"]
    b._impl.put_meta({"t": 5})
    assert Backend.memory(namespace="test_roundtrip")._impl.get_meta() == {"t": 5}


def test_fs_backend_torn_write(tmp_path):
    b = Backend.filesystem(tmp_path / "p")
    b._impl.append("s", b"complete")
    # simulate a torn tail write
    import os

    path = b._impl._stream_path("s")
    with open(path, "ab") as f:
        f.write((100).to_bytes(8, "little"))
        f.write(b"short")
    assert b._impl.read_all("s") == [b"complete"]


def test_udf_disk_cache(tmp_path, monkeypatch):
    calls = []

    @pw.udf(cache_strategy=pw.udfs.DiskCache(str(tmp_path / "cache")))
    def slow(x: int) -> int:
        calls.append(x)
        return x * 2

    from tests.utils import T, run_to_rows

    t = T(
        """
    x
    1
    2
    """
    )
    out1 = run_to_rows(t.select(y=slow(pw.this.x)))
    G.clear()
    t2 = T(
        """
    x
    1
    2
    """
    )
    out2 = run_to_rows(t2.select(y=slow(pw.this.x)))
    assert out1 == out2 == [(2,), (4,)]
    assert sorted(calls) == [1, 2]  # second run fully served from cache
