"""Tier-1 tests for the pre-flight static analyzer
(``pathway_tpu/analysis/``): every diagnostic code has a trigger graph
and a near-miss, plus the strict-mode abort-before-connectors gate."""

from __future__ import annotations

import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import (
    SEV_ERROR,
    SEV_WARNING,
    AnalysisError,
    analyze,
)
from pathway_tpu.internals import dtype as dt


def codes(diags):
    return [d.code for d in diags]


def _static_table():
    class S(pw.Schema):
        word: str
        n: int

    return pw.debug.table_from_rows(S, [("a", 1), ("b", 2)])


class _Subject(pw.io.python.ConnectorSubject):
    """Never-started source: graphs here are analyzed, not run."""

    def run(self) -> None:  # pragma: no cover - not executed
        pass


def _streaming_table():
    class S(pw.Schema):
        word: str
        n: int

    return pw.io.python.read(_Subject(), schema=S)


# ---------------------------------------------------------------- T001


def test_t001_join_key_type_mismatch():
    class L(pw.Schema):
        k: int
        v: int

    class R(pw.Schema):
        k: str
        w: int

    left = pw.debug.table_from_rows(L, [(1, 10)])
    right = pw.debug.table_from_rows(R, [("1", 20)])
    left.join(right, left.k == right.k).select(pw.this.v, pw.this.w)
    diags = analyze()
    t001 = [d for d in diags if d.code == "PW-T001"]
    assert t001 and t001[0].severity == SEV_ERROR


def test_t001_join_key_match_clean():
    class L(pw.Schema):
        k: int
        v: int

    class R(pw.Schema):
        k: int
        w: int

    left = pw.debug.table_from_rows(L, [(1, 10)])
    right = pw.debug.table_from_rows(R, [(1, 20)])
    left.join(right, left.k == right.k).select(pw.this.v, pw.this.w)
    assert "PW-T001" not in codes(analyze())


def test_t001_declare_type_contradiction():
    t = _static_table()
    t.select(s=pw.declare_type(str, pw.this.n + 1))
    diags = analyze()
    t001 = [d for d in diags if d.code == "PW-T001"]
    assert t001 and t001[0].severity == SEV_ERROR


def test_t001_declare_type_widening_clean():
    t = _static_table()
    # int -> float widening is a legal declaration
    t.select(f=pw.declare_type(float, pw.this.n + 1))
    assert "PW-T001" not in codes(analyze())


# ---------------------------------------------------------------- P001


def test_p001_call_py_on_streaming_column():
    t = _streaming_table()
    t.select(u=pw.apply(str.upper, t.word))
    diags = analyze()
    p001 = [d for d in diags if d.code == "PW-P001"]
    assert p001 and p001[0].severity == SEV_WARNING


def test_p001_static_call_py_clean():
    t = _static_table()
    t.select(u=pw.apply(str.upper, t.word))
    assert "PW-P001" not in codes(analyze())


def test_p001_vectorized_streaming_clean():
    t = _streaming_table()
    t.select(m=t.n + 1)  # lowers to pure VM bytecode, no CALL_PY
    assert "PW-P001" not in codes(analyze())


# ---------------------------------------------------------------- S001


def test_s001_unwindowed_groupby_over_stream():
    t = _streaming_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    diags = analyze()
    s001 = [d for d in diags if d.code == "PW-S001"]
    assert s001 and s001[0].severity == SEV_WARNING


def test_s001_static_groupby_clean():
    t = _static_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def _streaming_events():
    class S(pw.Schema):
        k: str
        t: int
        v: int

    return pw.io.python.read(_Subject(), schema=S)


def test_s001_interval_join_bounds_downstream_state():
    """A finite-interval temporal join is watermark-evicted: stateful
    consumers downstream of it must not be reported as unbounded."""
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    j = temporal.interval_join(
        a, b, a.t, b.t, temporal.interval(-1, 1), pw.left.k == pw.right.k
    ).select(k=pw.left.k, v=pw.left.v)
    j.groupby(j.k).reduce(j.k, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def test_s001_asof_join_bounds_downstream_state():
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    j = temporal.asof_join(
        a, b, a.t, b.t, pw.left.k == pw.right.k
    ).select(k=pw.left.k, v=pw.left.v)
    j.groupby(j.k).reduce(j.k, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def test_s001_asof_now_join_bounds_downstream_state():
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    j = temporal.asof_now_join(a, b, pw.left.k == pw.right.k).select(
        k=pw.left.k, v=pw.left.v
    )
    j.groupby(j.k).reduce(j.k, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def test_s001_plain_join_still_fires_downstream():
    """Positive control for the temporal near-misses: the same shape with
    an unwindowed join keeps the diagnostic."""
    a = _streaming_events()
    b = _streaming_events()
    a.join(b, a.k == b.k).select(k=pw.left.k, v=pw.right.v)
    diags = analyze()
    assert "PW-S001" in codes(diags)


# ---------------------------------------------------------------- S002


def test_s002_deduplicate_over_retracting_input():
    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    diags = analyze()
    s002 = [d for d in diags if d.code == "PW-S002"]
    assert s002 and s002[0].severity == SEV_ERROR


def test_s002_deduplicate_over_append_only_clean():
    t = _static_table()
    t.deduplicate(value=t.n, acceptor=lambda new, old: new > old)
    assert "PW-S002" not in codes(analyze())


# ---------------------------------------------------------------- D001


def test_d001_dead_column():
    t = _static_table()
    sel = t.select(t.word, dead=t.n + 1)
    sel.select(t2=pw.this.word)._capture_node()
    diags = analyze()
    d001 = [d for d in diags if d.code == "PW-D001"]
    assert d001 and d001[0].severity == SEV_WARNING
    assert "dead" in d001[0].message


def test_d001_used_column_clean():
    t = _static_table()
    sel = t.select(t.word, kept=t.n + 1)
    sel.select(t2=pw.this.word, k=pw.this.kept)._capture_node()
    assert "PW-D001" not in codes(analyze())


# ---------------------------------------------------------------- N001


def test_n001_optional_into_declared_non_optional_sink():
    t = _static_table()
    opt = pw.if_else(t.n > 1, t.n, None)  # Optional[int]
    t.select(v=pw.declare_type(int, opt))._capture_node()
    diags = analyze()
    n001 = [d for d in diags if d.code == "PW-N001"]
    assert n001 and n001[0].severity == SEV_WARNING


def test_n001_unwrap_clean():
    t = _static_table()
    opt = pw.if_else(t.n > 1, t.n, None)
    t.select(v=pw.unwrap(opt))._capture_node()
    assert "PW-N001" not in codes(analyze())


# ------------------------------------------------------------ surfaces


def test_analyze_returns_sorted_diagnostics():
    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    diags = analyze()
    sevs = [d.severity for d in diags]
    assert sevs == sorted(sevs, key=(SEV_ERROR, SEV_WARNING, "info").index)
    assert all(d.format() for d in diags)


def test_strict_mode_aborts_before_connector_starts():
    started = threading.Event()

    class Tracking(pw.io.python.ConnectorSubject):
        def run(self) -> None:
            started.set()

    class S(pw.Schema):
        word: str

    t = pw.io.python.read(Tracking(), schema=S)
    # an error-severity finding: dedup over a retracting input
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    with pytest.raises(AnalysisError) as ei:
        pw.run(strict=True)
    assert any(d.code == "PW-S002" for d in ei.value.diagnostics)
    assert not started.is_set(), "connector thread ran despite strict abort"


def test_strict_env_var(monkeypatch):
    monkeypatch.setenv("PATHWAY_STRICT", "1")

    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    with pytest.raises(AnalysisError):
        pw.run()


def test_non_strict_run_tolerates_warnings():
    t = _static_table()
    t.select(t.word, t.n)._capture_node()
    ctx = pw.run(strict=True)  # clean graph: strict run proceeds
    assert ctx is not None


def test_package_exports():
    assert pw.analyze is analyze
    assert pw.Diagnostic is not None
    assert pw.AnalysisError is AnalysisError
