"""Tier-1 tests for the pre-flight static analyzer
(``pathway_tpu/analysis/``): every diagnostic code has a trigger graph
and a near-miss, plus the strict-mode abort-before-connectors gate."""

from __future__ import annotations

import pathlib
import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import (
    SEV_ERROR,
    SEV_WARNING,
    AnalysisError,
    analyze,
)
from pathway_tpu.analysis import memory as mem
from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G

REPO = pathlib.Path(__file__).resolve().parent.parent


def codes(diags):
    return [d.code for d in diags]


def _static_table():
    class S(pw.Schema):
        word: str
        n: int

    return pw.debug.table_from_rows(S, [("a", 1), ("b", 2)])


class _Subject(pw.io.python.ConnectorSubject):
    """Never-started source: graphs here are analyzed, not run."""

    def run(self) -> None:  # pragma: no cover - not executed
        pass


def _streaming_table():
    class S(pw.Schema):
        word: str
        n: int

    return pw.io.python.read(_Subject(), schema=S)


# ---------------------------------------------------------------- T001


def test_t001_join_key_type_mismatch():
    class L(pw.Schema):
        k: int
        v: int

    class R(pw.Schema):
        k: str
        w: int

    left = pw.debug.table_from_rows(L, [(1, 10)])
    right = pw.debug.table_from_rows(R, [("1", 20)])
    left.join(right, left.k == right.k).select(pw.this.v, pw.this.w)
    diags = analyze()
    t001 = [d for d in diags if d.code == "PW-T001"]
    assert t001 and t001[0].severity == SEV_ERROR


def test_t001_join_key_match_clean():
    class L(pw.Schema):
        k: int
        v: int

    class R(pw.Schema):
        k: int
        w: int

    left = pw.debug.table_from_rows(L, [(1, 10)])
    right = pw.debug.table_from_rows(R, [(1, 20)])
    left.join(right, left.k == right.k).select(pw.this.v, pw.this.w)
    assert "PW-T001" not in codes(analyze())


def test_t001_declare_type_contradiction():
    t = _static_table()
    t.select(s=pw.declare_type(str, pw.this.n + 1))
    diags = analyze()
    t001 = [d for d in diags if d.code == "PW-T001"]
    assert t001 and t001[0].severity == SEV_ERROR


def test_t001_declare_type_widening_clean():
    t = _static_table()
    # int -> float widening is a legal declaration
    t.select(f=pw.declare_type(float, pw.this.n + 1))
    assert "PW-T001" not in codes(analyze())


# ---------------------------------------------------------------- P001


def test_p001_call_py_on_streaming_column():
    t = _streaming_table()
    t.select(u=pw.apply(str.upper, t.word))
    diags = analyze()
    p001 = [d for d in diags if d.code == "PW-P001"]
    assert p001 and p001[0].severity == SEV_WARNING


def test_p001_static_call_py_clean():
    t = _static_table()
    t.select(u=pw.apply(str.upper, t.word))
    assert "PW-P001" not in codes(analyze())


def test_p001_vectorized_streaming_clean():
    t = _streaming_table()
    t.select(m=t.n + 1)  # lowers to pure VM bytecode, no CALL_PY
    assert "PW-P001" not in codes(analyze())


# ---------------------------------------------------------------- S001


def test_s001_unwindowed_groupby_over_stream():
    t = _streaming_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    diags = analyze()
    s001 = [d for d in diags if d.code == "PW-S001"]
    assert s001 and s001[0].severity == SEV_WARNING


def test_s001_static_groupby_clean():
    t = _static_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def _streaming_events():
    class S(pw.Schema):
        k: str
        t: int
        v: int

    return pw.io.python.read(_Subject(), schema=S)


def test_s001_interval_join_bounds_downstream_state():
    """A finite-interval temporal join is watermark-evicted: stateful
    consumers downstream of it must not be reported as unbounded."""
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    j = temporal.interval_join(
        a, b, a.t, b.t, temporal.interval(-1, 1), pw.left.k == pw.right.k
    ).select(k=pw.left.k, v=pw.left.v)
    j.groupby(j.k).reduce(j.k, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def test_s001_asof_join_bounds_downstream_state():
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    j = temporal.asof_join(
        a, b, a.t, b.t, pw.left.k == pw.right.k
    ).select(k=pw.left.k, v=pw.left.v)
    j.groupby(j.k).reduce(j.k, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def test_s001_asof_now_join_bounds_downstream_state():
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    j = temporal.asof_now_join(a, b, pw.left.k == pw.right.k).select(
        k=pw.left.k, v=pw.left.v
    )
    j.groupby(j.k).reduce(j.k, c=pw.reducers.count())
    assert "PW-S001" not in codes(analyze())


def test_s001_plain_join_still_fires_downstream():
    """Positive control for the temporal near-misses: the same shape with
    an unwindowed join keeps the diagnostic."""
    a = _streaming_events()
    b = _streaming_events()
    a.join(b, a.k == b.k).select(k=pw.left.k, v=pw.right.v)
    diags = analyze()
    assert "PW-S001" in codes(diags)


# ---------------------------------------------------------------- S002


def test_s002_deduplicate_over_retracting_input():
    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    diags = analyze()
    s002 = [d for d in diags if d.code == "PW-S002"]
    assert s002 and s002[0].severity == SEV_ERROR


def test_s002_deduplicate_over_append_only_clean():
    t = _static_table()
    t.deduplicate(value=t.n, acceptor=lambda new, old: new > old)
    assert "PW-S002" not in codes(analyze())


# ---------------------------------------------------------------- D001


def test_d001_dead_column():
    t = _static_table()
    sel = t.select(t.word, dead=t.n + 1)
    sel.select(t2=pw.this.word)._capture_node()
    diags = analyze()
    d001 = [d for d in diags if d.code == "PW-D001"]
    assert d001 and d001[0].severity == SEV_WARNING
    assert "dead" in d001[0].message


def test_d001_used_column_clean():
    t = _static_table()
    sel = t.select(t.word, kept=t.n + 1)
    sel.select(t2=pw.this.word, k=pw.this.kept)._capture_node()
    assert "PW-D001" not in codes(analyze())


# ---------------------------------------------------------------- N001


def test_n001_optional_into_declared_non_optional_sink():
    t = _static_table()
    opt = pw.if_else(t.n > 1, t.n, None)  # Optional[int]
    t.select(v=pw.declare_type(int, opt))._capture_node()
    diags = analyze()
    n001 = [d for d in diags if d.code == "PW-N001"]
    assert n001 and n001[0].severity == SEV_WARNING


def test_n001_unwrap_clean():
    t = _static_table()
    opt = pw.if_else(t.n > 1, t.n, None)
    t.select(v=pw.unwrap(opt))._capture_node()
    assert "PW-N001" not in codes(analyze())


# ------------------------------------------------------------ surfaces


def test_analyze_returns_sorted_diagnostics():
    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    diags = analyze()
    sevs = [d.severity for d in diags]
    assert sevs == sorted(sevs, key=(SEV_ERROR, SEV_WARNING, "info").index)
    assert all(d.format() for d in diags)


def test_strict_mode_aborts_before_connector_starts():
    started = threading.Event()

    class Tracking(pw.io.python.ConnectorSubject):
        def run(self) -> None:
            started.set()

    class S(pw.Schema):
        word: str

    t = pw.io.python.read(Tracking(), schema=S)
    # an error-severity finding: dedup over a retracting input
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    with pytest.raises(AnalysisError) as ei:
        pw.run(strict=True)
    assert any(d.code == "PW-S002" for d in ei.value.diagnostics)
    assert not started.is_set(), "connector thread ran despite strict abort"


def test_strict_env_var(monkeypatch):
    monkeypatch.setenv("PATHWAY_STRICT", "1")

    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.deduplicate(value=agg.c, acceptor=lambda new, old: new > old)
    with pytest.raises(AnalysisError):
        pw.run()


def test_non_strict_run_tolerates_warnings():
    t = _static_table()
    t.select(t.word, t.n)._capture_node()
    ctx = pw.run(strict=True)  # clean graph: strict run proceeds
    assert ctx is not None


def test_package_exports():
    assert pw.analyze is analyze
    assert pw.Diagnostic is not None
    assert pw.AnalysisError is AnalysisError
    assert pw.estimate_memory is mem.estimate_memory
    assert pw.MemoryReport is mem.MemoryReport
    assert pw.EstimateParams is mem.EstimateParams


# ------------------------------------------------- distribution helpers


def _files_table(tmp_path):
    """Byte-range-partitioned, non-order-preserving source (PR 9 split)."""
    d = tmp_path / "data"
    d.mkdir(exist_ok=True)
    (d / "part.jsonl").write_text(
        '{"word": "a", "n": 1}\n{"word": "b", "n": 2}\n'
    )

    class S(pw.Schema):
        word: str
        n: int

    return pw.io.jsonlines.read(str(d), schema=S, mode="static")


def _input_node():
    return next(
        n for n in G.engine_graph.nodes if isinstance(n, eg.InputNode)
    )


# ---------------------------------------------------------------- X001


def test_x001_dedup_over_byte_range_files(tmp_path):
    t = _files_table(tmp_path)
    t.deduplicate(value=t.n, acceptor=lambda new, old: new > old)
    diags = analyze()
    x001 = [d for d in diags if d.code == "PW-X001"]
    assert x001 and x001[0].severity == SEV_ERROR
    assert "order" in x001[0].message


def test_x001_index_upsert_over_byte_range_files(tmp_path):
    from pathway_tpu.stdlib.indexing import BruteForceKnnFactory

    docs = _files_table(tmp_path)
    docs = docs.select(
        word=pw.this.word,
        vec=pw.apply(lambda n: (float(n), 0.0), pw.this.n),
    )
    index = BruteForceKnnFactory(dimensions=2, reserved_space=8).build_data_index(
        docs.vec, docs
    )

    class QueryS(pw.Schema):
        qx: float
        qy: float

    queries = pw.io.python.read(_Subject(), schema=QueryS)
    queries = queries.select(
        qvec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.qx, pw.this.qy)
    )
    # the index node only materializes once a query consumes it
    index.query_as_of_now(queries.qvec, number_of_matches=1)
    assert "PW-X001" in codes(analyze())


def test_x001_python_fed_index_upsert_clean():
    """The ISSUE near-miss: a ``pw.io.python``-fed upsert stream is a
    single reader, so the keyed index upsert must NOT fire PW-X001."""
    from pathway_tpu.stdlib.indexing import BruteForceKnnFactory

    class DocS(pw.Schema):
        doc_id: str = pw.column_definition(primary_key=True)
        vx: float
        vy: float

    docs = pw.io.python.read(_Subject(), schema=DocS)
    docs = docs.select(
        doc_id=pw.this.doc_id,
        vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
    )
    BruteForceKnnFactory(dimensions=2, reserved_space=8).build_data_index(
        docs.vec, docs
    )
    assert "PW-X001" not in codes(analyze())


def test_x001_python_fed_dedup_clean():
    t = _streaming_table()
    t.deduplicate(value=t.n, acceptor=lambda new, old: new > old)
    assert "PW-X001" not in codes(analyze())


def test_x001_unordered_partitioned_upsert_source():
    """The source itself is the order-sensitive consumer when it dedups
    an upsert session across an unordered split."""
    _streaming_table()
    _input_node().meta["source"].update(
        {"upsert": True, "partitioning": "round-robin", "order_preserving": False}
    )
    diags = analyze()
    x001 = [d for d in diags if d.code == "PW-X001"]
    assert x001 and x001[0].severity == SEV_ERROR
    assert "upsert" in x001[0].message


# ---------------------------------------------------------------- X002


def test_x002_non_copartitioned_groupby(tmp_path):
    t = _files_table(tmp_path)
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    diags = analyze()
    x002 = [d for d in diags if d.code == "PW-X002"]
    assert x002 and x002[0].severity == SEV_WARNING
    assert "exchange" in x002[0].message
    # volume estimate comes from the source's build-time dtype annotation
    assert "bytes/row" in x002[0].message


def test_x002_copartitioned_regroup_clean(tmp_path):
    """A second groupby on the first one's key is already co-partitioned:
    only the first (source-fed) groupby warns."""
    t = _files_table(tmp_path)
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg.groupby(agg.word).reduce(agg.word, m=pw.reducers.max(agg.c))
    diags = analyze()
    x002 = [d for d in diags if d.code == "PW-X002"]
    assert len(x002) == 1


def test_x002_local_source_clean():
    t = _streaming_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    assert "PW-X002" not in codes(analyze())


# ---------------------------------------------------------------- X003


def test_x003_order_dependent_reducer_to_sink(tmp_path):
    t = _files_table(tmp_path)
    agg = t.groupby(t.word).reduce(t.word, last=pw.reducers.latest(t.n))
    agg._capture_node()
    diags = analyze()
    x003 = [d for d in diags if d.code == "PW-X003"]
    assert x003 and x003[0].severity == SEV_ERROR
    assert "latest" in x003[0].message


def test_x003_commutative_reducer_clean(tmp_path):
    t = _files_table(tmp_path)
    agg = t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    agg._capture_node()
    assert "PW-X003" not in codes(analyze())


def test_x003_ordered_source_clean():
    t = _streaming_table()
    agg = t.groupby(t.word).reduce(t.word, last=pw.reducers.latest(t.n))
    agg._capture_node()
    assert "PW-X003" not in codes(analyze())


# ---------------------------------------------------------------- R001


def test_r001_external_state_without_hooks():
    t = _streaming_table()
    node = eg.Node(G.engine_graph, [t._node], "external_sink")
    node.adapter = object()
    diags = analyze()
    r001 = [d for d in diags if d.code == "PW-R001"]
    assert r001 and r001[0].severity == SEV_ERROR
    assert "checkpoint" in r001[0].message


class _StatefulAdapter:
    def state_dict(self):
        return {}

    def load_state_dict(self, state):
        pass


class _HookedNode(eg.Node):
    def snapshot_state(self, ctx):
        return {}

    def on_restore(self, ctx):
        pass


def test_r001_hooked_external_state_clean():
    t = _streaming_table()
    node = _HookedNode(G.engine_graph, [t._node], "hooked_sink")
    node.adapter = _StatefulAdapter()
    assert "PW-R001" not in codes(analyze())


def test_r001_unserializable_adapter_flagged():
    """Hooks overridden but the adapter cannot round-trip its state:
    snapshot_state has nothing to fold in, still a coverage hole."""
    t = _streaming_table()
    node = _HookedNode(G.engine_graph, [t._node], "hooked_sink")
    node.adapter = object()
    diags = analyze()
    r001 = [d for d in diags if d.code == "PW-R001"]
    assert r001 and "state_dict" in r001[0].message


def test_r001_static_path_clean():
    """Out-of-band state on a static (bounded, replayable-from-source)
    path is not a recovery hazard."""
    t = _static_table()
    node = eg.Node(G.engine_graph, [t._node], "static_sink")
    node.adapter = object()
    assert "PW-R001" not in codes(analyze())


# ---------------------------------------------------------------- R002


def test_r002_single_owner_index_without_standby():
    """Availability hole: checkpoint-covered (hooks + stateful adapter,
    so no PW-R001) but the only copy of serving state lives on one rank
    with no snapshot-backed standby."""
    t = _streaming_table()
    node = _HookedNode(G.engine_graph, [t._node], "index_sink")
    node.adapter = _StatefulAdapter()
    diags = analyze()
    r002 = [d for d in diags if d.code == "PW-R002"]
    assert r002 and r002[0].severity == SEV_WARNING
    assert "standby" in r002[0].message


def test_r002_standby_annotation_clean():
    """Near-miss: the same single-owner node with a declared
    snapshot-backed standby (meta['failover']['standby']) is covered."""
    t = _streaming_table()
    node = _HookedNode(G.engine_graph, [t._node], "index_sink")
    node.adapter = _StatefulAdapter()
    node.meta["failover"] = {"standby": True}
    assert "PW-R002" not in codes(analyze())


def test_r002_static_path_clean():
    """A bounded static pipeline has no availability window to cover."""
    t = _static_table()
    node = _HookedNode(G.engine_graph, [t._node], "index_sink")
    node.adapter = _StatefulAdapter()
    assert "PW-R002" not in codes(analyze())


def test_r002_sharded_serving_graph_clean_single_owner_flagged():
    """The composed serving graph: RagServingApp(shards=2) stamps the
    standby annotation (near-miss), the default single-owner app does
    not (trigger)."""
    from pathway_tpu.serving import RagServingApp

    app = RagServingApp(shards=2)
    try:
        app.build()
        assert "PW-R002" not in codes(analyze())
    finally:
        app.close()

    G.clear()
    app2 = RagServingApp()
    try:
        app2.build()
        diags = analyze()
        r002 = [d for d in diags if d.code == "PW-R002"]
        assert r002 and r002[0].severity == SEV_WARNING
    finally:
        app2.close()


# ------------------------------------- M001 / M002 / M003 (memory pass)


def _keyed_streaming_events():
    """Upsert-keyed stream: live cardinality is O(keys), not O(stream)."""

    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        t: int
        v: int

    return pw.io.python.read(_Subject(), schema=S)


def _stream_join(sink: bool):
    a = _streaming_events()
    b = _streaming_events()
    j = a.join(b, a.k == b.k).select(k=pw.left.k, v=pw.right.v)
    if sink:
        j._capture_node()


def test_m001_stream_linear_state_reaching_sink():
    _stream_join(sink=True)
    diags = analyze()
    m1 = [d for d in diags if d.code == "PW-M001"]
    assert m1 and all(d.severity == SEV_ERROR for d in m1)
    assert m1[0].details["growth"] == mem.G_STREAM
    assert m1[0].details["estimated_bytes"] > 0


def test_m001_needs_sink_but_m003_still_warns():
    """Same join, nothing captured: not an M001 error (no sink pays the
    cost at read time), but snapshot bytes still grow -> M003."""
    _stream_join(sink=False)
    diags = analyze()
    assert "PW-M001" not in codes(diags)
    m3 = [d for d in diags if d.code == "PW-M003"]
    assert m3 and all(d.severity == SEV_WARNING for d in m3)
    assert m3[0].details["growth"] == mem.G_STREAM


def test_m001_m003_upsert_keyed_join_clean():
    """The fix the M001 message recommends: key the sources and the same
    join shape retains O(keys), even with a sink attached."""
    a = _keyed_streaming_events()
    b = _keyed_streaming_events()
    a.join(b, a.k == b.k).select(
        k=pw.left.k, v=pw.right.v
    )._capture_node()
    got = codes(analyze())
    assert "PW-M001" not in got
    assert "PW-M003" not in got


def test_m003_bounded_temporal_join_clean():
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    temporal.interval_join(
        a, b, a.t, b.t, temporal.interval(-1, 1), pw.left.k == pw.right.k
    ).select(k=pw.left.k, v=pw.left.v)
    got = codes(analyze())
    assert "PW-M003" not in got
    assert "PW-M001" not in got


def test_m002_budget_breach_carries_breakdown(monkeypatch):
    monkeypatch.setenv("PATHWAY_MEMORY_BUDGET", "64K")
    t = _streaming_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    diags = analyze()
    m2 = [d for d in diags if d.code == "PW-M002"]
    assert m2 and m2[0].severity == SEV_WARNING
    det = m2[0].details
    assert det["budget_bytes"] == 64 * 1024
    assert det["estimated_bytes"] > det["budget_bytes"]
    sizes = [b for _label, b in det["breakdown"]]
    assert sizes and sizes == sorted(sizes, reverse=True)


def test_m002_ample_budget_clean(monkeypatch):
    monkeypatch.setenv("PATHWAY_MEMORY_BUDGET", "1TiB")
    t = _streaming_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    assert "PW-M002" not in codes(analyze())


# ------------------------------------------------ estimator unit tests


def test_growth_lattice_total_order():
    order = (mem.G_CONSTANT, mem.G_BOUNDED, mem.G_KEYS, mem.G_STREAM)
    for i, lo in enumerate(order):
        for hi in order[i:]:
            assert mem.growth_join(lo, hi) == hi
            assert mem.growth_meet(lo, hi) == lo
    assert mem.growth_join() == mem.G_CONSTANT
    assert mem.growth_meet() == mem.G_STREAM


def test_dtype_width_from_annotations():
    assert mem.dtype_width(dt.INT) == 8
    assert mem.dtype_width(dt.DATE_TIME_UTC) == 8
    assert mem.dtype_width(dt.STR, str_bytes=40) == 40
    assert mem.dtype_width(dt.JSON, str_bytes=10) == 40  # nested payload
    assert mem.dtype_width(dt.ANY) == 24  # unannotated boxed object
    assert mem.dtype_width(dt.Optional(dt.INT)) == 8  # optionality is free


def test_parse_budget_suffixes():
    assert mem.parse_budget(None) is None
    assert mem.parse_budget("") is None
    assert mem.parse_budget("4096") == 4096
    assert mem.parse_budget("64K") == 64 * 1024
    assert mem.parse_budget("64KB") == 64 * 1024
    assert mem.parse_budget("4GiB") == 4 * (1 << 30)
    assert mem.parse_budget("1.5M") == int(1.5 * (1 << 20))
    assert mem.parse_budget("2T") == 2 * (1 << 40)
    assert mem.parse_budget("lots") is None


def test_estimate_params_env_and_overrides(monkeypatch):
    monkeypatch.setenv("PATHWAY_MEMORY_ROWS", "123")
    monkeypatch.setenv("PATHWAY_MEMORY_KEYS", "7")
    monkeypatch.setenv("PATHWAY_MEMORY_STR_BYTES", "not-a-number")
    p = mem.EstimateParams.from_env(workers=3)
    assert p.rows == 123
    assert p.distinct_keys == 7
    assert p.str_bytes == mem.EstimateParams.str_bytes  # bad env -> default
    assert p.workers == 3  # explicit override beats env
    assert p.cardinality(mem.G_STREAM) == 123
    assert p.cardinality(mem.G_KEYS) == 7
    assert p.cardinality(mem.G_BOUNDED) == p.window_rows
    assert p.cardinality(mem.G_CONSTANT) == 0


def test_split_bytes_placement_lattice():
    assert mem._split_bytes(("single",), 100, 4) == 100
    assert mem._split_bytes(("repl",), 100, 4) == 100  # every rank holds it
    assert mem._split_bytes(("key", "word"), 100, 4) == 25
    assert mem._split_bytes(("key", "word"), 101, 4) == 26  # ceil, not floor
    assert mem._split_bytes(("key", "word"), 100, 1) == 100


def test_window_bounds_join_retention_not_stream_length():
    from pathway_tpu.stdlib import temporal

    a = _streaming_events()
    b = _streaming_events()
    temporal.interval_join(
        a, b, a.t, b.t, temporal.interval(-1, 1), pw.left.k == pw.right.k
    ).select(k=pw.left.k)
    small = pw.estimate_memory(optimize=0, window_rows=16)
    big = pw.estimate_memory(optimize=0, window_rows=4096)
    j_small = next(o for o in small.operators if o.kind == "IntervalJoinNode")
    j_big = next(o for o in big.operators if o.kind == "IntervalJoinNode")
    assert j_small.growth == mem.G_BOUNDED
    assert j_small.total_bytes < j_big.total_bytes
    # a 100x longer stream must not move a window-bounded buffer
    longer = pw.estimate_memory(optimize=0, window_rows=16, rows=100_000_000)
    j_longer = next(
        o for o in longer.operators if o.kind == "IntervalJoinNode"
    )
    assert j_longer.total_bytes == j_small.total_bytes


def test_per_worker_split_with_partitioned_source(tmp_path):
    t = _files_table(tmp_path)
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    one = pw.estimate_memory(optimize=0, workers=1)
    four = pw.estimate_memory(optimize=0, workers=4)
    assert four.workers == 4
    assert 0 < four.max_worker_bytes < one.max_worker_bytes
    assert four.total_bytes == one.total_bytes  # split, not shrunk


def test_memory_report_surfaces():
    t = _streaming_table()
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())
    rep = pw.estimate_memory()
    assert rep.total_bytes > 0
    assert rep.by_id()  # node-keyed view
    txt = rep.format()
    assert "TOTAL" in txt and "groupby" in txt


# --------------------------------- golden: plan-aware estimates (sat 3)


def test_golden_dead_column_elided_from_optimized_estimate():
    """The estimate must price the graph that RUNS: a join side's dead
    column is nulled by the plan rewriter, so the optimize=2 report is
    strictly cheaper than the raw optimize=0 one."""
    a = _streaming_events()
    sel = a.select(a.k, dead=a.k)  # str-width ballast, never used
    b = _streaming_events()
    sel.join(b, sel.k == b.k).select(
        k=pw.left.k, v=pw.right.v
    )._capture_node()
    r0 = pw.estimate_memory(optimize=0)
    r2 = pw.estimate_memory(optimize=2)
    assert r0.level == 0 and r2.level == 2
    j0 = next(o for o in r0.operators if o.kind == "JoinNode")
    j2 = next(o for o in r2.operators if o.kind == "JoinNode")
    assert j2.total_bytes < j0.total_bytes
    assert r2.total_bytes < r0.total_bytes


# ----------------------- predicted vs measured (runtime cross-check)


def test_predicted_vs_measured_operator_state(monkeypatch):
    """End-to-end cross-validation in miniature: run a real streaming
    groupby, then join the static estimate against the scheduler's
    sampled ``approx_state_bytes`` via ``memory_stats`` — same label
    join and same loose-bound contract ``bench_capacity`` enforces."""
    n_rows, n_keys = 600, 40
    monkeypatch.setenv("PATHWAY_MEMORY_ROWS", str(n_rows))
    monkeypatch.setenv("PATHWAY_MEMORY_KEYS", str(n_keys))
    monkeypatch.setenv("PATHWAY_MEMORY_STR_BYTES", "8")

    class Feed(pw.io.python.ConnectorSubject):
        def run(self) -> None:
            for i in range(n_rows):
                self.next(word=f"w{i % n_keys}", n=i)
            self.commit()

    class S(pw.Schema):
        word: str
        n: int

    t = pw.io.python.read(Feed(), schema=S)
    t.groupby(t.word).reduce(t.word, c=pw.reducers.count())._capture_node()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    from pathway_tpu.internals.monitoring import memory_stats

    sched = G.active_scheduler
    assert sched is not None
    stats = memory_stats(sched)
    joined = {
        label: v
        for label, v in stats.items()
        if v["estimated"] > 0 and v["measured"] > 0
    }
    assert joined, stats  # estimate and probe agree on operator labels
    predicted = sum(v["estimated"] for v in joined.values())
    measured = sum(v["measured"] for v in joined.values())
    assert 0.1 <= predicted / measured <= 10.0, stats


# ---------------------------------------------- registry + docs (sat 1)


def test_registry_is_single_source_of_truth():
    from pathway_tpu.analysis.diagnostics import CODE_INFO, CODES, render_code_table

    table = render_code_table()
    for code, (sev, desc) in CODE_INFO.items():
        assert CODES[code] == sev
        assert code in table and sev in table
        assert desc  # every code carries a human description
    for code in ("PW-X001", "PW-X002", "PW-X003", "PW-R001"):
        assert code in CODE_INFO

    import pathway_tpu.analysis.diagnostics as diag_mod

    for code in CODE_INFO:
        assert code in (diag_mod.__doc__ or ""), code


def test_readme_documents_every_code():
    readme = (REPO / "README.md").read_text()
    from pathway_tpu.analysis.diagnostics import CODE_INFO

    for code in CODE_INFO:
        assert f"`{code}`" in readme, f"{code} missing from README table"


# ----------------------------------------------- acceptance graphs


def test_wordcount_graph_zero_errors(tmp_path):
    t = _files_table(tmp_path)
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    counts._capture_node()
    diags = analyze()
    assert not [d for d in diags if d.severity == SEV_ERROR], diags


def test_index_churn_graph_zero_errors():
    from pathway_tpu.stdlib.indexing import BruteForceKnnFactory

    class DocS(pw.Schema):
        doc_id: str = pw.column_definition(primary_key=True)
        vx: float
        vy: float

    class QueryS(pw.Schema):
        qid: str = pw.column_definition(primary_key=True)
        qx: float
        qy: float

    docs = pw.io.python.read(_Subject(), schema=DocS)
    docs = docs.select(
        doc_id=pw.this.doc_id,
        vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
    )
    queries = pw.io.python.read(_Subject(), schema=QueryS)
    queries = queries.select(
        qid=pw.this.qid,
        qvec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.qx, pw.this.qy),
    )
    index = BruteForceKnnFactory(dimensions=2, reserved_space=8).build_data_index(
        docs.vec, docs
    )
    index.query_as_of_now(queries.qvec, number_of_matches=2)._capture_node()
    diags = analyze()
    assert not [d for d in diags if d.severity == SEV_ERROR], diags


def test_rag_serving_graph_zero_errors():
    from pathway_tpu.serving import RagServingApp, TenantPolicy

    app = RagServingApp(
        {"t": TenantPolicy("interactive", rate_per_s=10.0, burst=4, queue_cap=8)},
        embed_dim=8,
        delta_cap=8,
        auto_merge=False,
    )
    app.build()
    try:
        diags = analyze()
        assert not [d for d in diags if d.severity == SEV_ERROR], diags
        # satellite 2: serving nodes carry build-time stage annotations
        stages = {
            n.meta["serving"]["stage"]
            for n in G.engine_graph.nodes
            if "serving" in n.meta
        }
        assert {"ingest", "chunk", "index-upsert"} <= stages
    finally:
        app.close()


def test_strict_mode_surfaces_distribution_errors(tmp_path):
    t = _files_table(tmp_path)
    t.deduplicate(value=t.n, acceptor=lambda new, old: new > old)
    with pytest.raises(AnalysisError) as ei:
        pw.run(strict=True)
    assert any(d.code == "PW-X001" for d in ei.value.diagnostics)
    from pathway_tpu.analysis import count_by_severity

    counts = count_by_severity(ei.value.diagnostics)
    assert counts.get("error", 0) >= 1  # the /status + metrics payload


# ------------------------------------------- PW-J device safety (ISSUE 20)


def _dscan(src, filename="pathway_tpu/parallel/mod.py"):
    from pathway_tpu.analysis.device import scan_source

    return scan_source(src, filename)


_JIT_PRELUDE = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "\n"
    "_score = jax.jit(lambda q, c: q @ c.T)\n"
    "\n"
)


def test_j001_unpadded_param_into_jit():
    src = _JIT_PRELUDE + (
        "def search(queries, corpus):\n"
        "    return _score(jnp.asarray(queries), corpus)\n"
    )
    diags = _dscan(src)
    assert codes(diags) == ["PW-J001"]
    assert diags[0].severity == SEV_ERROR
    assert diags[0].details["pattern"] == "unpadded_param"


def test_j001_bucketed_padding_clean():
    src = _JIT_PRELUDE + (
        "def search(queries, corpus):\n"
        "    queries = pad_rows(queries, bucket_size(len(queries)))\n"
        "    return _score(jnp.asarray(queries), corpus)\n"
    )
    assert _dscan(src) == []


def test_j001_ceil_div_multiple_padding():
    # multiple-of-block padding still compiles one program per distinct
    # block count — the recompile storm the IVF fix removed
    src = _JIT_PRELUDE + (
        "def search(queries, corpus):\n"
        "    n = queries.shape[0]\n"
        "    pad = ((n + 8 - 1) // 8) * 8\n"
        "    queries = pad_rows(queries, pad)\n"
        "    return _score(jnp.asarray(queries), corpus)\n"
    )
    diags = _dscan(src)
    assert codes(diags) == ["PW-J001"]
    assert diags[0].details["pattern"] == "ceil_div_multiple"


def test_j001_ceil_div_over_bucketed_blocks_clean():
    # the fixed IVF shape: block COUNT rounded to a power of two
    src = _JIT_PRELUDE + (
        "def search(queries, corpus, qb):\n"
        "    n = queries.shape[0]\n"
        "    pad = qb * bucket_size(-(-n // qb), min_bucket=1)\n"
        "    queries = pad_rows(queries, pad)\n"
        "    return _score(jnp.asarray(queries), corpus)\n"
    )
    assert _dscan(src) == []


def test_j001_cold_path_clean():
    # train/init/restore paths compile once by design
    src = _JIT_PRELUDE + (
        "def train_step(batch, corpus):\n"
        "    return _score(jnp.asarray(batch), corpus)\n"
    )
    assert _dscan(src) == []


def test_j001_waiver_comment_suppresses():
    src = _JIT_PRELUDE + (
        "def search(queries, corpus):\n"
        "    return _score(jnp.asarray(queries), corpus)"
        "  # pw-j001: fixed upstream batch size\n"
    )
    assert _dscan(src) == []


def test_j002_transfer_in_hot_loop():
    src = (
        "import jax\n"
        "def serve(batches):\n"
        "    out = []\n"
        "    for b in batches:\n"
        "        out.append(jax.device_put(b))\n"
        "    return out\n"
    )
    diags = _dscan(src)
    assert codes(diags) == ["PW-J002"]
    assert diags[0].severity == SEV_WARNING


def test_j002_pipelined_readback_clean():
    # copy_to_host_async is the cure, not the disease
    src = (
        "import jax\n"
        "def serve(outs):\n"
        "    for o in outs:\n"
        "        o.copy_to_host_async()\n"
        "    return jax.device_get(outs)\n"
    )
    assert _dscan(src) == []


def test_j002_comprehension_not_a_loop():
    # a device_put list comprehension is one batched staging step, not a
    # per-iteration stall (executor._dispatch idiom)
    src = (
        "import jax\n"
        "def dispatch(args, shardings):\n"
        "    return [jax.device_put(a, s) for a, s in zip(args, shardings)]\n"
    )
    assert _dscan(src) == []


def test_j003_inplace_without_donation():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def scatter(buf, idx, vals):\n"
        "    return buf.at[idx].set(vals)\n"
    )
    diags = _dscan(src)
    assert codes(diags) == ["PW-J003"]
    assert diags[0].severity == SEV_WARNING


def test_j003_donated_scatter_clean():
    src = (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def scatter(buf, idx, vals):\n"
        "    return buf.at[idx].set(vals)\n"
    )
    assert _dscan(src) == []


def test_j003_safe_twin_of_donated_scatter_clean():
    # sharded_knn's deliberate non-donating *_safe twin for
    # concurrent-dispatch windows
    src = (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def scatter(buf, idx, vals):\n"
        "    return buf.at[idx].set(vals)\n"
        "@jax.jit\n"
        "def scatter_safe(buf, idx, vals):\n"
        "    return buf.at[idx].set(vals)\n"
    )
    assert _dscan(src) == []


def test_j004_collective_under_rank_branch():
    src = (
        "import jax\n"
        "def exchange(x, rank):\n"
        "    if rank == 0:\n"
        "        return jax.lax.psum(x, 'i')\n"
        "    return x\n"
    )
    diags = _dscan(src)
    assert codes(diags) == ["PW-J004"]
    assert diags[0].severity == SEV_ERROR


def test_j004_fires_even_on_cold_paths():
    # a deadlock at init hangs the mesh too — coldness is no excuse
    src = (
        "import jax\n"
        "def init_mesh(x, rank):\n"
        "    if rank == 0:\n"
        "        return jax.lax.psum(x, 'i')\n"
        "    return x\n"
    )
    assert codes(_dscan(src)) == ["PW-J004"]


def test_j004_static_config_branch_clean():
    # every process computes the same truth value — not divergent
    src = (
        "import jax\n"
        "class Index:\n"
        "    def exchange(self, x):\n"
        "        if self.mesh is not None:\n"
        "            return jax.lax.psum(x, 'i')\n"
        "        return x\n"
    )
    assert _dscan(src) == []


def test_j005_blocking_sync_under_lock():
    src = (
        "import jax\n"
        "class Index:\n"
        "    def swap(self, new):\n"
        "        with self._lock:\n"
        "            self._buf = new\n"
        "            self._buf.block_until_ready()\n"
    )
    diags = _dscan(src)
    assert codes(diags) == ["PW-J005"]
    assert diags[0].severity == SEV_WARNING


def test_j005_sync_outside_lock_clean():
    src = (
        "import jax\n"
        "class Index:\n"
        "    def swap(self, new):\n"
        "        new.block_until_ready()\n"
        "        with self._lock:\n"
        "            self._buf = new\n"
    )
    assert _dscan(src) == []


def test_j005_serving_lane_readback():
    src = (
        "import jax\n"
        "def answer_lane(out):\n"
        "    return out.item()\n"
    )
    diags = _dscan(src, filename="pathway_tpu/serving/lanes.py")
    assert codes(diags) == ["PW-J005"]
    # same function outside the serving tree: nothing to serialize
    assert _dscan(src, filename="pathway_tpu/parallel/lanes.py") == []


def test_jitted_body_is_exempt_from_hot_checks():
    # inside a traced body coercions are free: they fold into the program
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def kernel(xs):\n"
        "    acc = jnp.asarray(0.0)\n"
        "    for x in xs:\n"
        "        acc = acc + jnp.asarray(x)\n"
        "    return acc\n"
    )
    assert _dscan(src) == []


def test_device_surface_scans_clean():
    """Acceptance: the committed device modules carry zero PW-J errors
    and zero predicted recompile sites — the static half of the
    zero-recompile invariant BENCH_device.json cross-validates live."""
    from pathway_tpu.analysis.device import device_module_files, scan_paths

    report = scan_paths(device_module_files())
    assert len(report.files) >= 10
    assert report.errors == 0, report.diagnostics
    assert report.predicted_recompile_sites == 0


def test_device_profile_shape():
    from pathway_tpu.analysis.device import device_profile

    prof = device_profile(refresh=True)
    assert set(prof) >= {
        "files_scanned",
        "findings",
        "errors",
        "by_code",
        "predicted_recompile_sites",
    }
    assert prof["errors"] == 0


def test_j_codes_registered():
    from pathway_tpu.analysis.diagnostics import CODE_INFO, SEV_ERROR, SEV_WARNING

    assert CODE_INFO["PW-J001"][0] == SEV_ERROR
    assert CODE_INFO["PW-J002"][0] == SEV_WARNING
    assert CODE_INFO["PW-J003"][0] == SEV_WARNING
    assert CODE_INFO["PW-J004"][0] == SEV_ERROR
    assert CODE_INFO["PW-J005"][0] == SEV_WARNING


def test_device_pass_runs_in_analyze_for_serving_graphs():
    """check_device is wired into ALL_PASSES: a graph whose node carries
    a serving stage annotation sweeps the whole device surface."""
    from pathway_tpu.analysis.passes import ALL_PASSES
    from pathway_tpu.analysis.device import check_device

    assert check_device in ALL_PASSES
    t = _static_table()
    t.select(w=pw.this.word)._capture_node()
    for n in G.engine_graph.nodes:
        n.meta["serving"] = {"stage": "ingest"}
        break
    diags = analyze()
    assert not [d for d in diags if d.code.startswith("PW-J")], diags


def _indexed_docs_graph():
    """Python-fed docs feeding a KNN index (the device-resident state
    the per-chip budget prices)."""
    from pathway_tpu.stdlib.indexing import BruteForceKnnFactory

    class DocS(pw.Schema):
        doc_id: str = pw.column_definition(primary_key=True)
        vx: float
        vy: float

    docs = pw.io.python.read(_Subject(), schema=DocS)
    docs = docs.select(
        doc_id=pw.this.doc_id,
        vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
    )
    index = BruteForceKnnFactory(
        dimensions=2, reserved_space=4096
    ).build_data_index(docs.vec, docs)
    index.query_as_of_now(docs.vec, number_of_matches=2)


def test_device_budget_per_chip(monkeypatch):
    """PATHWAY_DEVICE_BUDGET_BYTES: the device-resident share of the
    estimate must fit per chip; PW-M002 carries the device scope."""
    monkeypatch.setenv("PATHWAY_DEVICE_BUDGET_BYTES", "1")
    monkeypatch.setenv("PATHWAY_DEVICE_CHIPS", "2")
    _indexed_docs_graph()
    diags = analyze()
    dev = [
        d
        for d in diags
        if d.code == "PW-M002"
        and d.details.get("scope") == "device-per-chip"
    ]
    assert dev, codes(diags)
    det = dev[0].details
    assert det["chips"] == 2
    assert det["estimated_bytes"] > det["budget_bytes"]
    assert det["breakdown"]


def test_device_budget_ample_clean(monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_BUDGET_BYTES", "1TiB")
    _indexed_docs_graph()
    assert not [
        d
        for d in analyze()
        if d.code == "PW-M002"
        and d.details.get("scope") == "device-per-chip"
    ]
