"""Property test: randomized pipelines are worker-count invariant.

The scale-out contract (reference thread-count CI matrix,
``tests/utils.py:37-50``) says ANY pipeline produces identical output at
any worker count — not just the hand-picked ones in test_multiworker.py.
Each seed deterministically generates a small pipeline from a closed
grammar (filter / select / groupby-reduce / join, all mapping the column
shape ``(k: str, a: int, b: int)`` to itself) and runs it at 1, 2 and 4
thread workers; the captured rows, keys included, must match exactly.
"""

from __future__ import annotations

import json
import random

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.cluster import Cluster
from pathway_tpu.engine.graph import CaptureNode
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G

_LETTERS = "abcdefg"


class _S(pw.Schema):
    k: str
    a: int
    b: int


def _write_inputs(tmp_path, seed: int):
    rng = random.Random(seed + 7919)  # data stream separate from pipeline
    main = tmp_path / "main.jsonl"
    main.write_text(
        "\n".join(
            json.dumps(
                {
                    "k": rng.choice(_LETTERS),
                    "a": rng.randint(-50, 50),
                    "b": rng.randint(0, 9),
                }
            )
            for _ in range(60)
        )
    )
    # lookup side: at most one row per key so joins stay 1:N
    side = tmp_path / "side.jsonl"
    side.write_text(
        "\n".join(
            json.dumps({"k": k, "a": rng.randint(-10, 10), "b": rng.randint(0, 9)})
            for k in _LETTERS
            if rng.random() < 0.8
        )
    )
    return main, side


def _apply_stage(rng: random.Random, t, side):
    op = rng.choice(["filter", "select", "groupby", "join"])
    if op == "filter":
        c = rng.randint(-20, 20)
        if rng.random() < 0.5:
            return t.filter(t.a > c)
        return t.filter(t.b != (c % 7))
    if op == "select":
        c = rng.randint(1, 5)
        return t.select(t.k, a=t.a * c + t.b, b=t.b + 1)
    if op == "groupby":
        return t.groupby(t.k).reduce(
            t.k,
            a=pw.reducers.sum(t.a),
            b=pw.reducers.max(t.b),
        )
    j = t.join(side, t.k == side.k)
    return j.select(t.k, a=pw.left.a + pw.right.a, b=pw.left.b + pw.right.b)


def _run_pipeline(seed: int, n_threads: int, main_file, side_file) -> dict:
    G.clear()
    rng = random.Random(seed)
    t = pw.io.jsonlines.read(str(main_file), schema=_S, mode="static")
    side = pw.io.jsonlines.read(str(side_file), schema=_S, mode="static")
    for _ in range(rng.randint(2, 4)):
        t = _apply_stage(rng, t, side)
    cap = CaptureNode(G.engine_graph, t._node)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    cluster = Cluster(threads=n_threads)
    try:
        ctx = sched.run_cluster(cluster)
    finally:
        cluster.close()
    return dict(ctx.state(cap)["rows"])


@pytest.mark.parametrize("seed", [1, 2, 3, 5, 8])
def test_random_pipeline_worker_count_invariant(tmp_path, seed):
    main_file, side_file = _write_inputs(tmp_path, seed)
    baseline = _run_pipeline(seed, 1, main_file, side_file)
    for n_threads in (2, 4):
        got = _run_pipeline(seed, n_threads, main_file, side_file)
        assert got == baseline, (
            f"seed {seed}: {n_threads}-worker run diverged from single-worker"
        )
