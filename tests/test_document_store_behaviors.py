"""DocumentStore pipeline behaviors: splitters, post-processors,
metadata merge, retrieval filters, statistics/inputs endpoints, and
live updates through the index (reference ``document_store.py`` +
``tests/unit/test_document_store.py`` roles).
"""

from __future__ import annotations

import dataclasses

import pytest

import pathway_tpu as pw
from pathway_tpu.models import MINILM_L6
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.embedders import TPUEncoderEmbedder
from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter, null_splitter
from tests.utils import run_to_rows

import jax.numpy as jnp

TINY = dataclasses.replace(
    MINILM_L6, layers=2, hidden=64, heads=4, mlp_dim=128, dtype=jnp.float32
)


@pytest.fixture(scope="module")
def embedder():
    return TPUEncoderEmbedder(config=TINY)


def _docs(rows):
    return pw.debug.table_from_rows(
        pw.schema_from_types(data=bytes, _metadata=dict),
        rows,
    )


def _store(docs, embedder, **kwargs):
    return DocumentStore(
        docs,
        retriever_factory=BruteForceKnnFactory(
            embedder=embedder, reserved_space=64
        ),
        **kwargs,
    )


def _retrieve(store, query, k=2, metadata_filter=None, glob=None):
    queries = pw.debug.table_from_rows(
        pw.schema_from_types(
            query=str, k=int, metadata_filter=str, filepath_globpattern=str
        ),
        [(query, k, metadata_filter, glob)],
    )
    out = store.retrieve_query(queries)
    rows = run_to_rows(out.select(out.result))
    return rows[0][0] if rows else []


def test_token_count_splitter_respects_bounds():
    sp = TokenCountSplitter(min_tokens=5, max_tokens=10)
    text = "word " * 60
    chunks = sp.__wrapped__(text)
    assert len(chunks) >= 5
    for chunk_text, _meta in chunks:
        n = len(chunk_text.split())
        assert 1 <= n <= 10


def test_null_splitter_passthrough():
    out = null_splitter("whole doc stays intact")
    assert out == [("whole doc stays intact", {})]


def test_store_statistics_and_inputs(embedder):
    pw.G.clear()
    docs = _docs(
        [
            (b"apples grow on trees", {"path": "/a/fruit.txt", "modified_at": 5}),
            (b"the tpu multiplies matrices", {"path": "/b/tpu.txt", "modified_at": 9}),
        ]
    )
    store = _store(docs, embedder)
    stats_q = pw.debug.table_from_rows(pw.schema_from_types(q=int), [(0,)])
    stats = run_to_rows(store.statistics_query(stats_q.select()))
    assert stats and stats[0][0]["file_count"] == 2
    inputs_q = pw.debug.table_from_rows(
        pw.schema_from_types(metadata_filter=str, filepath_globpattern=str),
        [(None, "*.txt")],
    )
    inputs = run_to_rows(store.inputs_query(inputs_q))
    paths = {d["path"] for d in inputs[0][0]}
    assert paths == {"/a/fruit.txt", "/b/tpu.txt"}


def test_retrieval_glob_and_metadata_filters(embedder):
    pw.G.clear()
    docs = _docs(
        [
            (b"apples and oranges in the orchard", {"path": "/a/fruit.txt", "modified_at": 5}),
            (b"apples compile matrix kernels", {"path": "/b/tpu.md", "modified_at": 9}),
        ]
    )
    store = _store(docs, embedder)
    all_hits = _retrieve(store, "apples", k=5)
    assert len(all_hits) == 2
    txt_only = _retrieve(store, "apples", k=5, glob="*.txt")
    assert [d["metadata"]["path"] for d in txt_only] == ["/a/fruit.txt"]
    newer = _retrieve(
        store, "apples", k=5, metadata_filter="modified_at > `7`"
    )
    assert [d["metadata"]["path"] for d in newer] == ["/b/tpu.md"]


def test_doc_post_processors_rewrite_text(embedder):
    pw.G.clear()
    docs = _docs([(b"MIXED case Document", {"path": "/x.txt"})])

    def lower_all(text: str, metadata: dict):
        return text.lower(), {**metadata, "post": True}

    store = _store(docs, embedder, doc_post_processors=[lower_all])
    hits = _retrieve(store, "mixed case document", k=1)
    assert hits and hits[0]["text"] == "mixed case document"
    assert hits[0]["metadata"]["post"] is True


def test_splitter_chunks_searchable_individually(embedder):
    """A long doc split into chunks: retrieval returns the RELEVANT
    chunk, with the source path in every chunk's metadata."""
    pw.G.clear()
    part_a = "quantum chromodynamics lattice simulation " * 3
    part_b = "sourdough bread fermentation starter " * 3
    docs = _docs([((part_a + part_b).encode(), {"path": "/long.txt"})])
    store = _store(
        docs,
        embedder,
        splitter=TokenCountSplitter(min_tokens=3, max_tokens=12),
    )
    hits = _retrieve(store, "sourdough fermentation", k=1)
    assert hits and "sourdough" in hits[0]["text"]
    assert hits[0]["metadata"]["path"] == "/long.txt"


def test_parser_errors_do_not_abort_store(embedder):
    """A document whose parser raises lands in the error flow; the other
    documents still index (per-row containment)."""
    pw.G.clear()
    docs = _docs(
        [
            (b"good document about apples", {"path": "/good.txt"}),
            (b"\x00\x01broken", {"path": "/bad.bin"}),
        ]
    )

    class PickyParser(pw.udfs.UDF):
        def __wrapped__(self, data, **kw):
            if b"\x00" in data:
                raise ValueError("unparseable")
            return [(data.decode(), {})]

    store = _store(docs, embedder, parser=PickyParser())
    hits = _retrieve(store, "apples", k=5)
    assert [d["metadata"]["path"] for d in hits] == ["/good.txt"]
