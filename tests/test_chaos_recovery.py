"""Crash-recovery drills driven by the seedable fault-injection harness
(``pathway_tpu.testing.chaos``): torn persistence writes, kill-mid-epoch
restarts, crash between operator snapshot and commit."""

import random
import time as _time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import Scheduler
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.resilience import ConnectorRecoveryPolicy
from pathway_tpu.io._connector import DictSource, input_table
from pathway_tpu.persistence import (
    Backend,
    Config,
    PersistenceMode,
    attach_persistence,
)
from pathway_tpu.testing import ChaosError, chaos, flaky_once

pytestmark = pytest.mark.chaos


class WordSchema(pw.Schema):
    word: str


ROWS = [{"word": w} for w in ["a", "b", "a", "c", "a", "b"]]
EXPECTED = {"a": 3, "b": 2, "c": 1}


def _build(gen, results, policy=None, name="wsrc"):
    src = DictSource(gen, WordSchema, commit_every=2)
    t = input_table(src, WordSchema, name=name, recovery_policy=policy)
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())

    def on_change(key, row, time, is_addition):
        if is_addition:
            results[row["word"]] = row["n"]
        elif results.get(row["word"]) == row["n"]:
            del results[row["word"]]

    pw.io.subscribe(counts, on_change=on_change)


def _run(tmp_path, mode=None):
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    cfg = (
        Config.simple_config(Backend.filesystem(tmp_path / "snap"))
        if mode is None
        else Config.simple_config(
            Backend.filesystem(tmp_path / "snap"),
            persistence_mode=mode,
            snapshot_interval_ms=0,
        )
    )
    attach_persistence(sched, cfg)
    sched.run()
    return sched


# ---------------------------------------------------------------------------
# harness smoke test (tier-1-safe: no engine, no sleeps beyond ~10ms)


def test_chaos_smoke():
    class Service:
        def __init__(self):
            self.calls = 0

        def ping(self):
            self.calls += 1
            return "pong"

    svc = Service()
    with chaos(seed=3) as c:
        c.raise_on_nth_call(svc, "ping", n=2)
        assert svc.ping() == "pong"
        with pytest.raises(ChaosError):
            svc.ping()
        assert svc.ping() == "pong"  # transient: only the 2nd call failed
        assert c.call_count(svc, "ping") == 3
        assert svc.calls == 2  # the faulted call never reached the body
    assert svc.ping() == "pong"  # patch restored on exit

    svc2 = Service()
    with chaos() as c:
        c.raise_on_nth_call(svc2, "ping", n=2, every=True)  # permanent fault
        c.inject_latency(svc2, "ping", delay_s=0.001, jitter_s=0.002)
        svc2.ping()
        for _ in range(3):
            with pytest.raises(ChaosError):
                svc2.ping()


def test_chaos_seeded_latency_is_deterministic():
    draws = []
    for _ in range(2):
        c = chaos(seed=42)
        draws.append([c.rng.uniform(0.0, 1.0) for _ in range(5)])
    assert draws[0] == draws[1]


# ---------------------------------------------------------------------------
# torn persistence writes


def test_torn_append_leaves_committed_prefix_readable(tmp_path):
    impl = Backend.filesystem(tmp_path / "p")._impl
    impl.append("s", b"first")
    impl.append("s", b"second")
    with chaos() as c:
        c.torn_write(impl, on_nth=1, keep_fraction=0.5)
        with pytest.raises(ChaosError):
            impl.append("s", b"third-record-payload")
    # the torn tail is invisible; the log keeps serving the full prefix
    assert impl.read_all("s") == [b"first", b"second"]
    # "restart": recovery truncates to the complete prefix (exactly what
    # replay_events does), then appends land cleanly past the torn bytes
    impl.truncate("s", 2)
    impl.append("s", b"fourth")
    assert impl.read_all("s") == [b"first", b"second", b"fourth"]


def test_torn_write_during_run_recovers_on_restart(tmp_path):
    """A crash mid-append while recording the input snapshot: the run
    dies, the restart replays only complete committed records and the
    reader resumes — final counts match the fault-free run."""
    backend = Backend.filesystem(tmp_path / "snap")

    results1: dict = {}
    _build(lambda: iter(ROWS), results1)
    sched = Scheduler(G.engine_graph, autocommit_ms=10)
    attach_persistence(sched, Config.simple_config(backend))
    with chaos() as c:
        # tear a mid-log data record; the reader thread dies with
        # ChaosError and the run finishes on the committed prefix
        c.torn_write(backend._impl, on_nth=4, keep_fraction=0.3)
        sched.run()

    G.clear()
    results2: dict = {}
    _build(lambda: iter(ROWS), results2)
    _run(tmp_path)
    assert results2 == EXPECTED


# ---------------------------------------------------------------------------
# kill mid-epoch → resume: identical tables


def test_kill_mid_epoch_resume_produces_identical_tables(tmp_path):
    """Run 1 faults mid-stream and the supervisor restarts it under
    persistence recording; run 2 resumes from the snapshot with appended
    rows.  Both runs end exactly right — no loss, no double-apply."""
    policy = ConnectorRecoveryPolicy(
        max_restarts=3, initial_delay_ms=5, jitter_ms=0, seed=0
    )
    results1: dict = {}
    _build(flaky_once(ROWS, 4), results1, policy=policy)
    sched = _run(tmp_path)
    assert results1 == EXPECTED
    stats = next(
        v for k, v in sched.connector_stats.items() if k.startswith("wsrc#")
    )
    assert stats["restarts"] == 1

    # "restart the process": fresh graph, same snapshot dir, more input
    G.clear()
    rows2 = ROWS + [{"word": "a"}, {"word": "d"}]
    results2: dict = {}
    _build(lambda: iter(rows2), results2, policy=policy)
    _run(tmp_path)
    assert results2 == {"a": 4, "b": 2, "c": 1, "d": 1}


# ---------------------------------------------------------------------------
# crash between operator snapshot and commit


def test_crash_after_operator_snapshot_resumes_exactly(tmp_path):
    """OPERATOR_PERSISTING: the process dies right after an operator
    snapshot lands on disk.  Resume must replay only the tail past the
    snapshot's consumed counts — the restarted run's final counts equal a
    fresh fault-free run's."""
    results1: dict = {}
    _build(lambda: iter(ROWS), results1)
    sched = Scheduler(G.engine_graph, autocommit_ms=5)
    attach_persistence(
        sched,
        Config.simple_config(
            Backend.filesystem(tmp_path / "snap"),
            persistence_mode=PersistenceMode.OPERATOR_PERSISTING,
            snapshot_interval_ms=0,
        ),
    )
    with chaos() as c:
        c.crash_between_snapshot_and_commit(sched.persistence, on_nth=1)
        with pytest.raises(ChaosError):
            sched.run()
    sched.stop()  # "the process died": reap reader threads before run 2
    _time.sleep(0.05)

    G.clear()
    results2: dict = {}
    _build(lambda: iter(ROWS), results2)
    _run(tmp_path, mode=PersistenceMode.OPERATOR_PERSISTING)
    assert results2 == EXPECTED


# ---------------------------------------------------------------------------
# randomized drill (excluded from tier-1)


@pytest.mark.slow
def test_randomized_fault_points_always_exactly_once(tmp_path):
    """Sweep seeded random fault points over the stream; every drill must
    deliver exactly-once after the supervised restart."""
    rng = random.Random(2026)
    policy = ConnectorRecoveryPolicy(
        max_restarts=3, initial_delay_ms=5, jitter_ms=0, seed=0
    )
    for drill in range(5):
        G.clear()
        fail_at = rng.randrange(1, len(ROWS))
        results: dict = {}
        _build(flaky_once(ROWS, fail_at), results, policy=policy)
        sched = Scheduler(G.engine_graph, autocommit_ms=10)
        sched.run()
        assert results == EXPECTED, (drill, fail_at, results)


# ---------------------------------------------------------------------------
# gray-failure primitives (ISSUE 13): seedable, restore-safe, scoped


def test_asymmetric_partition_validates_mode_and_restores():
    from pathway_tpu.engine.cluster import _PeerSender

    with pytest.raises(ValueError, match="drop.*delay|mode"):
        chaos(seed=0).asymmetric_partition(0, 1, mode="bogus")
    orig = _PeerSender._transmit
    with chaos(seed=0) as c:
        c.asymmetric_partition(0, 1, mode="drop")
        c.asymmetric_partition(1, 0, mode="delay", delay_s=0.0)
        assert _PeerSender._transmit is not orig
    assert _PeerSender._transmit is orig  # both patches unwound


def test_asymmetric_partition_scopes_one_direction():
    """Frames src->dst vanish; every other (links, peer) pair passes."""
    from pathway_tpu.engine.cluster import _PeerSender

    sent = []

    class _Links:
        process_id = 1

    class _Sender:
        links = _Links()

        def __init__(self, peer):
            self.peer = peer

    orig = _PeerSender._transmit
    try:
        _PeerSender._transmit = lambda self, body, n: sent.append(
            (self.links.process_id, self.peer)
        )
        with chaos(seed=0) as c:
            c.asymmetric_partition(1, 0, mode="drop")
            wrapper = _PeerSender._transmit
            wrapper(_Sender(0), b"", 1)  # 1 -> 0: dropped
            wrapper(_Sender(2), b"", 1)  # 1 -> 2: delivered
        assert sent == [(1, 2)]
    finally:
        _PeerSender._transmit = orig


def test_pause_resume_stops_and_continues_process():
    """SIGSTOP/SIGCONT drill against a real child: silent while paused,
    running again after the timer fires."""
    import subprocess
    import sys

    proc = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(20)"])

    def state() -> str:
        with open(f"/proc/{proc.pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0]

    try:
        with chaos(seed=1) as c:
            c.pause_resume(proc.pid, pause_s=0.3)
            _time.sleep(0.1)
            assert state() == "T", f"process not stopped: {state()}"
            _time.sleep(0.5)
            assert state() in ("S", "R"), f"process never resumed: {state()}"
    finally:
        proc.kill()
        proc.wait()


def test_pause_resume_restore_fires_pending_sigcont():
    """A failing drill must not leak a stopped process: chaos restore
    delivers the pending SIGCONT early."""
    import subprocess
    import sys

    proc = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(20)"])

    def state() -> str:
        with open(f"/proc/{proc.pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0]

    try:
        c = chaos(seed=2)
        with c:
            c.pause_resume(proc.pid, pause_s=60.0)
            _time.sleep(0.1)
            assert state() == "T"
        _time.sleep(0.1)  # context exit == restore == SIGCONT now
        assert state() in ("S", "R"), f"restore leaked a stopped process: {state()}"
    finally:
        proc.kill()
        proc.wait()


def test_slow_peer_is_seeded_delay_wrapper():
    from pathway_tpu.engine.cluster import _PeerSender

    orig = _PeerSender._transmit
    with chaos(seed=4) as c:
        c.slow_peer(0, delay_s=0.0, jitter_s=0.0)
        assert _PeerSender._transmit is not orig
    assert _PeerSender._transmit is orig
