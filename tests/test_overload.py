"""Overload drills: end-to-end backpressure and brownout shedding.

The pressure chain under test (ISSUE 16): a firehose source charges the
bytes-accounted ingest buffer (``PATHWAY_INGEST_BUFFER_BYTES``) and its
reader pauses/sheds/fails per ``on_overflow``; a slow-but-alive exchange
peer throttles producers through sender-side credit
(``PATHWAY_EXCHANGE_CREDIT_BYTES``) instead of being isolated; a stalled
sink holds the epoch cut so pressure propagates back to the sources; and
serving brownout sheds best-effort classes first while interactive
traffic keeps flowing.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.scheduler import (
    IngestCredit,
    IngestOverflow,
)
from pathway_tpu.testing.chaos import chaos

# ---------------------------------------------------------------------------
# ingest credit accounting (unit)


def test_ingest_credit_charge_consume_roundtrip():
    credit = IngestCredit(1000)
    s0 = credit.charge(7, 300, 2, "pause", None)
    s1 = credit.charge(7, 300, 1, "pause", None)
    assert (s0, s1) == (0, 1)
    t = credit.totals()
    assert t["buffered_bytes"] == 600
    assert t["buffered_rows"] == 3
    assert 0.0 < t["level"] <= 1.0
    assert credit.consume(7, s0) is True
    assert credit.consume(7, s1) is True
    t = credit.totals()
    assert t["buffered_bytes"] == 0
    assert t["buffered_rows"] == 0


def test_ingest_credit_always_admits_when_empty():
    # one oversized item passes an empty buffer: the cap bounds
    # *accumulation*, not item size — otherwise a single wide batch
    # could never be ingested at all
    credit = IngestCredit(100)
    seq = credit.charge(1, 5000, 1, "pause", None)
    assert credit.consume(1, seq) is True


def test_ingest_credit_shed_oldest_advances_floor():
    credit = IngestCredit(1000)
    s0 = credit.charge(1, 600, 3, "shed_oldest", None)
    # second charge overflows: the source's oldest buffered item is shed
    s1 = credit.charge(1, 600, 2, "shed_oldest", None)
    assert credit.consume(1, s0) is False, "shed item must be discarded"
    assert credit.consume(1, s1) is True
    snap = credit.snapshot()[1]
    assert snap["shed_rows"] == 3
    assert snap["shed_bytes"] == 600
    assert credit.totals()["buffered_bytes"] == 0


def test_ingest_credit_shed_only_touches_own_source():
    credit = IngestCredit(1000)
    other = credit.charge(2, 900, 1, "pause", None)
    # source 1 has nothing buffered to shed: it is admitted over-cap
    # rather than shedding source 2's data or deadlocking
    mine = credit.charge(1, 500, 1, "shed_oldest", None)
    assert credit.consume(2, other) is True
    assert credit.consume(1, mine) is True
    assert credit.snapshot().get(2, {}).get("shed_rows", 0) == 0


def test_ingest_credit_fail_mode_raises():
    credit = IngestCredit(100)
    credit.charge(1, 80, 1, "fail", None)
    with pytest.raises(IngestOverflow, match="PATHWAY_INGEST_BUFFER_BYTES"):
        credit.charge(1, 80, 1, "fail", None)


def test_ingest_credit_pause_blocks_until_consume():
    credit = IngestCredit(1000)
    s0 = credit.charge(1, 800, 1, "pause", None)
    stats: dict = {}
    admitted = threading.Event()

    def producer() -> None:
        credit.charge(1, 800, 1, "pause", None, stats)
        admitted.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    assert not admitted.wait(0.2), "charge admitted past a full buffer"
    assert stats.get("paused") is True, "paused flag not raised while parked"
    assert credit.totals()["paused_sources"] == 1
    credit.consume(1, s0)  # drain frees room -> reader wakes
    assert admitted.wait(5.0), "consume never released the paused reader"
    t.join(5.0)
    assert stats.get("paused") is False
    assert stats.get("pauses", 0) >= 1
    assert credit.stalls_total >= 1
    assert credit.stall_ms_total > 0


def test_ingest_credit_pause_released_by_stop_event():
    credit = IngestCredit(100)
    credit.charge(1, 90, 1, "pause", None)
    stop = threading.Event()
    done = threading.Event()

    def producer() -> None:
        credit.charge(1, 90, 1, "pause", stop)
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    assert not done.wait(0.2)
    stop.set()  # shutdown must interrupt a paused reader
    assert done.wait(5.0), "stop event never released the paused reader"
    t.join(5.0)


# ---------------------------------------------------------------------------
# firehose -> ingest buffer -> drain (end to end, single process)


class _CountSchema(pw.Schema):
    word: str
    payload: str


def _firehose_pipeline(c: chaos, total_rows: int, on_overflow: str):
    src = c.firehose_source(
        None, total_rows, vocab=8, payload_bytes=64, commit_every=50
    )
    t = pw.io.python.read(src, schema=_CountSchema, on_overflow=on_overflow)
    return t.groupby(t.word).reduce(t.word, n=pw.reducers.count())


def _run_and_collect(table: pw.Table, tmp_path) -> dict[str, int]:
    import json

    out = tmp_path / "out.jsonl"
    pw.io.jsonlines.write(table, str(out))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    state: dict[str, int] = {}
    with open(out) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            if row["diff"] > 0:
                state[row["word"]] = row["n"]
            elif state.get(row["word"]) == row["n"]:
                del state[row["word"]]
    return state


def test_firehose_pause_is_lossless(tmp_path, monkeypatch):
    """An unpaced firehose into a small ingest buffer: the reader must
    pause (bounded memory) and every row must still arrive — pause mode
    trades latency for zero loss."""
    monkeypatch.setenv("PATHWAY_INGEST_BUFFER_BYTES", "16384")
    total = 1200
    pw.G.clear()
    with chaos(seed=11) as c:
        counts = _run_and_collect(
            _firehose_pipeline(c, total, "pause"), tmp_path
        )
    sched = pw.G.active_scheduler
    totals = sched.ingest_credit.totals()
    assert sum(counts.values()) == total, (
        f"rows lost under pause backpressure: {counts} (totals {totals})"
    )
    assert totals["stalls_total"] >= 1, (
        f"firehose never hit the buffer cap — not an overload run: {totals}"
    )
    assert totals["shed_rows_total"] == 0
    assert totals["buffered_bytes"] == 0, "drain left charged bytes behind"
    pressure = sched.ingest_pressure()
    assert "python" in pressure["sources"], pressure


def test_firehose_shed_oldest_accounts_every_row(tmp_path, monkeypatch):
    """Under shed_oldest nothing is *silently* lost: rows that arrive
    plus rows counted shed must equal rows produced."""
    monkeypatch.setenv("PATHWAY_INGEST_BUFFER_BYTES", "8192")
    total = 1500
    pw.G.clear()
    with chaos(seed=12) as c:
        # stall the sink briefly so the drain genuinely falls behind the
        # unpaced producer and the shed path actually fires
        c.stall_sink(0.05, limit=8)
        counts = _run_and_collect(
            _firehose_pipeline(c, total, "shed_oldest"), tmp_path
        )
    totals = pw.G.active_scheduler.ingest_credit.totals()
    arrived = sum(counts.values())
    assert arrived + totals["shed_rows_total"] == total, (
        f"{arrived} arrived + {totals['shed_rows_total']} shed != {total}"
    )
    assert totals["shed_rows_total"] >= 1, (
        f"overload never triggered shedding: {totals}"
    )
    assert totals["stalls_total"] == 0, "shed_oldest must not pause"


def test_stalled_sink_backpressures_to_source(tmp_path, monkeypatch):
    """A wedged sink writer holds the epoch cut (sinks are synchronous),
    the drain stops taking, the buffer fills, and the *reader* pauses —
    pressure propagates the whole way back with no loss."""
    monkeypatch.setenv("PATHWAY_INGEST_BUFFER_BYTES", "8192")
    total = 800
    pw.G.clear()
    with chaos(seed=13) as c:
        c.stall_sink(0.1, limit=6)
        counts = _run_and_collect(
            _firehose_pipeline(c, total, "pause"), tmp_path
        )
    totals = pw.G.active_scheduler.ingest_credit.totals()
    assert sum(counts.values()) == total, (
        f"rows lost behind a stalled sink: {counts}"
    )
    assert totals["stalls_total"] >= 1, (
        f"stalled sink never propagated to the reader: {totals}"
    )


def test_slow_consumer_rank_is_correct_and_complete(tmp_path):
    """slow_consumer drags a rank's epochs without breaking it: the run
    completes with exact results (degraded, never isolated)."""
    pw.G.clear()
    with chaos(seed=14) as c:
        c.slow_consumer(0, factor=1.5)
        counts = _run_and_collect(
            _firehose_pipeline(c, 400, "pause"), tmp_path
        )
        from pathway_tpu.engine.scheduler import Scheduler

        assert c.call_count(Scheduler, "run_epoch") >= 1
    assert sum(counts.values()) == 400


# ---------------------------------------------------------------------------
# exchange credit: slow-but-alive peers throttle, dead peers release

_port_counter = [17000 + (os.getpid() % 500) * 16]


def _next_port(n: int = 4) -> int:
    import socket

    while True:
        base = _port_counter[0]
        _port_counter[0] += n
        if _port_counter[0] > 60000:
            _port_counter[0] = 17000
        try:
            socks = []
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            for s in socks:
                s.close()
            return base
        except OSError:
            for s in socks:
                s.close()


def _link_pair(first_port: int):
    """Both ends of a 2-process TCP mesh built in one process (end 0
    blocks in its constructor, so it goes on a thread)."""
    from pathway_tpu.engine.cluster import _ProcessLinks

    out: dict[int, _ProcessLinks] = {}

    def build0() -> None:
        out[0] = _ProcessLinks(
            0, 2, first_port, heartbeat_s=0.1, liveness_timeout_s=5.0
        )

    t = threading.Thread(target=build0, daemon=True)
    t.start()
    out[1] = _ProcessLinks(
        1, 2, first_port, heartbeat_s=0.1, liveness_timeout_s=5.0
    )
    t.join(10.0)
    assert 0 in out, "mesh never completed"
    return out[0], out[1]


def _boxes(n_updates: int) -> list:
    # boxes[src_tid][dst_tid] of (int_key, values, diff) updates
    return [[[(i, ("v" * 40,), 1) for i in range(n_updates)]]]


@pytest.mark.chaos
def test_exchange_credit_throttles_slow_but_alive_peer(monkeypatch):
    """A peer that receives but does not consume parks the producer at
    the credit cap (bounded backlog, credit_stalls recorded) WITHOUT
    being isolated; consuming drains the window and the producer
    finishes."""
    monkeypatch.setenv("PATHWAY_EXCHANGE_CREDIT_BYTES", "8192")
    links0, links1 = _link_pair(_next_port(2))
    n_frames = 6
    try:
        sent = []

        def producer() -> None:
            for i in range(n_frames):
                links0.send_updates_async(1, ("s", i), _boxes(60))
                sent.append(i)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with links0.stats_lock:
                stalls = links0.stats["credit_stalls"]
            if stalls >= 1:
                break
            time.sleep(0.02)
        assert stalls >= 1, "producer never parked on the credit window"
        assert len(sent) < n_frames, "all frames sent without any throttle"
        # slow, not dead: bounded backlog, no isolation, no failure
        pressure = links0.exchange_pressure()
        assert pressure["peers"][1]["state"] == "alive", pressure
        assert pressure["peers"][1]["backlog_bytes"] <= 2 * 8192, (
            f"backlog exceeded the credit window: {pressure}"
        )
        assert links0._failed is None
        # the consumer drains -> grants flow back -> producer completes
        for i in range(n_frames):
            got = links1.recv_from_all(("s", i))
            assert 0 in got
        t.join(10.0)
        assert not t.is_alive(), "producer still parked after full drain"
        assert len(sent) == n_frames
        assert links0.pressure_level() >= 0.0
        with links0.stats_lock:
            assert links0.stats["credit_stall_ms"] > 0
    finally:
        links0.close()
        links1.close()


@pytest.mark.chaos
def test_exchange_credit_oversized_frame_passes_empty_window(monkeypatch):
    """One frame larger than the whole window must still transit when
    the window is empty — credit bounds accumulation, not frame size."""
    monkeypatch.setenv("PATHWAY_EXCHANGE_CREDIT_BYTES", "512")
    links0, links1 = _link_pair(_next_port(2))
    try:
        links0.send_updates_async(1, ("big", 0), _boxes(200))
        got = links1.recv_from_all(("big", 0))
        assert 0 in got
    finally:
        links0.close()
        links1.close()


@pytest.mark.chaos
def test_credit_waiter_released_by_link_failure(monkeypatch):
    """DEAD releases where SLOW parks: a producer parked on the credit
    window must escape promptly when the link fails rather than waiting
    for grants that will never come."""
    monkeypatch.setenv("PATHWAY_EXCHANGE_CREDIT_BYTES", "4096")
    links0, links1 = _link_pair(_next_port(2))
    try:
        released = threading.Event()

        def producer() -> None:
            for i in range(8):
                links0.send_updates_async(1, ("d", i), _boxes(60))
            released.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with links0.stats_lock:
                if links0.stats["credit_stalls"] >= 1:
                    break
            time.sleep(0.02)
        assert not released.is_set(), "producer never throttled"
        links1.close()  # peer death: socket EOF fails the link
        assert released.wait(10.0), (
            "producer stayed parked on a dead peer's credit window"
        )
        t.join(5.0)
    finally:
        links0.close()
        links1.close()


@pytest.mark.chaos
def test_close_drops_backlog_of_suspect_peer():
    """Regression (ISSUE 16 satellite): ``close()`` with a backlogged
    mailbox for a non-ALIVE peer must DROP the backlog, not drain it into
    a possibly-stalled socket — teardown stays bounded."""
    from pathway_tpu.engine.cluster import PEER_SUSPECT, _K_OBJ

    links0, links1 = _link_pair(_next_port(2))
    try:
        sender = links0._senders[1]
        gate = threading.Event()
        orig_transmit = sender._transmit
        data_frames_sent = []

        def blocking_transmit(body, n_frames):
            if n_frames:
                data_frames_sent.append(n_frames)
                gate.wait(10.0)  # wedge: a stalled sendall
            return orig_transmit(body, n_frames)

        sender._transmit = blocking_transmit
        sender.enqueue(("a", 0), _K_OBJ, {"x": 1})
        # wait for the sender to take frame A into the wedged transmit
        deadline = time.monotonic() + 5.0
        while not data_frames_sent and time.monotonic() < deadline:
            time.sleep(0.01)
        assert data_frames_sent, "sender never picked up the first frame"
        # B and C pile up behind the wedged transmission
        sender.enqueue(("b", 0), _K_OBJ, {"x": 2})
        sender.enqueue(("c", 0), _K_OBJ, {"x": 3})
        with links0._cv:
            links0._peer_state[1] = PEER_SUSPECT
        closer = threading.Thread(target=links0.close, daemon=True)
        closer.start()
        gate.set()  # release the wedge; the drop branch must fire
        closer.join(10.0)
        assert not closer.is_alive(), "close() hung behind the backlog"
        with links0.stats_lock:
            dropped = links0.stats["frames_dropped_on_close"]
        assert dropped >= 2, (
            f"suspect peer's backlog was drained, not dropped ({dropped})"
        )
        # only the first (pre-suspect) transmission carried data frames
        assert len(data_frames_sent) == 1, data_frames_sent
    finally:
        links0.close()
        links1.close()


# ---------------------------------------------------------------------------
# serving brownout: shed batch first, hold interactive


def _controller(clock):
    from pathway_tpu.serving.admission import AdmissionController, TenantPolicy

    return AdmissionController(
        {
            "live": TenantPolicy("interactive", rate_per_s=100, queue_cap=64),
            "bulk": TenantPolicy("batch", rate_per_s=100, queue_cap=64),
        },
        clock=clock,
    )


def test_brownout_sheds_batch_before_interactive():
    from pathway_tpu.io.http import RetryLater

    t = [0.0]
    ac = _controller(lambda: t[0])
    ac.set_pressure("engine", 0.6)

    live_ok = bulk_shed = 0
    retry_afters = []
    for _ in range(10):
        t[0] += 0.01
        ac.admit("live").release()  # interactive holds under brownout
        live_ok += 1
        try:
            ac.admit("bulk").release()
        except RetryLater as e:
            bulk_shed += 1
            retry_afters.append(e.retry_after)
    assert live_ok == 10
    assert bulk_shed >= 8, f"batch class not shed under pressure ({bulk_shed})"
    assert all(ra > 0 for ra in retry_afters), retry_afters
    stats = ac.stats()
    assert stats["pressure"]["level"] == pytest.approx(0.6)
    assert stats["pressure"]["brownout_shed_total"].get("batch", 0) >= 8
    assert stats["pressure"]["brownout_shed_total"].get("interactive", 0) == 0


def test_brownout_recovers_when_pressure_clears():
    t = [0.0]
    ac = _controller(lambda: t[0])
    ac.set_pressure("engine", 0.9)
    assert ac.try_admit("bulk") is None, "full brownout admitted batch"
    ac.set_pressure("engine", 0.0)  # pressure released: buckets re-arm
    t[0] += 0.1
    ticket = ac.try_admit("bulk")
    assert ticket is not None, "brownout outlived the pressure signal"
    ticket.release()
    assert ac.stats()["pressure"]["level"] == 0.0


def test_push_pressure_fans_out_to_live_controllers():
    from pathway_tpu import serving

    t = [0.0]
    ac = _controller(lambda: t[0])
    serving.push_pressure("engine", 0.7)
    assert ac.pressure_level() == pytest.approx(0.7)
    serving.push_pressure("engine", 0.0)
    assert ac.pressure_level() == 0.0


def test_slo_scheduler_pressure_stretches_light_classes():
    from pathway_tpu.serving import SloScheduler

    sched = SloScheduler()
    sched.set_pressure(0.8)
    assert sched.stats()["pressure"] == pytest.approx(0.8)
    sched.set_pressure(0.0)
    assert sched.stats()["pressure"] == 0.0
