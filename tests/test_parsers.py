"""LLM xpack parsers — incl. the built-in PDF text extractor
(reference ``xpacks/llm/parsers.py``; pypdf-free fallback in
``xpacks/llm/_pdf.py``)."""

import zlib

from pathway_tpu.xpacks.llm.parsers import ParseUtf8, PypdfParser
from pathway_tpu.xpacks.llm._pdf import extract_pdf_text


def _minimal_pdf(content: bytes, compress: bool) -> bytes:
    """A structurally plausible one-page PDF around ``content``."""
    if compress:
        data = zlib.compress(content)
        filt = b"/Filter /FlateDecode "
    else:
        data = content
        filt = b""
    stream = (
        b"5 0 obj\n<< " + filt + b"/Length " + str(len(data)).encode()
        + b" >>\nstream\n" + data + b"\nendstream\nendobj\n"
    )
    return (
        b"%PDF-1.4\n"
        b"1 0 obj\n<< /Type /Catalog /Pages 2 0 R >>\nendobj\n"
        b"2 0 obj\n<< /Type /Pages /Kids [3 0 R] /Count 1 >>\nendobj\n"
        b"3 0 obj\n<< /Type /Page /Parent 2 0 R /Contents 5 0 R >>\nendobj\n"
        + stream
        + b"trailer\n<< /Root 1 0 R >>\n%%EOF\n"
    )


CONTENT = (
    b"BT /F1 12 Tf 72 720 Td (Hello PDF world) Tj "
    b"0 -14 Td [(Numbers: ) -250 (1 and 2)] TJ "
    b"T* (escaped \\(parens\\) and \\134backslash) Tj ET"
)


def test_extract_uncompressed_pdf():
    pages = extract_pdf_text(_minimal_pdf(CONTENT, compress=False))
    assert len(pages) == 1
    text = pages[0]
    assert "Hello PDF world" in text
    assert "Numbers: 1 and 2" in text
    assert "escaped (parens) and \\backslash" in text


def test_extract_flate_pdf_and_hex_strings():
    content = (
        b"BT (plain) Tj 0 -14 Td <48692068657821> Tj ET"  # "Hi hex!"
    )
    pages = extract_pdf_text(_minimal_pdf(content, compress=True))
    assert pages == ["plain\nHi hex!"]


def test_extract_rejects_non_pdf():
    import pytest

    with pytest.raises(ValueError, match="PDF"):
        extract_pdf_text(b"plain text, no header")


def test_pypdf_parser_udf_fallback_path(monkeypatch):
    # force the built-in path even when pypdf is installed
    import builtins

    real_import = builtins.__import__

    def no_pypdf(name, *a, **kw):
        if name.startswith("pypdf"):
            raise ImportError("forced for fallback test")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_pypdf)
    parser = PypdfParser()
    out = parser.__wrapped__(_minimal_pdf(CONTENT, compress=True))
    assert len(out) == 1
    text, meta = out[0]
    assert "Hello PDF world" in text and meta == {"page": 0}


def test_extract_nested_parens_tj_brackets_hex_quote():
    content = (
        b"BT (see (figure 1) here) Tj "
        b"[(a]b) -100 (c)] TJ "
        b"<4869> ' ET"
    )
    pages = extract_pdf_text(_minimal_pdf(content, compress=False))
    assert len(pages) == 1
    text = pages[0]
    assert "see (figure 1) here" in text
    assert "a]bc" in text  # ']' inside a TJ string doesn't end the array
    assert "Hi" in text  # hex string shown with the ' operator


def test_parse_utf8():
    out = ParseUtf8().__wrapped__("héllo".encode())
    assert out[0][0] == "héllo"
