"""LLM xpack parsers — incl. the built-in PDF text extractor
(reference ``xpacks/llm/parsers.py``; pypdf-free fallback in
``xpacks/llm/_pdf.py``)."""

import zlib

from pathway_tpu.xpacks.llm.parsers import ParseUtf8, PypdfParser
from pathway_tpu.xpacks.llm._pdf import extract_pdf_text


def _minimal_pdf(content: bytes, compress: bool) -> bytes:
    """A structurally plausible one-page PDF around ``content``."""
    if compress:
        data = zlib.compress(content)
        filt = b"/Filter /FlateDecode "
    else:
        data = content
        filt = b""
    stream = (
        b"5 0 obj\n<< " + filt + b"/Length " + str(len(data)).encode()
        + b" >>\nstream\n" + data + b"\nendstream\nendobj\n"
    )
    return (
        b"%PDF-1.4\n"
        b"1 0 obj\n<< /Type /Catalog /Pages 2 0 R >>\nendobj\n"
        b"2 0 obj\n<< /Type /Pages /Kids [3 0 R] /Count 1 >>\nendobj\n"
        b"3 0 obj\n<< /Type /Page /Parent 2 0 R /Contents 5 0 R >>\nendobj\n"
        + stream
        + b"trailer\n<< /Root 1 0 R >>\n%%EOF\n"
    )


CONTENT = (
    b"BT /F1 12 Tf 72 720 Td (Hello PDF world) Tj "
    b"0 -14 Td [(Numbers: ) -250 (1 and 2)] TJ "
    b"T* (escaped \\(parens\\) and \\134backslash) Tj ET"
)


def test_extract_uncompressed_pdf():
    pages = extract_pdf_text(_minimal_pdf(CONTENT, compress=False))
    assert len(pages) == 1
    text = pages[0]
    assert "Hello PDF world" in text
    assert "Numbers: 1 and 2" in text
    assert "escaped (parens) and \\backslash" in text


def test_extract_flate_pdf_and_hex_strings():
    content = (
        b"BT (plain) Tj 0 -14 Td <48692068657821> Tj ET"  # "Hi hex!"
    )
    pages = extract_pdf_text(_minimal_pdf(content, compress=True))
    assert pages == ["plain\nHi hex!"]


def test_extract_rejects_non_pdf():
    import pytest

    with pytest.raises(ValueError, match="PDF"):
        extract_pdf_text(b"plain text, no header")


def test_pypdf_parser_udf_fallback_path(monkeypatch):
    # force the built-in path even when pypdf is installed
    import builtins

    real_import = builtins.__import__

    def no_pypdf(name, *a, **kw):
        if name.startswith("pypdf"):
            raise ImportError("forced for fallback test")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_pypdf)
    parser = PypdfParser()
    out = parser.__wrapped__(_minimal_pdf(CONTENT, compress=True))
    assert len(out) == 1
    text, meta = out[0]
    assert "Hello PDF world" in text and meta == {"page": 0}


def test_extract_nested_parens_tj_brackets_hex_quote():
    content = (
        b"BT (see (figure 1) here) Tj "
        b"[(a]b) -100 (c)] TJ "
        b"<4869> ' ET"
    )
    pages = extract_pdf_text(_minimal_pdf(content, compress=False))
    assert len(pages) == 1
    text = pages[0]
    assert "see (figure 1) here" in text
    assert "a]bc" in text  # ']' inside a TJ string doesn't end the array
    assert "Hi" in text  # hex string shown with the ' operator


def test_parse_utf8():
    out = ParseUtf8().__wrapped__("héllo".encode())
    assert out[0][0] == "héllo"


# ---------------------------------------------------------------------------
# built-in HTML / DOCX extraction (_doc.py)


def _minimal_docx() -> bytes:
    """A structurally valid DOCX (zip of WordprocessingML)."""
    import io
    import zipfile

    W = "http://schemas.openxmlformats.org/wordprocessingml/2006/main"
    document = f"""<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<w:document xmlns:w="{W}"><w:body>
<w:p><w:pPr><w:pStyle w:val="Heading1"/></w:pPr><w:r><w:t>Quarterly Report</w:t></w:r></w:p>
<w:p><w:r><w:t>Revenue grew by </w:t></w:r><w:r><w:t>ten percent.</w:t></w:r></w:p>
<w:p><w:pPr><w:numPr><w:ilvl w:val="0"/></w:numPr></w:pPr><w:r><w:t>first item</w:t></w:r></w:p>
<w:tbl><w:tr><w:tc><w:p><w:r><w:t>Region</w:t></w:r></w:p></w:tc>
<w:tc><w:p><w:r><w:t>Sales</w:t></w:r></w:p></w:tc></w:tr>
<w:tr><w:tc><w:p><w:r><w:t>EMEA</w:t></w:r></w:p></w:tc>
<w:tc><w:p><w:r><w:t>120</w:t></w:r></w:p></w:tc></w:tr></w:tbl>
</w:body></w:document>"""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr(
            "[Content_Types].xml",
            '<?xml version="1.0"?><Types xmlns="http://schemas.openxmlformats.org/package/2006/content-types"/>',
        )
        zf.writestr("word/document.xml", document)
    return buf.getvalue()


_HTML = b"""<!DOCTYPE html><html><head><title>Fruit Guide</title>
<style>body { color: red }</style><script>var x = 1;</script></head>
<body><h1>All About Fruit</h1>
<p>Apples grow on trees.</p>
<ul><li>sweet</li><li>crunchy</li></ul>
<table><tr><th>Name</th><th>Color</th></tr>
<tr><td>banana</td><td>yellow</td></tr></table>
</body></html>"""


def test_extract_html_blocks_categories():
    from pathway_tpu.xpacks.llm._doc import extract_html_blocks

    blocks = extract_html_blocks(_HTML)
    cats = {t: m["category"] for t, m in blocks}
    assert cats["All About Fruit"] == "Title"
    assert cats["Apples grow on trees."] == "NarrativeText"
    assert cats["sweet"] == "ListItem"
    table = [t for t, m in blocks if m["category"] == "Table"]
    assert len(table) == 1 and "banana" in table[0] and "yellow" in table[0]
    # script/style never leak into text
    assert not any("var x" in t or "color: red" in t for t, _ in blocks)
    assert all(m.get("page_title") == "Fruit Guide" for _, m in blocks)


def test_extract_docx_blocks_categories():
    from pathway_tpu.xpacks.llm._doc import extract_docx_blocks

    blocks = extract_docx_blocks(_minimal_docx())
    cats = {t: m["category"] for t, m in blocks}
    assert cats["Quarterly Report"] == "Title"
    # runs join into one paragraph
    assert cats["Revenue grew by ten percent."] == "NarrativeText"
    assert cats["first item"] == "ListItem"
    table = [t for t, m in blocks if m["category"] == "Table"]
    assert len(table) == 1 and "EMEA\t120" in table[0]


def test_parse_unstructured_builtin_sniffing():
    from pathway_tpu.xpacks.llm.parsers import ParseUnstructured

    # elements mode keeps per-block category metadata
    elems = ParseUnstructured(mode="elements").__wrapped__(_HTML)
    assert any(m["category"] == "Title" for _, m in elems)
    # single mode joins; docx sniffed from PK zip magic
    single = ParseUnstructured(mode="single").__wrapped__(_minimal_docx())
    assert len(single) == 1 and "Quarterly Report" in single[0][0]
    # pdf sniffed from %PDF magic, paged mode groups per page
    paged = ParseUnstructured(mode="paged").__wrapped__(
        _minimal_pdf(CONTENT, compress=True)
    )
    assert len(paged) == 1 and "Hello PDF world" in paged[0][0]
    # plain text falls through to utf-8
    txt = ParseUnstructured().__wrapped__(b"just plain text")
    assert txt == [("just plain text", {})] or txt[0][0] == "just plain text"


def test_parse_html_docx_udfs():
    from pathway_tpu.xpacks.llm.parsers import ParseDocx, ParseHtml

    html_blocks = ParseHtml(mode="elements").__wrapped__(_HTML)
    assert any(m["category"] == "ListItem" for _, m in html_blocks)
    docx_single = ParseDocx().__wrapped__(_minimal_docx())
    assert "Revenue grew by ten percent." in docx_single[0][0]


# ---------------------------------------------------------------------------
# layout-aware PDF chunking (reference openparse_utils.py; built-in
# engine in xpacks/llm/_layout.py)


def _layout_pdf() -> bytes:
    """Two-column page: titles at 18pt, body at 10pt, and a 3x3 table in
    the left column with x-aligned cells."""
    content = (
        # full-width title
        b"BT /F1 18 Tf 72 760 Td (Quarterly Report) Tj ET "
        # left column: heading + body + table
        b"BT /F1 14 Tf 72 720 Td (Revenue) Tj ET "
        b"BT /F1 10 Tf 72 700 Td (Revenue grew in every region this) Tj ET "
        b"BT /F1 10 Tf 72 688 Td (quarter, led by the north.) Tj ET "
        # table rows: cells at x = 72, 140, 210
        b"BT /F1 10 Tf 1 0 0 1 72 660 Tm (Region) Tj 1 0 0 1 140 660 Tm (Q1) Tj "
        b"1 0 0 1 210 660 Tm (Q2) Tj ET "
        b"BT /F1 10 Tf 1 0 0 1 72 646 Tm (North) Tj 1 0 0 1 140 646 Tm (10) Tj "
        b"1 0 0 1 210 646 Tm (14) Tj ET "
        b"BT /F1 10 Tf 1 0 0 1 72 632 Tm (South) Tj 1 0 0 1 140 632 Tm (8) Tj "
        b"1 0 0 1 210 632 Tm (9) Tj ET "
        # right column (x=340): its own heading + body
        b"BT /F1 14 Tf 340 720 Td (Outlook) Tj ET "
        b"BT /F1 10 Tf 340 700 Td (Guidance remains unchanged for) Tj ET "
        b"BT /F1 10 Tf 340 688 Td (the remainder of the year.) Tj ET"
    )
    return _minimal_pdf(content, compress=False)


def test_layout_spans_positions():
    from pathway_tpu.xpacks.llm._layout import extract_pdf_spans

    pages = extract_pdf_spans(_layout_pdf())
    assert len(pages) == 1
    spans = pages[0]
    by_text = {s.text: s for s in spans}
    assert by_text["Quarterly Report"].size == 18.0
    assert by_text["Region"].x == 72.0 and by_text["Q2"].x == 210.0
    assert by_text["North"].y == 646.0


def test_layout_nodes_headings_tables_columns():
    from pathway_tpu.xpacks.llm._layout import pdf_layout_nodes

    nodes = pdf_layout_nodes(_layout_pdf())
    kinds = [(n.kind, n.text.split("\n")[0][:20]) for n in nodes]
    headings = [n.text for n in nodes if n.kind == "heading"]
    assert "Quarterly Report" in headings
    assert "Revenue" in headings and "Outlook" in headings
    tables = [n for n in nodes if n.kind == "table"]
    assert len(tables) == 1, kinds
    rows = tables[0].text.split("\n")
    assert rows[0] == "Region | Q1 | Q2"
    assert rows[1] == "North | 10 | 14"
    assert rows[2] == "South | 8 | 9"
    # reading order: left column (Revenue...) fully before right (Outlook)
    order = [n.text.split("\n")[0] for n in nodes]
    assert order.index("Revenue") < order.index("Outlook")
    full_left = "\n".join(n.text for n in nodes)
    assert full_left.index("led by the north") < full_left.index("Guidance")


def test_layout_chunking_keeps_tables_intact():
    from pathway_tpu.xpacks.llm._layout import chunk_pdf_layout

    chunks = chunk_pdf_layout(_layout_pdf(), max_chars=60)
    # the table never splits even under a tiny budget
    table_chunks = [c for c, m in chunks if "Region | Q1 | Q2" in c]
    assert len(table_chunks) == 1
    assert "South | 8 | 9" in table_chunks[0]
    # headings open their sections
    heads = [m["heading"] for _c, m in chunks]
    assert "Revenue" in heads and "Outlook" in heads
    # bbox metadata present and sane
    for _c, m in chunks:
        x0, y0, x1, y1 = m["bbox"]
        assert x0 <= x1 and y0 <= y1


def test_openparse_udf_end_to_end():
    from pathway_tpu.xpacks.llm.parsers import OpenParse

    parser = OpenParse(max_chars=200)
    chunks = parser.__wrapped__(_layout_pdf())
    assert any("Region | Q1 | Q2" in text for text, _m in chunks)
    assert all("page" in m and "bbox" in m for _t, m in chunks)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="llm"):
        OpenParse(table_args={"parsing_algorithm": "llm"})
    with _pytest.raises(ValueError, match="algorithm"):
        OpenParse(table_args={"parsing_algorithm": "bogus"})


def test_document_store_ingests_layout_pdf():
    """DocumentStore end-to-end over a multi-column PDF with a table:
    table cells stay intact inside retrieved chunks (round-4 verdict
    item 8's done criterion)."""
    import pathway_tpu as pw
    from pathway_tpu.xpacks.llm.parsers import OpenParse

    pw.G.clear()
    rows = [(_layout_pdf(), "report.pdf")]
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(data=bytes, path=str), rows
    )
    parser = OpenParse(max_chars=400)
    parsed = docs.select(
        chunks=pw.apply(lambda b: [c for c, _m in parser.__wrapped__(b)], docs.data),
        path=docs.path,
    )
    flat = parsed.flatten(parsed.chunks)
    out = []
    pw.io.subscribe(flat, on_change=lambda k, row, t, add: out.append(row["chunks"]))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    table_chunk = next(c for c in out if "Region | Q1 | Q2" in c)
    assert "North | 10 | 14" in table_chunk and "South | 8 | 9" in table_chunk


def test_openparse_llm_table_pass_preserves_prose():
    from pathway_tpu.xpacks.llm.parsers import OpenParse

    class FakeLLM:
        calls: list = []

        def __wrapped__(self, messages):
            self.calls.append(messages[0]["content"])
            return "| MD TABLE |"

    llm = FakeLLM()
    parser = OpenParse(
        max_chars=400, table_args={"parsing_algorithm": "llm"}, llm=llm
    )
    chunks = parser.__wrapped__(_layout_pdf())
    joined = "\n".join(t for t, _m in chunks)
    # prose untouched, table replaced
    assert "led by the north" in joined
    assert "| MD TABLE |" in joined
    assert "Region | Q1 | Q2" not in joined
    # the llm saw ONLY the table rows, not the prose
    assert len(llm.calls) == 1
    assert "Region | Q1 | Q2" in llm.calls[0]
    assert "led by the north" not in llm.calls[0]


def test_layout_quote_operators_move_then_show():
    """' and \" move to the next line BEFORE showing (ISO 32000-1
    §9.4.3): three '-shown strings land on three distinct baselines."""
    from pathway_tpu.xpacks.llm._layout import extract_pdf_spans

    content = (
        b"BT /F1 10 Tf 12 TL 1 0 0 1 72 700 Tm (first) Tj "
        b"(second) ' (third) ' ET"
    )
    spans = extract_pdf_spans(_minimal_pdf(content, compress=False))[0]
    ys = {s.text: s.y for s in spans}
    assert ys["first"] == 700.0
    assert ys["second"] == 688.0
    assert ys["third"] == 676.0
